"""NodeClaim lifecycle: launch -> registration -> initialization -> liveness.

Counterpart of reference pkg/controllers/nodeclaim/lifecycle
(controller.go:168-173, launch.go, registration.go, initialization.go,
liveness.go). Each reconcile pass runs the sub-reconcilers in order; the
finalize path drains the node and awaits instance termination.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.cloudprovider import errors
from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodeclaim import (
    COND_INITIALIZED,
    COND_LAUNCHED,
    COND_REGISTERED,
    NodeClaim,
)
from karpenter_tpu.models.taints import UNREGISTERED_NO_EXECUTE_TAINT, is_known_ephemeral_taint
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import Clock

LAUNCH_TTL_SECONDS = 5 * 60.0  # liveness.go:59 registration/launch timeout
# Transient launch errors (throttle/timeout/API flake) retry per
# reconcile, bounded: once the budget is spent the claim is given up the
# same way an ICE is (deleted; pods re-schedule onto a fresh claim)
MAX_LAUNCH_ATTEMPTS = 5
LAUNCH_ATTEMPTS_ANNOTATION = "karpenter-tpu.sh/launch-attempts"


class NodeClaimLifecycleController:
    def __init__(
        self,
        store: ObjectStore,
        cloud: CloudProvider,
        clock: Clock,
        terminator=None,
        unavailable=None,
    ):
        self.store = store
        self.cloud = cloud
        self.clock = clock
        if terminator is None:
            from karpenter_tpu.controllers.node_termination import NodeTerminationController

            terminator = NodeTerminationController(store, clock)
        self.terminator = terminator
        # the shared unavailable-offerings blackout cache (Manager wires
        # the same instance into the Provisioner); standalone harnesses
        # get a private one so marking is always safe
        if unavailable is None:
            from karpenter_tpu.cloudprovider.unavailable import UnavailableOfferings

            unavailable = UnavailableOfferings(clock)
        self.unavailable = unavailable

    def reconcile(self, claim: NodeClaim) -> None:
        from karpenter_tpu.tracing.tracer import TRACER

        with TRACER.span("lifecycle.nodeclaim", claim=claim.name):
            self._reconcile(claim)

    def _reconcile(self, claim: NodeClaim) -> None:
        if claim.metadata.deleting:
            self._finalize(claim)
            return
        changed = False
        if l.TERMINATION_FINALIZER not in claim.metadata.finalizers:
            claim.metadata.finalizers.append(l.TERMINATION_FINALIZER)
            changed = True
        changed |= self._transition(claim, self._launch, COND_LAUNCHED)
        changed |= self._transition(claim, self._register, COND_REGISTERED)
        changed |= self._transition(claim, self._initialize, COND_INITIALIZED)
        self._liveness(claim)
        # write back only on transition — unconditional updates would
        # re-trigger the informer forever (idempotent-reconciler discipline)
        if changed and self.store.get(ObjectStore.NODECLAIMS, claim.name) is not None:
            self.store.update(ObjectStore.NODECLAIMS, claim)

    def _transition(self, claim: NodeClaim, sub, condition_type: str) -> bool:
        """Run one sub-reconciler; when it flips its condition true, record
        creation -> condition age into the transition-duration histogram
        (the reference's nodeclaim duration family analog)."""
        changed = sub(claim)
        if changed and claim.conditions.is_true(condition_type):
            from karpenter_tpu.utils import metrics

            metrics.NODECLAIM_TRANSITION_DURATION.observe(
                max(self.clock.now() - claim.metadata.creation_timestamp, 0.0),
                condition_type=condition_type,
            )
        return changed

    # -- launch (launch.go:47-127) -------------------------------------------

    def _launch(self, claim: NodeClaim) -> bool:
        if claim.conditions.is_true(COND_LAUNCHED):
            return False
        try:
            self.cloud.create(claim)
        except errors.InsufficientCapacityError as e:
            # the failed offerings enter the blackout cache FIRST, so the
            # re-scheduled pods can't be solved straight back onto the
            # same (instance type, zone, capacity type) for the TTL
            # (reference pkg/providers ICE-cache parity)
            self.unavailable.mark_from_error(e)
            # fail fast: delete the claim so pods re-schedule (launch.go:81)
            claim.conditions.set_false(COND_LAUNCHED, "InsufficientCapacity", str(e), self.clock.now())
            claim.metadata.finalizers = []
            self.store.delete(ObjectStore.NODECLAIMS, claim.name)
            return False
        except errors.TransientError as e:
            return self._transient_launch_failure(claim, e)
        except errors.NodeClassNotReadyError as e:
            return claim.conditions.set_false(
                COND_LAUNCHED, "NodeClassNotReady", str(e), self.clock.now()
            )
        except errors.CreateError as e:
            return claim.conditions.set_false(COND_LAUNCHED, e.reason, str(e), self.clock.now())
        claim.conditions.set_true(COND_LAUNCHED, "Launched", now=self.clock.now())
        return True

    def _transient_launch_failure(self, claim: NodeClaim, err: Exception) -> bool:
        """Bounded retry + requeue for retryable launch errors: the
        attempt count rides a claim annotation (it must survive process
        restarts like everything else about the claim); each failure
        writes the claim back, whose MODIFIED event requeues the next
        attempt. Budget exhausted -> give up exactly like an ICE."""
        from karpenter_tpu.utils import metrics

        metrics.TRANSIENT_RETRIES.inc(controller="nodeclaim.lifecycle")
        attempts = int(claim.metadata.annotations.get(LAUNCH_ATTEMPTS_ANNOTATION, "0")) + 1
        claim.metadata.annotations[LAUNCH_ATTEMPTS_ANNOTATION] = str(attempts)
        if attempts >= MAX_LAUNCH_ATTEMPTS:
            claim.conditions.set_false(
                COND_LAUNCHED,
                "TransientLaunchFailed",
                f"gave up after {attempts} attempts: {err}",
                self.clock.now(),
            )
            claim.metadata.finalizers = []
            self.store.delete(ObjectStore.NODECLAIMS, claim.name)
            return False
        claim.conditions.set_false(
            COND_LAUNCHED,
            "TransientLaunchFailure",
            f"attempt {attempts}/{MAX_LAUNCH_ATTEMPTS}: {err}",
            self.clock.now(),
        )
        # the annotation changed even when the condition text didn't:
        # report the object dirty so the write-back (and its requeueing
        # MODIFIED event) always happens
        return True

    # -- registration (registration.go:59-206) --------------------------------

    def _register(self, claim: NodeClaim) -> bool:
        if not claim.conditions.is_true(COND_LAUNCHED) or claim.conditions.is_true(COND_REGISTERED):
            return False
        node = self._node_for(claim)
        if node is None:
            return False
        # sync labels/annotations/taints from the claim onto the node
        # (registration.go:207-221 syncNode): claim taints + startup
        # taints merge in unless the provider opted out of taint syncing
        synced = self._sync_node(claim, node)
        # provider registration hooks gate completion (registration.go:
        # 96-105 checkRegistrationHooks + types.go:103-118): until every
        # hook is ready the node stays synced but UNREGISTERED (the
        # NoExecute taint keeps workloads off)
        hooks = self.cloud.registration_hooks()
        if any(not h.registered(claim) for h in hooks):
            if synced:  # write back only on change (idempotent reconciler)
                self.store.update(ObjectStore.NODES, node)
            return False
        node.metadata.labels[l.NODE_REGISTERED_LABEL_KEY] = "true"
        node.spec.taints = [
            t for t in node.spec.taints if not t.match(UNREGISTERED_NO_EXECUTE_TAINT)
        ]
        claim.status.node_name = node.name
        self.store.update(ObjectStore.NODES, node)
        claim.conditions.set_true(COND_REGISTERED, "Registered", now=self.clock.now())
        return True

    @staticmethod
    def _sync_node(claim: NodeClaim, node) -> bool:
        """registration.go:207-221: labels/annotations always sync; taints
        merge (no duplicates) unless karpenter.sh/do-not-sync-taints.
        Returns True when anything actually changed."""
        changed = False
        for src, dst in (
            (claim.metadata.labels, node.metadata.labels),
            (claim.metadata.annotations, node.metadata.annotations),
        ):
            for k, v in src.items():
                if dst.get(k) != v:
                    dst[k] = v
                    changed = True
        if node.metadata.labels.get(l.DO_NOT_SYNC_TAINTS_LABEL_KEY) != "true":
            for t in list(claim.spec.taints) + list(claim.spec.startup_taints):
                if not any(existing.match(t) for existing in node.spec.taints):
                    node.spec.taints.append(t)
                    changed = True
        return changed

    # -- initialization (initialization.go:56-263) -----------------------------

    def _initialize(self, claim: NodeClaim) -> bool:
        if not claim.conditions.is_true(COND_REGISTERED) or claim.conditions.is_true(COND_INITIALIZED):
            return False
        node = self._node_for(claim)
        if node is None or not node.status.ready:
            return False
        # initialization waits for BOTH ladders to clear: every known
        # ephemeral taint (e.g. node.kubernetes.io/not-ready) AND every
        # startup taint (initialization.go:78-81 StartupTaintsRemoved +
        # KnownEphemeralTaintsRemoved)
        blocking = [
            t
            for t in node.spec.taints
            if is_known_ephemeral_taint(t)
            or any(t.match(st) for st in claim.spec.startup_taints)
        ]
        if blocking:
            return False
        # requested resources registered (initialization.go:130-146): the
        # kubelet zeroes extended resources on startup, so a requested
        # resource with zero allocatable means its device plugin hasn't
        # registered yet — initialization must wait
        for res_name, qty in claim.spec.requests.items():
            if qty > 0 and not node.status.allocatable.get(res_name, 0.0):
                return False
        # DRA driver pools published (initialization.go:148-178): every
        # driver recorded on the claim must have a ResourceSlice pinned to
        # this node before workloads can rely on its devices
        drivers = claim.metadata.annotations.get(l.DRA_DRIVERS_ANNOTATION_KEY)
        if drivers:
            published = {
                s.driver
                for s in self.store.list(ObjectStore.RESOURCE_SLICES)
                if s.node_name == node.name
            }
            if any(d and d not in published for d in drivers.split(",")):
                return False
        node.metadata.labels[l.NODE_INITIALIZED_LABEL_KEY] = "true"
        self.store.update(ObjectStore.NODES, node)
        claim.conditions.set_true(COND_INITIALIZED, "Initialized", now=self.clock.now())
        return True

    # -- liveness (liveness.go:59-113) -----------------------------------------

    def _liveness(self, claim: NodeClaim) -> None:
        if claim.conditions.is_true(COND_REGISTERED):
            return
        age = self.clock.now() - claim.metadata.creation_timestamp
        if age > LAUNCH_TTL_SECONDS:
            # stamp the reason BEFORE deleting so the informer's DELETED
            # event (and anything reading the final object) can tell a
            # liveness reap from an operator delete (liveness.go:87-93)
            claim.conditions.set_false(
                COND_REGISTERED,
                "LivenessTimeout",
                f"registration did not complete within {LAUNCH_TTL_SECONDS:.0f}s",
                self.clock.now(),
            )
            claim.metadata.finalizers = []
            self.store.delete(ObjectStore.NODECLAIMS, claim.name)

    # -- finalize (controller.go:198) -------------------------------------------

    def _finalize(self, claim: NodeClaim) -> None:
        from karpenter_tpu.controllers.node_termination import TERMINATION_TS_ANNOTATION
        from karpenter_tpu.models import labels as labels_mod
        from karpenter_tpu.utils import metrics

        # stamp the forced-termination wall time ONCE at finalize start
        # (lifecycle/controller.go:289): claims without a TGP wait for the
        # drain forever, exactly like the reference
        termination_time = None
        tgp = claim.spec.termination_grace_period_seconds
        if tgp is not None:
            stamped = claim.metadata.annotations.get(TERMINATION_TS_ANNOTATION)
            if stamped is None:
                termination_time = self.clock.now() + tgp
                # repr keeps full float precision — %g would truncate epoch
                # timestamps to 6 significant digits
                claim.metadata.annotations[TERMINATION_TS_ANNOTATION] = repr(termination_time)
                self.store.update(ObjectStore.NODECLAIMS, claim)
            else:
                termination_time = float(stamped)
        # drain first: taint + evict pods so they reschedule (the node
        # termination flow, termination/controller.go:93-191); pods that
        # refuse disruption block finalization until the TGP forces them
        node = self._node_for(claim)
        if node is not None:
            _, blocking = self.terminator.prepare(node, termination_time)
            grace_elapsed = (
                termination_time is not None and self.clock.now() >= termination_time
            )
            if blocking and not grace_elapsed:
                # requeue: the drain is incomplete and the grace period (if
                # any) hasn't expired — the instance must keep running
                return
            # await volume detachment (termination/controller.go:236-277):
            # the attach-detach controller deletes VolumeAttachments as
            # drained pods' volumes unmount; terminating the instance
            # first would strand writes. The reference additionally
            # filters out attachments held ONLY by non-drainable pods
            # (filterVolumeAttachments) — vacuous in this harness, where
            # eviction is synchronous: any pod still blocking the drain
            # returned above, so every pod reaching this point has been
            # evicted. The TGP overrides the wait.
            pending = [
                va
                for va in self.store.list(ObjectStore.VOLUME_ATTACHMENTS)
                if va.node_name == node.name
            ]
            if pending and not grace_elapsed:
                from karpenter_tpu.models.nodeclaim import COND_VOLUMES_DETACHED

                claim.conditions.set_unknown(
                    COND_VOLUMES_DETACHED,
                    "AwaitingVolumeDetachment",
                    f"{len(pending)} volume attachments pending",
                    self.clock.now(),
                )
                return
            from karpenter_tpu.models.nodeclaim import COND_VOLUMES_DETACHED

            if pending:
                claim.conditions.set_false(
                    COND_VOLUMES_DETACHED,
                    "TerminationGracePeriodElapsed",
                    "TerminationGracePeriodElapsed",
                    self.clock.now(),
                )
            else:
                claim.conditions.set_true(
                    COND_VOLUMES_DETACHED, "VolumesDetached", now=self.clock.now()
                )
        # then instance termination (the provider owns the node object in
        # simulated clouds); the store node is only force-dropped if the
        # provider had already lost the instance
        try:
            if claim.status.provider_id:
                self.cloud.delete(claim)
        except errors.NodeClaimNotFoundError:
            pass  # instance already gone — finalizer can drop
        except errors.TransientError as e:
            # retryable (throttle/brownout): keep the finalizer and
            # requeue — the instance MUST NOT leak because one delete
            # call flaked (the reference retries until NotFound)
            metrics.TRANSIENT_RETRIES.inc(controller="nodeclaim.lifecycle")
            claim.conditions.set_unknown(
                "InstanceTerminating",
                "TransientDeleteFailure",
                str(e),
                self.clock.now(),
            )
            return
        # terminated = the instance is actually gone (counted here, after
        # the delete, so a transiently-failed finalize can't double-count)
        metrics.NODECLAIMS_TERMINATED.inc(
            reason=claim.metadata.annotations.get(
                "karpenter.sh/termination-reason", "deleted"
            ),
            nodepool=claim.metadata.labels.get(labels_mod.NODEPOOL_LABEL_KEY, ""),
        )
        node = self._node_for(claim)
        if node is not None:
            node.metadata.finalizers = []
            self.store.delete(ObjectStore.NODES, node.name)
        self.store.remove_finalizer(ObjectStore.NODECLAIMS, claim.name, l.TERMINATION_FINALIZER)
        # deletion -> finalizer drop: the claim's full termination wall
        # time (drain + volume detach + instance delete)
        if claim.metadata.deletion_timestamp is not None:
            metrics.NODECLAIM_TERMINATION_DURATION.observe(
                max(self.clock.now() - claim.metadata.deletion_timestamp, 0.0)
            )

    # -- helpers -----------------------------------------------------------------

    def _node_for(self, claim: NodeClaim):
        if not claim.status.provider_id:
            return None
        return self.store.node_by_provider_id(claim.status.provider_id)
