"""NodeOverlay runtime controller: validation, conflict detection, and
atomic swap of the evaluated overlay store.

Counterpart of reference pkg/controllers/nodeoverlay/controller.go:62-300 +
store.go:45-288: one reconcile revalidates EVERY overlay against every
nodepool's (pre-overlay) catalog, surfaces runtime-validation failures and
weight-ties as status conditions, and publishes the surviving overlays +
the evaluated-pool set atomically. Until a pool appears in an evaluated
store, the overlay decorator refuses its catalog with
UnevaluatedNodePoolError (store.go:64-65,84-85) and provisioning skips the
pool. Reconciles re-run every 6 hours (controller.go:140) and immediately
on overlay / nodepool events (manager wiring, controller.go:146-152).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from karpenter_tpu.cloudprovider.instancetype import InstanceType
from karpenter_tpu.models import labels as l
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.scheduling.requirements import node_selector_requirement
from karpenter_tpu.state.store import ObjectStore

CONDITION_VALIDATION_SUCCEEDED = "ValidationSucceeded"
REQUEUE_SECONDS = 6 * 3600.0  # controller.go:140


@dataclass
class EvaluatedOverlays:
    """One immutable evaluation result (internalInstanceTypeStore):
    the surviving overlays in weight order + the pools they were
    evaluated against. Swapped atomically into the shared store."""

    active: list = field(default_factory=list)  # valid, conflict-free
    evaluated_pools: frozenset = frozenset()


class EvaluatedOverlayStore:
    """Shared seam between the controller (writer) and the overlay
    cloud-provider decorator (reader) — store.go:45-100. `None` current
    value means the controller has not completed a single evaluation,
    so EVERY pool is unevaluated."""

    def __init__(self):
        self._lock = threading.Lock()
        self._current: EvaluatedOverlays | None = None

    def swap(self, evaluated: EvaluatedOverlays) -> None:
        with self._lock:
            self._current = evaluated

    def current(self) -> EvaluatedOverlays | None:
        with self._lock:
            return self._current

    def reset(self) -> None:  # store.go:288 (tests)
        with self._lock:
            self._current = None


def runtime_validate(overlay) -> str | None:
    """types.go RuntimeValidate: price strings must parse, capacity
    values must be non-negative, requirement operators must construct.
    Returns an error string, or None when valid."""
    if overlay.price is not None:
        p = overlay.price
        try:
            float(p[:-1] if p.endswith("%") else p)
        except (ValueError, TypeError):
            return f"invalid price {p!r}: not absolute, ±delta, or ±percent"
        if p.endswith("%") and not p.startswith(("+", "-")):
            return f"invalid price {p!r}: percent adjustments need a sign"
        if not p.startswith(("+", "-")) and float(p) < 0:
            return f"invalid price {p!r}: absolute price must be >= 0"
    for res_name, qty in overlay.capacity.items():
        if qty < 0:
            return f"invalid capacity {res_name}={qty}: must be >= 0"
    try:
        for r in overlay.requirements:
            node_selector_requirement(r["key"], r["operator"], r.get("values", ()))
    except (KeyError, ValueError) as err:
        return f"invalid requirement: {err}"
    return None


def _pool_context_reqs(pool, it: InstanceType) -> Requirements:
    """The requirement surface an overlay matches against: the shared
    nodepool base (overlay.pool_base_reqs — validation and application
    must agree) + the instance type's own requirements."""
    from karpenter_tpu.cloudprovider.overlay import pool_base_reqs

    reqs = pool_base_reqs(pool)
    reqs.add(*it.requirements.values())
    return reqs


class NodeOverlayController:
    """The reconcile loop (controller.go:73-140)."""

    def __init__(self, store: ObjectStore, inner_cloud, clock, evaluated_store: EvaluatedOverlayStore):
        self.store = store
        self.inner = inner_cloud  # PRE-overlay provider: evaluation must
        # see the raw catalog, not its own last output
        self.clock = clock
        self.evaluated = evaluated_store
        self._next_requeue = 0.0

    # -- scheduling --------------------------------------------------------

    def maybe_reconcile(self) -> dict | None:
        """Periodic entry point (the 6h RequeueAfter)."""
        if self.clock.now() < self._next_requeue:
            return None
        return self.reconcile()

    def reconcile(self) -> dict:
        overlays = sorted(
            self.store.list(ObjectStore.NODE_OVERLAYS),
            key=lambda o: (-o.weight, o.name),  # OrderByWeight
        )
        pools = self.store.nodepools()
        if not overlays:
            # nothing to validate: publish the evaluated-pool set without
            # building a single catalog (pool events land on the
            # provisioning-critical path)
            self.evaluated.swap(
                EvaluatedOverlays(
                    active=[],
                    evaluated_pools=frozenset(p.metadata.name for p in pools),
                )
            )
            self._next_requeue = self.clock.now() + REQUEUE_SECONDS
            return {
                "overlays": 0,
                "active": 0,
                "conflicted": 0,
                "invalid": 0,
                "evaluated_pools": len(pools),
            }
        pool_its = {}
        for p in pools:
            # a single broken pool must not block overlays on healthy ones
            # (controller.go:92-101)
            try:
                pool_its[p.metadata.name] = (p, self.inner.get_instance_types(p))
            except Exception:  # noqa: BLE001 — provider errors skip the pool
                continue

        invalid: dict[str, str] = {}
        conflicted: list[str] = []
        active: list = []
        # conflict tracking, assuming weight-descending processing order
        # (store.go:212-288): price per (pool, it, offering-key), capacity
        # per (pool, it) tracking the LOWEST weight that touched it
        price_seen: dict[tuple, int] = {}  # -> lowest weight so far
        cap_seen: dict[tuple, tuple] = {}  # -> (lowest weight, its resource keys)

        for o in overlays:
            err = runtime_validate(o)
            if err is not None:
                invalid[o.name] = err
                continue
            reqs = Requirements(
                *(
                    node_selector_requirement(r["key"], r["operator"], r.get("values", ()))
                    for r in o.requirements
                )
            )
            touches = []  # deferred writes: validate-all-then-store
            conflict = False
            for pool_name, (pool, its) in pool_its.items():
                for it in its:
                    ctx = _pool_context_reqs(pool, it)
                    if not ctx.is_compatible(reqs, l.WELL_KNOWN_LABELS):
                        continue
                    offerings = [
                        of
                        for of in it.offerings
                        if _offering_compatible(it, of, reqs)
                    ]
                    if not offerings:
                        continue
                    if o.price is not None:
                        for of in offerings:
                            key = (pool_name, it.name, _offering_key(of))
                            if price_seen.get(key) == o.weight:
                                conflict = True  # store.go:267-287
                            touches.append(("price", key))
                    if o.capacity:
                        key = (pool_name, it.name)
                        prev = cap_seen.get(key)
                        if (
                            prev is not None
                            and prev[0] == o.weight
                            and any(r in prev[1] for r in o.capacity)
                        ):
                            conflict = True  # store.go:212-238
                        touches.append(("cap", key))
                if conflict:
                    break
            if conflict:
                conflicted.append(o.name)
                continue
            # atomic store phase (controller.go:174-179)
            for kind, key in touches:
                if kind == "price":
                    price_seen[key] = o.weight
                else:
                    cap_seen[key] = (o.weight, frozenset(o.capacity))
            active.append(o)

        self._update_statuses(overlays, invalid, conflicted)
        self.evaluated.swap(
            EvaluatedOverlays(
                active=active,
                evaluated_pools=frozenset(pool_its),
            )
        )
        self._next_requeue = self.clock.now() + REQUEUE_SECONDS
        return {
            "overlays": len(overlays),
            "active": len(active),
            "conflicted": len(conflicted),
            "invalid": len(invalid),
            "evaluated_pools": len(pool_its),
        }

    def _update_statuses(self, overlays, invalid, conflicted) -> None:
        now = self.clock.now()
        for o in overlays:
            if o.name in invalid:
                o.conditions.set_false(
                    CONDITION_VALIDATION_SUCCEEDED,
                    "RuntimeValidation",
                    invalid[o.name],
                    now=now,
                )
            elif o.name in conflicted:
                o.conditions.set_false(
                    CONDITION_VALIDATION_SUCCEEDED,
                    "Conflict",
                    "conflict with another overlay",
                    now=now,
                )
            else:
                o.conditions.set_true(
                    CONDITION_VALIDATION_SUCCEEDED, "Validated", now=now
                )


def _offering_key(of) -> tuple:
    """Stable identity for an offering's requirement surface
    (of.Requirements.String() in store.go:240-258)."""
    return tuple(
        sorted(
            (r.key, r.complement, tuple(sorted(r.values)))
            for r in of.requirements.values()
        )
    )


def _offering_compatible(it: InstanceType, of, overlay_reqs: Requirements) -> bool:
    combined = it.requirements.copy()
    combined.add(*of.requirements.values())
    return combined.is_compatible(overlay_reqs, l.WELL_KNOWN_LABELS)
