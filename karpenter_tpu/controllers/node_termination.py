"""Node termination: taint -> drain -> delete instance -> drop finalizer.

Counterpart of reference pkg/controllers/node/termination
(controller.go:93-191, terminator/terminator.go:96-138): eviction happens
in priority groups (non-critical first, critical last). Evictions here are
immediate — terminationGracePeriod enforcement (terminator.go:140-176,
force-deleting pods whose graceful eviction would overrun the period) is
not modeled yet because the harness has no graceful pod shutdown to race.

Evicted pods return to Pending/Unschedulable, so the provisioner
reschedules them — the harness analog of the kube eviction API.
"""

from __future__ import annotations

from karpenter_tpu.models.node import Node
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.models.taints import DISRUPTED_NO_SCHEDULE_TAINT
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import Clock

CRITICAL_PRIORITY_THRESHOLD = 2_000_000_000  # system-cluster-critical


class Terminator:
    """Priority-grouped drainer (terminator/terminator.go:96-138)."""

    def __init__(self, store: ObjectStore, clock: Clock):
        self.store = store
        self.clock = clock

    def drain(self, node: Node) -> int:
        """Evict every evictable pod on the node; returns how many moved.

        Non-critical pods are evicted before critical ones so critical
        workloads keep running while replacements come up.
        """
        pods = [
            p
            for p in self.store.pods()
            if p.spec.node_name == node.name and not p.is_terminal()
        ]
        pods.sort(key=lambda p: (p.spec.priority >= CRITICAL_PRIORITY_THRESHOLD, p.name))
        evicted = 0
        for pod in pods:
            self._evict(pod)
            evicted += 1
        return evicted

    def _evict(self, pod: Pod) -> None:
        """The eviction-API analog: unbind and mark unschedulable so the
        provisioner picks the pod up again."""
        pod.spec.node_name = ""
        pod.status.phase = "Pending"
        pod.status.conditions["PodScheduled"] = "Unschedulable"
        self.store.update(ObjectStore.PODS, pod)


class NodeTerminationController:
    """Drives the termination of nodes whose claims are deleting."""

    def __init__(self, store: ObjectStore, clock: Clock):
        self.store = store
        self.clock = clock
        self.terminator = Terminator(store, clock)

    def prepare(self, node: Node) -> int:
        """Taint + drain (controller.go:93-138). Returns pods evicted."""
        if not any(t.match(DISRUPTED_NO_SCHEDULE_TAINT) for t in node.spec.taints):
            node.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
            self.store.update(ObjectStore.NODES, node)
        return self.terminator.drain(node)
