"""Node termination: taint -> drain -> delete instance -> drop finalizer.

Counterpart of reference pkg/controllers/node/termination
(controller.go:93-191, terminator/terminator.go:96-176): eviction happens
in priority groups (non-critical first, critical last); pods that refuse
disruption (do-not-disrupt annotation, PDB-blocked) are NOT evicted by the
normal drain — they block the node's finalization until the claim's
terminationGracePeriod forces them out:

  * node termination time T = finalize start + claim TGP, stamped as an
    annotation (lifecycle/controller.go:289);
  * a blocked pod is preemptively deleted at T - pod.TGP so it still gets
    its full grace before the machine dies, with the delete's grace
    clamped to the node's remaining life (DeleteExpiringPods,
    terminator.go:140-176);
  * once now >= T the controller stops waiting for drain/volumes entirely
    (controller.go:244-258).

Evicted pods return to Pending/Unschedulable, so the provisioner
reschedules them — the harness analog of the kube eviction API.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.models import labels as l
from karpenter_tpu.models.node import Node
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.models.taints import DISRUPTED_NO_SCHEDULE_TAINT
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import Clock

CRITICAL_PRIORITY_THRESHOLD = 2_000_000_000  # system-cluster-critical

# annotation carrying the node's forced-termination wall time
# (lifecycle/controller.go:289 TerminationTimestampAnnotationKey)
TERMINATION_TS_ANNOTATION = l.GROUP + "/nodeclaim-termination-timestamp"


class Terminator:
    """Priority-grouped drainer with TGP enforcement
    (terminator/terminator.go:96-176)."""

    def __init__(self, store: ObjectStore, clock: Clock):
        self.store = store
        self.clock = clock

    def _blocked(self, pod: Pod, pdb_blocked: frozenset) -> bool:
        """Pods the voluntary drain must not evict: do-not-disrupt opt-outs
        and PDB-protected pods (the eviction queue's 429 path,
        terminator/eviction.go:93-222)."""
        if pod.metadata.annotations.get(l.DO_NOT_DISRUPT_ANNOTATION_KEY) == "true":
            return True
        return pod.uid in pdb_blocked

    def drain(
        self, node: Node, node_termination_time: Optional[float] = None
    ) -> tuple[int, list[Pod]]:
        """Evict every evictable pod; preemptively delete blocked pods whose
        grace window is due. Returns (pods moved, pods still blocking)."""
        from karpenter_tpu.models.pdb import blocked_pod_uids

        pods = [
            p
            for p in self.store.pods()
            if p.spec.node_name == node.name and not p.is_terminal()
        ]
        pdb_blocked = frozenset(
            blocked_pod_uids(self.store.list(ObjectStore.PDBS), self.store.pods())
        )
        # Non-critical pods are evicted before critical ones so critical
        # workloads keep running while replacements come up.
        pods.sort(key=lambda p: (p.spec.priority >= CRITICAL_PRIORITY_THRESHOLD, p.name))
        evicted = 0
        remaining: list[Pod] = []
        now = self.clock.now()
        for pod in pods:
            if not self._blocked(pod, pdb_blocked):
                self._evict(pod)
                evicted += 1
                continue
            # DeleteExpiringPods (terminator.go:140-166): delete at
            # T - pod.TGP so the pod still gets its full grace, clamped to
            # the node's remaining life (min 1s — never force from etcd)
            if node_termination_time is not None:
                delete_time = node_termination_time - pod.spec.termination_grace_period_seconds
                if now >= delete_time:
                    grace = max(node_termination_time - now, 1.0)
                    self._evict(pod, grace_seconds=grace)
                    evicted += 1
                    continue
            remaining.append(pod)
        return evicted, remaining

    def _evict(self, pod: Pod, grace_seconds: Optional[float] = None) -> None:
        """The eviction-API analog: unbind and mark unschedulable so the
        provisioner picks the pod up again. grace_seconds records the
        clamped TGP of a preemptive delete (observability only — the
        harness has no in-container shutdown to race)."""
        pod.spec.node_name = ""
        pod.status.phase = "Pending"
        pod.status.conditions["PodScheduled"] = "Unschedulable"
        if grace_seconds is not None:
            pod.metadata.annotations[l.GROUP + "/preemptive-delete-grace-seconds"] = repr(
                grace_seconds
            )
        self.store.update(ObjectStore.PODS, pod)


class NodeTerminationController:
    """Drives the termination of nodes whose claims are deleting."""

    def __init__(self, store: ObjectStore, clock: Clock):
        self.store = store
        self.clock = clock
        self.terminator = Terminator(store, clock)

    def prepare(
        self, node: Node, node_termination_time: Optional[float] = None
    ) -> tuple[int, list[Pod]]:
        """Taint + drain (controller.go:93-138). Returns (pods evicted,
        pods still blocking the drain)."""
        if not any(t.match(DISRUPTED_NO_SCHEDULE_TAINT) for t in node.spec.taints):
            node.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
            self.store.update(ObjectStore.NODES, node)
        return self.terminator.drain(node, node_termination_time)
