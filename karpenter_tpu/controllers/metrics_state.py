"""Per-object state metric controllers: pod + node gauges.

Counterparts of reference pkg/controllers/metrics/pod/controller.go
(karpenter_pods_state, startup/bound durations) and
pkg/controllers/metrics/node/controller.go (allocatable, total pod
requests, utilization). The reference recomputes gauges per reconcile
event; this harness recomputes the whole family per maintenance pass,
clearing first so series for vanished objects don't linger.
"""

from __future__ import annotations

from karpenter_tpu.models import labels as l
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.clock import Clock


class PodMetricsController:
    """karpenter_pods_state + startup/bound latency summaries
    (metrics/pod/controller.go:61-170)."""

    def __init__(self, store: ObjectStore, clock: Clock):
        self.store = store
        self.clock = clock
        self._bound_seen: set[str] = set()
        self._started_seen: set[str] = set()

    def reconcile(self) -> None:
        metrics.POD_STATE.values.clear()
        now = self.clock.now()
        pods = self.store.pods()
        # uids are never reused — prune deleted pods so the dedup sets
        # don't grow with total pods ever seen
        live = {p.uid for p in pods}
        self._bound_seen &= live
        self._started_seen &= live
        for pod in pods:
            node = None
            if pod.spec.node_name:
                node = self.store.get(ObjectStore.NODES, pod.spec.node_name)
            metrics.POD_STATE.set(
                1.0,
                name=pod.name,
                namespace=pod.metadata.namespace,
                node=pod.spec.node_name,
                nodepool=(
                    node.metadata.labels.get(l.NODEPOOL_LABEL_KEY, "") if node else ""
                ),
                phase=pod.status.phase,
                scheduled=str(bool(pod.spec.node_name)).lower(),
            )
            # latency summaries observed once per pod at the transition
            if pod.spec.node_name and pod.uid not in self._bound_seen:
                self._bound_seen.add(pod.uid)
                metrics.POD_BOUND_DURATION.observe(
                    max(now - pod.metadata.creation_timestamp, 0.0)
                )
            if (
                pod.status.phase == "Running"
                or (pod.spec.node_name and pod.status.start_time is not None)
            ) and pod.uid not in self._started_seen:
                self._started_seen.add(pod.uid)
                start = (
                    pod.status.start_time
                    if pod.status.start_time is not None
                    else now
                )
                metrics.POD_STARTUP_DURATION.observe(
                    max(start - pod.metadata.creation_timestamp, 0.0)
                )


class NodeMetricsController:
    """karpenter_nodes_* resource gauges
    (metrics/node/controller.go:70-140)."""

    def __init__(self, store: ObjectStore, cluster: Cluster):
        self.store = store
        self.cluster = cluster

    def reconcile(self) -> None:
        metrics.NODE_ALLOCATABLE.values.clear()
        metrics.NODE_TOTAL_POD_REQUESTS.values.clear()
        metrics.NODE_UTILIZATION.values.clear()
        for sn in self.cluster.nodes():
            node = sn.node
            if node is None:
                continue
            pool = node.metadata.labels.get(l.NODEPOOL_LABEL_KEY, "")
            alloc = dict(node.status.allocatable)
            requested: dict[str, float] = {}
            for pod in sn.pods.values():
                if not pod.is_terminal():
                    requested = res.merge(requested, pod.total_requests())
            for rname, qty in alloc.items():
                metrics.NODE_ALLOCATABLE.set(
                    qty, node_name=node.name, nodepool=pool, resource_type=rname
                )
                req = requested.get(rname, 0.0)
                metrics.NODE_TOTAL_POD_REQUESTS.set(
                    req, node_name=node.name, nodepool=pool, resource_type=rname
                )
                if qty > 0:
                    metrics.NODE_UTILIZATION.set(
                        100.0 * req / qty,
                        node_name=node.name,
                        nodepool=pool,
                        resource_type=rname,
                    )


class StatusConditionMetricsController:
    """operator_status_condition_count gauges over claims and pools
    (operatorpkg status.NewController analog, controllers.go:140-158)."""

    def __init__(self, store: ObjectStore):
        self.store = store

    def reconcile(self) -> None:
        metrics.STATUS_CONDITION_COUNT.values.clear()
        for kind, objs in (
            ("NodeClaim", self.store.nodeclaims()),
            ("NodePool", self.store.nodepools()),
        ):
            for obj in objs:
                for cond in obj.conditions.all():
                    key = dict(kind=kind, type=cond.type, status=cond.status)
                    cur = metrics.STATUS_CONDITION_COUNT.get(**key)
                    metrics.STATUS_CONDITION_COUNT.set(cur + 1.0, **key)
