"""Device allocation controller.

Counterpart of the reference's deviceallocation controller
(pkg/controllers/nodeclaim/deviceallocation/) fused with the dra-kwok-driver
harness (kwok/apis + dra driver): once a NodeClaim that carried simulated
device allocations launches and its node's instance type is known, the
controller collapses the per-IT superposition to the chosen type, writes the
ResourceClaim's status allocation (devices + node selector + reservedFor),
and publishes the instance type's template ResourceSlices as node-local
in-cluster slices — the driver's job in a real cluster.

Template device identities are node-scoped at publish time (pool name gets
the node suffix) so two nodes launched from the same instance type never
merge into one pool with duplicate device names, which pool gathering would
flag invalid (pool.go:311).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from karpenter_tpu.models import labels as l
from karpenter_tpu.scheduling.dra.types import (
    AllocatedDevice,
    DeviceClaimStatus,
    ResourceSlice,
)
from karpenter_tpu.scheduling.requirements import Requirement, Requirements
from karpenter_tpu.state.store import ObjectStore


def _node_scoped_pool(pool: str, node_name: str) -> str:
    return f"{pool}-{node_name}"


@dataclass
class PendingAllocation:
    """One claim's simulated allocation awaiting launch collapse."""

    claim_name: str
    nodeclaim_name: str  # "" for existing-node allocations
    node_name: str  # set for existing-node allocations
    metadata: object  # dra.allocator.ResourceClaimAllocationMetadata
    pod_uids: list[str] = field(default_factory=list)
    # per-IT template slices of the originating candidate set, for publish
    it_slices: dict[str, list[ResourceSlice]] = field(default_factory=dict)


class DeviceAllocationController:
    def __init__(self, store: ObjectStore, clock=None):
        from karpenter_tpu.state.store import EventType

        self.store = store
        self.clock = clock
        self._pending: list[PendingAllocation] = []
        self._published_nodes: set[str] = set()
        store.watch(
            ObjectStore.NODES,
            lambda ev, obj: (
                self.on_node_deleted(obj.metadata.name) if ev == EventType.DELETED else None
            ),
        )

    def register(self, pending: PendingAllocation) -> None:
        self._pending.append(pending)

    def reconcile_once(self) -> int:
        """Resolve pending allocations whose node is known; returns how many
        claim statuses were written."""
        written = 0
        still_pending: list[PendingAllocation] = []
        for p in self._pending:
            outcome = self._resolve_node(p)
            if outcome == "drop":
                # Target vanished (failed launch / GC / node deleted): the
                # claim stays unallocated and the next loop re-runs the DFS.
                continue
            if outcome == "wait":
                still_pending.append(p)
                continue
            node_name, it_name = outcome
            if self._write_allocation(p, node_name, it_name):
                written += 1
        self._pending = still_pending
        return written

    def _resolve_node(self, p: PendingAllocation):
        """(node_name, instance_type) once launch collapsed the claim;
        "wait" while launch is in flight; "drop" when the target is gone."""
        if p.node_name:
            node = self.store.get(ObjectStore.NODES, p.node_name)
            if node is None:
                return "drop"
            return p.node_name, node.metadata.labels.get(l.LABEL_INSTANCE_TYPE, "")
        claim = self.store.get(ObjectStore.NODECLAIMS, p.nodeclaim_name)
        if claim is None:
            return "drop"
        it_name = claim.metadata.labels.get(l.LABEL_INSTANCE_TYPE, "")
        if not it_name or not claim.status.provider_id:
            return "wait"
        node = self.store.node_by_provider_id(claim.status.provider_id)
        if node is None:
            return "wait"
        return node.metadata.name, it_name

    def _write_allocation(self, p: PendingAllocation, node_name: str, it_name: str) -> bool:
        rc = self.store.get(ObjectStore.RESOURCE_CLAIMS, p.claim_name)
        if rc is None:
            return False
        if rc.allocation is not None:
            # Already committed (a later pod joined the claim): just extend
            # the consumer reservation (reservedFor maintenance).
            new_uids = [u for u in p.pod_uids if u not in rc.reserved_for]
            if new_uids:
                rc.reserved_for.extend(new_uids)
                self.store.update(ObjectStore.RESOURCE_CLAIMS, rc)
            return False
        meta = p.metadata
        results = meta.devices.get(it_name)
        if results is None and meta.devices:
            # Launch collapsed to a type the allocator never simulated
            # (shouldn't happen: the claim's requirements pin the surviving
            # set). Writing another IT's simulated devices would reference
            # hardware that doesn't exist on this node — leave the claim
            # unallocated so the next loop re-runs the DFS against reality.
            return False
        devices = []
        for r in results or []:
            pool = r.device_id.pool
            if r.device_id.template:
                pool = _node_scoped_pool(pool, node_name)
            devices.append(
                AllocatedDevice(
                    request=str(r.request_name),
                    driver=r.device_id.driver,
                    pool=pool,
                    device=r.device_id.device,
                    consumed_capacity=dict(r.consumed_capacity) if r.consumed_capacity else None,
                )
            )
        if meta.used_template_devices:
            # Node-local devices: the claim is usable only from this node.
            terms = [Requirements(Requirement.new(l.LABEL_HOSTNAME, "In", node_name))]
        else:
            contributed = meta.contributed_requirements.get(it_name)
            terms = [contributed.copy()] if contributed and len(contributed) else None
        rc.allocation = DeviceClaimStatus(devices=devices, node_selector_terms=terms)
        rc.reserved_for = list(p.pod_uids)
        self.store.update(ObjectStore.RESOURCE_CLAIMS, rc)
        if meta.used_template_devices:
            self._publish_slices(p, node_name, it_name)
        return True

    def _publish_slices(self, p: PendingAllocation, node_name: str, it_name: str) -> None:
        """The driver's half: surface the launched instance's template
        devices as published, node-pinned ResourceSlices."""
        if node_name in self._published_nodes:
            return
        self._published_nodes.add(node_name)
        from karpenter_tpu.models.objects import ObjectMeta

        # Group template slices per (driver, pool): pool gathering treats a
        # counter-bearing slice as counter-only (pool.go:293-296), so a
        # template carrying both devices and SharedCounters publishes as two
        # slices, and resource_slice_count covers the full scoped pool.
        by_pool: dict[tuple[str, str], list[ResourceSlice]] = {}
        for tmpl in p.it_slices.get(it_name, []):
            by_pool.setdefault((tmpl.driver, tmpl.pool), []).append(tmpl)
        for (driver, orig_pool), tmpls in by_pool.items():
            pool = _node_scoped_pool(orig_pool, node_name)
            device_slices = [t for t in tmpls if t.devices]
            counter_sets = [cs for t in tmpls for cs in (t.shared_counters or [])]
            total = len(device_slices) + (1 if counter_sets else 0)
            published: list[ResourceSlice] = []
            for t in device_slices:
                published.append(
                    ResourceSlice(
                        driver=driver,
                        pool=pool,
                        devices=list(t.devices),
                        generation=1,
                        node_name=node_name,
                    )
                )
            if counter_sets:
                published.append(
                    ResourceSlice(
                        driver=driver,
                        pool=pool,
                        generation=1,
                        shared_counters=counter_sets,
                    )
                )
            for idx, s in enumerate(published):
                s.resource_slice_count = total
                s.metadata = ObjectMeta(name=f"{node_name}-{driver}-{orig_pool}-{idx}")
                if self.store.get(ObjectStore.RESOURCE_SLICES, s.metadata.name) is None:
                    self.store.create(ObjectStore.RESOURCE_SLICES, s)

    def on_node_deleted(self, node_name: str) -> None:
        """Driver cleanup: withdraw the node's published slices — including
        the pool's counter-set slice, which carries no node pin but shares
        the node-prefixed name (leaving it would strand a permanently
        incomplete pool that fails every All-mode claim)."""
        self._published_nodes.discard(node_name)
        for s in list(self.store.list(ObjectStore.RESOURCE_SLICES)):
            if s.node_name == node_name or s.metadata.name.startswith(f"{node_name}-"):
                self.store.delete(ObjectStore.RESOURCE_SLICES, s.metadata.name)
