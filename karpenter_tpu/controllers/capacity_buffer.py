"""Capacity buffers: headroom reservation via virtual pods.

Counterpart of reference pkg/apis/autoscaling/v1beta1 CapacityBuffer +
pkg/controllers/capacitybuffer and the virtual-pod injection in
provisioning (buffers.go:72-190): a buffer asks for N replicas of a pod
template to be schedulable at all times; the provisioner injects synthetic
pods so capacity stays warm, and real pods displace them naturally
(virtual pods never bind, so their nodes always look available to the
kube-scheduler).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field

from karpenter_tpu.models.objects import ObjectMeta
from karpenter_tpu.models.pod import Pod, PodSpec

BUFFER_POD_ANNOTATION = "karpenter.sh/capacity-buffer"


@dataclass
class CapacityBuffer:
    """autoscaling.x-k8s.io/v1beta1 CapacityBuffer (capacitybuffer.go:73)."""

    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="buffer"))
    pod_template: PodSpec = field(default_factory=PodSpec)
    replicas: int = 0

    @property
    def name(self) -> str:
        return self.metadata.name


def virtual_pods(buffers: list[CapacityBuffer]) -> list[Pod]:
    """Synthetic pods injected into a Solve (buffers.go:72-190); marked so
    nomination and binding skip them (scheduler.go:305-344)."""
    out = []
    for buffer in buffers:
        for i in range(buffer.replicas):
            pod = Pod(
                metadata=ObjectMeta(
                    name=f"buffer-{buffer.name}-{i}",
                    annotations={BUFFER_POD_ANNOTATION: buffer.name},
                ),
                spec=copy.deepcopy(buffer.pod_template),
            )
            pod.status.conditions["PodScheduled"] = "Unschedulable"
            out.append(pod)
    return out


def is_buffer_pod(pod: Pod) -> bool:
    return BUFFER_POD_ANNOTATION in pod.metadata.annotations
