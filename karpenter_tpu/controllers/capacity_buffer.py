"""Capacity buffers: headroom reservation via virtual pods + the buffer
status controller.

Counterpart of reference pkg/apis/autoscaling/v1beta1 CapacityBuffer +
pkg/controllers/capacitybuffer/controller.go (template resolution, replica
computation, ReadyForProvisioning) + the provisioning-side Provisioning
condition and virtual-pod injection (buffers.go:39-380): a buffer asks for
N replicas of a pod template to be schedulable at all times; the
provisioner injects synthetic pods so capacity stays warm, real pods
displace them naturally (virtual pods never bind, so their nodes always
look available to the kube-scheduler), and the buffer's status reports
whether the headroom currently fits existing capacity.
"""

from __future__ import annotations

import copy
import math
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.models.objects import ConditionSet, ObjectMeta
from karpenter_tpu.models.pod import Pod, PodSpec
from karpenter_tpu.state.store import ObjectStore

BUFFER_POD_ANNOTATION = "karpenter.sh/capacity-buffer"

# conditions (v1beta1/constants.go + buffers.go:303-355)
COND_READY_FOR_PROVISIONING = "ReadyForProvisioning"
COND_PROVISIONING = "Provisioning"

RECONCILE_SECONDS = 30.0  # controller.go:103 RequeueAfter


@dataclass
class PodTemplate:
    """A core/v1 PodTemplate the buffer's podTemplateRef resolves against
    (apps.ResolvePodTemplateRef)."""

    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="template"))
    spec: PodSpec = field(default_factory=PodSpec)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class Scalable:
    """A scale-subresource target the buffer's scalableRef resolves
    against (apps.ResolveScalableRef): replicas + the pod shape."""

    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="scalable"))
    replicas: int = 0
    pod_spec: PodSpec = field(default_factory=PodSpec)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class CapacityBufferStatus:
    replicas: Optional[int] = None  # resolved desired replica count
    pod_template_generation: Optional[int] = None


@dataclass
class CapacityBuffer:
    """autoscaling.x-k8s.io/v1beta1 CapacityBuffer (capacitybuffer.go:73)."""

    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="buffer"))
    pod_template: PodSpec = field(default_factory=PodSpec)
    replicas: int = 0
    # refs resolved by the buffer controller (controller.go:146-176)
    pod_template_ref: Optional[str] = None
    scalable_ref: Optional[str] = None
    percentage: Optional[int] = None  # of the scalable's replicas
    limits: dict[str, float] = field(default_factory=dict)
    status: CapacityBufferStatus = field(default_factory=CapacityBufferStatus)
    conditions: ConditionSet = field(default_factory=ConditionSet)

    @property
    def name(self) -> str:
        return self.metadata.name


def _limit_replicas(limits: dict[str, float], spec: PodSpec) -> Optional[int]:
    """floor(limit/request) minimum over overlapping resources
    (helpers.go:32-56); None when limits constrain nothing."""
    requests = spec.requests or {}
    best = None
    for res_name, limit in limits.items():
        req = requests.get(res_name, 0.0)
        if req <= 0.0:
            continue
        n = int(math.floor(limit / req))
        best = n if best is None else min(best, n)
    return best


def _percentage_replicas(scalable_replicas: int, percentage: int) -> int:
    """ceil(replicas * pct / 100), floored at 1 when both positive
    (helpers.go:59-68)."""
    n = int(math.ceil(scalable_replicas * percentage / 100.0))
    if n < 1 and percentage > 0 and scalable_replicas > 0:
        n = 1
    return n


def resolved_replicas(buffer: CapacityBuffer) -> int:
    """The buffer's effective replica count: controller-resolved status
    when present, else the inline spec (bare-harness compatibility — the
    same fallback posture as the overlay decorator's direct mode)."""
    if buffer.conditions.is_true(COND_READY_FOR_PROVISIONING):
        return buffer.status.replicas or 0
    if buffer.conditions.is_false(COND_READY_FOR_PROVISIONING):
        return 0  # resolution failed: no headroom until it recovers
    return buffer.replicas


def resolve_buffer(
    buffer: CapacityBuffer, store: Optional[ObjectStore]
) -> tuple[Optional[PodSpec], Optional[int], Optional[str]]:
    """THE resolution walk (controller.go:146-176), shared by the status
    controller, the virtual-pod factory and the provisioner's cache key so
    they can never disagree: podTemplateRef > scalableRef > inline.
    Returns (spec, scalable_replicas, failure_reason)."""
    if buffer.pod_template_ref is not None:
        tmpl = (
            store.get(ObjectStore.POD_TEMPLATES, buffer.pod_template_ref)
            if store is not None
            else None
        )
        if tmpl is None:
            return None, None, "PodTemplateNotFound"
        return tmpl.spec, None, None
    if buffer.scalable_ref is not None:
        s = (
            store.get(ObjectStore.SCALABLES, buffer.scalable_ref)
            if store is not None
            else None
        )
        if s is None:
            return None, None, "ScalableRefNotFound"
        return s.pod_spec, s.replicas, None
    return buffer.pod_template, None, None


def resolved_pod_spec(
    buffer: CapacityBuffer, store: Optional[ObjectStore]
) -> Optional[PodSpec]:
    """The pod shape to replicate, or None when a ref doesn't resolve."""
    spec, _replicas, _reason = resolve_buffer(buffer, store)
    return spec


def virtual_pods(
    buffers: list[CapacityBuffer], store: Optional[ObjectStore] = None
) -> list[Pod]:
    """Synthetic pods injected into a Solve (buffers.go:63-190); marked so
    nomination and binding skip them (scheduler.go:305-344)."""
    out = []
    for buffer in buffers:
        spec = resolved_pod_spec(buffer, store)
        if spec is None:
            continue
        for i in range(resolved_replicas(buffer)):
            pod = Pod(
                metadata=ObjectMeta(
                    name=f"buffer-{buffer.name}-{i}",
                    annotations={BUFFER_POD_ANNOTATION: buffer.name},
                ),
                spec=copy.deepcopy(spec),
            )
            pod.status.conditions["PodScheduled"] = "Unschedulable"
            out.append(pod)
    return out


def is_buffer_pod(pod: Pod) -> bool:
    return BUFFER_POD_ANNOTATION in pod.metadata.annotations


def buffer_of(pod: Pod) -> Optional[str]:
    return pod.metadata.annotations.get(BUFFER_POD_ANNOTATION)


class CapacityBufferController:
    """Resolve each buffer's pod shape, compute the target replica count,
    stamp ReadyForProvisioning, and trigger provisioning
    (capacitybuffer/controller.go:70-103). Reconciles every 30s and on
    buffer / pod-template / scalable events (manager wiring)."""

    def __init__(self, store: ObjectStore, clock, trigger=None):
        self.store = store
        self.clock = clock
        self.trigger = trigger  # the batcher (ProvisionerTrigger analog)
        self._next = 0.0
        # last resolved (replicas, spec-content) per buffer: the periodic
        # requeue only triggers provisioning when something CHANGED, so an
        # idle cluster doesn't re-solve every 30s
        self._last_sig: dict[str, tuple] = {}

    def maybe_reconcile(self) -> Optional[dict]:
        if self.clock.now() < self._next:
            return None
        return self.reconcile()

    def reconcile(self) -> dict:
        now = self.clock.now()
        resolved = 0
        failed = 0
        changed = 0
        buffers = self.store.list(ObjectStore.CAPACITY_BUFFERS)
        live = {b.name for b in buffers}
        removed = len(self._last_sig.keys() - live)
        # a deleted buffer must trigger a pass too: its headroom counts
        # need recomputing (or clearing) so emptiness can reclaim nodes
        changed += removed
        self._last_sig = {k: v for k, v in self._last_sig.items() if k in live}
        for cb in buffers:
            spec, scalable_replicas, reason = resolve_buffer(cb, self.store)
            if reason is not None:
                cb.conditions.set_false(
                    COND_READY_FOR_PROVISIONING,
                    reason,
                    f"{reason}: {cb.pod_template_ref or cb.scalable_ref!r}",
                    now=now,
                )
                failed += 1
                continue
            if cb.pod_template_ref is not None:
                tmpl = self.store.get(ObjectStore.POD_TEMPLATES, cb.pod_template_ref)
                cb.status.pod_template_generation = getattr(
                    tmpl.metadata, "generation", None
                )
            candidates: list[int] = []
            if (
                cb.percentage is not None
                and scalable_replicas is not None
                and scalable_replicas > 0
            ):
                candidates.append(
                    _percentage_replicas(scalable_replicas, cb.percentage)
                )

            # replicas = max(fixed, percentage), bounded by limits; with
            # no size constraint, limits alone determine the count
            # (controller.go computeReplicas:185-215)
            if cb.replicas:
                candidates.append(cb.replicas)
            desired = max(candidates) if candidates else 0
            if cb.limits and spec is not None:
                lim = _limit_replicas(cb.limits, spec)
                if lim is not None:
                    desired = min(desired, lim) if candidates else lim
            cb.status.replicas = desired
            cb.conditions.set_true(
                COND_READY_FOR_PROVISIONING,
                "Resolved",
                "Pod template resolved successfully",
                now=now,
            )
            resolved += 1
            sig = (desired, hash(repr(spec)))
            if self._last_sig.get(cb.name) != sig:
                self._last_sig[cb.name] = sig
                changed += 1
        if changed and self.trigger is not None:
            self.trigger.trigger()
        self._next = now + RECONCILE_SECONDS
        return {"resolved": resolved, "failed": failed}


def update_provisioning_statuses(store: ObjectStore, result, clock) -> dict[str, int]:
    """Post-solve Provisioning conditions + per-node buffer pod counts
    (buffers.go:140-380 computeProvisioningCondition /
    bufferPodCountsFromResults): headroom fully on existing capacity sets
    True (FitsExistingCapacity); headroom that opened new claims or failed
    sets False (RequiresNewCapacity). Returns node_name -> buffer pod
    count so the emptiness path won't delete nodes hosting headroom."""
    now = clock.now()
    buffers = store.list(ObjectStore.CAPACITY_BUFFERS)
    if not buffers:
        return {}
    by_buffer: dict[str, dict[str, int]] = {}

    def bucket(name: str) -> dict[str, int]:
        return by_buffer.setdefault(name, {"new": 0, "existing": 0, "failed": 0})

    node_counts: dict[str, int] = {}
    for claim in result.claims:
        for p in claim.pods:
            b = buffer_of(p)
            if b is not None:
                bucket(b)["new"] += 1
    for node in result.existing or []:
        for p in node.pods:
            b = buffer_of(p)
            if b is not None:
                bucket(b)["existing"] += 1
                node_counts[node.name] = node_counts.get(node.name, 0) + 1
    for p, _reason in result.unschedulable:
        b = buffer_of(p)
        if b is not None:
            bucket(b)["failed"] += 1
    for cb in buffers:
        if cb.conditions.is_false(COND_READY_FOR_PROVISIONING):
            cb.conditions.set_false(
                COND_PROVISIONING,
                "NotReadyForProvisioning",
                "Buffer is not ReadyForProvisioning",
                now=now,
            )
            continue
        desired = resolved_replicas(cb)
        if desired == 0:
            cb.conditions.set_false(
                COND_PROVISIONING,
                "BufferEmpty",
                "Buffer has zero desired replicas",
                now=now,
            )
            continue
        s = by_buffer.get(cb.name)
        if s is None:
            continue  # nothing observed this cycle: leave unchanged
        if s["new"] > 0 or s["failed"] > 0:
            cb.conditions.set_false(
                COND_PROVISIONING,
                "RequiresNewCapacity",
                f"{s['new']}/{desired} virtual pods required new capacity, "
                f"{s['failed']} failed",
                now=now,
            )
        elif s["existing"] == desired:
            cb.conditions.set_true(
                COND_PROVISIONING,
                "FitsExistingCapacity",
                f"All {desired} virtual pods fit on existing capacity",
                now=now,
            )
    return node_counts
