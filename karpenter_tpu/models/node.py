"""Node: the machine object fabricated by the simulated cloud/kubelet."""

from __future__ import annotations

from dataclasses import dataclass, field

from karpenter_tpu.models.objects import ObjectMeta
from karpenter_tpu.models.taints import Taint


@dataclass
class NodeStatus:
    capacity: dict[str, float] = field(default_factory=dict)
    allocatable: dict[str, float] = field(default_factory=dict)
    ready: bool = False


@dataclass
class NodeSpec:
    provider_id: str = ""
    taints: list[Taint] = field(default_factory=list)
    unschedulable: bool = False
    # CSINode analog: per-driver volume attach limits published by the
    # node's kubelet (csinode.spec.drivers[].allocatable.count; consumed by
    # cluster.go:845-857 populateVolumeLimits)
    csi_drivers: dict[str, int] = field(default_factory=dict)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="node"))
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class VolumeAttachment:
    """storage.k8s.io/v1 VolumeAttachment (the subset termination awaits —
    termination/controller.go:236-277): the attach-detach controller
    deletes these as volumes unmount from a draining node. `pvc_name`
    stands in for the Pod -> PVC -> PV <- VolumeAttachment join the
    reference walks (filterVolumeAttachments): the harness PVC is the
    volume identity."""

    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="attachment"))
    node_name: str = ""
    attacher: str = ""
    pvc_name: str = ""

    @property
    def name(self) -> str:
        return self.metadata.name
