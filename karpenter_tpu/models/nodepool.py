"""NodePool: template + policy for a fleet of nodes.

Counterpart of reference pkg/apis/v1/nodepool.go:42-171 (NodePoolSpec,
Disruption, Budget, Limits) and nodepool.go:355 (MustGetAllowedDisruptions).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.models.objects import ConditionSet, ObjectMeta
from karpenter_tpu.models.taints import Taint

# Consolidation policies (nodepool.go:160-171)
CONSOLIDATION_WHEN_EMPTY = "WhenEmpty"
CONSOLIDATION_WHEN_EMPTY_OR_UNDERUTILIZED = "WhenEmptyOrUnderutilized"
CONSOLIDATION_BALANCED = "Balanced"

# Balanced policy approval constant k (nodepool.go:171): approve a
# consolidation iff savingsRatio/disruptionRatio >= 1/k.
BALANCED_K = 2

# Disruption reasons (shared with disruption engine)
REASON_UNDERUTILIZED = "Underutilized"
REASON_EMPTY = "Empty"
REASON_DRIFTED = "Drifted"
REASON_ALL = "All"

# NodePool status condition types
CONDITION_VALIDATION_SUCCEEDED = "ValidationSucceeded"
CONDITION_NODECLASS_READY = "NodeClassReady"
CONDITION_NODE_REGISTRATION_HEALTHY = "NodeRegistrationHealthy"
CONDITION_READY = "Ready"

NODEPOOL_HASH_VERSION = "v1"


@dataclass
class Budget:
    """Max simultaneous disruptions, optionally cron-windowed
    (nodepool.go:119-158)."""

    nodes: str = "10%"  # absolute int or percentage string
    schedule: Optional[str] = None  # cron expression; active window start
    duration_seconds: Optional[float] = None
    reasons: list[str] = field(default_factory=list)  # empty = all reasons

    def __post_init__(self) -> None:
        # schedule and duration must be set together (CRD validation parity)
        if (self.schedule is None) != (self.duration_seconds is None):
            raise ValueError("budget schedule and duration must be specified together")

    def allowed(self, total_nodes: int) -> int:
        s = self.nodes.strip()
        if s.endswith("%"):
            # round UP (nodepool.go:391-396 GetScaledValueFromIntOrPercent
            # roundUp=true) so small pools still allow one disruption
            return int(math.ceil(total_nodes * float(s[:-1]) / 100.0))
        return int(s)

    def is_active(self, now: float) -> bool:
        if self.schedule is None:
            return True
        from karpenter_tpu.utils.cron import in_window

        return in_window(self.schedule, self.duration_seconds or 0.0, now)


@dataclass
class Disruption:
    consolidate_after_seconds: Optional[float] = 0.0  # None = Never
    consolidation_policy: str = CONSOLIDATION_WHEN_EMPTY_OR_UNDERUTILIZED
    budgets: list[Budget] = field(default_factory=lambda: [Budget()])


@dataclass
class Limits:
    """Resource caps incl. the synthetic 'nodes' resource (nodepool.go:~Limits)."""

    resources: dict[str, float] = field(default_factory=dict)

    def exceeded_by(self, usage: dict[str, float]) -> Optional[str]:
        for k, limit in self.resources.items():
            u = usage.get(k, 0.0)
            if u > limit + 1e-9:
                return f"resource {k} usage {u} exceeds limit {limit}"
        return None


@dataclass
class NodeClaimTemplateSpec:
    """The NodeClaim spec stamped out by this pool."""

    taints: list[Taint] = field(default_factory=list)
    startup_taints: list[Taint] = field(default_factory=list)
    requirements: list[dict] = field(default_factory=list)  # {key, operator, values, minValues}
    node_class_ref: Optional[dict] = None
    expire_after_seconds: Optional[float] = None  # None = Never
    termination_grace_period_seconds: Optional[float] = None


@dataclass
class NodeClaimTemplate:
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    spec: NodeClaimTemplateSpec = field(default_factory=NodeClaimTemplateSpec)


@dataclass
class NodePoolSpec:
    template: NodeClaimTemplate = field(default_factory=NodeClaimTemplate)
    disruption: Disruption = field(default_factory=Disruption)
    limits: Optional[Limits] = None
    weight: int = 0  # 1-100; higher = tried first (nodepool.go:~Weight)
    replicas: Optional[int] = None  # static capacity pools
    # batched placement objective for this pool's templates (objectives/
    # registry POLICIES); None defers to KTPU_OBJECTIVE, then lexical
    placement_objective: Optional[str] = None


@dataclass
class NodePoolStatus:
    resources: dict[str, float] = field(default_factory=dict)
    node_count: int = 0


@dataclass
class NodePool:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="default"))
    spec: NodePoolSpec = field(default_factory=NodePoolSpec)
    status: NodePoolStatus = field(default_factory=NodePoolStatus)
    conditions: ConditionSet = field(default_factory=ConditionSet)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def is_static(self) -> bool:
        return self.spec.replicas is not None

    def allowed_disruptions(self, reason: str, total_nodes: int, now: float) -> int:
        """Min over active budgets matching the reason
        (nodepool.go:355 MustGetAllowedDisruptions)."""
        allowed = total_nodes  # no budget = unbounded by budgets
        for budget in self.spec.disruption.budgets:
            if budget.reasons and reason not in budget.reasons and REASON_ALL not in budget.reasons:
                continue
            if not budget.is_active(now):
                continue
            allowed = min(allowed, budget.allowed(total_nodes))
        return allowed

    def static_hash(self) -> str:
        """Hash of drift-relevant static fields (nodepool.go:334-344)."""
        import hashlib
        import json

        payload = {
            "labels": self.spec.template.labels,
            "annotations": self.spec.template.annotations,
            "node_class_ref": self.spec.template.spec.node_class_ref,
            "taints": [(t.key, t.value, t.effect) for t in self.spec.template.spec.taints],
            "startup_taints": [(t.key, t.value, t.effect) for t in self.spec.template.spec.startup_taints],
            "expire_after": self.spec.template.spec.expire_after_seconds,
            "termination_grace_period": self.spec.template.spec.termination_grace_period_seconds,
        }
        return hashlib.sha256(json.dumps(payload, sort_keys=True).encode()).hexdigest()[:16]
