"""NodeClaim: one requested machine.

Counterpart of reference pkg/apis/v1/nodeclaim.go:27 (spec) and
nodeclaim_status.go:25-72 (status + condition types).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.models.objects import ConditionSet, ObjectMeta
from karpenter_tpu.models.taints import Taint

# Condition types (nodeclaim_status.go:25-37)
COND_LAUNCHED = "Launched"
COND_REGISTERED = "Registered"
COND_INITIALIZED = "Initialized"
COND_READY = "Ready"
COND_CONSOLIDATABLE = "Consolidatable"
COND_DRIFTED = "Drifted"
COND_DRAINED = "Drained"
COND_VOLUMES_DETACHED = "VolumesDetached"
COND_INSTANCE_TERMINATING = "InstanceTerminating"
COND_CONSISTENT_STATE_FOUND = "ConsistentStateFound"
COND_DISRUPTION_REASON = "DisruptionReason"


@dataclass
class NodeClaimSpec:
    taints: list[Taint] = field(default_factory=list)
    startup_taints: list[Taint] = field(default_factory=list)
    requirements: list[dict] = field(default_factory=list)  # {key, operator, values, minValues}
    requests: dict[str, float] = field(default_factory=dict)
    node_class_ref: Optional[dict] = None
    termination_grace_period_seconds: Optional[float] = None
    expire_after_seconds: Optional[float] = None


@dataclass
class NodeClaimStatus:
    provider_id: str = ""
    node_name: str = ""
    image_id: str = ""
    capacity: dict[str, float] = field(default_factory=dict)
    allocatable: dict[str, float] = field(default_factory=dict)
    last_pod_event_time: Optional[float] = None


@dataclass
class NodeClaim:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="nodeclaim"))
    spec: NodeClaimSpec = field(default_factory=NodeClaimSpec)
    status: NodeClaimStatus = field(default_factory=NodeClaimStatus)
    conditions: ConditionSet = field(default_factory=ConditionSet)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def nodepool_name(self) -> Optional[str]:
        from karpenter_tpu.models import labels as l

        return self.metadata.labels.get(l.NODEPOOL_LABEL_KEY)

    @property
    def capacity_type(self) -> Optional[str]:
        from karpenter_tpu.models import labels as l

        return self.metadata.labels.get(l.CAPACITY_TYPE_LABEL_KEY)

    @property
    def instance_type_name(self) -> Optional[str]:
        from karpenter_tpu.models import labels as l

        return self.metadata.labels.get(l.LABEL_INSTANCE_TYPE)
