"""Minimal Pod model — just what scheduling and lifecycle need.

Match expressions are plain dicts {key, operator, values} so fixtures read
like YAML. Resource requests are canonical float dicts (see utils.resources).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.models.objects import ObjectMeta
from karpenter_tpu.models.taints import Toleration
from karpenter_tpu.utils import resources as res


@dataclass
class NodeSelectorTerm:
    match_expressions: list[dict] = field(default_factory=list)


@dataclass
class PreferredSchedulingTerm:
    weight: int = 1
    match_expressions: list[dict] = field(default_factory=list)


@dataclass
class NodeAffinity:
    # requiredDuringSchedulingIgnoredDuringExecution: list of OR'd terms
    required: list[NodeSelectorTerm] = field(default_factory=list)
    preferred: list[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    topology_key: str = ""
    label_selector: dict[str, str] = field(default_factory=dict)  # matchLabels only (v0)
    namespaces: list[str] = field(default_factory=list)


@dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    label_selector: dict[str, str] = field(default_factory=dict)
    min_domains: Optional[int] = None
    node_affinity_policy: str = "Honor"  # Honor | Ignore
    node_taints_policy: str = "Ignore"  # Honor | Ignore


@dataclass
class HostPort:
    port: int
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class PodSpec:
    requests: dict[str, float] = field(default_factory=dict)
    limits: dict[str, float] = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: list[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity: list[PodAffinityTerm] = field(default_factory=list)
    preferred_pod_affinity: list[PodAffinityTerm] = field(default_factory=list)
    preferred_pod_anti_affinity: list[PodAffinityTerm] = field(default_factory=list)
    tolerations: list[Toleration] = field(default_factory=list)
    topology_spread_constraints: list[TopologySpreadConstraint] = field(default_factory=list)
    host_ports: list[HostPort] = field(default_factory=list)
    node_name: str = ""
    scheduler_name: str = "default-scheduler"
    priority: int = 0
    pvc_names: list[str] = field(default_factory=list)
    restart_policy: str = "Always"
    # Names of ResourceClaims (DRA) this pod consumes (pod.spec.resourceClaims)
    resource_claims: list[str] = field(default_factory=list)
    # pod.spec.terminationGracePeriodSeconds (k8s defaults to 30s); drives
    # the TGP-clamped preemptive delete during drain (terminator.go:140-176)
    termination_grace_period_seconds: float = 30.0


@dataclass
class PodStatus:
    phase: str = "Pending"
    conditions: dict[str, str] = field(default_factory=dict)
    nominated_node_name: str = ""
    start_time: Optional[float] = None


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="pod"))
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def is_scheduled(self) -> bool:
        return bool(self.spec.node_name)

    def is_pending(self) -> bool:
        return self.status.phase == "Pending" and not self.spec.node_name

    def is_terminal(self) -> bool:
        return self.status.phase in ("Succeeded", "Failed")

    def is_provisionable(self) -> bool:
        """Pending, unbound, and marked unschedulable by the kube-scheduler
        (reference pkg/utils/pod/scheduling.go IsProvisionable)."""
        return self.is_pending() and self.status.conditions.get("PodScheduled") == "Unschedulable"

    def total_requests(self) -> dict[str, float]:
        return res.merge(self.spec.requests, {res.PODS: 1.0})


def make_pod(
    name: str,
    cpu: "str | float" = "100m",
    memory: "str | float" = "64Mi",
    node_selector: Optional[dict[str, str]] = None,
    **kwargs,
) -> Pod:
    """Convenience factory for tests/benchmarks."""
    spec = PodSpec(
        requests={res.CPU: res.parse_quantity(cpu), res.MEMORY: res.parse_quantity(memory)},
        node_selector=node_selector or {},
        **kwargs,
    )
    pod = Pod(metadata=ObjectMeta(name=name), spec=spec)
    pod.status.conditions["PodScheduled"] = "Unschedulable"
    return pod
