"""DaemonSet model — enough for daemon-overhead accounting.

The reference subtracts the requests of daemonset pods that would schedule
onto a node from its usable capacity (scheduler.go:963-1043 daemon
overhead groups). The harness models a DaemonSet as a pod template that
lands on every compatible node.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from karpenter_tpu.models.objects import ObjectMeta
from karpenter_tpu.models.pod import Pod, PodSpec


@dataclass
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="daemonset"))
    pod_template: PodSpec = field(default_factory=PodSpec)

    @property
    def name(self) -> str:
        return self.metadata.name

    def as_pod(self) -> Pod:
        """The template as a schedulable pod for compatibility checks."""
        import copy

        pod = Pod(
            metadata=ObjectMeta(name=f"daemon-{self.name}"),
            spec=copy.deepcopy(self.pod_template),
        )
        return pod
