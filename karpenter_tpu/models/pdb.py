"""PodDisruptionBudget model + limits computation.

Counterpart of reference pkg/utils/pdb (pdb.Limits): a PDB caps voluntary
evictions of its selected pods; a node whose eviction would overrun any
matching PDB cannot be a disruption candidate (disruption/types.go:160).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.models.objects import ObjectMeta
from karpenter_tpu.models.pod import Pod


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="pdb"))
    selector: dict[str, str] = field(default_factory=dict)  # matchLabels
    min_available: Optional[str] = None  # int or percentage string
    max_unavailable: Optional[str] = None

    @property
    def name(self) -> str:
        return self.metadata.name

    def selects(self, pod: Pod) -> bool:
        if not self.selector:
            return False
        return (
            pod.metadata.namespace == self.metadata.namespace
            and all(pod.metadata.labels.get(k) == v for k, v in self.selector.items())
        )

    def _resolve(self, value: str, total: int, round_up: bool) -> int:
        s = value.strip()
        if s.endswith("%"):
            frac = float(s[:-1]) / 100.0 * total
            return int(math.ceil(frac)) if round_up else int(math.floor(frac))
        return int(s)

    def disruptions_allowed(self, matching_healthy: int) -> int:
        """How many of the matching pods may be evicted right now."""
        if self.max_unavailable is not None:
            # Kubernetes rounds maxUnavailable percentages UP
            # (GetScaledValueFromIntOrPercent roundUp=true)
            return max(self._resolve(self.max_unavailable, matching_healthy, True), 0)
        if self.min_available is not None:
            keep = self._resolve(self.min_available, matching_healthy, True)
            return max(matching_healthy - keep, 0)
        return matching_healthy


def blocked_pod_uids(pdbs: list[PodDisruptionBudget], pods: list[Pod]) -> set[str]:
    """Pods whose eviction would violate some PDB (zero budget left).

    The harness treats every running bound pod as healthy.
    """
    out: set[str] = set()
    for pdb in pdbs:
        matching = [p for p in pods if pdb.selects(p) and p.is_scheduled() and not p.is_terminal()]
        if pdb.disruptions_allowed(len(matching)) <= 0:
            out.update(p.uid for p in matching)
    return out
