"""NodePool runtime validation — the checks CRD schema/CEL can't express.

Counterpart of reference pkg/apis/v1/nodepool_validation.go:28-58 +
nodeclaim_validation.go:66-150 (RuntimeValidate): label syntax and
restricted-domain rules, taint syntax + duplicate key/effect detection
across taints and startupTaints, requirement operator/key/value checks.
Consumed by the nodepool.validation controller
(pkg/controllers/nodepool/validation/controller.go:61-84), which flips the
ValidationSucceeded condition and thereby gates pool readiness.
"""

from __future__ import annotations

import re

from karpenter_tpu.models import labels as l

_NAME_RE = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")
_DNS1123_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$")

SUPPORTED_OPERATORS = frozenset(
    {"In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt", "Gte", "Lte"}
)
_VALID_EFFECTS = frozenset({"NoSchedule", "PreferNoSchedule", "NoExecute", ""})


def _qualified_name_errors(key: str) -> list[str]:
    """k8s validation.IsQualifiedName: optional DNS-subdomain prefix +
    63-char name part."""
    errs = []
    parts = key.split("/")
    if len(parts) > 2 or not key:
        return [f"{key!r} is not a qualified name"]
    if len(parts) == 2:
        prefix, name = parts
        if not prefix or len(prefix) > 253 or not _DNS1123_RE.match(prefix):
            errs.append(f"prefix of {key!r} is not a valid DNS subdomain")
    else:
        name = parts[0]
    if not name or len(name) > 63 or not _NAME_RE.match(name):
        errs.append(f"name part of {key!r} must be 1-63 alphanumerics/-/_/.")
    return errs


def _label_value_errors(value: str) -> list[str]:
    if value == "":
        return []
    if len(value) > 63 or not _NAME_RE.match(value):
        return [f"label value {value!r} must be <=63 alphanumerics/-/_/."]
    return []


def _restricted_label_error(key: str) -> str | None:
    """IsRestrictedLabel (labels.go:138-148): well-known keys pass; the
    karpenter.sh domain (and subdomains) is reserved otherwise."""
    if key in l.WELL_KNOWN_LABELS:
        return None
    if l.is_restricted_label(key):
        return (
            f"using label {key} is not allowed as it might interfere with "
            "the internal provisioning logic"
        )
    return None


def validate_nodepool(pool) -> list[str]:
    """All runtime-validation errors for a NodePool; empty = valid."""
    errs: list[str] = []
    tmpl = pool.spec.template

    # validateLabels (nodepool_validation.go:33-49)
    for key, value in tmpl.labels.items():
        if key == l.NODEPOOL_LABEL_KEY:
            errs.append(f"invalid key name {key!r} in labels, restricted")
        errs.extend(_qualified_name_errors(key))
        errs.extend(_label_value_errors(value))
        restricted = _restricted_label_error(key)
        if restricted:
            errs.append(restricted)

    # validateTaints incl. duplicate key/effect across BOTH lists
    # (nodeclaim_validation.go:66-101)
    seen: set[tuple[str, str]] = set()
    for field_name, taints in (
        ("taints", tmpl.spec.taints),
        ("startupTaints", tmpl.spec.startup_taints),
    ):
        for taint in taints:
            if not taint.key:
                errs.append(f"missing taint key in {field_name}")
            else:
                errs.extend(_qualified_name_errors(taint.key))
            if taint.value:
                errs.extend(_qualified_name_errors(taint.value))
            if taint.effect not in _VALID_EFFECTS:
                errs.append(f"invalid effect {taint.effect!r} in {field_name}")
            pair = (taint.key, taint.effect)
            if pair in seen:
                errs.append(
                    f"duplicate taint Key/Effect pair {taint.key}={taint.effect}"
                )
            seen.add(pair)

    # validateRequirements + NodePoolKeyDoesNotExist
    # (nodeclaim_validation.go:108-150, nodepool_validation.go:51-57)
    for r in tmpl.spec.requirements:
        key = r.get("key", "")
        key = l.NORMALIZED_LABELS.get(key, key)
        if key == l.NODEPOOL_LABEL_KEY:
            errs.append(f"invalid key: {key!r} in requirements, restricted")
        op = r.get("operator", "")
        if op not in SUPPORTED_OPERATORS:
            errs.append(f"key {key} has an unsupported operator {op}")
        restricted = _restricted_label_error(key)
        if restricted:
            errs.append(restricted)
        errs.extend(_qualified_name_errors(key))
        for value in r.get("values", ()):
            errs.extend(_label_value_errors(value))
        if op in ("Gt", "Lt", "Gte", "Lte"):
            values = r.get("values", ())
            if len(values) != 1 or not str(values[0]).lstrip("-").isdigit():
                errs.append(f"key {key}: {op} requires a single integer value")
        min_values = r.get("minValues")
        if min_values is not None:
            if op != "In":
                errs.append(f"key {key}: minValues requires operator In")
            elif min_values > len(r.get("values", ())):
                errs.append(
                    f"key {key}: minValues {min_values} exceeds the value count"
                )
    return errs
