"""API object model: NodePool, NodeClaim, Pod, Node, labels, taints.

Counterpart of the reference API layer (reference: pkg/apis/v1), re-designed
as a lightweight Kubernetes-free Python object model. Durable state still
follows the same shape — spec / status / conditions / finalizers / labels /
annotations — so the reconciler semantics carry over unchanged.
"""

from karpenter_tpu.models.labels import *  # noqa: F401,F403
from karpenter_tpu.models.objects import ObjectMeta, StatusCondition  # noqa: F401
from karpenter_tpu.models.taints import Taint, Toleration  # noqa: F401
from karpenter_tpu.models.pod import Pod, PodSpec, TopologySpreadConstraint  # noqa: F401
from karpenter_tpu.models.nodepool import NodePool, NodePoolSpec, Budget, Disruption, Limits  # noqa: F401
from karpenter_tpu.models.nodeclaim import NodeClaim, NodeClaimSpec, NodeClaimStatus  # noqa: F401
from karpenter_tpu.models.node import Node  # noqa: F401
