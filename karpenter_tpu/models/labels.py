"""Well-known labels, taint keys, annotations and value constants.

Counterpart of reference pkg/apis/v1/labels.go:34-154 and taints.go:27-40.
We keep the upstream karpenter.sh group and the standard kubernetes.io label
keys so existing pod specs, nodepool manifests and tooling carry over
verbatim (this framework is a drop-in replacement, not a side-by-side
install).
"""

from __future__ import annotations

GROUP = "karpenter.sh"

# kubernetes.io standard labels
LABEL_TOPOLOGY_ZONE = "topology.kubernetes.io/zone"
LABEL_TOPOLOGY_REGION = "topology.kubernetes.io/region"
LABEL_INSTANCE_TYPE = "node.kubernetes.io/instance-type"
LABEL_ARCH = "kubernetes.io/arch"
LABEL_OS = "kubernetes.io/os"
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_WINDOWS_BUILD = "node.kubernetes.io/windows-build"

# deprecated aliases (normalized away; reference labels.go:138-146)
LABEL_ZONE_BETA = "failure-domain.beta.kubernetes.io/zone"
LABEL_REGION_BETA = "failure-domain.beta.kubernetes.io/region"
LABEL_ARCH_BETA = "beta.kubernetes.io/arch"
LABEL_OS_BETA = "beta.kubernetes.io/os"
LABEL_INSTANCE_TYPE_LEGACY = "beta.kubernetes.io/instance-type"

# our labels
NODEPOOL_LABEL_KEY = GROUP + "/nodepool"
NODE_INITIALIZED_LABEL_KEY = GROUP + "/initialized"
NODE_REGISTERED_LABEL_KEY = GROUP + "/registered"
CAPACITY_TYPE_LABEL_KEY = GROUP + "/capacity-type"
DO_NOT_SYNC_TAINTS_LABEL_KEY = GROUP + "/do-not-sync-taints"

# annotations
DO_NOT_DISRUPT_ANNOTATION_KEY = GROUP + "/do-not-disrupt"
# comma-separated DRA driver names whose device pools must publish before
# the claim initializes (labels.go:56-59)
DRA_DRIVERS_ANNOTATION_KEY = GROUP + "/requested-dra-drivers"
NODEPOOL_HASH_ANNOTATION_KEY = GROUP + "/nodepool-hash"
NODEPOOL_HASH_VERSION_ANNOTATION_KEY = GROUP + "/nodepool-hash-version"
NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY = GROUP + "/nodeclaim-termination-timestamp"
NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY = GROUP + "/nodeclaim-min-values-relaxed"

# finalizers
TERMINATION_FINALIZER = GROUP + "/termination"

# taint keys (reference taints.go:27-40)
DISRUPTED_TAINT_KEY = GROUP + "/disrupted"
UNREGISTERED_TAINT_KEY = GROUP + "/unregistered"

# capacity types
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPE_RESERVED = "reserved"

# reservation id injected into reserved offerings' requirements
# (cloudprovider/types.go:50-53 ReservationIDLabel; providers register it
# as well-known so claims without the key stay compatible)
RESERVATION_ID_LABEL_KEY = GROUP + "/reservation-id"

ARCH_AMD64 = "amd64"
ARCH_ARM64 = "arm64"

WELL_KNOWN_LABELS = frozenset(
    {
        NODEPOOL_LABEL_KEY,
        LABEL_TOPOLOGY_ZONE,
        LABEL_TOPOLOGY_REGION,
        LABEL_INSTANCE_TYPE,
        LABEL_ARCH,
        LABEL_OS,
        CAPACITY_TYPE_LABEL_KEY,
        LABEL_WINDOWS_BUILD,
        RESERVATION_ID_LABEL_KEY,
    }
)

RESTRICTED_LABELS = frozenset({LABEL_HOSTNAME})
RESTRICTED_LABEL_DOMAINS = frozenset({GROUP})

# alias -> canonical (reference labels.go:138-146)
NORMALIZED_LABELS: dict[str, str] = {
    LABEL_ZONE_BETA: LABEL_TOPOLOGY_ZONE,
    LABEL_ARCH_BETA: LABEL_ARCH,
    LABEL_OS_BETA: LABEL_OS,
    LABEL_INSTANCE_TYPE_LEGACY: LABEL_INSTANCE_TYPE,
    LABEL_REGION_BETA: LABEL_TOPOLOGY_REGION,
}

# normalized-key -> {original value -> normalized value}
NORMALIZED_LABEL_VALUES: dict[str, dict[str, str]] = {}

WELL_KNOWN_VALUES_FOR_REQUIREMENTS: dict[str, frozenset[str]] = {
    CAPACITY_TYPE_LABEL_KEY: frozenset({CAPACITY_TYPE_ON_DEMAND, CAPACITY_TYPE_SPOT, CAPACITY_TYPE_RESERVED}),
}

WELL_KNOWN_LABELS_FOR_OFFERINGS = frozenset({LABEL_TOPOLOGY_ZONE, CAPACITY_TYPE_LABEL_KEY})


def get_label_domain(key: str) -> str:
    return key.split("/", 1)[0] if "/" in key else ""


def is_restricted_label(key: str) -> bool:
    """True if the label may interfere with provisioning (labels.go:141-154)."""
    if key in WELL_KNOWN_LABELS:
        return False
    domain = get_label_domain(key)
    for restricted in RESTRICTED_LABEL_DOMAINS:
        if domain == restricted or domain.endswith("." + restricted):
            return True
    return key in RESTRICTED_LABELS
