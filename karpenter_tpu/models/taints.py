"""Taint / Toleration model with standard Kubernetes matching semantics."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from karpenter_tpu.models import labels as l

# Effects
NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

# Toleration operators
TOLERATION_OP_EXISTS = "Exists"
TOLERATION_OP_EQUAL = "Equal"


@dataclass(frozen=True)
class Taint:
    key: str
    effect: str
    value: str = ""

    def match(self, other: "Taint") -> bool:
        """MatchTaint: same key and effect (value ignored)."""
        return self.key == other.key and self.effect == other.effect


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = TOLERATION_OP_EQUAL
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[float] = None

    def tolerates(self, taint: Taint) -> bool:
        """Standard k8s ToleratesTaint semantics."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        # empty key with Exists tolerates everything
        if not self.key:
            return self.operator == TOLERATION_OP_EXISTS
        if self.operator == TOLERATION_OP_EXISTS:
            return True
        return self.value == taint.value


# Karpenter-managed taints (reference pkg/apis/v1/taints.go:27-40)
DISRUPTED_NO_SCHEDULE_TAINT = Taint(key=l.DISRUPTED_TAINT_KEY, effect=NO_SCHEDULE)
UNREGISTERED_NO_EXECUTE_TAINT = Taint(key=l.UNREGISTERED_TAINT_KEY, effect=NO_EXECUTE)

# Taints expected while a node initializes; ignored on uninitialized managed
# nodes (reference pkg/scheduling/taints.go:38-52).
TAINT_NODE_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_NODE_UNREACHABLE = "node.kubernetes.io/unreachable"
TAINT_EXTERNAL_CLOUD_PROVIDER = "node.cloudprovider.kubernetes.io/uninitialized"

KNOWN_EPHEMERAL_TAINTS = (
    Taint(key=TAINT_NODE_NOT_READY, effect=NO_SCHEDULE),
    Taint(key=TAINT_NODE_NOT_READY, effect=NO_EXECUTE),
    Taint(key=TAINT_NODE_UNREACHABLE, effect=NO_SCHEDULE),
    Taint(key=TAINT_EXTERNAL_CLOUD_PROVIDER, effect=NO_SCHEDULE, value="true"),
    UNREGISTERED_NO_EXECUTE_TAINT,
)

KNOWN_EPHEMERAL_TAINT_KEY_PREFIXES = ("readiness.k8s.io/",)


def is_known_ephemeral_taint(taint: Taint) -> bool:
    if any(known.match(taint) for known in KNOWN_EPHEMERAL_TAINTS):
        return True
    return any(taint.key.startswith(p) for p in KNOWN_EPHEMERAL_TAINT_KEY_PREFIXES)
