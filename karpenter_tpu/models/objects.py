"""Base object machinery: metadata, status conditions.

Durable state keeps the Kubernetes shape (metadata / spec / status /
conditions / finalizers) so reconciler semantics from the reference carry
over, but objects are plain Python dataclasses stored in an in-memory API
server model (karpenter_tpu/state) rather than CRDs in etcd.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Optional

_uid_counter = itertools.count(1)


def new_uid(prefix: str = "obj") -> str:
    return f"{prefix}-{next(_uid_counter):08d}"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=lambda: new_uid())
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    finalizers: list[str] = field(default_factory=list)
    creation_timestamp: float = field(default_factory=time.time)
    deletion_timestamp: Optional[float] = None
    resource_version: int = 0
    owner_uid: Optional[str] = None

    @property
    def deleting(self) -> bool:
        return self.deletion_timestamp is not None


# Condition statuses
CONDITION_TRUE = "True"
CONDITION_FALSE = "False"
CONDITION_UNKNOWN = "Unknown"


@dataclass
class StatusCondition:
    type: str
    status: str = CONDITION_UNKNOWN
    reason: str = ""
    message: str = ""
    last_transition_time: float = field(default_factory=time.time)

    @property
    def is_true(self) -> bool:
        return self.status == CONDITION_TRUE


class ConditionSet:
    """Helper over a list of StatusCondition with transition timestamps."""

    def __init__(self) -> None:
        self._conditions: dict[str, StatusCondition] = {}

    def get(self, ctype: str) -> Optional[StatusCondition]:
        return self._conditions.get(ctype)

    def is_true(self, *ctypes: str) -> bool:
        return all((c := self._conditions.get(t)) is not None and c.is_true for t in ctypes)

    def is_false(self, ctype: str) -> bool:
        """Explicitly False (unset/Unknown is NOT false)."""
        c = self._conditions.get(ctype)
        return c is not None and c.status == CONDITION_FALSE

    def has(self, ctype: str) -> bool:
        return ctype in self._conditions

    def set_true(self, ctype: str, reason: str = "", message: str = "", now: Optional[float] = None) -> bool:
        return self._set(ctype, CONDITION_TRUE, reason, message, now)

    def set_false(self, ctype: str, reason: str = "", message: str = "", now: Optional[float] = None) -> bool:
        return self._set(ctype, CONDITION_FALSE, reason, message, now)

    def set_unknown(self, ctype: str, reason: str = "", message: str = "", now: Optional[float] = None) -> bool:
        return self._set(ctype, CONDITION_UNKNOWN, reason, message, now)

    def clear(self, ctype: str) -> None:
        self._conditions.pop(ctype, None)

    def _set(self, ctype: str, status: str, reason: str, message: str, now: Optional[float]) -> bool:
        """Returns True if the condition transitioned."""
        existing = self._conditions.get(ctype)
        if existing is not None and existing.status == status:
            existing.reason, existing.message = reason, message
            return False
        # status-condition auto-metrics (operatorpkg status.NewController
        # analog, reference controllers.go:140-158)
        from karpenter_tpu.utils.metrics import STATUS_CONDITION_TRANSITIONS

        STATUS_CONDITION_TRANSITIONS.inc(type=ctype, status=status)
        self._conditions[ctype] = StatusCondition(
            type=ctype,
            status=status,
            reason=reason,
            message=message,
            last_transition_time=now if now is not None else time.time(),
        )
        return True

    def transition_time(self, ctype: str) -> Optional[float]:
        c = self._conditions.get(ctype)
        return c.last_transition_time if c else None

    def all(self) -> list[StatusCondition]:
        return list(self._conditions.values())
