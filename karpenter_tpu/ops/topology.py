"""Topology as tensors: spread/affinity/anti-affinity inside the scan.

The reference evaluates topology per (pod, candidate) with map lookups
(topologygroup.go:150-440); here every group's domain->count map becomes a
row of a count matrix carried through the solver scan:

  vocab-key groups   counts [NGv, V]   — domains are vocab value ids of
                     (zone, custom)      the group's key
  hostname groups    counts [NGh, S]   — domains are candidate slots
                                         (S = E existing + N claims); a new
                                         claim IS a fresh hostname domain

Per scan step, validity masks for ALL candidates × ALL groups are computed
at once; the winning candidate's key masks are narrowed (spread collapses
to the min-count domain with sorted-name rank tie-breaks, matching the
host oracle) and its counts committed.

Approximation (documented): pod hostname *selectors* interacting with
hostname affinity groups treat podDomains as Exists — the static
pod×candidate masks already enforce hostname selectors for placement.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import numpy as np

import jax.numpy as jnp

from karpenter_tpu.models import labels as l
from karpenter_tpu.ops.encode import ReqSetTensors

BIG_I32 = jnp.int32(2**31 - 1)
RANK_BASE = 1 << 16  # count * RANK_BASE + rank must not overflow int32... counts < 2^14

TYPE_SPREAD = 0
TYPE_AFFINITY = 1
TYPE_ANTI = 2


class TopologyTensors(NamedTuple):
    # vocab-key groups
    vg_key: jnp.ndarray  # [NGv] i32
    vg_type: jnp.ndarray  # [NGv] i32
    vg_skew: jnp.ndarray  # [NGv] i32
    vg_min_domains: jnp.ndarray  # [NGv] i32 (0 = unset)
    vg_domains: jnp.ndarray  # [NGv, V] bool
    vg_counts0: jnp.ndarray  # [NGv, V] i32
    vg_rank: jnp.ndarray  # [NGv, V] i32 (sorted-name rank; BIG for non-domains)
    vg_valid: jnp.ndarray  # [NGv] bool
    # hostname groups
    hg_type: jnp.ndarray  # [NGh] i32
    hg_skew: jnp.ndarray  # [NGh] i32
    hg_counts0: jnp.ndarray  # [NGh, S] i32
    hg_extra_nonempty: jnp.ndarray  # [NGh] bool — counts exist outside the slot space
    hg_valid: jnp.ndarray  # [NGh] bool


class PodTopology(NamedTuple):
    """Per-pod group relationships (host-precomputed)."""

    vg_applies: jnp.ndarray  # [P, NGv] bool — group restricts the pod
    vg_records: jnp.ndarray  # [P, NGv] bool — pod's placement counts into group
    vg_self: jnp.ndarray  # [P, NGv] bool — group selector matches the pod
    hg_applies: jnp.ndarray  # [P, NGh] bool
    hg_records: jnp.ndarray  # [P, NGh] bool
    hg_self: jnp.ndarray  # [P, NGh] bool
    strict_mask: jnp.ndarray  # [P, K, V] bool — strict pod requirement masks


def encode_topology_counts(
    topology,
    encoder,
    e_slots: int,
    n_slots: int,
    existing_names: Sequence[str],
    v_pad: int,
    base_vg: Sequence,
    base_hg: Sequence,
):
    """Numpy-only (vg_counts0, hg_counts0) for a scenario topology, with
    rows ALIGNED to a baseline's group lists by group identity — the batched
    what-if path re-seeds counts per scenario and must not round-trip tiny
    arrays through the device (each host<->device hop costs ~80ms over the
    TPU tunnel). Inverse anti-affinity groups derive from bound pods, which
    differ per exclusion set, so positional alignment is unsound; groups
    are matched by ident() instead. Returns None when the scenario's group
    multiset diverges from the baseline's (callers fall back to sequential
    simulation)."""
    groups = topology.groups + topology.inverse_groups
    vg = [g for g in groups if g.key != l.LABEL_HOSTNAME]
    hg = [g for g in groups if g.key == l.LABEL_HOSTNAME]

    def align(scenario_groups, base_groups):
        by_ident: dict = {}
        for g in scenario_groups:
            by_ident.setdefault(g.ident(), []).append(g)
        ordered = []
        for b in base_groups:
            bucket = by_ident.get(b.ident())
            if not bucket:
                return None
            ordered.append(bucket.pop(0))
        if any(bucket for bucket in by_ident.values()):
            return None  # scenario has groups the baseline encoding lacks
        return ordered

    vg_aligned = align(vg, base_vg)
    hg_aligned = align(hg, base_hg)
    if vg_aligned is None or hg_aligned is None:
        return None

    NGv, NGh = _pow2(max(len(base_vg), 1), 1), _pow2(max(len(base_hg), 1), 1)
    S = e_slots + n_slots
    vocab = encoder.vocab
    vg_counts0 = np.zeros((NGv, v_pad), dtype=np.int32)
    for j, g in enumerate(vg_aligned):
        kid = vocab.add_key(g.key)
        for name, count in g.domains.items():
            vid = vocab.value_to_id[kid].get(name)
            if vid is not None:
                vg_counts0[j, vid] = count
    slot_of = {name: i for i, name in enumerate(existing_names)}
    hg_counts0 = np.zeros((NGh, S), dtype=np.int32)
    for j, g in enumerate(hg_aligned):
        for name, count in g.domains.items():
            if count <= 0:
                continue
            s = slot_of.get(name)
            if s is not None:
                hg_counts0[j, s] = count
    return vg_counts0, hg_counts0


_EMPTY_TT_CACHE: dict = {}
_EMPTY_PT_CACHE: dict = {}


def empty_topology_tensors(v_pad: int, s_slots: int) -> TopologyTensors:
    """The no-groups TopologyTensors (one invalid padding row per family),
    cached per (v_pad, slot-space) so topology-free solves skip domain-
    tensor construction AND the per-round host->device uploads entirely.
    Field-for-field identical to what encode_topology builds when the
    group lists are empty (skews default to 1, valid bits all False)."""
    key = (v_pad, s_slots)
    tt = _EMPTY_TT_CACHE.get(key)
    if tt is None:
        if len(_EMPTY_TT_CACHE) >= 64:
            _EMPTY_TT_CACHE.clear()
        tt = _EMPTY_TT_CACHE[key] = TopologyTensors(
            vg_key=jnp.zeros(1, dtype=jnp.int32),
            vg_type=jnp.zeros(1, dtype=jnp.int32),
            vg_skew=jnp.ones(1, dtype=jnp.int32),
            vg_min_domains=jnp.zeros(1, dtype=jnp.int32),
            vg_domains=jnp.zeros((1, v_pad), dtype=bool),
            vg_counts0=jnp.zeros((1, v_pad), dtype=jnp.int32),
            vg_rank=jnp.full((1, v_pad), 2**30, dtype=jnp.int32),
            vg_valid=jnp.zeros(1, dtype=bool),
            hg_type=jnp.zeros(1, dtype=jnp.int32),
            hg_skew=jnp.ones(1, dtype=jnp.int32),
            hg_counts0=jnp.zeros((1, s_slots), dtype=jnp.int32),
            hg_extra_nonempty=jnp.zeros(1, dtype=bool),
            hg_valid=jnp.zeros(1, dtype=bool),
        )
    return tt


def encode_topology(
    topology,
    encoder,
    e_slots: int,
    n_slots: int,
    existing_names: Sequence[str],
    v_pad: "int | None" = None,
):
    """Host Topology + ProblemEncoder -> TopologyTensors.

    existing_names maps hostname domains to slots [0, E); counts on
    hostnames outside the slot space set hg_extra_nonempty. v_pad
    overrides the domain-axis pad (callers that re-pad to a bucketed
    vocab width pass it here so the empty fast path caches at the final
    width and pad_to_v becomes a no-op).
    """
    from karpenter_tpu.controllers.provisioning.topology import TopologyType

    vocab = encoder.vocab
    V = max(vocab.max_values, 1)
    if v_pad is None:
        v_pad = _pow2(V)
    groups = topology.groups + topology.inverse_groups
    if not groups:
        # topology-free fast path: no domains to scatter, nothing varies
        # per solve — hand back the cached empty tensors
        return empty_topology_tensors(v_pad, e_slots + n_slots), [], []
    vg = [g for g in groups if g.key != l.LABEL_HOSTNAME]
    hg = [g for g in groups if g.key == l.LABEL_HOSTNAME]
    NGv, NGh = _pow2(max(len(vg), 1), 1), _pow2(max(len(hg), 1), 1)
    S = e_slots + n_slots
    type_map = {
        TopologyType.SPREAD: TYPE_SPREAD,
        TopologyType.AFFINITY: TYPE_AFFINITY,
        TopologyType.ANTI_AFFINITY: TYPE_ANTI,
    }

    vg_key = np.zeros(NGv, dtype=np.int32)
    vg_type = np.zeros(NGv, dtype=np.int32)
    vg_skew = np.ones(NGv, dtype=np.int32)
    vg_mind = np.zeros(NGv, dtype=np.int32)
    vg_domains = np.zeros((NGv, v_pad), dtype=bool)
    vg_counts0 = np.zeros((NGv, v_pad), dtype=np.int32)
    vg_rank = np.full((NGv, v_pad), 2**30, dtype=np.int32)
    vg_valid = np.zeros(NGv, dtype=bool)
    for j, g in enumerate(vg):
        kid = vocab.add_key(g.key)
        vg_key[j] = kid
        vg_type[j] = type_map[g.type]
        vg_skew[j] = g.max_skew
        vg_mind[j] = g.min_domains or 0
        for rank, name in enumerate(sorted(g.domains)):
            vid = vocab.value_to_id[kid].get(name)
            if vid is None:
                continue  # domain value unseen by any requirement: unreachable
            vg_domains[j, vid] = True
            vg_counts0[j, vid] = g.domains[name]
            vg_rank[j, vid] = rank
        vg_valid[j] = True

    slot_of = {name: i for i, name in enumerate(existing_names)}
    hg_type = np.zeros(NGh, dtype=np.int32)
    hg_skew = np.ones(NGh, dtype=np.int32)
    hg_counts0 = np.zeros((NGh, S), dtype=np.int32)
    hg_extra = np.zeros(NGh, dtype=bool)
    hg_valid = np.zeros(NGh, dtype=bool)
    for j, g in enumerate(hg):
        hg_type[j] = type_map[g.type]
        hg_skew[j] = g.max_skew
        for name, count in g.domains.items():
            if count <= 0:
                continue
            s = slot_of.get(name)
            if s is None:
                hg_extra[j] = True
            else:
                hg_counts0[j, s] = count
        hg_valid[j] = True

    tensors = TopologyTensors(
        vg_key=jnp.asarray(vg_key),
        vg_type=jnp.asarray(vg_type),
        vg_skew=jnp.asarray(vg_skew),
        vg_min_domains=jnp.asarray(vg_mind),
        vg_domains=jnp.asarray(vg_domains),
        vg_counts0=jnp.asarray(vg_counts0),
        vg_rank=jnp.asarray(vg_rank),
        vg_valid=jnp.asarray(vg_valid),
        hg_type=jnp.asarray(hg_type),
        hg_skew=jnp.asarray(hg_skew),
        hg_counts0=jnp.asarray(hg_counts0),
        hg_extra_nonempty=jnp.asarray(hg_extra),
        hg_valid=jnp.asarray(hg_valid),
    )
    return tensors, vg, hg


def encode_pod_topology(topology, vg, hg, pods, strict_tensors: ReqSetTensors):
    """Returns (PodTopology, host numpy twins {vga, vgr, hga, hgr}) — the
    twins are the pre-put arrays (free to expose), read host-side for
    batchability classification where a device round trip would cost
    ~100ms over a tunneled TPU."""
    P = strict_tensors.mask.shape[0]
    NGv, NGh = len(vg), len(hg)
    if NGv == 0 and NGh == 0:
        # topology-free fast path: every relation mask is all-False; one
        # shared cached [P, 1] zeros serves all six fields (read-only)
        cached = _EMPTY_PT_CACHE.get(P)
        if cached is None:
            if len(_EMPTY_PT_CACHE) >= 64:
                _EMPTY_PT_CACHE.clear()
            z = np.zeros((P, 1), dtype=bool)
            cached = _EMPTY_PT_CACHE[P] = (z, jnp.asarray(z))
        z, jz = cached
        pt = PodTopology(
            vg_applies=jz, vg_records=jz, vg_self=jz,
            hg_applies=jz, hg_records=jz, hg_self=jz,
            strict_mask=strict_tensors.mask,
        )
        return pt, {"vga": z, "vgr": z, "hga": z, "hgr": z}
    NGv_pad = _pow2(max(NGv, 1), 1)
    NGh_pad = _pow2(max(NGh, 1), 1)
    vga = np.zeros((P, NGv_pad), dtype=bool)
    vgr = np.zeros((P, NGv_pad), dtype=bool)
    vgs = np.zeros((P, NGv_pad), dtype=bool)
    hga = np.zeros((P, NGh_pad), dtype=bool)
    hgr = np.zeros((P, NGh_pad), dtype=bool)
    hgs = np.zeros((P, NGh_pad), dtype=bool)
    inverse = set(id(g) for g in topology.inverse_groups)
    for i, pod in enumerate(pods):
        for j, g in enumerate(vg):
            sel = g.selects(pod)
            own = pod.uid in g.owners and topology.still_declared(g, pod)
            if id(g) in inverse:
                vga[i, j] = sel
                vgr[i, j] = own
            else:
                vga[i, j] = own
                vgr[i, j] = sel
            vgs[i, j] = sel
        for j, g in enumerate(hg):
            sel = g.selects(pod)
            own = pod.uid in g.owners and topology.still_declared(g, pod)
            if id(g) in inverse:
                hga[i, j] = sel
                hgr[i, j] = own
            else:
                hga[i, j] = own
                hgr[i, j] = sel
            hgs[i, j] = sel
    pt = PodTopology(
        vg_applies=jnp.asarray(vga),
        vg_records=jnp.asarray(vgr),
        vg_self=jnp.asarray(vgs),
        hg_applies=jnp.asarray(hga),
        hg_records=jnp.asarray(hgr),
        hg_self=jnp.asarray(hgs),
        strict_mask=strict_tensors.mask,
    )
    return pt, {"vga": vga, "vgr": vgr, "hga": hga, "hgr": hgr}


def take_pod_topology(pt: PodTopology, idx) -> PodTopology:
    """Index/slice every per-pod row (kind gathers, chunk slices)."""
    return PodTopology(
        vg_applies=pt.vg_applies[idx],
        vg_records=pt.vg_records[idx],
        vg_self=pt.vg_self[idx],
        hg_applies=pt.hg_applies[idx],
        hg_records=pt.hg_records[idx],
        hg_self=pt.hg_self[idx],
        strict_mask=pt.strict_mask[idx],
    )


def _pow2(n: int, floor: int = 1) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


def pad_to_v(tensors: TopologyTensors, v_pad: int) -> TopologyTensors:
    """Re-pad the per-domain tensors to a bucketed vocab width."""
    cur = tensors.vg_domains.shape[1]
    if cur == v_pad:
        return tensors
    pad = v_pad - cur
    return tensors._replace(
        vg_domains=jnp.pad(tensors.vg_domains, ((0, 0), (0, pad))),
        vg_counts0=jnp.pad(tensors.vg_counts0, ((0, 0), (0, pad))),
        vg_rank=jnp.pad(tensors.vg_rank, ((0, 0), (0, pad)), constant_values=2**30),
    )


# ---------------------------------------------------------------------------
# device-side step functions (called from ops.solver inside the scan)
# ---------------------------------------------------------------------------


class VGPodPre(NamedTuple):
    """Candidate-independent per-pod precompute (once per scan step)."""

    pd: jnp.ndarray  # [NGv, V] pod strict domains per group
    eff: jnp.ndarray  # [NGv, V] count + self
    ok_skew: jnp.ndarray  # [NGv, V]
    opts: jnp.ndarray  # [NGv, V] affinity options (count>0, pod-compatible)
    bootstrap: jnp.ndarray  # [NGv]
    cnt_zero: jnp.ndarray  # [NGv, V]
    gate: jnp.ndarray  # [NGv] group applies to this pod
    key_touched: jnp.ndarray  # [K]
    keys_eq: jnp.ndarray  # [NGv, K]


def vg_pod_precompute(
    topo: TopologyTensors,
    counts: jnp.ndarray,  # [NGv, V]
    pod_strict_mask: jnp.ndarray,  # [K, V]
    applies: jnp.ndarray,  # [NGv]
    self_sel: jnp.ndarray,  # [NGv]
    n_keys: int,
) -> VGPodPre:
    pd = pod_strict_mask[topo.vg_key]  # [NGv, V]
    dom = topo.vg_domains
    cnt = counts
    self_add = self_sel.astype(jnp.int32)

    # spread min-count (topologygroup.go:298-320 domainMinCount)
    in_universe = dom & pd
    supported = jnp.sum(in_universe, axis=-1).astype(jnp.int32)
    masked_cnt = jnp.where(in_universe, cnt, BIG_I32)
    minc = jnp.min(masked_cnt, axis=-1)
    minc = jnp.where(
        (topo.vg_min_domains > 0) & (supported < topo.vg_min_domains), 0, minc
    )
    minc = jnp.where(minc == BIG_I32, 0, minc)  # no supported domains
    eff = cnt + self_add[:, None]  # [NGv, V]
    ok_skew = (eff - minc[:, None]) <= topo.vg_skew[:, None]

    # affinity terms (topologygroup.go:324-381)
    opts = dom & pd & (cnt > 0)
    group_empty = ~jnp.any(cnt > 0, axis=-1)
    no_compat = ~jnp.any(pd & (cnt > 0), axis=-1)
    bootstrap = self_sel & (group_empty | no_compat)

    gate = applies & topo.vg_valid
    keys_eq = topo.vg_key[:, None] == jnp.arange(n_keys)[None, :]  # [NGv, K]
    key_touched = jnp.any(gate[:, None] & keys_eq, axis=0)  # [K]
    return VGPodPre(
        pd=pd,
        eff=eff,
        ok_skew=ok_skew,
        opts=opts,
        bootstrap=bootstrap,
        cnt_zero=cnt == 0,
        gate=gate,
        key_touched=key_touched,
        keys_eq=keys_eq,
    )


def _onehot_rows(space: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """[C, NG, V] one-hot of idx per (c, j), zeroed where space is empty.

    Broadcast-compare against an iota instead of a scatter: XLA lowers
    gather/scatter on TPU to serialized loops, and this runs inside the
    solver's per-pod step (and the kind scan's per-pod inner loop)."""
    V = space.shape[-1]
    oh = jnp.arange(V, dtype=idx.dtype)[None, None, :] == idx[:, :, None]
    return oh & jnp.any(space, axis=-1, keepdims=True)


def vg_evaluate(
    topo: TopologyTensors,
    pre: VGPodPre,
    comb_mask: jnp.ndarray,  # [C, K, V] candidate combined masks
):
    """Returns (feasible [C], upd [C, K, V], narrowed [C, NGv, V]).

    upd is the mask to AND into the winning candidate's requirements;
    narrowed is the per-group chosen-domain mask (for count commits).
    """
    nd = jnp.take(comb_mask, topo.vg_key, axis=1)  # [C, NGv, V]
    dom = topo.vg_domains

    # ---- spread (topologygroup.go:229-298) -----------------------------
    valid_sp = dom[None] & nd & pre.ok_skew[None]  # [C, NGv, V]
    spread_key = jnp.where(valid_sp, pre.eff[None] * RANK_BASE + topo.vg_rank[None], BIG_I32)
    sp_mask = _onehot_rows(valid_sp, jnp.argmin(spread_key, axis=-1))
    any_sp = jnp.any(valid_sp, axis=-1)

    # ---- affinity (topologygroup.go:324-381) ----------------------------
    opts_c = pre.opts[None] & nd  # [C, NGv, V]
    any_opts = jnp.any(opts_c, axis=-1, keepdims=True)
    boot_space = dom[None] & pre.pd[None] & nd
    boot_idx = jnp.argmin(jnp.where(boot_space, topo.vg_rank[None], BIG_I32), axis=-1)
    boot_mask = _onehot_rows(boot_space, boot_idx)
    aff_mask = jnp.where(any_opts, opts_c, boot_mask & pre.bootstrap[None, :, None])
    any_aff = jnp.any(aff_mask, axis=-1)

    # ---- anti-affinity (topologygroup.go:404-440) ------------------------
    anti_mask = dom[None] & pre.pd[None] & nd & pre.cnt_zero[None]
    any_anti = jnp.any(anti_mask, axis=-1)

    # ---- select by type ---------------------------------------------------
    t = topo.vg_type[None, :]
    narrowed = jnp.where(
        (t == TYPE_SPREAD)[..., None],
        sp_mask,
        jnp.where((t == TYPE_AFFINITY)[..., None], aff_mask, anti_mask),
    )  # [C, NGv, V]
    ok = jnp.where(t == TYPE_SPREAD, any_sp, jnp.where(t == TYPE_AFFINITY, any_aff, any_anti))
    feasible = jnp.all(~pre.gate[None, :] | ok, axis=-1)  # [C]

    # ---- requirement update (AND all applying groups per key) ------------
    # contrib[c, j, k, v] = ~(gate[j] & key_j==k) | narrowed[c, j, v]
    contrib = (
        ~(pre.gate[None, :, None, None] & pre.keys_eq[None, :, :, None])
    ) | narrowed[:, :, None, :]
    upd = jnp.all(contrib, axis=1)  # [C, K, V]
    return feasible, upd, narrowed


def vg_commit(
    topo: TopologyTensors,
    counts: jnp.ndarray,  # [NGv, V]
    final_mask: jnp.ndarray,  # [K, V] winner's updated requirement masks
    final_inf: jnp.ndarray,  # [K] winner's complement bits
    records: jnp.ndarray,  # [NGv]
) -> jnp.ndarray:
    """Commit counts (topology.go:190-212): record the final values of the
    group's key — all of them for anti-affinity, only a collapsed single
    value otherwise, and never for complement (infinite) requirements."""
    vals = final_mask[topo.vg_key]  # [NGv, V]
    finite = ~final_inf[topo.vg_key]  # [NGv]
    single = jnp.sum(vals, axis=-1) == 1
    is_anti = topo.vg_type == TYPE_ANTI
    do = records & topo.vg_valid & finite & (is_anti | single)
    delta = jnp.where(do[:, None] & vals, 1, 0)
    return counts + delta


def hg_evaluate(
    topo: TopologyTensors,
    counts: jnp.ndarray,  # [NGh, S]
    cand_slots: jnp.ndarray,  # [C] i32 — candidate hostname slots
    applies: jnp.ndarray,  # [NGh]
    self_sel: jnp.ndarray,  # [NGh]
) -> jnp.ndarray:
    """[C] bool — hostname-group feasibility per candidate slot."""
    cnt_s = counts[:, cand_slots].T  # [C, NGh]
    self_add = self_sel.astype(jnp.int32)[None, :]
    ok_spread = (cnt_s + self_add) <= topo.hg_skew[None, :]
    group_empty = ~(jnp.any(counts > 0, axis=-1) | topo.hg_extra_nonempty)  # [NGh]
    ok_aff = (cnt_s > 0) | (self_sel & group_empty)[None, :]
    ok_anti = cnt_s == 0
    t = topo.hg_type[None, :]
    ok = jnp.where(t == TYPE_SPREAD, ok_spread, jnp.where(t == TYPE_AFFINITY, ok_aff, ok_anti))
    gate = applies & topo.hg_valid
    return jnp.all(~gate[None, :] | ok, axis=-1)


def hg_commit(
    counts: jnp.ndarray,  # [NGh, S]
    slot,  # scalar i32 — winning candidate's hostname slot
    records: jnp.ndarray,  # [NGh]
    valid: jnp.ndarray,  # [NGh]
) -> jnp.ndarray:
    delta = (records & valid).astype(counts.dtype)
    return counts.at[:, slot].add(delta)
