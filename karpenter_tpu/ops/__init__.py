"""JAX tensor encoding + solver kernels — the TPU hot loop.

This package reformulates the reference's scheduling hot loop
(pkg/controllers/provisioning/scheduling, pkg/scheduling) as dense tensor
algebra:

  encode.py   label vocabularies; Requirements -> boolean mask tensors;
              pods / instance types / offerings -> dense arrays
  kernels.py  requirement-set algebra as batched boolean kernels
              (has_intersection / intersects / compatible / intersect_sets)
  solver.py   the scheduling solver: compat × fits × offering feasibility
              masks + first-fit-decreasing packing via lax.scan
"""

from karpenter_tpu.ops.encode import (  # noqa: F401
    InstanceTypeTensors,
    PodTensors,
    ProblemEncoder,
    ReqSetTensors,
    Vocab,
)
