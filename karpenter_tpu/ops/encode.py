"""Phase 0: lossless tensor encoding of the scheduling problem.

The reference's Requirement (pkg/scheduling/requirement.go:36) is a
compressed set over one label key's values: either a finite set, or the
complement of one, with optional integer bounds. We encode a *batch* of
requirement sets as dense tensors over a problem-wide vocabulary:

  mask[B, K, V]  bool  which vocab values the requirement admits — each
                       entity's own bounds are already folded in host-side
                       (mask[b,k,v] == req.has(vocab_value))
  inf[B, K]      bool  complement bit: admits values OUTSIDE the vocab
  excl[B, K]     bool  complement has a non-empty exclusion set (NotIn-ness;
                       distinguishes NotIn from Exists for leniency rules)
  gte/lte[B, K]  int32 inclusive bounds with sentinels; only consulted for
                       complement×complement intersections (all finite cases
                       are fully captured by the masks)
  defined[B, K]  bool  whether the entity constrains this key at all

Undefined keys are encoded as the identity element of intersection
(mask=all-ones, inf=1, excl=0, bounds=sentinels, defined=0), which makes
"missing key reads as Exists" (requirements.go:160-166) automatic.

Because the vocab is built from EVERY value mentioned anywhere in the
problem (pods, instance types, templates, offerings, existing nodes), set
emptiness over the vocab is exact: In-sets can never have admissible values
the masks don't see. The only out-of-vocab freedom is the complement
"infinite remainder", captured by `inf` + the bounds.

Key algebraic facts the kernels rely on (golden-tested against the Python
oracle in tests/test_encode.py):

  nonempty(A ∩ B) = any(A.mask & B.mask) | (A.inf & B.inf & bounds_overlap)
  encode(A ∩ B)   = (A.mask & B.mask, A.inf & B.inf, A.excl | B.excl,
                     max(gte), min(lte), A.defined | B.defined)
  lenient(A)      = defined & ((inf & excl) | (~inf & ~any(mask)))
                    — operator ∈ {NotIn, DoesNotExist}
"""

from __future__ import annotations

from typing import Iterable, NamedTuple, Optional, Sequence

import numpy as np

import jax.numpy as jnp

from karpenter_tpu.cloudprovider.instancetype import InstanceType
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.utils import resources as res

INT_MIN = -(2**31) + 1
INT_MAX = 2**31 - 1

# Canonical resource axis prefix; extended resources appended per problem.
BASE_RESOURCES = (res.CPU, res.MEMORY, res.PODS, res.EPHEMERAL_STORAGE)


def place_sharded(arr, mesh, *axes):
    """Place an encode output on `mesh` SHARDED from birth (ISSUE 8):
    one device_put with a NamedSharding instead of replicating the host
    array to every device and re-constraining inside the kernels. Axis
    names absent from the mesh (or extent 1) degrade to None; mesh=None
    is the single-device no-op. Note eager device_put requires the
    sharded axis sizes to divide the mesh extents — callers pass
    mesh-padded tensors (shard_instance_types pads T to a multiple of
    the "it" extent)."""
    import jax
    import jax.numpy as jnp

    if mesh is None:
        return jnp.asarray(arr)
    from jax.sharding import NamedSharding, PartitionSpec

    shape = dict(mesh.shape)
    names = [a if (a in shape and shape[a] > 1) else None for a in axes]
    return jax.device_put(arr, NamedSharding(mesh, PartitionSpec(*names)))


def next_pow2(n: int, floor: int = 1) -> int:
    """Smallest power of two >= n (>= floor)."""
    out = floor
    while out < n:
        out *= 2
    return out


class PadBucketCache:
    """Recompile-amortization cache for tighter-than-pow2 pad buckets.

    Pow2 padding bounds the number of compiled executables but wastes up
    to ~2x of every batch axis (a 1100-row per-pod chunk pads to 2048).
    Multiple-of-`step` buckets are tight but churn more compiled shapes.
    This cache splits the difference: per axis kind it remembers every
    bucket it has handed out; a request reuses the smallest cached bucket
    within the request's pow2 ceiling (no new executable, waste never
    worse than pow2) and only mints the tight multiple-of-step bucket
    when nothing cached covers it. Steady-state workloads therefore
    converge on a few tight shapes instead of recompiling per solve.
    """

    def __init__(self, limit: int = 256):
        self._known: dict[str, set[int]] = {}
        self._limit = limit
        # padded-vs-real element accounting for bench --report-padding
        self.real: dict[str, int] = {}
        self.padded: dict[str, int] = {}

    def pad(self, kind: str, n: int, step: int = 8, floor: Optional[int] = None) -> int:
        n = max(n, 1)
        floor = floor if floor is not None else step
        tight = max(floor, -(-n // step) * step)
        ceiling = next_pow2(n, floor)
        known = self._known.setdefault(kind, set())
        covering = [p for p in known if tight <= p <= ceiling]
        out = min(covering) if covering else tight
        if not covering:
            if len(known) >= self._limit:
                known.clear()
            known.add(tight)
        self.real[kind] = self.real.get(kind, 0) + n
        self.padded[kind] = self.padded.get(kind, 0) + out
        return out

    def waste_report(self) -> dict:
        """Per-axis padded-vs-real rows since construction (cumulative)."""
        out = {}
        for kind, real in self.real.items():
            padded = self.padded.get(kind, real)
            out[kind] = {
                "real": real,
                "padded": padded,
                "waste_frac": round(1.0 - real / padded, 4) if padded else 0.0,
            }
        return out


class Vocab:
    """Per-key value vocabulary for one problem instance."""

    def __init__(self) -> None:
        self.keys: list[str] = []
        self.key_to_id: dict[str, int] = {}
        self.values: list[list[str]] = []  # per key
        self.value_to_id: list[dict[str, int]] = []

    def add_key(self, key: str) -> int:
        kid = self.key_to_id.get(key)
        if kid is None:
            kid = len(self.keys)
            self.key_to_id[key] = kid
            self.keys.append(key)
            self.values.append([])
            self.value_to_id.append({})
        return kid

    def add_value(self, key: str, value: str) -> int:
        kid = self.add_key(key)
        vid = self.value_to_id[kid].get(value)
        if vid is None:
            vid = len(self.values[kid])
            self.value_to_id[kid][value] = vid
            self.values[kid].append(value)
        return vid

    def observe(self, reqs: Requirements, skip_keys: frozenset[str] = frozenset()) -> None:
        for r in reqs:
            if r.key in skip_keys:
                continue
            self.add_key(r.key)
            for v in r.values:
                self.add_value(r.key, v)

    @property
    def n_keys(self) -> int:
        return len(self.keys)

    @property
    def max_values(self) -> int:
        return max((len(v) for v in self.values), default=0)

    def well_known_mask(self) -> np.ndarray:
        return np.array([k in l.WELL_KNOWN_LABELS for k in self.keys], dtype=bool)


class ReqSetTensors(NamedTuple):
    """A batch of encoded requirement sets; leading axis is the batch."""

    mask: jnp.ndarray  # [B, K, V] bool
    inf: jnp.ndarray  # [B, K] bool
    excl: jnp.ndarray  # [B, K] bool
    gte: jnp.ndarray  # [B, K] int32
    lte: jnp.ndarray  # [B, K] int32
    defined: jnp.ndarray  # [B, K] bool

    @property
    def batch(self) -> int:
        return self.mask.shape[0]


def _requirement_row(vocab: Vocab, k: int, r, V: int, memo: dict) -> np.ndarray:
    """[V] bool admitted-value row for one requirement at key id k.

    Rows are memoized by requirement CONTENT — deployment-shaped problems
    repeat the same selectors across hundreds of kinds, so each distinct
    requirement encodes once per pass. Bound-free requirements take a
    vectorized id-indexing path instead of the O(V) per-value has() loop
    (the host-side encode kind pass's former hot spot)."""
    vals = vocab.values[k]
    key = (k, r.complement, r.gte, r.lte, frozenset(r.values))
    row = memo.get(key)
    if row is not None:
        return row
    row = np.zeros(V, dtype=bool)
    if r.gte is None and r.lte is None:
        ids = [vocab.value_to_id[k][v] for v in r.values if v in vocab.value_to_id[k]]
        if r.complement:
            row[: len(vals)] = True
            row[ids] = False
        else:
            row[ids] = True
    else:
        for vid, value in enumerate(vals):
            row[vid] = r.has(value)
    memo[key] = row
    return row


def encode_requirements_np(
    vocab: Vocab,
    req_sets: Sequence[Requirements],
    k_pad: Optional[int] = None,
    v_pad: Optional[int] = None,
    skip_keys: frozenset[str] = frozenset(),
    row_memo: Optional[dict] = None,
) -> tuple[np.ndarray, ...]:
    """Host-array twin of encode_requirements: returns the six component
    arrays as numpy (mask, inf, excl, gte, lte, defined) so callers can
    cache/assemble rows without device round trips."""
    B = len(req_sets)
    K = k_pad or max(vocab.n_keys, 1)
    V = v_pad or max(vocab.max_values, 1)
    mask = np.ones((B, K, V), dtype=bool)
    inf = np.ones((B, K), dtype=bool)
    excl = np.zeros((B, K), dtype=bool)
    gte = np.full((B, K), INT_MIN, dtype=np.int32)
    lte = np.full((B, K), INT_MAX, dtype=np.int32)
    defined = np.zeros((B, K), dtype=bool)
    memo: dict = row_memo if row_memo is not None else {}
    # padding key slots beyond the vocab stay at the identity encoding
    for b, reqs in enumerate(req_sets):
        for r in reqs:
            if r.key in skip_keys:
                continue
            k = vocab.key_to_id[r.key]
            # vocab slots beyond this key's value count are not real values
            mask[b, k] = _requirement_row(vocab, k, r, V, memo)
            inf[b, k] = r.complement
            excl[b, k] = r.complement and bool(r.values)
            # saturating clamp to int32 on both sides
            gte[b, k] = min(max(r.gte, INT_MIN), INT_MAX) if r.gte is not None else INT_MIN
            lte[b, k] = min(max(r.lte, INT_MIN), INT_MAX) if r.lte is not None else INT_MAX
            defined[b, k] = True
    return mask, inf, excl, gte, lte, defined


def encode_requirements(
    vocab: Vocab,
    req_sets: Sequence[Requirements],
    k_pad: Optional[int] = None,
    v_pad: Optional[int] = None,
    skip_keys: frozenset[str] = frozenset(),
) -> ReqSetTensors:
    """Encode requirement sets against an already-built vocab.

    Every value referenced by req_sets must already be in the vocab
    (call vocab.observe first); unknown keys raise. Keys in skip_keys are
    left out of the dense encoding entirely (the caller must enforce their
    semantics by other means — see ProblemEncoder's instance-type-name
    special-casing).
    """
    mask, inf, excl, gte, lte, defined = encode_requirements_np(
        vocab, req_sets, k_pad, v_pad, skip_keys
    )
    return ReqSetTensors(
        mask=jnp.asarray(mask),
        inf=jnp.asarray(inf),
        excl=jnp.asarray(excl),
        gte=jnp.asarray(gte),
        lte=jnp.asarray(lte),
        defined=jnp.asarray(defined),
    )


class InstanceTypeTensors(NamedTuple):
    """Dense instance-type catalog.

    GR is the allocatable-override group axis (types.go:196-334): group 0 is
    the base allocatable; extra groups come from offerings with capacity /
    overhead overrides. Padded groups have alloc=-inf so nothing fits them.
    """

    reqs: ReqSetTensors  # [T, K, V]
    alloc: jnp.ndarray  # [T, GR, R] f32
    cap: jnp.ndarray  # [T, R] f32 — full capacity (NodePool limits filtering)
    group_valid: jnp.ndarray  # [T, GR] bool
    zc_avail: jnp.ndarray  # [T, GR, Z, C] bool — available offering exists in (zone, ct)
    price_zc: jnp.ndarray  # [T, Z, C] f32 — min available price, +inf when none
    valid: jnp.ndarray  # [T] bool — real (non-padding) instance type
    # reserved offerings by (type, reservation-id value id, zone value id);
    # feeds the in-scan ReservationManager twin (reservationmanager.go)
    res_ofs: jnp.ndarray  # [T, RID, Z] bool

    @property
    def n_types(self) -> int:
        return self.alloc.shape[0]


class PodTensors(NamedTuple):
    reqs: ReqSetTensors  # [P, K, V] (preferences folded in per reference semantics)
    strict_reqs: ReqSetTensors  # [P, K, V] required-only (for relaxation)
    requests: jnp.ndarray  # [P, R] f32 (includes pods=1)
    valid: jnp.ndarray  # [P] bool

    @property
    def n_pods(self) -> int:
        return self.requests.shape[0]


class ProblemEncoder:
    """Builds the vocab + resource axis, then encodes entities.

    Usage: construct, observe() everything, then encode_* — the vocab is
    frozen by the first encode call.
    """

    def __init__(self, special_it_name: bool = True) -> None:
        self.vocab = Vocab()
        self.resource_names: list[str] = list(BASE_RESOURCES)
        self._resource_ids: dict[str, int] = {n: i for i, n in enumerate(self.resource_names)}
        # zone / capacity-type key ids for offering encoding
        self.vocab.add_key(l.LABEL_TOPOLOGY_ZONE)
        self.vocab.add_key(l.CAPACITY_TYPE_LABEL_KEY)
        # Two keys would dominate the value vocabulary and are excluded from
        # the dense encoding (their semantics are enforced by other means):
        #   * instance-type NAME (one value per catalog entry, 400-1000):
        #     claims track name-set intersection exactly through their
        #     viable-instance-type bitmask; pod/template name selectors fold
        #     into static allowed-type masks (it_allow_mask).
        #   * hostname (one value per existing node): hostname selectors
        #     fold into the static pod×node / pod×template masks computed
        #     host-side (hostname_allows); hostname topology spread gets
        #     dedicated machinery in the topology phase.
        self.skip_keys: frozenset[str] = (
            frozenset({l.LABEL_INSTANCE_TYPE, l.LABEL_HOSTNAME}) if special_it_name else frozenset()
        )

    # -- observation -------------------------------------------------------

    def observe_resources(self, rl: dict[str, float]) -> None:
        for name in rl:
            if name not in self._resource_ids:
                self._resource_ids[name] = len(self.resource_names)
                self.resource_names.append(name)

    def observe_requirements(self, reqs: Requirements) -> None:
        self.vocab.observe(reqs, self.skip_keys)

    def observe_pod(self, pod: Pod) -> None:
        self.vocab.observe(Requirements.from_pod(pod), self.skip_keys)
        self.observe_resources(pod.total_requests())

    def observe_instance_type(self, it: InstanceType) -> None:
        self.vocab.observe(it.requirements, self.skip_keys)
        self.observe_resources(it.capacity)
        for o in it.offerings:
            self.vocab.observe(o.requirements, self.skip_keys)
            self.observe_resources(o.capacity_override)

    def hostname_allows(self, reqs: Requirements, hostname: Optional[str]) -> bool:
        """Whether a requirement set's hostname requirement admits the given
        hostname (None = a not-yet-named new node: only requirement sets
        without a concrete hostname demand are satisfiable)."""
        if not reqs.has(l.LABEL_HOSTNAME):
            return True
        r = reqs.get(l.LABEL_HOSTNAME)
        if hostname is None:
            return r.is_lenient()
        return r.has(hostname)

    def it_allow_mask(self, req_sets: Sequence[Requirements], its: Sequence[InstanceType]) -> np.ndarray:
        """[B, T] bool — which instance types each requirement set's
        instance-type-NAME requirement admits (True when undefined)."""
        out = np.ones((len(req_sets), len(its)), dtype=bool)
        for b, reqs in enumerate(req_sets):
            if not reqs.has(l.LABEL_INSTANCE_TYPE):
                continue
            r = reqs.get(l.LABEL_INSTANCE_TYPE)
            for t, it in enumerate(its):
                out[b, t] = r.has(it.name)
        return out

    # -- encoding ----------------------------------------------------------

    @property
    def n_resources(self) -> int:
        return len(self.resource_names)

    def resources_vector(self, rl: dict[str, float]) -> np.ndarray:
        out = np.zeros(self.n_resources, dtype=np.float32)
        for name, v in rl.items():
            out[self._resource_ids[name]] = v
        return out

    def encode_requirements(
        self, req_sets: Sequence[Requirements], k_pad: Optional[int] = None, v_pad: Optional[int] = None
    ) -> ReqSetTensors:
        return encode_requirements(self.vocab, req_sets, k_pad, v_pad, self.skip_keys)

    def encode_pods(self, pods: Sequence[Pod]) -> PodTensors:
        reqs = self.encode_requirements([Requirements.from_pod(p) for p in pods])
        strict = self.encode_requirements(
            [Requirements.from_pod(p, include_preferred=False) for p in pods]
        )
        requests = np.stack(
            [self.resources_vector(p.total_requests()) for p in pods]
        ) if pods else np.zeros((0, self.n_resources), dtype=np.float32)
        return PodTensors(
            reqs=reqs,
            strict_reqs=strict,
            requests=jnp.asarray(requests, dtype=jnp.float32),
            valid=jnp.ones(len(pods), dtype=bool),
        )

    def encode_instance_types(self, its: Sequence[InstanceType]) -> InstanceTypeTensors:
        T = len(its)
        zone_kid = self.vocab.key_to_id[l.LABEL_TOPOLOGY_ZONE]
        ct_kid = self.vocab.key_to_id[l.CAPACITY_TYPE_LABEL_KEY]
        Z = max(len(self.vocab.values[zone_kid]), 1)
        C = max(len(self.vocab.values[ct_kid]), 1)
        GR = max((len(it.allocatable_offerings()) for it in its), default=1)
        R = self.n_resources

        reqs = self.encode_requirements([it.requirements for it in its])
        alloc = np.full((T, GR, R), -np.inf, dtype=np.float32)
        cap = np.zeros((T, R), dtype=np.float32)
        group_valid = np.zeros((T, GR), dtype=bool)
        zc_avail = np.zeros((T, GR, Z, C), dtype=bool)
        price_zc = np.full((T, Z, C), np.inf, dtype=np.float32)

        zone_values = self.vocab.values[zone_kid]
        ct_values = self.vocab.values[ct_kid]
        rid_kid = self.vocab.key_to_id.get(l.RESERVATION_ID_LABEL_KEY)
        rid_values = self.vocab.values[rid_kid] if rid_kid is not None else []
        RID = max(len(rid_values), 1)
        res_ofs = np.zeros((T, RID, Z), dtype=bool)
        for t, it in enumerate(its):
            cap[t] = self.resources_vector(it.capacity)
            for g, group in enumerate(it.allocatable_offerings()):
                alloc[t, g] = self.resources_vector(group.allocatable)
                group_valid[t, g] = True
                for o in group.offerings:  # already available-filtered
                    # An offering admits every (zone, ct) its requirements
                    # allow: a missing key reads as Exists (all values), and
                    # multi-value requirements mark multiple cells.
                    zreq = o.requirements.get(l.LABEL_TOPOLOGY_ZONE)
                    creq = o.requirements.get(l.CAPACITY_TYPE_LABEL_KEY)
                    zs = [z for z, v in enumerate(zone_values) if zreq.has(v)]
                    cs = [c for c, v in enumerate(ct_values) if creq.has(v)]
                    # empty vocab for a key means no entity constrains it:
                    # mark the padding column, which unconstrained claim
                    # masks (all-ones) always admit
                    if not zone_values and zreq.complement:
                        zs = [0]
                    if not ct_values and creq.complement:
                        cs = [0]
                    for z in zs:
                        for c in cs:
                            zc_avail[t, g, z, c] = True
                            price_zc[t, z, c] = min(price_zc[t, z, c], o.price)
            for o in it.offerings:
                if o.capacity_type != l.CAPACITY_TYPE_RESERVED or not o.available:
                    continue
                rid = o.reservation_id
                if rid not in rid_values:
                    continue  # unseen by any requirement: unreachable
                r = rid_values.index(rid)
                zreq = o.requirements.get(l.LABEL_TOPOLOGY_ZONE)
                for z, v in enumerate(zone_values):
                    if zreq.has(v):
                        res_ofs[t, r, z] = True
        return InstanceTypeTensors(
            reqs=reqs,
            alloc=jnp.asarray(alloc),
            cap=jnp.asarray(cap),
            group_valid=jnp.asarray(group_valid),
            zc_avail=jnp.asarray(zc_avail),
            price_zc=jnp.asarray(price_zc),
            valid=jnp.ones(T, dtype=bool),
            res_ofs=jnp.asarray(res_ofs),
        )

    def zone_ct_key_ids(self) -> tuple[int, int]:
        return (
            self.vocab.key_to_id[l.LABEL_TOPOLOGY_ZONE],
            self.vocab.key_to_id[l.CAPACITY_TYPE_LABEL_KEY],
        )

    def reservation_ids(self) -> tuple[int, int, list[str]]:
        """(rid key id, reserved-ct value id, rid names in value-id order);
        -1 ids when no reservation vocabulary exists."""
        rid_kid = self.vocab.key_to_id.get(l.RESERVATION_ID_LABEL_KEY, -1)
        ct_kid = self.vocab.key_to_id.get(l.CAPACITY_TYPE_LABEL_KEY)
        res_vid = -1
        if ct_kid is not None and l.CAPACITY_TYPE_RESERVED in self.vocab.values[ct_kid]:
            res_vid = self.vocab.values[ct_kid].index(l.CAPACITY_TYPE_RESERVED)
        rid_names = list(self.vocab.values[rid_kid]) if rid_kid >= 0 else []
        return rid_kid, res_vid, rid_names


def type_price_column(itt: InstanceTypeTensors) -> jnp.ndarray:
    """[T] f32 — each type's min available offering price over every
    (zone, capacity-type) cell, +inf when the catalog never priced it.
    The objective kernels' per-claim price floor (a claim's cheapest
    still-viable type), derived from the already-encoded price_zc slab so
    it needs no second catalog walk and pads identically."""
    return jnp.min(itt.price_zc, axis=(1, 2))


def template_price_column(tmpl_its, price_t) -> np.ndarray:
    """[G] f32 — per-template price floor: min type price over the
    template's statically-compatible member types (+inf when none are
    priced). Host-side companion of type_price_column for rank
    construction and the consolidation ordering."""
    return np.where(
        np.asarray(tmpl_its, dtype=bool),
        np.asarray(price_t, dtype=np.float32)[None, :],
        np.float32(np.inf),
    ).min(axis=1)
