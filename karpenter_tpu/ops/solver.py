"""The TPU scheduling solver: batched feasibility masks + scan-FFD packing.

This replaces the reference's Scheduler.Solve hot loop
(pkg/controllers/provisioning/scheduling/scheduler.go:440,
nodeclaim.go:124-242, existingnode.go:32-200, nodeclaim.go:541).
Reformulation:

  * Pods are pre-sorted first-fit-decreasing host-side (queue.go:72-90).
  * One `lax.scan` step places one pod through the reference's 3-tier
    cascade (scheduler.go:582-612):
      tier 1  existing nodes, earliest-index wins (addToExistingNode)
      tier 2  in-flight simulated NodeClaims, fewest-pods-first with
              earliest-slot tie-break (addToInflightNode, :598)
      tier 3  a new claim from the highest-priority weight-ordered
              compatible template (addToNewNodeClaim)
  * The per-(claim, instance-type) triple mask — requirements-intersect ×
    resource-fits × offering-available (nodeclaim.go:541) — is computed for
    ALL claims and instance types at once on the VPU/MXU instead of the
    reference's goroutine fan-out.
  * NodePool limits ride along as per-template budget vectors: new claims
    filter instance types by remaining capacity and subtract the max
    capacity over the claim's viable types on open (scheduler.go:708-727,
    :1068 filterByRemainingResources / subtractMax).

The solver is pure and stateless per call; all problem tensors are jit
ARGUMENTS, so re-encoding (e.g. after vocab growth) reuses the compiled
executable whenever shapes are unchanged.

Assignment index space: [0, E) = existing-node slot, [E, E+N) = claim
slot, NO_CLAIM / NO_ROOM sentinels otherwise.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from karpenter_tpu.obs import waterfall as _waterfall
from karpenter_tpu.obs.observatory import named_kernel
from karpenter_tpu.ops import kernels
from karpenter_tpu.ops import topology as topo_ops
from karpenter_tpu.ops.encode import INT_MAX, INT_MIN, InstanceTypeTensors, PodTensors, ReqSetTensors
from karpenter_tpu.ops.topology import PodTopology, TopologyTensors


def _wf_timed(name):
    """Attribute the host-side cost of one dispatch entry point (trace,
    jit-cache lookup, enqueue — execution itself is async) to the active
    round waterfall as an `enqueue.<name>` leaf; the device-side wall
    surfaces later under the drain/wire leaves that observe it. No-op
    outside a round (one contextvar read)."""
    import time as _time

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            t0 = _time.perf_counter()
            out = fn(*args, **kwargs)
            _waterfall.add_current(
                f"enqueue.{name}", _time.perf_counter() - t0
            )
            return out

        return wrapped

    return deco


def _ambient_mesh():
    """The device mesh entered via `with mesh:` at trace time, or None."""
    from jax.interpreters import pxla

    m = pxla.thread_resources.env.physical_mesh
    return None if m.empty else m


def shard_hint(x, *axes):
    """with_sharding_constraint against the AMBIENT mesh; a no-op outside
    one, so the single-device executables are untouched.

    Axis names absent from the mesh (or with extent 1) degrade to None,
    and trailing unnamed dims replicate. The ambient mesh is part of the
    jit cache key (the resource env), so annotated kernels retrace — once
    — when first called under a mesh; GSPMD then keeps the hot [W, T]
    viability masks, bank [NCAP, T] columns and kscan [W, T, GR] grid
    partitioned across (dp × it) instead of replicating them per device."""
    mesh = _ambient_mesh()
    if mesh is None:
        return x
    shape = dict(mesh.shape)
    names = [a if (a in shape and shape[a] > 1) else None for a in axes]
    if not any(n is not None for n in names):
        return x
    from jax.sharding import NamedSharding, PartitionSpec

    names += [None] * (x.ndim - len(names))
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*names))
    )


# assignment sentinels
NO_CLAIM = -1  # no compatible existing node, in-flight claim, or template
NO_ROOM = -2  # a template was feasible but the claim-slot capacity is full
# a gang blocked by an all-or-nothing constraint the solver cannot relax
# (e.g. a finite node budget smaller than the slice): every member fails
# together; the host keeps the gang pending instead of escalating slots
GANG_SPILL = -3
BIG = jnp.int32(2**31 - 1)


class Templates(NamedTuple):
    """NodeClaim templates in weight-priority order (index 0 = first try)."""

    reqs: ReqSetTensors  # [G, K, V]
    its: jnp.ndarray  # [G, T] bool — statically compatible instance types
    daemon_requests: jnp.ndarray  # [G, R] f32 — daemonset overhead per template
    valid: jnp.ndarray  # [G] bool
    budget: jnp.ndarray  # [G, R] f32 — remaining pool limits (+inf unlimited)
    nodes_budget: jnp.ndarray  # [G] f32 — remaining node-count limit (+inf)
    # minValues flexibility floors (types.go:399-433; Strict policy):
    # mv_key indexes the pre-gathered mv_it_values slab (-1 = the
    # instance-type NAME key, -2 = unused)
    mv_key: jnp.ndarray  # [G, M] i32
    mv_min: jnp.ndarray  # [G, M] i32 (0 = unused)
    # [T, J, V] — per min-keyed label, the values each instance type
    # DEFINES (finite sets only: undefined/complement keys contribute
    # nothing, matching Requirements.Get(k).Values())
    mv_it_values: jnp.ndarray
    # placement-objective template order (objectives/): tier-3 opens the
    # FIRST FEASIBLE template in ascending rank instead of ascending
    # index. None = legacy weight order — identity rank, for which
    # argmin(where(feas, rank, BIG)) IS argmax(feas) bit-for-bit
    # (including the all-infeasible case, where both land on index 0)
    rank: Optional[jnp.ndarray] = None  # [G] i32


def _pick_template(tmpl_feas: jnp.ndarray, templates: "Templates") -> jnp.ndarray:
    """Tier-3 template choice: first feasible in objective-rank order.

    With no rank column (the default `lexical` policy) this is the
    literal legacy computation — argmax over the feasibility mask, i.e.
    the lowest-index (highest-weight) feasible template. A rank column
    reorders the SAME choice via argmin(where(feas, rank, BIG)); for the
    identity rank the two are bit-identical (both return 0 when nothing
    is feasible), which is the `lexical` bit-parity argument: the policy
    mechanism costs nothing and changes nothing unless a rank is set."""
    if templates.rank is None:
        return jnp.argmax(tmpl_feas)
    return jnp.argmin(jnp.where(tmpl_feas, templates.rank, BIG))


class ExistingNodes(NamedTuple):
    """Existing/in-flight real nodes (tier 1). reqs seed from node labels;
    avail is allocatable minus current pods and daemon overhead. Port and
    volume bitsets ride as packed uint32 bitfields (kernels.pack_bool_np
    layout) so the per-step conflict tests are fused bitwise ops."""

    reqs: ReqSetTensors  # [E, K, V]
    avail: jnp.ndarray  # [E, R] f32 — remaining schedulable resources
    valid: jnp.ndarray  # [E] bool
    ports: jnp.ndarray  # [E, NPp] uint32 — host ports already in use (packed)
    # CSI attach limits (volumeusage.go:201-208): distinct-PVC columns over
    # a (driver, pvc) vocabulary; resident volumes seed vols, per-driver
    # limits are +inf when the node publishes none
    vols: jnp.ndarray  # [E, NVp] uint32 — PVCs already attached (packed)
    vol_limits: jnp.ndarray  # [E, ND] f32 — per-driver attach caps
    vol_driver: jnp.ndarray  # [ND, NVp] uint32 — per-driver packed column mask


class SolverState(NamedTuple):
    """The scan carry.

    The claims axis is an ACTIVE WINDOW: hot per-claim tensors (reqs, its,
    used, ports, held, ...) cover only W resident open claims, not the
    full logical claim space [0, NCAP). `slot_of` maps window rows to
    global claim ids (NCAP sentinel = unused row); `n_open` counts global
    opens while `w_open` counts window residents. Claims that can never
    take another pod are evicted between dispatches (compact_state) into
    the append-only frozen bank — global-id-indexed decode columns the
    scan step never rescans. Hostname-group counts stay global-slot
    indexed, so frozen claims keep constraining topology."""

    # tier-1 existing nodes
    exist_reqs: ReqSetTensors  # [E, K, V] — evolve as pods land
    exist_used: jnp.ndarray  # [E, R]
    # tier-2 in-flight claims (hot window, axis W)
    reqs: ReqSetTensors  # [W, K, V]
    used: jnp.ndarray  # [W, R]
    its: jnp.ndarray  # [W, T] bool
    template: jnp.ndarray  # [W] int32
    open: jnp.ndarray  # [W] bool
    pods: jnp.ndarray  # [W] int32
    n_open: jnp.ndarray  # [] int32 — global claims opened (next global id)
    # window bookkeeping
    slot_of: jnp.ndarray  # [W] i32 — global claim id per row (NCAP = unused)
    w_open: jnp.ndarray  # [] i32 — open claims resident in the window
    w_hw: jnp.ndarray  # [] i32 — high-water of w_open (window sizing)
    spills: jnp.ndarray  # [] i32 — opens refused because the window was full
    # frozen bank (global claim axis NCAP): decode-only columns of closed
    # claims, written once at eviction, never rescanned
    bank_frozen: jnp.ndarray  # [NCAP] bool
    bank_template: jnp.ndarray  # [NCAP] i32
    bank_its: jnp.ndarray  # [NCAP, T] bool
    bank_used: jnp.ndarray  # [NCAP, R] f32
    bank_held: jnp.ndarray  # [NCAP, RID] bool
    # vg-narrowed requirement rows at the topology keys (decode's
    # fold_narrowing inputs; TK = max(len(topo_kids), 1))
    bank_tk_mask: jnp.ndarray  # [NCAP, TK, V] bool
    bank_tk_inf: jnp.ndarray  # [NCAP, TK] bool
    bank_tk_def: jnp.ndarray  # [NCAP, TK] bool
    # limits
    budget: jnp.ndarray  # [G, R]
    nodes_budget: jnp.ndarray  # [G]
    # topology counts
    vg_counts: jnp.ndarray  # [NGv, V]
    hg_counts: jnp.ndarray  # [NGh, E+NCAP+1] — global hostname slots
    # host ports in use (hostportusage.go:35-97), packed bitfields
    exist_ports: jnp.ndarray  # [E, NPp] uint32
    claim_ports: jnp.ndarray  # [W, NPp] uint32
    # distinct PVCs attached per existing node (volumeusage.go:187-229);
    # claims have no CSINode, so no claim-side twin exists
    exist_vols: jnp.ndarray  # [E, NVp] uint32
    # reserved-capacity twin (reservationmanager.go:28-115)
    res_cap: jnp.ndarray  # [RID] i32 — remaining capacity per reservation id
    held: jnp.ndarray  # [W, RID] bool — reservations each claim holds


class SolveResult(NamedTuple):
    assignment: jnp.ndarray  # [P] int32
    claims: SolverState


def _fits_and_offering(
    total: jnp.ndarray,  # [B, R] requested totals
    comb: ReqSetTensors,  # [B, K, V] combined requirements
    it: InstanceTypeTensors,
    zone_kid: int,
    ct_kid: int,
) -> jnp.ndarray:
    """[B, T] bool — exists an allocatable group where resources fit AND a
    compatible offering is available (nodeclaim.go:630-652 fits())."""
    # fits per group: [B, T, GR]. Resources with zero requested always pass,
    # matching the host's "only check requested keys" (resources.fits) even
    # when an allocatable entry is negative (overhead exceeding capacity).
    t = total[:, None, None, :]
    fit = jnp.all((t <= it.alloc[None, :, :, :]) | (t == 0.0), axis=-1)
    fit = fit & it.group_valid[None, :, :]
    zmask = comb.mask[:, zone_kid, :]  # [B, V] — admitted zones
    cmask = comb.mask[:, ct_kid, :]
    Z = it.zc_avail.shape[2]
    C = it.zc_avail.shape[3]
    off = jnp.einsum(
        "tgzc,nz,nc->ntg",
        it.zc_avail.astype(jnp.bfloat16),
        zmask[:, :Z].astype(jnp.bfloat16),
        cmask[:, :C].astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    ) > 0
    return jnp.any(fit & off, axis=-1)  # [B, T]


def _broadcast_pod(pod: ReqSetTensors, n: int) -> ReqSetTensors:
    return ReqSetTensors(
        mask=jnp.broadcast_to(pod.mask[None], (n,) + pod.mask.shape),
        inf=jnp.broadcast_to(pod.inf[None], (n,) + pod.inf.shape),
        excl=jnp.broadcast_to(pod.excl[None], (n,) + pod.excl.shape),
        gte=jnp.broadcast_to(pod.gte[None], (n,) + pod.gte.shape),
        lte=jnp.broadcast_to(pod.lte[None], (n,) + pod.lte.shape),
        defined=jnp.broadcast_to(pod.defined[None], (n,) + pod.defined.shape),
    )


def identity_reqs(n: int, k: int, v: int) -> ReqSetTensors:
    """The intersection-identity encoding (all keys undefined)."""
    return ReqSetTensors(
        mask=jnp.ones((n, k, v), dtype=bool),
        inf=jnp.ones((n, k), dtype=bool),
        excl=jnp.zeros((n, k), dtype=bool),
        gte=jnp.full((n, k), -(2**31) + 1, dtype=jnp.int32),
        lte=jnp.full((n, k), 2**31 - 1, dtype=jnp.int32),
        defined=jnp.zeros((n, k), dtype=bool),
    )


def _min_values_ok(
    viable: jnp.ndarray,  # [C, T] bool — surviving instance types
    mv_key_c: jnp.ndarray,  # [C, M] i32 — indexes into the J axis
    mv_min_c: jnp.ndarray,  # [C, M] i32
    mv_it_values: jnp.ndarray,  # [T, J, V] bool — pre-gathered min-keyed values
) -> jnp.ndarray:
    """[C] bool — distinct-value floors hold over the viable set
    (SatisfiesMinValues, types.go:399-433)."""
    present = (
        jnp.einsum(
            "ct,tjv->cjv",
            viable.astype(jnp.bfloat16),
            mv_it_values.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        > 0
    )
    counts_all = jnp.sum(present, axis=-1).astype(jnp.int32)  # [C, J]
    name_count = jnp.sum(viable, axis=-1).astype(jnp.int32)  # [C]
    key = jnp.clip(mv_key_c, 0, mv_it_values.shape[1] - 1)
    per_key = jnp.take_along_axis(counts_all, key, axis=1)  # [C, M]
    cnt = jnp.where(mv_key_c == -1, name_count[:, None], per_key)
    ok = (mv_min_c <= 0) | (cnt >= mv_min_c)
    return jnp.all(ok, axis=-1)


def _make_step(
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    mv_active: bool,
    topo_kids: tuple,
    rid_kid: int,
    res_vid: int,
    res_active: bool,
    res_strict: bool,
    annotate: bool = True,
):
    """Build the per-pod scan step closure shared by solve/solve_from.
    The claims axis it scans is the ACTIVE WINDOW (W = the carry's hot
    claims axis, read off the state shapes at trace time); n_claims stays
    the GLOBAL claim-space cap (hostname slots, bank width)."""
    NCAP = n_claims
    # annotate=False inside the dp-batched speculative dispatch (see
    # _make_fill_step): the leading vmap axis IS the "dp" mesh axis there
    _hint = shard_hint if annotate else (lambda x, *a: x)
    K = it.reqs.mask.shape[1]
    E = exist.avail.shape[0]
    G = templates.its.shape[0]
    no_wk = jnp.zeros_like(well_known)
    RID = it.res_ofs.shape[1]
    Zr = it.res_ofs.shape[2]
    # static [K] mask of keys handled exactly per-step (topology narrowing);
    # the incremental tier-2 classification covers the rest
    kid_mask = jnp.zeros(K, dtype=bool)
    for k in topo_kids:
        kid_mask = kid_mask.at[k].set(True)

    def _reserve_options(viable, comb):
        """[B, RID] bool — reserved offerings compatible with each
        candidate over its viable types (offeringsToReserve's scan,
        nodeclaim.go:313-332): an available reserved offering on a
        surviving type whose zone, capacity-type and reservation-id the
        combined requirements admit."""
        zmask = comb.mask[:, zone_kid, :Zr]
        ridmask = comb.mask[:, rid_kid, :RID]
        ct_res = comb.mask[:, ct_kid, res_vid]
        hit = (
            jnp.einsum(
                "bt,trz,bz->br",
                viable.astype(jnp.bfloat16),
                it.res_ofs.astype(jnp.bfloat16),
                zmask.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            > 0
        )
        return hit & ridmask & ct_res[:, None]

    def step(state: SolverState, xs):
        (
            pod_reqs,
            pod_requests,
            tmpl_ok_g,
            it_allow,
            exist_ok_e,
            ports_p,
            port_conf_p,
            vols_p,
            pod_valid,
            vg_applies,
            vg_records,
            vg_self,
            hg_applies,
            hg_records,
            hg_self,
            strict_mask,
        ) = xs
        W = state.open.shape[0]

        # ---- tier 1: existing nodes (earliest index wins) -----------------
        pod_e = _broadcast_pod(pod_reqs, E)
        comb_e = kernels.intersect_sets(state.exist_reqs, pod_e)
        # strict Compatible — no AllowUndefinedWellKnownLabels
        # (existingnode.go:101 n.requirements.Compatible(podData.Requirements))
        exist_compat = kernels.compatible_elemwise(state.exist_reqs, pod_e, no_wk)
        total_e = state.exist_used + pod_requests[None, :]
        t_e = total_e
        exist_fit = jnp.all((t_e <= exist.avail) | (t_e == 0.0), axis=-1)
        vg_pre = topo_ops.vg_pod_precompute(
            topo, state.vg_counts, strict_mask, vg_applies, vg_self, K
        )
        key_touched = vg_pre.key_touched
        topo_e, upd_e, _ = topo_ops.vg_evaluate(topo, vg_pre, comb_e.mask)
        topo_eh = topo_ops.hg_evaluate(
            topo, state.hg_counts, jnp.arange(E, dtype=jnp.int32), hg_applies, hg_self
        )
        ports_ok_e = ~kernels.packed_conflict(port_conf_p[None, :], state.exist_ports)  # [E]
        # CSI attach limits: distinct PVCs per driver after the add must
        # stay within each node's published caps (volumeusage.go:201-208)
        newv_e = state.exist_vols | vols_p[None, :]  # [E, NVp]
        vcount_e = kernels.packed_count_and(
            newv_e[:, None, :], exist.vol_driver[None, :, :]
        ).astype(jnp.float32)  # [E, ND]
        # volume-free pods skip the check entirely (the host gates on
        # `if pod_vols` — a node already OVER a shrunk cap still takes
        # podless-volume adds, volumeusage.go exceedsLimits call sites)
        vols_ok_e = jnp.all(vcount_e <= exist.vol_limits, axis=-1) | ~kernels.packed_any(vols_p)
        feas_e = (
            exist.valid
            & exist_ok_e
            & exist_compat
            & exist_fit
            & topo_e
            & topo_eh
            & ports_ok_e
            & vols_ok_e
            & pod_valid
        )
        pick_e = jnp.argmin(jnp.where(feas_e, jnp.arange(E, dtype=jnp.int32), BIG))
        found_e = jnp.any(feas_e)

        # ---- tier 2: in-flight claims (fewest pods, earliest slot) --------
        # the scan touches only the W window rows; hostname-group reads go
        # through slot_of so frozen claims' counts still apply
        pod_b = _broadcast_pod(pod_reqs, W)
        comb = kernels.intersect_sets(state.reqs, pod_b)
        claim_ok = kernels.compatible_elemwise(state.reqs, pod_b, well_known)
        topo_n, upd_n, _ = topo_ops.vg_evaluate(topo, vg_pre, comb.mask)
        topo_nh = topo_ops.hg_evaluate(
            topo,
            state.hg_counts,
            E + state.slot_of,
            hg_applies,
            hg_self,
        )
        # the topology-narrowed requirements feed instance-type filtering
        # (nodeclaim.go:199-213: topology comes before the IT filter)
        comb_t = _apply_topo(comb, upd_n, key_touched)

        # ---- incremental it-compat (replaces the O(N·T·K·V) per-step
        # intersects recompute — the round-1 dominant cost). Each
        # (claim, key) of comb_t is classified:
        #   == pod row   -> read the per-step [T, K] pod×type table
        #   == claim row -> implied true wherever state.its holds (state.its
        #                   certifies intersects(it, claim) from the step
        #                   that stored the row)
        #   topology key -> exact per-key einsum (static, small set)
        #   otherwise    -> partial-overlap conflict; rare -> lax.cond runs
        #                   the full pairwise intersects for this step.
        # Only claims that can be picked (open & Compatible) gate the
        # fallback; garbage values elsewhere are masked by feas/state.its.
        eqP = kernels.set_eq_rows(comb_t, _broadcast_pod(pod_reqs, W))  # [W, K]
        eqC = kernels.set_eq_rows(comb_t, state.reqs)  # [W, K]
        nonkid = ~kid_mask[None, :]
        need_exact = ~eqP & ~eqC & nonkid
        any_fallback = jnp.any(
            state.open & claim_ok & jnp.any(need_exact, axis=-1)
        )

        def _full_compat():
            return kernels.intersects(it.reqs, comb_t).T  # [N, T]

        def _fast_compat():
            pod_tkok = kernels.per_key_ok_table(it.reqs, pod_reqs)  # [T, K]
            use_pk = (eqP & ~eqC & nonkid).astype(jnp.bfloat16)
            viol = (
                jnp.einsum(
                    "nk,tk->nt",
                    use_pk,
                    (~pod_tkok).astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                )
                > 0.0
            )
            ok = ~viol
            for k in topo_kids:
                ok &= kernels.per_key_ok_at(it.reqs, comb_t, k)
            return ok

        it_compat = _hint(
            jax.lax.cond(any_fallback, _full_compat, _fast_compat), "dp", "it"
        )
        total = state.used + pod_requests[None, :]
        fits_off = _fits_and_offering(total, comb_t, it, zone_kid, ct_kid)
        new_its = state.its & it_compat & fits_off & it_allow[None, :]
        tol = tmpl_ok_g[state.template]
        ports_ok_n = ~kernels.packed_conflict(port_conf_p[None, :], state.claim_ports)  # [W]
        feas = (
            state.open
            & claim_ok
            & tol
            & topo_n
            & topo_nh
            & ports_ok_n
            & jnp.any(new_its, axis=-1)
            & pod_valid
            & ~found_e
        )
        if mv_active:
            feas &= _min_values_ok(
                new_its,
                templates.mv_key[state.template],
                templates.mv_min[state.template],
                templates.mv_it_values,
            )
        if res_active:
            ofs_c = _reserve_options(new_its, comb_t)  # [N, RID]
            to_res = ofs_c & (state.held | (state.res_cap > 0)[None, :])
            if res_strict:
                # strict mode (scheduler.go:75-78): fail the add when
                # compatible reserved offerings exist but none can be
                # reserved, or when it would drop existing reservations
                no_res = ~jnp.any(to_res, axis=-1)
                feas &= ~(
                    (jnp.any(ofs_c, axis=-1) | jnp.any(state.held, axis=-1)) & no_res
                )
        else:
            to_res = state.held  # unused; keeps shapes uniform
        # fewest-pods-first with earliest-slot tie-break: window order is
        # open order (compaction is stable), so relative comparisons match
        # the un-windowed global-slot keys exactly
        order_key = state.pods * jnp.int32(W) + jnp.arange(W, dtype=jnp.int32)
        pick = jnp.argmin(jnp.where(feas, order_key, BIG))
        found = jnp.any(feas)

        # ---- tier 3: new claim from weight-ordered templates ----------------
        pod_g = _broadcast_pod(pod_reqs, G)
        comb0 = kernels.intersect_sets(templates.reqs, pod_g)
        tmpl_compat = kernels.compatible_elemwise(templates.reqs, pod_g, well_known)
        topo_g, upd_g, _ = topo_ops.vg_evaluate(topo, vg_pre, comb0.mask)
        # fresh hostname domain; hg_counts carries a spare slot at E+N so
        # this read stays in bounds when all N claim slots are open
        new_slot = E + state.n_open
        topo_gh = topo_ops.hg_evaluate(
            topo,
            state.hg_counts,
            jnp.broadcast_to(new_slot, (G,)).astype(jnp.int32),
            hg_applies,
            hg_self,
        )
        comb0_t = _apply_topo(comb0, upd_g, key_touched)
        it_compat0 = kernels.intersects(it.reqs, comb0_t).T  # [G, T]
        total0 = templates.daemon_requests + pod_requests[None, :]
        fits_off0 = _fits_and_offering(total0, comb0_t, it, zone_kid, ct_kid)
        # NodePool limits: exclude instance types whose full capacity would
        # breach the remaining budget (scheduler.go:1068)
        cap_ok = jnp.all(
            (it.cap[None, :, :] <= state.budget[:, None, :]), axis=-1
        )  # [G, T]
        its0 = (
            templates.its
            & it_compat0
            & fits_off0
            & it_allow[None, :]
            & cap_ok
        )
        tmpl_feas = (
            templates.valid
            & tmpl_compat
            & tmpl_ok_g
            & topo_g
            & topo_gh
            & jnp.any(its0, axis=-1)
            & (state.nodes_budget >= 1.0)
        )
        if mv_active:
            tmpl_feas &= _min_values_ok(
                its0, templates.mv_key, templates.mv_min, templates.mv_it_values
            )
        if res_active:
            ofs0 = _reserve_options(its0, comb0_t)  # [G, RID]
            to_res0 = ofs0 & (state.res_cap > 0)[None, :]
            if res_strict:
                tmpl_feas &= ~(jnp.any(ofs0, axis=-1) & ~jnp.any(to_res0, axis=-1))
        else:
            to_res0 = jnp.zeros((G, state.held.shape[1]), dtype=bool)
        g = _pick_template(tmpl_feas, templates)
        any_template = jnp.any(tmpl_feas) & pod_valid & ~found_e & ~found
        can_open = any_template & (state.w_open < W) & (state.n_open < NCAP)
        # a refusal with global capacity left is a WINDOW spill: the host
        # escalates the window and re-solves (same NO_ROOM recovery path)
        spilled = any_template & ~can_open & (state.n_open < NCAP)

        # ---- merge the three outcomes ----------------------------------------
        # assignments carry GLOBAL slots (decode is window-agnostic);
        # carry updates address the window row cslot
        open_slot = state.w_open
        gslot = jnp.where(found, state.slot_of[pick], state.n_open)
        slot = jnp.where(found_e, pick_e, E + gslot)
        place = found_e | found | can_open
        assignment = jnp.where(
            place,
            slot.astype(jnp.int32),
            jnp.where(any_template, jnp.int32(NO_ROOM), jnp.int32(NO_CLAIM)),
        )

        # existing-node updates (topology-narrowed requirements are stored)
        upd_exist = found_e
        comb_e_t = _apply_topo(comb_e, upd_e, key_touched)
        new_exist_reqs = kernels.select_set(
            upd_exist,
            kernels.update_set_at(state.exist_reqs, pick_e, kernels.take_set(comb_e_t, pick_e)),
            state.exist_reqs,
        )
        new_exist_used = jnp.where(
            upd_exist, state.exist_used.at[pick_e].set(total_e[pick_e]), state.exist_used
        )
        new_exist_ports = jnp.where(
            upd_exist,
            state.exist_ports.at[pick_e].set(state.exist_ports[pick_e] | ports_p),
            state.exist_ports,
        )
        new_exist_vols = jnp.where(
            upd_exist,
            state.exist_vols.at[pick_e].set(state.exist_vols[pick_e] | vols_p),
            state.exist_vols,
        )

        # claim updates (tier 2 or 3)
        upd_claim = (found | can_open) & ~found_e
        cslot = jnp.where(found, pick, open_slot)
        sel_reqs = kernels.select_set(
            found, kernels.take_set(comb_t, pick), kernels.take_set(comb0_t, g)
        )
        sel_its = jnp.where(found, new_its[pick], its0[g])
        sel_used = jnp.where(
            found, total[pick], templates.daemon_requests[g] + pod_requests
        )
        sel_template = jnp.where(found, state.template[pick], g.astype(jnp.int32))

        # topology count commits for the winning candidate (global slots)
        final_reqs = kernels.select_set(found_e, kernels.take_set(comb_e_t, pick_e), sel_reqs)
        slot_h = jnp.where(found_e, pick_e, E + gslot).astype(jnp.int32)
        new_vg_counts = jnp.where(
            place,
            topo_ops.vg_commit(topo, state.vg_counts, final_reqs.mask, final_reqs.inf, vg_records),
            state.vg_counts,
        )
        new_hg_counts = jnp.where(
            place,
            topo_ops.hg_commit(state.hg_counts, slot_h, hg_records, topo.hg_valid),
            state.hg_counts,
        )
        new_reqs = kernels.select_set(
            upd_claim, kernels.update_set_at(state.reqs, cslot, sel_reqs), state.reqs
        )
        new_used = jnp.where(upd_claim, state.used.at[cslot].set(sel_used), state.used)
        new_claim_its = jnp.where(upd_claim, state.its.at[cslot].set(sel_its), state.its)
        new_template = jnp.where(
            upd_claim, state.template.at[cslot].set(sel_template), state.template
        )
        new_open = jnp.where(upd_claim, state.open.at[cslot].set(True), state.open)
        new_pods = jnp.where(upd_claim, state.pods.at[cslot].add(1), state.pods)
        new_claim_ports = jnp.where(
            upd_claim,
            state.claim_ports.at[cslot].set(state.claim_ports[cslot] | ports_p),
            state.claim_ports,
        )
        opened = can_open & ~found
        opened_i = jnp.where(opened, 1, 0).astype(jnp.int32)
        new_n_open = state.n_open + opened_i
        new_w_open = state.w_open + opened_i
        new_slot_of = jnp.where(
            opened, state.slot_of.at[cslot].set(state.n_open), state.slot_of
        )

        # reserved-capacity commit: reserve new ids, release dropped ones
        # (nodeclaim.go:260-262 Reserve + releaseReservedOfferings)
        if res_active:
            sel_res = jnp.where(found, to_res[pick], to_res0[g])  # [RID]
            prev_res = jnp.where(
                found, state.held[pick], jnp.zeros_like(state.held[0])
            )
            newly = sel_res & ~prev_res
            released = prev_res & ~sel_res
            new_res_cap = jnp.where(
                upd_claim,
                state.res_cap + released.astype(jnp.int32) - newly.astype(jnp.int32),
                state.res_cap,
            )
            new_held = jnp.where(
                upd_claim, state.held.at[cslot].set(sel_res), state.held
            )
        else:
            new_res_cap, new_held = state.res_cap, state.held

        # limits bookkeeping on open: subtract the max capacity over the
        # claim's viable instance types (scheduler.go:791 subtractMax)
        max_cap = jnp.max(
            jnp.where(its0[g][:, None], it.cap, -jnp.inf), axis=0
        )  # [R]
        max_cap = jnp.where(jnp.isfinite(max_cap), max_cap, 0.0)
        new_budget = jnp.where(
            opened, state.budget.at[g].add(-max_cap), state.budget
        )
        new_nodes_budget = jnp.where(
            opened, state.nodes_budget.at[g].add(-1.0), state.nodes_budget
        )

        return (
            SolverState(
                exist_reqs=new_exist_reqs,
                exist_used=new_exist_used,
                reqs=new_reqs,
                used=new_used,
                its=new_claim_its,
                template=new_template,
                open=new_open,
                pods=new_pods,
                n_open=new_n_open,
                slot_of=new_slot_of,
                w_open=new_w_open,
                w_hw=jnp.maximum(state.w_hw, new_w_open),
                spills=state.spills + jnp.where(spilled, 1, 0).astype(jnp.int32),
                bank_frozen=state.bank_frozen,
                bank_template=state.bank_template,
                bank_its=state.bank_its,
                bank_used=state.bank_used,
                bank_held=state.bank_held,
                bank_tk_mask=state.bank_tk_mask,
                bank_tk_inf=state.bank_tk_inf,
                bank_tk_def=state.bank_tk_def,
                budget=new_budget,
                nodes_budget=new_nodes_budget,
                vg_counts=new_vg_counts,
                hg_counts=new_hg_counts,
                exist_ports=new_exist_ports,
                claim_ports=new_claim_ports,
                exist_vols=new_exist_vols,
                res_cap=new_res_cap,
                held=new_held,
            ),
            assignment,
        )

    return step


def initial_state(
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    topo: TopologyTensors,
    n_claims: int,
    n_ports: int,
    res_cap0=None,
    window: int = 0,
    topo_kids: tuple = (),
) -> SolverState:
    """The empty carry (no pods placed yet). `window` bounds the hot
    claims axis (0 = the full global space n_claims); `n_ports` is the
    PACKED port-bitset lane count."""
    NB = n_claims
    W = min(window, NB) if window else NB
    K = it.reqs.mask.shape[1]
    V = it.reqs.mask.shape[2]
    R = it.alloc.shape[2]
    T = it.alloc.shape[0]
    E = exist.avail.shape[0]
    RID = it.res_ofs.shape[1]
    TK = max(len(topo_kids), 1)
    return SolverState(
        exist_reqs=exist.reqs,
        exist_used=jnp.zeros((E, R), dtype=jnp.float32),
        reqs=identity_reqs(W, K, V),
        used=jnp.zeros((W, R), dtype=jnp.float32),
        its=shard_hint(jnp.zeros((W, T), dtype=bool), "dp", "it"),
        template=jnp.zeros(W, dtype=jnp.int32),
        open=jnp.zeros(W, dtype=bool),
        pods=jnp.zeros(W, dtype=jnp.int32),
        n_open=jnp.int32(0),
        slot_of=jnp.full(W, NB, dtype=jnp.int32),
        w_open=jnp.int32(0),
        w_hw=jnp.int32(0),
        spills=jnp.int32(0),
        bank_frozen=jnp.zeros(NB, dtype=bool),
        bank_template=jnp.zeros(NB, dtype=jnp.int32),
        bank_its=shard_hint(jnp.zeros((NB, T), dtype=bool), "dp", "it"),
        bank_used=jnp.zeros((NB, R), dtype=jnp.float32),
        bank_held=jnp.zeros((NB, RID), dtype=bool),
        bank_tk_mask=jnp.zeros((NB, TK, V), dtype=bool),
        bank_tk_inf=jnp.zeros((NB, TK), dtype=bool),
        bank_tk_def=jnp.zeros((NB, TK), dtype=bool),
        budget=templates.budget,
        nodes_budget=templates.nodes_budget,
        vg_counts=topo.vg_counts0,
        hg_counts=topo.hg_counts0,
        exist_ports=exist.ports,
        claim_ports=jnp.zeros((W, n_ports), dtype=jnp.uint32),
        exist_vols=exist.vols,
        res_cap=(
            jnp.asarray(res_cap0, dtype=jnp.int32)
            if res_cap0 is not None
            else jnp.zeros(RID, dtype=jnp.int32)
        ),
        held=jnp.zeros((W, RID), dtype=bool),
    )


def _bank_rows(state: SolverState, idx: jnp.ndarray, topo_kids: tuple):
    """Scatter the window's decode columns into the bank at global ids
    `idx` (out-of-range sentinel rows drop)."""
    out = dict(
        bank_frozen=state.bank_frozen.at[idx].set(True, mode="drop"),
        bank_template=state.bank_template.at[idx].set(state.template, mode="drop"),
        bank_its=state.bank_its.at[idx].set(state.its, mode="drop"),
        bank_used=state.bank_used.at[idx].set(state.used, mode="drop"),
        bank_held=state.bank_held.at[idx].set(state.held, mode="drop"),
    )
    if topo_kids:
        tk = list(topo_kids)
        out.update(
            bank_tk_mask=state.bank_tk_mask.at[idx].set(
                state.reqs.mask[:, tk, :], mode="drop"
            ),
            bank_tk_inf=state.bank_tk_inf.at[idx].set(
                state.reqs.inf[:, tk], mode="drop"
            ),
            bank_tk_def=state.bank_tk_def.at[idx].set(
                state.reqs.defined[:, tk], mode="drop"
            ),
        )
    return out


@_wf_timed("compact_state")
@named_kernel("compact_state")
@functools.partial(jax.jit, static_argnames=("n_claims", "topo_kids"))
def compact_state(
    state: SolverState,
    it: InstanceTypeTensors,
    r_min: jnp.ndarray,  # [R] f32 — elementwise min request over remaining pods
    n_claims: int,
    topo_kids: tuple = (),
) -> tuple[SolverState, jnp.ndarray]:
    """Evict capacity-dead claims from the active window into the frozen
    bank, then stable-compact survivors to the front.

    A claim is dead when no viable (type, group) cell fits used + r_min
    under the step's total-based pass rule — every remaining pod requests
    at least r_min elementwise, so the claim can never again pass the
    tier-2 fits check (feasibility is an AND, hence eviction is sound and
    the compacted solve stays bit-identical). Stable compaction preserves
    open order, so the fewest-pods/earliest-slot tie-break is unchanged.
    Returns (state', n_closed)."""
    NB = n_claims
    W = state.open.shape[0]
    K = state.reqs.mask.shape[1]
    V = state.reqs.mask.shape[2]
    total = state.used + r_min[None, :]
    t = total[:, None, None, :]
    fit = jnp.all((t <= it.alloc[None]) | (t == 0.0), axis=-1)  # [W, T, GR]
    alive_cap = jnp.any(
        fit & it.group_valid[None] & state.its[:, :, None], axis=(1, 2)
    )
    close = state.open & ~alive_cap
    bank = _bank_rows(state, jnp.where(close, state.slot_of, NB), topo_kids)
    alive = state.open & ~close
    perm = jnp.argsort(~alive, stable=True)
    alive_p = alive[perm]
    ident = identity_reqs(W, K, V)
    reqs2 = kernels.select_set(alive_p, kernels.take_set(state.reqs, perm), ident)
    return (
        state._replace(
            reqs=reqs2,
            used=jnp.where(alive_p[:, None], state.used[perm], 0.0),
            its=jnp.where(alive_p[:, None], state.its[perm], False),
            template=jnp.where(alive_p, state.template[perm], 0),
            open=alive_p,
            pods=jnp.where(alive_p, state.pods[perm], 0),
            slot_of=jnp.where(alive_p, state.slot_of[perm], NB),
            w_open=jnp.sum(alive_p).astype(jnp.int32),
            claim_ports=jnp.where(
                alive_p[:, None], state.claim_ports[perm], jnp.uint32(0)
            ),
            held=jnp.where(alive_p[:, None], state.held[perm], False),
            **bank,
        ),
        jnp.sum(close).astype(jnp.int32),
    )


@named_kernel("retract_tail")
@jax.jit
def retract_tail(state: SolverState, cut: jnp.ndarray) -> SolverState:
    """Undo every claim with global id >= `cut`: the resident-session
    retract kernel (ISSUE 7). Closes the matching window rows (unused
    rows carry the NB sentinel, so they stay closed), clears the matching
    frozen-bank rows, stable-compacts survivors to the front, and rolls
    n_open back to `cut`.

    Soundness contract (enforced HOST-side by ResidentSession before
    dispatching): the retracted claims form an exact open-order suffix,
    hold only the departed pods, and the session is free of topology
    groups, finite budgets, and reservations — so no cross-claim
    accumulator (vg/hg counts, budget, res_cap) carries their imprint.
    Under those conditions the post-retract state is exactly the state a
    cold solve of the surviving pods (in session order) produces, up to
    the w_hw/spills heuristics, which never influence placement."""
    NB = state.bank_frozen.shape[0]
    W = state.open.shape[0]
    K = state.reqs.mask.shape[1]
    V = state.reqs.mask.shape[2]
    cut = jnp.asarray(cut, dtype=jnp.int32)
    alive = state.open & (state.slot_of < cut)
    bkeep = jnp.arange(NB, dtype=jnp.int32) < cut
    perm = jnp.argsort(~alive, stable=True)
    alive_p = alive[perm]
    ident = identity_reqs(W, K, V)
    reqs2 = kernels.select_set(alive_p, kernels.take_set(state.reqs, perm), ident)
    return state._replace(
        reqs=reqs2,
        used=jnp.where(alive_p[:, None], state.used[perm], 0.0),
        its=jnp.where(alive_p[:, None], state.its[perm], False),
        template=jnp.where(alive_p, state.template[perm], 0),
        open=alive_p,
        pods=jnp.where(alive_p, state.pods[perm], 0),
        n_open=cut,
        slot_of=jnp.where(alive_p, state.slot_of[perm], NB),
        w_open=jnp.sum(alive_p).astype(jnp.int32),
        claim_ports=jnp.where(
            alive_p[:, None], state.claim_ports[perm], jnp.uint32(0)
        ),
        held=jnp.where(alive_p[:, None], state.held[perm], False),
        bank_frozen=state.bank_frozen & bkeep,
        bank_template=jnp.where(bkeep, state.bank_template, 0),
        bank_its=jnp.where(bkeep[:, None], state.bank_its, False),
        bank_used=jnp.where(bkeep[:, None], state.bank_used, 0.0),
        bank_held=jnp.where(bkeep[:, None], state.bank_held, False),
        bank_tk_mask=jnp.where(bkeep[:, None, None], state.bank_tk_mask, False),
        bank_tk_inf=jnp.where(bkeep[:, None], state.bank_tk_inf, False),
        bank_tk_def=jnp.where(bkeep[:, None], state.bank_tk_def, False),
    )


@named_kernel("global_template")
@jax.jit
def global_template(state: SolverState) -> jnp.ndarray:
    """[NCAP] i32 — the global template column alone (the pipelined
    decode's per-dispatch snapshot; a claim's template is fixed at open,
    so merging window over bank is exact for every opened slot)."""
    return state.bank_template.at[state.slot_of].set(state.template, mode="drop")


@named_kernel("global_claims")
@functools.partial(jax.jit, static_argnames=("topo_kids",))
def global_claims(state: SolverState, topo_kids: tuple = ()) -> dict:
    """Merge the hot window over the frozen bank into global-slot-indexed
    decode columns (template/its/used/held [+ vg-narrowed topo-key rows]).
    Window rows override bank rows at their global id; unused rows carry
    the NB sentinel and drop."""
    sl = state.slot_of
    out = dict(
        template=state.bank_template.at[sl].set(state.template, mode="drop"),
        its=state.bank_its.at[sl].set(state.its, mode="drop"),
        used=state.bank_used.at[sl].set(state.used, mode="drop"),
        held=state.bank_held.at[sl].set(state.held, mode="drop"),
    )
    if topo_kids:
        tk = list(topo_kids)
        out.update(
            tk_mask=state.bank_tk_mask.at[sl].set(state.reqs.mask[:, tk, :], mode="drop"),
            tk_inf=state.bank_tk_inf.at[sl].set(state.reqs.inf[:, tk], mode="drop"),
            tk_def=state.bank_tk_def.at[sl].set(state.reqs.defined[:, tk], mode="drop"),
        )
    return out


def _xs(
    pods, pod_tmpl_ok, pod_it_allow, pod_exist_ok, pod_ports, pod_port_conf,
    pod_topo, pod_vols,
):
    return (
        pods.reqs,
        pods.requests,
        pod_tmpl_ok,
        pod_it_allow,
        pod_exist_ok,
        pod_ports,
        pod_port_conf,
        pod_vols,
        pods.valid,
        pod_topo.vg_applies,
        pod_topo.vg_records,
        pod_topo.vg_self,
        pod_topo.hg_applies,
        pod_topo.hg_records,
        pod_topo.hg_self,
        pod_topo.strict_mask,
    )


_STATIC = (
    "zone_kid",
    "ct_kid",
    "n_claims",
    "mv_active",
    "topo_kids",
    "rid_kid",
    "res_vid",
    "res_active",
    "res_strict",
    "window",
)


@_wf_timed("solve")
@named_kernel("solve")
@functools.partial(jax.jit, static_argnames=_STATIC)
def solve(
    pods: PodTensors,
    pod_tmpl_ok: jnp.ndarray,  # [P, G] bool — tolerates taints + skipped-key static checks
    pod_it_allow: jnp.ndarray,  # [P, T] bool — instance types the pod's NAME selector admits
    pod_exist_ok: jnp.ndarray,  # [P, E] bool — static checks vs existing nodes
    pod_ports: jnp.ndarray,  # [P, NP] bool — the pod's own host-port keys
    pod_port_conf: jnp.ndarray,  # [P, NP] bool — keys the pod CONFLICTS with (wildcard-expanded)
    pod_vols: jnp.ndarray,  # [P, NV] bool — the pod's distinct (driver, pvc) columns
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,  # [K] bool
    topo: TopologyTensors,
    pod_topo: PodTopology,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    mv_active: bool = False,
    topo_kids: tuple = (),
    res_cap0=None,
    rid_kid: int = -1,
    res_vid: int = -1,
    res_active: bool = False,
    res_strict: bool = False,
    window: int = 0,
) -> SolveResult:
    state = initial_state(
        exist, it, templates, topo, n_claims, pod_ports.shape[1], res_cap0,
        window=window, topo_kids=topo_kids,
    )
    step = _make_step(
        exist, it, templates, well_known, topo, zone_kid, ct_kid, n_claims,
        mv_active, topo_kids, rid_kid, res_vid, res_active, res_strict,
    )
    xs = _xs(
        pods, pod_tmpl_ok, pod_it_allow, pod_exist_ok, pod_ports,
        pod_port_conf, pod_topo, pod_vols,
    )
    state, assignment = jax.lax.scan(step, state, xs)
    return SolveResult(assignment=assignment, claims=state)


@_wf_timed("solve_from")
@named_kernel("solve_from")
@functools.partial(jax.jit, static_argnames=_STATIC)
def solve_from(
    state: SolverState,
    pods: PodTensors,
    pod_tmpl_ok: jnp.ndarray,
    pod_it_allow: jnp.ndarray,
    pod_exist_ok: jnp.ndarray,
    pod_ports: jnp.ndarray,
    pod_port_conf: jnp.ndarray,
    pod_vols: jnp.ndarray,
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    pod_topo: PodTopology,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    mv_active: bool = False,
    topo_kids: tuple = (),
    rid_kid: int = -1,
    res_vid: int = -1,
    res_active: bool = False,
    res_strict: bool = False,
    window: int = 0,  # unused here: the carry's shapes define the window
) -> SolveResult:
    """Resume the scan from an explicit carry — the chunked-solve entry:
    the host splits a large pod batch into fixed-size chunks (bounded
    per-dispatch transfers and a single compiled executable) and threads
    SolverState between calls. Bit-identical to one big scan.

    This is also the software pipeline's dispatch unit (scheduler._decode
    chunk groups): every chunk is issued asynchronously with the carry
    threaded through, then fetched + decoded while later chunks still run
    on device. The pipeline's early claim materialization leans on a
    carry invariant shared by all three dispatch kernels: a claim slot's
    `template` entry is written exactly once, when the slot opens, and
    never rewritten — so a post-chunk `state.template` snapshot is final
    for every slot the chunk (or any earlier chunk) opened."""
    step = _make_step(
        exist, it, templates, well_known, topo, zone_kid, ct_kid, n_claims,
        mv_active, topo_kids, rid_kid, res_vid, res_active, res_strict,
    )
    xs = _xs(
        pods, pod_tmpl_ok, pod_it_allow, pod_exist_ok, pod_ports,
        pod_port_conf, pod_topo, pod_vols,
    )
    state, assignment = jax.lax.scan(step, state, xs)
    return SolveResult(assignment=assignment, claims=state)


@named_kernel("solve_whatif")
@functools.partial(jax.jit, static_argnames=_STATIC)
def solve_whatif(
    scen_pod_idx: jnp.ndarray,  # [S, L] i32 — this scenario's pods (indices into the union)
    scen_active: jnp.ndarray,  # [S, L] bool — real entries (False = padding)
    scen_count: jnp.ndarray,  # [S, L] bool — pods whose failure matters (displaced)
    scen_exist_valid: jnp.ndarray,  # [S, E] bool — per-scenario surviving nodes
    scen_vg_counts0: jnp.ndarray,  # [S, NGv, V] i32 — per-scenario topology seeds
    scen_hg_counts0: jnp.ndarray,  # [S, NGh, Sl] i32
    pods: PodTensors,
    pod_tmpl_ok: jnp.ndarray,
    pod_it_allow: jnp.ndarray,
    pod_exist_ok: jnp.ndarray,
    pod_ports: jnp.ndarray,
    pod_port_conf: jnp.ndarray,
    pod_vols: jnp.ndarray,  # [P, NV] — displaced pods carry their PVCs
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    pod_topo: PodTopology,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    mv_active: bool = False,
    topo_kids: tuple = (),
    res_cap0=None,
    rid_kid: int = -1,
    res_vid: int = -1,
    res_active: bool = False,
    res_strict: bool = False,
    window: int = 0,
):
    """Batched consolidation what-ifs: S disruption scenarios solved in ONE
    device dispatch (the reference runs SimulateScheduling sequentially per
    candidate set — multinodeconsolidation.go:136-183). Every scenario
    shares the encoded union problem; each gathers its OWN compact pod list
    (scan length L = the largest scenario, not the union size — singleton
    candidate scenarios stay cheap even when the union holds every
    candidate's pods), plus its exclusion mask and topology count seeds.
    vmap vectorizes the whole thing across the batch.

    Returns per-scenario (n_unsched [S] i32 — failures among the pods each
    scenario counts, n_open [S] i32 — new claims opened).
    """

    def one(idx, active, count, exist_valid, vg0, hg0):
        ex = exist._replace(valid=exist_valid)
        tp = topo._replace(vg_counts0=vg0, hg_counts0=hg0)
        valid = pods.valid[idx] & active
        pd = PodTensors(
            reqs=kernels.take_set(pods.reqs, idx),
            strict_reqs=kernels.take_set(pods.strict_reqs, idx),
            requests=pods.requests[idx],
            valid=valid,
        )
        state = initial_state(
            ex, it, templates, tp, n_claims, pod_ports.shape[1], res_cap0,
            window=window, topo_kids=topo_kids,
        )
        step = _make_step(
            ex, it, templates, well_known, tp, zone_kid, ct_kid, n_claims,
            mv_active, topo_kids, rid_kid, res_vid, res_active, res_strict,
        )
        xs = _xs(
            pd,
            pod_tmpl_ok[idx],
            pod_it_allow[idx],
            pod_exist_ok[idx],
            pod_ports[idx],
            pod_port_conf[idx],
            topo_ops.take_pod_topology(pod_topo, idx),
            # CSI attach limits ride the what-if exactly like the live
            # solve: displaced pods re-attach their distinct-PVC columns
            # against each surviving node's caps (volumeusage.go:201-208 x
            # multinodeconsolidation.go:136-183)
            pod_vols[idx],
        )
        state, assignment = jax.lax.scan(step, state, xs)
        n_unsched = jnp.sum(count & valid & (assignment < 0)).astype(jnp.int32)
        return n_unsched, state.n_open

    return jax.vmap(one)(
        scen_pod_idx, scen_active, scen_count, scen_exist_valid, scen_vg_counts0, scen_hg_counts0
    )


# ---------------------------------------------------------------------------
# Kind-level batch placement (the north-star path)
# ---------------------------------------------------------------------------
#
# Real workloads are deployment-shaped: P pods collapse to a few hundred
# distinct KINDS (identical spec+labels). The per-pod scan places one pod
# per step; this path places a whole kind per step with closed-form
# water-fill mathematics, matching the per-pod cascade exactly:
#
#   tier 1  identical pods fill existing nodes in index order until each
#           node's capacity (resources, ports, hostname-topology) runs out
#           — the earliest-feasible-node-per-pod loop IS a cumsum fill.
#   tier 2  fewest-pods-first with earliest-slot tie-break over claims with
#           per-claim capacities IS water-filling: raise a level L over pod
#           counts; at the boundary level the remainder goes to eligible
#           claims in slot order.
#   tier 3  once in-flight capacity is exhausted, each new claim is filled
#           to capacity before the next opens (the fresh claim always has
#           the fewest pods), so opens are ceil(rem / per-claim-capacity).
#
# Hostname topology groups (TSC-hostname, anti-affinity) fold in as
# per-slot capacity clamps: hostname spread's global min is always 0 (a
# new node is always creatable), so a slot at count c takes at most
# skew - self - c + 1 more recording pods; anti-affinity slots take 1.
# Vocab-key (zonal) groups narrow requirements per placement and stay on
# the per-pod scan — the host only routes kinds with no vg interaction
# (and no minValues/reservations/finite budgets) here.
#
# Accumulation convention: a batch of c identical pods charges
# used + c*req in ONE f32 multiply-add (the host decode mirrors this
# exactly). This is closer to the reference's infinite-precision
# resource.Quantity arithmetic than c sequential f32 adds, but differs
# from the per-pod engines at float rounding boundaries; quantities that
# are f32-product-exact (milli-CPU counts, Mi memory, powers of two) are
# bit-identical across all engines.

COUNT_CAP = jnp.int32(2**22)  # "unbounded" per-candidate fill cap


class FillYs(NamedTuple):
    """Per-segment fill record (the decode expands these to per-pod
    assignments host-side)."""

    fill_e: jnp.ndarray  # [E] i32 — pods landed per existing node
    fill_c: jnp.ndarray  # [W] i32 — pods landed per WINDOW row (the host
    # maps rows to global claim ids via the dispatch's slot_of snapshot)
    open_start: jnp.ndarray  # [] i32 — w_open before this segment
    n_opened: jnp.ndarray  # [] i32 — new claims opened (contiguous rows)
    tmpl: jnp.ndarray  # [] i32 — template of opened claims (-1 = none)
    leftover: jnp.ndarray  # [] i32 — pods that failed to place
    status: jnp.ndarray  # [] i32 — NO_CLAIM / NO_ROOM for the leftover


def _count_cap_seq(used: jnp.ndarray, req: jnp.ndarray, limit: jnp.ndarray) -> jnp.ndarray:
    """[...] i32 — max c >= 0 with used + c*req <= limit elementwise over
    the trailing resource axis.

    The per-resource pass condition is TOTAL-based — `(t <= limit) |
    (t == 0.0)` — matching the per-pod engine's _fits_and_offering and the
    reference's resources.fits (a zero total passes even against negative
    headroom from daemon overhead; a zero REQUEST alone does not).

    Product convention (see module comment): the check is the f32
    multiply-add, with a +/-1 correction around the float division
    estimate, and the returned count re-verified against the check itself
    (zero on failure) so the result can never overcommit. The +/-1 window
    is exact whenever the quotient is below 2^23 — always, in practice:
    every pod requests pods=1 and allocatable pods is O(hundreds), so the
    binding quotient never approaches the f32 integer cliff.
    """
    pos = req > 0.0
    safe = jnp.where(pos, req, 1.0)
    head = limit - used
    est = jnp.min(jnp.where(pos, head / safe, jnp.inf), axis=-1)
    est = jnp.floor(jnp.where(jnp.isfinite(est), est, jnp.float32(COUNT_CAP)))
    c0 = jnp.clip(est, 0.0, jnp.float32(COUNT_CAP)).astype(jnp.int32)

    def ok(c):
        t = used + c[..., None].astype(jnp.float32) * req
        return jnp.all((t <= limit) | (t == 0.0), axis=-1)

    up = ok(c0 + 1)
    mid = ok(c0)
    dn = ok(jnp.maximum(c0 - 1, 0))
    return jnp.where(
        mid,
        jnp.where(up, c0 + 1, c0),
        jnp.where(dn, jnp.maximum(c0 - 1, 0), 0),
    )


def _hg_slot_caps(
    topo: TopologyTensors,
    counts: jnp.ndarray,  # [NGh, S]
    slots: jnp.ndarray,  # [C] i32
    applies: jnp.ndarray,  # [NGh] bool
    records: jnp.ndarray,  # [NGh] bool
    self_sel: jnp.ndarray,  # [NGh] bool
) -> jnp.ndarray:
    """[C] i32 — how many MORE pods of this kind each slot admits under the
    hostname groups (hg_evaluate's per-pod checks solved for the max count).
    Empty-group affinity bootstrap is excluded host-side."""
    cnt = counts[:, slots].T  # [C, NGh]
    rec = records[None, :]
    self_ = self_sel[None, :].astype(jnp.int32)
    skew = topo.hg_skew[None, :]
    inf = COUNT_CAP.astype(jnp.int32)
    spread = jnp.where(
        rec,
        skew - self_ - cnt + 1,
        jnp.where(cnt + self_ <= skew, inf, 0),
    )
    anti = jnp.where(cnt == 0, jnp.where(rec, 1, inf), 0)
    aff = jnp.where(cnt > 0, inf, 0)
    t = topo.hg_type[None, :]
    cap = jnp.where(
        t == topo_ops.TYPE_SPREAD,
        spread,
        jnp.where(t == topo_ops.TYPE_AFFINITY, aff, anti),
    )
    gate = (applies & topo.hg_valid)[None, :]
    cap = jnp.where(gate, cap, inf)
    return jnp.clip(jnp.min(cap, axis=-1), 0, COUNT_CAP)


def _fits_off_counted(
    used: jnp.ndarray,  # [B, R] — base usage per candidate row
    counts: jnp.ndarray,  # [B, T, GR] i32 — candidate fill counts
    req: jnp.ndarray,  # [R]
    it: InstanceTypeTensors,
    off: jnp.ndarray,  # [B, T, GR] bool — offering-available per group
) -> jnp.ndarray:
    """[B, T, GR] bool — used + counts*req fits the group's allocatable.
    Written as a static loop over the (small) resource axis so no
    [B, T, GR, R] intermediate materializes. The pass condition is
    total-based (`t == 0.0`), mirroring _fits_and_offering — a zero REQUEST
    with nonzero existing usage must still be checked against allocatable
    (e.g. daemon overhead exceeding capacity on an unrequested resource)."""
    R = req.shape[0]
    okc = off & it.group_valid[None, :, :]
    cf = counts.astype(jnp.float32)
    for r in range(R):
        t = used[:, None, None, r] + cf * req[r]
        okc &= (t <= it.alloc[None, :, :, r]) | (t == 0.0)
    return okc


def _claim_fill_caps(
    used: jnp.ndarray,  # [B, R]
    viable: jnp.ndarray,  # [B, T] bool — surviving instance types per row
    req: jnp.ndarray,  # [R]
    it: InstanceTypeTensors,
    off: jnp.ndarray,  # [B, T, GR] bool
) -> jnp.ndarray:
    """[B] i32 — max pods addable per candidate row: the best (type, group)
    among the row's viable types (fits-per-count is monotone, so the max
    count over viable cells equals the per-pod loop's stopping point)."""
    R = req.shape[0]
    pos = req > 0.0
    safe = jnp.where(pos, req, 1.0)
    okc = off & it.group_valid[None, :, :] & viable[:, :, None]
    est = jnp.full(okc.shape, jnp.float32(COUNT_CAP))
    for r in range(R):  # static unroll over the small resource axis
        head = it.alloc[None, :, :, r] - used[:, None, None, r]
        ratio = jnp.where(pos[r], head / safe[r], jnp.inf)
        est = jnp.minimum(est, ratio)
    c0 = jnp.clip(
        jnp.floor(jnp.where(jnp.isfinite(est), est, jnp.float32(COUNT_CAP))),
        0.0,
        jnp.float32(COUNT_CAP),
    ).astype(jnp.int32)

    def ok(c):
        acc = okc
        cf = c.astype(jnp.float32)
        for r in range(R):
            t = used[:, None, None, r] + cf * req[r]
            acc = acc & ((t <= it.alloc[None, :, :, r]) | (t == 0.0))
        return acc

    up = ok(c0 + 1)
    mid = ok(c0)
    dn = ok(jnp.maximum(c0 - 1, 0))
    # re-verified against the check itself (zero on failure) — see
    # _count_cap_seq for why the +/-1 window is exact in practice
    c = jnp.where(
        mid,
        jnp.where(up, c0 + 1, c0),
        jnp.where(dn, jnp.maximum(c0 - 1, 0), 0),
    )
    c = jnp.where(okc, c, 0)
    return jnp.max(jnp.max(c, axis=-1), axis=-1)  # [B]


def _water_fill(
    p: jnp.ndarray,  # [N] i32 — current pod counts
    f: jnp.ndarray,  # [N] i32 — per-claim additional capacity
    rem: jnp.ndarray,  # [] i32 — pods to place
) -> jnp.ndarray:
    """[N] i32 — distribute rem pods by fewest-pods-first with
    earliest-slot tie-break (the per-pod argmin over (pods, slot) loop in
    closed form): raise a water level L over the counts; claims fill to
    min(f, L-1-p); the remainder at level L goes to eligible claims in
    slot order."""
    f = jnp.minimum(f, rem)  # keeps int32 sums safe and levels tight
    total = jnp.sum(f)

    def placed(L):
        return jnp.sum(jnp.minimum(f, jnp.maximum(0, L - p)))

    # smallest L with placed(L) >= rem (search space: counts are < 2^22)
    def body(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) // 2
        geq = placed(mid) >= rem
        return jnp.where(geq, lo, mid + 1), jnp.where(geq, mid, hi)

    lo, hi = jax.lax.fori_loop(
        0, 24, body, (jnp.int32(0), jnp.max(p) + rem + 1)
    )
    L = lo
    base = jnp.minimum(f, jnp.maximum(0, (L - 1) - p))
    r0 = rem - jnp.sum(base)
    elig = (f > 0) & (p + base == L - 1) & (base < f)
    rank = jnp.cumsum(elig.astype(jnp.int32)) - elig.astype(jnp.int32)
    extra = (elig & (rank < r0)).astype(jnp.int32)
    fill = base + extra
    return jnp.where(total <= rem, f, fill)


class FillXs(NamedTuple):
    """Per-segment (pod kind) inputs to the fill scan."""

    reqs: ReqSetTensors  # [B, K, V]
    requests: jnp.ndarray  # [B, R]
    tmpl_ok: jnp.ndarray  # [B, G]
    it_allow: jnp.ndarray  # [B, T]
    exist_ok: jnp.ndarray  # [B, E]
    ports: jnp.ndarray  # [B, NP]
    port_conf: jnp.ndarray  # [B, NP]
    # distinct (driver, pvc) columns — IDENTICAL for every pod of a kind
    # (same pvc_names -> same PVCs), so a batch of c pods attaches the
    # set once: the check is count-independent
    vols: jnp.ndarray  # [B, NV]
    count: jnp.ndarray  # [B] i32 — pods of this kind (0 = padding row)
    hg_applies: jnp.ndarray  # [B, NGh]
    hg_records: jnp.ndarray  # [B, NGh]
    hg_self: jnp.ndarray  # [B, NGh]


def _make_fill_step(
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    annotate: bool = True,
):
    NCAP = n_claims
    # annotate=False inside the dp-batched speculative dispatch: there the
    # leading vmap axis IS the "dp" mesh axis, so hinting W over dp again
    # would fight the batch partitioning
    _hint = shard_hint if annotate else (lambda x, *a: x)
    E = exist.avail.shape[0]
    G = templates.its.shape[0]
    no_wk = jnp.zeros_like(well_known)

    def _off_for(comb, B):
        """[B, T, GR] bool — offering available in a (zone, ct) the
        combined requirements admit (the offering half of fits_off)."""
        zmask = comb.mask[:, zone_kid, :]
        cmask = comb.mask[:, ct_kid, :]
        Z = it.zc_avail.shape[2]
        C = it.zc_avail.shape[3]
        return (
            jnp.einsum(
                "tgzc,nz,nc->ntg",
                it.zc_avail.astype(jnp.bfloat16),
                zmask[:, :Z].astype(jnp.bfloat16),
                cmask[:, :C].astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            > 0
        )

    def step(state: SolverState, xs: FillXs):
        W = state.open.shape[0]
        count = xs.count
        requests = xs.requests
        self_conf = kernels.packed_conflict(xs.ports, xs.port_conf)

        # ---- tier 1: fill existing nodes in index order -------------------
        pod_e = _broadcast_pod(xs.reqs, E)
        comb_e = kernels.intersect_sets(state.exist_reqs, pod_e)
        compat_e = kernels.compatible_elemwise(state.exist_reqs, pod_e, no_wk)
        ports_ok_e = ~kernels.packed_conflict(xs.port_conf[None, :], state.exist_ports)
        cap_res_e = _count_cap_seq(state.exist_used, requests[None, :], exist.avail)
        cap_topo_e = _hg_slot_caps(
            topo,
            state.hg_counts,
            jnp.arange(E, dtype=jnp.int32),
            xs.hg_applies,
            xs.hg_records,
            xs.hg_self,
        )
        cap_e = jnp.minimum(cap_res_e, cap_topo_e)
        cap_e = jnp.where(self_conf, jnp.minimum(cap_e, 1), cap_e)
        # CSI attach limits: a kind's pods share one PVC set, so the check
        # is count-independent — the node admits the kind iff the union
        # stays within every driver cap (volumeusage.go:201-208)
        newv_e = state.exist_vols | xs.vols[None, :]
        vcount_e = kernels.packed_count_and(
            newv_e[:, None, :], exist.vol_driver[None, :, :]
        ).astype(jnp.float32)
        # volume-free kinds skip the check (host parity — see per-pod step)
        vols_ok_e = jnp.all(vcount_e <= exist.vol_limits, axis=-1) | ~kernels.packed_any(xs.vols)
        feas_e = exist.valid & xs.exist_ok & compat_e & ports_ok_e & vols_ok_e
        cap_e = jnp.where(feas_e, cap_e, 0)
        cap_e = jnp.minimum(cap_e, count)
        before = jnp.cumsum(cap_e) - cap_e
        fill_e = jnp.clip(count - before, 0, cap_e)
        rem = count - jnp.sum(fill_e)

        landed_e = fill_e > 0
        new_exist_used = state.exist_used + fill_e[:, None].astype(jnp.float32) * requests[None, :]
        new_exist_reqs = kernels.select_set(landed_e, comb_e, state.exist_reqs)
        new_exist_ports = jnp.where(
            landed_e[:, None], state.exist_ports | xs.ports[None, :], state.exist_ports
        )
        new_exist_vols = jnp.where(
            landed_e[:, None], state.exist_vols | xs.vols[None, :], state.exist_vols
        )

        # ---- tier 2: water-fill in-flight claims (the active window) ------
        pod_b = _broadcast_pod(xs.reqs, W)
        comb = kernels.intersect_sets(state.reqs, pod_b)
        claim_ok = kernels.compatible_elemwise(state.reqs, pod_b, well_known)
        it_compat = kernels.intersects(it.reqs, comb).T  # [W, T]
        off_n = _hint(_off_for(comb, W), "dp", "it")
        allow_t = xs.it_allow[None, :]
        viable = _hint(state.its & it_compat & allow_t, "dp", "it")
        cap_res_n = _claim_fill_caps(state.used, viable, requests, it, off_n)
        cap_topo_n = _hg_slot_caps(
            topo,
            state.hg_counts,
            E + state.slot_of,
            xs.hg_applies,
            xs.hg_records,
            xs.hg_self,
        )
        ports_ok_n = ~kernels.packed_conflict(xs.port_conf[None, :], state.claim_ports)
        tol = xs.tmpl_ok[state.template]
        feas_n = state.open & claim_ok & tol & ports_ok_n
        f_n = jnp.minimum(cap_res_n, cap_topo_n)
        f_n = jnp.where(self_conf, jnp.minimum(f_n, 1), f_n)
        f_n = jnp.where(feas_n, f_n, 0)
        fill_c2 = _water_fill(state.pods, f_n, rem)
        rem2 = rem - jnp.sum(fill_c2)

        landed_n = fill_c2 > 0
        used2 = state.used + fill_c2[:, None].astype(jnp.float32) * requests[None, :]
        fits_final = jnp.any(
            _fits_off_counted(state.used, jnp.broadcast_to(fill_c2[:, None, None], off_n.shape), requests, it, off_n),
            axis=-1,
        )  # [N, T]
        its2 = _hint(
            jnp.where(landed_n[:, None], viable & fits_final, state.its), "dp", "it"
        )
        reqs2 = kernels.select_set(landed_n, comb, state.reqs)
        pods2 = state.pods + fill_c2
        ports2 = jnp.where(
            landed_n[:, None], state.claim_ports | xs.ports[None, :], state.claim_ports
        )

        # ---- tier 3: open new claims, each filled to capacity -------------
        pod_g = _broadcast_pod(xs.reqs, G)
        comb0 = kernels.intersect_sets(templates.reqs, pod_g)
        tmpl_compat = kernels.compatible_elemwise(templates.reqs, pod_g, well_known)
        it_compat0 = kernels.intersects(it.reqs, comb0).T  # [G, T]
        off_g = _off_for(comb0, G)
        # the one-pod fits check mirrors the per-pod step's fits_off0
        fits_off0 = jnp.any(
            _fits_off_counted(
                templates.daemon_requests,
                jnp.ones(off_g.shape, dtype=jnp.int32),
                requests,
                it,
                off_g,
            ),
            axis=-1,
        )
        cap_ok = jnp.all(it.cap[None, :, :] <= state.budget[:, None, :], axis=-1)
        its0 = templates.its & it_compat0 & fits_off0 & allow_t & cap_ok
        cap_topo_fresh = _hg_slot_caps(
            topo,
            state.hg_counts,
            jnp.broadcast_to(E + state.n_open, (1,)).astype(jnp.int32),
            xs.hg_applies,
            xs.hg_records,
            xs.hg_self,
        )[0]
        tmpl_feas = (
            templates.valid
            & tmpl_compat
            & xs.tmpl_ok
            & jnp.any(its0, axis=-1)
            & (state.nodes_budget >= 1.0)
        )
        g = _pick_template(tmpl_feas, templates)
        any_template = jnp.any(tmpl_feas) & (cap_topo_fresh > 0)
        f_new0 = _claim_fill_caps(
            templates.daemon_requests, its0, requests, it, off_g
        )[g]
        f_new = jnp.minimum(f_new0, cap_topo_fresh)
        f_new = jnp.where(self_conf, jnp.minimum(f_new, 1), f_new)
        f_new = jnp.where(any_template, jnp.maximum(f_new, 0), 0)
        # fresh claims take contiguous WINDOW rows at w_open and contiguous
        # GLOBAL ids at n_open; the window and the global cap both bound
        # the opens (a window-bound shortfall is a spill the host recovers)
        avail_w = jnp.maximum(W - state.w_open, 0)
        avail_cap = jnp.maximum(NCAP - state.n_open, 0)
        slots_avail = jnp.minimum(avail_w, avail_cap)
        want = jnp.where(
            f_new > 0, (rem2 + f_new - 1) // jnp.maximum(f_new, 1), 0
        )
        n_new = jnp.minimum(want, slots_avail)
        spilled = (want > n_new) & (avail_cap > slots_avail)
        idx = jnp.arange(W, dtype=jnp.int32)
        i_new = idx - state.w_open
        is_new = (i_new >= 0) & (i_new < n_new)
        c_new = jnp.where(is_new, jnp.clip(rem2 - i_new * f_new, 0, f_new), 0)
        placed3 = jnp.sum(c_new)
        leftover = rem2 - placed3
        status = jnp.where(any_template, jnp.int32(NO_ROOM), jnp.int32(NO_CLAIM))
        new_slot_of = jnp.where(is_new, state.n_open + i_new, state.slot_of)

        used3 = jnp.where(
            is_new[:, None],
            templates.daemon_requests[g][None, :]
            + c_new[:, None].astype(jnp.float32) * requests[None, :],
            used2,
        )
        off_new = jnp.broadcast_to(off_g[g][None], (W,) + off_g.shape[1:])
        fits_new = jnp.any(
            _fits_off_counted(
                jnp.broadcast_to(templates.daemon_requests[g][None, :], (W, requests.shape[0])),
                jnp.broadcast_to(c_new[:, None, None], off_new.shape),
                requests,
                it,
                off_new,
            ),
            axis=-1,
        )  # [W, T]
        its3 = jnp.where(is_new[:, None], its0[g][None, :] & fits_new, its2)
        reqs3 = kernels.select_set(is_new, _broadcast_pod(kernels.take_set(comb0, g), W), reqs2)
        template3 = jnp.where(is_new, g.astype(jnp.int32), state.template)
        open3 = state.open | is_new
        pods3 = jnp.where(is_new, c_new, pods2)
        ports3 = jnp.where(
            (is_new & (c_new > 0))[:, None], ports2 | xs.ports[None, :], ports2
        )
        new_n_open = state.n_open + n_new
        new_w_open = state.w_open + n_new

        # hostname-group count commits for every landed pod, scattered at
        # GLOBAL slots (window rows map through slot_of; unused-row adds
        # carry count 0 into the spare column, a no-op)
        S = state.hg_counts.shape[1]
        fill_claims = jnp.where(is_new, c_new, fill_c2)
        fill_slots = jnp.pad(fill_e, (0, S - E)).at[E + new_slot_of].add(
            fill_claims, mode="drop"
        )
        rec = (xs.hg_records & topo.hg_valid).astype(jnp.int32)
        new_hg_counts = state.hg_counts + rec[:, None] * fill_slots[None, :]

        # budget bookkeeping (the host only routes kinds here when every
        # candidate template budget is unlimited, so these stay +inf)
        max_cap = jnp.max(jnp.where(its0[g][:, None], it.cap, -jnp.inf), axis=0)
        max_cap = jnp.where(jnp.isfinite(max_cap), max_cap, 0.0)
        new_budget = state.budget.at[g].add(-max_cap * n_new.astype(jnp.float32))
        new_nodes_budget = state.nodes_budget.at[g].add(-n_new.astype(jnp.float32))

        ys = FillYs(
            fill_e=fill_e,
            fill_c=fill_claims,
            open_start=state.w_open,
            n_opened=n_new,
            tmpl=jnp.where(n_new > 0, g.astype(jnp.int32), jnp.int32(-1)),
            leftover=leftover,
            status=status,
        )
        return (
            state._replace(
                exist_reqs=new_exist_reqs,
                exist_used=new_exist_used,
                reqs=reqs3,
                used=used3,
                its=its3,
                template=template3,
                open=open3,
                pods=pods3,
                n_open=new_n_open,
                slot_of=new_slot_of,
                w_open=new_w_open,
                w_hw=jnp.maximum(state.w_hw, new_w_open),
                spills=state.spills + jnp.where(spilled, 1, 0).astype(jnp.int32),
                budget=new_budget,
                nodes_budget=new_nodes_budget,
                hg_counts=new_hg_counts,
                exist_ports=new_exist_ports,
                claim_ports=ports3,
                exist_vols=new_exist_vols,
            ),
            ys,
        )

    return step


_FILL_STATIC = ("zone_kid", "ct_kid", "n_claims")


@_wf_timed("solve_fill")
@named_kernel("solve_fill")
@functools.partial(jax.jit, static_argnames=_FILL_STATIC)
def solve_fill(
    state: SolverState,
    xs: FillXs,
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
) -> tuple[SolverState, FillYs]:
    """Scan kind-level batch placement over B segments, threading the same
    SolverState the per-pod scan uses — the host interleaves the two
    dispatches freely (vg-topology kinds per-pod, everything else here)."""
    step = _make_fill_step(
        exist, it, templates, well_known, topo, zone_kid, ct_kid, n_claims
    )
    return jax.lax.scan(step, state, xs)


# ---------------------------------------------------------------------------
# dp-sharded speculative fill (ISSUE 8): independent chunk groups solve
# concurrently across the mesh's dp rows, merged exact-or-replay
# ---------------------------------------------------------------------------
#
# The pipelined fill splits a big solve into ~K chunk groups of whole kind
# segments. Sequentially, group g's dispatch sees the claims groups 0..g-1
# opened; the couplings between fill groups (infinite budgets, no
# reservations, no enforced minValues are implied by the fill routing
# itself) are (a) water-fills into earlier groups' still-open claims,
# (b) the global claim-id counter, (c) existing-node capacity debits and
# (d) hostname-group counts. (a)/(b) are handled by the deadness + graft
# machinery below; (c)/(d) (ISSUE 14) by per-row deltas whose
# disjointness the verdict proves on device (_exist_conflict_ok, the hg
# record-vs-apply bit) and which merge order-free. So:
#
#   * every dp row solves ITS group against the SAME base state in one
#     batched vmapped dispatch (rows sharded over the mesh's dp axis —
#     each row's scan is row-local, no cross-row collectives);
#   * the host merges groups in order. A group commits WITHOUT re-solving
#     iff every live open claim in the committed state is capacity-dead
#     w.r.t. the group's elementwise-min request (window_live_dead — the
#     frozen-bank eviction rule as a predicate): then no pod of the group
#     could have landed on ANY pre-existing claim (fits is total-based and
#     monotone in the request), so the speculative solve from the base
#     equals the sequential solve from the committed state row-for-row, up
#     to the claim-id offset. merge_shard_fill grafts the group's fresh
#     rows onto the committed window with ids shifted by that offset —
#     committed claims effectively became decode-only rows the group
#     constrained against but never rescanned, exactly the bank's
#     contract.
#   * any failed check (live non-dead claims, leftovers, window spill, or
#     window/claim-axis overflow at the graft) REPLAYS the group as a
#     normal sequential dispatch — so the dp path is bit-identical to the
#     single-device solve by construction, never by luck.


class ShardFillState(NamedTuple):
    """The window-row slice + counters + existing-node debit state +
    hostname-group counts of one speculative per-shard fill solve. Bank,
    budget, vg-topology and reservation state are unchanged by
    construction on the fill-routable problem class, so they never cross
    the merge (and the dp dispatch never materializes DP copies of the
    [NCAP, T] bank). Existing-node fields and hg counts DO mutate under
    real existing nodes / topology-bearing kinds (ISSUE 14); the verdict's
    disjointness bits prove the per-row deltas merge order-free."""

    reqs: ReqSetTensors  # [W, K, V]
    used: jnp.ndarray  # [W, R]
    its: jnp.ndarray  # [W, T]
    template: jnp.ndarray  # [W]
    open: jnp.ndarray  # [W]
    pods: jnp.ndarray  # [W]
    slot_of: jnp.ndarray  # [W]
    claim_ports: jnp.ndarray  # [W, NPp]
    held: jnp.ndarray  # [W, RID]
    n_open: jnp.ndarray  # [] i32
    w_open: jnp.ndarray  # [] i32
    spills: jnp.ndarray  # [] i32
    exist_reqs: ReqSetTensors  # [E, K, V]
    exist_used: jnp.ndarray  # [E, R]
    exist_ports: jnp.ndarray  # [E, NPp]
    exist_vols: jnp.ndarray  # [E, NVp]
    hg_counts: jnp.ndarray  # [NGh, E + NCAP + 1]


@_wf_timed("solve_fill_dp")
@named_kernel("solve_fill_dp")
@functools.partial(jax.jit, static_argnames=_FILL_STATIC)
def solve_fill_dp(
    state: SolverState,
    xs_b: FillXs,  # leading [DP] group axis on every tensor
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
) -> tuple[ShardFillState, FillYs, jnp.ndarray]:
    """Speculative dp fan-out: one batched dispatch runs every dp row's
    chunk group against the same base state (vmap over the leading group
    axis, inputs sharded over the mesh's dp rows). Returns per-row slim
    states + fill grids + ONE packed commit-verdict word (`_dp_verdict_word`
    — every commit check evaluated on device, prefix-ANDed over rows and
    bit-packed via kernels.pack_bool), so the host merge loop fetches a
    single uint32 lane per round instead of per-group scalar probes. The
    host commits the verdict's leading-ones prefix in order via
    merge_shard_fill and replays the first refused group
    (scheduler._run_fill_dp)."""

    def one(xs: FillXs):
        step = _make_fill_step(
            exist, it, templates, well_known, topo, zone_kid, ct_kid,
            n_claims, annotate=False,
        )
        st, ys = jax.lax.scan(step, state, xs)
        return (
            ShardFillState(
                reqs=st.reqs, used=st.used, its=st.its, template=st.template,
                open=st.open, pods=st.pods, slot_of=st.slot_of,
                claim_ports=st.claim_ports, held=st.held, n_open=st.n_open,
                w_open=st.w_open, spills=st.spills,
                exist_reqs=st.exist_reqs, exist_used=st.exist_used,
                exist_ports=st.exist_ports, exist_vols=st.exist_vols,
                hg_counts=st.hg_counts,
            ),
            ys,
        )

    # group-axis hints: every row's tensors live on its dp row; it_allow
    # additionally keeps its catalog axis on "it" (it was gathered from the
    # it-sharded per-kind allow mask — re-replicating it would force a full
    # rematerialization)
    allow = xs_b.it_allow
    xs_b = jax.tree_util.tree_map(
        lambda a: a if a is allow else shard_hint(a, "dp"), xs_b
    )
    xs_b = xs_b._replace(it_allow=shard_hint(allow, "dp", None, "it"))
    spec, ys = jax.vmap(one)(xs_b)
    r_min = _dp_group_r_min(xs_b.count, xs_b.requests)
    live = (xs_b.count > 0)[:, :, None]
    # hostname-group disjointness (topology-bearing fill, ISSUE 14): the
    # fill's only topology reads are hg caps gated on the segment's
    # applies mask, so record-vs-apply disjointness between rows keeps
    # every gated count read bitwise-unchanged by earlier commits —
    # exactly solve_kscan_dp's rule, minus the vg half (batchable kinds
    # carry no vg interactions by construction)
    app_h = jnp.any(live & xs_b.hg_applies, axis=1) & topo.hg_valid[None]
    rec_h = jnp.any(live & xs_b.hg_records, axis=1) & topo.hg_valid[None]
    hg_ok = kernels.pairwise_commit_ok(
        jnp.any(rec_h[:, None, :] & app_h[None, :, :], axis=-1)
    )
    exist_ok_rows = jnp.any(live & xs_b.exist_ok, axis=1)
    exist_bit = _exist_conflict_ok(state, spec, exist, exist_ok_rows, r_min)
    verdict = _dp_verdict_word(
        state, spec, r_min, n_claims,
        lambda u, iv, om, rm: _rows_dead(u, iv, om, it, rm),
        touched=jax.vmap(lambda fc: fill_touched_below(fc, state.w_open))(
            ys.fill_c
        ),
        extra_ok=(jnp.sum(ys.leftover, axis=1) == 0) & hg_ok & exist_bit,
    )
    return spec, ys, verdict


# placement-objective ids (objectives/registry.py POLICIES order); static
# jit args so each objective's score reduction compiles to a fixed formula
OBJ_LEXICAL = 0
OBJ_COST_MIN = 1
OBJ_FRAG_AWARE = 2
OBJ_TOPO_SPREAD = 3
OBJ_GANG_SLICE = 4

# the variant verdict word reserves the top byte for the winner index, so
# at most 24 rank variants ride one uint32 lane
VARIANT_MAX = 24


def _objective_score(base, st, price_t, objective: int, G: int):
    """[] f32 — the device half of one objective's realized score over the
    claims THIS dispatch opened (window rows [base.w_open, st.w_open)).
    The host oracle (objectives/oracle.py score_opened) mirrors each
    formula in np.float32 — the objective-twin audit compares the two."""
    W = st.open.shape[0]
    rows = jnp.arange(W, dtype=jnp.int32)
    opened = (rows >= base.w_open) & (rows < st.w_open) & st.open
    n_opened = (st.w_open - base.w_open).astype(jnp.float32)
    if objective == OBJ_COST_MIN:
        # cheapest still-viable instance type per opened claim (price_t is
        # the catalog's per-type min offering price, +inf when unpriced)
        row_price = jnp.min(
            jnp.where(st.its, price_t[None, :], jnp.inf), axis=1
        )
        return jnp.sum(jnp.where(opened, row_price, 0.0))
    if objective == OBJ_FRAG_AWARE:
        # fewest fresh claims first, then densest packing onto them
        landed = jnp.sum(jnp.where(opened, st.pods, 0).astype(jnp.float32))
        return n_opened * jnp.float32(1e6) - landed
    if objective == OBJ_TOPO_SPREAD:
        # sum of squared per-template claim counts: minimized when fresh
        # claims balance across the (zone/offering-bearing) templates
        cnt = jnp.zeros(G, dtype=jnp.float32).at[st.template].add(
            opened.astype(jnp.float32)
        )
        return jnp.sum(cnt * cnt)
    if objective == OBJ_GANG_SLICE:
        # slice-footprint slack vs the fullest block (gang/oracle.py
        # hosts_needed: uniform full blocks minimize hosts), plus the
        # block count itself
        p_max = jnp.max(jnp.where(opened, st.pods, 0))
        slack = jnp.where(opened, p_max - st.pods, 0).astype(jnp.float32)
        return jnp.sum(slack) + n_opened
    return jnp.float32(0.0)


_VARIANT_STATIC = ("zone_kid", "ct_kid", "n_claims", "objective")


@_wf_timed("solve_fill_variants")
@named_kernel("solve_fill_variants")
@functools.partial(jax.jit, static_argnames=_VARIANT_STATIC)
def solve_fill_variants(
    state: SolverState,
    xs: FillXs,
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    ranks: jnp.ndarray,  # [KV, G] i32 — row 0 = the policy's canonical rank
    price_t: jnp.ndarray,  # [T] f32 — per-type min offering price (+inf unknown)
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    objective: int,
) -> tuple[ShardFillState, FillYs, jnp.ndarray, jnp.ndarray]:
    """K objective-perturbed rank variants of ONE chunk group ride the dp
    axis: every variant solves the SAME group against the SAME base state
    under its own template rank (vmap over the rank axis, rows sharded
    over the mesh's dp rows — padded-idle dp rows are free variant
    capacity), and the realized objective score of each outcome folds
    into ONE packed verdict word the host fetches per merge round:

      bits [0, KV)   per-variant feasibility — the commit bits (zero
                     leftovers, no window spill), same semantics as
                     _dp_verdict_word's fit checks;
      bits [24, 32)  the argmin-score winner among feasible variants
                     (ties to the lowest index; variant 0 carries the
                     policy's canonical rank, so a scoreless tie is the
                     canonical outcome).

    Unlike the speculative dp fan-out there is no cross-variant merge to
    prove: exactly one variant commits, and its state IS the sequential
    solve of this group under that rank — full-fidelity scan from the
    committed base, nothing speculative. No feasible variant (word low
    bits all zero) replays the group through the normal sequential
    dispatch and its escalation ladder."""
    KV = ranks.shape[0]
    G = templates.its.shape[0]

    def one(rank_v):
        step = _make_fill_step(
            exist, it, templates._replace(rank=rank_v), well_known, topo,
            zone_kid, ct_kid, n_claims, annotate=False,
        )
        st, ys = jax.lax.scan(step, state, xs)
        score = _objective_score(state, st, price_t, objective, G)
        return (
            ShardFillState(
                reqs=st.reqs, used=st.used, its=st.its, template=st.template,
                open=st.open, pods=st.pods, slot_of=st.slot_of,
                claim_ports=st.claim_ports, held=st.held, n_open=st.n_open,
                w_open=st.w_open, spills=st.spills,
                exist_reqs=st.exist_reqs, exist_used=st.exist_used,
                exist_ports=st.exist_ports, exist_vols=st.exist_vols,
                hg_counts=st.hg_counts,
            ),
            ys,
            score,
        )

    spec, ys, scores = jax.vmap(one)(shard_hint(ranks, "dp"))
    feasible = (jnp.sum(ys.leftover, axis=1) == 0) & (
        spec.spills == state.spills
    )
    best = jnp.argmin(jnp.where(feasible, scores, jnp.inf))
    winner = jnp.where(jnp.any(feasible), best, 0).astype(jnp.uint32)
    word = kernels.pack_bool(feasible)[0] | (winner << jnp.uint32(24))
    return spec, ys, word, scores


def _rows_dead(used, its, open_mask, it, r_min):
    """[] bool — TRUE when every live open row in (used, its, open_mask)
    is capacity-dead w.r.t. r_min: used + r_min fits no viable
    (type, group) cell — compact_state's eviction rule as a read-only
    predicate over an explicit row slice."""
    total = used + r_min[None, :]
    t = total[:, None, None, :]
    fit = jnp.all((t <= it.alloc[None]) | (t == 0.0), axis=-1)
    alive_cap = jnp.any(
        fit & it.group_valid[None] & its[:, :, None], axis=(1, 2)
    )
    return ~jnp.any(open_mask & alive_cap)


def _dp_group_r_min(count, requests):
    """[DP, R] — each dp row's elementwise-min request over its live
    (count > 0) segments. All-padding rows go +inf: inf totals fit no
    cell (and 0*inf NaNs compare false in the grid's verify step), so a
    padded row is trivially dead — its commit is then decided by the fit
    checks alone, which a no-op group passes with k == opened == 0."""
    return jnp.min(
        jnp.where((count > 0)[:, :, None], requests, jnp.inf), axis=1
    )


def _exist_touched(spec, base):
    """[E] bool — existing nodes whose state a speculative row mutated,
    detected as ANY field delta vs the round base (used debits, narrowed
    requirements, port claims, volume attaches). Zero-delta landings are
    genuinely commutative — a pod that changes no existing-node field
    cannot change any later row's evaluation of that node — so the delta
    mask is exactly the set of nodes whose merge order matters."""

    def diff(a, b):
        return jnp.any(
            a != b, axis=tuple(range(1, a.ndim))
        )

    d = diff(spec.exist_used, base.exist_used)
    d |= diff(spec.exist_ports, base.exist_ports)
    d |= diff(spec.exist_vols, base.exist_vols)
    for f in ("mask", "inf", "excl", "gte", "lte", "defined"):
        d |= diff(getattr(spec.exist_reqs, f), getattr(base.exist_reqs, f))
    return d


def _exist_conflict_ok(state, spec, exist, exist_ok_rows, r_min):
    """[DP] bool — the existing-node debit disjointness bit (ISSUE 14a).
    Row r commits past row q only when no node q TOUCHED (field delta vs
    the round base) is VIABLE for r. Viability is the conservative
    superset `valid & static-exist_ok & capacity(base used, r_min) > 0`:
    _count_cap_seq's total-based pass rule is monotone decreasing in both
    the request (every pod of r requests >= r_min) and the used vector
    (post-commit used >= base used), and the remaining per-node gates
    (compat, ports, volumes, hg caps) only narrow — so a node non-viable
    at the base yields capacity 0 / infeasible in BOTH the speculative
    and the sequential world, making r's per-node evaluation bitwise
    identical on every node it could possibly use. Disjoint touch sets
    then merge order-free as whole-field grafts (_graft_exist_fields)."""
    touched = jax.vmap(_exist_touched, in_axes=(0, None))(spec, state)
    cap = jax.vmap(
        lambda rm: _count_cap_seq(state.exist_used, rm[None, :], exist.avail)
    )(r_min)  # [DP, E]
    viable = exist.valid[None, :] & exist_ok_rows & (cap > 0)
    conflict = jnp.any(
        touched[:, None, :] & viable[None, :, :], axis=-1
    )  # [q, r]
    return kernels.pairwise_commit_ok(conflict)


def _dp_verdict_word(state, spec, r_min, n_claims, rows_dead, touched, extra_ok):
    """[lanes] uint32 — the packed per-round commit verdict, every check
    on device (ISSUE 13 rung 1: no per-group scalar probes). Row r's bit
    is set iff r and every row before it pass ALL commit conditions:

      * every live open claim of the BASE state is capacity-dead for
        r's elementwise-min request (rows_dead — the family-specific
        deadness predicate), and so is every claim OPENED by each
        earlier row q < r (the cross check: those rows are exactly what
        the sequential solve would have committed before r);
      * r touched no pre-base window row (touched) and passes the
        family extra (fill: zero leftovers; all families: the vg/hg
        record-vs-apply and existing-node debit disjointness bits);
        r_min is the caller's [DP, R] per-row elementwise-min request
        (_dp_group_r_min for segment scans, a valid-masked min for the
        per-pod family);
      * r's spill counter is unchanged, and the cumulative window/
        claim-axis graft offsets stay in bounds (conservative under
        mid-prefix compaction, which only shrinks w_open).

    The prefix-AND means the host reads leading ones = groups to commit
    in order; the first zero bit replays sequentially (exact-or-replay,
    bit-parity by construction)."""
    DP = spec.w_open.shape[0]
    W = state.open.shape[0]
    rows = jnp.arange(W, dtype=jnp.int32)
    opened_rows = (
        (rows[None, :] >= state.w_open)
        & (rows[None, :] < spec.w_open[:, None])
        & spec.open
    )  # [DP, W] — each row's freshly opened claims

    def dead_for(rm):
        base = rows_dead(state.used, state.its, state.open, rm)
        cross = jax.vmap(lambda u, iv, om: rows_dead(u, iv, om, rm))(
            spec.used, spec.its, opened_rows
        )
        return base, cross

    # sequential map over the (tiny) dp extent keeps the [W, T, GR]-sized
    # deadness intermediates at one r at a time instead of DP^2 of them
    dead_base, cross = jax.lax.map(dead_for, r_min)  # [DP], [DP(r), DP(q)]
    qi = jnp.arange(DP, dtype=jnp.int32)
    cross_ok = jnp.all(cross | (qi[None, :] >= qi[:, None]), axis=1)
    spill_ok = spec.spills == state.spills
    k = spec.w_open - state.w_open
    opened_n = spec.n_open - state.n_open
    fit_w = state.w_open + jnp.cumsum(k) <= W
    fit_n = state.n_open + jnp.cumsum(opened_n) <= jnp.int32(n_claims)
    ok = (
        dead_base & cross_ok & ~touched & extra_ok & spill_ok & fit_w & fit_n
    )
    prefix = jnp.cumsum((~ok).astype(jnp.int32)) == 0
    return kernels.pack_bool(prefix)


@jax.jit
def window_live_dead(state: SolverState, it: InstanceTypeTensors, r_min: jnp.ndarray):
    """[] bool — TRUE when every live open window claim is capacity-dead
    w.r.t. r_min (used + r_min fits no viable (type, group) cell —
    compact_state's eviction rule as a read-only predicate). Every pod of
    a chunk group requests >= the group's elementwise-min r_min, and the
    total-based fits rule is monotone in the request, so TRUE proves a
    fill of that group cannot touch any existing open claim: the dp
    merge's commit condition, evaluated on device inside solve_fill_dp's
    verdict word (kept as a standalone jit for the differential tests)."""
    return _rows_dead(state.used, state.its, state.open, it, r_min)


@jax.jit
def fill_touched_below(fill_c: jnp.ndarray, w_lo: jnp.ndarray):
    """[] bool — did any fill land on a window row < w_lo? The dp commit's
    second condition: a speculative group must not have filled any row
    that pre-existed its base (those rows may since have been filled by a
    REPLAYED earlier group — deadness at commit time does not imply
    deadness at speculation time, so a base-row fill invalidates the
    speculation even when window_live_dead now holds)."""
    W = fill_c.shape[-1]
    rows = jnp.arange(W, dtype=jnp.int32)
    return jnp.any((fill_c > 0) & (rows < w_lo)[None, :])


@jax.jit
def take_dp_row(tree, r: jnp.ndarray):
    """Slice dp row r out of a batched spec-result pytree as ONE compiled
    program (eagerly slicing ~24 sharded leaves enqueues that many tiny
    multi-device programs — the merge loop keeps collective-bearing
    computations strictly one-at-a-time, see _run_fill_dp)."""
    return jax.tree_util.tree_map(lambda a: a[r], tree)


def _graft_window_fields(committed, spec, base_n_open, base_w_open):
    """The window graft shared by every speculative family: spec rows
    [base_w_open, spec.w_open) — fresh opens append contiguously within
    one dispatch — land at committed.w_open.. with global ids shifted by
    delta = (committed.n_open - base_n_open). Returns the SolverState
    field updates plus (shifted_slot_map, delta); families layer their
    extra state (kscan: vg/hg counts, assignment ids) on top."""
    W = committed.open.shape[0]
    NB = committed.bank_frozen.shape[0]
    base_n_open = jnp.asarray(base_n_open, dtype=jnp.int32)
    base_w_open = jnp.asarray(base_w_open, dtype=jnp.int32)
    k = spec.w_open - base_w_open
    delta = committed.n_open - base_n_open
    idx = jnp.arange(W, dtype=jnp.int32)
    pos = idx - committed.w_open
    grab = (pos >= 0) & (pos < k)
    src = jnp.clip(base_w_open + pos, 0, W - 1)
    shifted = jnp.where(
        (spec.slot_of >= base_n_open) & (spec.slot_of < NB),
        spec.slot_of + delta,
        spec.slot_of,
    )

    def take(cf, sf):
        g = grab.reshape(grab.shape + (1,) * (cf.ndim - 1))
        return jnp.where(g, sf[src], cf)

    reqs = kernels.select_set(
        grab, kernels.take_set(spec.reqs, src), committed.reqs
    )
    w_open = committed.w_open + k
    fields = dict(
        reqs=reqs,
        used=take(committed.used, spec.used),
        its=take(committed.its, spec.its),
        template=take(committed.template, spec.template),
        open=committed.open | grab,
        pods=take(committed.pods, spec.pods),
        slot_of=jnp.where(grab, shifted[src], committed.slot_of),
        claim_ports=take(committed.claim_ports, spec.claim_ports),
        held=take(committed.held, spec.held),
        n_open=committed.n_open + (spec.n_open - base_n_open),
        w_open=w_open,
        w_hw=jnp.maximum(committed.w_hw, w_open),
    )
    return fields, shifted, delta


def _graft_exist_fields(committed, spec, base):
    """Existing-node debit merge: whole-field graft of every node the
    spec row touched (field delta vs the ROUND base). The verdict's
    debit-disjointness bit proves touch sets are pairwise disjoint across
    the committed prefix and untouched by the base-viability of later
    rows, so per-node where-grafts compose order-free and equal the
    sequential replay bit-for-bit."""
    touched = _exist_touched(spec, base)
    return dict(
        exist_reqs=kernels.select_set(
            touched, spec.exist_reqs, committed.exist_reqs
        ),
        exist_used=jnp.where(
            touched[:, None], spec.exist_used, committed.exist_used
        ),
        exist_ports=jnp.where(
            touched[:, None], spec.exist_ports, committed.exist_ports
        ),
        exist_vols=jnp.where(
            touched[:, None], spec.exist_vols, committed.exist_vols
        ),
    )


def _merge_hg_delta(committed, spec_hg, base, delta, spec_n_open):
    """Hostname-group count merge shared by every speculative family:
    existing-node columns [0, E) add their deltas in place (those slots
    are global, no id shift), fresh-claim columns shift by the claim-id
    delta before adding — the same id isomorphism the window graft
    applies to slot_of. Committed rows' recorded groups are pairwise
    disjoint from later rows' applied groups (verdict), so the adds are
    order-free."""
    E = committed.exist_used.shape[0]
    S = committed.hg_counts.shape[1]
    base_n = jnp.asarray(base.n_open, dtype=jnp.int32)
    cols = jnp.arange(S, dtype=jnp.int32)
    src_c = jnp.clip(cols - delta, 0, S - 1)
    dh = spec_hg - base.hg_counts
    in_rng = (cols - delta >= E + base_n) & (cols - delta < E + spec_n_open)
    return (
        committed.hg_counts
        + jnp.where(in_rng[None, :], jnp.take(dh, src_c, axis=1), 0)
        + jnp.where((cols < E)[None, :], dh, 0)
    )


@_wf_timed("merge_shard_fill")
@jax.jit
def merge_shard_fill(
    committed: SolverState,
    spec: ShardFillState,
    base: SolverState,
) -> tuple[SolverState, jnp.ndarray]:
    """Graft a committed speculative fill group onto the committed state.
    Exact under the commit conditions (window_live_dead for the group,
    zero leftovers/spills, hg record-vs-apply + existing-node debit
    disjointness, no window or claim-axis overflow), which the verdict
    word proves BEFORE the host dispatches this. `base` is the ROUND
    base state every row of the dispatch speculated from — the reference
    the exist/hg deltas are taken against. Returns
    (merged, shifted_slot_map): the spec dispatch's window->global map
    re-based into committed ids, i.e. the decode's slot snapshot for the
    group's fill grids."""
    fields, shifted, delta = _graft_window_fields(
        committed, spec, base.n_open, base.w_open
    )
    exist_fields = _graft_exist_fields(committed, spec, base)
    hg = _merge_hg_delta(committed, spec.hg_counts, base, delta, spec.n_open)
    return committed._replace(hg_counts=hg, **exist_fields, **fields), shifted


# ---------------------------------------------------------------------------
# Gang-atomic slice placement (all-or-nothing batched device constraint)
# ---------------------------------------------------------------------------
#
# A gang is one scan segment (its pods are one content-identical kind, in
# rank order). Placement is tier-3 ONLY — a multi-host slice is a group of
# freshly-opened DEDICATED claims from one weight-ordered template; gangs
# never land on existing nodes and never share claims with singleton pods.
# The step computes the slice shape in closed form:
#
#   f    = per-host capacity (the fill kernel's per-claim water level)
#   want = ceil(size / f) hosts
#
# and commits atomically: either every member lands (rank r on host
# r // f — contiguous rank blocks, so co-ranked pods sit on adjacent
# chips) or NO member does. Committed gang claims bypass the active
# window entirely and are written straight into the frozen bank: they are
# full-by-construction and dedicated, so the scan must never rescan them
# and later tier-2 water-fills must never see them. n_open still advances,
# so global claim ids, hostname-placeholder order, and the fresh-slot
# hostname reads of later dispatches stay identical to the host oracle's
# gangs-first bookkeeping.
#
# Spill semantics reuse the existing recovery ladder: a claims-axis-bound
# refusal is NO_ROOM (solve_round escalates the axis and re-solves); a
# refusal no escalation can fix (finite node budget below the slice size,
# or a rank block refused by topology/capacity under narrowing) is
# GANG_SPILL — the host reports every member together and keeps the gang
# pending. Since ISSUE 20 rung 2 the routed class covers finite budgets
# (per-block subtractMax debits over the block's narrowed remaining
# types), vocab-key topology whose groups unify to ONE key with
# <= KSCAN_D values (the kscan _vg_eval narrowing runs once per rank
# block — counts are fixed within a block because the host records after
# the block's add loop), and hostname-group interaction (hg_evaluate at
# each block's fresh slot, commits scaled by the block's pod count).
# Only enforced minValues, reservations, and non-unifiable vg keys still
# degrade the solve to the host oracle, which implements the identical
# semantics exactly.


class GangYs(NamedTuple):
    """Per-gang segment record (scalars; the decode expands rank blocks)."""

    open_g: jnp.ndarray  # [] i32 — first global claim id of the slice
    n_opened: jnp.ndarray  # [] i32 — hosts opened (0 = gang did not place)
    fill: jnp.ndarray  # [] i32 — pods per host (last host takes the rest)
    tmpl: jnp.ndarray  # [] i32 — slice template (-1 = none)
    leftover: jnp.ndarray  # [] i32 — 0 (placed) or the full gang size
    status: jnp.ndarray  # [] i32 — NO_CLAIM / NO_ROOM / GANG_SPILL


def _make_gang_step(
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    maxg: int,
    key_kid: int = -1,
    D: int = 1,
    tk_idx: int = -1,
):
    NCAP = n_claims
    G = templates.its.shape[0]
    T = templates.its.shape[1]
    E = exist.avail.shape[0]
    i32 = jnp.int32
    has_key = key_kid >= 0

    def step(state: SolverState, xs: KindXs):
        count = xs.count
        requests = xs.requests
        R = requests.shape[0]
        self_conf = kernels.packed_conflict(xs.ports, xs.port_conf)
        gate = xs.vg_applies & topo.vg_valid
        recs = xs.vg_records & topo.vg_valid
        rec_h = xs.hg_records & topo.hg_valid
        key_touched = jnp.any(gate)
        is_anti = topo.vg_type == topo_ops.TYPE_ANTI

        # slice template selection — the fill step's tier 3 verbatim. The
        # host's chosen-template loop never consults topology (counts or
        # hostname groups): a template whose blocks later fail on topology
        # spills the gang rather than falling through to the next template
        # (ISSUE 20 rung 2 matches that exactly, so the pre-rung
        # cap_topo_fresh clamp — vacuous on the then-routed class — is
        # gone).
        pod_g = _broadcast_pod(xs.reqs, G)
        comb0 = kernels.intersect_sets(templates.reqs, pod_g)
        tmpl_compat = kernels.compatible_elemwise(templates.reqs, pod_g, well_known)
        it_compat0 = kernels.intersects(it.reqs, comb0).T  # [G, T]
        zmask = comb0.mask[:, zone_kid, :]
        cmask = comb0.mask[:, ct_kid, :]
        Z = it.zc_avail.shape[2]
        C = it.zc_avail.shape[3]
        off_g = (
            jnp.einsum(
                "tgzc,nz,nc->ntg",
                it.zc_avail.astype(jnp.bfloat16),
                zmask[:, :Z].astype(jnp.bfloat16),
                cmask[:, :C].astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            > 0
        )
        fits_off0 = jnp.any(
            _fits_off_counted(
                templates.daemon_requests,
                jnp.ones(off_g.shape, dtype=jnp.int32),
                requests,
                it,
                off_g,
            ),
            axis=-1,
        )
        cap_ok = jnp.all(it.cap[None, :, :] <= state.budget[:, None, :], axis=-1)
        its0 = templates.its & it_compat0 & fits_off0 & xs.it_allow[None, :] & cap_ok
        tmpl_feas = (
            templates.valid
            & tmpl_compat
            & xs.tmpl_ok
            & jnp.any(its0, axis=-1)
            & (state.nodes_budget >= 1.0)
        )
        g = _pick_template(tmpl_feas, templates)
        any_t = jnp.any(tmpl_feas) & (count > 0)

        # slice shape: per-host capacity f, hosts want = ceil(size / f)
        f0 = _claim_fill_caps(templates.daemon_requests, its0, requests, it, off_g)[g]
        f = jnp.where(self_conf, jnp.minimum(f0, 1), f0)
        f = jnp.where(any_t, jnp.maximum(f, 0), 0)
        want = jnp.where(f > 0, (count + f - 1) // jnp.maximum(f, 1), 0)
        avail_cap = jnp.maximum(NCAP - state.n_open, 0)
        budget_ok = state.nodes_budget[g] >= want.astype(jnp.float32)
        shaped = any_t & (f > 0)
        try_place = shaped & (want <= avail_cap) & budget_ok

        j = jnp.arange(maxg, dtype=i32)
        c_j = jnp.clip(count - j * f, 0, f)  # [MAXG] pods on host j
        used_j = (
            templates.daemon_requests[g][None, :]
            + c_j[:, None].astype(jnp.float32) * requests[None, :]
        )
        its0_g = its0[g]

        # ---- rank-block loop (ISSUE 20 rung 2) ---------------------------
        # The host places block j on a fresh hostname with counts FIXED
        # within the block (records land after the block's add loop), then
        # re-filters the budget-filtered candidate types against the
        # narrowed requirements at the block's pod count and charges the
        # budget per block over that remaining set. Any block failure
        # spills the whole gang (full rollback — the all-or-nothing select
        # below). One eval per block is exact: narrowing is idempotent and
        # every pod of a block is content-identical.
        if has_key:
            pd = xs.strict_mask[key_kid, :D]
            eval_candidates = _vg_eval(topo, gate, xs.vg_self, pd, D)
            admit = _kscan_admit(it, key_kid, D)
            grid_row = _cap_res_grid(
                templates.daemon_requests[g][None], requests, it
            )[0]  # [T, GR]
            if key_kid == zone_kid:
                offd = (
                    jnp.einsum(
                        "tgzc,c->ztg",
                        it.zc_avail.astype(jnp.bfloat16),
                        cmask[g, :C].astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32,
                    )
                    > 0
                )[:D]  # [D, T, GR]
            else:
                offd = jnp.broadcast_to(off_g[g][None], (D,) + off_g[g].shape)
            # [T, D] max pods addable per type IF the block lands in
            # domain d (same quantifier exchange as _kscan_capd, kept
            # per-type: the block's remaining set feeds bank_its and the
            # per-block budget debit)
            capTd = jnp.max(jnp.where(offd, grid_row[None], 0), axis=-1).T
            z0 = comb0.mask[g, key_kid, :D]
            win_zinf = comb0.inf[g, key_kid] & ~key_touched
        else:
            off_j = jnp.broadcast_to(off_g[g][None], (maxg,) + off_g.shape[1:])
            fits_legacy = jnp.any(
                _fits_off_counted(
                    jnp.broadcast_to(
                        templates.daemon_requests[g][None, :], (maxg, R)
                    ),
                    jnp.broadcast_to(c_j[:, None, None], off_j.shape),
                    requests,
                    it,
                    off_j,
                ),
                axis=-1,
            )  # [MAXG, T]

        def block(jj, c):
            active = try_place & (jj < want)
            cj = jnp.clip(count - jj * f, 0, f)
            slot = (E + state.n_open + jj).astype(i32)
            hg_ok = topo_ops.hg_evaluate(
                topo, c["hgc"], slot[None], xs.hg_applies, xs.hg_self
            )[0]
            if has_key:
                feas, newz = eval_candidates(z0[None], c["cnt"])
                zj = newz[0]
                compat_j = jnp.where(
                    key_touched, jnp.any(zj[None, :] & admit, axis=-1), True
                )
                fits_j = jnp.any(zj[None, :] & (capTd >= cj), axis=-1)
                its_j = its0_g & compat_j & fits_j
                vg_ok = feas[0]
            else:
                zj = jnp.zeros((D,), dtype=bool)
                its_j = its0_g & fits_legacy[jj]
                vg_ok = jnp.bool_(True)
            blk_ok = vg_ok & hg_ok & jnp.any(its_j)
            commit = active & blk_ok & c["ok"]
            # records land AFTER the block's add loop: each of the cj
            # content-identical pods records once against fixed counts
            if has_key:
                single = jnp.sum(zj) == 1
                do = recs & ~win_zinf & (is_anti | single)
                delta = (do[:, None] & zj[None, :]).astype(i32) * cj
                cnt2 = jnp.where(commit, c["cnt"] + delta, c["cnt"])
            else:
                cnt2 = c["cnt"]
            hgc2 = jnp.where(
                commit,
                c["hgc"].at[:, slot].add(
                    jnp.where(rec_h, cj, 0).astype(c["hgc"].dtype)
                ),
                c["hgc"],
            )
            # per-block budget debit over the block's REMAINING types
            # (subtractMax per opened claim — scheduler.go:791)
            max_cap_j = jnp.max(
                jnp.where(its_j[:, None], it.cap, -jnp.inf), axis=0
            )
            max_cap_j = jnp.where(jnp.isfinite(max_cap_j), max_cap_j, 0.0)
            return dict(
                cnt=cnt2,
                hgc=hgc2,
                ok=c["ok"] & (blk_ok | ~active),
                its_b=c["its_b"].at[jj].set(its_j),
                z_b=c["z_b"].at[jj].set(zj),
                debit=jnp.where(commit, c["debit"] + max_cap_j, c["debit"]),
            )

        carry0 = dict(
            cnt=state.vg_counts[:, :D],
            hgc=state.hg_counts,
            ok=jnp.bool_(True),
            its_b=jnp.zeros((maxg, T), dtype=bool),
            z_b=jnp.zeros((maxg, D), dtype=bool),
            debit=jnp.zeros((R,), dtype=jnp.float32),
        )
        carry = jax.lax.fori_loop(0, maxg, block, carry0)
        placed = try_place & carry["ok"]

        # NO_ROOM = axis-bound (the host escalates n_claims and re-solves);
        # GANG_SPILL = a constraint no escalation fixes (node budget, or a
        # rank block refused by topology/capacity under narrowing)
        status = jnp.where(
            shaped & ~budget_ok,
            i32(GANG_SPILL),
            jnp.where(
                try_place & ~carry["ok"],
                i32(GANG_SPILL),
                jnp.where(shaped, i32(NO_ROOM), i32(NO_CLAIM)),
            ),
        )

        # atomic commit: rank block j -> global claim id n_open + j,
        # written STRAIGHT into the frozen bank (dedicated + full); the
        # narrowed key row rides the bank_tk columns so decode folds the
        # block's domain into the claim requirements exactly like a
        # window-retired kscan claim
        active_rows = placed & (j < want)
        gid = jnp.where(active_rows, state.n_open + j, i32(NCAP))
        opened = jnp.where(placed, want, 0)
        wf = opened.astype(jnp.float32)
        bank_extra = {}
        if tk_idx >= 0:
            base_mask = comb0.mask[g, key_kid]  # [V]
            V = base_mask.shape[0]
            tk_rows = jnp.concatenate(
                [
                    carry["z_b"],
                    jnp.broadcast_to(base_mask[D:][None, :], (maxg, V - D)),
                ],
                axis=1,
            )
            def_bit = comb0.defined[g, key_kid] | key_touched
            bank_extra = dict(
                bank_tk_mask=state.bank_tk_mask.at[gid, tk_idx].set(
                    tk_rows, mode="drop"
                ),
                bank_tk_inf=state.bank_tk_inf.at[gid, tk_idx].set(
                    jnp.broadcast_to(win_zinf, (maxg,)), mode="drop"
                ),
                bank_tk_def=state.bank_tk_def.at[gid, tk_idx].set(
                    jnp.broadcast_to(def_bit, (maxg,)), mode="drop"
                ),
            )
        new_state = state._replace(
            bank_frozen=state.bank_frozen.at[gid].set(True, mode="drop"),
            bank_template=state.bank_template.at[gid].set(g.astype(i32), mode="drop"),
            bank_its=state.bank_its.at[gid].set(carry["its_b"], mode="drop"),
            bank_used=state.bank_used.at[gid].set(used_j, mode="drop"),
            n_open=state.n_open + opened,
            budget=state.budget.at[g].add(
                -jnp.where(placed, carry["debit"], 0.0)
            ),
            nodes_budget=state.nodes_budget.at[g].add(-wf),
            vg_counts=jnp.where(
                placed,
                state.vg_counts.at[:, :D].set(carry["cnt"]),
                state.vg_counts,
            ),
            hg_counts=jnp.where(placed, carry["hgc"], state.hg_counts),
            **bank_extra,
        )
        ys = GangYs(
            open_g=state.n_open,
            n_opened=opened,
            fill=f,
            tmpl=jnp.where(placed, g.astype(i32), i32(-1)),
            leftover=jnp.where(placed, 0, count).astype(i32),
            status=status,
        )
        return new_state, ys

    return step


_GANG_STATIC = (
    "zone_kid", "ct_kid", "n_claims", "maxg", "key_kid", "n_domains",
    "tk_idx",
)


@_wf_timed("solve_gang")
@named_kernel("solve_gang")
@functools.partial(jax.jit, static_argnames=_GANG_STATIC)
def solve_gang(
    state: SolverState,
    xs: KindXs,
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    maxg: int,
    key_kid: int = -1,
    n_domains: int = 1,
    tk_idx: int = -1,
) -> tuple[SolverState, GangYs]:
    """Scan gang-atomic slice placement over B gang segments (one segment
    per gang, pods in rank order), threading the same SolverState as the
    other dispatch kernels. `maxg` statically bounds hosts-per-slice
    (a gang of N pods never needs more than N hosts). `key_kid`/
    `n_domains` name the ONE narrow vocab key the gang kinds' vg groups
    share (-1 = no vg interaction — the scheduler host-routes gangs whose
    keys don't unify), and `tk_idx` is that key's row in the bank's
    topo_kids columns so committed blocks persist their narrowed domain
    for decode. Hostname-group (spread) interaction needs no static: the
    rank-block loop evaluates and commits hg counts at each block's fresh
    slot, scaled by the block's pod count."""
    step = _make_gang_step(
        exist, it, templates, well_known, topo, zone_kid, ct_kid, n_claims,
        maxg, key_kid, n_domains, tk_idx,
    )
    return jax.lax.scan(step, state, xs)


def _apply_topo(reqs: ReqSetTensors, upd: jnp.ndarray, touched: jnp.ndarray) -> ReqSetTensors:
    """AND the topology domain masks into candidate requirements: touched
    keys become concrete finite sets (requirements.Add of an In set)."""
    inf = reqs.inf & ~touched[None, :]
    return ReqSetTensors(
        mask=reqs.mask & upd,
        inf=inf,
        excl=reqs.excl & inf,
        gte=jnp.where(inf, reqs.gte, INT_MIN),
        lte=jnp.where(inf, reqs.lte, INT_MAX),
        defined=reqs.defined | touched[None, :],
    )


# ---------------------------------------------------------------------------
# Same-kind batched placement for vocab-key (zonal) topology kinds
# ---------------------------------------------------------------------------
# The per-pod scan pays O(N·K·V + N·T·K·V) PER POD; for a run of identical
# pods everything but the topology counts, per-claim narrowed domain sets,
# and capacities is invariant. The kind scan hoists the invariant work to
# one full-width precompute PER SEGMENT and replays the pod loop as a tiny
# inner scan over a compact [*, D] domain representation (D = the vocab
# width of the ONE topology key the kind interacts with — zones in
# practice). Decisions replicate the per-pod step exactly:
#   tier 1 earliest existing node, tier 2 fewest-pods/earliest-slot,
#   tier 3 first weight-ordered feasible template; spread narrows to the
#   single (min count, sorted-name rank) domain (topologygroup.go:229-298),
#   affinity to the compatible counted set or rank-min bootstrap
#   (:324-381), anti-affinity to zero-count domains (:404-440); count
#   commits only for single-valued/anti finite sets (topology.go:190-212).
# Routing preconditions (host-enforced in the scheduler): every vg group
# the kind applies to or records into shares ONE vocab key with <= KSCAN_D
# values, and the usual fill exclusions (minValues enforced, reservations,
# finite budgets) hold. Hostname groups need no exclusion — hg counts ride
# the inner carry exactly like the per-pod step.

KSCAN_D = 16  # max domain width a kind-scan key may have


class KindXs(NamedTuple):
    """Per-segment (pod kind) inputs to the kind scan."""

    reqs: ReqSetTensors  # [B, K, V]
    strict_mask: jnp.ndarray  # [B, K, V]
    requests: jnp.ndarray  # [B, R]
    tmpl_ok: jnp.ndarray  # [B, G]
    it_allow: jnp.ndarray  # [B, T]
    exist_ok: jnp.ndarray  # [B, E]
    ports: jnp.ndarray  # [B, NP]
    port_conf: jnp.ndarray  # [B, NP]
    vols: jnp.ndarray  # [B, NV]
    count: jnp.ndarray  # [B] i32 — pods of this kind (0 = padding row)
    vg_applies: jnp.ndarray  # [B, NGv]
    vg_records: jnp.ndarray  # [B, NGv]
    vg_self: jnp.ndarray  # [B, NGv]
    hg_applies: jnp.ndarray  # [B, NGh]
    hg_records: jnp.ndarray  # [B, NGh]
    hg_self: jnp.ndarray  # [B, NGh]


def _cap_res_grid(
    used: jnp.ndarray,  # [B, R]
    req: jnp.ndarray,  # [R]
    it: InstanceTypeTensors,
) -> jnp.ndarray:
    """[B, T, GR] i32 — max count per (type, allocatable-group) cell with
    used + c*req within alloc (same ±1-corrected estimate and total-based
    pass rule as _claim_fill_caps; viability/offering masks apply later)."""
    R = req.shape[0]
    pos = req > 0.0
    safe = jnp.where(pos, req, 1.0)
    est = jnp.full((used.shape[0],) + it.alloc.shape[:2], jnp.float32(COUNT_CAP))
    for r in range(R):
        head = it.alloc[None, :, :, r] - used[:, None, None, r]
        est = jnp.minimum(est, jnp.where(pos[r], head / safe[r], jnp.inf))
    c0 = jnp.clip(jnp.floor(est), 0.0, jnp.float32(COUNT_CAP)).astype(jnp.int32)

    def ok(c):
        acc = it.group_valid[None]
        cf = c.astype(jnp.float32)
        for r in range(R):
            t = used[:, None, None, r] + cf * req[r]
            acc = acc & ((t <= it.alloc[None, :, :, r]) | (t == 0.0))
        return acc

    up = ok(c0 + 1)
    mid = ok(c0)
    dn = ok(jnp.maximum(c0 - 1, 0))
    c = jnp.where(
        mid,
        jnp.where(up, c0 + 1, c0),
        jnp.where(dn, jnp.maximum(c0 - 1, 0), 0),
    )
    return jnp.where(it.group_valid[None], c, 0)


def _kscan_admit(it: InstanceTypeTensors, key_kid: int, D: int) -> jnp.ndarray:
    """[T, D] bool — the per-key intersects() term between each instance
    type's requirement at key_kid and the single-value set {d}: a finite
    single value makes the inf and both-lenient terms vacuous, leaving
    ~defined | mask-hit."""
    return ~it.reqs.defined[:, key_kid, None] | it.reqs.mask[:, key_kid, :D]


def _kscan_capd(
    grid: jnp.ndarray,  # [B, T, GR] i32 — resource caps
    viable: jnp.ndarray,  # [B, T] bool
    ct_mask: jnp.ndarray,  # [B, V]
    zmask_full: jnp.ndarray,  # [B, V] — zone mask (non-zone-key case)
    it: InstanceTypeTensors,
    key_kid: int,
    zone_kid: int,
    D: int,
) -> jnp.ndarray:
    """[B, D] i32 — max pods addable per candidate row IF placed in domain
    d of key_kid: max over (type, group) cells admitted by the domain with
    an available offering there. Quantifier exchange makes the per-domain
    max exactly the per-pod engine's any((fits & off), T) at each count."""
    C = it.zc_avail.shape[3]
    admit = _kscan_admit(it, key_kid, D)
    cols = []
    if key_kid == zone_kid:
        for d in range(D):
            off_d = (
                jnp.einsum(
                    "tgc,nc->ntg",
                    it.zc_avail[:, :, d, :].astype(jnp.bfloat16),
                    ct_mask[:, :C].astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                )
                > 0
            )
            m = viable[:, :, None] & admit[None, :, d, None] & off_d
            cols.append(jnp.max(jnp.where(m, grid, 0), axis=(1, 2)))
    else:
        Z = it.zc_avail.shape[2]
        off = (
            jnp.einsum(
                "tgzc,nz,nc->ntg",
                it.zc_avail.astype(jnp.bfloat16),
                zmask_full[:, :Z].astype(jnp.bfloat16),
                ct_mask[:, :C].astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            > 0
        )
        base = viable[:, :, None] & off
        for d in range(D):
            m = base & admit[None, :, d, None]
            cols.append(jnp.max(jnp.where(m, grid, 0), axis=(1, 2)))
    return jnp.stack(cols, axis=-1)


def _kscan_fits_final(
    grid: jnp.ndarray,  # [B, T, GR] i32
    placed: jnp.ndarray,  # [B] i32
    zset: jnp.ndarray,  # [B, D] bool — final narrowed domains
    ct_mask: jnp.ndarray,  # [B, V]
    zmask_full: jnp.ndarray,  # [B, V]
    it: InstanceTypeTensors,
    key_kid: int,
    zone_kid: int,
    D: int,
) -> jnp.ndarray:
    """[B, T] bool — fits_off at the final count within the final narrowed
    domains (the AND over every landing's fits_off: both terms are
    monotone, so the sequential conjunction equals the final check). The
    per-key it-compat effect of narrowing is NOT included — callers fold
    it via kernels.per_key_ok_at on the written-back requirements."""
    C = it.zc_avail.shape[3]
    Z = it.zc_avail.shape[2]
    fits = grid >= placed[:, None, None]
    if key_kid == zone_kid:
        # narrowing IS the zone mask: an un-narrowed complement row keeps
        # its all-true mask, so no special inf route is needed
        zm = zset[:, :Z] if Z <= D else jnp.pad(zset, ((0, 0), (0, Z - D)))
    else:
        zm = zmask_full[:, :Z]
    off = (
        jnp.einsum(
            "tgzc,nz,nc->ntg",
            it.zc_avail.astype(jnp.bfloat16),
            zm.astype(jnp.bfloat16),
            ct_mask[:, :C].astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        > 0
    )
    return jnp.any(fits & off, axis=-1)


def _vg_eval(topo: TopologyTensors, gate, selfs, pd, D: int):
    """Factory for the kscan/gang vocab-key topology evaluation: returns
    eval_candidates(zs [C, D], cnt [NGv, D]) -> (feasible [C], newz
    [C, D]) — vg_evaluate on the compact domain columns (exact: D covers
    every vocab value of the key). Shared verbatim by _make_kind_step's
    per-pod inner loop and _make_gang_step's per-rank-block loop (ISSUE
    20 rung 2) — the body reads only the apply gate, self-selection, and
    the pod's strict domain mask, so both callers evaluate identical
    narrowing."""
    dom = topo.vg_domains[:, :D]
    rank = topo.vg_rank[:, :D]
    skew = topo.vg_skew
    mind = topo.vg_min_domains
    in_universe = dom & pd[None, :]
    supported = jnp.sum(in_universe, axis=-1).astype(jnp.int32)
    self_add = selfs.astype(jnp.int32)

    def eval_candidates(zs, cnt):
        masked = jnp.where(in_universe, cnt, topo_ops.BIG_I32)
        minc = jnp.min(masked, axis=-1)
        minc = jnp.where((mind > 0) & (supported < mind), 0, minc)
        minc = jnp.where(minc == topo_ops.BIG_I32, 0, minc)
        eff = cnt + self_add[:, None]
        ok_skew = (eff - minc[:, None]) <= skew[:, None]
        opts = dom & pd[None, :] & (cnt > 0)
        group_empty = ~jnp.any(cnt > 0, axis=-1)
        no_compat = ~jnp.any(pd[None, :] & (cnt > 0), axis=-1)
        bootstrap = selfs & (group_empty | no_compat)
        cnt_zero = cnt == 0

        valid_sp = dom[None] & zs[:, None, :] & ok_skew[None]
        sp_key = jnp.where(
            valid_sp, eff[None] * topo_ops.RANK_BASE + rank[None], topo_ops.BIG_I32
        )
        sp_mask = topo_ops._onehot_rows(valid_sp, jnp.argmin(sp_key, axis=-1))
        any_sp = jnp.any(valid_sp, axis=-1)

        opts_c = opts[None] & zs[:, None, :]
        any_opts = jnp.any(opts_c, axis=-1, keepdims=True)
        boot_space = (dom & pd[None, :])[None] & zs[:, None, :]
        boot_idx = jnp.argmin(
            jnp.where(boot_space, rank[None], topo_ops.BIG_I32), axis=-1
        )
        boot_mask = topo_ops._onehot_rows(boot_space, boot_idx)
        aff_mask = jnp.where(
            any_opts, opts_c, boot_mask & bootstrap[None, :, None]
        )
        any_aff = jnp.any(aff_mask, axis=-1)

        anti_mask = boot_space & cnt_zero[None]
        any_anti = jnp.any(anti_mask, axis=-1)

        t = topo.vg_type[None, :]
        narrowed = jnp.where(
            (t == topo_ops.TYPE_SPREAD)[..., None],
            sp_mask,
            jnp.where((t == topo_ops.TYPE_AFFINITY)[..., None], aff_mask, anti_mask),
        )
        ok = jnp.where(
            t == topo_ops.TYPE_SPREAD,
            any_sp,
            jnp.where(t == topo_ops.TYPE_AFFINITY, any_aff, any_anti),
        )
        feasible = jnp.all(~gate[None, :] | ok, axis=-1)
        upd = jnp.all(~gate[None, :, None] | narrowed, axis=1)  # [C, D]
        return feasible, zs & upd

    return eval_candidates


def _make_kind_step(
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    key_kid: int,
    D: int,
    maxc: int,
    grid_incremental: bool = True,
    annotate: bool = True,
):
    NCAP = n_claims
    E = exist.avail.shape[0]
    G = templates.its.shape[0]
    no_wk = jnp.zeros_like(well_known)
    i32 = jnp.int32
    _hint = shard_hint if annotate else (lambda x, *a: x)

    def seg_step(carry, xs: KindXs):
        state, grid_prev, grid_req, grid_valid = carry
        W = state.open.shape[0]
        count = xs.count
        requests = xs.requests
        self_conf = kernels.packed_conflict(xs.ports, xs.port_conf)
        pd = xs.strict_mask[key_kid, :D]  # [D] pod strict domains
        key_touched = jnp.any(xs.vg_applies & topo.vg_valid)

        # ---- per-segment invariants (one full-width pass) -----------------
        # tier 2: claims (the active window)
        pod_b = _broadcast_pod(xs.reqs, W)
        comb = kernels.intersect_sets(state.reqs, pod_b)
        claim_ok = kernels.compatible_elemwise(state.reqs, pod_b, well_known)
        it_compat = kernels.intersects(it.reqs, comb).T  # [W, T]
        viable0 = _hint(state.its & it_compat & xs.it_allow[None, :], "dp", "it")
        tol = xs.tmpl_ok[state.template]
        ports_ok_n = ~kernels.packed_conflict(xs.port_conf[None, :], state.claim_ports)
        static_n0 = claim_ok & tol & ports_ok_n
        ct_n = comb.mask[:, ct_kid, :]
        zfull_n = comb.mask[:, zone_kid, :]
        # ---- incremental capacity grid (STATUS Known-gaps lever) ----------
        # The [W, T, GR] grid depends only on (state.used, requests). When
        # consecutive segments carry bit-identical request vectors the
        # previous segment's boundary-adjusted grid (each landed row's
        # cells already debited by its pod count, fresh rows seeded from
        # the template grid) IS this segment's grid, so the full-width
        # divide-and-verify recompute is skipped via lax.cond. The debit
        # convention (cap' = cap - landed) is exact whenever quantities
        # are f32-product-exact — the same caveat the batch-placement
        # multiply-add convention already carries (module comment); the
        # kind scan already compares grid-at-segment-start against landed
        # counters within a segment, so this extends an existing
        # convention across same-request boundaries, not a new one.
        grid_reused = grid_valid & jnp.all(requests == grid_req)
        if not grid_incremental:
            # guard quarantine / shadow-audit exact twin: force the
            # full-width divide-and-verify recompute at every boundary
            grid_reused = jnp.bool_(False)
        grid_n = _hint(
            jax.lax.cond(
                grid_reused,
                lambda: grid_prev,
                lambda: _cap_res_grid(state.used, requests, it),
            ),
            "dp",
            "it",
        )  # [W, T, GR]
        capd_n0 = _kscan_capd(
            grid_n, viable0, ct_n, zfull_n, it, key_kid, zone_kid, D
        )

        # tier 1: existing nodes
        pod_e = _broadcast_pod(xs.reqs, E)
        comb_e = kernels.intersect_sets(state.exist_reqs, pod_e)
        compat_e = kernels.compatible_elemwise(state.exist_reqs, pod_e, no_wk)
        ports_ok_e = ~kernels.packed_conflict(xs.port_conf[None, :], state.exist_ports)
        newv_e = state.exist_vols | xs.vols[None, :]
        vcount_e = kernels.packed_count_and(
            newv_e[:, None, :], exist.vol_driver[None, :, :]
        ).astype(jnp.float32)
        vols_ok_e = jnp.all(vcount_e <= exist.vol_limits, axis=-1) | ~kernels.packed_any(xs.vols)
        cap_e = _count_cap_seq(state.exist_used, requests[None, :], exist.avail)
        static_e = exist.valid & xs.exist_ok & compat_e & ports_ok_e & vols_ok_e
        cap_e = jnp.where(static_e, cap_e, 0)
        cap_e = jnp.where(self_conf, jnp.minimum(cap_e, 1), cap_e)

        # tier 3: fresh templates
        pod_g = _broadcast_pod(xs.reqs, G)
        comb0 = kernels.intersect_sets(templates.reqs, pod_g)
        tmpl_compat = kernels.compatible_elemwise(templates.reqs, pod_g, well_known)
        it_compat0 = kernels.intersects(it.reqs, comb0).T  # [G, T]
        its0 = templates.its & it_compat0 & xs.it_allow[None, :]
        static_g = templates.valid & tmpl_compat & xs.tmpl_ok
        ct_g = comb0.mask[:, ct_kid, :]
        zfull_g = comb0.mask[:, zone_kid, :]
        grid_g = _cap_res_grid(templates.daemon_requests, requests, it)
        capd_g = _kscan_capd(
            grid_g, its0, ct_g, zfull_g, it, key_kid, zone_kid, D
        )
        capd_g = jnp.where(self_conf, jnp.minimum(capd_g, 1), capd_g)
        z0_g = comb0.mask[:, key_kid, :D]
        zinf_g = comb0.inf[:, key_kid]

        # vg group geometry for THIS kind (every gated group shares
        # key_kid); the evaluation body lives in _vg_eval — shared with
        # the gang rank-block loop
        gate = xs.vg_applies & topo.vg_valid  # [NGv]
        recs = xs.vg_records & topo.vg_valid
        is_anti = topo.vg_type == topo_ops.TYPE_ANTI
        eval_candidates = _vg_eval(topo, gate, xs.vg_self, pd, D)

        # carry only what a landing actually mutates; everything else is
        # derivable from (pl_n, n_open) against segment-start state — the
        # while-loop body's HLO count is the inner-loop cost driver:
        #   zinf: collapses to comb.inf & ~key_touched on ANY landing, so
        #     the winner's post-commit value never needs per-slot state
        #   open/static/tol for fresh slots: true exactly on
        #     [n_open0, n_open) (tier 3 opens contiguously)
        #   total pods: state.pods + pl_n
        zin0 = comb.inf[:, key_kid]
        zie0 = comb_e.inf[:, key_kid]
        w_open0 = state.w_open
        arange_n = jnp.arange(W, dtype=i32)
        carry0 = dict(
            zn=comb.mask[:, key_kid, :D],
            ze=comb_e.mask[:, key_kid, :D],
            capd=capd_n0,
            pl_n=jnp.zeros(W, dtype=i32),
            pl_e=jnp.zeros(E, dtype=i32),
            tmpl_n=state.template,
            cnt=state.vg_counts[:, :D],
            hgc=state.hg_counts,
            n_open=state.n_open,
            w_open=state.w_open,
            slot_of=state.slot_of,
            spills=state.spills,
        )

        def pod_step(c, i):
            valid = i < count
            # ONE fused topology/hg evaluation over every candidate tier —
            # the inner loop runs per pod, so HLO count per iteration is
            # the cost driver
            zs_all = jnp.concatenate([c["ze"], c["zn"], z0_g], axis=0)
            f_topo, newz = eval_candidates(zs_all, c["cnt"])
            slots_all = jnp.concatenate(
                [
                    jnp.arange(E, dtype=i32),
                    E + c["slot_of"],
                    jnp.broadcast_to(E + c["n_open"], (G,)).astype(i32),
                ]
            )
            hg_ok = topo_ops.hg_evaluate(
                topo, c["hgc"], slots_all, xs.hg_applies, xs.hg_self
            )

            # tier 1: earliest feasible existing node
            feas_e = (c["pl_e"] < cap_e) & f_topo[:E] & hg_ok[:E] & valid
            pick_e = jnp.argmin(jnp.where(feas_e, jnp.arange(E, dtype=i32), BIG))
            found_e = jnp.any(feas_e)
            newz_e = newz[:E]

            # tier 2: fewest pods, earliest slot (window order = open order)
            newz_n = newz[E : E + W]
            lim_n = jnp.where(self_conf, jnp.minimum(c["capd"], 1), c["capd"])
            fits_n = jnp.any(newz_n & (lim_n > c["pl_n"][:, None]), axis=-1)
            fresh_here = (arange_n >= w_open0) & (arange_n < c["w_open"])
            open_n = state.open | fresh_here
            stat_n = static_n0 | fresh_here
            feas_n = (
                open_n & stat_n & f_topo[E : E + W] & fits_n
                & hg_ok[E : E + W] & valid & ~found_e
            )
            order = (state.pods + c["pl_n"]) * i32(W) + arange_n
            pick = jnp.argmin(jnp.where(feas_n, order, BIG))
            found = jnp.any(feas_n)

            # tier 3: first weight-ordered feasible template
            newz_g = newz[E + W :]
            fits_g = jnp.any(newz_g & (capd_g >= 1), axis=-1)
            tmpl_feas = static_g & f_topo[E + W :] & fits_g & hg_ok[E + W :]
            g = _pick_template(tmpl_feas, templates)
            any_t = jnp.any(tmpl_feas) & valid & ~found_e & ~found
            can_open = any_t & (c["w_open"] < W) & (c["n_open"] < NCAP)
            spilled = any_t & ~can_open & (c["n_open"] < NCAP)

            place = found_e | found | can_open
            cslot = jnp.where(found, pick, c["w_open"])
            gslot = jnp.where(found, c["slot_of"][pick], c["n_open"])
            slot = jnp.where(found_e, pick_e, E + gslot)
            assignment = jnp.where(
                place,
                slot.astype(i32),
                jnp.where(any_t, i32(NO_ROOM), i32(NO_CLAIM)),
            )

            # winner's narrowed set + commits
            win_z = jnp.where(
                found_e,
                newz_e[pick_e],
                jnp.where(found, newz_n[pick], newz_g[g]),
            )
            win_zinf_old = jnp.where(
                found_e,
                zie0[pick_e],
                jnp.where(found, zin0[pick], zinf_g[g]),
            )
            win_zinf = win_zinf_old & ~key_touched
            single = jnp.sum(win_z) == 1
            do = recs & ~win_zinf & (is_anti | single)
            delta = (do[:, None] & win_z[None, :]).astype(i32)
            cnt2 = jnp.where(place, c["cnt"] + delta, c["cnt"])
            slot_h = jnp.where(found_e, pick_e, E + gslot).astype(i32)
            hgc2 = jnp.where(
                place,
                topo_ops.hg_commit(c["hgc"], slot_h, xs.hg_records, topo.hg_valid),
                c["hgc"],
            )

            upd_claim = (found | can_open) & ~found_e
            opened = can_open & ~found
            zn2 = jnp.where(
                upd_claim, c["zn"].at[cslot].set(win_z), c["zn"]
            )
            ze2 = jnp.where(
                found_e, c["ze"].at[pick_e].set(win_z), c["ze"]
            )
            capd2 = jnp.where(
                opened, c["capd"].at[cslot].set(capd_g[g]), c["capd"]
            )
            pl_n2 = jnp.where(upd_claim, c["pl_n"].at[cslot].add(1), c["pl_n"])
            pl_e2 = jnp.where(found_e, c["pl_e"].at[pick_e].add(1), c["pl_e"])
            tmpl2 = jnp.where(
                opened, c["tmpl_n"].at[cslot].set(g.astype(i32)), c["tmpl_n"]
            )
            opened_i = jnp.where(opened, 1, 0).astype(i32)
            slot_of2 = jnp.where(
                opened, c["slot_of"].at[cslot].set(c["n_open"]), c["slot_of"]
            )

            return (
                dict(
                    zn=zn2, ze=ze2, capd=capd2,
                    pl_n=pl_n2, pl_e=pl_e2,
                    tmpl_n=tmpl2, cnt=cnt2, hgc=hgc2,
                    n_open=c["n_open"] + opened_i,
                    w_open=c["w_open"] + opened_i,
                    slot_of=slot_of2,
                    spills=c["spills"] + jnp.where(spilled, 1, 0).astype(i32),
                ),
                assignment,
            )

        # dynamic trip count: segments rarely fill the maxc bucket, and
        # padded iterations are pure waste at one pod per step
        assignment0 = jnp.full(maxc, i32(NO_CLAIM))

        def while_cond(loop):
            i, _c, _a = loop
            return i < count

        def while_body(loop):
            i, c, assign = loop
            c2, a = pod_step(c, i)
            return i + 1, c2, assign.at[i].set(a)

        _, carry, assignment = jax.lax.while_loop(
            while_cond, while_body, (i32(0), carry0, assignment0)
        )

        # ---- segment-end writeback into the full SolverState --------------
        pl_n = carry["pl_n"]
        pl_e = carry["pl_e"]
        landed_n = pl_n > 0
        landed_e = pl_e > 0
        opened_here = landed_n & ~state.open
        tmpl_n = carry["tmpl_n"]
        zset_f = carry["zn"]
        zinf_f = zin0 & ~(key_touched & landed_n)

        # usage: one multiply-add per (segment, candidate) — the batch
        # placement convention (see the fill kernel's module comment)
        base_used = jnp.where(
            opened_here[:, None], templates.daemon_requests[tmpl_n], state.used
        )
        new_used = jnp.where(
            landed_n[:, None],
            base_used + pl_n[:, None].astype(jnp.float32) * requests[None, :],
            state.used,
        )
        new_exist_used = (
            state.exist_used
            + pl_e[:, None].astype(jnp.float32) * requests[None, :]
        )

        # requirements: claim ∩ pod (template ∩ pod for fresh claims) with
        # the key row narrowed to the carried domain set (_apply_topo
        # semantics: touched keys become finite In sets)
        base_reqs = kernels.select_set(
            opened_here, kernels.take_set(comb0, tmpl_n), comb
        )
        km = jnp.zeros_like(base_reqs.mask[:, key_kid, :])
        km = km.at[:, :D].set(zset_f)
        km = km | (
            base_reqs.mask[:, key_kid, :]
            & jnp.concatenate(
                [jnp.zeros((W, D), dtype=bool),
                 jnp.ones((W, km.shape[1] - D), dtype=bool)],
                axis=1,
            )
        )
        narrowed_mark = landed_n & key_touched
        new_mask = base_reqs.mask.at[:, key_kid, :].set(km)
        new_inf_k = jnp.where(landed_n, zinf_f, base_reqs.inf[:, key_kid])
        new_inf = base_reqs.inf.at[:, key_kid].set(new_inf_k)
        new_def = base_reqs.defined.at[:, key_kid].set(
            base_reqs.defined[:, key_kid] | narrowed_mark
        )
        new_gte = base_reqs.gte.at[:, key_kid].set(
            jnp.where(new_inf_k, base_reqs.gte[:, key_kid], INT_MIN)
        )
        new_lte = base_reqs.lte.at[:, key_kid].set(
            jnp.where(new_inf_k, base_reqs.lte[:, key_kid], INT_MAX)
        )
        final_reqs = ReqSetTensors(
            mask=new_mask, inf=new_inf, excl=base_reqs.excl.at[:, key_kid].set(
                base_reqs.excl[:, key_kid] & new_inf_k
            ),
            gte=new_gte, lte=new_lte, defined=new_def,
        )
        new_reqs = kernels.select_set(landed_n, final_reqs, state.reqs)

        # viable instance types at the final count within the final domains
        viable_base = kernels_select_bool(
            opened_here, its0[tmpl_n], viable0
        )
        ok_key = kernels.per_key_ok_at(it.reqs, final_reqs, key_kid)  # [N, T]
        grid_final = jnp.where(
            opened_here[:, None, None], grid_g[tmpl_n], grid_n
        )
        ct_final = jnp.where(opened_here[:, None], ct_g[tmpl_n], ct_n)
        zf_final = jnp.where(opened_here[:, None], zfull_g[tmpl_n], zfull_n)
        fits_f = _kscan_fits_final(
            grid_final, pl_n, zset_f, ct_final, zf_final, it,
            key_kid, zone_kid, D,
        )
        new_its = jnp.where(
            landed_n[:, None], viable_base & ok_key & fits_f, state.its
        )

        new_ports = jnp.where(
            landed_n[:, None], state.claim_ports | xs.ports[None, :], state.claim_ports
        )
        new_eports = jnp.where(
            landed_e[:, None], state.exist_ports | xs.ports[None, :], state.exist_ports
        )
        new_evols = jnp.where(
            landed_e[:, None], state.exist_vols | xs.vols[None, :], state.exist_vols
        )

        # existing-node requirements writeback (same key-row treatment)
        ekm = jnp.zeros_like(comb_e.mask[:, key_kid, :])
        ekm = ekm.at[:, :D].set(carry["ze"])
        ekm = ekm | (
            comb_e.mask[:, key_kid, :]
            & jnp.concatenate(
                [jnp.zeros((E, D), dtype=bool),
                 jnp.ones((E, ekm.shape[1] - D), dtype=bool)],
                axis=1,
            )
        )
        e_inf_k = zie0 & ~(key_touched & landed_e)
        e_marked = landed_e & key_touched
        final_ereqs = ReqSetTensors(
            mask=comb_e.mask.at[:, key_kid, :].set(ekm),
            inf=comb_e.inf.at[:, key_kid].set(e_inf_k),
            excl=comb_e.excl.at[:, key_kid].set(
                comb_e.excl[:, key_kid] & e_inf_k
            ),
            gte=comb_e.gte.at[:, key_kid].set(
                jnp.where(e_inf_k, comb_e.gte[:, key_kid], INT_MIN)
            ),
            lte=comb_e.lte.at[:, key_kid].set(
                jnp.where(e_inf_k, comb_e.lte[:, key_kid], INT_MAX)
            ),
            defined=comb_e.defined.at[:, key_kid].set(
                comb_e.defined[:, key_kid] | e_marked
            ),
        )
        new_ereqs = kernels.select_set(landed_e, final_ereqs, state.exist_reqs)

        new_vg = state.vg_counts.at[:, :D].set(carry["cnt"])

        # boundary grid update: debit landed rows by their pod counts
        # (fresh rows re-base on the template grid) instead of recomputing
        # the full [W, T, GR] divide-and-verify next segment when the
        # request vector repeats
        grid_base = jnp.where(
            opened_here[:, None, None], grid_g[tmpl_n], grid_n
        )
        grid_next = jnp.where(
            landed_n[:, None, None],
            jnp.maximum(grid_base - pl_n[:, None, None], 0),
            grid_n,
        )

        ys = KindYs(
            assignment=assignment.astype(jnp.int32),
            grid_reused=grid_reused,
        )
        return (
            (state._replace(
                exist_reqs=new_ereqs,
                exist_used=new_exist_used,
                reqs=new_reqs,
                used=new_used,
                its=new_its,
                template=jnp.where(opened_here, tmpl_n, state.template),
                open=state.open
                | ((arange_n >= w_open0) & (arange_n < carry["w_open"])),
                pods=state.pods + pl_n,
                n_open=carry["n_open"],
                slot_of=carry["slot_of"],
                w_open=carry["w_open"],
                w_hw=jnp.maximum(state.w_hw, carry["w_open"]),
                spills=carry["spills"],
                vg_counts=new_vg,
                hg_counts=carry["hgc"],
                exist_ports=new_eports,
                claim_ports=new_ports,
                exist_vols=new_evols,
            ), grid_next, requests, jnp.bool_(True)),
            ys,
        )

    return seg_step


class KindYs(NamedTuple):
    """Per-segment kind-scan record: each pod's chosen slot in E-space
    (existing < E, claims E+slot) or NO_ROOM / NO_CLAIM."""

    assignment: jnp.ndarray  # [MAXC] i32
    # whether this segment reused the previous segment's boundary-adjusted
    # capacity grid instead of the full-width recompute (metrics:
    # ktpu_kscan_grid_updates_total{mode})
    grid_reused: jnp.ndarray  # [] bool


def kernels_select_bool(cond, a, b):
    """jnp.where over a [N]-cond against [N, T] operands."""
    return jnp.where(cond[:, None], a, b)


_KSCAN_STATIC = (
    "zone_kid", "ct_kid", "n_claims", "key_kid", "n_domains", "maxc",
    "grid_incremental",
)


@_wf_timed("solve_kind_scan")
@named_kernel("solve_kind_scan")
@functools.partial(jax.jit, static_argnames=_KSCAN_STATIC)
def solve_kind_scan(
    state: SolverState,
    xs: KindXs,
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    key_kid: int,
    n_domains: int,
    maxc: int,
    grid_incremental: bool = True,
) -> tuple[SolverState, KindYs]:
    """Scan same-kind batched placement for vocab-key topology kinds over B
    segments, threading the same SolverState as the fill and per-pod scans
    (the host interleaves all three dispatches freely). The scan carry
    additionally threads the boundary-adjusted [W, T, GR] capacity grid so
    same-request segments skip the full-width recompute (grid_valid starts
    False: the first segment always computes fresh)."""
    step = _make_kind_step(
        exist, it, templates, well_known, topo, zone_kid, ct_kid,
        n_claims, key_kid, n_domains, maxc, grid_incremental,
    )
    W = state.open.shape[0]
    T, GR, R = it.alloc.shape
    carry0 = (
        state,
        shard_hint(jnp.zeros((W, T, GR), dtype=jnp.int32), "dp", "it"),
        jnp.zeros((R,), dtype=jnp.float32),
        jnp.bool_(False),
    )
    (state, _grid, _req, _valid), ys = jax.lax.scan(step, carry0, xs)
    return state, ys


# ---------------------------------------------------------------------------
# dp-sharded speculative kscan (ISSUE 13 rung 2): zonal-spread kinds join
# the speculative dp fan-out under a per-domain deadness predicate
# ---------------------------------------------------------------------------
#
# The kscan engine's only tier-2 gate on a pre-existing claim row is its
# per-domain capacity ceiling: lim = max over admitted (type, group)
# cells of the incremental [W, T, GR] grid, per domain of the kind's
# vocab key (_kscan_capd). The grid count is monotone DECREASING in both
# the request vector and the row's used vector, and _kscan_capd's max
# only shrinks under tighter viability/offering masks — so evaluating
# capd with the GROUP's elementwise-min request over SUPERSET masks
# (the row's raw its viability, all-true capacity-type and zone masks)
# upper-bounds every real candidate evaluation any pod of the group
# would see. All-domain capd == 0 under that bound proves the row can
# accept no pod of the group: the kscan deadness predicate, playing
# exactly window_live_dead's role for segment-scan groups.
#
# Exactness of the graft additionally needs the groups' topology count
# state to be independent: row r may only commit when no earlier row q
# RECORDS into a vocab-key or hostname group that r APPLIES (gated by
# vg_valid/hg_valid) — the count reads r's evaluation depends on are
# then bitwise-unchanged by q's commit. Recorded deltas still merge:
# vg counts add (deltas are order-free sums), hg counts shift their
# fresh-claim columns by the claim-id delta — the same id isomorphism
# the window graft applies to slot_of. Anything else (existing nodes,
# reservations, budgets) is excluded by the kscan routing preconditions
# plus the dp eligibility gate (scheduler._run_solve_inner).


class ShardKscanState(NamedTuple):
    """The window-row slice + counters + topology counts + existing-node
    debit state of one speculative per-shard kscan OR per-pod solve
    (solve_perpod_dp reuses this slice and merge_shard_kscan wholesale).
    Bank state is unchanged by construction on the dp-routable classes,
    so it never crosses the merge. Budget and reservation state DO ride
    the slice (ISSUE 20 rung 1): per-pod rows may debit pool budgets and
    consume reservation capacity, and the verdict's budget/reservation
    disjointness bits prove the per-row deltas merge order-free (kscan
    rows leave them at the base by routing, so their deltas are zero)."""

    reqs: ReqSetTensors  # [W, K, V]
    used: jnp.ndarray  # [W, R]
    its: jnp.ndarray  # [W, T]
    template: jnp.ndarray  # [W]
    open: jnp.ndarray  # [W]
    pods: jnp.ndarray  # [W]
    slot_of: jnp.ndarray  # [W]
    claim_ports: jnp.ndarray  # [W, NPp]
    held: jnp.ndarray  # [W, RID]
    n_open: jnp.ndarray  # [] i32
    w_open: jnp.ndarray  # [] i32
    spills: jnp.ndarray  # [] i32
    vg_counts: jnp.ndarray  # [NGv, V]
    hg_counts: jnp.ndarray  # [NGh, E + NCAP + 1]
    exist_reqs: ReqSetTensors  # [E, K, V]
    exist_used: jnp.ndarray  # [E, R]
    exist_ports: jnp.ndarray  # [E, NPp]
    exist_vols: jnp.ndarray  # [E, NVp]
    budget: jnp.ndarray  # [G, R] f32 (+inf = unlimited)
    nodes_budget: jnp.ndarray  # [G] f32
    res_cap: jnp.ndarray  # [RID] i32


def _shard_kscan_slice(st: SolverState) -> ShardKscanState:
    """The spec-state slice shared by solve_kscan_dp and solve_perpod_dp."""
    return ShardKscanState(
        reqs=st.reqs, used=st.used, its=st.its, template=st.template,
        open=st.open, pods=st.pods, slot_of=st.slot_of,
        claim_ports=st.claim_ports, held=st.held, n_open=st.n_open,
        w_open=st.w_open, spills=st.spills, vg_counts=st.vg_counts,
        hg_counts=st.hg_counts, exist_reqs=st.exist_reqs,
        exist_used=st.exist_used, exist_ports=st.exist_ports,
        exist_vols=st.exist_vols, budget=st.budget,
        nodes_budget=st.nodes_budget, res_cap=st.res_cap,
    )


def _budget_res_conflict(state, spec, apply_tmpl):
    """[q, r] bool — budget/reservation admission conflicts between dp
    rows (ISSUE 20 rung 1). Row q TOUCHES template g's budget when any
    budget or node-count delta vs the round base is nonzero (an infinite
    budget minus a finite debit stays +inf, so touch is automatically
    restricted to finite-budget templates); row r APPLIES g's budget when
    any of its live pods may consider g (`apply_tmpl[r, g]` — the
    per-pod step reads state.budget/nodes_budget only through templates
    that pass the pod's tmpl_ok gate). Reservations get one conservative
    bit: a row with any res_cap delta blocks every later row the moment
    reservations are active — held-row deltas ride the window graft and
    the pods-touched bit, so res_cap is the only cross-row register."""
    touch_b = jnp.any(spec.budget != state.budget[None], axis=-1) | (
        spec.nodes_budget != state.nodes_budget[None]
    )  # [DP, G]
    conflict = jnp.any(
        touch_b[:, None, :] & apply_tmpl[None, :, :], axis=-1
    )  # [q, r]
    touch_res = jnp.any(spec.res_cap != state.res_cap[None], axis=-1)  # [DP]
    conflict = conflict | touch_res[:, None]
    return conflict


def _kscan_rows_dead(used, its, open_mask, it, r_min, key_kid, zone_kid, D):
    """[] bool — TRUE when every live open row is per-domain capacity-dead
    w.r.t. r_min: the incremental-grid count at (used, r_min) yields
    capd == 0 in EVERY domain of the kind's vocab key over superset
    viability/offering masks. Monotone in the request, so TRUE for a
    group's elementwise-min request proves no pod of the group passes the
    kscan tier-2 fits gate (lim > placed needs lim >= 1) on that row."""
    W = used.shape[0]
    Z = it.zc_avail.shape[2]
    C = it.zc_avail.shape[3]
    grid = _cap_res_grid(used, r_min, it)
    capd = _kscan_capd(
        grid,
        its,
        jnp.ones((W, C), dtype=bool),
        jnp.ones((W, Z), dtype=bool),
        it,
        key_kid,
        zone_kid,
        D,
    )
    return ~jnp.any(open_mask & jnp.any(capd > 0, axis=-1))


@_wf_timed("solve_kscan_dp")
@named_kernel("solve_kscan_dp")
@functools.partial(jax.jit, static_argnames=_KSCAN_STATIC)
def solve_kscan_dp(
    state: SolverState,
    xs_b: KindXs,  # leading [DP] group axis on every tensor
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    key_kid: int,
    n_domains: int,
    maxc: int,
    grid_incremental: bool = True,
) -> tuple[ShardKscanState, KindYs, jnp.ndarray]:
    """Speculative dp fan-out for vocab-key (kscan) kinds: every dp row
    scans ITS chunk group of segments against the same base state, with
    the same packed commit-verdict contract as solve_fill_dp — deadness
    here is the per-domain grid predicate (_kscan_rows_dead) plus vg/hg
    record-vs-apply disjointness between rows. The grid carry starts
    fresh per row (grid_valid False), so chunked groups trade some
    cross-boundary grid reuse for the dp fan-out."""
    step = _make_kind_step(
        exist, it, templates, well_known, topo, zone_kid, ct_kid,
        n_claims, key_kid, n_domains, maxc, grid_incremental,
        annotate=False,
    )
    W = state.open.shape[0]
    T, GR, R = it.alloc.shape

    def one(xs: KindXs):
        carry0 = (
            state,
            jnp.zeros((W, T, GR), dtype=jnp.int32),
            jnp.zeros((R,), dtype=jnp.float32),
            jnp.bool_(False),
        )
        (st, _grid, _req, _valid), ys = jax.lax.scan(step, carry0, xs)
        return _shard_kscan_slice(st), ys

    allow = xs_b.it_allow
    xs_b = jax.tree_util.tree_map(
        lambda a: a if a is allow else shard_hint(a, "dp"), xs_b
    )
    xs_b = xs_b._replace(it_allow=shard_hint(allow, "dp", None, "it"))
    spec, ys = jax.vmap(one)(xs_b)

    W_rows = jnp.arange(W, dtype=jnp.int32)
    touched = jnp.any(
        (spec.pods > state.pods[None, :]) & (W_rows < state.w_open)[None, :],
        axis=-1,
    )
    # record-vs-apply disjointness over each row's LIVE segments: q < r
    # recording into a group r applies would change counts r's evaluation
    # read — never commit r past such a q
    live = (xs_b.count > 0)[:, :, None]
    app_v = jnp.any(live & xs_b.vg_applies, axis=1) & topo.vg_valid[None]
    rec_v = jnp.any(live & xs_b.vg_records, axis=1) & topo.vg_valid[None]
    app_h = jnp.any(live & xs_b.hg_applies, axis=1) & topo.hg_valid[None]
    rec_h = jnp.any(live & xs_b.hg_records, axis=1) & topo.hg_valid[None]
    conflict = (
        jnp.any(rec_v[:, None, :] & app_v[None, :, :], axis=-1)
        | jnp.any(rec_h[:, None, :] & app_h[None, :, :], axis=-1)
    )  # [q, r]
    topo_ok = kernels.pairwise_commit_ok(conflict)
    r_min = _dp_group_r_min(xs_b.count, xs_b.requests)
    exist_ok_rows = jnp.any(live & xs_b.exist_ok, axis=1)
    exist_bit = _exist_conflict_ok(state, spec, exist, exist_ok_rows, r_min)
    verdict = _dp_verdict_word(
        state, spec, r_min, n_claims,
        lambda u, iv, om, rm: _kscan_rows_dead(
            u, iv, om, it, rm, key_kid, zone_kid, n_domains
        ),
        touched=touched,
        extra_ok=topo_ok & exist_bit,
    )
    return spec, ys, verdict


@_wf_timed("merge_shard_kscan")
@jax.jit
def merge_shard_kscan(
    committed: SolverState,
    spec: ShardKscanState,
    assignment: jnp.ndarray,  # [B, MAXC] / [L] i32 — the row's slots
    base: SolverState,  # the ROUND base every row speculated from
) -> tuple[SolverState, jnp.ndarray, jnp.ndarray]:
    """Graft a committed speculative kscan (or per-pod) group: the shared
    window graft plus the topology count merge — vg deltas add
    (order-free sums over disjoint-by-verdict groups), hg deltas add in
    place on the existing-node columns [0, E) and shift their
    fresh-claim columns by the claim-id delta before adding
    (_merge_hg_delta) — plus the existing-node debit graft
    (_graft_exist_fields, whole-field per touched node), plus the
    budget/reservation debit deltas (ISSUE 20 rung 1): finite budgets
    add the row's (spec - base) debit, infinite budgets stay +inf (the
    isfinite guard keeps inf - inf from poisoning the sum), and res_cap
    adds the plain i32 delta. The verdict's budget/reservation
    disjointness bits make these sums order-free; kscan-routed rows
    leave all three at the base so their deltas vanish. The group's
    assignment slots >= E + base.n_open re-base by the claim-id delta;
    existing-node assignments (< E) and the NO_ROOM/NO_CLAIM sentinels
    (< 0) pass through. Returns (merged, shifted_slot_map,
    shifted_assignment)."""
    fields, shifted, delta = _graft_window_fields(
        committed, spec, base.n_open, base.w_open
    )
    E = committed.exist_used.shape[0]
    base_n = jnp.asarray(base.n_open, dtype=jnp.int32)
    vg = committed.vg_counts + (spec.vg_counts - base.vg_counts)
    hg = _merge_hg_delta(committed, spec.hg_counts, base, delta, spec.n_open)
    exist_fields = _graft_exist_fields(committed, spec, base)
    budget = committed.budget + jnp.where(
        jnp.isfinite(base.budget), spec.budget - base.budget, 0.0
    )
    nodes_budget = committed.nodes_budget + jnp.where(
        jnp.isfinite(base.nodes_budget),
        spec.nodes_budget - base.nodes_budget,
        0.0,
    )
    res_cap = committed.res_cap + (spec.res_cap - base.res_cap)
    assign = jnp.where(
        assignment >= E + base_n, assignment + delta, assignment
    )
    merged = committed._replace(
        vg_counts=vg, hg_counts=hg, budget=budget,
        nodes_budget=nodes_budget, res_cap=res_cap,
        **exist_fields, **fields,
    )
    return merged, shifted, assign


# ---------------------------------------------------------------------------
# dp-sharded speculative per-pod scan (ISSUE 14c): hostname-anti-affinity
# and every other per-pod-routed kind joins the speculative dp fan-out
# ---------------------------------------------------------------------------
#
# The per-pod engine is the most general dispatch. Its step mutates
# exactly the ShardKscanState slice: window rows, counters, vg/hg
# counts, existing-node fields, and — since ISSUE 20 rung 1 — the
# budget/nodes_budget debits and reservation capacity (bank fields
# still pass through untouched on this class). One chunk of the
# per-pod scan per dp row speculates against the round base under the
# SAME commit conditions as the kscan family — window deadness for the
# chunk's valid-min request, pods-touched, vg/hg record-vs-apply
# disjointness, existing-node debit disjointness — plus two new bits:
# budget touch-vs-apply (a row that debits a finite budget blocks later
# rows whose pods could read it) and a conservative any-res_cap-delta
# bit. minValues needs no bit at all: mv only TIGHTENS a row's landing
# options and writes no cross-row state, so deadness stays sound.
# Commits go through merge_shard_kscan (hostname-group deltas shift
# their fresh columns, add in place on [0, E); budget/res_cap deltas
# add under the disjointness proof).


@_wf_timed("solve_perpod_dp")
@named_kernel("solve_perpod_dp")
@functools.partial(jax.jit, static_argnames=_STATIC)
def solve_perpod_dp(
    state: SolverState,
    pods: PodTensors,  # leading [DP] chunk axis on every tensor
    pod_tmpl_ok: jnp.ndarray,  # [DP, L, G]
    pod_it_allow: jnp.ndarray,  # [DP, L, T]
    pod_exist_ok: jnp.ndarray,  # [DP, L, E]
    pod_ports: jnp.ndarray,  # [DP, L, NP]
    pod_port_conf: jnp.ndarray,  # [DP, L, NP]
    pod_vols: jnp.ndarray,  # [DP, L, NV]
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    pod_topo: PodTopology,  # leading [DP] on every tensor
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    mv_active: bool = False,
    topo_kids: tuple = (),
    rid_kid: int = -1,
    res_vid: int = -1,
    res_active: bool = False,
    res_strict: bool = False,
    window: int = 0,  # unused here: the carry's shapes define the window
) -> tuple[ShardKscanState, jnp.ndarray, jnp.ndarray]:
    """Speculative dp fan-out for per-pod chunks: every dp row scans ITS
    pod chunk against the same base state with the per-pod step
    (annotate=False — the leading vmap axis is the dp mesh axis), under
    the packed commit-verdict contract shared with solve_fill_dp /
    solve_kscan_dp. Padding rows (valid all-false) go r_min = +inf and
    are trivially dead, so short rounds commit as no-ops. Returns
    (per-row ShardKscanState, per-row assignment, verdict word)."""
    step = _make_step(
        exist, it, templates, well_known, topo, zone_kid, ct_kid, n_claims,
        mv_active, topo_kids, rid_kid, res_vid, res_active, res_strict,
        annotate=False,
    )

    def one(xs):
        st, assignment = jax.lax.scan(step, state, xs)
        return _shard_kscan_slice(st), assignment

    xs_b = _xs(
        pods, pod_tmpl_ok, pod_it_allow, pod_exist_ok, pod_ports,
        pod_port_conf, pod_topo, pod_vols,
    )
    # the [DP, L, T] allow mask keeps its catalog axis on "it" (the same
    # split solve_fill_dp/solve_kscan_dp use) so GSPMD doesn't fully
    # rematerialize the tensor flipping between placements
    allow = pod_it_allow
    xs_b = jax.tree_util.tree_map(
        lambda a: a if a is allow else shard_hint(a, "dp"), xs_b
    )
    xs_b = xs_b[:3] + (shard_hint(allow, "dp", None, "it"),) + xs_b[4:]
    spec, assignment = jax.vmap(one)(xs_b)

    W = state.open.shape[0]
    W_rows = jnp.arange(W, dtype=jnp.int32)
    touched = jnp.any(
        (spec.pods > state.pods[None, :]) & (W_rows < state.w_open)[None, :],
        axis=-1,
    )
    valid = pods.valid[:, :, None]  # [DP, L, 1]
    r_min = jnp.min(
        jnp.where(valid, pods.requests, jnp.inf), axis=1
    )  # [DP, R]
    app_v = jnp.any(valid & pod_topo.vg_applies, axis=1) & topo.vg_valid[None]
    rec_v = jnp.any(valid & pod_topo.vg_records, axis=1) & topo.vg_valid[None]
    app_h = jnp.any(valid & pod_topo.hg_applies, axis=1) & topo.hg_valid[None]
    rec_h = jnp.any(valid & pod_topo.hg_records, axis=1) & topo.hg_valid[None]
    conflict = (
        jnp.any(rec_v[:, None, :] & app_v[None, :, :], axis=-1)
        | jnp.any(rec_h[:, None, :] & app_h[None, :, :], axis=-1)
    )  # [q, r]
    topo_ok = kernels.pairwise_commit_ok(conflict)
    exist_ok_rows = jnp.any(valid & pod_exist_ok, axis=1)
    exist_bit = _exist_conflict_ok(state, spec, exist, exist_ok_rows, r_min)
    # ISSUE 20 rung 1: budget touch-vs-apply + reservation disjointness.
    # A row that debits template g's budget (or node count) may not
    # commit ahead of a later row whose pods could read g's remaining
    # budget through their tmpl_ok gate; any reservation-capacity delta
    # conservatively blocks all later rows (reservations are rare and
    # res_cap is the only cross-row reservation register — held rows
    # ride the window graft and the pods-touched bit).
    apply_tmpl = jnp.any(valid & pod_tmpl_ok, axis=1)  # [DP, G]
    budget_bit = kernels.pairwise_commit_ok(
        _budget_res_conflict(state, spec, apply_tmpl)
    )
    verdict = _dp_verdict_word(
        state, spec, r_min, n_claims,
        lambda u, iv, om, rm: _rows_dead(u, iv, om, it, rm),
        touched=touched,
        extra_ok=topo_ok & exist_bit & budget_bit,
    )
    return spec, assignment, verdict
