"""The TPU scheduling solver: batched feasibility masks + scan-FFD packing.

This replaces the reference's Scheduler.Solve hot loop
(pkg/controllers/provisioning/scheduling/scheduler.go:440,
nodeclaim.go:124-242, nodeclaim.go:541). Reformulation:

  * Pods are pre-sorted first-fit-decreasing host-side (queue.go:72-90).
  * One `lax.scan` step places one pod. The carry holds every in-flight
    simulated NodeClaim as dense state: combined requirement tensors
    [N, K, V], resource usage [N, R], and the boolean set of still-viable
    instance types [N, T].
  * The per-(claim, instance-type) triple mask — requirements-intersect ×
    resource-fits × offering-available (nodeclaim.go:541's compat/fits/
    hasOffering) — is computed for ALL claims and instance types at once on
    the VPU/MXU instead of the reference's goroutine fan-out.
  * Claim selection mirrors the reference's ordering exactly: in-flight
    claims sorted fewest-pods-first with earliest-index tie-break
    (scheduler.go:598-599), via a single argmin over (pod_count, slot).
  * If no in-flight claim fits, a new claim opens from the highest-priority
    (weight-ordered) compatible template (scheduler.go:695+), or the pod is
    marked unschedulable.

The solver is pure and stateless per call (SURVEY.md §5 checkpoint/resume:
problem tensors are rebuilt from cluster state each cycle). All problem
tensors are jit ARGUMENTS, not closure constants, so re-encoding the
problem (e.g. after vocab growth) reuses the compiled executable whenever
shapes are unchanged; callers pad pods/keys/vocab to bucketed sizes to
keep shapes stable.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from karpenter_tpu.ops import kernels
from karpenter_tpu.ops.encode import InstanceTypeTensors, PodTensors, ReqSetTensors

# assignment sentinels
NO_CLAIM = -1  # no compatible in-flight claim or template
NO_ROOM = -2  # a template was feasible but the claim-slot capacity is full
BIG = jnp.int32(2**31 - 1)


class Templates(NamedTuple):
    """NodeClaim templates in weight-priority order (index 0 = first try)."""

    reqs: ReqSetTensors  # [G, K, V]
    its: jnp.ndarray  # [G, T] bool — statically compatible instance types
    daemon_requests: jnp.ndarray  # [G, R] f32 — daemonset overhead per template
    valid: jnp.ndarray  # [G] bool


class ClaimsState(NamedTuple):
    """The scan carry: all in-flight simulated NodeClaims."""

    reqs: ReqSetTensors  # [N, K, V]
    used: jnp.ndarray  # [N, R] f32 — pod requests incl. daemon overhead
    its: jnp.ndarray  # [N, T] bool — viable instance types
    template: jnp.ndarray  # [N] int32
    open: jnp.ndarray  # [N] bool
    pods: jnp.ndarray  # [N] int32
    n_open: jnp.ndarray  # [] int32


class SolveResult(NamedTuple):
    assignment: jnp.ndarray  # [P] int32 — claim slot, NO_CLAIM or NO_ROOM
    claims: ClaimsState


def _fits_and_offering(
    total: jnp.ndarray,  # [N, R] requested totals per claim
    comb: ReqSetTensors,  # [N, K, V] combined claim∩pod requirements
    it: InstanceTypeTensors,
    zone_kid: int,
    ct_kid: int,
) -> jnp.ndarray:
    """[N, T] bool — exists an allocatable group where resources fit AND a
    compatible offering is available (nodeclaim.go:630-652 fits()).

    Offering compatibility reduces to: the claim's zone mask admits the
    offering zone and its capacity-type mask admits the offering ct — both
    well-known keys whose values are always in-vocab.
    """
    # fits per group: [N, T, GR]. Resources with zero requested always pass,
    # matching the host's "only check requested keys" (resources.fits) even
    # when an allocatable entry is negative (overhead exceeding capacity).
    t = total[:, None, None, :]
    fit = jnp.all((t <= it.alloc[None, :, :, :]) | (t == 0.0), axis=-1)
    fit = fit & it.group_valid[None, :, :]
    # offering availability per group: [N, T, GR]
    zmask = comb.mask[:, zone_kid, :]  # [N, V] — admitted zones
    cmask = comb.mask[:, ct_kid, :]  # [N, V]
    Z = it.zc_avail.shape[2]
    C = it.zc_avail.shape[3]
    off = jnp.einsum(
        "tgzc,nz,nc->ntg",
        it.zc_avail,
        zmask[:, :Z],
        cmask[:, :C],
        preferred_element_type=jnp.float32,
    ) > 0
    return jnp.any(fit & off, axis=-1)  # [N, T]


def _broadcast_pod(pod: ReqSetTensors, n: int) -> ReqSetTensors:
    return ReqSetTensors(
        mask=jnp.broadcast_to(pod.mask[None], (n,) + pod.mask.shape),
        inf=jnp.broadcast_to(pod.inf[None], (n,) + pod.inf.shape),
        excl=jnp.broadcast_to(pod.excl[None], (n,) + pod.excl.shape),
        gte=jnp.broadcast_to(pod.gte[None], (n,) + pod.gte.shape),
        lte=jnp.broadcast_to(pod.lte[None], (n,) + pod.lte.shape),
        defined=jnp.broadcast_to(pod.defined[None], (n,) + pod.defined.shape),
    )


def _init_claims(n: int, k: int, v: int, r: int, t: int) -> ClaimsState:
    identity = ReqSetTensors(
        mask=jnp.ones((n, k, v), dtype=bool),
        inf=jnp.ones((n, k), dtype=bool),
        excl=jnp.zeros((n, k), dtype=bool),
        gte=jnp.full((n, k), -(2**31) + 1, dtype=jnp.int32),
        lte=jnp.full((n, k), 2**31 - 1, dtype=jnp.int32),
        defined=jnp.zeros((n, k), dtype=bool),
    )
    return ClaimsState(
        reqs=identity,
        used=jnp.zeros((n, r), dtype=jnp.float32),
        its=jnp.zeros((n, t), dtype=bool),
        template=jnp.zeros(n, dtype=jnp.int32),
        open=jnp.zeros(n, dtype=bool),
        pods=jnp.zeros(n, dtype=jnp.int32),
        n_open=jnp.int32(0),
    )


@functools.partial(jax.jit, static_argnames=("zone_kid", "ct_kid", "n_claims"))
def solve(
    pods: PodTensors,
    pod_tol: jnp.ndarray,  # [P, G] bool
    pod_it_allow: jnp.ndarray,  # [P, T] bool — instance types the pod's NAME selector admits
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,  # [K] bool
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
) -> SolveResult:
    N = n_claims
    K = it.reqs.mask.shape[1]
    V = it.reqs.mask.shape[2]
    R = it.alloc.shape[2]
    T = it.alloc.shape[0]

    def step(state: ClaimsState, xs):
        pod_reqs, pod_requests, tol_g, it_allow, pod_valid = xs

        pod_b = _broadcast_pod(pod_reqs, N)
        comb = kernels.intersect_sets(state.reqs, pod_b)  # [N, K, V]

        # claim-level requirement compat (nodeclaim.go:147):
        # claim.reqs.Compatible(pod.reqs, AllowUndefinedWellKnownLabels)
        claim_ok = kernels.compatible_elemwise(state.reqs, pod_b, well_known)  # [N]

        # instance-type triple mask against the NEW combined requirements
        it_compat = kernels.intersects(it.reqs, comb).T  # [N, T]
        total = state.used + pod_requests[None, :]
        fits_off = _fits_and_offering(total, comb, it, zone_kid, ct_kid)
        new_its = state.its & it_compat & fits_off & it_allow[None, :]  # [N, T]

        tol = tol_g[state.template]  # [N] — tolerates claim's template taints
        feas = state.open & claim_ok & tol & jnp.any(new_its, axis=-1) & pod_valid

        # fewest-pods-first with earliest-slot tie-break (scheduler.go:598)
        order_key = state.pods * jnp.int32(N) + jnp.arange(N, dtype=jnp.int32)
        pick = jnp.argmin(jnp.where(feas, order_key, BIG))
        found = feas[pick]

        # --- new-claim path: templates in weight order (scheduler.go:695) --
        G = templates.its.shape[0]
        pod_g = _broadcast_pod(pod_reqs, G)
        comb0 = kernels.intersect_sets(templates.reqs, pod_g)
        tmpl_ok = kernels.compatible_elemwise(templates.reqs, pod_g, well_known)  # [G]
        it_compat0 = kernels.intersects(it.reqs, comb0).T  # [G, T]
        total0 = templates.daemon_requests + pod_requests[None, :]
        fits_off0 = _fits_and_offering(total0, comb0, it, zone_kid, ct_kid)
        its0 = templates.its & it_compat0 & fits_off0 & it_allow[None, :]  # [G, T]
        tmpl_feas = templates.valid & tmpl_ok & tol_g & jnp.any(its0, axis=-1)
        g = jnp.argmax(tmpl_feas)  # earliest weight-ordered feasible template
        any_template = jnp.any(tmpl_feas) & pod_valid & ~found
        can_open = any_template & (state.n_open < N)

        slot = jnp.where(found, pick, state.n_open)
        place = found | can_open
        assignment = jnp.where(
            place,
            slot.astype(jnp.int32),
            jnp.where(any_template, jnp.int32(NO_ROOM), jnp.int32(NO_CLAIM)),
        )

        # merged update values for the chosen slot
        sel_reqs = kernels.select_set(
            found,
            kernels.take_set(comb, pick),
            kernels.take_set(comb0, g),
        )
        sel_its = jnp.where(found, new_its[pick], its0[g])
        sel_used = jnp.where(
            found,
            total[pick],
            templates.daemon_requests[g] + pod_requests,
        )
        sel_template = jnp.where(found, state.template[pick], g.astype(jnp.int32))

        def apply(state: ClaimsState) -> ClaimsState:
            return ClaimsState(
                reqs=kernels.update_set_at(state.reqs, slot, sel_reqs),
                used=state.used.at[slot].set(sel_used),
                its=state.its.at[slot].set(sel_its),
                template=state.template.at[slot].set(sel_template),
                open=state.open.at[slot].set(True),
                pods=state.pods.at[slot].add(1),
                n_open=state.n_open + jnp.where(found, 0, 1).astype(jnp.int32),
            )

        new_state = jax.tree.map(
            lambda a, b: jnp.where(
                place.reshape((1,) * a.ndim) if a.ndim else place, a, b
            ),
            apply(state),
            state,
        )
        return new_state, assignment

    state = _init_claims(N, K, V, R, T)
    xs = (pods.reqs, pods.requests, pod_tol, pod_it_allow, pods.valid)
    state, assignment = jax.lax.scan(step, state, xs)
    return SolveResult(assignment=assignment, claims=state)
