"""The TPU scheduling solver: batched feasibility masks + scan-FFD packing.

This replaces the reference's Scheduler.Solve hot loop
(pkg/controllers/provisioning/scheduling/scheduler.go:440,
nodeclaim.go:124-242, existingnode.go:32-200, nodeclaim.go:541).
Reformulation:

  * Pods are pre-sorted first-fit-decreasing host-side (queue.go:72-90).
  * One `lax.scan` step places one pod through the reference's 3-tier
    cascade (scheduler.go:582-612):
      tier 1  existing nodes, earliest-index wins (addToExistingNode)
      tier 2  in-flight simulated NodeClaims, fewest-pods-first with
              earliest-slot tie-break (addToInflightNode, :598)
      tier 3  a new claim from the highest-priority weight-ordered
              compatible template (addToNewNodeClaim)
  * The per-(claim, instance-type) triple mask — requirements-intersect ×
    resource-fits × offering-available (nodeclaim.go:541) — is computed for
    ALL claims and instance types at once on the VPU/MXU instead of the
    reference's goroutine fan-out.
  * NodePool limits ride along as per-template budget vectors: new claims
    filter instance types by remaining capacity and subtract the max
    capacity over the claim's viable types on open (scheduler.go:708-727,
    :1068 filterByRemainingResources / subtractMax).

The solver is pure and stateless per call; all problem tensors are jit
ARGUMENTS, so re-encoding (e.g. after vocab growth) reuses the compiled
executable whenever shapes are unchanged.

Assignment index space: [0, E) = existing-node slot, [E, E+N) = claim
slot, NO_CLAIM / NO_ROOM sentinels otherwise.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from karpenter_tpu.ops import kernels
from karpenter_tpu.ops import topology as topo_ops
from karpenter_tpu.ops.encode import INT_MAX, INT_MIN, InstanceTypeTensors, PodTensors, ReqSetTensors
from karpenter_tpu.ops.topology import PodTopology, TopologyTensors

# assignment sentinels
NO_CLAIM = -1  # no compatible existing node, in-flight claim, or template
NO_ROOM = -2  # a template was feasible but the claim-slot capacity is full
BIG = jnp.int32(2**31 - 1)


class Templates(NamedTuple):
    """NodeClaim templates in weight-priority order (index 0 = first try)."""

    reqs: ReqSetTensors  # [G, K, V]
    its: jnp.ndarray  # [G, T] bool — statically compatible instance types
    daemon_requests: jnp.ndarray  # [G, R] f32 — daemonset overhead per template
    valid: jnp.ndarray  # [G] bool
    budget: jnp.ndarray  # [G, R] f32 — remaining pool limits (+inf unlimited)
    nodes_budget: jnp.ndarray  # [G] f32 — remaining node-count limit (+inf)
    # minValues flexibility floors (types.go:399-433; Strict policy):
    # mv_key indexes the pre-gathered mv_it_values slab (-1 = the
    # instance-type NAME key, -2 = unused)
    mv_key: jnp.ndarray  # [G, M] i32
    mv_min: jnp.ndarray  # [G, M] i32 (0 = unused)
    # [T, J, V] — per min-keyed label, the values each instance type
    # DEFINES (finite sets only: undefined/complement keys contribute
    # nothing, matching Requirements.Get(k).Values())
    mv_it_values: jnp.ndarray


class ExistingNodes(NamedTuple):
    """Existing/in-flight real nodes (tier 1). reqs seed from node labels;
    avail is allocatable minus current pods and daemon overhead."""

    reqs: ReqSetTensors  # [E, K, V]
    avail: jnp.ndarray  # [E, R] f32 — remaining schedulable resources
    valid: jnp.ndarray  # [E] bool
    ports: jnp.ndarray  # [E, NP] bool — host ports already in use


class SolverState(NamedTuple):
    """The scan carry."""

    # tier-1 existing nodes
    exist_reqs: ReqSetTensors  # [E, K, V] — evolve as pods land
    exist_used: jnp.ndarray  # [E, R]
    # tier-2 in-flight claims
    reqs: ReqSetTensors  # [N, K, V]
    used: jnp.ndarray  # [N, R]
    its: jnp.ndarray  # [N, T] bool
    template: jnp.ndarray  # [N] int32
    open: jnp.ndarray  # [N] bool
    pods: jnp.ndarray  # [N] int32
    n_open: jnp.ndarray  # [] int32
    # limits
    budget: jnp.ndarray  # [G, R]
    nodes_budget: jnp.ndarray  # [G]
    # topology counts
    vg_counts: jnp.ndarray  # [NGv, V]
    hg_counts: jnp.ndarray  # [NGh, E+N]
    # host ports in use (hostportusage.go:35-97)
    exist_ports: jnp.ndarray  # [E, NP] bool
    claim_ports: jnp.ndarray  # [N, NP] bool
    # reserved-capacity twin (reservationmanager.go:28-115)
    res_cap: jnp.ndarray  # [RID] i32 — remaining capacity per reservation id
    held: jnp.ndarray  # [N, RID] bool — reservations each claim holds


class SolveResult(NamedTuple):
    assignment: jnp.ndarray  # [P] int32
    claims: SolverState


def _fits_and_offering(
    total: jnp.ndarray,  # [B, R] requested totals
    comb: ReqSetTensors,  # [B, K, V] combined requirements
    it: InstanceTypeTensors,
    zone_kid: int,
    ct_kid: int,
) -> jnp.ndarray:
    """[B, T] bool — exists an allocatable group where resources fit AND a
    compatible offering is available (nodeclaim.go:630-652 fits())."""
    # fits per group: [B, T, GR]. Resources with zero requested always pass,
    # matching the host's "only check requested keys" (resources.fits) even
    # when an allocatable entry is negative (overhead exceeding capacity).
    t = total[:, None, None, :]
    fit = jnp.all((t <= it.alloc[None, :, :, :]) | (t == 0.0), axis=-1)
    fit = fit & it.group_valid[None, :, :]
    zmask = comb.mask[:, zone_kid, :]  # [B, V] — admitted zones
    cmask = comb.mask[:, ct_kid, :]
    Z = it.zc_avail.shape[2]
    C = it.zc_avail.shape[3]
    off = jnp.einsum(
        "tgzc,nz,nc->ntg",
        it.zc_avail.astype(jnp.bfloat16),
        zmask[:, :Z].astype(jnp.bfloat16),
        cmask[:, :C].astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    ) > 0
    return jnp.any(fit & off, axis=-1)  # [B, T]


def _broadcast_pod(pod: ReqSetTensors, n: int) -> ReqSetTensors:
    return ReqSetTensors(
        mask=jnp.broadcast_to(pod.mask[None], (n,) + pod.mask.shape),
        inf=jnp.broadcast_to(pod.inf[None], (n,) + pod.inf.shape),
        excl=jnp.broadcast_to(pod.excl[None], (n,) + pod.excl.shape),
        gte=jnp.broadcast_to(pod.gte[None], (n,) + pod.gte.shape),
        lte=jnp.broadcast_to(pod.lte[None], (n,) + pod.lte.shape),
        defined=jnp.broadcast_to(pod.defined[None], (n,) + pod.defined.shape),
    )


def identity_reqs(n: int, k: int, v: int) -> ReqSetTensors:
    """The intersection-identity encoding (all keys undefined)."""
    return ReqSetTensors(
        mask=jnp.ones((n, k, v), dtype=bool),
        inf=jnp.ones((n, k), dtype=bool),
        excl=jnp.zeros((n, k), dtype=bool),
        gte=jnp.full((n, k), -(2**31) + 1, dtype=jnp.int32),
        lte=jnp.full((n, k), 2**31 - 1, dtype=jnp.int32),
        defined=jnp.zeros((n, k), dtype=bool),
    )


def _min_values_ok(
    viable: jnp.ndarray,  # [C, T] bool — surviving instance types
    mv_key_c: jnp.ndarray,  # [C, M] i32 — indexes into the J axis
    mv_min_c: jnp.ndarray,  # [C, M] i32
    mv_it_values: jnp.ndarray,  # [T, J, V] bool — pre-gathered min-keyed values
) -> jnp.ndarray:
    """[C] bool — distinct-value floors hold over the viable set
    (SatisfiesMinValues, types.go:399-433)."""
    present = (
        jnp.einsum(
            "ct,tjv->cjv",
            viable.astype(jnp.bfloat16),
            mv_it_values.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        > 0
    )
    counts_all = jnp.sum(present, axis=-1).astype(jnp.int32)  # [C, J]
    name_count = jnp.sum(viable, axis=-1).astype(jnp.int32)  # [C]
    key = jnp.clip(mv_key_c, 0, mv_it_values.shape[1] - 1)
    per_key = jnp.take_along_axis(counts_all, key, axis=1)  # [C, M]
    cnt = jnp.where(mv_key_c == -1, name_count[:, None], per_key)
    ok = (mv_min_c <= 0) | (cnt >= mv_min_c)
    return jnp.all(ok, axis=-1)


def _make_step(
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    mv_active: bool,
    topo_kids: tuple,
    rid_kid: int,
    res_vid: int,
    res_active: bool,
    res_strict: bool,
):
    """Build the per-pod scan step closure shared by solve/solve_from."""
    N = n_claims
    K = it.reqs.mask.shape[1]
    E = exist.avail.shape[0]
    G = templates.its.shape[0]
    no_wk = jnp.zeros_like(well_known)
    RID = it.res_ofs.shape[1]
    Zr = it.res_ofs.shape[2]
    # static [K] mask of keys handled exactly per-step (topology narrowing);
    # the incremental tier-2 classification covers the rest
    kid_mask = jnp.zeros(K, dtype=bool)
    for k in topo_kids:
        kid_mask = kid_mask.at[k].set(True)

    def _reserve_options(viable, comb):
        """[B, RID] bool — reserved offerings compatible with each
        candidate over its viable types (offeringsToReserve's scan,
        nodeclaim.go:313-332): an available reserved offering on a
        surviving type whose zone, capacity-type and reservation-id the
        combined requirements admit."""
        zmask = comb.mask[:, zone_kid, :Zr]
        ridmask = comb.mask[:, rid_kid, :RID]
        ct_res = comb.mask[:, ct_kid, res_vid]
        hit = (
            jnp.einsum(
                "bt,trz,bz->br",
                viable.astype(jnp.bfloat16),
                it.res_ofs.astype(jnp.bfloat16),
                zmask.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            > 0
        )
        return hit & ridmask & ct_res[:, None]

    def step(state: SolverState, xs):
        (
            pod_reqs,
            pod_requests,
            tmpl_ok_g,
            it_allow,
            exist_ok_e,
            ports_p,
            port_conf_p,
            pod_valid,
            vg_applies,
            vg_records,
            vg_self,
            hg_applies,
            hg_records,
            hg_self,
            strict_mask,
        ) = xs

        # ---- tier 1: existing nodes (earliest index wins) -----------------
        pod_e = _broadcast_pod(pod_reqs, E)
        comb_e = kernels.intersect_sets(state.exist_reqs, pod_e)
        # strict Compatible — no AllowUndefinedWellKnownLabels
        # (existingnode.go:101 n.requirements.Compatible(podData.Requirements))
        exist_compat = kernels.compatible_elemwise(state.exist_reqs, pod_e, no_wk)
        total_e = state.exist_used + pod_requests[None, :]
        t_e = total_e
        exist_fit = jnp.all((t_e <= exist.avail) | (t_e == 0.0), axis=-1)
        vg_pre = topo_ops.vg_pod_precompute(
            topo, state.vg_counts, strict_mask, vg_applies, vg_self, K
        )
        key_touched = vg_pre.key_touched
        topo_e, upd_e, _ = topo_ops.vg_evaluate(topo, vg_pre, comb_e.mask)
        topo_eh = topo_ops.hg_evaluate(
            topo, state.hg_counts, jnp.arange(E, dtype=jnp.int32), hg_applies, hg_self
        )
        ports_ok_e = ~jnp.any(port_conf_p[None, :] & state.exist_ports, axis=-1)  # [E]
        feas_e = (
            exist.valid
            & exist_ok_e
            & exist_compat
            & exist_fit
            & topo_e
            & topo_eh
            & ports_ok_e
            & pod_valid
        )
        pick_e = jnp.argmin(jnp.where(feas_e, jnp.arange(E, dtype=jnp.int32), BIG))
        found_e = jnp.any(feas_e)

        # ---- tier 2: in-flight claims (fewest pods, earliest slot) --------
        pod_b = _broadcast_pod(pod_reqs, N)
        comb = kernels.intersect_sets(state.reqs, pod_b)
        claim_ok = kernels.compatible_elemwise(state.reqs, pod_b, well_known)
        topo_n, upd_n, _ = topo_ops.vg_evaluate(topo, vg_pre, comb.mask)
        topo_nh = topo_ops.hg_evaluate(
            topo,
            state.hg_counts,
            E + jnp.arange(N, dtype=jnp.int32),
            hg_applies,
            hg_self,
        )
        # the topology-narrowed requirements feed instance-type filtering
        # (nodeclaim.go:199-213: topology comes before the IT filter)
        comb_t = _apply_topo(comb, upd_n, key_touched)

        # ---- incremental it-compat (replaces the O(N·T·K·V) per-step
        # intersects recompute — the round-1 dominant cost). Each
        # (claim, key) of comb_t is classified:
        #   == pod row   -> read the per-step [T, K] pod×type table
        #   == claim row -> implied true wherever state.its holds (state.its
        #                   certifies intersects(it, claim) from the step
        #                   that stored the row)
        #   topology key -> exact per-key einsum (static, small set)
        #   otherwise    -> partial-overlap conflict; rare -> lax.cond runs
        #                   the full pairwise intersects for this step.
        # Only claims that can be picked (open & Compatible) gate the
        # fallback; garbage values elsewhere are masked by feas/state.its.
        eqP = kernels.set_eq_rows(comb_t, _broadcast_pod(pod_reqs, N))  # [N, K]
        eqC = kernels.set_eq_rows(comb_t, state.reqs)  # [N, K]
        nonkid = ~kid_mask[None, :]
        need_exact = ~eqP & ~eqC & nonkid
        any_fallback = jnp.any(
            state.open & claim_ok & jnp.any(need_exact, axis=-1)
        )

        def _full_compat():
            return kernels.intersects(it.reqs, comb_t).T  # [N, T]

        def _fast_compat():
            pod_tkok = kernels.per_key_ok_table(it.reqs, pod_reqs)  # [T, K]
            use_pk = (eqP & ~eqC & nonkid).astype(jnp.bfloat16)
            viol = (
                jnp.einsum(
                    "nk,tk->nt",
                    use_pk,
                    (~pod_tkok).astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                )
                > 0.0
            )
            ok = ~viol
            for k in topo_kids:
                ok &= kernels.per_key_ok_at(it.reqs, comb_t, k)
            return ok

        it_compat = jax.lax.cond(any_fallback, _full_compat, _fast_compat)
        total = state.used + pod_requests[None, :]
        fits_off = _fits_and_offering(total, comb_t, it, zone_kid, ct_kid)
        new_its = state.its & it_compat & fits_off & it_allow[None, :]
        tol = tmpl_ok_g[state.template]
        ports_ok_n = ~jnp.any(port_conf_p[None, :] & state.claim_ports, axis=-1)  # [N]
        feas = (
            state.open
            & claim_ok
            & tol
            & topo_n
            & topo_nh
            & ports_ok_n
            & jnp.any(new_its, axis=-1)
            & pod_valid
            & ~found_e
        )
        if mv_active:
            feas &= _min_values_ok(
                new_its,
                templates.mv_key[state.template],
                templates.mv_min[state.template],
                templates.mv_it_values,
            )
        if res_active:
            ofs_c = _reserve_options(new_its, comb_t)  # [N, RID]
            to_res = ofs_c & (state.held | (state.res_cap > 0)[None, :])
            if res_strict:
                # strict mode (scheduler.go:75-78): fail the add when
                # compatible reserved offerings exist but none can be
                # reserved, or when it would drop existing reservations
                no_res = ~jnp.any(to_res, axis=-1)
                feas &= ~(
                    (jnp.any(ofs_c, axis=-1) | jnp.any(state.held, axis=-1)) & no_res
                )
        else:
            to_res = state.held  # unused; keeps shapes uniform
        order_key = state.pods * jnp.int32(N) + jnp.arange(N, dtype=jnp.int32)
        pick = jnp.argmin(jnp.where(feas, order_key, BIG))
        found = jnp.any(feas)

        # ---- tier 3: new claim from weight-ordered templates ----------------
        pod_g = _broadcast_pod(pod_reqs, G)
        comb0 = kernels.intersect_sets(templates.reqs, pod_g)
        tmpl_compat = kernels.compatible_elemwise(templates.reqs, pod_g, well_known)
        topo_g, upd_g, _ = topo_ops.vg_evaluate(topo, vg_pre, comb0.mask)
        # fresh hostname domain; hg_counts carries a spare slot at E+N so
        # this read stays in bounds when all N claim slots are open
        new_slot = E + state.n_open
        topo_gh = topo_ops.hg_evaluate(
            topo,
            state.hg_counts,
            jnp.broadcast_to(new_slot, (G,)).astype(jnp.int32),
            hg_applies,
            hg_self,
        )
        comb0_t = _apply_topo(comb0, upd_g, key_touched)
        it_compat0 = kernels.intersects(it.reqs, comb0_t).T  # [G, T]
        total0 = templates.daemon_requests + pod_requests[None, :]
        fits_off0 = _fits_and_offering(total0, comb0_t, it, zone_kid, ct_kid)
        # NodePool limits: exclude instance types whose full capacity would
        # breach the remaining budget (scheduler.go:1068)
        cap_ok = jnp.all(
            (it.cap[None, :, :] <= state.budget[:, None, :]), axis=-1
        )  # [G, T]
        its0 = (
            templates.its
            & it_compat0
            & fits_off0
            & it_allow[None, :]
            & cap_ok
        )
        tmpl_feas = (
            templates.valid
            & tmpl_compat
            & tmpl_ok_g
            & topo_g
            & topo_gh
            & jnp.any(its0, axis=-1)
            & (state.nodes_budget >= 1.0)
        )
        if mv_active:
            tmpl_feas &= _min_values_ok(
                its0, templates.mv_key, templates.mv_min, templates.mv_it_values
            )
        if res_active:
            ofs0 = _reserve_options(its0, comb0_t)  # [G, RID]
            to_res0 = ofs0 & (state.res_cap > 0)[None, :]
            if res_strict:
                tmpl_feas &= ~(jnp.any(ofs0, axis=-1) & ~jnp.any(to_res0, axis=-1))
        else:
            to_res0 = jnp.zeros((G, state.held.shape[1]), dtype=bool)
        g = jnp.argmax(tmpl_feas)
        any_template = jnp.any(tmpl_feas) & pod_valid & ~found_e & ~found
        can_open = any_template & (state.n_open < N)

        # ---- merge the three outcomes ----------------------------------------
        open_slot = state.n_open
        slot = jnp.where(
            found_e,
            pick_e,
            jnp.where(found, E + pick, E + open_slot),
        )
        place = found_e | found | can_open
        assignment = jnp.where(
            place,
            slot.astype(jnp.int32),
            jnp.where(any_template, jnp.int32(NO_ROOM), jnp.int32(NO_CLAIM)),
        )

        # existing-node updates (topology-narrowed requirements are stored)
        upd_exist = found_e
        comb_e_t = _apply_topo(comb_e, upd_e, key_touched)
        new_exist_reqs = kernels.select_set(
            upd_exist,
            kernels.update_set_at(state.exist_reqs, pick_e, kernels.take_set(comb_e_t, pick_e)),
            state.exist_reqs,
        )
        new_exist_used = jnp.where(
            upd_exist, state.exist_used.at[pick_e].set(total_e[pick_e]), state.exist_used
        )
        new_exist_ports = jnp.where(
            upd_exist,
            state.exist_ports.at[pick_e].set(state.exist_ports[pick_e] | ports_p),
            state.exist_ports,
        )

        # claim updates (tier 2 or 3)
        upd_claim = (found | can_open) & ~found_e
        cslot = jnp.where(found, pick, open_slot)
        sel_reqs = kernels.select_set(
            found, kernels.take_set(comb_t, pick), kernels.take_set(comb0_t, g)
        )
        sel_its = jnp.where(found, new_its[pick], its0[g])
        sel_used = jnp.where(
            found, total[pick], templates.daemon_requests[g] + pod_requests
        )
        sel_template = jnp.where(found, state.template[pick], g.astype(jnp.int32))

        # topology count commits for the winning candidate
        final_reqs = kernels.select_set(found_e, kernels.take_set(comb_e_t, pick_e), sel_reqs)
        slot_h = jnp.where(found_e, pick_e, E + cslot).astype(jnp.int32)
        new_vg_counts = jnp.where(
            place,
            topo_ops.vg_commit(topo, state.vg_counts, final_reqs.mask, final_reqs.inf, vg_records),
            state.vg_counts,
        )
        new_hg_counts = jnp.where(
            place,
            topo_ops.hg_commit(state.hg_counts, slot_h, hg_records, topo.hg_valid),
            state.hg_counts,
        )
        new_reqs = kernels.select_set(
            upd_claim, kernels.update_set_at(state.reqs, cslot, sel_reqs), state.reqs
        )
        new_used = jnp.where(upd_claim, state.used.at[cslot].set(sel_used), state.used)
        new_claim_its = jnp.where(upd_claim, state.its.at[cslot].set(sel_its), state.its)
        new_template = jnp.where(
            upd_claim, state.template.at[cslot].set(sel_template), state.template
        )
        new_open = jnp.where(upd_claim, state.open.at[cslot].set(True), state.open)
        new_pods = jnp.where(upd_claim, state.pods.at[cslot].add(1), state.pods)
        new_claim_ports = jnp.where(
            upd_claim,
            state.claim_ports.at[cslot].set(state.claim_ports[cslot] | ports_p),
            state.claim_ports,
        )
        opened = can_open & ~found
        new_n_open = state.n_open + jnp.where(opened, 1, 0).astype(jnp.int32)

        # reserved-capacity commit: reserve new ids, release dropped ones
        # (nodeclaim.go:260-262 Reserve + releaseReservedOfferings)
        if res_active:
            sel_res = jnp.where(found, to_res[pick], to_res0[g])  # [RID]
            prev_res = jnp.where(
                found, state.held[pick], jnp.zeros_like(state.held[0])
            )
            newly = sel_res & ~prev_res
            released = prev_res & ~sel_res
            new_res_cap = jnp.where(
                upd_claim,
                state.res_cap + released.astype(jnp.int32) - newly.astype(jnp.int32),
                state.res_cap,
            )
            new_held = jnp.where(
                upd_claim, state.held.at[cslot].set(sel_res), state.held
            )
        else:
            new_res_cap, new_held = state.res_cap, state.held

        # limits bookkeeping on open: subtract the max capacity over the
        # claim's viable instance types (scheduler.go:791 subtractMax)
        max_cap = jnp.max(
            jnp.where(its0[g][:, None], it.cap, -jnp.inf), axis=0
        )  # [R]
        max_cap = jnp.where(jnp.isfinite(max_cap), max_cap, 0.0)
        new_budget = jnp.where(
            opened, state.budget.at[g].add(-max_cap), state.budget
        )
        new_nodes_budget = jnp.where(
            opened, state.nodes_budget.at[g].add(-1.0), state.nodes_budget
        )

        return (
            SolverState(
                exist_reqs=new_exist_reqs,
                exist_used=new_exist_used,
                reqs=new_reqs,
                used=new_used,
                its=new_claim_its,
                template=new_template,
                open=new_open,
                pods=new_pods,
                n_open=new_n_open,
                budget=new_budget,
                nodes_budget=new_nodes_budget,
                vg_counts=new_vg_counts,
                hg_counts=new_hg_counts,
                exist_ports=new_exist_ports,
                claim_ports=new_claim_ports,
                res_cap=new_res_cap,
                held=new_held,
            ),
            assignment,
        )

    return step


def initial_state(
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    topo: TopologyTensors,
    n_claims: int,
    n_ports: int,
    res_cap0=None,
) -> SolverState:
    """The empty carry (no pods placed yet)."""
    N = n_claims
    K = it.reqs.mask.shape[1]
    V = it.reqs.mask.shape[2]
    R = it.alloc.shape[2]
    T = it.alloc.shape[0]
    E = exist.avail.shape[0]
    return SolverState(
        exist_reqs=exist.reqs,
        exist_used=jnp.zeros((E, R), dtype=jnp.float32),
        reqs=identity_reqs(N, K, V),
        used=jnp.zeros((N, R), dtype=jnp.float32),
        its=jnp.zeros((N, T), dtype=bool),
        template=jnp.zeros(N, dtype=jnp.int32),
        open=jnp.zeros(N, dtype=bool),
        pods=jnp.zeros(N, dtype=jnp.int32),
        n_open=jnp.int32(0),
        budget=templates.budget,
        nodes_budget=templates.nodes_budget,
        vg_counts=topo.vg_counts0,
        hg_counts=topo.hg_counts0,
        exist_ports=exist.ports,
        claim_ports=jnp.zeros((N, n_ports), dtype=bool),
        res_cap=(
            jnp.asarray(res_cap0, dtype=jnp.int32)
            if res_cap0 is not None
            else jnp.zeros(it.res_ofs.shape[1], dtype=jnp.int32)
        ),
        held=jnp.zeros((N, it.res_ofs.shape[1]), dtype=bool),
    )


def _xs(pods, pod_tmpl_ok, pod_it_allow, pod_exist_ok, pod_ports, pod_port_conf, pod_topo):
    return (
        pods.reqs,
        pods.requests,
        pod_tmpl_ok,
        pod_it_allow,
        pod_exist_ok,
        pod_ports,
        pod_port_conf,
        pods.valid,
        pod_topo.vg_applies,
        pod_topo.vg_records,
        pod_topo.vg_self,
        pod_topo.hg_applies,
        pod_topo.hg_records,
        pod_topo.hg_self,
        pod_topo.strict_mask,
    )


_STATIC = (
    "zone_kid",
    "ct_kid",
    "n_claims",
    "mv_active",
    "topo_kids",
    "rid_kid",
    "res_vid",
    "res_active",
    "res_strict",
)


@functools.partial(jax.jit, static_argnames=_STATIC)
def solve(
    pods: PodTensors,
    pod_tmpl_ok: jnp.ndarray,  # [P, G] bool — tolerates taints + skipped-key static checks
    pod_it_allow: jnp.ndarray,  # [P, T] bool — instance types the pod's NAME selector admits
    pod_exist_ok: jnp.ndarray,  # [P, E] bool — static checks vs existing nodes
    pod_ports: jnp.ndarray,  # [P, NP] bool — the pod's own host-port keys
    pod_port_conf: jnp.ndarray,  # [P, NP] bool — keys the pod CONFLICTS with (wildcard-expanded)
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,  # [K] bool
    topo: TopologyTensors,
    pod_topo: PodTopology,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    mv_active: bool = False,
    topo_kids: tuple = (),
    res_cap0=None,
    rid_kid: int = -1,
    res_vid: int = -1,
    res_active: bool = False,
    res_strict: bool = False,
) -> SolveResult:
    state = initial_state(
        exist, it, templates, topo, n_claims, pod_ports.shape[1], res_cap0
    )
    step = _make_step(
        exist, it, templates, well_known, topo, zone_kid, ct_kid, n_claims,
        mv_active, topo_kids, rid_kid, res_vid, res_active, res_strict,
    )
    xs = _xs(pods, pod_tmpl_ok, pod_it_allow, pod_exist_ok, pod_ports, pod_port_conf, pod_topo)
    state, assignment = jax.lax.scan(step, state, xs)
    return SolveResult(assignment=assignment, claims=state)


@functools.partial(jax.jit, static_argnames=_STATIC)
def solve_from(
    state: SolverState,
    pods: PodTensors,
    pod_tmpl_ok: jnp.ndarray,
    pod_it_allow: jnp.ndarray,
    pod_exist_ok: jnp.ndarray,
    pod_ports: jnp.ndarray,
    pod_port_conf: jnp.ndarray,
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    pod_topo: PodTopology,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    mv_active: bool = False,
    topo_kids: tuple = (),
    rid_kid: int = -1,
    res_vid: int = -1,
    res_active: bool = False,
    res_strict: bool = False,
) -> SolveResult:
    """Resume the scan from an explicit carry — the chunked-solve entry:
    the host splits a large pod batch into fixed-size chunks (bounded
    per-dispatch transfers and a single compiled executable) and threads
    SolverState between calls. Bit-identical to one big scan."""
    step = _make_step(
        exist, it, templates, well_known, topo, zone_kid, ct_kid, n_claims,
        mv_active, topo_kids, rid_kid, res_vid, res_active, res_strict,
    )
    xs = _xs(pods, pod_tmpl_ok, pod_it_allow, pod_exist_ok, pod_ports, pod_port_conf, pod_topo)
    state, assignment = jax.lax.scan(step, state, xs)
    return SolveResult(assignment=assignment, claims=state)


@functools.partial(jax.jit, static_argnames=_STATIC)
def solve_whatif(
    scen_pod_idx: jnp.ndarray,  # [S, L] i32 — this scenario's pods (indices into the union)
    scen_active: jnp.ndarray,  # [S, L] bool — real entries (False = padding)
    scen_count: jnp.ndarray,  # [S, L] bool — pods whose failure matters (displaced)
    scen_exist_valid: jnp.ndarray,  # [S, E] bool — per-scenario surviving nodes
    scen_vg_counts0: jnp.ndarray,  # [S, NGv, V] i32 — per-scenario topology seeds
    scen_hg_counts0: jnp.ndarray,  # [S, NGh, Sl] i32
    pods: PodTensors,
    pod_tmpl_ok: jnp.ndarray,
    pod_it_allow: jnp.ndarray,
    pod_exist_ok: jnp.ndarray,
    pod_ports: jnp.ndarray,
    pod_port_conf: jnp.ndarray,
    exist: ExistingNodes,
    it: InstanceTypeTensors,
    templates: Templates,
    well_known: jnp.ndarray,
    topo: TopologyTensors,
    pod_topo: PodTopology,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    mv_active: bool = False,
    topo_kids: tuple = (),
    res_cap0=None,
    rid_kid: int = -1,
    res_vid: int = -1,
    res_active: bool = False,
    res_strict: bool = False,
):
    """Batched consolidation what-ifs: S disruption scenarios solved in ONE
    device dispatch (the reference runs SimulateScheduling sequentially per
    candidate set — multinodeconsolidation.go:136-183). Every scenario
    shares the encoded union problem; each gathers its OWN compact pod list
    (scan length L = the largest scenario, not the union size — singleton
    candidate scenarios stay cheap even when the union holds every
    candidate's pods), plus its exclusion mask and topology count seeds.
    vmap vectorizes the whole thing across the batch.

    Returns per-scenario (n_unsched [S] i32 — failures among the pods each
    scenario counts, n_open [S] i32 — new claims opened).
    """

    def one(idx, active, count, exist_valid, vg0, hg0):
        ex = exist._replace(valid=exist_valid)
        tp = topo._replace(vg_counts0=vg0, hg_counts0=hg0)
        valid = pods.valid[idx] & active
        pd = PodTensors(
            reqs=kernels.take_set(pods.reqs, idx),
            strict_reqs=kernels.take_set(pods.strict_reqs, idx),
            requests=pods.requests[idx],
            valid=valid,
        )
        state = initial_state(ex, it, templates, tp, n_claims, pod_ports.shape[1], res_cap0)
        step = _make_step(
            ex, it, templates, well_known, tp, zone_kid, ct_kid, n_claims,
            mv_active, topo_kids, rid_kid, res_vid, res_active, res_strict,
        )
        xs = _xs(
            pd,
            pod_tmpl_ok[idx],
            pod_it_allow[idx],
            pod_exist_ok[idx],
            pod_ports[idx],
            pod_port_conf[idx],
            topo_ops.take_pod_topology(pod_topo, idx),
        )
        state, assignment = jax.lax.scan(step, state, xs)
        n_unsched = jnp.sum(count & valid & (assignment < 0)).astype(jnp.int32)
        return n_unsched, state.n_open

    return jax.vmap(one)(
        scen_pod_idx, scen_active, scen_count, scen_exist_valid, scen_vg_counts0, scen_hg_counts0
    )


def _apply_topo(reqs: ReqSetTensors, upd: jnp.ndarray, touched: jnp.ndarray) -> ReqSetTensors:
    """AND the topology domain masks into candidate requirements: touched
    keys become concrete finite sets (requirements.Add of an In set)."""
    inf = reqs.inf & ~touched[None, :]
    return ReqSetTensors(
        mask=reqs.mask & upd,
        inf=inf,
        excl=reqs.excl & inf,
        gte=jnp.where(inf, reqs.gte, INT_MIN),
        lte=jnp.where(inf, reqs.lte, INT_MAX),
        defined=reqs.defined | touched[None, :],
    )
