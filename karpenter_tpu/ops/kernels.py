"""Batched requirement-set algebra kernels.

Each kernel is pure tensor algebra over ReqSetTensors batches, shaped for
XLA fusion on TPU: boolean masks ride the VPU, reductions over the vocab
axis fuse into the surrounding ops, and all shapes are static.

Semantics are golden-tested against the Python oracle
(karpenter_tpu/scheduling/requirements.py) in tests/test_encode.py:

  has_intersection  <->  Requirement.has_intersection   (requirement.go:220)
  intersects        <->  Requirements.Intersects        (requirements.go:254)
  compatible        <->  Requirements.Compatible        (requirements.go:181)
  intersect_sets    <->  Requirements.Add               (requirements.go:133)
"""

from __future__ import annotations

import jax.numpy as jnp

from karpenter_tpu.ops.encode import ReqSetTensors


def lenient(r: ReqSetTensors) -> jnp.ndarray:
    """[B, K] bool — operator ∈ {NotIn, DoesNotExist}.

    NotIn       = complement with non-empty exclusions (inf & excl)
    DoesNotExist= concrete empty set (~inf & no admissible vocab value)
    """
    any_mask = jnp.any(r.mask, axis=-1)
    return r.defined & ((r.inf & r.excl) | (~r.inf & ~any_mask))


def _pairwise(a: jnp.ndarray, b: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Broadcast [A, ...] and [B, ...] to [A, B, ...]."""
    return a[:, None], b[None, :]


def has_intersection_keys(a: ReqSetTensors, b: ReqSetTensors) -> jnp.ndarray:
    """[A, B, K] bool — per-key non-empty intersection.

    nonempty(A∩B) = any(maskA & maskB)
                  | (infA & infB & max(gte) <= min(lte))
    The finite cases need no bounds check: each side's mask already folds in
    its own bounds, and a value admitted by both satisfies both bounds.
    """
    mask_a, mask_b = _pairwise(a.mask, b.mask)
    hit = jnp.any(mask_a & mask_b, axis=-1)  # [A, B, K]
    inf_a, inf_b = _pairwise(a.inf, b.inf)
    gte = jnp.maximum(*_pairwise(a.gte, b.gte))
    lte = jnp.minimum(*_pairwise(a.lte, b.lte))
    return hit | (inf_a & inf_b & (gte <= lte))


def intersects(a: ReqSetTensors, b: ReqSetTensors) -> jnp.ndarray:
    """[A, B] bool — all shared keys intersect (requirements.go:254-274).

    A failed per-key intersection is forgiven when BOTH operators are in
    {NotIn, DoesNotExist}.
    """
    shared = jnp.logical_and(*_pairwise(a.defined, b.defined))  # [A, B, K]
    both_lenient = jnp.logical_and(*_pairwise(lenient(a), lenient(b)))
    ok = ~shared | has_intersection_keys(a, b) | both_lenient
    return jnp.all(ok, axis=-1)


def compatible(r: ReqSetTensors, q: ReqSetTensors, well_known: jnp.ndarray) -> jnp.ndarray:
    """[A, B] bool — r (node side) can loosely meet q (incoming pod side).

    Custom (non-well-known) keys of q must be defined on r unless q's
    operator is lenient; then all shared keys must intersect
    (requirements.go:181-197).
    """
    q_defined = q.defined[None, :]  # [1, B, K]
    r_defined = r.defined[:, None]  # [A, 1, K]
    q_lenient = lenient(q)[None, :]
    custom_ok = ~q_defined | well_known[None, None, :] | r_defined | q_lenient
    return jnp.all(custom_ok, axis=-1) & intersects(r, q)


def has_intersection_keys_elemwise(a: ReqSetTensors, b: ReqSetTensors) -> jnp.ndarray:
    """[B, K] bool — per-key non-empty intersection over a shared batch."""
    hit = jnp.any(a.mask & b.mask, axis=-1)
    gte = jnp.maximum(a.gte, b.gte)
    lte = jnp.minimum(a.lte, b.lte)
    return hit | (a.inf & b.inf & (gte <= lte))


def intersects_elemwise(a: ReqSetTensors, b: ReqSetTensors) -> jnp.ndarray:
    """[B] bool — intersects() over aligned batches (no pairwise blowup)."""
    shared = a.defined & b.defined
    both_lenient = lenient(a) & lenient(b)
    ok = ~shared | has_intersection_keys_elemwise(a, b) | both_lenient
    return jnp.all(ok, axis=-1)


def compatible_elemwise(a: ReqSetTensors, b: ReqSetTensors, well_known: jnp.ndarray) -> jnp.ndarray:
    """[B] bool — compatible() over aligned batches (a=node side, b=incoming)."""
    custom_ok = ~b.defined | well_known[None, :] | a.defined | lenient(b)
    return jnp.all(custom_ok, axis=-1) & intersects_elemwise(a, b)


def set_eq_rows(a: ReqSetTensors, b: ReqSetTensors) -> jnp.ndarray:
    """[..., K] bool — full-tuple per-key equality over broadcastable
    batches (same mask, complement bit, exclusions, bounds, defined).

    Two equal encodings denote the same requirement, so any intersection
    test against a third set gives identical results — the foundation of
    the solver's incremental tier-2 classification.
    """
    return (
        jnp.all(a.mask == b.mask, axis=-1)
        & (a.inf == b.inf)
        & (a.excl == b.excl)
        & (a.gte == b.gte)
        & (a.lte == b.lte)
        & (a.defined == b.defined)
    )


def per_key_ok_table(a: ReqSetTensors, b: ReqSetTensors) -> jnp.ndarray:
    """[A, K] bool — the per-key term of intersects() between every row of
    a and a SINGLE set b (shape [K, V]): ~shared | nonempty | both_lenient.

    intersects(a_i, b) == all_k(per_key_ok_table(a, b)[i, k]).
    """
    shared = a.defined & b.defined[None, :]
    hit = jnp.any(a.mask & b.mask[None], axis=-1)
    gte = jnp.maximum(a.gte, b.gte[None, :])
    lte = jnp.minimum(a.lte, b.lte[None, :])
    nonempty = hit | (a.inf & b.inf[None, :] & (gte <= lte))
    both_lenient = lenient(a) & lenient(b)[None, :]  # lenient() is shape-generic
    return ~shared | nonempty | both_lenient


def per_key_ok_at(a: ReqSetTensors, b: ReqSetTensors, k: int) -> jnp.ndarray:
    """[B, A] bool — the per-key intersects() term at static key k between
    every row of a ([A, K, V]) and every row of b ([B, K, V]).

    The [B, A] orientation matches the solver's [claims, types] layout.
    """
    shared = b.defined[:, None, k] & a.defined[None, :, k]
    hit = (
        jnp.einsum(
            "bv,av->ba",
            b.mask[:, k, :].astype(jnp.bfloat16),
            a.mask[:, k, :].astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        > 0.0
    )
    gte = jnp.maximum(b.gte[:, None, k], a.gte[None, :, k])
    lte = jnp.minimum(b.lte[:, None, k], a.lte[None, :, k])
    nonempty = hit | (b.inf[:, None, k] & a.inf[None, :, k] & (gte <= lte))
    len_a = lenient(a)[None, :, k]
    len_b = lenient(b)[:, None, k]
    return ~shared | nonempty | (len_a & len_b)


def intersect_sets(a: ReqSetTensors, b: ReqSetTensors) -> ReqSetTensors:
    """Elementwise requirement-set intersection over a shared batch shape.

    The encoding of A∩B: masks AND (own-bounds folded in), complement AND,
    exclusions OR, bounds tighten, defined OR. Cross-bounds filtering of
    finite values is implicit: a vocab value survives only if admitted by
    both masks, hence by both bounds (a value in both masks satisfies both
    sides' own bounds, so it satisfies the tightened bounds).

    Canonicalization mirrors requirement.go:186-213: complement∩complement
    with empty bounds (gte > lte) collapses to concrete DoesNotExist (the
    mask-AND is already empty in that case — see above — so only the
    complement bit needs clearing), and concrete results carry no bounds or
    exclusions. This keeps the derived leniency bit exact.
    """
    from karpenter_tpu.ops.encode import INT_MAX, INT_MIN

    inf0 = a.inf & b.inf
    gte0 = jnp.maximum(a.gte, b.gte)
    lte0 = jnp.minimum(a.lte, b.lte)
    inf = inf0 & (gte0 <= lte0)
    return ReqSetTensors(
        mask=a.mask & b.mask,
        inf=inf,
        excl=(a.excl | b.excl) & inf,
        gte=jnp.where(inf, gte0, INT_MIN),
        lte=jnp.where(inf, lte0, INT_MAX),
        defined=a.defined | b.defined,
    )


def select_set(pred: jnp.ndarray, a: ReqSetTensors, b: ReqSetTensors) -> ReqSetTensors:
    """where(pred, a, b) over every component; pred broadcasts from [B]."""
    def w(x, y):
        p = pred.reshape(pred.shape + (1,) * (x.ndim - pred.ndim))
        return jnp.where(p, x, y)

    return ReqSetTensors(
        mask=w(a.mask, b.mask),
        inf=w(a.inf, b.inf),
        excl=w(a.excl, b.excl),
        gte=w(a.gte, b.gte),
        lte=w(a.lte, b.lte),
        defined=w(a.defined, b.defined),
    )


def take_set(r: ReqSetTensors, idx) -> ReqSetTensors:
    """Index the batch axis (static or traced index)."""
    return ReqSetTensors(
        mask=r.mask[idx],
        inf=r.inf[idx],
        excl=r.excl[idx],
        gte=r.gte[idx],
        lte=r.lte[idx],
        defined=r.defined[idx],
    )


def update_set_at(r: ReqSetTensors, idx, value: ReqSetTensors) -> ReqSetTensors:
    """Functional batch-element update (for scan carries)."""
    return ReqSetTensors(
        mask=r.mask.at[idx].set(value.mask),
        inf=r.inf.at[idx].set(value.inf),
        excl=r.excl.at[idx].set(value.excl),
        gte=r.gte.at[idx].set(value.gte),
        lte=r.lte.at[idx].set(value.lte),
        defined=r.defined.at[idx].set(value.defined),
    )


# ---------------------------------------------------------------------------
# Packed boolean bitsets
# ---------------------------------------------------------------------------
# Host-port and CSI-volume bitsets ([*, NP] / [*, NV] bool) only ever see
# three operations in the solve kernels: conflict tests (any(a & b)),
# union updates (a | b) and per-group popcounts. Packing 32 columns into
# one uint32 lane shrinks both the carry bytes and the per-step VPU work
# by 32x, and each test fuses into a single bitwise op + reduce.

PACK_LANE = 32


def packed_width(n: int) -> int:
    """uint32 lanes needed for an n-column bitset (>= 1)."""
    return max(-(-n // PACK_LANE), 1)


def pack_bool_np(a) -> "np.ndarray":
    """Host-side packer: [..., N] bool -> [..., ceil(N/32)] uint32, column
    j landing in lane j//32 at bit j%32 (little-endian within the lane)."""
    import numpy as np

    a = np.asarray(a, dtype=bool)
    n = a.shape[-1]
    lanes = packed_width(n)
    pad = lanes * PACK_LANE - n
    if pad:
        a = np.concatenate(
            [a, np.zeros(a.shape[:-1] + (pad,), dtype=bool)], axis=-1
        )
    bits = a.reshape(a.shape[:-1] + (lanes, PACK_LANE)).astype(np.uint32)
    weights = (np.uint32(1) << np.arange(PACK_LANE, dtype=np.uint32))
    return (bits * weights).sum(axis=-1, dtype=np.uint32)


def pack_bool(a: jnp.ndarray) -> jnp.ndarray:
    """Device-side twin of pack_bool_np (same lane/bit layout)."""
    n = a.shape[-1]
    lanes = packed_width(n)
    pad = lanes * PACK_LANE - n
    if pad:
        a = jnp.concatenate(
            [a, jnp.zeros(a.shape[:-1] + (pad,), dtype=bool)], axis=-1
        )
    bits = a.reshape(a.shape[:-1] + (lanes, PACK_LANE)).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(PACK_LANE, dtype=jnp.uint32)
    return jnp.sum(bits * weights, axis=-1, dtype=jnp.uint32)


def unpack_bool(packed: jnp.ndarray, n: int) -> jnp.ndarray:
    """[..., L] uint32 -> [..., n] bool (inverse of pack_bool)."""
    lanes = packed.shape[-1]
    shifts = jnp.arange(PACK_LANE, dtype=jnp.uint32)
    bits = (packed[..., :, None] >> shifts) & jnp.uint32(1)
    flat = bits.reshape(packed.shape[:-1] + (lanes * PACK_LANE,))
    return flat[..., :n].astype(bool)


def unpack_bool_np(packed, n: int) -> "np.ndarray":
    """Host-side twin of unpack_bool: [L] uint32 -> [n] bool."""
    import numpy as np

    packed = np.asarray(packed, dtype=np.uint32)
    shifts = np.arange(PACK_LANE, dtype=np.uint32)
    bits = (packed[..., :, None] >> shifts) & np.uint32(1)
    flat = bits.reshape(packed.shape[:-1] + (packed.shape[-1] * PACK_LANE,))
    return flat[..., :n].astype(bool)


def leading_ones(packed, n: int) -> int:
    """Host-side decode of a dp commit-verdict word: the number of
    LEADING set bits among the first n columns of a pack_bool-packed [L]
    uint32 word. The device already prefix-ANDs the per-row verdicts, so
    this is exactly 'how many groups commit'; mixed trailing bits after
    the first zero (which a well-formed word never carries) are ignored
    — decode stops at the first clear bit either way."""
    bits = unpack_bool_np(packed, n)
    k = 0
    for b in bits.reshape(-1)[:n]:
        if not b:
            break
        k += 1
    return k


def pairwise_commit_ok(conflict: jnp.ndarray) -> jnp.ndarray:
    """[DP] bool from a [q, r] conflict matrix — row r passes iff no
    EARLIER row q < r conflicts with it. The shared triangular reduction
    behind every dp-speculative disjointness bit (vg/hg record-vs-apply,
    existing-node touch-vs-viable): conflicts at q >= r are ignored
    because the sequential replay order only ever commits prefixes."""
    n = conflict.shape[0]
    qi = jnp.arange(n, dtype=jnp.int32)
    return jnp.all(~conflict | (qi[:, None] >= qi[None, :]), axis=0)


def packed_conflict(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """[...] bool — any(a & b) over the packed trailing axis (the fused
    test half of every port-conflict / volume-overlap check)."""
    return jnp.any((a & b) != 0, axis=-1)


def packed_any(a: jnp.ndarray) -> jnp.ndarray:
    """[...] bool — any set bit over the packed trailing axis."""
    return jnp.any(a != 0, axis=-1)


def packed_or(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Union update (the fused update half of test-and-update)."""
    return a | b


def packed_count_and(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """[...] int32 — popcount(a & b) over the packed trailing axis; exact
    (integer) twin of the bf16 membership einsum it replaces."""
    import jax

    return jnp.sum(
        jax.lax.population_count(a & b).astype(jnp.int32), axis=-1
    )


def value_allowed(r: ReqSetTensors, key_id: int, value_ids: jnp.ndarray) -> jnp.ndarray:
    """[B, ...] bool — does each set admit vocab value value_ids of key_id?

    Used for offering checks: claim's zone/capacity-type mask indexed by the
    offering's zone/ct vocab ids. Values are always in-vocab by
    construction, so `inf` freedom never applies.
    """
    return r.mask[..., key_id, :][..., value_ids]


def _pack_wire(arrs):
    """Device-side packer (jit-compiled per leaf-shape signature): ravel
    every leaf, concatenate per dtype in first-appearance order, bools
    packbits to bits, everything else bitcasts to bytes, one uint8 wire."""
    import jax

    by_dtype: dict = {}
    for a in arrs:
        by_dtype.setdefault(a.dtype, []).append(a)
    wire_parts = []
    for dtype, parts in by_dtype.items():
        buf = (
            jnp.concatenate([p.ravel() for p in parts])
            if len(parts) > 1
            else parts[0].ravel()
        )
        if dtype == jnp.bool_:
            wire_parts.append(jnp.packbits(buf))
        else:
            wire_parts.append(jax.lax.bitcast_convert_type(buf, jnp.uint8).ravel())
    return (
        jnp.concatenate(wire_parts) if len(wire_parts) > 1 else wire_parts[0]
    )


_PACK_CACHE: dict = {}
_PACK_CACHE_LIMIT = 512

_REPL_CACHE: dict = {}


def _canonicalize_for_wire(arrs):
    """GSPMD workaround: the jitted wire packer (packbits + uint8 bitcast
    over a concat of every leaf) miscompiles on this jax/XLA version when
    ANY input is partitioned over a mesh — fetched integers come back
    scaled by the shard count and bools bit-shift (see the fetch_tree
    regression in tests/test_shard.py). Re-lay every non-fully-replicated
    leaf as replicated on its mesh with ONE cached jitted identity
    dispatch (a plain parameter all-gather the partitioner handles), so
    the packer always compiles over replicated data. Leaves with exotic
    non-NamedSharding layouts fall back to a host fetch and skip the
    packer entirely."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    idx = [
        i
        for i, a in enumerate(arrs)
        if not getattr(a.sharding, "is_fully_replicated", True)
    ]
    if not idx:
        return arrs
    out = list(arrs)
    named = [i for i in idx if isinstance(arrs[i].sharding, NamedSharding)]
    for i in idx:
        if i not in named:
            out[i] = np.asarray(arrs[i])  # exotic layout: host fetch
    if named:
        mesh = arrs[named[0]].sharding.mesh
        sub = [arrs[i] for i in named]
        sig = (mesh, tuple((a.shape, str(a.dtype)) for a in sub))
        rep = _REPL_CACHE.get(sig)
        if rep is None:
            if len(_REPL_CACHE) >= _PACK_CACHE_LIMIT:
                _REPL_CACHE.clear()
            rep = _REPL_CACHE[sig] = jax.jit(
                lambda xs: xs,
                out_shardings=NamedSharding(mesh, PartitionSpec()),
            )
        # drain in-flight producers before enqueueing the all-gather: on
        # the virtual-device CPU backend, two collective-bearing
        # computations in flight can deadlock at their rendezvous (seen
        # as a fetch_tree hang in the dp merge loop); one-at-a-time is
        # also what the fetch semantics already imply — this call IS the
        # sync point
        jax.block_until_ready(sub)
        fixed = rep(sub)
        for j, i in enumerate(named):
            out[i] = fixed[j]
    return out


def fetch_tree(tree, wf_label="wire"):
    """Batched device->host transfer of an arbitrary pytree.

    Per-array `np.asarray` pays a full host<->device round trip PER LEAF —
    ruinous over a tunneled TPU (~70ms/transfer measured). Every device
    leaf is flattened into ONE uint8 wire buffer: bools packbits to bits
    (8x fewer bytes — they dominate decode payloads), other dtypes bitcast
    to bytes. One transfer, host-side re-slicing/unpacking at memory speed.
    The packing itself is jit-compiled per leaf-shape signature — done
    eagerly it costs one tunneled dispatch PER OP, and interleaved solves
    fetch hundreds of leaves. Non-array leaves pass through untouched.

    The blocked host time is attributed to the active round waterfall
    under `wf_label` — callers with a more specific seam (the dp merge
    loops' verdict sync) relabel it so wire vs sync stay separable.
    """
    import time as _time

    from karpenter_tpu.obs import waterfall as _waterfall

    t0 = _time.perf_counter()
    out = _fetch_tree_impl(tree)
    _waterfall.add_current(wf_label, _time.perf_counter() - t0)
    return out


def _fetch_tree_impl(tree):
    import jax
    import numpy as np

    leaves, treedef = jax.tree.flatten(tree)
    dev_idx = [i for i, x in enumerate(leaves) if isinstance(x, jax.Array)]
    out = list(leaves)
    if dev_idx:
        arrs = _canonicalize_for_wire([leaves[i] for i in dev_idx])
        # exotic-layout leaves came back as host arrays already
        pairs = list(zip(dev_idx, arrs))
        for i, a in pairs:
            if not isinstance(a, jax.Array):
                out[i] = a
        dev_idx = [i for i, a in pairs if isinstance(a, jax.Array)]
        if not dev_idx:
            return jax.tree.unflatten(treedef, out)
        arrs = [a for _i, a in pairs if isinstance(a, jax.Array)]
        sig = tuple((a.shape, str(a.dtype)) for a in arrs)
        packer = _PACK_CACHE.get(sig)
        if packer is None:
            if len(_PACK_CACHE) >= _PACK_CACHE_LIMIT:
                _PACK_CACHE.clear()
            packer = _PACK_CACHE[sig] = jax.jit(_pack_wire)
        wire = np.asarray(packer(arrs))
        # group layout mirrors _pack_wire exactly: dtype groups in
        # first-appearance order
        by_dtype: dict = {}
        for i in dev_idx:
            by_dtype.setdefault(np.dtype(leaves[i].dtype), []).append(i)
        woff = 0
        for dtype, idxs in by_dtype.items():
            n = sum(leaves[i].size for i in idxs)
            nbytes = -(-n // 8) if dtype == np.bool_ else n * dtype.itemsize
            seg = wire[woff : woff + nbytes]
            woff += nbytes
            if dtype == np.bool_:
                host = np.unpackbits(seg, count=n).astype(bool)
            else:
                host = seg.view(dtype)[:n]
            off = 0
            for i in idxs:
                p = leaves[i]
                out[i] = host[off : off + p.size].reshape(p.shape)
                off += p.size
    return jax.tree.unflatten(treedef, out)
