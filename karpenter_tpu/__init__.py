"""karpenter-tpu: a TPU-native cluster-autoscaling framework.

A brand-new framework with the capabilities of kubernetes-sigs/karpenter:
node provisioning + disruption whose hot loops (pod -> instance-type
bin-packing, requirements intersection, topology spread, multi-node
consolidation search) run as a batched constraint-satisfaction solver on TPU
via JAX/XLA, while a Python control plane keeps Karpenter's reconciler
semantics (NodePool/NodeClaim objects, cluster-state mirror, lifecycle and
disruption controllers, kwok-style simulated cloud provider).

Layer map (mirrors reference layer map, SURVEY.md section 1):
  models/         API object model (NodePool, NodeClaim, Pod, labels, taints)
  scheduling/     host-side exact-semantics primitives (Requirements algebra)
  cloudprovider/  SPI + InstanceType/Offering + fake/kwok providers
  ops/            JAX tensor encoding + solver kernels (the TPU hot loop)
  parallel/       device-mesh sharding of the solver
  state/          in-memory cluster state mirror
  controllers/    provisioning / disruption / lifecycle reconcilers
  utils/          resource arithmetic, clocks, misc
"""

__version__ = "0.1.0"
