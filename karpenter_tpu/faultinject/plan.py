"""Fault plans: what to break, where, and how often.

A ``FaultPlan`` is a seeded list of ``FaultRule``s. Each rule names a
fault point (``cloud.create``, ``rpc.stream.chunk``, ``solver.dispatch``,
``api.patch``, ...), a mode, and activation gates:

- ``mode="error"``  raises a typed exception (``error`` picks the kind
  from the taxonomy below) at the guarded call site;
- ``mode="latency"`` sleeps ``delay_s`` and lets the call proceed — the
  slow-dependency half of chaos testing;
- repetition rides ``times`` (total fires) / ``skip`` (hits to let pass
  first) / ``p`` (per-hit probability under the PLAN's seeded RNG), so a
  scripted storm like "fail the first 3 launches" or a statistical one
  like "30% of chunk frames" are both one rule.

``match`` filters on the call-site context kwargs by equality
(``{"point": "rpc.stream.chunk", "match": {"index": 2}}`` cuts the
stream at exactly chunk 2), and the point name itself accepts
``fnmatch`` globs (``cloud.*``).

Determinism: the plan owns one ``random.Random(seed)``; two activations
of the same plan against the same call sequence inject the same faults.
That is the property the chaos e2e suite leans on — a faulted run is
reproducible from (plan JSON, workload), no flake hunting.

Error taxonomy (``error`` kinds):

====================  =====================================================
``transient``         cloudprovider.errors.TransientError (retryable)
``throttle``          cloudprovider.errors.ThrottleError (retryable)
``timeout``           cloudprovider.errors.CloudTimeoutError (retryable)
``ice``               cloudprovider.errors.InsufficientCapacityError
``terminal``          cloudprovider.errors.TerminalError
``runtime``           RuntimeError (an unclassified crash, e.g. a device
                      dispatch blowing up mid-solve)
``unavailable``       a grpc.RpcError with code UNAVAILABLE (transport cut)
``exhausted``         a grpc.RpcError with code RESOURCE_EXHAUSTED
====================  =====================================================
"""

from __future__ import annotations

import fnmatch
import json
import os
import random
from dataclasses import dataclass, field
from typing import Optional

ENV_FAULT_PLAN = "KTPU_FAULT_PLAN"


def make_error(kind: str, message: str) -> Exception:
    """Resolve an ``error`` kind to an exception instance (lazy imports:
    the plan module must stay importable from anywhere without dragging
    in grpc or the provider stack)."""
    from karpenter_tpu.cloudprovider import errors as cpe

    if kind == "transient":
        return cpe.TransientError(message)
    if kind == "throttle":
        return cpe.ThrottleError(message)
    if kind == "timeout":
        return cpe.CloudTimeoutError(message)
    if kind == "ice":
        return cpe.InsufficientCapacityError(message)
    if kind == "terminal":
        return cpe.TerminalError(message)
    if kind == "runtime":
        return RuntimeError(message)
    if kind in ("unavailable", "exhausted"):
        from karpenter_tpu.rpc.retry import injected_rpc_error

        return injected_rpc_error(kind, message)
    raise ValueError(f"unknown fault error kind {kind!r}")


@dataclass
class FaultRule:
    point: str
    mode: str = "error"  # error | latency
    error: str = "transient"
    p: float = 1.0
    times: Optional[int] = None  # total fires allowed; None = unlimited
    skip: int = 0  # matching hits to let pass before becoming eligible
    delay_s: float = 0.0
    match: dict = field(default_factory=dict)
    message: str = ""
    # runtime state (reset on plan activation)
    hits: int = 0
    fires: int = 0

    def matches(self, name: str, ctx: dict) -> bool:
        if name != self.point and not fnmatch.fnmatchcase(name, self.point):
            return False
        return all(ctx.get(k) == v for k, v in self.match.items())

    def reset(self) -> None:
        self.hits = 0
        self.fires = 0


@dataclass
class FaultPlan:
    rules: list[FaultRule] = field(default_factory=list)
    seed: int = 0

    @classmethod
    def from_dict(cls, spec: dict) -> "FaultPlan":
        rules = [
            FaultRule(
                point=r["point"],
                mode=r.get("mode", "error"),
                error=r.get("error", "transient"),
                p=float(r.get("p", 1.0)),
                times=r.get("times"),
                skip=int(r.get("skip", 0)),
                delay_s=float(r.get("delay_s", 0.0)),
                match=dict(r.get("match", {})),
                message=r.get("message", ""),
            )
            for r in spec.get("rules", ())
        ]
        return cls(rules=rules, seed=int(spec.get("seed", 0)))

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        """KTPU_FAULT_PLAN: inline JSON, or a path to a JSON file (bare
        path or ``@path``). Empty/unset means no plan."""
        raw = os.environ.get(ENV_FAULT_PLAN, "").strip()
        if not raw:
            return None
        if raw.startswith("@"):
            raw = raw[1:]
        if not raw.lstrip().startswith("{"):
            with open(raw) as f:
                raw = f.read()
        return cls.from_json(raw)

    def rng(self) -> random.Random:
        return random.Random(self.seed)
