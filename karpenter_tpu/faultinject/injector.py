"""The process-global fault injector.

Design constraints mirror the tracer (tracing/tracer.py), in order:

- ~zero cost when disabled (the default): ``FAULT.point(...)`` is one
  attribute check and an immediate return — no allocation, no lock, no
  rule walk. The chaos suite pins this with the same bar as the
  disabled-tracer gate.
- deterministic when enabled: rule eligibility (``p``) draws from the
  PLAN's seeded RNG under a lock, so a given (plan, call sequence) pair
  always injects the same faults.
- observable: every fire counts into ``ktpu_fault_injections_total``
  {point, mode} and stamps ``fault_point`` / ``fault_mode`` attrs on the
  live trace span, so injected faults are visible in ``/debug/traces``
  next to the stage they broke.

Activation: ``FAULT.activate(plan)`` / ``FAULT.deactivate()`` directly,
the ``active_plan`` context manager in tests, or the ``KTPU_FAULT_PLAN``
env var (read once when this module first loads — every guarded module
imports it, so ``python -m ...`` entrypoints need no wiring).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Optional

from karpenter_tpu.faultinject.plan import FaultPlan, FaultRule, make_error


class FaultInjector:
    def __init__(self):
        self.enabled = False
        self._plan: Optional[FaultPlan] = None
        self._rng = None
        self._lock = threading.Lock()
        self.counters: dict[tuple[str, str], int] = {}  # (point, mode) -> fires
        self._env_checked = False

    # -- lifecycle ---------------------------------------------------------

    def activate(self, plan: FaultPlan) -> None:
        with self._lock:
            for rule in plan.rules:
                rule.reset()
            self._plan = plan
            self._rng = plan.rng()
            self.counters = {}
            self.enabled = True

    def deactivate(self) -> None:
        with self._lock:
            self.enabled = False
            self._plan = None
            self._rng = None

    def maybe_activate_from_env(self) -> bool:
        """One-shot env activation (KTPU_FAULT_PLAN); idempotent."""
        if self._env_checked:
            return self.enabled
        self._env_checked = True
        plan = FaultPlan.from_env()
        if plan is not None:
            self.activate(plan)
        return self.enabled

    # -- the guard ---------------------------------------------------------

    def point(self, name: str, /, **ctx) -> None:
        """The fault point every hardened path guards with. Disabled is
        the hot path: one attribute check, immediate return. ``name`` is
        positional-only so ctx kwargs can use any key (including "name",
        e.g. the apiserver seams' object name)."""
        if not self.enabled:
            return
        self._fire(name, ctx)

    def _fire(self, name: str, ctx: dict) -> None:
        rule = None
        with self._lock:
            plan = self._plan
            if plan is None:
                return
            for r in plan.rules:
                if not r.matches(name, ctx):
                    continue
                r.hits += 1
                if r.hits <= r.skip:
                    continue
                if r.times is not None and r.fires >= r.times:
                    continue
                if r.p < 1.0 and self._rng.random() >= r.p:
                    continue
                r.fires += 1
                key = (name, r.mode)
                self.counters[key] = self.counters.get(key, 0) + 1
                rule = r
                break  # first eligible rule wins
        if rule is None:
            return
        self._record(name, rule)
        if rule.mode == "latency":
            time.sleep(rule.delay_s)
            return
        raise make_error(rule.error, rule.message or f"injected fault at {name}")

    @staticmethod
    def _record(name: str, rule: FaultRule) -> None:
        """Metric + trace-span visibility for one fire (outside the plan
        lock: metrics/tracer take their own)."""
        from karpenter_tpu.utils.metrics import FAULT_INJECTIONS

        FAULT_INJECTIONS.inc(point=name, mode=rule.mode)
        from karpenter_tpu.tracing.tracer import TRACER

        cur = TRACER.current()
        if cur is not None:
            cur.set(fault_point=name, fault_mode=rule.mode)

    # -- readout -----------------------------------------------------------

    def fires(self, point: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                n for (p, _), n in self.counters.items() if point is None or p == point
            )


# the process-global injector every guarded site imports
FAULT = FaultInjector()
FAULT.maybe_activate_from_env()


@contextmanager
def active_plan(plan_or_spec):
    """Test fixture: activate a plan (FaultPlan, dict, or JSON string)
    for the block, deactivating on exit even when the block raises."""
    if isinstance(plan_or_spec, str):
        plan = FaultPlan.from_json(plan_or_spec)
    elif isinstance(plan_or_spec, dict):
        plan = FaultPlan.from_dict(plan_or_spec)
    else:
        plan = plan_or_spec
    FAULT.activate(plan)
    try:
        yield FAULT
    finally:
        FAULT.deactivate()
