"""Deterministic, seeded fault injection for the control plane.

The chaos-engineering counterpart of the reference's scripted fake
provider errors (pkg/cloudprovider/fake) generalized into a subsystem:
named fault points guard every hardened hot path (cloud launches, the
SolveStream wire, device dispatch, apiserver writes), a seeded
``FaultPlan`` decides which crossings break and how, and every injected
fault is counted (``ktpu_fault_injections_total``) and stamped onto the
live trace. ``tests/test_faults.py`` drives the seeded chaos scenarios;
``KTPU_FAULT_PLAN`` activates a plan in any entrypoint.

Registered fault points (grep ``FAULT.point`` for the live list):

=====================  ====================================================
``cloud.create``       provider launch, after offering resolution (the ctx
                       carries instance_type/zone/capacity_type so an
                       injected ICE blackouts the real offering)
``cloud.delete``       provider instance termination
``rpc.solve.send``     client-side, before a Solve/SolveStream crossing
``rpc.stream.chunk``   client-side, per received chunk frame (``index``)
``solver.dispatch``    top of the device dispatch inside a solve
``api.create``         ObjectStore.create (apiserver POST analog)
``api.patch``          ObjectStore.update (apiserver PATCH analog)
``api.delete``         ObjectStore.delete (apiserver DELETE analog)
``solver.resident.apply``  resident delta apply, ``stage`` = ``begin``
                       (before the retract pass) or ``mid`` (between
                       retract and append — a fault here proves the
                       transactional invalidate path; ctx carries
                       ``arrivals``/``retracts``)
``solver.merge.commit``  dp-speculative shard merge, just before the
                       commit decision (ctx: ``segments``/``opened``)
``rpc.session.evict``  server-side resident-session registry lookup; a
                       FIRING rule here forcibly evicts the session (the
                       raised error is swallowed), so the client's next
                       Solve observes a typed SESSION_LOST
=====================  ====================================================
"""

from karpenter_tpu.faultinject.injector import FAULT, FaultInjector, active_plan
from karpenter_tpu.faultinject.plan import (
    ENV_FAULT_PLAN,
    FaultPlan,
    FaultRule,
    make_error,
)

__all__ = [
    "FAULT",
    "FaultInjector",
    "FaultPlan",
    "FaultRule",
    "ENV_FAULT_PLAN",
    "active_plan",
    "make_error",
]
