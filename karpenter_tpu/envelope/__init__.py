"""Resource-envelope harness: host RSS/CPU sampling + scenario e2e suite.

The "other half" of the performance story (reference
test/suites/performance): throughput is measured by bench.py, the
control plane's resource footprint by this package. Three parts:

- sampler.py — background thread reading /proc/self/statm RSS +
  getrusage CPU per named stage (P50/P95/max RSS, CPU-seconds,
  average cores), exported as ktpu_host_rss_bytes /
  ktpu_cpu_seconds_total gauges and the /debug/envelope endpoint
- spec.py — Envelope(max_wall_s, max_rss_mb_p95, max_cpu_cores)
  assertions mirroring thresholds.go
- scenarios.py — scale-out / consolidation / drift / hostname-spread
  e2e scenarios on the kwok provider + fake clock
"""

from karpenter_tpu.envelope.sampler import (
    ResourceSampler,
    StageStats,
    measured,
    percentile,
    read_cpu_seconds,
    read_rss_bytes,
)
from karpenter_tpu.envelope.scenarios import SCENARIOS, ScenarioResult, run_scenario
from karpenter_tpu.envelope.spec import Envelope, EnvelopeExceeded

__all__ = [
    "SCENARIOS",
    "Envelope",
    "EnvelopeExceeded",
    "ResourceSampler",
    "ScenarioResult",
    "StageStats",
    "measured",
    "percentile",
    "read_cpu_seconds",
    "read_rss_bytes",
    "run_scenario",
]
