"""Resource envelopes: declarative wall/RSS/CPU ceilings per scenario.

Counterpart of the reference e2e performance thresholds
(test/suites/performance/thresholds.go:28-43 and basic_test.go:50-81):
scale-out must finish < 2 min at < 260 MB P95 RSS and < 0.5 average
cores, with separate envelopes for consolidation, drift, hostname-spread
and do-not-disrupt. There the measured process is a dedicated controller
pod scraped from outside; here the control plane, solver client and test
harness share one Python process that also carries the JAX runtime, so
the RSS ceiling is expressed as GROWTH of the P95 RSS above a baseline
taken at scenario start — an absolute ceiling would mostly measure how
much of libtpu/XLA happened to be resident before the scenario ran.

CPU has two ceilings: ``max_cpu_cores`` bounds average concurrency
(cpu_s / wall_s — a busy-wait or runaway thread pool fails it even when
the wall stays inside budget) and the optional ``max_cpu_s`` bounds total
compute. Ceilings are deliberately set with headroom over measured
reality and ratcheted down over rounds, the same discipline
tests/test_perf_gate.py applies to throughput.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from karpenter_tpu.envelope.sampler import StageStats


class EnvelopeExceeded(AssertionError):
    """A scenario left its resource envelope; message lists every breach."""


@dataclass(frozen=True)
class Envelope:
    """Ceilings for one scenario (thresholds.go rows)."""

    max_wall_s: float
    max_rss_mb_p95: float  # P95 RSS growth above the scenario-start baseline
    max_cpu_cores: float  # average concurrency over the scenario
    max_cpu_s: Optional[float] = None

    def violations(self, stats: StageStats, baseline_rss_mb: float = 0.0) -> list[str]:
        out = []
        if stats.wall_s > self.max_wall_s:
            out.append(f"wall {stats.wall_s:.2f}s > {self.max_wall_s}s")
        growth = stats.rss_mb_p95 - baseline_rss_mb
        if growth > self.max_rss_mb_p95:
            out.append(
                f"P95 RSS growth {growth:.1f}MB > {self.max_rss_mb_p95}MB "
                f"(P95 {stats.rss_mb_p95:.1f}MB over baseline {baseline_rss_mb:.1f}MB)"
            )
        if stats.avg_cores > self.max_cpu_cores:
            out.append(f"avg cores {stats.avg_cores:.2f} > {self.max_cpu_cores}")
        if self.max_cpu_s is not None and stats.cpu_s > self.max_cpu_s:
            out.append(f"cpu {stats.cpu_s:.2f}s > {self.max_cpu_s}s")
        return out

    def check(self, stats: StageStats, baseline_rss_mb: float = 0.0) -> None:
        breaches = self.violations(stats, baseline_rss_mb)
        if breaches:
            raise EnvelopeExceeded(
                f"scenario {stats.name!r} out of envelope: " + "; ".join(breaches)
            )
