"""Host resource sampler: background RSS/CPU series per named stage.

The measurement half of the resource-envelope subsystem (the analog of
the reference e2e performance suite's controller memory/CPU thresholds,
test/suites/performance/thresholds.go:28-43: the suite scrapes the
controller pod's RSS and CPU around each scenario and asserts P95/avg
ceilings). Here the control plane, solver client and harness share one
process, so the sampler reads the process's own counters:

- RSS from ``/proc/self/statm`` (live VmRSS, NOT the ru_maxrss high-water
  mark — a one-time XLA compile spike would make every later assertion
  vacuous; same rationale as testing.measure_resources)
- CPU from ``resource.getrusage(RUSAGE_SELF)`` user+system time, which
  covers ALL threads (XLA's thread pool included), unlike
  time.process_time on some platforms

A daemon thread ticks every ``interval_s`` (default 100 ms) and appends
the reading to every currently-open stage, so P50/P95/max RSS and
average-cores come from a real time series rather than two endpoint
snapshots. Stages are re-entrant and nest freely::

    sampler = ResourceSampler()
    with sampler:                       # or .start()/.stop()
        with sampler.stage("encode"):
            ...
        with sampler.stage("solve"):
            with sampler.stage("solve/device"):
                ...
    sampler.stats["solve"].rss_mb_p95

Every tick also publishes ``ktpu_host_rss_bytes`` / ``ktpu_cpu_seconds_total``
through utils/metrics.py, and the last-started sampler registers itself as
the process-global one the ``--enable-profiling`` ``/debug/envelope``
endpoint snapshots (utils/runtime.py).

Optional ``trace_python_alloc=True`` adds a tracemalloc peak per stage —
~2-4x slower, so it stays behind the flag (the reference equivalently
keeps pprof heap profiles behind --enable-profiling).
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Optional

DEFAULT_INTERVAL_S = 0.1

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def read_rss_bytes() -> int:
    """Live resident set size (VmRSS) of this process in bytes."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except OSError:  # non-Linux: the high-water mark is all there is
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def read_cpu_seconds() -> float:
    """User + system CPU seconds across ALL threads of this process."""
    import resource

    ru = resource.getrusage(resource.RUSAGE_SELF)
    return ru.ru_utime + ru.ru_stime


def percentile(series, q: float) -> float:
    """Nearest-rank percentile (the reference thresholds use P95 the same
    way: the sample at ceil(q*n), no interpolation — thresholds.go:36)."""
    values = sorted(series)
    if not values:
        return math.nan
    rank = max(1, math.ceil(q * len(values)))
    return float(values[min(rank, len(values)) - 1])


@dataclass
class StageStats:
    """One closed stage's resource envelope measurements."""

    name: str
    wall_s: float
    cpu_s: float
    avg_cores: float  # cpu_s / wall_s
    rss_mb_p50: float
    rss_mb_p95: float
    rss_mb_max: float
    samples: int  # RSS readings backing the percentiles
    tracemalloc_peak_mb: Optional[float] = None

    def as_dict(self) -> dict:
        out = {
            "wall_s": round(self.wall_s, 4),
            "cpu_s": round(self.cpu_s, 4),
            "avg_cores": round(self.avg_cores, 3),
            "rss_mb_p50": round(self.rss_mb_p50, 1),
            "rss_mb_p95": round(self.rss_mb_p95, 1),
            "rss_mb_max": round(self.rss_mb_max, 1),
            "samples": self.samples,
        }
        if self.tracemalloc_peak_mb is not None:
            out["tracemalloc_peak_mb"] = round(self.tracemalloc_peak_mb, 2)
        return out


@dataclass
class _OpenStage:
    name: str
    start_wall: float
    start_cpu: float
    rss_bytes: list[int] = field(default_factory=list)


# last-started sampler; the /debug/envelope endpoint snapshots it
_GLOBAL: Optional["ResourceSampler"] = None
_GLOBAL_LOCK = threading.Lock()


def global_sampler() -> Optional["ResourceSampler"]:
    with _GLOBAL_LOCK:
        return _GLOBAL


class ResourceSampler:
    def __init__(
        self,
        interval_s: float = DEFAULT_INTERVAL_S,
        trace_python_alloc: bool = False,
        series_capacity: int = 1200,
    ):
        self.interval_s = interval_s
        self.trace_python_alloc = trace_python_alloc
        self.stats: dict[str, StageStats] = {}  # last closed run per name
        # cumulative CPU seconds the sampling itself consumed (thread CPU
        # time, not wall: a tick blocked on the GIL behind a busy workload
        # is time the WORKLOAD ran, not sampling overhead)
        self.overhead_s = 0.0
        self._lock = threading.Lock()
        self._open: list[_OpenStage] = []  # stack order; all receive ticks
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # recent (monotonic_t, rss_bytes, cpu_s) for the live endpoint
        self.series: deque[tuple[float, int, float]] = deque(maxlen=series_capacity)
        # one persistent handle, seek(0)+read per tick (procfs allows it):
        # keeps the tick at two syscalls instead of open/read/close
        try:
            self._statm = open("/proc/self/statm")
        except OSError:
            self._statm = None
        from karpenter_tpu.utils import metrics as _metrics  # bind once

        self._metrics = _metrics

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ResourceSampler":
        global _GLOBAL
        if self._thread is not None:
            return self
        if self.trace_python_alloc:
            import tracemalloc

            if not tracemalloc.is_tracing():
                tracemalloc.start()
        if self._statm is None:
            try:
                self._statm = open("/proc/self/statm")
            except OSError:
                pass
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="ktpu-envelope-sampler", daemon=True
        )
        self._thread.start()
        with _GLOBAL_LOCK:
            _GLOBAL = self
        return self

    def stop(self) -> None:
        global _GLOBAL
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        if self._statm is not None:
            self._statm.close()
            self._statm = None
        with _GLOBAL_LOCK:
            if _GLOBAL is self:
                _GLOBAL = None

    def __enter__(self) -> "ResourceSampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the tick ----------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()

    def tick(self) -> None:
        """One sample; public so threadless tests can drive it directly."""
        c0 = time.thread_time()
        now = time.perf_counter()
        if self._statm is not None:
            self._statm.seek(0)
            rss = int(self._statm.read().split()[1]) * _PAGE_SIZE
        else:
            rss = read_rss_bytes()
        cpu = read_cpu_seconds()
        with self._lock:
            self.series.append((now, rss, cpu))
            for stage in self._open:
                stage.rss_bytes.append(rss)
        self._metrics.HOST_RSS_BYTES.set(float(rss))
        self._metrics.HOST_CPU_SECONDS.set(cpu)
        self.overhead_s += time.thread_time() - c0

    # -- stages ------------------------------------------------------------

    @contextmanager
    def stage(self, name: str):
        if self.trace_python_alloc:
            import tracemalloc

            if not tracemalloc.is_tracing():
                tracemalloc.start()
            tracemalloc.reset_peak()
        record = _OpenStage(
            name=name,
            start_wall=time.perf_counter(),
            start_cpu=read_cpu_seconds(),
            rss_bytes=[read_rss_bytes()],
        )
        with self._lock:
            self._open.append(record)
        try:
            yield self
        finally:
            end_wall = time.perf_counter()
            end_cpu = read_cpu_seconds()
            record.rss_bytes.append(read_rss_bytes())
            with self._lock:
                self._open.remove(record)
            peak_mb = None
            if self.trace_python_alloc:
                import tracemalloc

                peak_mb = tracemalloc.get_traced_memory()[1] / 2**20
            self.stats[name] = _close(record, end_wall, end_cpu, peak_mb)

    def snapshot(self) -> dict:
        """Live view for the /debug/envelope endpoint."""
        with self._lock:
            series = list(self.series)[-120:]
            open_names = [s.name for s in self._open]
        return {
            "interval_s": self.interval_s,
            "overhead_s": round(self.overhead_s, 6),
            "rss_mb": round(read_rss_bytes() / 2**20, 1),
            "cpu_s": round(read_cpu_seconds(), 3),
            "open_stages": open_names,
            "stages": {name: st.as_dict() for name, st in self.stats.items()},
            "series": [
                {"t": round(t, 3), "rss_mb": round(r / 2**20, 1), "cpu_s": round(c, 3)}
                for t, r, c in series
            ],
        }


def _close(record: _OpenStage, end_wall: float, end_cpu: float, peak_mb) -> StageStats:
    wall = max(end_wall - record.start_wall, 1e-9)
    cpu = max(end_cpu - record.start_cpu, 0.0)
    rss_mb = [b / 2**20 for b in record.rss_bytes]
    return StageStats(
        name=record.name,
        wall_s=wall,
        cpu_s=cpu,
        avg_cores=cpu / wall,
        rss_mb_p50=percentile(rss_mb, 0.50),
        rss_mb_p95=percentile(rss_mb, 0.95),
        rss_mb_max=max(rss_mb),
        samples=len(rss_mb),
        tracemalloc_peak_mb=peak_mb,
    )


@contextmanager
def measured(
    result: dict,
    stage: str = "stage",
    sampler: Optional[ResourceSampler] = None,
    interval_s: float = 0.05,
):
    """Run a block under a stage and fill ``result`` with the envelope
    fields every bench stage dict must carry: ``host_rss_mb`` (P95 of the
    absolute RSS series over the stage) and ``cpu_s`` (CPU-seconds spent in
    it), plus ``avg_cores``. Borrows ``sampler`` when given; otherwise
    spins up (and tears down) a transient one."""
    own = sampler is None
    s = sampler if sampler is not None else ResourceSampler(interval_s=interval_s)
    if own:
        s.start()
    try:
        with s.stage(stage):
            yield result
    finally:
        if own:
            s.stop()
        stats = s.stats.get(stage)
        if stats is not None:
            result["host_rss_mb"] = round(stats.rss_mb_p95, 1)
            result["cpu_s"] = round(stats.cpu_s, 3)
            result["avg_cores"] = round(stats.avg_cores, 3)
