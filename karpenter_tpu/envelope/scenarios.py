"""Declarative e2e scenarios with resource-envelope assertions.

Counterpart of the reference e2e performance suite
(test/suites/performance/basic_test.go:50-81): scale-out, consolidation,
drift and hostname-spread each run end-to-end and must land inside an
Envelope (wall, P95 RSS, CPU). The reference drives a real cluster via
KWOK nodes and scrapes the controller pod; here the same lifecycle runs
through the in-process harness — kwok provider + fake clock + Manager +
KubeSchedulerSim (controllers/manager.py) — while the envelope sampler
watches this process's RSS/CPU.

The fake clock means wall-clock here is pure compute (solves, reconciles,
binds), not the reference's instance-boot waits, so the wall ceilings are
tighter than the reference's 2 min while the RSS/CPU ceilings carry the
JAX-runtime context (spec.py explains the growth-above-baseline form).

Usage::

    from karpenter_tpu.envelope import run_scenario
    result = run_scenario("scale_out")      # asserts the default envelope
    result.stats.rss_mb_p95, result.detail["nodes"]
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from karpenter_tpu.envelope.sampler import ResourceSampler, StageStats, read_rss_bytes
from karpenter_tpu.envelope.spec import Envelope


def _harness(catalog_size: int = 64, consolidate_after: float = 0.0):
    """The kwok + fake-clock stack every scenario runs on (the same shape
    tests/test_disruption.py builds): one pool, open disruption budgets,
    pinned on-demand so consolidation replacements aren't spot-gated."""
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
    from karpenter_tpu.controllers.manager import Manager
    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.nodepool import Budget, NodePool
    from karpenter_tpu.state.store import ObjectStore
    from karpenter_tpu.utils.clock import FakeClock

    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=instance_types(catalog_size))
    mgr = Manager(store, cloud, clock)
    pool = NodePool()
    pool.metadata.name = "default"
    pool.spec.disruption.consolidate_after_seconds = consolidate_after
    pool.spec.disruption.consolidation_policy = "WhenEmptyOrUnderutilized"
    pool.spec.disruption.budgets = [Budget(nodes="100%")]
    pool.spec.template.spec.requirements = [
        {
            "key": l.CAPACITY_TYPE_LABEL_KEY,
            "operator": "In",
            "values": [l.CAPACITY_TYPE_ON_DEMAND],
        }
    ]
    store.create(ObjectStore.NODEPOOLS, pool)
    return clock, store, cloud, mgr


def _settle(mgr, store, cloud, rounds: int = 4) -> None:
    from karpenter_tpu.controllers.manager import KubeSchedulerSim

    for _ in range(rounds):
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        if all(p.spec.node_name for p in store.pods()):
            break
        mgr.batcher.trigger()


def _provision(mgr, store, cloud, pods) -> None:
    from karpenter_tpu.state.store import ObjectStore

    for p in pods:
        store.create(ObjectStore.PODS, p)
    _settle(mgr, store, cloud)


def _delete_pods(store, mgr, predicate) -> None:
    from karpenter_tpu.state.store import ObjectStore

    for pod in list(store.pods()):
        if predicate(pod):
            pod.status.phase = "Succeeded"
            store.update(ObjectStore.PODS, pod)
            store.delete(ObjectStore.PODS, pod.name)
    mgr.run_until_idle()


def _disruption_cycles(clock, store, cloud, mgr, polls: int = 8, step: float = 20.0):
    """Poll disruption through its 15s validation window, re-binding the
    churn each round (the loop every disruption e2e drives)."""
    from karpenter_tpu.controllers.manager import KubeSchedulerSim

    executed = None
    for _ in range(polls):
        cmd = mgr.run_disruption_once()
        executed = executed or cmd
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        clock.step(step)
    return executed


# -- scenarios (basic_test.go:50-81 rows) ------------------------------------


def scale_out(n_pods: int = 500) -> dict:
    """500 pending pods -> nodes launched, registered, Ready, every pod
    bound (basic_test.go:50-59 'scale out')."""
    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.pod import make_pod

    clock, store, cloud, mgr = _harness(catalog_size=64)
    zones = ("test-zone-1", "test-zone-2", "test-zone-3", "test-zone-4")
    pods = []
    for i in range(n_pods):
        sel = {}
        if i % 5 == 1:
            sel[l.LABEL_TOPOLOGY_ZONE] = zones[i % len(zones)]
        if i % 5 == 3:
            sel[l.CAPACITY_TYPE_LABEL_KEY] = l.CAPACITY_TYPE_ON_DEMAND
        pods.append(
            make_pod(
                f"so-{i}",
                cpu=(0.25, 0.5, 1.0, 2.0)[i % 4],
                memory=("512Mi", "1Gi", "2Gi")[i % 3],
                node_selector=sel,
            )
        )
    _provision(mgr, store, cloud, pods)
    bound = sum(1 for p in store.pods() if p.spec.node_name)
    assert bound == n_pods, f"only {bound}/{n_pods} pods bound"
    ready = sum(1 for n in store.nodes() if n.status.ready)
    assert ready == len(store.nodes()) and ready > 0
    return {"pods": n_pods, "nodes": ready}


def consolidation(n_pods: int = 24) -> dict:
    """Provision, finish half the workload, consolidate: capacity must
    shrink while every survivor stays bound (basic_test.go 'consolidation',
    multi-node first per the method cascade)."""
    from karpenter_tpu.models.pod import make_pod

    clock, store, cloud, mgr = _harness(catalog_size=64)
    survivors = {f"co-{i}" for i in range(n_pods // 2)}
    _provision(
        mgr, store, cloud,
        [make_pod(f"co-{i}", cpu=1.5, memory="1Gi") for i in range(n_pods)],
    )
    cpu_before = sum(n.status.capacity["cpu"] for n in store.nodes())
    _delete_pods(store, mgr, lambda p: p.name not in survivors)
    clock.step(60.0)
    executed = _disruption_cycles(clock, store, cloud, mgr)
    assert executed is not None, "no consolidation command produced"
    _settle(mgr, store, cloud)
    cpu_after = sum(n.status.capacity["cpu"] for n in store.nodes())
    assert cpu_after < cpu_before, "no capacity reclaimed"
    stranded = [p.name for p in store.pods() if not p.spec.node_name]
    assert not stranded, f"pods stranded after consolidation: {stranded}"
    return {
        "pods": len(survivors),
        "cpu_before": cpu_before,
        "cpu_after": cpu_after,
        "command_reason": executed.reason,
    }


def drift(n_pods: int = 6) -> dict:
    """Stamp claims Drifted via a template change and replace them: every
    original claim gone, every pod re-bound (basic_test.go 'drift')."""
    from karpenter_tpu.models.pod import make_pod
    from karpenter_tpu.state.store import ObjectStore

    clock, store, cloud, mgr = _harness(catalog_size=32)
    _provision(
        mgr, store, cloud,
        [make_pod(f"dr-{i}", cpu=1.0) for i in range(n_pods)],
    )
    original = {c.name for c in store.nodeclaims()}
    pool = store.get(ObjectStore.NODEPOOLS, "default")
    pool.spec.template.labels["drift-round"] = "r2"
    store.update(ObjectStore.NODEPOOLS, pool)
    marked = mgr.mark_drift()
    assert marked >= 1, "template change marked nothing Drifted"
    clock.step(30.0)
    replaced = None
    for _ in range(6 * max(1, len(original))):
        replaced = _disruption_cycles(clock, store, cloud, mgr, polls=2) or replaced
        mgr.mark_drift()  # new claims get checked too
        if not original & {c.name for c in store.nodeclaims()}:
            break
    remaining = original & {c.name for c in store.nodeclaims()}
    assert not remaining, f"drifted claims never replaced: {sorted(remaining)}"
    _settle(mgr, store, cloud)
    stranded = [p.name for p in store.pods() if not p.spec.node_name]
    assert not stranded, f"pods stranded after drift: {stranded}"
    return {"pods": n_pods, "claims_replaced": len(original), "marked": marked}


def hostname_spread(n_pods: int = 20) -> dict:
    """Hostname topology-spread at maxSkew 1: pods land one-per-domain-step
    across distinct nodes (basic_test.go 'hostname topology spread')."""
    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod
    from karpenter_tpu.state.store import ObjectStore

    clock, store, cloud, mgr = _harness(catalog_size=32)
    pods = []
    for i in range(n_pods):
        p = make_pod(f"hs-{i}", cpu=0.5)
        p.metadata.labels = {"spread": "host"}
        p.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=l.LABEL_HOSTNAME,
                label_selector={"spread": "host"},
            )
        ]
        pods.append(p)
    _provision(mgr, store, cloud, pods)
    bound = [p for p in store.pods() if p.spec.node_name]
    assert len(bound) == n_pods, f"only {len(bound)}/{n_pods} bound"
    per_node: dict[str, int] = {}
    for p in bound:
        per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
    skew = max(per_node.values()) - min(per_node.values())
    assert skew <= 1, f"hostname skew {skew} > 1: {per_node}"
    return {"pods": n_pods, "nodes": len(per_node), "skew": skew}


def training_storm(n_gangs: int = 3, gang_size: int = 4, n_singles: int = 10) -> dict:
    """Training-job storm (ISSUE 6): all-or-nothing gangs mixed with
    singleton pods in one batch. Every gang lands complete on dedicated
    slice hosts (a slice never shares a node with singletons), every
    singleton binds, and no gang is ever observably part-bound."""
    from karpenter_tpu.gang import gang_of, make_gang_pods, partially_bound_gangs
    from karpenter_tpu.models.pod import make_pod

    clock, store, cloud, mgr = _harness(catalog_size=64)
    pods = []
    for gi in range(n_gangs):
        pods.extend(make_gang_pods(f"storm-{gi}", gang_size, cpu=1.5))
    for i in range(n_singles):
        pods.append(make_pod(f"ts-{i}", cpu=(0.25, 0.5, 1.0)[i % 3]))
    _provision(mgr, store, cloud, pods)
    partial = partially_bound_gangs(store.pods())
    assert not partial, f"partially bound gangs: {partial}"
    stranded = [p.name for p in store.pods() if not p.spec.node_name]
    assert not stranded, f"stranded pods: {stranded}"
    # slice dedication: every node hosting a gang pod hosts ONLY that gang
    gang_nodes: dict[str, str] = {}
    for p in store.pods():
        parsed = gang_of(p)
        if parsed is not None:
            key = gang_nodes.setdefault(p.spec.node_name, parsed[0])
            assert key == parsed[0], (
                f"two gangs share slice host {p.spec.node_name}"
            )
    for p in store.pods():
        if gang_of(p) is None:
            assert p.spec.node_name not in gang_nodes, (
                f"singleton {p.name} shares slice host {p.spec.node_name}"
            )
    return {
        "gangs": n_gangs,
        "gang_pods": n_gangs * gang_size,
        "singles": n_singles,
        "slice_hosts": len(gang_nodes),
        "nodes": len(store.nodes()),
    }


# -- registry + runner --------------------------------------------------------

# Default envelopes, calibrated on the 8-device CPU-mesh CI harness
# (r6 measurements: scale_out 2.8s wall / +40MB P95 growth / 0.99 avg
# cores; consolidation 4.8s / +83MB; drift 2.2s / +43MB; hostname_spread
# 2.7s / +148MB incl. first-compile). Ceilings carry ~6-10x headroom for
# slower CI and cold-compile variance, and ratchet down over rounds the
# way the perf gates do. The reference rows these mirror: scale-out
# < 2 min / < 260MB P95 / < 0.5 cores (basic_test.go:50-59) — its wall
# covers real instance boots and its process is an otherwise-idle
# controller pod, hence the different shapes of the same discipline.
_CORES_CEILING = 6.0  # measured ~1.0: a busy-wait/thread-leak tripwire

SCENARIOS: dict[str, tuple[Callable[[], dict], Envelope]] = {
    "scale_out": (
        scale_out,
        Envelope(max_wall_s=90.0, max_rss_mb_p95=600.0, max_cpu_cores=_CORES_CEILING),
    ),
    "consolidation": (
        consolidation,
        Envelope(max_wall_s=60.0, max_rss_mb_p95=600.0, max_cpu_cores=_CORES_CEILING),
    ),
    "drift": (
        drift,
        Envelope(max_wall_s=60.0, max_rss_mb_p95=500.0, max_cpu_cores=_CORES_CEILING),
    ),
    "hostname_spread": (
        hostname_spread,
        Envelope(max_wall_s=60.0, max_rss_mb_p95=600.0, max_cpu_cores=_CORES_CEILING),
    ),
    "training_storm": (
        training_storm,
        Envelope(max_wall_s=90.0, max_rss_mb_p95=600.0, max_cpu_cores=_CORES_CEILING),
    ),
}


@dataclass
class ScenarioResult:
    name: str
    detail: dict
    stats: StageStats
    envelope: Envelope
    baseline_rss_mb: float

    def as_dict(self) -> dict:
        return {
            "detail": self.detail,
            "baseline_rss_mb": round(self.baseline_rss_mb, 1),
            **self.stats.as_dict(),
        }


def run_scenario(
    name: str,
    envelope: Optional[Envelope] = None,
    sampler: Optional[ResourceSampler] = None,
    check: bool = True,
    **scenario_kwargs,
) -> ScenarioResult:
    """Run one named scenario under the sampler and (by default) assert its
    envelope. Raises EnvelopeExceeded on breach."""
    fn, default_env = SCENARIOS[name]
    env = envelope or default_env
    own = sampler is None
    s = sampler if sampler is not None else ResourceSampler(interval_s=0.05)
    baseline_mb = read_rss_bytes() / 2**20
    if own:
        s.start()
    try:
        with s.stage(name):
            detail = fn(**scenario_kwargs)
    finally:
        if own:
            s.stop()
    stats = s.stats[name]
    result = ScenarioResult(
        name=name,
        detail=detail,
        stats=stats,
        envelope=env,
        baseline_rss_mb=baseline_mb,
    )
    if check:
        env.check(stats, baseline_rss_mb=baseline_mb)
    return result
