"""Shadow-audit verdicts: compare, count, and escalate divergences.

The audit itself lives at each fast-path call site (the scheduler knows
how to run its own exact twin); this module owns what every site shares —
the canonical result signature the resident audit compares, the verdict
bookkeeping (``ktpu_guard_audits_total``, an in-process audit log the
replay harness reads), and the divergence escalation: repro bundle to
``KTPU_GUARD_DIR``, a Warning event on the recorder (when the operator
wired one in), and the per-path quarantine trip.
"""

from __future__ import annotations

import threading
from typing import Optional

from karpenter_tpu.guard import bundle as bundle_mod
from karpenter_tpu.guard import config
from karpenter_tpu.guard.quarantine import QUARANTINE
from karpenter_tpu.utils.logging import get_logger
from karpenter_tpu.utils.metrics import GUARD_AUDITS

_LOG_LOCK = threading.Lock()
#: every audit verdict this process, newest last: {path, verdict, reason}
AUDIT_LOG: list = []
#: verdict fan-out: fleet members subscribe to rebroadcast audit results
AUDIT_LISTENERS: list = []


def add_audit_listener(fn) -> None:
    with _LOG_LOCK:
        if fn not in AUDIT_LISTENERS:
            AUDIT_LISTENERS.append(fn)


def remove_audit_listener(fn) -> None:
    with _LOG_LOCK:
        if fn in AUDIT_LISTENERS:
            AUDIT_LISTENERS.remove(fn)


def reset_log() -> None:
    with _LOG_LOCK:
        AUDIT_LOG.clear()


def divergences(path: Optional[str] = None) -> list:
    with _LOG_LOCK:
        return [
            rec
            for rec in AUDIT_LOG
            if rec["verdict"] == "divergence" and (path is None or rec["path"] == path)
        ]


def result_signature(result) -> tuple:
    """Canonical, comparison-stable form of a SchedulingResult.

    Bit-exactness is the contract the fast paths prove, so nothing is
    rounded: two results are equal iff every claim (slot, hostname,
    template, instance-type set, pod order, resource usage), every
    assignment, every existing-node binding, and every unschedulable
    verdict match exactly.
    """
    claims = tuple(
        sorted(
            (
                int(c.slot),
                c.hostname,
                c.template.nodepool_name,
                tuple(sorted(it.name for it in c.instance_types)),
                tuple(p.uid for p in c.pods),
                tuple(sorted((k, float(v)) for k, v in c.used.items())),
            )
            for c in result.claims
        )
    )
    existing = tuple(
        sorted(
            (n.name, tuple(sorted(p.uid for p in n.pods)))
            for n in result.existing
        )
    )
    return (
        claims,
        tuple(sorted((u, int(s)) for u, s in result.assignments.items())),
        tuple(sorted(result.existing_assignments.items())),
        existing,
        tuple(sorted((p.uid, r) for p, r in result.unschedulable)),
    )


def record_audit(path: str, verdict: str, reason: str = "") -> None:
    GUARD_AUDITS.inc(path=path, verdict=verdict)
    with _LOG_LOCK:
        AUDIT_LOG.append({"path": path, "verdict": verdict, "reason": reason})
        listeners = list(AUDIT_LISTENERS)
    for fn in listeners:
        try:
            fn(path, verdict, reason)
        except Exception:  # a broken bus must not mask the verdict
            pass


def handle_divergence(
    path: str,
    reason: str,
    sched,
    pods_by_uid: dict,
    rounds: list,
    existing_nodes=(),
    detail: Optional[dict] = None,
) -> Optional[str]:
    """A fast path disagreed with its exact twin: count it, capsule it,
    quarantine it. Returns the bundle file path (None when KTPU_GUARD_DIR
    is unset or the write fails — escalation still happens)."""
    record_audit(path, "divergence", reason)
    log = get_logger().with_values(controller="guard")
    bundle_path = None
    gdir = config.guard_dir()
    if gdir:
        try:
            doc = bundle_mod.make_bundle(
                path, reason, sched, pods_by_uid, rounds, existing_nodes, detail
            )
            bundle_path = bundle_mod.write_bundle(doc, gdir)
        except Exception as err:  # never let bundle IO mask the divergence
            log.error("guard: repro bundle write failed", path=path, error=str(err))
    log.error(
        "guard: shadow audit DIVERGENCE — fast path disagrees with its "
        "exact twin; quarantining",
        path=path,
        reason=reason,
        bundle=bundle_path or "",
    )
    recorder = config.event_recorder()
    if recorder is not None:
        from karpenter_tpu.utils.events import Event

        recorder.publish(
            Event(
                "Solver",
                path,
                "Warning",
                "GuardDivergence",
                f"shadow audit divergence on fast path {path!r}: {reason}"
                + (f" (bundle: {bundle_path})" if bundle_path else ""),
            )
        )
    QUARANTINE.trip(path, reason=reason)
    return bundle_path
