"""Per-fast-path circuit breakers.

A shadow-audit divergence trips the breaker for that one path; every
subsequent solve routes onto the exact twin (resident -> snapshot
solves, speculative -> sequential replay, grid -> full recompute,
encode_cache -> bypass) until the TTL expires or the process restarts.
The breaker is deliberately dumb — no half-open probing: the only way a
quarantined path earns trust back is time (operators watching
``ktpu_guard_quarantined`` can also clear it by restarting with a fix).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from karpenter_tpu.guard import config
from karpenter_tpu.utils.logging import get_logger
from karpenter_tpu.utils.metrics import GUARD_QUARANTINE_TTL, GUARD_QUARANTINED


def _log():
    return get_logger().with_values(controller="guard")


class Quarantine:
    def __init__(self, now: Callable[[], float] = time.monotonic):
        self._now = now
        self._lock = threading.Lock()
        self._until: Dict[str, float] = {}
        self._reason: Dict[str, str] = {}
        # all-time trip count per path (survives expiry/clear: the whole
        # point is counting how often a path keeps lying)
        self._trips: Dict[str, int] = {}
        # trip fan-out: fleet members subscribe so one replica's
        # divergence quarantines the path fleet-wide
        self._listeners: list = []

    def add_listener(self, fn: Callable[[str, str, float, str], None]) -> None:
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def trip(
        self,
        path: str,
        reason: str = "",
        ttl_s: Optional[float] = None,
        source: str = "local",
    ) -> None:
        ttl = config.quarantine_ttl_s() if ttl_s is None else ttl_s
        with self._lock:
            self._until[path] = self._now() + ttl
            self._reason[path] = reason
            self._trips[path] = self._trips.get(path, 0) + 1
            listeners = list(self._listeners)
        GUARD_QUARANTINED.set(1, path=path)
        GUARD_QUARANTINE_TTL.set(ttl, path=path)
        _log().warn(
            "guard: quarantined fast path; routing onto the exact twin",
            path=path,
            ttl_s=ttl,
            reason=reason or "audit divergence",
            source=source,
        )
        for fn in listeners:
            try:
                fn(path, reason, ttl, source)
            except Exception:  # a broken bus must not block the breaker
                pass

    def active(self, path: str) -> bool:
        with self._lock:
            until = self._until.get(path)
            if until is None:
                return False
            if self._now() >= until:
                self._until.pop(path, None)
                self._reason.pop(path, None)
                expired = True
            else:
                return True
        if expired:
            GUARD_QUARANTINED.set(0, path=path)
            GUARD_QUARANTINE_TTL.set(0, path=path)
            _log().info("guard: quarantine expired", path=path)
        return False

    def reason(self, path: str) -> str:
        with self._lock:
            return self._reason.get(path, "")

    def clear(self, path: str) -> None:
        with self._lock:
            self._until.pop(path, None)
            self._reason.pop(path, None)
        GUARD_QUARANTINED.set(0, path=path)
        GUARD_QUARANTINE_TTL.set(0, path=path)

    def reset(self) -> None:
        with self._lock:
            paths = list(self._until)
            self._until.clear()
            self._reason.clear()
            self._trips.clear()
        for p in paths:
            GUARD_QUARANTINED.set(0, path=p)
            GUARD_QUARANTINE_TTL.set(0, path=p)

    def snapshot(self) -> Dict[str, float]:
        """path -> seconds remaining (for diagnostics / bench JSON)."""
        now = self._now()
        with self._lock:
            return {p: max(0.0, t - now) for p, t in self._until.items()}

    def state(self) -> Dict[str, dict]:
        """Full inspectable state for /debug/quarantine: every path that
        has ever tripped, with TTL remaining (0 when expired/cleared),
        the tripping reason, and the all-time trip count."""
        now = self._now()
        with self._lock:
            paths = set(self._trips) | set(self._until)
            return {
                p: {
                    "ttl_s": round(max(0.0, self._until.get(p, now) - now), 3),
                    "active": self._until.get(p, now) > now,
                    "reason": self._reason.get(p, ""),
                    "trips": self._trips.get(p, 0),
                }
                for p in sorted(paths)
            }


QUARANTINE = Quarantine()
