"""Dispatch watchdog: a deadline around device work that can hang.

PR 8 surfaced the failure class this exists for: a CPU-backend
collective rendezvous that never completes leaves ``block_until_ready``
blocked in C++ forever — no Python exception, no signal delivery into
the runtime, a provisioner wedged mid-solve. The watchdog runs the
dispatch on a worker thread and bounds the wait; on deadline it dumps
every thread's stack (the post-mortem the hang would otherwise eat),
counts the stall, and raises ``DispatchStallError`` so the scheduler's
degradation ladder fails the solve over to the host path instead of
hanging.

The stuck worker CANNOT be killed — Python has no way to interrupt a
thread blocked in native code — so it is leaked as a daemon thread. That
is the deliberate trade: a leaked thread per stall (rare, counted,
logged) versus a controller that never provisions again. Default
``KTPU_WATCHDOG_S=0`` disables the wrapper entirely (direct call, zero
threads, zero overhead).
"""

from __future__ import annotations

import contextvars
import sys
import threading
import traceback
from typing import Callable, TypeVar

from karpenter_tpu.guard import config
from karpenter_tpu.utils.logging import get_logger
from karpenter_tpu.utils.metrics import WATCHDOG_STALLS

T = TypeVar("T")


class DispatchStallError(RuntimeError):
    """The device dispatch blew its watchdog deadline (stalled backend)."""

    def __init__(self, section: str, deadline_s: float):
        super().__init__(
            f"device dispatch stalled: section {section!r} did not complete "
            f"within KTPU_WATCHDOG_S={deadline_s:g}s"
        )
        self.section = section
        self.deadline_s = deadline_s


def dump_all_stacks() -> str:
    """All-thread stack dump (the trace the hang would otherwise eat)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, "?")
        stack = "".join(traceback.format_stack(frame))
        chunks.append(f"--- thread {name} ({ident}) ---\n{stack}")
    return "\n".join(chunks)


def run_guarded(fn: Callable[[], T], section: str) -> T:
    """Run ``fn`` under the dispatch watchdog.

    Disabled (the default) this is a direct call. Enabled, ``fn`` runs on
    a worker thread carrying the caller's contextvars (tracing spans and
    fault plans stay attached) and the caller joins with a deadline.
    """
    deadline = config.watchdog_s()
    if deadline <= 0.0:
        return fn()

    result: list = []
    failure: list = []
    ctx = contextvars.copy_context()

    def _work():
        try:
            result.append(ctx.run(fn))
        except BaseException as err:  # noqa: BLE001 — re-raised on the caller
            failure.append(err)

    worker = threading.Thread(
        target=_work, name=f"ktpu-watchdog-{section}", daemon=True
    )
    worker.start()
    worker.join(deadline)
    if worker.is_alive():
        WATCHDOG_STALLS.inc(section=section)
        stacks = dump_all_stacks()
        log = get_logger().with_values(controller="guard")
        log.error(
            "watchdog: dispatch stalled; leaking the stuck worker and "
            "failing the solve into the host-fallback ladder",
            section=section,
            deadline_s=deadline,
            stacks=stacks,
        )
        _record_stall_span(section, deadline)
        raise DispatchStallError(section, deadline)
    if failure:
        raise failure[0]
    return result[0]


def _record_stall_span(section: str, deadline_s: float) -> None:
    """Stamp the stall onto the live trace ring (no-op when tracing is
    off or there is no open parent span)."""
    try:
        from karpenter_tpu.tracing import TRACER

        TRACER.record_span(
            "guard.watchdog.stall", deadline_s, section=section, stalled=True
        )
    except Exception:
        pass
