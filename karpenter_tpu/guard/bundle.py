"""Self-contained divergence repro bundles.

When a shadow audit catches a fast path disagreeing with its exact twin,
the most valuable artifact is not the log line — it is a deterministic
reproduction. A bundle is one JSON file holding the encoded problem
(templates via the RPC codec, pods/existing nodes as base64 protobuf),
the solve sequence that reached the divergent round, and the env/backend
signature (jax version, platform, device kind, every ``KTPU_*`` knob and
``XLA_FLAGS``) — everything ``python -m karpenter_tpu.guard.replay``
needs to re-run the round on a like-for-like backend and exit nonzero if
the divergence reproduces. The PR 8 GSPMD wire-packer miscompile is the
motivating case: a wrong-numbers bug that only manifests under one
backend signature wants exactly this capsule.
"""

from __future__ import annotations

import base64
import json
import os
import time
from typing import Optional


def backend_signature() -> dict:
    """The environment fingerprint a divergence must be replayed under."""
    import jax
    import numpy as np

    try:
        dev = jax.devices()[0]
        device_kind = getattr(dev, "device_kind", "")
        n_devices = jax.device_count()
        platform = dev.platform
    except Exception:
        device_kind, n_devices, platform = "", 0, "unknown"
    return {
        "jax": jax.__version__,
        "numpy": np.__version__,
        "platform": platform,
        "device_kind": device_kind,
        "device_count": n_devices,
    }


def _env_snapshot() -> dict:
    keep = {k: v for k, v in os.environ.items() if k.startswith("KTPU_")}
    # the shard family opt-out knobs are snapshotted even when UNSET:
    # replay must reproduce the dp-vs-sequential routing decision, and
    # "unset" (dp-eligible, the default) is itself a routing input — a
    # replay host where one happens to be exported would route the
    # family differently and never reach the diverging merge
    for knob in ("KTPU_SHARD_EXISTING", "KTPU_SHARD_PERPOD", "KTPU_SHARD_KSCAN"):
        keep.setdefault(knob, "")
    if os.environ.get("XLA_FLAGS"):
        keep["XLA_FLAGS"] = os.environ["XLA_FLAGS"]
    if os.environ.get("JAX_PLATFORMS"):
        keep["JAX_PLATFORMS"] = os.environ["JAX_PLATFORMS"]
    return keep


def make_bundle(
    path: str,
    reason: str,
    sched,
    pods_by_uid: dict,
    rounds: list,
    existing_nodes=(),
    detail: Optional[dict] = None,
) -> dict:
    """Assemble a bundle document.

    ``rounds`` is the solve sequence as lists of pod uids — replay feeds
    each list (resolved against ``pods_by_uid``) through one solve; the
    LAST round is the one whose fast path diverged.
    """
    from karpenter_tpu.rpc.codec import encode_templates
    from karpenter_tpu.rpc.convert import existing_to_pb, pod_to_pb

    pods_b64 = {
        uid: base64.b64encode(pod_to_pb(p).SerializeToString()).decode()
        for uid, p in pods_by_uid.items()
    }
    existing_b64 = [
        base64.b64encode(existing_to_pb(n).SerializeToString()).decode()
        for n in existing_nodes
    ]
    return {
        "version": 1,
        "path": path,
        "reason": reason,
        "created_unix": time.time(),
        "backend": backend_signature(),
        "env": _env_snapshot(),
        "scheduler": {
            "max_claims": int(sched.max_claims),
            "pod_pad": int(sched.pod_pad) if sched.pod_pad else None,
        },
        "templates_b64": base64.b64encode(encode_templates(sched.templates)).decode(),
        "pods": pods_b64,
        "existing": existing_b64,
        "rounds": [list(r) for r in rounds],
        "detail": detail or {},
    }


def write_doc(doc: dict, dirpath: str, fname: str) -> str:
    """Atomic JSON document write (tmp + rename), shared by divergence
    bundles and the round ledger's problem capsules / materializations —
    readers never see a torn file."""
    os.makedirs(dirpath, exist_ok=True)
    out = os.path.join(dirpath, fname)
    tmp = out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, sort_keys=True, indent=1)
    os.replace(tmp, out)
    return out


def write_bundle(doc: dict, guard_dir: str) -> str:
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(doc["created_unix"]))
    fname = f"divergence-{doc['path']}-{stamp}-{os.getpid()}.json"
    return write_doc(doc, guard_dir, fname)


def load_bundle(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("version") != 1:
        raise ValueError(f"unsupported bundle version {doc.get('version')!r}")
    for key in ("path", "templates_b64", "pods", "rounds"):
        if key not in doc:
            raise ValueError(f"bundle missing {key!r}")
    return doc


def materialize(doc: dict):
    """bundle -> (templates, pods_by_uid, existing_nodes, rounds)."""
    from karpenter_tpu.rpc import solver_pb2 as pb
    from karpenter_tpu.rpc.codec import decode_templates
    from karpenter_tpu.rpc.convert import existing_from_pb, pod_from_pb

    templates = decode_templates(base64.b64decode(doc["templates_b64"]))
    pods_by_uid = {}
    for uid, raw in doc["pods"].items():
        m = pb.Pod()
        m.ParseFromString(base64.b64decode(raw))
        pods_by_uid[uid] = pod_from_pb(m)
    existing = []
    for i, raw in enumerate(doc.get("existing", [])):
        m = pb.ExistingNode()
        m.ParseFromString(base64.b64decode(raw))
        existing.append(existing_from_pb(m, i))
    return templates, pods_by_uid, existing, [list(r) for r in doc["rounds"]]
