"""Guardrails: trust-but-verify over every fast path with an exactness proof.

The last three subsystems stacked exactness-critical fast paths —
resident delta rounds, the dp-speculative shard merge, the incremental
kscan capacity grid, the encode-row cache — whose bit-parity proofs run
in CI, not production. This package is the production half of those
proofs (the consistency-controller idea from the reference, applied to
the solver):

- **Shadow audits** (``audit``, ``config.should_audit``): with
  probability ``KTPU_GUARD_AUDIT_RATE`` a fast-path crossing is
  re-derived via its exact twin and compared bit-exact; a divergence
  writes a self-contained repro bundle (``bundle``), emits
  ``ktpu_guard_audits_total{verdict="divergence"}`` + a Warning event,
  and trips the path's breaker.
- **Quarantine** (``quarantine.QUARANTINE``): a tripped path routes
  every subsequent solve onto its exact twin until TTL expiry
  (``KTPU_GUARD_TTL_S``) or restart.
- **Dispatch watchdog** (``watchdog.run_guarded``): a deadline around
  device dispatch that converts a stalled backend (the PR 8 rendezvous
  deadlock class) into a host-fallback solve instead of a hang.
- **Replay** (``python -m karpenter_tpu.guard.replay <bundle>``):
  deterministically re-runs a divergence bundle; exits nonzero when the
  divergence reproduces.
"""

from karpenter_tpu.guard.audit import (
    AUDIT_LOG,
    divergences,
    handle_divergence,
    record_audit,
    reset_log,
    result_signature,
)
from karpenter_tpu.guard.config import (
    PATHS,
    audit_rate,
    guard_dir,
    lying,
    set_event_recorder,
    should_audit,
    watchdog_s,
)
from karpenter_tpu.guard.quarantine import QUARANTINE, Quarantine
from karpenter_tpu.guard.watchdog import DispatchStallError, run_guarded

__all__ = [
    "AUDIT_LOG",
    "DispatchStallError",
    "PATHS",
    "QUARANTINE",
    "Quarantine",
    "audit_rate",
    "divergences",
    "guard_dir",
    "handle_divergence",
    "lying",
    "record_audit",
    "reset_log",
    "result_signature",
    "run_guarded",
    "set_event_recorder",
    "should_audit",
    "watchdog_s",
]
