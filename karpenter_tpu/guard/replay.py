"""Deterministic divergence-bundle replay.

``python -m karpenter_tpu.guard.replay <bundle.json>`` rebuilds the
scheduler from the bundle's encoded problem, restores the recorded
``KTPU_*`` knobs (including a recorded lying-path fixture — that is how
the seeded CI check proves the loop closes), forces the audit rate to
1.0, and re-runs the recorded solve sequence. Exit status:

- **1** — the divergence REPRODUCED (the audit fired again); the bundle
  is a live bug capsule on this backend.
- **0** — every audit passed; either the bug is fixed or it does not
  manifest under this backend signature (the recorded one is printed so
  the operator can tell which).
- **2** — the bundle is unreadable/inconsistent (replay never ran).

Replay is read-only: it never writes new bundles (``KTPU_GUARD_DIR`` is
cleared) and quarantine state is process-local, so a reproduced
divergence cannot cascade.
"""

from __future__ import annotations

import json
import os
import sys

# env keys replay refuses to import from the bundle: platform selection
# must stay the operator's choice (replaying a TPU bundle on a CPU dev
# box is the common triage flow — the backend mismatch is REPORTED, not
# silently forced)
_SKIP_ENV = ("JAX_PLATFORMS", "XLA_FLAGS", "KTPU_GUARD_DIR")


def _restore_env(doc: dict) -> None:
    for key, value in doc.get("env", {}).items():
        if key in _SKIP_ENV:
            continue
        os.environ[key] = value
    os.environ["KTPU_GUARD_AUDIT_RATE"] = "1.0"
    os.environ.pop("KTPU_GUARD_DIR", None)


def replay(bundle_path: str) -> int:
    from karpenter_tpu.guard import bundle as bundle_mod

    try:
        doc = bundle_mod.load_bundle(bundle_path)
    except Exception as err:
        print(f"guard.replay: unreadable bundle: {err}", file=sys.stderr)
        return 2

    _restore_env(doc)

    # import AFTER the env restore so knob-sensitive module state (scan
    # window, caches, shard_dp) initializes the way the divergent run had it
    from karpenter_tpu import guard
    from karpenter_tpu.controllers.provisioning import TPUScheduler

    try:
        templates, pods_by_uid, existing, rounds = bundle_mod.materialize(doc)
    except Exception as err:
        print(f"guard.replay: bundle did not materialize: {err}", file=sys.stderr)
        return 2

    sched_cfg = doc.get("scheduler", {})
    sched = TPUScheduler(
        templates,
        max_claims=sched_cfg.get("max_claims"),
        pod_pad=sched_cfg.get("pod_pad"),
    )
    path = doc["path"]
    guard.reset_log()
    guard.QUARANTINE.reset()

    session = sched.resident_session() if path == "resident" else None
    for i, uids in enumerate(rounds):
        missing = [u for u in uids if u not in pods_by_uid]
        if missing:
            print(f"guard.replay: round {i} references unknown pods {missing[:4]}",
                  file=sys.stderr)
            return 2
        pods = [pods_by_uid[u] for u in uids]
        exist = [n.clone() for n in existing]
        # quarantine trips on a reproduced divergence mid-sequence; clear
        # it so every remaining round still exercises the fast path
        guard.QUARANTINE.reset()
        if session is not None:
            session.solve(pods, exist)
        else:
            sched.solve(pods, exist)

    reproduced = guard.divergences(path)
    here = bundle_mod.backend_signature()
    summary = {
        "bundle": bundle_path,
        "path": path,
        "reason": doc.get("reason", ""),
        "rounds": len(rounds),
        "audits": len(guard.AUDIT_LOG),
        "divergences": len(reproduced),
        "recorded_backend": doc.get("backend", {}),
        "replay_backend": here,
        "backend_match": doc.get("backend", {}) == here,
        "reproduced": bool(reproduced),
    }
    print(json.dumps(summary, indent=1, sort_keys=True))
    return 1 if reproduced else 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m karpenter_tpu.guard.replay <bundle.json>",
              file=sys.stderr)
        return 2
    return replay(argv[0])


if __name__ == "__main__":
    sys.exit(main())
