"""Guardrail configuration: env knobs and the audit sampling decision.

Everything is read per-call (not cached at import) so tests and the
replay harness can flip knobs with ``monkeypatch.setenv`` / a plain
``os.environ`` update without re-importing the world:

``KTPU_GUARD_AUDIT_RATE``  probability in [0, 1] that a fast-path
                           crossing is shadow-audited against its exact
                           twin (default 0 — guard disabled; the hot
                           path pays one env read per crossing)
``KTPU_GUARD_DIR``         where divergence repro bundles are written;
                           unset means no bundle files (the metric,
                           event, and quarantine still fire)
``KTPU_GUARD_TTL_S``       quarantine TTL in seconds (default 300)
``KTPU_GUARD_SEED``        seeds the sampling RNG (deterministic audit
                           schedules for the chaos suite)
``KTPU_GUARD_LIE``         comma list of fast paths made to lie
                           (test-only: the seeded lying-fast-path
                           fixture that proves audits catch divergence)
``KTPU_WATCHDOG_S``        dispatch watchdog deadline in seconds
                           (default 0 — disabled, direct call)
"""

from __future__ import annotations

import os
import random
import threading
from typing import Optional

ENV_AUDIT_RATE = "KTPU_GUARD_AUDIT_RATE"
ENV_GUARD_DIR = "KTPU_GUARD_DIR"
ENV_GUARD_TTL = "KTPU_GUARD_TTL_S"
ENV_GUARD_SEED = "KTPU_GUARD_SEED"
ENV_GUARD_LIE = "KTPU_GUARD_LIE"
ENV_WATCHDOG = "KTPU_WATCHDOG_S"

#: the guarded fast paths (quarantine keys / audit metric labels);
#: "objective" quarantines the placement-objective scorer back onto the
#: lexical policy (objectives/registry.py active_policy); "gang"
#: quarantines the device gang kernel's constraint-bearing class (gang ×
#: topology / finite budgets) back onto the host oracle (_GangHostRoute)
PATHS = ("resident", "speculative", "grid", "encode_cache", "objective", "gang")

_LOCK = threading.Lock()
_RNG: Optional[random.Random] = None
_RNG_SEED: Optional[str] = None


def _float_env(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def audit_rate() -> float:
    """Sampling probability, clamped to [0, 1]."""
    return min(1.0, max(0.0, _float_env(ENV_AUDIT_RATE, 0.0)))


def quarantine_ttl_s() -> float:
    return max(0.0, _float_env(ENV_GUARD_TTL, 300.0))


def watchdog_s() -> float:
    return max(0.0, _float_env(ENV_WATCHDOG, 0.0))


def guard_dir() -> Optional[str]:
    return os.environ.get(ENV_GUARD_DIR) or None


def lie_paths() -> frozenset:
    raw = os.environ.get(ENV_GUARD_LIE, "")
    return frozenset(p.strip() for p in raw.split(",") if p.strip())


def lying(path: str) -> bool:
    """Test-only: is this fast path configured to return wrong answers?"""
    return path in lie_paths()


def _rng() -> random.Random:
    # re-seed when KTPU_GUARD_SEED changes so a monkeypatched seed takes
    # effect mid-process (the chaos suite relies on this)
    global _RNG, _RNG_SEED
    seed = os.environ.get(ENV_GUARD_SEED, "")
    if _RNG is None or seed != _RNG_SEED:
        _RNG = random.Random(int(seed) if seed else 0)
        _RNG_SEED = seed
    return _RNG


def should_audit(path: str) -> bool:
    """One sampling decision per fast-path crossing.

    Disabled (rate 0, the default) this is a dict lookup and a float
    compare — the cost the bench ``--guard`` stage gates under 1% of a
    solve. A quarantined path is never audited: it is already routed
    onto its exact twin, there is nothing to shadow.
    """
    rate = audit_rate()
    if rate <= 0.0:
        return False
    from karpenter_tpu.guard.quarantine import QUARANTINE

    if QUARANTINE.active(path):
        return False
    if rate >= 1.0:
        return True
    with _LOCK:
        return _rng().random() < rate


# optional K8s event sink: the operator wires its Recorder in; solves
# running standalone (bench, tests) leave it None and only get metrics
_EVENT_RECORDER = None


def set_event_recorder(recorder) -> None:
    global _EVENT_RECORDER
    _EVENT_RECORDER = recorder


def event_recorder():
    return _EVENT_RECORDER
