"""CSI volume attach-limit tracking and volume-topology alternatives.

Counterparts of reference pkg/scheduling/volumeusage.go:45-230 (per-node
per-driver distinct-PVC attach limits) and
pkg/controllers/provisioning/scheduling/volumetopology.go:65-225 (per-volume
topology requirement ALTERNATIVES, merged across a pod's volumes by
compatible cross-product with a full-product fallback).

Volumes are tracked as driver -> set of PVC ids; two pods mounting the same
PVC consume one attachment. Limits come from the node's CSINode-published
per-driver allocatable counts (cluster.go:845-857 populateVolumeLimits);
drivers without a published limit are unconstrained. Only existing nodes
enforce limits (existingnode.go:88) — a new NodeClaim has no CSINode yet.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.models import labels as l
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.scheduling.requirements import Operator, Requirement, Requirements

# Volumes: driver name -> set of PVC ids (volumeusage.go:45)
Volumes = dict


def vol_union(a: Volumes, b: Volumes) -> Volumes:
    """Union two driver->pvc-set maps (volumeusage.go:56-70)."""
    out = {k: set(v) for k, v in a.items()}
    for k, v in b.items():
        out.setdefault(k, set()).update(v)
    return out


class VolumeUsage:
    """Per-node attach tracking (volumeusage.go:187-229): the union of every
    resident pod's volumes, a per-pod index for removal, and per-driver
    limits from the node's CSINode."""

    def __init__(self):
        self.volumes: Volumes = {}
        self.pod_volumes: dict[str, Volumes] = {}
        self.limits: dict[str, int] = {}

    def add_limit(self, driver: str, count: int) -> None:
        self.limits[driver] = count

    def exceeds_limits(self, vols: Volumes) -> Optional[str]:
        """Error string when adding vols would push any limited driver over
        its distinct-volume cap (volumeusage.go:201-208), else None."""
        for driver, pvcs in vol_union(self.volumes, vols).items():
            limit = self.limits.get(driver)
            if limit is not None and len(pvcs) > limit:
                return (
                    f"would exceed volume limit, provisioner={driver} "
                    f"volume-count={len(pvcs)} volume-limit={limit}"
                )
        return None

    def add(self, pod_uid: str, vols: Volumes) -> None:
        self.pod_volumes[pod_uid] = {k: set(v) for k, v in vols.items()}
        self.volumes = vol_union(self.volumes, vols)

    def delete_pod(self, pod_uid: str) -> None:
        """Rebuild from scratch — pvc ids may be shared (volumeusage.go:222)."""
        self.pod_volumes.pop(pod_uid, None)
        self.volumes = {}
        for vols in self.pod_volumes.values():
            self.volumes = vol_union(self.volumes, vols)

    def copy(self) -> "VolumeUsage":
        out = VolumeUsage()
        out.volumes = {k: set(v) for k, v in self.volumes.items()}
        out.pod_volumes = {
            uid: {k: set(v) for k, v in vols.items()} for uid, vols in self.pod_volumes.items()
        }
        out.limits = dict(self.limits)
        return out


def get_volumes(pod: Pod, pvcs_by_name: dict, classes_by_name: dict) -> Volumes:
    """The pod's CSI volumes as driver -> {pvc ids} (GetVolumes,
    volumeusage.go:82-113). Driver resolution (ResolveDriver,
    volumeusage.go:115-152): a bound PVC uses its PV's CSI driver (modeled
    as pvc.driver); an unbound PVC uses its StorageClass provisioner.
    Unknown PVCs/classes and empty driver names are skipped — non-CSI or
    already-deleted volumes don't count against limits."""
    out: Volumes = {}
    for name in pod.spec.pvc_names:
        pvc = pvcs_by_name.get(name)
        if pvc is None:
            continue
        driver = getattr(pvc, "driver", None)
        if driver is None:
            sc = classes_by_name.get(pvc.storage_class)
            driver = getattr(sc, "provisioner", "") if sc is not None else ""
        if driver:
            out.setdefault(driver, set()).add(pvc.name)
    return out


def _term_requirements(term: dict) -> Requirements:
    """One topology term (key -> allowed values) as a Requirements set."""
    reqs = Requirements()
    for key, values in term.items():
        reqs.add(Requirement.new(key, Operator.IN, *values))
    return reqs


def _volume_alternatives(pvc, classes_by_name: dict) -> list[Requirements]:
    """Topology alternatives for one PVC (getRequirements,
    volumetopology.go:143-170): a bound volume pins its zone (the PV
    node-affinity path); an unbound PVC takes one alternative per
    StorageClass allowed-topology term (each term is OR'd,
    volumetopology.go:172-190)."""
    if pvc.bound_zone is not None:
        reqs = Requirements()
        reqs.add(Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, pvc.bound_zone))
        return [reqs]
    sc = classes_by_name.get(pvc.storage_class)
    if sc is None:
        return []
    terms = getattr(sc, "allowed_topologies", None)
    if terms:
        return [_term_requirements(t) for t in terms]
    if sc.zones is not None:
        # single term over the zone key (the common case)
        reqs = Requirements()
        reqs.add(Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, *sorted(sc.zones)))
        return [reqs]
    return []


def _compatible(a: Optional[Requirements], b: Optional[Requirements]) -> bool:
    if a is None or b is None:
        return True
    return a.intersects_ok(b)


def _merge(a: Optional[Requirements], b: Requirements) -> Requirements:
    merged = Requirements()
    if a is not None:
        merged.add(*a.values())
    merged.add(*b.values())
    return merged


def merge_alternatives(
    alternatives: list[Optional[Requirements]], vol_alts: list[Requirements]
) -> list[Requirements]:
    """Cross-product merge of per-volume alternatives
    (mergeVolumeRequirementAlternatives, volumetopology.go:93-126): prefer
    only compatible branches; when every branch is incompatible keep the
    full product so the pod stays schedulable-looking (the reference keeps
    it for metrics/decision parity)."""
    compat = [
        _merge(existing, va)
        for existing in alternatives
        for va in vol_alts
        if _compatible(existing, va)
    ]
    if compat:
        return compat
    return [_merge(existing, va) for existing in alternatives for va in vol_alts]


def volume_requirement_alternatives(
    pod: Pod, pvcs_by_name: dict, classes_by_name: dict
) -> list[Requirements]:
    """All valid topology-requirement combinations for the pod's volumes
    (GetRequirements, volumetopology.go:65-91), or [] when unconstrained."""
    alternatives: list[Optional[Requirements]] = [None]
    for name in pod.spec.pvc_names:
        pvc = pvcs_by_name.get(name)
        if pvc is None:
            continue
        vol_alts = _volume_alternatives(pvc, classes_by_name)
        if not vol_alts:
            continue
        alternatives = merge_alternatives(alternatives, vol_alts)
    if len(alternatives) == 1 and alternatives[0] is None:
        return []
    return [a for a in alternatives if a is not None]
