"""Reserved-capacity accounting for a single Solve.

Counterpart of reference
pkg/controllers/provisioning/scheduling/reservationmanager.go:28-115 and
the reserve/release/strict-mode flow in nodeclaim.go:256-349:

  * capacity per reservation id (min over duplicate offerings — multiple
    nodepools may reference one reservation with a capacity update between
    GetInstanceTypes calls)
  * hostname -> reserved-id set; Reserve/Release are idempotent per host
  * offerings_to_reserve: pessimistically reserve EVERY compatible,
    available, reservable reserved offering over a claim's remaining
    instance types
  * Strict mode fails an add when compatible reserved offerings exist but
    none can be reserved, or when the add would drop a claim's existing
    reservations to zero; Fallback lets the claim fall through to
    spot/on-demand

In Fallback mode a claim whose only offerings are reserved-but-exhausted
is still created (the type filter counts reserved offerings as available,
mirroring nodeclaim.go:541's hasOffering); the launch then fails with
InsufficientCapacity and the lifecycle controller deletes the claim and
reschedules — the reference's fail-fast path (launch.go:81).
"""

from __future__ import annotations

from typing import Iterable, Optional

from karpenter_tpu.models import labels as l
from karpenter_tpu.scheduling.requirements import Requirements

RESERVED_MODE_FALLBACK = "fallback"
RESERVED_MODE_STRICT = "strict"


class ReservedOfferingError(Exception):
    """An add failed on reservation grounds (nodeclaim.go:64-80); distinct
    from ordinary incompatibility so callers can treat it as retryable."""


class ReservationManager:
    def __init__(self, instance_types: Iterable):
        self.capacity: dict[str, int] = {}
        for it in instance_types:
            for o in it.offerings:
                if o.capacity_type != l.CAPACITY_TYPE_RESERVED:
                    continue
                rid = o.reservation_id
                cur = self.capacity.get(rid)
                if cur is None or cur > o.reservation_capacity:
                    self.capacity[rid] = o.reservation_capacity
        self.reservations: dict[str, set[str]] = {}  # hostname -> {rid}

    def can_reserve(self, hostname: str, offering) -> bool:
        rid = offering.reservation_id
        if rid in self.reservations.get(hostname, ()):
            return True
        return self.capacity.get(rid, 0) > 0

    def reserve(self, hostname: str, offerings: Iterable) -> None:
        held = self.reservations.setdefault(hostname, set())
        for o in offerings:
            rid = o.reservation_id
            if rid in held:
                continue
            self.capacity[rid] -= 1
            assert self.capacity[rid] >= 0, f"over-reserved {rid}"
            held.add(rid)

    def release(self, hostname: str, *rids: str) -> None:
        held = self.reservations.get(hostname)
        if not held:
            return
        for rid in rids:
            if rid in held:
                held.discard(rid)
                self.capacity[rid] += 1

    def has_reservation(self, hostname: str, offering) -> bool:
        return offering.reservation_id in self.reservations.get(hostname, ())

    def remaining(self, rid: str) -> int:
        return self.capacity.get(rid, 0)


def offerings_to_reserve(
    rm: Optional[ReservationManager],
    hostname: str,
    instance_types: Iterable,
    claim_reqs: Requirements,
    held_rids: frozenset[str],
    mode: str,
) -> list:
    """The set of reserved offerings to (pessimistically) hold for a claim
    after an add (nodeclaim.go:304-349 offeringsToReserve). Raises
    ReservedOfferingError on the Strict-mode failure conditions. rm=None
    means the ReservedCapacity feature gate is off -> no reservations."""
    if rm is None:
        return []
    has_compatible = False
    out = []
    seen: set[str] = set()
    for it in instance_types:
        for o in it.offerings:
            if o.capacity_type != l.CAPACITY_TYPE_RESERVED or not o.available:
                continue
            if claim_reqs.compatible(o.requirements, l.WELL_KNOWN_LABELS) is not None:
                continue
            has_compatible = True
            if o.reservation_id in seen:
                continue
            if o.reservation_id in held_rids or rm.can_reserve(hostname, o):
                seen.add(o.reservation_id)
                out.append(o)
    if mode == RESERVED_MODE_STRICT:
        if has_compatible and not out:
            raise ReservedOfferingError(
                "compatible reserved offerings exist but none could be reserved"
            )
        if held_rids and not out:
            raise ReservedOfferingError(
                "updated constraints would drop all reserved offering options"
            )
    return out
