"""Host-port conflict tracking + the PVC/StorageClass object models.

Counterpart of reference pkg/scheduling/hostportusage.go:35-97: two pods
exposing the same (hostIP, port, protocol) cannot share a node; "0.0.0.0"
conflicts with every IP.

Volume-topology ALTERNATIVES and CSI attach-limit tracking live in
scheduling/volumes.py (volumetopology.go / volumeusage.go counterparts);
this module keeps the storage object models they consume.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

from karpenter_tpu.models.objects import ObjectMeta
from karpenter_tpu.models.pod import HostPort, Pod

WILDCARD_IP = "0.0.0.0"


def port_key(hp: HostPort) -> tuple[str, int, str]:
    return (hp.host_ip or WILDCARD_IP, hp.port, hp.protocol)


def conflicts(existing: Iterable[tuple[str, int, str]], pod: Pod) -> bool:
    """True if any of the pod's host ports collide with used ports
    (hostportusage.go:60-97): same port+protocol collide when either IP is
    the wildcard or the IPs match."""
    used = list(existing)
    for hp in pod.spec.host_ports:
        ip, port, proto = port_key(hp)
        for uip, uport, uproto in used:
            if port != uport or proto != uproto:
                continue
            if ip == WILDCARD_IP or uip == WILDCARD_IP or ip == uip:
                return True
    return False


# -- volume topology ---------------------------------------------------------


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="standard"))
    zones: Optional[list[str]] = None  # allowedTopologies; None = any zone
    # CSI driver name, the attach-limit tracking key (volumeusage.go:156)
    provisioner: str = ""
    # full allowedTopologies: each term (key -> values dict) is one OR'd
    # alternative (volumetopology.go:176-186); overrides `zones` when set
    allowed_topologies: Optional[list[dict]] = None

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="pvc"))
    storage_class: str = "standard"
    bound_zone: Optional[str] = None  # a bound volume pins its zone
    # bound PV's CSI driver (ResolveDriver's driverFromVolume path,
    # volumeusage.go:168-180); None = resolve via the storage class
    driver: Optional[str] = None

    @property
    def name(self) -> str:
        return self.metadata.name
