"""Host-port conflict tracking and volume topology requirements.

Counterparts of reference pkg/scheduling/hostportusage.go:35-97 and
volumetopology.go:65-141.

Host ports: two pods exposing the same (hostIP, port, protocol) cannot
share a node; "0.0.0.0" conflicts with every IP.

Volume topology: each PVC restricts the pod to the zones its storage class
allows (a bound volume pins a single zone); the pod's effective zone
requirement is the intersection across its PVCs. (The reference builds
combinatorial alternatives when classes list multiple allowed topologies —
this port collapses to the intersection, the single-combination case.)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

from karpenter_tpu.models import labels as l
from karpenter_tpu.models.objects import ObjectMeta
from karpenter_tpu.models.pod import HostPort, Pod
from karpenter_tpu.scheduling.requirements import Operator, Requirement

WILDCARD_IP = "0.0.0.0"


def port_key(hp: HostPort) -> tuple[str, int, str]:
    return (hp.host_ip or WILDCARD_IP, hp.port, hp.protocol)


def conflicts(existing: Iterable[tuple[str, int, str]], pod: Pod) -> bool:
    """True if any of the pod's host ports collide with used ports
    (hostportusage.go:60-97): same port+protocol collide when either IP is
    the wildcard or the IPs match."""
    used = list(existing)
    for hp in pod.spec.host_ports:
        ip, port, proto = port_key(hp)
        for uip, uport, uproto in used:
            if port != uport or proto != uproto:
                continue
            if ip == WILDCARD_IP or uip == WILDCARD_IP or ip == uip:
                return True
    return False


# -- volume topology ---------------------------------------------------------


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="standard"))
    zones: Optional[list[str]] = None  # allowedTopologies; None = any zone

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="pvc"))
    storage_class: str = "standard"
    bound_zone: Optional[str] = None  # a bound volume pins its zone

    @property
    def name(self) -> str:
        return self.metadata.name


def volume_zone_requirement(
    pod: Pod,
    pvcs_by_name: dict[str, PersistentVolumeClaim],
    classes_by_name: dict[str, StorageClass],
) -> Optional[Requirement]:
    """The pod's zone requirement implied by its PVCs, or None.

    Unknown PVCs/classes impose no constraint (they may not exist yet —
    the reference defers those pods, we schedule permissively).
    """
    allowed: Optional[set[str]] = None
    for name in pod.spec.pvc_names:
        pvc = pvcs_by_name.get(name)
        if pvc is None:
            continue
        if pvc.bound_zone is not None:
            zones = {pvc.bound_zone}
        else:
            sc = classes_by_name.get(pvc.storage_class)
            if sc is None or sc.zones is None:
                continue
            zones = set(sc.zones)
        allowed = zones if allowed is None else (allowed & zones)
    if allowed is None:
        return None
    return Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, *sorted(allowed))
