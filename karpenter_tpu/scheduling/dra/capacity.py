"""Consumable-capacity math for multi-allocatable devices.

Counterpart of reference pkg/scheduling/dynamicresources/consumable_capacity.go
(policy evaluation at :358-464). A multi-allocatable device is shared across
claims; each allocation consumes per-dimension quantities computed from the
request and the device's request policy (default fill, range rounding,
valid-value rounding), and the allocator verifies the running total never
exceeds the dimension's capacity.
"""

from __future__ import annotations

import math
from typing import Optional

from karpenter_tpu.scheduling.dra.types import DeviceCapacity, RequestPolicy

_REL_TOL = 1e-9


def _leq(a: float, b: float) -> bool:
    return a <= b or math.isclose(a, b, rel_tol=_REL_TOL)


def fill_empty_request(capacity: DeviceCapacity) -> float:
    """Unrequested dimension: policy default if set, else the full value
    (consumable_capacity.go:380-385)."""
    p = capacity.request_policy
    if p is not None and p.default is not None:
        return p.default
    return capacity.value


def round_up_range(requested: float, policy: RequestPolicy) -> float:
    """Round up into [min, min + N*step] (consumable_capacity.go:392-408)."""
    lo = policy.valid_range_min
    assert lo is not None
    if requested < lo:
        return lo
    step = policy.valid_range_step
    if step is None or step <= 0:
        return requested
    n = math.ceil((requested - lo) / step - _REL_TOL)
    return lo + step * n


def round_up_valid_values(requested: float, valid_values: list[float]) -> float:
    """First valid value >= requested; requested itself if none
    (consumable_capacity.go:412-420)."""
    for v in valid_values:
        if _leq(requested, v):
            return v
    return requested


def calculate_consumed(requested: Optional[float], capacity: DeviceCapacity) -> float:
    """Consumed quantity for one dimension (consumable_capacity.go:362-376)."""
    if requested is None:
        return fill_empty_request(capacity)
    p = capacity.request_policy
    if p is None:
        return requested
    if p.valid_range_min is not None:
        return round_up_range(requested, p)
    if p.valid_values:
        return round_up_valid_values(requested, p.valid_values)
    return requested


def violates_policy(consumed: float, policy: Optional[RequestPolicy]) -> bool:
    """Post-rounding policy check (consumable_capacity.go:424-464)."""
    if policy is None:
        return False
    if policy.default is not None and math.isclose(consumed, policy.default, rel_tol=_REL_TOL):
        return False
    if policy.valid_range_min is not None:
        if policy.valid_range_max is not None and consumed > policy.valid_range_max * (1 + _REL_TOL):
            return True
        step = policy.valid_range_step
        if step:
            n = (consumed - policy.valid_range_min) / step
            if not math.isclose(n, round(n), abs_tol=1e-6):
                return True
        return False
    if policy.valid_values:
        return not any(math.isclose(consumed, v, rel_tol=_REL_TOL) for v in policy.valid_values)
    return False


def compute_consumed_capacity(
    capacity_requests: Optional[dict[str, float]],
    device_capacity: dict[str, DeviceCapacity],
) -> Optional[dict[str, float]]:
    """Per-dimension consumed quantities for one allocation, or None when
    the device has no capacity dimensions. Raises ValueError on requests for
    nonexistent dimensions or policy violations
    (consumable_capacity.go:290-312,346-356)."""
    if capacity_requests:
        for name in capacity_requests:
            if name not in device_capacity:
                raise ValueError(f"capacity dimension {name!r} does not exist on device")
    if not device_capacity:
        return None
    consumed: dict[str, float] = {}
    for name, cap in device_capacity.items():
        requested = capacity_requests.get(name) if capacity_requests else None
        c = calculate_consumed(requested, cap)
        if violates_policy(c, cap.request_policy):
            raise ValueError(f"capacity request violates policy for dimension {name!r}")
        consumed[name] = c
    return consumed


def add_capacity(dst: Optional[dict[str, float]], src: Optional[dict[str, float]]) -> dict[str, float]:
    if not src:
        return dst if dst is not None else {}
    if dst is None:
        dst = {}
    for name, qty in src.items():
        dst[name] = dst.get(name, 0.0) + qty
    return dst


def sub_capacity(dst: dict[str, float], src: Optional[dict[str, float]]) -> dict[str, float]:
    if not src:
        return dst
    for name, qty in src.items():
        dst[name] = dst.get(name, 0.0) - qty
    return dst
