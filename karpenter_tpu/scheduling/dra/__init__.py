"""Dynamic Resource Allocation (DRA) for the TPU-native framework.

Counterpart of reference pkg/scheduling/dynamicresources/ — the reference's
single largest production package. Device-claim allocation is modeled as a
backtracking DFS over in-cluster ResourceSlices and per-instance-type
cloud-provider device templates, with snapshot/restore of topology
requirements, stateful match constraints, consumable (multi-allocation)
capacity, and partitionable devices drawing from shared counter budgets.

The search itself is host-side by design: DRA problems are small, deep, and
data-dependent (claims per pod are bounded by AllocationResultsMaxSize), the
opposite shape of the scan-friendly pod-packing hot loop that runs on the
TPU. The host allocator feeds its surviving-instance-type sets and
contributed topology requirements into the same claim pipeline both engines
share, so DRA pods constrain the solve without entering the device kernel.
"""

from karpenter_tpu.scheduling.dra.types import (
    ALLOCATION_RESULTS_MAX_SIZE,
    AttrValue,
    CounterConsumption,
    CounterSet,
    Device,
    DeviceCapacity,
    DeviceClaimStatus,
    DeviceClass,
    DeviceID,
    DeviceRequest,
    DeviceSubRequest,
    MatchConstraintSpec,
    PoolKey,
    RequestName,
    RequestPolicy,
    ResourceClaim,
    ResourceSlice,
)
from karpenter_tpu.scheduling.dra.cel import SelectorCache, SelectorError
from karpenter_tpu.scheduling.dra.pool import DeviceWithID, Pool, filter_pools, gather_pools
from karpenter_tpu.scheduling.dra.tracker import AllocatedDeviceState, AllocationTracker
from karpenter_tpu.scheduling.dra.allocator import (
    AllocationResult,
    Allocator,
    DeviceAllocationResult,
    DRAError,
    DRANodeClaim,
    ResourceClaimAllocationMetadata,
)

__all__ = [
    "ALLOCATION_RESULTS_MAX_SIZE",
    "AllocatedDeviceState",
    "AllocationResult",
    "AllocationTracker",
    "Allocator",
    "AttrValue",
    "CounterConsumption",
    "CounterSet",
    "Device",
    "DeviceAllocationResult",
    "DeviceCapacity",
    "DeviceClaimStatus",
    "DeviceClass",
    "DeviceID",
    "DeviceRequest",
    "DeviceSubRequest",
    "DeviceWithID",
    "DRAError",
    "DRANodeClaim",
    "MatchConstraintSpec",
    "Pool",
    "PoolKey",
    "RequestName",
    "RequestPolicy",
    "ResourceClaim",
    "ResourceSlice",
    "SelectorCache",
    "SelectorError",
    "filter_pools",
    "gather_pools",
]
