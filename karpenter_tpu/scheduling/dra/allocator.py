"""The DRA allocator: per-pod backtracking DFS over device pools.

Counterpart of reference pkg/scheduling/dynamicresources/allocator.go and
request.go. One Allocator is shared across a scheduling loop; Allocate() is
read-only on the shared state, and a successful result carries an
Allocation handle whose commit() applies it — mirroring the reference's
split so the scheduler can discard failed candidate evaluations for free.

Per instance type, the DFS walks claims → requests → sub-requests → device
slots (allocator.go:716-765), trying in-cluster devices first so variance
across ITs stays low, then the IT's template devices. Allocating a device
with slice topology pushes a (requirements, pools) snapshot that
backtracking pops (allocator.go:920-976). ITs whose DFS fails are pruned;
requirements contributed by surviving ITs accumulate so the result is
always representable by a single NodeClaim (allocator.go:663-669).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from karpenter_tpu.models import labels as l
from karpenter_tpu.scheduling.dra.capacity import (
    add_capacity,
    compute_consumed_capacity,
    sub_capacity,
)
from karpenter_tpu.scheduling.dra.cel import SelectorCache, SelectorError
from karpenter_tpu.scheduling.dra.constraints import (
    AttributeBindings,
    BindingFallback,
    MatchAttributeConstraint,
)
from karpenter_tpu.scheduling.dra.pool import DeviceWithID, Pool, filter_pools, gather_pools
from karpenter_tpu.scheduling.dra.tracker import (
    AllocatedDeviceState,
    AllocationTracker,
    Capacity,
    Counters,
)
from karpenter_tpu.scheduling.dra.types import (
    ALLOCATION_RESULTS_MAX_SIZE,
    DeviceClass,
    DeviceID,
    DeviceRequest,
    DeviceSubRequest,
    PoolKey,
    RequestName,
    ResourceClaim,
    ResourceSlice,
    node_selector_to_requirements,
)
from karpenter_tpu.scheduling.requirements import Requirement, Requirements

# DFS wall-clock budget per pod allocation (allocator.go:41-43).
ALLOCATE_TIMEOUT_SECONDS = 5.0


class DRAError(Exception):
    """Allocation or validation failure; the pod cannot use this NodeClaim."""


@dataclass
class DRANodeClaim:
    """The allocator's view of a node claim — existing node, pre-initialized
    node, or in-flight claim (types.go:72-93)."""

    id: str
    nodepool: str
    requirements: Requirements
    instance_types: list[str]
    # Per-instance-type cloud-provider template slices (potential devices).
    resource_slices: dict[str, list[ResourceSlice]] = field(default_factory=dict)
    node_name: str = ""


@dataclass
class DeviceAllocationResult:
    """One device granted to a claim under one instance type
    (allocator.go:136-143)."""

    device_id: DeviceID
    request_name: RequestName
    consumed_capacity: Optional[dict[str, float]] = None


@dataclass
class ResourceClaimAllocationMetadata:
    """In-memory allocation state for one claim (allocator.go:87-134)."""

    nodeclaim_id: str
    contributed_requirements: dict[str, Requirements] = field(default_factory=dict)
    total_requirements: Requirements = field(default_factory=Requirements)
    used_template_devices: bool = False
    devices: dict[str, list[DeviceAllocationResult]] = field(default_factory=dict)


@dataclass
class AllocationResult:
    """Output of a successful Allocate(): surviving ITs, accumulated
    topology requirements, and the commit handle (None when nothing new was
    allocated)."""

    instance_types: list[str]
    requirements: Requirements
    allocation: Optional[Callable[[], None]] = None

    def commit(self) -> None:
        if self.allocation is not None:
            self.allocation()


@dataclass
class _RequestData:
    """Parsed request (request.go:84-116)."""

    name: RequestName
    selectors: list[str] = field(default_factory=list)
    num_devices: int = 0
    allocation_mode: str = "ExactCount"
    capacity_requests: Optional[dict[str, float]] = None
    all_devices: list[DeviceWithID] = field(default_factory=list)
    all_template_devices_by_it: dict[str, list[DeviceWithID]] = field(default_factory=dict)
    sub_requests: list["_RequestData"] = field(default_factory=list)


@dataclass
class _ClaimData:
    id: str
    requests: list[_RequestData] = field(default_factory=list)
    constraints: list[MatchAttributeConstraint] = field(default_factory=list)


@dataclass
class _DeviceAllocation:
    """One DFS-path device pick (allocator.go:557-563)."""

    claim_index: int
    device: DeviceWithID
    consumed_capacity: Optional[dict[str, float]]
    request_name: RequestName


class Allocator:
    """Shared allocator for one scheduling loop (allocator.go:48-67)."""

    def __init__(
        self,
        in_cluster_slices: list[ResourceSlice],
        allocated_state: Optional[AllocatedDeviceState] = None,
        device_classes: Optional[dict[str, DeviceClass]] = None,
        attribute_bindings: Optional[AttributeBindings] = None,
        deleting_pod_uids: Optional[set[str]] = None,
    ):
        self.tracker = AllocationTracker(allocated_state)
        self.selector_cache = SelectorCache()
        self.device_classes = device_classes or {}
        self.attribute_bindings = attribute_bindings or AttributeBindings()
        self.in_cluster_slices = in_cluster_slices
        self.deleting_pod_uids = deleting_pod_uids or set()
        self.pool_cache: dict[str, list[Pool]] = {}
        self.claim_allocation_metadata: dict[str, ResourceClaimAllocationMetadata] = {}
        # Seed counter budgets up-front so Allocate() stays read-only on the
        # tracker (allocator.go:174-179).
        for pool in gather_pools(in_cluster_slices, Requirements(), ""):
            self.tracker.init_remaining_counters(pool)

    def metadata_for_claim(self, claim_key: str) -> Optional[ResourceClaimAllocationMetadata]:
        return self.claim_allocation_metadata.get(claim_key)

    def release_instance_types(self, nodeclaim_id: str, *it_names: str) -> None:
        """Free device allocations for ITs pruned from a NodeClaim
        (allocator.go:253-288): drops their contributed requirements and
        recomputes claim totals so later pods can relax."""
        self.tracker.release_instance_types(nodeclaim_id, *it_names)
        for meta in self.claim_allocation_metadata.values():
            if meta.nodeclaim_id != nodeclaim_id:
                continue
            needs_recompute = False
            for it_name in it_names:
                if meta.contributed_requirements.get(it_name):
                    needs_recompute = True
                meta.contributed_requirements.pop(it_name, None)
                meta.devices.pop(it_name, None)
            if needs_recompute:
                updated = Requirements()
                for it_reqs in meta.contributed_requirements.values():
                    updated.add(*it_reqs.values())
                meta.total_requirements = updated

    # -- claim classification ---------------------------------------------

    def _claim_reserved_entirely_by_deleting_pods(self, claim: ResourceClaim) -> bool:
        """allocator.go:465-484: all pod consumers deleting → re-allocate."""
        if not claim.reserved_for:
            return False
        return all(uid in self.deleting_pod_uids for uid in claim.reserved_for)

    def _classify_claims(
        self, nodeclaim: DRANodeClaim, claims: list[ResourceClaim]
    ) -> tuple[list[ResourceClaim], Requirements]:
        """Split claims into unallocated vs already-allocated, folding the
        allocated ones' topology into the effective requirements
        (allocator.go:406-463)."""
        requirements = nodeclaim.requirements.copy()
        if nodeclaim.node_name:
            # An existing node has a concrete hostname; node-pinned devices
            # contribute hostname topology that must land on defined keys.
            requirements.add(Requirement.new(l.LABEL_HOSTNAME, "In", nodeclaim.node_name))
        unallocated: list[ResourceClaim] = []
        for claim in claims:
            if claim.allocation is not None and self._claim_reserved_entirely_by_deleting_pods(claim):
                unallocated.append(claim)
                continue
            if claim.allocation is not None:
                reqs = node_selector_to_requirements(claim.allocation.node_selector_terms)
                if reqs is not None:
                    if not requirements.is_compatible(reqs, l.WELL_KNOWN_LABELS):
                        raise DRAError(
                            f"claim {claim.key}: in-cluster allocation topology incompatible with NodeClaim"
                        )
                    requirements.add(*reqs.values())
                continue
            meta = self.claim_allocation_metadata.get(claim.key)
            if meta is not None:
                if meta.used_template_devices:
                    if meta.nodeclaim_id != nodeclaim.id:
                        raise DRAError(
                            f"claim {claim.key} is bound to a different in-flight NodeClaim"
                        )
                elif len(meta.total_requirements) != 0:
                    if not requirements.is_compatible(meta.total_requirements, l.WELL_KNOWN_LABELS):
                        raise DRAError(
                            f"claim {claim.key}: in-memory allocation topology incompatible with NodeClaim"
                        )
                    requirements.add(*meta.total_requirements.values())
                continue
            unallocated.append(claim)
        return unallocated, requirements

    # -- request validation ------------------------------------------------

    def _build_request_data(
        self,
        claim: ResourceClaim,
        name: RequestName,
        req: "DeviceRequest | DeviceSubRequest",
        pools: list[Pool],
        template_devices_by_it: dict[str, list[DeviceWithID]],
    ) -> _RequestData:
        cls = self.device_classes.get(req.device_class)
        if req.device_class and cls is None:
            raise DRAError(f"claim {claim.key} request {name}: DeviceClass {req.device_class!r} not found")
        selectors = list(cls.selectors) if cls else []
        selectors.extend(req.selectors)
        for s in selectors:
            try:
                self.selector_cache.compile(s)
            except SelectorError as e:
                raise DRAError(f"claim {claim.key} request {name}: {e}") from None

        rd = _RequestData(
            name=name,
            selectors=selectors,
            num_devices=req.count,
            allocation_mode="ExactCount",
            capacity_requests=dict(req.capacity_requests) if req.capacity_requests else None,
        )
        if req.allocation_mode == "All":
            rd.allocation_mode = "All"
            rd.num_devices = 0
            rd.all_devices = self._collect_all_mode(claim, pools, selectors)
            if template_devices_by_it:
                in_cluster = rd.all_devices
                for it_name, devices in template_devices_by_it.items():
                    matched = [
                        dw
                        for dw in devices
                        if self._matches(dw, selectors)
                    ]
                    # Keep the IT if it has matches, or in-cluster devices
                    # keep the request satisfiable with zero templates
                    # (request.go:363-377).
                    if matched or in_cluster:
                        rd.all_template_devices_by_it[it_name] = matched
        return rd

    def _matches(self, dw: DeviceWithID, selectors: list[str]) -> bool:
        return all(self.selector_cache.matches(s, dw.device, dw.id) for s in selectors)

    def _collect_all_mode(
        self, claim: ResourceClaim, pools: list[Pool], selectors: list[str]
    ) -> list[DeviceWithID]:
        """All-mode needs a complete, valid view (request.go:386-409)."""
        devices: list[DeviceWithID] = []
        for pool in pools:
            if pool.invalid:
                raise DRAError(
                    f"claim {claim.key}: pool {pool.key.driver}/{pool.key.pool} is invalid (duplicate device names)"
                )
            if pool.incomplete:
                raise DRAError(
                    f"claim {claim.key}: pool {pool.key.driver}/{pool.key.pool} is incomplete (missing slices)"
                )
            devices.extend(dw for dw in pool.devices if self._matches(dw, selectors))
        return devices

    def _validate_claim(
        self,
        claim: ResourceClaim,
        pools: list[Pool],
        template_devices_by_it: dict[str, list[DeviceWithID]],
    ) -> _ClaimData:
        """request.go:130-259 — parse constraints + requests, enforce the
        device-count cap, prune ITs whose template devices overflow it."""
        cd = _ClaimData(id=claim.key)
        for spec in claim.constraints:
            if spec.distinct_attribute is not None:
                raise DRAError(f"claim {claim.key}: DistinctAttribute constraints not supported")
            if not spec.attribute:
                raise DRAError(f"claim {claim.key}: unsupported constraint type")
            cd.constraints.append(
                MatchAttributeConstraint(
                    attribute=spec.attribute,
                    request_names=frozenset(spec.requests),
                )
            )
        for req in claim.requests:
            if req.first_available:
                parent = _RequestData(name=RequestName(req.name))
                for sub in req.first_available:
                    sub_rd = self._build_request_data(
                        claim, RequestName(req.name, sub.name), sub, pools, template_devices_by_it
                    )
                    parent.sub_requests.append(sub_rd)
                cd.requests.append(parent)
            else:
                cd.requests.append(
                    self._build_request_data(
                        claim, RequestName(req.name), req, pools, template_devices_by_it
                    )
                )

        # Base device total (IT-independent part), request.go:186-205.
        base_total = 0
        for rd in cd.requests:
            if rd.sub_requests:
                base_total += min(sub.num_devices + len(sub.all_devices) for sub in rd.sub_requests)
            else:
                base_total += rd.num_devices + len(rd.all_devices)
        if base_total > ALLOCATION_RESULTS_MAX_SIZE:
            raise DRAError(
                f"claim {claim.key} requests {base_total} devices, exceeding the maximum of {ALLOCATION_RESULTS_MAX_SIZE}"
            )

        # Per-IT pruning of template All-mode devices (request.go:207-255).
        all_its: set[str] = set()
        for rd in cd.requests:
            for sub in rd.sub_requests or [rd]:
                all_its.update(sub.all_template_devices_by_it)
        pruned = 0
        for it_name in all_its:
            template_count = 0
            for rd in cd.requests:
                if rd.sub_requests:
                    template_count += min(
                        len(sub.all_template_devices_by_it.get(it_name, [])) for sub in rd.sub_requests
                    )
                else:
                    template_count += len(rd.all_template_devices_by_it.get(it_name, []))
            if base_total + template_count > ALLOCATION_RESULTS_MAX_SIZE:
                pruned += 1
                for rd in cd.requests:
                    for sub in rd.sub_requests or [rd]:
                        sub.all_template_devices_by_it.pop(it_name, None)
        if all_its and pruned == len(all_its):
            raise DRAError(
                f"claim {claim.key}: no instance type can satisfy this claim within the maximum of "
                f"{ALLOCATION_RESULTS_MAX_SIZE} devices"
            )
        return cd

    # -- allocation --------------------------------------------------------

    def allocate(self, nodeclaim: DRANodeClaim, claims: list[ResourceClaim]) -> AllocationResult:
        """Satisfy all of a pod's claims against one NodeClaim
        (allocator.go:290-396). Raises DRAError when no instance type can."""
        if not claims:
            return AllocationResult(instance_types=list(nodeclaim.instance_types), requirements=Requirements())

        unallocated, requirements = self._classify_claims(nodeclaim, claims)
        if not unallocated:
            return AllocationResult(
                instance_types=list(nodeclaim.instance_types), requirements=requirements
            )

        cached = self.pool_cache.get(nodeclaim.id)
        if cached is not None:
            pools = filter_pools(cached, requirements, nodeclaim.node_name)
        else:
            pools = gather_pools(self.in_cluster_slices, requirements, nodeclaim.node_name)

        template_devices_by_it: dict[str, list[DeviceWithID]] = {}
        for it_name, slices in nodeclaim.resource_slices.items():
            for s in slices:
                for d in s.devices:
                    template_devices_by_it.setdefault(it_name, []).append(
                        DeviceWithID(
                            device=d,
                            id=DeviceID(driver=s.driver, pool=s.pool, device=d.name, template=True),
                        )
                    )

        claim_data = [self._validate_claim(c, pools, template_devices_by_it) for c in unallocated]
        search = _Search(
            allocator=self,
            nodeclaim=nodeclaim,
            pools=pools,
            template_devices_by_it=template_devices_by_it,
            claim_data=claim_data,
            requirements=requirements,
        )
        return search.run(list(nodeclaim.instance_types))


class _Search:
    """Per-Allocate() mutable DFS state (allocator.go:486-540)."""

    def __init__(
        self,
        allocator: Allocator,
        nodeclaim: DRANodeClaim,
        pools: list[Pool],
        template_devices_by_it: dict[str, list[DeviceWithID]],
        claim_data: list[_ClaimData],
        requirements: Requirements,
    ):
        self.allocator = allocator
        self.tracker = allocator.tracker
        self.nodeclaim = nodeclaim
        self.initial_pools = pools
        self.pools = pools
        self.pools_by_key: dict[PoolKey, Pool] = {}
        self.template_devices_by_it = template_devices_by_it
        self.claim_data = claim_data
        self.requirements = requirements
        self.it_name = ""
        self.deadline = time.monotonic() + ALLOCATE_TIMEOUT_SECONDS
        self.match_cache: dict[tuple[DeviceID, int, int, int], bool] = {}

        self.allocated_devices: set[DeviceID] = set()
        self.allocation_path: list[_DeviceAllocation] = []
        self.allocating_counters: Counters = {}
        self.template_allocating_counters: Counters = {}
        self.template_remaining_counters: Optional[Counters] = None
        self.allocating_capacity: Capacity = {}
        self.template_allocating_capacity: Capacity = {}
        self.snapshots: list[tuple[Requirements, list[Pool]]] = []

    # -- top-level per-IT loop --------------------------------------------

    def run(self, instance_types: list[str]) -> AllocationResult:
        surviving: list[str] = []
        device_ids_by_it: dict[str, list[DeviceID]] = {}
        counters_by_it: dict[str, Counters] = {}
        template_counters_by_it: dict[str, Counters] = {}
        capacity_by_it: dict[str, Capacity] = {}
        template_capacity_by_it: dict[str, Capacity] = {}
        template_counter_totals_by_it: dict[str, Counters] = {}

        claim_meta = [
            ResourceClaimAllocationMetadata(nodeclaim_id=self.nodeclaim.id)
            for _ in self.claim_data
        ]

        for it_name in instance_types:
            if time.monotonic() > self.deadline:
                break
            self.it_name = it_name
            self._restore_state()
            fallback = BindingFallback(
                bindings=self.allocator.attribute_bindings,
                nodepool=self.nodeclaim.nodepool,
                instance_type=it_name,
            )
            for cd in self.claim_data:
                for con in cd.constraints:
                    con.binding_fallback = fallback

            if not self._counters_feasible():
                continue
            if not self._dfs(0, 0, -1, 0):
                continue

            surviving.append(it_name)
            counters_by_it[it_name] = self.allocating_counters
            template_counters_by_it[it_name] = self.template_allocating_counters
            capacity_by_it[it_name] = self.allocating_capacity
            template_capacity_by_it[it_name] = self.template_allocating_capacity
            if (
                self.template_remaining_counters is not None
                and self.tracker.template_remaining_for_it(self.nodeclaim.id, it_name) is None
            ):
                template_counter_totals_by_it[it_name] = self.template_remaining_counters
            self.allocating_counters = {}
            self.template_allocating_counters = {}
            self.allocating_capacity = {}
            self.template_allocating_capacity = {}

            device_ids_by_it[it_name] = [da.device.id for da in self.allocation_path]
            it_reqs = Requirements()
            for da in self.allocation_path:
                meta = claim_meta[da.claim_index]
                if da.device.topology_requirements is not None:
                    claim_it_reqs = meta.contributed_requirements.setdefault(it_name, Requirements())
                    claim_it_reqs.add(*da.device.topology_requirements.values())
                    it_reqs.add(*da.device.topology_requirements.values())
                if da.device.id.template:
                    meta.used_template_devices = True
                meta.devices.setdefault(it_name, []).append(
                    DeviceAllocationResult(
                        device_id=da.device.id,
                        request_name=da.request_name,
                        consumed_capacity=da.consumed_capacity,
                    )
                )
            # Later ITs must stay representable alongside this one
            # (allocator.go:663-669).
            self.requirements.add(*it_reqs.values())

        if not surviving:
            raise DRAError("no instance type can satisfy the allocation")

        nodeclaim_requirements = Requirements()
        meta_by_claim: dict[str, ResourceClaimAllocationMetadata] = {}
        for idx, meta in enumerate(claim_meta):
            total = Requirements()
            for it_reqs in meta.contributed_requirements.values():
                for req in it_reqs.values():
                    total.add(req)
                    nodeclaim_requirements.add(req)
            meta.total_requirements = total
            meta_by_claim[self.claim_data[idx].id] = meta

        filtered_pools = filter_pools(self.initial_pools, self.requirements, self.nodeclaim.node_name)
        allocator = self.allocator
        nodeclaim_id = self.nodeclaim.id

        def commit() -> None:
            """allocation.Commit (allocator.go:231-251)."""
            allocator.tracker.commit(
                nodeclaim_id,
                device_ids_by_it,
                counters_by_it,
                template_counters_by_it,
                capacity_by_it,
                template_capacity_by_it,
                template_counter_totals_by_it,
            )
            allocator.pool_cache[nodeclaim_id] = filtered_pools
            for claim_id, meta in meta_by_claim.items():
                if claim_id in allocator.claim_allocation_metadata:
                    raise AssertionError("attempted to commit claim which was already allocated")
                allocator.claim_allocation_metadata[claim_id] = meta

        return AllocationResult(
            instance_types=surviving,
            requirements=nodeclaim_requirements,
            allocation=commit,
        )

    def _restore_state(self) -> None:
        """Reset mutable DFS state for a new IT (allocator.go:986-1004);
        requirements intentionally persist across ITs."""
        self.allocation_path = []
        self.pools = self.initial_pools
        self._build_pool_index()
        self.allocated_devices = set()
        self.allocating_counters = {}
        self.template_allocating_counters = {}
        self.template_remaining_counters = self._build_template_counters()
        self.allocating_capacity = {}
        self.template_allocating_capacity = {}
        self.snapshots = []
        for cd in self.claim_data:
            for con in cd.constraints:
                con.reset()

    def _build_pool_index(self) -> None:
        self.pools_by_key = {p.key: p for p in self.pools}

    def _build_template_counters(self) -> Optional[Counters]:
        """allocator.go:1013-1061 — per-(NC, IT) template budgets, from the
        tracker when a prior pod initialized them, else computed locally."""
        remaining = self.tracker.template_remaining_for_it(self.nodeclaim.id, self.it_name)
        if remaining is not None:
            return remaining
        slices = self.nodeclaim.resource_slices.get(self.it_name)
        if not slices:
            return None
        totals: Counters = {}
        for s in slices:
            if not s.shared_counters:
                continue
            pool_key = PoolKey(driver=s.driver, pool=s.pool)
            counter_sets = totals.setdefault(pool_key, {})
            for cs in s.shared_counters:
                dst = counter_sets.setdefault(cs.name, {})
                for name, value in cs.counters.items():
                    dst[name] = value
        return totals or None

    # -- DFS ---------------------------------------------------------------

    def _dfs(self, claim_idx: int, req_idx: int, sub_req_idx: int, slot_idx: int) -> bool:
        if time.monotonic() > self.deadline:
            return False
        if claim_idx >= len(self.claim_data):
            return True
        cd = self.claim_data[claim_idx]
        if req_idx >= len(cd.requests):
            return self._dfs(claim_idx + 1, 0, -1, 0)
        rd = cd.requests[req_idx] if sub_req_idx < 0 else cd.requests[req_idx].sub_requests[sub_req_idx]

        if sub_req_idx < 0 and rd.sub_requests:
            # FirstAvailable: alternatives in priority order (allocator.go:781-788).
            for sub_idx in range(len(rd.sub_requests)):
                if self._dfs(claim_idx, req_idx, sub_idx, 0):
                    return True
            return False

        num_slots = self._num_slots(rd)
        if rd.allocation_mode == "All" and num_slots == 0:
            return False
        if slot_idx == 0 and self._claim_device_count(claim_idx) + num_slots > ALLOCATION_RESULTS_MAX_SIZE:
            return False
        if slot_idx >= num_slots:
            return self._dfs(claim_idx, req_idx + 1, -1, 0)

        if rd.allocation_mode == "All":
            # Each slot maps to one predetermined device (allocator.go:827-841).
            in_cluster = len(rd.all_devices)
            if slot_idx < in_cluster:
                dw = rd.all_devices[slot_idx]
                return self._try_device(claim_idx, req_idx, sub_req_idx, slot_idx, cd, rd, dw)
            template_devices = rd.all_template_devices_by_it.get(self.it_name, [])
            template_idx = slot_idx - in_cluster
            if template_idx < len(template_devices):
                dw = template_devices[template_idx]
                return self._try_device(claim_idx, req_idx, sub_req_idx, slot_idx, cd, rd, dw)
            return False

        # ExactCount: iterate devices lazily from current pools then templates
        # (allocator.go:800-823) so pool re-filtering is reflected mid-search.
        for pool in self.pools:
            if pool.incomplete:
                continue
            exhausted = self._exhausted_counters(pool)
            for dw in pool.devices:
                if exhausted and any(
                    (cc.counter_set, name) in exhausted
                    for cc in dw.device.consumes_counters
                    for name, value in cc.counters.items()
                    if value > 0
                ):
                    continue
                if self._try_device(claim_idx, req_idx, sub_req_idx, slot_idx, cd, rd, dw):
                    return True
        for dw in self.template_devices_by_it.get(self.it_name, []):
            if self._try_device(claim_idx, req_idx, sub_req_idx, slot_idx, cd, rd, dw):
                return True
        return False

    def _num_slots(self, rd: _RequestData) -> int:
        if rd.allocation_mode == "All":
            return len(rd.all_devices) + len(rd.all_template_devices_by_it.get(self.it_name, []))
        return rd.num_devices

    def _claim_device_count(self, claim_idx: int) -> int:
        return sum(1 for da in self.allocation_path if da.claim_index == claim_idx)

    def _try_device(
        self,
        claim_idx: int,
        req_idx: int,
        sub_req_idx: int,
        slot_idx: int,
        cd: _ClaimData,
        rd: _RequestData,
        dw: DeviceWithID,
    ) -> bool:
        """allocator.go:847-983 — availability, counters, selector match,
        constraints, topology compatibility; record, recurse, backtrack."""
        device_id = dw.id

        # 1. Availability: capacity gates multi-alloc devices, binary
        #    tracking gates exclusive ones.
        consumed: Optional[dict[str, float]] = None
        if dw.device.allow_multiple_allocations:
            ok, consumed = self._check_capacity(dw, rd)
            if not ok:
                return False
        else:
            if self.tracker.is_allocated(device_id, self.nodeclaim.id, self.it_name):
                return False
            if device_id in self.allocated_devices:
                return False

        # 2. Shared counter budgets.
        if dw.device.consumes_counters:
            pool_key = PoolKey(driver=device_id.driver, pool=device_id.pool)
            if device_id.template:
                remaining = (self.template_remaining_counters or {}).get(pool_key)
            else:
                if pool_key not in self.pools_by_key:
                    return False
                remaining = self.tracker.remaining_counters.get(pool_key)
            if not self._check_counters(dw, pool_key, remaining, device_id.template):
                return False

        # 3. Selector match (cached per device/claim/request position).
        mk = (device_id, claim_idx, req_idx, sub_req_idx)
        matched = self.match_cache.get(mk)
        if matched is None:
            matched = self.allocator._matches(dw, rd.selectors)
            self.match_cache[mk] = matched
        if not matched:
            return False

        # 4. Constraints (stateful, with exact rollback on failure).
        added = 0
        for con in cd.constraints:
            if not con.add(rd.name, dw.device, device_id):
                for j in range(added - 1, -1, -1):
                    cd.constraints[j].remove(rd.name, dw.device, device_id)
                return False
            added += 1

        # 5. Topology compatibility; push a snapshot when tightening.
        pushed = False
        if dw.topology_requirements is not None:
            if not self.requirements.is_compatible(dw.topology_requirements, l.WELL_KNOWN_LABELS):
                for j in range(added - 1, -1, -1):
                    cd.constraints[j].remove(rd.name, dw.device, device_id)
                return False
            self.snapshots.append((self.requirements.copy(), self.pools))
            self.requirements.add(*dw.topology_requirements.values())
            self.pools = filter_pools(self.pools, self.requirements, self.nodeclaim.node_name)
            self._build_pool_index()
            pushed = True

        # Record.
        self.allocated_devices.add(device_id)
        self.allocation_path.append(
            _DeviceAllocation(
                claim_index=claim_idx,
                device=dw,
                consumed_capacity=consumed,
                request_name=rd.name,
            )
        )
        if dw.device.allow_multiple_allocations:
            # Ensure an entry exists so commit can identify multi-alloc
            # devices via capacity presence (allocator.go:947-954).
            cap_map = self.template_allocating_capacity if device_id.template else self.allocating_capacity
            cap_map.setdefault(device_id, {})
        self._deduct_capacity(consumed, device_id, device_id.template)
        self._deduct_counters(dw, device_id.template)

        if self._dfs(claim_idx, req_idx, sub_req_idx, slot_idx + 1):
            return True

        # Backtrack, reversing application order.
        self._restore_capacity(consumed, device_id, device_id.template)
        self._restore_counters(dw, device_id.template)
        self.allocation_path.pop()
        self.allocated_devices.discard(device_id)
        if pushed:
            reqs, pools = self.snapshots.pop()
            self.requirements = reqs
            self.pools = pools
            self._build_pool_index()
        for j in range(added - 1, -1, -1):
            cd.constraints[j].remove(rd.name, dw.device, device_id)
        return False

    # -- consumable capacity ----------------------------------------------

    def _check_capacity(self, dw: DeviceWithID, rd: _RequestData) -> tuple[bool, Optional[dict[str, float]]]:
        """consumable_capacity.go:31-72."""
        device_id = dw.id
        try:
            consumed = compute_consumed_capacity(rd.capacity_requests, dw.device.capacity)
        except ValueError:
            return False, None
        if consumed is None:
            return True, None
        if device_id.template:
            sources = []
            tc = self.tracker.template_consumed_capacity_for_it(self.nodeclaim.id, self.it_name)
            if tc is not None:
                sources.append(tc.get(device_id, {}))
            sources.append(self.template_allocating_capacity.get(device_id, {}))
        else:
            sources = [
                self.tracker.preallocated_consumed_capacity.get(device_id, {}),
                self.tracker.inflight_consumed_capacity.get(device_id, {}),
                self.allocating_capacity.get(device_id, {}),
            ]
        for name, qty in consumed.items():
            total = dw.device.capacity[name].value
            used = sum(src.get(name, 0.0) for src in sources) + qty
            if used > total * (1 + 1e-9):
                return False, None
        return True, consumed

    def _deduct_capacity(self, consumed: Optional[dict[str, float]], device_id: DeviceID, template: bool) -> None:
        if not consumed:
            return
        cap_map = self.template_allocating_capacity if template else self.allocating_capacity
        cap_map[device_id] = add_capacity(cap_map.get(device_id), consumed)

    def _restore_capacity(self, consumed: Optional[dict[str, float]], device_id: DeviceID, template: bool) -> None:
        if not consumed:
            return
        cap_map = self.template_allocating_capacity if template else self.allocating_capacity
        if device_id in cap_map:
            sub_capacity(cap_map[device_id], consumed)

    # -- shared counters ---------------------------------------------------

    def _exhausted_counters(self, pool: Pool) -> set[tuple[str, str]]:
        """(counterSet, counter) pairs with no budget left after DFS-local
        tentative draws. A fast-path prune refining the reference's
        pool-level poolCountersExhausted, which skips EVERY counter-consuming
        device once ANY pool counter hits zero — over-pruning devices that
        draw only on untouched sets."""
        if not pool.counter_sets:
            return set()
        remaining = self.tracker.remaining_counters.get(pool.key)
        allocating = self.allocating_counters.get(pool.key)
        if remaining is None or allocating is None:
            return set()
        out: set[tuple[str, str]] = set()
        for cs_name, counters in allocating.items():
            cs_remaining = remaining.get(cs_name)
            if cs_remaining is None:
                continue
            for name, alloc_value in counters.items():
                if name in cs_remaining and cs_remaining[name] - alloc_value <= 0:
                    out.add((cs_name, name))
        return out

    def _check_counters(
        self,
        dw: DeviceWithID,
        pool_key: PoolKey,
        remaining: Optional[dict[str, dict[str, float]]],
        template: bool,
    ) -> bool:
        """partitionable_devices.go checkCounters."""
        if not dw.device.consumes_counters:
            return True
        if remaining is None:
            return False
        allocating_sets = (
            self.template_allocating_counters if template else self.allocating_counters
        ).get(pool_key, {})
        for cc in dw.device.consumes_counters:
            cs_remaining = remaining.get(cc.counter_set)
            if cs_remaining is None:
                return False
            allocating = allocating_sets.get(cc.counter_set, {})
            for name, value in cc.counters.items():
                if name not in cs_remaining:
                    return False
                if cs_remaining[name] - allocating.get(name, 0.0) < value * (1 - 1e-9):
                    return False
        return True

    def _deduct_counters(self, dw: DeviceWithID, template: bool) -> None:
        if not dw.device.consumes_counters:
            return
        pool_key = PoolKey(driver=dw.id.driver, pool=dw.id.pool)
        counter_map = self.template_allocating_counters if template else self.allocating_counters
        counter_sets = counter_map.setdefault(pool_key, {})
        for cc in dw.device.consumes_counters:
            counters = counter_sets.setdefault(cc.counter_set, {})
            for name, value in cc.counters.items():
                counters[name] = counters.get(name, 0.0) + value

    def _restore_counters(self, dw: DeviceWithID, template: bool) -> None:
        if not dw.device.consumes_counters:
            return
        pool_key = PoolKey(driver=dw.id.driver, pool=dw.id.pool)
        counter_map = self.template_allocating_counters if template else self.allocating_counters
        counter_sets = counter_map.get(pool_key)
        if counter_sets is None:
            return
        for cc in dw.device.consumes_counters:
            counters = counter_sets.get(cc.counter_set)
            if counters is None:
                continue
            for name, value in cc.counters.items():
                if name in counters:
                    counters[name] -= value

    # -- pre-DFS feasibility ----------------------------------------------

    def _counters_feasible(self) -> bool:
        """partitionable_devices.go countersFeasible — lower-bound check for
        All-mode requests whose device sets are predetermined."""
        for cd in self.claim_data:
            for rd in cd.requests:
                if rd.sub_requests:
                    if not any(
                        sub.allocation_mode != "All" or self._all_mode_feasible(sub)
                        for sub in rd.sub_requests
                    ):
                        return False
                elif rd.allocation_mode == "All":
                    if not self._all_mode_feasible(rd):
                        return False
        return True

    def _all_mode_feasible(self, rd: _RequestData) -> bool:
        in_cluster_shadow: Counters = {}
        template_shadow: Counters = {}
        devices = list(rd.all_devices) + list(rd.all_template_devices_by_it.get(self.it_name, []))
        for dw in devices:
            if not dw.device.consumes_counters:
                continue
            pool_key = PoolKey(driver=dw.id.driver, pool=dw.id.pool)
            shadow = template_shadow if dw.id.template else in_cluster_shadow
            if pool_key not in shadow:
                if dw.id.template:
                    remaining = (self.template_remaining_counters or {}).get(pool_key)
                else:
                    remaining = self.tracker.remaining_counters.get(pool_key)
                if remaining is None:
                    return True
                shadow[pool_key] = {cs: dict(counters) for cs, counters in remaining.items()}
            pool_shadow = shadow[pool_key]
            for cc in dw.device.consumes_counters:
                cs_shadow = pool_shadow.get(cc.counter_set)
                if cs_shadow is None:
                    return False
                for name, value in cc.counters.items():
                    if name not in cs_shadow:
                        return False
                    cs_shadow[name] -= value
                    if cs_shadow[name] < -1e-9:
                        return False
        return True
