"""Stateful inter-device constraints and attribute bindings.

Counterparts of reference pkg/scheduling/dynamicresources/constraint.go and
attributebindings.go. MatchAttribute pins a value with the first allocated
device and rejects later devices that disagree; Add/Remove form an exact
undo pair so the DFS can backtrack. Attribute bindings cover runtime-only
attributes (e.g. a PCI-root id unknown until launch): the cloud provider
declares which devices on an instance type will share the value, and the
constraint falls back to group membership when the attribute is absent from
the device template.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.scheduling.dra.types import (
    AttrValue,
    Device,
    DeviceID,
    RequestName,
    attr_values_equal,
)

# Bare device identity (driver, pool, device) without the template flag —
# bindings are declared by the provider on template device names.
_BareID = tuple[str, str, str]


def _bare(device_id: DeviceID) -> _BareID:
    return (device_id.driver, device_id.pool, device_id.device)


@dataclass
class AttributeBindingDecl:
    """A provider-declared binding: these devices on this instance type will
    share a value for ``attribute`` at runtime."""

    attribute: str
    devices: list[_BareID]


class AttributeBindings:
    """Transitive-closure binding graph keyed by
    (attribute, nodepool, instance type) (attributebindings.go:41-167)."""

    def __init__(self) -> None:
        # attribute -> nodepool -> it -> device -> set of bound devices
        self._graph: dict[str, dict[str, dict[str, dict[_BareID, set[_BareID]]]]] = {}

    @staticmethod
    def build(decls_by_pool_it: dict[tuple[str, str], list[AttributeBindingDecl]]) -> "AttributeBindings":
        """decls_by_pool_it maps (nodepool, instance type name) to the
        provider's binding declarations for that instance type."""
        ab = AttributeBindings()
        for (nodepool, it_name), decls in decls_by_pool_it.items():
            for decl in decls:
                if len(decl.devices) < 2:
                    continue
                per_it = (
                    ab._graph.setdefault(decl.attribute, {})
                    .setdefault(nodepool, {})
                    .setdefault(it_name, {})
                )
                for i, dev in enumerate(decl.devices):
                    group = per_it.setdefault(dev, set())
                    for j, other in enumerate(decl.devices):
                        if i != j:
                            group.add(other)
        # Transitive closure per triple via BFS from each device
        # (attributebindings.go:137-166).
        for per_attr in ab._graph.values():
            for per_pool in per_attr.values():
                for per_it in per_pool.values():
                    closures: dict[_BareID, set[_BareID]] = {}
                    for device in per_it:
                        visited: set[_BareID] = set()
                        queue = deque([device])
                        while queue:
                            curr = queue.popleft()
                            if curr in visited:
                                continue
                            visited.add(curr)
                            queue.extend(n for n in per_it.get(curr, ()) if n not in visited)
                        visited.discard(device)
                        closures[device] = visited
                    per_it.update(closures)
        return ab

    def _lookup(self, nodepool: str, it_name: str, attribute: str) -> Optional[dict[_BareID, set[_BareID]]]:
        return self._graph.get(attribute, {}).get(nodepool, {}).get(it_name)

    def has_bindings(self, nodepool: str, it_name: str, attribute: str, device_id: DeviceID) -> bool:
        per_it = self._lookup(nodepool, it_name, attribute)
        return per_it is not None and _bare(device_id) in per_it

    def bound(self, nodepool: str, it_name: str, attribute: str, a: DeviceID, b: DeviceID) -> bool:
        per_it = self._lookup(nodepool, it_name, attribute)
        if per_it is None:
            return False
        group = per_it.get(_bare(a))
        if group is None:
            return False
        if _bare(a) == _bare(b):
            return len(group) > 0
        return _bare(b) in group


@dataclass
class BindingFallback:
    """Context for binding lookups during one IT's DFS
    (constraint.go:71-75)."""

    bindings: AttributeBindings
    nodepool: str
    instance_type: str


def lookup_attribute(device: Device, device_id: DeviceID, name: str) -> Optional[AttrValue]:
    """Qualified lookup with driver-domain fallback (constraint.go:168-180)."""
    if name in device.attributes:
        return device.attributes[name]
    domain, sep, ident = name.partition("/")
    if sep and domain == device_id.driver and ident in device.attributes:
        return device.attributes[ident]
    return None


@dataclass
class MatchAttributeConstraint:
    """All devices for the constrained requests share one attribute value
    (constraint.go:46-163). Concrete-value and binding-fallback paths are
    mutually exclusive once established."""

    attribute: str
    request_names: frozenset[str] = frozenset()
    binding_fallback: Optional[BindingFallback] = None

    pinned_value: Optional[AttrValue] = None
    used_binding: bool = False
    allocated_ids: list[DeviceID] = field(default_factory=list)

    def _applies(self, request_name: RequestName) -> bool:
        if not self.request_names:
            return True
        if request_name.parent in self.request_names:
            return True
        if request_name.sub:
            return str(request_name) in self.request_names
        return False

    def add(self, request_name: RequestName, device: Device, device_id: DeviceID) -> bool:
        if not self._applies(request_name):
            return True
        value = lookup_attribute(device, device_id, self.attribute)
        if value is not None:
            if self.used_binding:
                return False
            if not self.allocated_ids:
                self.pinned_value = value
                self.allocated_ids.append(device_id)
                return True
            if self.pinned_value is None or not attr_values_equal(self.pinned_value, value):
                return False
            self.allocated_ids.append(device_id)
            return True
        # Attribute absent — binding fallback path.
        if self.allocated_ids and not self.used_binding:
            return False
        fb = self.binding_fallback
        if fb is None:
            return False
        if not fb.bindings.has_bindings(fb.nodepool, fb.instance_type, self.attribute, device_id):
            return False
        if not self.allocated_ids:
            self.used_binding = True
            self.allocated_ids.append(device_id)
            return True
        # Bindings are transitive, so one representative check suffices.
        if not fb.bindings.bound(fb.nodepool, fb.instance_type, self.attribute, self.allocated_ids[0], device_id):
            return False
        self.allocated_ids.append(device_id)
        return True

    def remove(self, request_name: RequestName, device: Device, device_id: DeviceID) -> None:
        if not self._applies(request_name):
            return
        if self.allocated_ids:
            self.allocated_ids.pop()
        if not self.allocated_ids:
            self.pinned_value = None
            self.used_binding = False

    def reset(self) -> None:
        self.pinned_value = None
        self.used_binding = False
        self.allocated_ids.clear()
