"""DRA object model.

Counterpart of reference pkg/scheduling/dynamicresources/types.go and the
resource.k8s.io/v1 API surface the allocator consumes: ResourceSlices
(in-cluster and cloud-provider templates), Devices with typed attributes,
consumable capacity with request policies, shared counter sets
(partitionable devices), DeviceClasses, and ResourceClaims with Exactly /
FirstAvailable device requests and MatchAttribute constraints.

Quantities are floats throughout (the repo-wide convention from
utils/resources.parse_quantity); attribute values keep their Python type so
typed equality mirrors DeviceAttribute semantics (constraint.go:183-201):
an int attribute never matches a string attribute, and bools are compared
only against bools.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple, Optional, Sequence, Union

from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.utils.resources import parse_quantity

# resource.k8s.io/v1 AllocationResultsMaxSize — the hard cap on devices per
# claim, enforced up-front per claim and re-checked per-IT in the DFS
# (request.go:201-255, allocator.go:753-756).
ALLOCATION_RESULTS_MAX_SIZE = 32

# Attribute values are typed: str | int | bool | Version. Versions are
# modeled as strings tagged by wrapping in a 1-tuple is avoided — instead a
# dedicated class keeps typed-equality honest.
AttrValue = Union[str, int, bool, "Version"]


@dataclass(frozen=True)
class Version:
    """A semver-ish attribute value; equality is string equality."""

    value: str


def attr_values_equal(a: AttrValue, b: AttrValue) -> bool:
    """Typed equality: bool-vs-int and int-vs-str never match
    (constraint.go:183-201)."""
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, Version) != isinstance(b, Version):
        return False
    if type(a) in (int, str) and type(b) in (int, str) and type(a) is not type(b):
        return False
    return a == b


class DeviceID(NamedTuple):
    """Globally unique device identity (types.go:49-56). ``template`` marks
    potential (cloud-provider template) devices, which are tracked per
    (NodeClaim, InstanceType) rather than globally."""

    driver: str
    pool: str
    device: str
    template: bool = False

    def __str__(self) -> str:
        prefix = "virtual/" if self.template else ""
        return f"{prefix}{self.driver}/{self.pool}/{self.device}"


class PoolKey(NamedTuple):
    driver: str
    pool: str


class RequestName(NamedTuple):
    """Identifies a device request within a claim; ``sub`` is set for
    FirstAvailable sub-requests (types.go:60-70)."""

    parent: str
    sub: str = ""

    def __str__(self) -> str:
        return f"{self.parent}/{self.sub}" if self.sub else self.parent


@dataclass
class RequestPolicy:
    """Consumable-capacity request policy (consumable_capacity.go:358-420)."""

    default: Optional[float] = None
    valid_range_min: Optional[float] = None
    valid_range_max: Optional[float] = None
    valid_range_step: Optional[float] = None
    valid_values: Optional[list[float]] = None  # sorted ascending


@dataclass
class DeviceCapacity:
    value: float
    request_policy: Optional[RequestPolicy] = None


@dataclass
class CounterConsumption:
    """A device's draw against a pool-level shared counter set."""

    counter_set: str
    counters: dict[str, float] = field(default_factory=dict)


@dataclass
class CounterSet:
    name: str
    counters: dict[str, float] = field(default_factory=dict)


@dataclass
class Device:
    """One allocatable device within a slice."""

    name: str
    attributes: dict[str, AttrValue] = field(default_factory=dict)
    capacity: dict[str, DeviceCapacity] = field(default_factory=dict)
    allow_multiple_allocations: bool = False
    consumes_counters: list[CounterConsumption] = field(default_factory=list)


@dataclass
class ResourceSlice:
    """A group of devices published by a driver, either in-cluster (API
    server) or as a cloud-provider template for an instance type
    (types.go:98-260 collapses both behind one interface; here one concrete
    class with a ``potential`` flag serves both roles).

    Node accessibility is exactly one of: ``all_nodes``, ``node_name``
    (pinned to one concrete node), or ``node_selector_terms`` (ORed
    Requirements terms). Template slices are always node-local to the
    NodeClaim they are attached to and carry no selector.
    """

    driver: str
    pool: str
    devices: list[Device] = field(default_factory=list)
    generation: int = 0
    resource_slice_count: int = 1
    node_name: str = ""
    node_selector_terms: Optional[list[Requirements]] = None
    all_nodes: bool = False
    shared_counters: Optional[list[CounterSet]] = None
    potential: bool = False
    metadata: object = None  # ObjectMeta when persisted in the ObjectStore

    def __post_init__(self) -> None:
        if self.metadata is None:
            from karpenter_tpu.models.objects import ObjectMeta

            self.metadata = ObjectMeta(name=f"{self.driver}-{self.pool}")


@dataclass
class DeviceClass:
    """resource.k8s.io DeviceClass: a named bundle of selectors every
    request referencing it inherits (request.go:313-339)."""

    name: str
    selectors: list[str] = field(default_factory=list)
    metadata: object = None

    def __post_init__(self) -> None:
        if self.metadata is None:
            from karpenter_tpu.models.objects import ObjectMeta

            self.metadata = ObjectMeta(name=self.name)


@dataclass
class DeviceSubRequest:
    """One alternative inside a FirstAvailable request."""

    name: str
    device_class: str = ""
    selectors: list[str] = field(default_factory=list)
    allocation_mode: str = "ExactCount"  # or "All"
    count: int = 1
    capacity_requests: Optional[dict[str, float]] = None


@dataclass
class DeviceRequest:
    """A top-level device request: either Exactly (fields inline) or
    FirstAvailable (ordered ``first_available`` alternatives)."""

    name: str
    device_class: str = ""
    selectors: list[str] = field(default_factory=list)
    allocation_mode: str = "ExactCount"
    count: int = 1
    capacity_requests: Optional[dict[str, float]] = None
    first_available: list[DeviceSubRequest] = field(default_factory=list)


@dataclass
class MatchConstraintSpec:
    """MatchAttribute constraint spec: all devices for the named requests
    (all requests when empty) must share one value for ``attribute``."""

    attribute: str
    requests: list[str] = field(default_factory=list)
    distinct_attribute: Optional[str] = None  # unsupported, like the reference


@dataclass
class AllocatedDevice:
    """One committed device in a claim's status allocation."""

    request: str
    driver: str
    pool: str
    device: str
    consumed_capacity: Optional[dict[str, float]] = None


@dataclass
class DeviceClaimStatus:
    """Claim status once allocated: the chosen devices plus the node
    selector terms that scope where the claim is usable."""

    devices: list[AllocatedDevice] = field(default_factory=list)
    node_selector_terms: Optional[list[Requirements]] = None


@dataclass
class ResourceClaim:
    """resource.k8s.io ResourceClaim. ``allocation`` is set once committed
    (in-cluster); ``reserved_for`` lists consuming pod UIDs."""

    name: str
    namespace: str = "default"
    requests: list[DeviceRequest] = field(default_factory=list)
    constraints: list[MatchConstraintSpec] = field(default_factory=list)
    allocation: Optional[DeviceClaimStatus] = None
    reserved_for: list[str] = field(default_factory=list)  # pod UIDs
    metadata: object = None

    def __post_init__(self) -> None:
        if self.metadata is None:
            from karpenter_tpu.models.objects import ObjectMeta

            self.metadata = ObjectMeta(name=self.name)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def quantities(d: "dict[str, str | int | float] | None") -> dict[str, float]:
    """Parse a resource-list-style mapping of quantity strings to floats."""
    if not d:
        return {}
    return {k: parse_quantity(v) for k, v in d.items()}


def make_capacity(d: "dict[str, str | int | float] | None") -> dict[str, DeviceCapacity]:
    return {k: DeviceCapacity(value=parse_quantity(v)) for k, v in (d or {}).items()}


def or_node_selector_terms(terms: Sequence[Requirements]) -> Requirements:
    """Fold ORed node-selector terms into one Requirements set as a sound
    over-approximation: keys constrained by EVERY term keep the union of
    their constraints; keys any term leaves free are unconstrained. This
    deliberately diverges from the reference (types.go:262-274 adds all
    terms into one set, intersecting per key — which turns
    [zone In a] OR [zone In b] into an empty set): a node matching any term
    always satisfies the folded result."""
    if not terms:
        return Requirements()
    out = Requirements()
    common = set(terms[0].keys())
    for term in terms[1:]:
        common &= term.keys()
    for key in common:
        req = terms[0].get(key)
        for term in terms[1:]:
            req = req.union(term.get(key))
        out.add(req)
    return out


def node_selector_to_requirements(terms: Optional[Sequence[Requirements]]) -> Optional[Requirements]:
    """Requirements form of a claim allocation's node selector, or None when
    the allocation carries no topology constraint."""
    if terms is None:
        return None
    return or_node_selector_terms(terms)
