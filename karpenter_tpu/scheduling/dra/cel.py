"""Device selector expressions.

The reference evaluates CEL expressions against a device view
(request.go:431-463 via k8s.io/dynamic-resource-allocation/cel). This module
is the framework's equivalent: a small, safe expression engine over the same
device context, compiled once per distinct expression and cached
(the analog of dracel.Cache — allocator.go:370, request.go:334-339).

Supported surface (CEL-compatible where it matters to device selectors):

    device.driver                        -> str
    device.attributes["domain/name"]     -> typed attribute value
    device.capacity["dimension"]         -> float (quantity)
    device.allowMultipleAllocations      -> bool
    ==  !=  <  <=  >  >=  in             comparisons
    &&  ||  !                            boolean operators (CEL spelling)
    quantity("10Gi")                     -> float
    string/int/float/bool literals, lists, parentheses, + - * /

Attribute lookups use the driver-qualified fallback of
constraint.go:168-180: ``device.attributes["d/x"]`` on a device of driver
``d`` also matches an attribute published unqualified as ``x``.

Expressions are parsed with the Python ``ast`` module against a strict node
whitelist and evaluated with empty builtins — no calls other than
``quantity``, no dunder access, no comprehensions. A compile failure is a
validation error (claims referencing it are rejected, request.go:334-339); a
runtime failure (missing attribute, type mismatch) makes the device
non-matching, mirroring DeviceMatchesSelectors' error-as-no-match handling
in tryDevice (allocator.go:893-905).
"""

from __future__ import annotations

import ast
import re

from karpenter_tpu.scheduling.dra.types import Device, DeviceID, Version
from karpenter_tpu.utils.resources import parse_quantity


class SelectorError(Exception):
    """Raised for selector compile failures and runtime lookup misses."""


_ALLOWED_NODES = (
    ast.Expression,
    ast.BoolOp,
    ast.And,
    ast.Or,
    ast.UnaryOp,
    ast.Not,
    ast.USub,
    ast.BinOp,
    ast.Add,
    ast.Sub,
    ast.Mult,
    ast.Div,
    ast.Compare,
    ast.Eq,
    ast.NotEq,
    ast.Lt,
    ast.LtE,
    ast.Gt,
    ast.GtE,
    ast.In,
    ast.NotIn,
    ast.Name,
    ast.Load,
    ast.Attribute,
    ast.Subscript,
    ast.Constant,
    ast.Call,
    ast.List,
    ast.Tuple,
)

_ALLOWED_NAMES = {"device", "quantity", "True", "False"}

# CEL spellings -> Python: `&&`, `||`, and bare `!` (but not `!=`).
_CEL_REWRITES = (
    (re.compile(r"&&"), " and "),
    (re.compile(r"\|\|"), " or "),
    (re.compile(r"!(?!=)"), " not "),
    (re.compile(r"\btrue\b"), "True"),
    (re.compile(r"\bfalse\b"), "False"),
)


def _rewrite(expression: str) -> str:
    # Protect string literals from rewrites by splitting on quoted spans.
    parts = re.split(r"(\"[^\"]*\"|'[^']*')", expression)
    out = []
    for i, part in enumerate(parts):
        if i % 2 == 0:
            for pattern, repl in _CEL_REWRITES:
                part = pattern.sub(repl, part)
        out.append(part)
    return "".join(out)


def _validate(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise SelectorError(f"disallowed syntax: {type(node).__name__}")
        if isinstance(node, ast.Name) and node.id not in _ALLOWED_NAMES:
            raise SelectorError(f"unknown identifier: {node.id}")
        if isinstance(node, ast.Attribute) and node.attr.startswith("_"):
            raise SelectorError(f"disallowed attribute: {node.attr}")
        if isinstance(node, ast.Call):
            if not (isinstance(node.func, ast.Name) and node.func.id == "quantity"):
                raise SelectorError("only quantity(...) calls are allowed")
            if node.keywords or len(node.args) != 1:
                raise SelectorError("quantity takes exactly one positional argument")


class _AttrMap:
    """Attribute lookup with the driver-qualified fallback."""

    def __init__(self, device: Device, device_id: DeviceID):
        self._attrs = device.attributes
        self._driver = device_id.driver

    def __getitem__(self, name: str):
        if name in self._attrs:
            return _unwrap(self._attrs[name])
        domain, sep, ident = name.partition("/")
        if sep and domain == self._driver and ident in self._attrs:
            return _unwrap(self._attrs[ident])
        raise SelectorError(f"attribute {name!r} not present")

    def __contains__(self, name: str) -> bool:
        try:
            self[name]
            return True
        except SelectorError:
            return False


class _CapacityMap:
    def __init__(self, device: Device):
        self._capacity = device.capacity

    def __getitem__(self, name: str) -> float:
        if name not in self._capacity:
            raise SelectorError(f"capacity {name!r} not present")
        return self._capacity[name].value

    def __contains__(self, name: str) -> bool:
        return name in self._capacity


def _unwrap(value):
    return value.value if isinstance(value, Version) else value


class _DeviceView:
    """The ``device`` binding visible to selector expressions."""

    def __init__(self, device: Device, device_id: DeviceID):
        self.driver = device_id.driver
        self.attributes = _AttrMap(device, device_id)
        self.capacity = _CapacityMap(device)
        self.allowMultipleAllocations = device.allow_multiple_allocations


def _quantity(q) -> float:
    return parse_quantity(q)


class SelectorCache:
    """Compile-once cache for selector expressions (dracel.Cache analog)."""

    def __init__(self) -> None:
        self._compiled: dict[str, object] = {}
        self._errors: dict[str, SelectorError] = {}

    def compile(self, expression: str):
        """Compile an expression, caching both successes and failures.
        Raises SelectorError on invalid expressions."""
        if expression in self._errors:
            raise self._errors[expression]
        code = self._compiled.get(expression)
        if code is None:
            try:
                tree = ast.parse(_rewrite(expression), mode="eval")
                _validate(tree)
                code = compile(tree, "<selector>", "eval")
            except (SyntaxError, ValueError, SelectorError) as e:
                err = SelectorError(f"selector {expression!r}: {e}")
                self._errors[expression] = err
                raise err from None
            self._compiled[expression] = code
        return code

    def matches(self, expression: str, device: Device, device_id: DeviceID) -> bool:
        """Evaluate one selector against a device. Compile errors propagate
        (callers validate up-front); runtime errors mean no-match."""
        code = self.compile(expression)
        env = {"device": _DeviceView(device, device_id), "quantity": _quantity}
        try:
            return bool(eval(code, {"__builtins__": {}}, env))  # noqa: S307 - whitelisted AST
        except SelectorError:
            return False
        except (TypeError, KeyError, AttributeError, ZeroDivisionError, ValueError):
            # ValueError covers malformed quantity literals at eval time.
            return False


def device_matches_selectors(
    cache: SelectorCache,
    device: Device,
    device_id: DeviceID,
    selectors: list[str],
) -> bool:
    """AND semantics across selectors (request.go:431-463)."""
    return all(cache.matches(s, device, device_id) for s in selectors)
