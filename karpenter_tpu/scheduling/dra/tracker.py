"""Cross-pod allocation tracking for one scheduling loop.

Counterpart of reference pkg/scheduling/dynamicresources/allocationtracker.go
plus the tracker halves of consumable_capacity.go and
partitionable_devices.go. The tracker is the shared, committed state the
per-pod DFS reads: which devices earlier pods (or the API server) already
hold, how much consumable capacity and shared-counter budget is spoken for.

Karpenter's NodeClaim superposition makes allocation non-binary: an
in-flight NodeClaim is simultaneously "every surviving instance type", and
a device may be allocated under several of those candidate ITs at once.
Committed consumption is therefore tracked per (NodeClaim, IT) and rolled
up with a pessimistic max across ITs; pruning ITs releases exactly the
delta the max loses (partitionable_devices.go:29-79,
consumable_capacity.go:102-238).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.scheduling.dra.pool import Pool
from karpenter_tpu.scheduling.dra.types import DeviceID, PoolKey

# Nested alias soup, kept close to the reference's shapes:
# counters:  pool -> counterSet -> counter -> float
Counters = dict[PoolKey, dict[str, dict[str, float]]]
# capacity:  device -> dimension -> float
Capacity = dict[DeviceID, dict[str, float]]


@dataclass
class AllocatedDeviceState:
    """Seed state from the cluster: devices exclusively held by committed
    claims, and aggregated consumed capacity of multi-allocatable devices
    (allocator.go:145-150)."""

    exclusive_devices: set[DeviceID] = field(default_factory=set)
    consumed_capacity: Capacity = field(default_factory=dict)


@dataclass
class InflightAllocationMetadata:
    """Which NodeClaim holds an in-cluster device, and under which candidate
    instance types (allocationtracker.go:114-123)."""

    nodeclaim_id: str
    instance_types: set[str] = field(default_factory=set)


def _merge_counters(dst: Counters, src: Counters) -> None:
    for pool_key, counter_sets in src.items():
        dst_sets = dst.setdefault(pool_key, {})
        for cs_name, counters in counter_sets.items():
            dst_counters = dst_sets.setdefault(cs_name, {})
            for name, value in counters.items():
                dst_counters[name] = dst_counters.get(name, 0.0) + value


def _counter_max(by_it: dict[str, Counters]) -> Counters:
    """Pessimistic per-counter max across instance types
    (partitionable_devices.go pessimisticCounterMax)."""
    out: Counters = {}
    for counters in by_it.values():
        for pool_key, counter_sets in counters.items():
            out_sets = out.setdefault(pool_key, {})
            for cs_name, cmap in counter_sets.items():
                out_counters = out_sets.setdefault(cs_name, {})
                for name, value in cmap.items():
                    if value > out_counters.get(name, 0.0):
                        out_counters[name] = value
    return out


def _capacity_max(by_it: dict[str, Capacity]) -> Capacity:
    """Pessimistic per-device per-dimension max across instance types
    (consumable_capacity.go:265-285)."""
    out: Capacity = {}
    for devices in by_it.values():
        for device_id, dims in devices.items():
            out_dims = out.setdefault(device_id, {})
            for name, qty in dims.items():
                if qty > out_dims.get(name, 0.0):
                    out_dims[name] = qty
    return out


class AllocationTracker:
    """Committed allocation state shared across all pods in one loop."""

    def __init__(self, allocated_state: Optional[AllocatedDeviceState] = None):
        state = allocated_state or AllocatedDeviceState()
        self.preallocated_devices: set[DeviceID] = {
            DeviceID(d.driver, d.pool, d.device) for d in state.exclusive_devices
        }
        self.preallocated_consumed_capacity: Capacity = {
            DeviceID(d.driver, d.pool, d.device): dict(v)
            for d, v in state.consumed_capacity.items()
        }
        self.inflight_cluster_allocations: dict[DeviceID, InflightAllocationMetadata] = {}
        # nodeclaim -> it -> device ids (acceleration index, and template twin)
        self.inflight_by_nodeclaim: dict[str, dict[str, set[DeviceID]]] = {}
        self.inflight_template_allocations: dict[str, dict[str, set[DeviceID]]] = {}
        # Rolled-up (pessimistic-max) consumption visible to every DFS.
        self.inflight_consumed_capacity: Capacity = {}
        self.remaining_counters: Counters = {}
        # Precise per-(nodeclaim, it) records enabling exact release.
        self._capacity_by_nodeclaim_it: dict[str, dict[str, Capacity]] = {}
        self._counters_by_nodeclaim_it: dict[str, dict[str, Counters]] = {}
        # Template (per-IT-local) state; no pessimistic max needed.
        self._template_capacity: dict[str, dict[str, Capacity]] = {}
        self._template_remaining_counters: dict[str, dict[str, Counters]] = {}

    # -- counter budgets ---------------------------------------------------

    def init_remaining_counters(self, pool: Pool) -> None:
        """Seed a pool's budget: totals minus the draw of devices already
        allocated in-cluster (including non-targeting ones)
        (allocator.go:174-179 + partitionable seeding)."""
        if not pool.counter_sets or pool.key in self.remaining_counters:
            return
        remaining = {cs: dict(counters) for cs, counters in pool.counter_sets.items()}
        self.remaining_counters[pool.key] = remaining
        for dw in list(pool.devices) + list(pool.non_targeting_devices):
            if dw.id in self.preallocated_devices or dw.id in self.preallocated_consumed_capacity:
                for cc in dw.device.consumes_counters:
                    cs = remaining.get(cc.counter_set)
                    if cs is None:
                        continue
                    for name, value in cc.counters.items():
                        cs[name] = cs.get(name, 0.0) - value

    def template_remaining_for_it(self, nodeclaim_id: str, it_name: str) -> Optional[Counters]:
        return self._template_remaining_counters.get(nodeclaim_id, {}).get(it_name)

    def init_template_remaining_counters(self, nodeclaim_id: str, it_name: str, totals: Counters) -> None:
        per_nc = self._template_remaining_counters.setdefault(nodeclaim_id, {})
        if it_name not in per_nc:
            per_nc[it_name] = totals

    def template_consumed_capacity_for_it(self, nodeclaim_id: str, it_name: str) -> Optional[Capacity]:
        return self._template_capacity.get(nodeclaim_id, {}).get(it_name)

    # -- allocation status -------------------------------------------------

    def is_allocated(self, device_id: DeviceID, nodeclaim_id: str, it_name: str) -> bool:
        """Allocation is relative to the asking (NodeClaim, IT)
        (allocationtracker.go:231-268): a device held by the same NodeClaim
        under *other* ITs is still free for this IT, because the NodeClaim
        collapses to one IT at launch."""
        if device_id.template:
            return device_id in self.inflight_template_allocations.get(nodeclaim_id, {}).get(it_name, set())
        if device_id in self.preallocated_devices:
            return True
        meta = self.inflight_cluster_allocations.get(device_id)
        if meta is not None:
            if meta.nodeclaim_id != nodeclaim_id:
                return True
            return it_name in meta.instance_types
        return False

    # -- commit ------------------------------------------------------------

    def commit(
        self,
        nodeclaim_id: str,
        device_ids_by_it: dict[str, list[DeviceID]],
        counter_consumption_by_it: dict[str, Counters],
        template_counter_consumption_by_it: dict[str, Counters],
        capacity_consumption_by_it: dict[str, Capacity],
        template_capacity_consumption_by_it: dict[str, Capacity],
        template_counter_totals_by_it: dict[str, Counters],
    ) -> None:
        """Apply one pod's successful allocation (allocationtracker.go:126-174)."""
        for it_name, device_ids in device_ids_by_it.items():
            for device_id in device_ids:
                if device_id.template:
                    # Multi-alloc template devices are tracked via capacity.
                    if device_id in template_capacity_consumption_by_it.get(it_name, {}):
                        continue
                    self.inflight_template_allocations.setdefault(nodeclaim_id, {}).setdefault(
                        it_name, set()
                    ).add(device_id)
                    continue
                if device_id in capacity_consumption_by_it.get(it_name, {}):
                    continue
                self.inflight_by_nodeclaim.setdefault(nodeclaim_id, {}).setdefault(it_name, set()).add(
                    device_id
                )
                meta = self.inflight_cluster_allocations.get(device_id)
                if meta is not None:
                    if meta.nodeclaim_id != nodeclaim_id:
                        raise AssertionError("device already allocated for a different nodeclaim")
                    if it_name in meta.instance_types:
                        raise AssertionError("device already allocated for instance type")
                    meta.instance_types.add(it_name)
                else:
                    self.inflight_cluster_allocations[device_id] = InflightAllocationMetadata(
                        nodeclaim_id=nodeclaim_id, instance_types={it_name}
                    )
        self._commit_counters(nodeclaim_id, counter_consumption_by_it)
        for it_name, totals in template_counter_totals_by_it.items():
            self.init_template_remaining_counters(nodeclaim_id, it_name, totals)
        self._commit_template_counters(nodeclaim_id, template_counter_consumption_by_it)
        self._commit_capacity(nodeclaim_id, capacity_consumption_by_it)
        self._commit_template_capacity(nodeclaim_id, template_capacity_consumption_by_it)

    def _commit_counters(self, nodeclaim_id: str, by_it: dict[str, Counters]) -> None:
        if not by_it:
            return
        stored = self._counters_by_nodeclaim_it.setdefault(nodeclaim_id, {})
        old_max = _counter_max(stored) if stored else {}
        for it_name, counters in by_it.items():
            if it_name not in stored:
                stored[it_name] = counters
            else:
                _merge_counters(stored[it_name], counters)
        new_max = _counter_max(stored)
        self._apply_counter_delta(old_max, new_max)

    def _apply_counter_delta(self, old_max: Counters, new_max: Counters) -> None:
        """Deduct (new - old) pessimistic max from remaining budgets
        (partitionable_devices.go subtractDeltaFromRemaining)."""
        for pool_key, counter_sets in new_max.items():
            pool_remaining = self.remaining_counters.get(pool_key)
            if pool_remaining is None:
                continue
            for cs_name, counters in counter_sets.items():
                cs_remaining = pool_remaining.get(cs_name)
                if cs_remaining is None:
                    continue
                for name, new_value in counters.items():
                    old_value = old_max.get(pool_key, {}).get(cs_name, {}).get(name, 0.0)
                    delta = new_value - old_value
                    if delta > 0:
                        cs_remaining[name] = cs_remaining.get(name, 0.0) - delta

    def _commit_template_counters(self, nodeclaim_id: str, by_it: dict[str, Counters]) -> None:
        if not by_it:
            return
        per_nc = self._template_remaining_counters.get(nodeclaim_id)
        if per_nc is None:
            return
        for it_name, counters in by_it.items():
            remaining = per_nc.get(it_name)
            if remaining is None:
                continue
            for pool_key, counter_sets in counters.items():
                rem_sets = remaining.get(pool_key, {})
                for cs_name, cmap in counter_sets.items():
                    rem_counters = rem_sets.get(cs_name, {})
                    for name, value in cmap.items():
                        rem_counters[name] = rem_counters.get(name, 0.0) - value

    def _commit_capacity(self, nodeclaim_id: str, by_it: dict[str, Capacity]) -> None:
        if not by_it:
            return
        stored = self._capacity_by_nodeclaim_it.setdefault(nodeclaim_id, {})
        old_max = _capacity_max(stored) if stored else {}
        for it_name, devices in by_it.items():
            stored_devices = stored.setdefault(it_name, {})
            for device_id, dims in devices.items():
                stored_dims = stored_devices.setdefault(device_id, {})
                for name, qty in dims.items():
                    stored_dims[name] = stored_dims.get(name, 0.0) + qty
        new_max = _capacity_max(stored)
        for device_id, dims in new_max.items():
            for name, new_qty in dims.items():
                delta = new_qty - old_max.get(device_id, {}).get(name, 0.0)
                if delta > 0:
                    inflight = self.inflight_consumed_capacity.setdefault(device_id, {})
                    inflight[name] = inflight.get(name, 0.0) + delta

    def _commit_template_capacity(self, nodeclaim_id: str, by_it: dict[str, Capacity]) -> None:
        if not by_it:
            return
        stored = self._template_capacity.setdefault(nodeclaim_id, {})
        for it_name, devices in by_it.items():
            stored_devices = stored.setdefault(it_name, {})
            for device_id, dims in devices.items():
                stored_dims = stored_devices.setdefault(device_id, {})
                for name, qty in dims.items():
                    stored_dims[name] = stored_dims.get(name, 0.0) + qty

    # -- release -----------------------------------------------------------

    def release_instance_types(self, nodeclaim_id: str, *it_names: str) -> None:
        """Free everything a NodeClaim held under pruned instance types
        (allocationtracker.go:198-229)."""
        for it_name in it_names:
            devices = self.inflight_by_nodeclaim.get(nodeclaim_id, {}).pop(it_name, set())
            for device_id in devices:
                meta = self.inflight_cluster_allocations.get(device_id)
                if meta is None or it_name not in meta.instance_types:
                    raise AssertionError("inflight allocation metadata missing instance type reference")
                meta.instance_types.discard(it_name)
                if not meta.instance_types:
                    del self.inflight_cluster_allocations[device_id]
            self.inflight_template_allocations.get(nodeclaim_id, {}).pop(it_name, None)
        self._release_counters(nodeclaim_id, it_names)
        self._release_template(self._template_remaining_counters, nodeclaim_id, it_names)
        self._release_capacity(nodeclaim_id, it_names)
        self._release_template(self._template_capacity, nodeclaim_id, it_names)

    def _release_counters(self, nodeclaim_id: str, it_names) -> None:
        stored = self._counters_by_nodeclaim_it.get(nodeclaim_id)
        if stored is None:
            return
        old_max = _counter_max(stored)
        for it_name in it_names:
            stored.pop(it_name, None)
        new_max = _counter_max(stored)
        # Return (old - new) to the remaining budgets.
        for pool_key, counter_sets in old_max.items():
            pool_remaining = self.remaining_counters.get(pool_key)
            if pool_remaining is None:
                continue
            for cs_name, counters in counter_sets.items():
                cs_remaining = pool_remaining.get(cs_name)
                if cs_remaining is None:
                    continue
                for name, old_value in counters.items():
                    delta = old_value - new_max.get(pool_key, {}).get(cs_name, {}).get(name, 0.0)
                    if delta > 0:
                        cs_remaining[name] = cs_remaining.get(name, 0.0) + delta
        if not stored:
            del self._counters_by_nodeclaim_it[nodeclaim_id]

    def _release_capacity(self, nodeclaim_id: str, it_names) -> None:
        stored = self._capacity_by_nodeclaim_it.get(nodeclaim_id)
        if stored is None:
            return
        old_max = _capacity_max(stored)
        for it_name in it_names:
            stored.pop(it_name, None)
        new_max = _capacity_max(stored)
        for device_id, dims in old_max.items():
            for name, old_qty in dims.items():
                delta = old_qty - new_max.get(device_id, {}).get(name, 0.0)
                if delta > 0:
                    inflight = self.inflight_consumed_capacity.get(device_id)
                    if inflight is None:
                        continue
                    remaining = inflight.get(name, 0.0) - delta
                    if remaining <= 1e-12:
                        inflight.pop(name, None)
                    else:
                        inflight[name] = remaining
                    if not inflight:
                        self.inflight_consumed_capacity.pop(device_id, None)
        if not stored:
            del self._capacity_by_nodeclaim_it[nodeclaim_id]

    @staticmethod
    def _release_template(store: dict[str, dict[str, object]], nodeclaim_id: str, it_names) -> None:
        per_nc = store.get(nodeclaim_id)
        if per_nc is None:
            return
        for it_name in it_names:
            per_nc.pop(it_name, None)
        if not per_nc:
            store.pop(nodeclaim_id, None)
