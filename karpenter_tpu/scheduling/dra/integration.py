"""DRA ↔ scheduler integration.

Counterpart of the allocator call sites in the reference scheduler
(scheduling/scheduler.go:139,253-258,571-589 resolvePodClaims,
nodeclaim.go:124-283 CanAdd/Add, existingnode.go:81). A DRAProblem is built
once per provisioning loop from store state (slices, device classes,
claims, committed allocations, deleting pods); each preference-relaxation
round gets a fresh Allocator via fresh_round() because rounds restart the
simulation from scratch.

DRA pods route through the host engine: the allocation DFS is deep,
data-dependent, and bounded-small (AllocationResultsMaxSize per claim) —
the structural opposite of the scan-friendly packing loop that runs on the
TPU — so TPUScheduler.solve delegates whole solves containing DRA pods to
its host-oracle twin, keeping the device kernel free of ragged control
flow. The gate is off by default, like the reference's feature flag.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.scheduling.dra.allocator import (
    AllocationResult,
    Allocator,
    DRAError,
    DRANodeClaim,
)
from karpenter_tpu.scheduling.dra.constraints import AttributeBindingDecl, AttributeBindings
from karpenter_tpu.scheduling.dra.tracker import AllocatedDeviceState
from karpenter_tpu.scheduling.dra.types import (
    DeviceClass,
    DeviceID,
    ResourceClaim,
    ResourceSlice,
)
from karpenter_tpu.scheduling.requirements import Requirements


def gather_allocated_state(
    claims: list[ResourceClaim],
    slices: list[ResourceSlice],
    deleting_pod_uids: set[str],
) -> AllocatedDeviceState:
    """Seed the tracker from committed claim allocations (the reference's
    gatherAllocatedDevices): exclusive devices vs aggregated consumed
    capacity for multi-alloc devices. A claim reserved entirely by deleting
    pods is freed from the seed so the DFS re-allocates it onto replacement
    capacity (allocator.go:62-66)."""
    multi_alloc: set[DeviceID] = set()
    for s in slices:
        for d in s.devices:
            if d.allow_multiple_allocations:
                multi_alloc.add(DeviceID(s.driver, s.pool, d.name))
    state = AllocatedDeviceState()
    for claim in claims:
        if claim.allocation is None:
            continue
        if claim.reserved_for and all(uid in deleting_pod_uids for uid in claim.reserved_for):
            continue  # migrating: device freed, claim re-runs the DFS
        for dev in claim.allocation.devices:
            device_id = DeviceID(dev.driver, dev.pool, dev.device)
            if device_id in multi_alloc or dev.consumed_capacity:
                dims = state.consumed_capacity.setdefault(device_id, {})
                for name, qty in (dev.consumed_capacity or {}).items():
                    dims[name] = dims.get(name, 0.0) + qty
            else:
                state.exclusive_devices.add(device_id)
    return state


def build_attribute_bindings(
    catalogs_by_pool: dict[str, list],
) -> AttributeBindings:
    """Fold the catalog's per-IT binding declarations into the transitive
    graph (attributebindings.go:93-135). catalogs_by_pool maps nodepool name
    to its InstanceType list."""
    decls: dict[tuple[str, str], list[AttributeBindingDecl]] = {}
    for nodepool, catalog in catalogs_by_pool.items():
        for it in catalog:
            if getattr(it, "dra_attribute_bindings", None):
                decls[(nodepool, it.name)] = list(it.dra_attribute_bindings)
    return AttributeBindings.build(decls)


@dataclass
class DRAProblem:
    """Per-scheduling-loop DRA inputs, shared across relaxation rounds."""

    in_cluster_slices: list[ResourceSlice] = field(default_factory=list)
    device_classes: dict[str, DeviceClass] = field(default_factory=dict)
    claims_by_pod: dict[str, list[ResourceClaim]] = field(default_factory=dict)
    errors_by_pod: dict[str, str] = field(default_factory=dict)
    allocated_state: AllocatedDeviceState = field(default_factory=AllocatedDeviceState)
    attribute_bindings: AttributeBindings = field(default_factory=AttributeBindings)
    deleting_pod_uids: set[str] = field(default_factory=set)

    @staticmethod
    def build(
        store,
        pods,
        catalogs_by_pool: dict[str, list],
        extra_deleting_uids: Optional[set[str]] = None,
    ) -> Optional["DRAProblem"]:
        """Resolve pod claim references against the store
        (scheduler.go:571-589 resolvePodClaims); None when no pod uses DRA.
        Pods whose claims can't be resolved are flagged — no candidate can
        accept them this loop."""
        from karpenter_tpu.state.store import ObjectStore

        problem = DRAProblem(
            in_cluster_slices=[
                s for s in store.list(ObjectStore.RESOURCE_SLICES) if not s.potential
            ],
            device_classes={c.name: c for c in store.list(ObjectStore.DEVICE_CLASSES)},
            attribute_bindings=build_attribute_bindings(catalogs_by_pool),
        )
        any_dra = False
        for pod in pods:
            names = pod.spec.resource_claims
            if not names:
                continue
            any_dra = True
            resolved = []
            for name in names:
                rc = store.get(ObjectStore.RESOURCE_CLAIMS, name)
                if rc is None:
                    problem.errors_by_pod[pod.uid] = f"ResourceClaim {name!r} not found"
                    break
                resolved.append(rc)
            else:
                problem.claims_by_pod[pod.uid] = resolved
        if not any_dra:
            return None
        # Pods migrating off deleting nodes free their claims' devices.
        deleting_nodes = {
            n.metadata.name for n in store.nodes() if getattr(n.metadata, "deletion_timestamp", None)
        }
        problem.deleting_pod_uids = {
            p.uid
            for p in store.pods()
            if getattr(p.metadata, "deletion_timestamp", None) or p.spec.node_name in deleting_nodes
        }
        if extra_deleting_uids:
            problem.deleting_pod_uids |= extra_deleting_uids
        problem.allocated_state = gather_allocated_state(
            store.list(ObjectStore.RESOURCE_CLAIMS),
            problem.in_cluster_slices,
            problem.deleting_pod_uids,
        )
        return problem

    def fresh_round(self) -> "DRARound":
        return DRARound(
            problem=self,
            allocator=Allocator(
                in_cluster_slices=self.in_cluster_slices,
                allocated_state=AllocatedDeviceState(
                    exclusive_devices=set(self.allocated_state.exclusive_devices),
                    consumed_capacity={
                        k: dict(v) for k, v in self.allocated_state.consumed_capacity.items()
                    },
                ),
                device_classes=self.device_classes,
                attribute_bindings=self.attribute_bindings,
                deleting_pod_uids=self.deleting_pod_uids,
            ),
        )


@dataclass
class DRARound:
    """One relaxation round's allocator plus the call-site helpers the host
    scheduler uses (the nodeclaim.go:164-283 seam)."""

    problem: DRAProblem
    allocator: Allocator

    def pod_claims(self, pod) -> Optional[list[ResourceClaim]]:
        """The pod's resolved claims; None when the pod doesn't use DRA."""
        if not pod.spec.resource_claims:
            return None
        return self.problem.claims_by_pod.get(pod.uid)

    def pod_error(self, pod) -> Optional[str]:
        return self.problem.errors_by_pod.get(pod.uid)

    def try_allocate(
        self,
        pod,
        nodeclaim_id: str,
        nodepool: str,
        requirements: Requirements,
        instance_types: list,
        node_name: str = "",
    ) -> Optional[AllocationResult]:
        """Simulate allocation for a candidate (nodeclaim.go:179-192);
        None when no instance type can satisfy the pod's claims there."""
        claims = self.pod_claims(pod)
        if claims is None:
            return AllocationResult(
                instance_types=[it.name for it in instance_types], requirements=Requirements()
            )
        resource_slices = {
            it.name: list(getattr(it, "dra_slices", []) or []) for it in instance_types
        }
        adapter = DRANodeClaim(
            id=nodeclaim_id,
            nodepool=nodepool,
            requirements=requirements,
            instance_types=[it.name for it in instance_types],
            resource_slices=resource_slices,
            node_name=node_name,
        )
        try:
            return self.allocator.allocate(adapter, claims)
        except DRAError:
            return None

    def try_allocate_existing(
        self,
        pod,
        node_name: str,
        requirements: Requirements,
    ) -> Optional[AllocationResult]:
        """Existing-node variant (existingnode.go:81): the node has one
        collapsed instance type and no template slices — only published
        (in-cluster) devices are reachable."""
        claims = self.pod_claims(pod)
        if claims is None:
            return AllocationResult(instance_types=[], requirements=Requirements())
        from karpenter_tpu.models import labels as l

        it_req = requirements.get(l.LABEL_INSTANCE_TYPE)
        it_name = it_req.any_value() if it_req is not None else ""
        pool_req = requirements.get(l.NODEPOOL_LABEL_KEY)
        adapter = DRANodeClaim(
            id=node_name,
            nodepool=pool_req.any_value() if pool_req is not None else "",
            requirements=requirements,
            instance_types=[it_name or "existing"],
            resource_slices={},
            node_name=node_name,
        )
        try:
            return self.allocator.allocate(adapter, claims)
        except DRAError:
            return None

    def commit(self, result: AllocationResult, nodeclaim_id: str, final_it_names: set[str]) -> None:
        """Commit a finalized placement and release ITs the downstream
        filters pruned from the allocator's surviving set
        (nodeclaim.go:265-283)."""
        result.commit()
        pruned = [it for it in result.instance_types if it not in final_it_names]
        if pruned:
            self.allocator.release_instance_types(nodeclaim_id, *pruned)
