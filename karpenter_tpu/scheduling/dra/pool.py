"""Device pools: gathering, generation supersession, and filtering.

Counterpart of reference pkg/scheduling/dynamicresources/pool.go. Pools
group in-cluster ResourceSlices by (driver, pool name). Completeness is a
global pool property (all slices at the newest generation counted,
pool.go:278-292), while device visibility is scoped to the NodeClaim being
evaluated: only slices whose node affinity matches contribute allocatable
devices. Devices that consume shared counters on *non-matching* slices are
kept as NonTargetingDevices so their counter draw stays visible
(pool.go:56-61,144-149).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.models import labels as l
from karpenter_tpu.scheduling.dra.types import (
    CounterSet,
    Device,
    DeviceID,
    PoolKey,
    ResourceSlice,
    or_node_selector_terms,
)
from karpenter_tpu.scheduling.requirements import Requirement, Requirements


@dataclass
class DeviceWithID:
    """A device plus identity and the topology requirements inherited from
    its slice's node selector (None for all-nodes and template devices) —
    pool.go:40-44."""

    device: Device
    id: DeviceID
    topology_requirements: Optional[Requirements] = None


@dataclass
class Pool:
    key: PoolKey
    slices: list[ResourceSlice] = field(default_factory=list)
    devices: list[DeviceWithID] = field(default_factory=list)
    non_targeting_devices: list[DeviceWithID] = field(default_factory=list)
    counter_sets: dict[str, dict[str, float]] = field(default_factory=dict)
    incomplete: bool = False
    invalid: bool = False


def slice_topology_requirements(s: ResourceSlice) -> Optional[Requirements]:
    """Requirements implied by a slice's node accessibility: None when the
    slice is all-nodes. Node-name-pinned slices contribute a hostname
    requirement (stricter than the reference, whose sliceTopologyRequirements
    returns nil for them — pool.go:199-215 — letting a claim satisfied from
    a node-local device be reused from another node); ORed selector terms
    fold via the sound union (see dra.types.or_node_selector_terms)."""
    if s.all_nodes:
        return None
    if s.node_name:
        return Requirements(Requirement.new(l.LABEL_HOSTNAME, "In", s.node_name))
    if s.node_selector_terms is None:
        return None
    return or_node_selector_terms(s.node_selector_terms)


def _slice_matches(s: ResourceSlice, requirements: Requirements, node_name: str) -> bool:
    """Accessibility of a slice to the evaluated NodeClaim
    (pool.go:180-197)."""
    if s.potential:
        raise AssertionError("potential slices must not enter pool gathering")
    if s.all_nodes:
        return True
    if s.shared_counters is not None:
        return True
    if s.node_name:
        return bool(node_name) and s.node_name == node_name
    if s.node_selector_terms is not None:
        # Terms are ORed; a term matches when compatible with requirements.
        return any(
            requirements.is_compatible(term, l.WELL_KNOWN_LABELS) for term in s.node_selector_terms
        )
    return False


def _device_with_id(key: PoolKey, d: Device, topo: Optional[Requirements]) -> DeviceWithID:
    return DeviceWithID(
        device=d,
        id=DeviceID(driver=key.driver, pool=key.pool, device=d.name),
        topology_requirements=topo,
    )


class _PoolBuilder:
    """Accumulates slices for one pool with generation supersession
    (pool.go:238-269): older generations are discarded, a newer generation
    replaces everything seen so far."""

    def __init__(self) -> None:
        self.entries: list[tuple[ResourceSlice, bool]] = []
        self.generation = 0
        self.resource_slice_count = 1

    def add(self, s: ResourceSlice, matched: bool) -> None:
        if not self.entries:
            self.entries.append((s, matched))
            self.generation = s.generation
            self.resource_slice_count = s.resource_slice_count
            return
        if s.generation < self.generation:
            return
        if s.generation > self.generation:
            self.entries = [(s, matched)]
            self.generation = s.generation
            self.resource_slice_count = s.resource_slice_count
            return
        self.entries.append((s, matched))

    def build(self, key: PoolKey) -> Optional[Pool]:
        pool = Pool(key=key)
        if len(self.entries) != self.resource_slice_count:
            pool.incomplete = True

        counter_set_slices: list[ResourceSlice] = []
        non_targeting_slices: list[ResourceSlice] = []
        seen_names: set[str] = set()
        for s, matched in self.entries:
            if s.shared_counters is not None:
                counter_set_slices.append(s)
                continue
            if not matched:
                non_targeting_slices.append(s)
                for d in s.devices:
                    if d.consumes_counters:
                        pool.non_targeting_devices.append(_device_with_id(key, d, None))
                continue
            pool.slices.append(s)
            topo = slice_topology_requirements(s)
            for d in s.devices:
                if d.name in seen_names:
                    pool.invalid = True
                seen_names.add(d.name)
                pool.devices.append(_device_with_id(key, d, topo))

        counter_sets, valid = _collect_counter_sets(counter_set_slices)
        pool.counter_sets = counter_sets
        pool.invalid = pool.invalid or not valid
        pool.invalid = pool.invalid or not _counter_consumption_valid(counter_sets, pool.slices)
        pool.invalid = pool.invalid or not _counter_consumption_valid(counter_sets, non_targeting_slices)

        if pool.invalid:
            # Invalid pools contribute no allocatable devices, but their
            # counter-consuming devices remain visible (pool.go:323-332).
            for dw in pool.devices:
                if dw.device.consumes_counters:
                    pool.non_targeting_devices.append(dw)
            pool.devices = []
            pool.slices = []
            return pool
        if not pool.slices and not pool.devices and not pool.non_targeting_devices:
            return None
        return pool


def _collect_counter_sets(
    slices: list[ResourceSlice],
) -> tuple[dict[str, dict[str, float]], bool]:
    """Aggregate SharedCounters; duplicate counter-set names invalidate the
    pool (pool.go:341-353)."""
    counter_sets: dict[str, dict[str, float]] = {}
    valid = True
    for s in slices:
        for cs in s.shared_counters or []:
            if cs.name in counter_sets:
                valid = False
            counter_sets[cs.name] = dict(cs.counters)
    return counter_sets, valid


def _counter_consumption_valid(
    counter_sets: dict[str, dict[str, float]],
    slices,
) -> bool:
    """Every consumed counter must exist in a declared counter set
    (pool.go:357-376). Accepts ResourceSlices or Pool slices."""
    for s in slices:
        devices = s.devices if isinstance(s, ResourceSlice) else [dw.device for dw in s]
        for d in devices:
            for cc in d.consumes_counters:
                cs = counter_sets.get(cc.counter_set)
                if cs is None:
                    return False
                for counter_name in cc.counters:
                    if counter_name not in cs:
                        return False
    return True


def gather_pools(
    in_cluster_slices: list[ResourceSlice],
    requirements: Requirements,
    node_name: str = "",
) -> list[Pool]:
    """Build the in-cluster pool set for a NodeClaim (pool.go:87-112)."""
    builders: dict[PoolKey, _PoolBuilder] = {}
    for s in in_cluster_slices:
        matched = _slice_matches(s, requirements, node_name)
        key = PoolKey(driver=s.driver, pool=s.pool)
        builders.setdefault(key, _PoolBuilder()).add(s, matched)
    pools = []
    for key, b in builders.items():
        p = b.build(key)
        if p is not None:
            pools.append(p)
    return pools


def filter_pools(
    pools: list[Pool],
    requirements: Requirements,
    node_name: str = "",
) -> list[Pool]:
    """Narrow cached pools against tightened requirements without
    regathering (pool.go:119-166)."""
    filtered = []
    for pool in pools:
        p = _filter_pool(pool, requirements, node_name)
        if p is not None:
            filtered.append(p)
    return filtered


def _filter_pool(pool: Pool, requirements: Requirements, node_name: str) -> Optional[Pool]:
    p = Pool(
        key=pool.key,
        incomplete=pool.incomplete,
        invalid=pool.invalid,
        counter_sets=pool.counter_sets,
        non_targeting_devices=list(pool.non_targeting_devices),
    )
    for s in pool.slices:
        if not _slice_matches(s, requirements, node_name):
            for d in s.devices:
                if d.consumes_counters:
                    p.non_targeting_devices.append(_device_with_id(pool.key, d, None))
            continue
        p.slices.append(s)
        topo = slice_topology_requirements(s)
        for d in s.devices:
            p.devices.append(_device_with_id(pool.key, d, topo))
    if p.invalid:
        return p
    if not p.slices and not p.devices and not p.non_targeting_devices:
        return None
    return p
