"""Host-side scheduling primitives with exact reference semantics.

These are the correctness oracles for the TPU solver: the tensor encoding in
karpenter_tpu/ops is golden-tested against this package.
"""

from karpenter_tpu.scheduling.requirements import (  # noqa: F401
    Operator,
    Requirement,
    Requirements,
    node_selector_requirement,
)
from karpenter_tpu.scheduling.taints import tolerates, tolerates_all  # noqa: F401
