"""Requirement set algebra.

Counterpart of reference pkg/scheduling/requirement.go and requirements.go.
A Requirement is a compressed set over the values of one label key: either a
finite ``values`` set, or the *complement* of one (NotIn/Exists), with
optional inclusive integer bounds gte/lte (Gt/Lt are canonicalized on
construction, requirement.go:87-108) and a MinValues flexibility floor.

This module is deliberately pure-Python and allocation-light: it is both the
control-plane implementation and the semantic oracle the JAX tensor encoding
(karpenter_tpu/ops/encode.py) is golden-tested against.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

from karpenter_tpu.models import labels as l


class Operator(str, enum.Enum):
    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"
    GT = "Gt"
    LT = "Lt"
    GTE = "Gte"
    LTE = "Lte"


_MAX_INT = 2**63 - 1


def _parse_int(s: str) -> Optional[int]:
    try:
        return int(s)
    except ValueError:
        return None


def _within_bounds(value: str, gte: Optional[int], lte: Optional[int]) -> bool:
    """Bounds admit only integer-parseable values (requirement.go:334-348)."""
    if gte is None and lte is None:
        return True
    v = _parse_int(value)
    if v is None:
        return False
    if gte is not None and v < gte:
        return False
    if lte is not None and v > lte:
        return False
    return True


@dataclass
class Requirement:
    """One label key's constraint. Construct via `new_requirement`."""

    key: str
    complement: bool = False
    values: frozenset[str] = field(default_factory=frozenset)
    gte: Optional[int] = None  # inclusive
    lte: Optional[int] = None  # inclusive
    min_values: Optional[int] = None

    # -- construction ------------------------------------------------------

    @staticmethod
    def new(key: str, operator: "Operator | str", *values: str, min_values: Optional[int] = None) -> "Requirement":
        op = Operator(operator)
        key = l.NORMALIZED_LABELS.get(key, key)
        value_map = l.NORMALIZED_LABEL_VALUES.get(key)
        if value_map:
            values = tuple(value_map.get(v, v) for v in values)

        if op is Operator.IN:
            return Requirement(key=key, complement=False, values=frozenset(values), min_values=min_values)
        if op is Operator.DOES_NOT_EXIST:
            return Requirement(key=key, complement=False, values=frozenset(), min_values=min_values)

        r = Requirement(key=key, complement=True, min_values=min_values)
        if op is Operator.NOT_IN:
            r.values = frozenset(values)
        elif op is Operator.GT:
            v = int(values[0])
            if v == _MAX_INT:
                # Gt MaxInt matches nothing (requirement.go:91-94)
                return Requirement.new(key, Operator.DOES_NOT_EXIST, min_values=min_values)
            r.gte = v + 1
        elif op is Operator.LT:
            r.lte = int(values[0]) - 1
        elif op is Operator.GTE:
            r.gte = int(values[0])
        elif op is Operator.LTE:
            r.lte = int(values[0])
        return r

    # -- semantics ---------------------------------------------------------

    def operator(self) -> Operator:
        """Derive the canonical operator (requirement.go:290-301)."""
        if self.complement:
            return Operator.NOT_IN if self.values else Operator.EXISTS
        return Operator.IN if self.values else Operator.DOES_NOT_EXIST

    def is_lenient(self) -> bool:
        """NotIn / DoesNotExist — tolerated on keys the other side lacks."""
        return self.operator() in (Operator.NOT_IN, Operator.DOES_NOT_EXIST)

    def has(self, value: str) -> bool:
        """True if the requirement admits the value (requirement.go:~Has)."""
        in_set = value in self.values
        ok = (not in_set) if self.complement else in_set
        return ok and _within_bounds(value, self.gte, self.lte)

    def intersection(self, other: "Requirement") -> "Requirement":
        """Exact set intersection (requirement.go:181-214)."""
        complement = self.complement and other.complement
        gte = _max_opt(self.gte, other.gte)
        lte = _min_opt(self.lte, other.lte)
        min_values = _max_opt(self.min_values, other.min_values)
        if gte is not None and lte is not None and gte > lte:
            return Requirement.new(self.key, Operator.DOES_NOT_EXIST, min_values=min_values)

        if self.complement and other.complement:
            values = self.values | other.values  # union of exclusions
        elif self.complement:
            values = other.values - self.values
        elif other.complement:
            values = self.values - other.values
        else:
            values = self.values & other.values
        values = frozenset(v for v in values if _within_bounds(v, gte, lte))
        if not complement:
            gte, lte = None, None  # concrete sets carry no bounds
        return Requirement(
            key=self.key, complement=complement, values=values, gte=gte, lte=lte, min_values=min_values
        )

    def union(self, other: "Requirement") -> "Requirement":
        """Sound over-approximation of set union (no Go counterpart — the
        reference folds ORed node-selector terms by intersection, which can
        collapse to an empty set; see dra.types.or_node_selector_terms).
        Every value admitted by either side is admitted by the result."""
        both_gte = self.gte is not None and other.gte is not None
        both_lte = self.lte is not None and other.lte is not None
        if self.complement and other.complement:
            values = self.values & other.values
            gte = min(self.gte, other.gte) if both_gte else None
            lte = max(self.lte, other.lte) if both_lte else None
            return Requirement(key=self.key, complement=True, values=values, gte=gte, lte=lte)
        if self.complement:
            return Requirement(key=self.key, complement=True, values=self.values - other.values)
        if other.complement:
            return Requirement(key=self.key, complement=True, values=other.values - self.values)
        return Requirement(key=self.key, complement=False, values=self.values | other.values)

    def has_intersection(self, other: "Requirement") -> bool:
        """Allocation-free fast path (requirement.go:220-254)."""
        gte = _max_opt(self.gte, other.gte)
        lte = _min_opt(self.lte, other.lte)
        if gte is not None and lte is not None and gte > lte:
            return False
        if self.complement and other.complement:
            return True
        if self.complement:
            return any(v not in self.values and _within_bounds(v, gte, lte) for v in other.values)
        if other.complement:
            return any(v not in other.values and _within_bounds(v, gte, lte) for v in self.values)
        return any(v in other.values and _within_bounds(v, gte, lte) for v in self.values)

    def any_value(self) -> str:
        """Some admissible value (requirement.go:~Any); deterministic here."""
        op = self.operator()
        if op is Operator.IN:
            return sorted(self.values)[0]
        if op in (Operator.NOT_IN, Operator.EXISTS):
            # The exclusion set rules out at most len(values) integers, so a
            # bounded scan of len(values)+1 candidates inside [gte, lte]
            # always finds an admissible value if one exists.
            span = len(self.values) + 1
            if self.gte is not None:
                candidates = range(self.gte, self.gte + span)
            elif self.lte is not None:
                candidates = range(self.lte, self.lte - span, -1)
            else:
                candidates = range(0, span)
            for v in candidates:
                if self.has(str(v)):
                    return str(v)
        return ""

    def __len__(self) -> int:
        # complement sets are "infinite minus exclusions" (requirement.go:303-308)
        if self.complement:
            return _MAX_INT - len(self.values)
        return len(self.values)

    def __str__(self) -> str:
        op = self.operator()
        if op in (Operator.EXISTS, Operator.DOES_NOT_EXIST):
            s = f"{self.key} {op.value}"
        else:
            vals = sorted(self.values)
            if len(vals) > 5:
                vals = vals[:5] + [f"and {len(vals) - 5} others"]
            s = f"{self.key} {op.value} {vals}"
        if self.gte is not None:
            s += f" >={self.gte}"
        if self.lte is not None:
            s += f" <={self.lte}"
        if self.min_values is not None:
            s += f" minValues {self.min_values}"
        return s


def _max_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def _min_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


def node_selector_requirement(key: str, operator: str, values: Iterable[str] = (), min_values: Optional[int] = None) -> Requirement:
    """Build a Requirement from a NodeSelectorRequirement-shaped triple."""
    return Requirement.new(key, operator, *values, min_values=min_values)


class Requirements:
    """A map key -> Requirement with intersection-on-add semantics.

    Counterpart of reference pkg/scheduling/requirements.go:36-274.
    """

    __slots__ = ("_reqs",)

    def __init__(self, *requirements: Requirement):
        self._reqs: dict[str, Requirement] = {}
        self.add(*requirements)

    # -- constructors ------------------------------------------------------

    @staticmethod
    def from_labels(labels: dict[str, str]) -> "Requirements":
        return Requirements(*(Requirement.new(k, Operator.IN, v) for k, v in labels.items()))

    @staticmethod
    def from_node_selector_requirements(reqs) -> "Requirements":
        """reqs: iterable of dicts {key, operator, values?, minValues?}."""
        return Requirements(
            *(
                node_selector_requirement(
                    r["key"], r["operator"], r.get("values", ()), r.get("minValues")
                )
                for r in reqs
            )
        )

    @staticmethod
    def from_pod(pod, include_preferred: bool = True) -> "Requirements":
        """Pod -> requirements (requirements.go:90-110): nodeSelector labels,
        heaviest preferred node-affinity term treated as required (when
        include_preferred), and the FIRST required node-affinity term (ORs
        are relaxed by an outer loop)."""
        reqs = Requirements.from_labels(dict(pod.spec.node_selector or {}))
        na = pod.spec.node_affinity
        if na is None:
            return reqs
        if include_preferred and na.preferred:
            heaviest = max(na.preferred, key=lambda t: t.weight)
            reqs.add(*(node_selector_requirement(m["key"], m["operator"], m.get("values", ())) for m in heaviest.match_expressions))
        if na.required:
            reqs.add(*(node_selector_requirement(m["key"], m["operator"], m.get("values", ())) for m in na.required[0].match_expressions))
        return reqs

    # -- map behavior ------------------------------------------------------

    def add(self, *requirements: Requirement) -> None:
        """Add with per-key intersection (requirements.go:133-140)."""
        for req in requirements:
            existing = self._reqs.get(req.key)
            if existing is not None:
                req = req.intersection(existing)
            self._reqs[req.key] = req

    def keys(self) -> set[str]:
        return set(self._reqs)

    def values(self) -> list[Requirement]:
        return list(self._reqs.values())

    def has(self, key: str) -> bool:
        return key in self._reqs

    def get(self, key: str) -> Requirement:
        """Missing keys read as Exists — any value (requirements.go:160-166)."""
        r = self._reqs.get(key)
        if r is None:
            return Requirement.new(key, Operator.EXISTS)
        return r

    def __iter__(self) -> Iterator[Requirement]:
        return iter(self._reqs.values())

    def __len__(self) -> int:
        return len(self._reqs)

    def __contains__(self, key: str) -> bool:
        return key in self._reqs

    def copy(self) -> "Requirements":
        out = Requirements()
        out._reqs = dict(self._reqs)
        return out

    def relax_min_values(self, key: str, min_values: int) -> None:
        """Lower a key's minValues floor (BestEffort relaxation,
        nodeclaim.go:214-219). Replaces the Requirement object — instances
        may be shared across claims and templates."""
        import dataclasses

        r = self._reqs.get(key)
        if r is not None:
            self._reqs[key] = dataclasses.replace(r, min_values=min_values)

    def labels(self) -> dict[str, str]:
        """Single-valued In requirements as labels (for node fabrication)."""
        out = {}
        for key, req in self._reqs.items():
            if req.operator() is Operator.IN:
                out[key] = req.any_value()
        return out

    # -- compatibility -----------------------------------------------------

    def compatible(self, incoming: "Requirements", allow_undefined: frozenset[str] = frozenset()) -> Optional[str]:
        """None if `incoming` can loosely be met by self, else an error string.

        Mirrors requirements.go:181-197: custom (non-allowed-undefined) keys
        in `incoming` must be defined on self unless the incoming operator is
        NotIn/DoesNotExist; then all shared keys must intersect.
        """
        for key in incoming.keys():
            if key in allow_undefined:
                continue
            if self.has(key) or incoming.get(key).is_lenient():
                continue
            return f'label "{key}" does not have known values'
        return self.intersects(incoming)

    def is_compatible(self, incoming: "Requirements", allow_undefined: frozenset[str] = frozenset()) -> bool:
        """Allocation-free boolean fast path (no error-string formatting —
        the reference keeps error construction lazy for the same reason,
        nodeclaim.go:543-556)."""
        for key in incoming._reqs:
            if key in allow_undefined:
                continue
            if key in self._reqs or incoming._reqs[key].is_lenient():
                continue
            return False
        return self.intersects_ok(incoming)

    def intersects_ok(self, incoming: "Requirements") -> bool:
        """Boolean twin of intersects() without error strings."""
        mine = self._reqs
        theirs = incoming._reqs
        if len(theirs) < len(mine):
            small, large = theirs, mine
        else:
            small, large = mine, theirs
        for key in small:
            if key not in large:
                continue
            existing = mine[key]
            inc = theirs[key]
            if not existing.has_intersection(inc):
                if inc.is_lenient() and existing.is_lenient():
                    continue
                return False
        return True

    def intersects(self, incoming: "Requirements") -> Optional[str]:
        """None if all shared keys intersect (requirements.go:254-274).

        A failed intersection is forgiven when BOTH sides' operators are in
        {NotIn, DoesNotExist} (both exclude, neither names a required value).
        """
        errs = []
        for key in self.keys() & incoming.keys():
            existing = self.get(key)
            inc = incoming.get(key)
            if not existing.has_intersection(inc):
                if inc.is_lenient() and existing.is_lenient():
                    continue
                errs.append(f"key {key}, {inc} not in {existing}")
        return "; ".join(errs) if errs else None

    def has_min_values(self) -> bool:
        return any(r.min_values is not None for r in self._reqs.values())

    def __str__(self) -> str:
        reqs = [str(r) for r in self._reqs.values() if r.key not in l.RESTRICTED_LABELS]
        return ", ".join(sorted(reqs))


# Capacity-type shorthands (reference cloudprovider/types.go ReservedRequirement etc.)
def spot_requirements() -> Requirements:
    return Requirements(Requirement.new(l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, l.CAPACITY_TYPE_SPOT))


def on_demand_requirements() -> Requirements:
    return Requirements(Requirement.new(l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, l.CAPACITY_TYPE_ON_DEMAND))


def reserved_requirements() -> Requirements:
    return Requirements(Requirement.new(l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, l.CAPACITY_TYPE_RESERVED))
