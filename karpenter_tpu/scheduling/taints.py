"""Taint toleration checks (reference pkg/scheduling/taints.go:78-112)."""

from __future__ import annotations

from typing import Iterable, Optional

from karpenter_tpu.models.taints import Taint, Toleration


def tolerates(tolerations: Iterable[Toleration], taint: Taint) -> bool:
    return any(t.tolerates(taint) for t in tolerations)


def tolerates_all(taints: Iterable[Taint], tolerations: Iterable[Toleration]) -> Optional[str]:
    """None if every taint is tolerated, else a message naming the first miss."""
    tolerations = list(tolerations)
    for taint in taints:
        if not tolerates(tolerations, taint):
            return f"did not tolerate taint {taint.key}={taint.value}:{taint.effect}"
    return None


def merge(taints: list[Taint], with_taints: Iterable[Taint]) -> list[Taint]:
    """Append taints not already present by key+effect (taints.go:100-112)."""
    out = list(taints)
    for taint in with_taints:
        if not any(taint.match(t) for t in out):
            out.append(taint)
    return out
