"""Reserved-capacity semantics: ReservationManager + scheduler integration
(reference reservationmanager.go:28-115, nodeclaim.go:256-349, FinalizeScheduling
nodeclaim.go:385-401), differentially tested across both engines."""

import pytest

from karpenter_tpu.cloudprovider.fake import instance_types, new_instance_type
from karpenter_tpu.cloudprovider.instancetype import RESERVATION_ID_LABEL
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.controllers.provisioning.host_scheduler import HostScheduler
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.scheduling.reservations import (
    RESERVED_MODE_STRICT,
    ReservationManager,
)


def pool(name="default"):
    p = NodePool()
    p.metadata.name = name
    return p


def reserved_catalog(cap=2, cpu=4, extra_plain=1):
    """One instance type with a reserved offering (capacity `cap` in
    test-zone-1) + optional plain types."""
    its = [
        new_instance_type(
            "res-4x",
            cpu=cpu,
            reservations=[("test-zone-1", "res-1", cap)],
        )
    ]
    for i in range(extra_plain):
        its.append(new_instance_type(f"plain-{i}", cpu=cpu))
    return its


class TestReservationManager:
    def test_capacity_min_over_duplicates(self):
        its = [
            new_instance_type("a", reservations=[("test-zone-1", "r", 5)]),
            new_instance_type("b", reservations=[("test-zone-1", "r", 3)]),
        ]
        rm = ReservationManager(its)
        assert rm.capacity["r"] == 3

    def test_idempotent_reserve_release(self):
        its = [new_instance_type("a", reservations=[("test-zone-1", "r", 2)])]
        rm = ReservationManager(its)
        o = [of for of in its[0].offerings if of.capacity_type == "reserved"][0]
        assert rm.can_reserve("h1", o)
        rm.reserve("h1", [o])
        rm.reserve("h1", [o])  # idempotent per host
        assert rm.remaining("r") == 1
        assert rm.has_reservation("h1", o)
        rm.release("h1", "r")
        rm.release("h1", "r")
        assert rm.remaining("r") == 2

    def test_exhausted_capacity_blocks_new_hosts(self):
        its = [new_instance_type("a", reservations=[("test-zone-1", "r", 1)])]
        rm = ReservationManager(its)
        o = [of for of in its[0].offerings if of.capacity_type == "reserved"][0]
        rm.reserve("h1", [o])
        assert not rm.can_reserve("h2", o)
        assert rm.can_reserve("h1", o)  # existing holder keeps it


def solve_both(catalog, pods, reserved_mode="fallback"):
    templates = build_templates([(pool(), catalog)])
    host = HostScheduler(templates, reserved_mode=reserved_mode).solve(pods)
    tpu = TPUScheduler(templates, reserved_mode=reserved_mode).solve(pods)
    assert len(host.claims) == len(tpu.claims)
    assert host.assignments == tpu.assignments
    for hc, tc in zip(host.claims, tpu.claims):
        assert hc.reserved_ids == tc.reserved_ids, (hc.slot, hc.reserved_ids, tc.reserved_ids)
        assert {it.name for it in hc.instance_types} == {it.name for it in tc.instance_types}
        assert hc.requirements.get(RESERVATION_ID_LABEL).values == (
            tc.requirements.get(RESERVATION_ID_LABEL).values
        )
        assert hc.requirements.get(l.CAPACITY_TYPE_LABEL_KEY).values == (
            tc.requirements.get(l.CAPACITY_TYPE_LABEL_KEY).values
        )
    return host, tpu


class TestReservedScheduling:
    def test_claim_pins_to_reserved(self):
        host, _ = solve_both(reserved_catalog(cap=2), [make_pod("p", cpu=1.0)])
        [claim] = host.claims
        assert claim.reserved_ids == {"res-1"}
        assert claim.requirements.get(l.CAPACITY_TYPE_LABEL_KEY).values == frozenset(
            {l.CAPACITY_TYPE_RESERVED}
        )
        assert claim.requirements.get(RESERVATION_ID_LABEL).values == frozenset({"res-1"})
        # reserved launches are free (WorstLaunchPrice precedence)
        assert claim.cheapest_launch()[1] == 0.0

    def test_stacking_pods_holds_one_reservation(self):
        """Multiple pods on one claim decrement capacity once (idempotent
        per-hostname reserve)."""
        host, _ = solve_both(
            reserved_catalog(cap=2), [make_pod(f"p-{i}", cpu=1.0) for i in range(3)]
        )
        [claim] = host.claims
        assert len(claim.pods) == 3
        assert claim.reserved_ids == {"res-1"}

    def test_fallback_after_capacity_exhausted(self):
        """cap=1: the first claim takes the reservation; a second claim
        (forced by big pods) falls back to spot/on-demand."""
        catalog = reserved_catalog(cap=1, cpu=4)
        pods = [make_pod(f"p-{i}", cpu=3.0) for i in range(2)]  # one pod per node
        host, _ = solve_both(catalog, pods)
        assert len(host.claims) == 2
        reserved = [c for c in host.claims if c.reserved_ids]
        plain = [c for c in host.claims if not c.reserved_ids]
        assert len(reserved) == 1 and len(plain) == 1
        assert plain[0].cheapest_launch()[1] > 0.0
        assert not plain[0].requirements.get(l.CAPACITY_TYPE_LABEL_KEY).has(
            l.CAPACITY_TYPE_RESERVED
        ) or plain[0].requirements.get(l.CAPACITY_TYPE_LABEL_KEY).values != frozenset(
            {l.CAPACITY_TYPE_RESERVED}
        )

    def test_strict_mode_fails_instead_of_falling_back(self):
        """Strict: when the reservation is exhausted the add must FAIL so a
        later loop can retry once capacity frees (scheduler.go:75-78)."""
        catalog = reserved_catalog(cap=1, cpu=4, extra_plain=0)
        pods = [make_pod(f"p-{i}", cpu=3.0) for i in range(2)]
        host, tpu = solve_both(catalog, pods, reserved_mode=RESERVED_MODE_STRICT)
        assert len(host.claims) == 1
        assert len(host.unschedulable) == 1
        assert len(tpu.unschedulable) == 1

    def test_release_on_narrowing(self):
        """A claim holding reservations in two zones releases the one a new
        pod's zone selector filters out."""
        its = [
            new_instance_type(
                "res-4x",
                cpu=4,
                reservations=[("test-zone-1", "r-a", 1), ("test-zone-2", "r-b", 1)],
            )
        ]
        templates = build_templates([(pool(), its)])
        host_sched = HostScheduler(templates)
        wide = make_pod("wide", cpu=1.0)
        narrow = make_pod(
            "narrow", cpu=1.0, node_selector={l.LABEL_TOPOLOGY_ZONE: "test-zone-1"}
        )
        result = host_sched.solve([wide, narrow])
        [claim] = result.claims
        assert claim.reserved_ids == {"r-a"}
        assert host_sched._rm.remaining("r-b") == 1, "narrowed-out reservation not released"

    def test_reserved_e2e_launch(self):
        """Full harness: a pod provisions onto reserved capacity; the
        launched node carries capacity-type=reserved + the reservation id
        and prices at zero."""
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
        from karpenter_tpu.state.store import ObjectStore
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = ObjectStore(clock)
        cloud = KwokCloudProvider(store, catalog=reserved_catalog(cap=2))
        mgr = Manager(store, cloud, clock)
        store.create(ObjectStore.NODEPOOLS, pool())
        store.create(ObjectStore.PODS, make_pod("p", cpu=1.0))
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        [node] = store.nodes()
        assert node.metadata.labels[l.CAPACITY_TYPE_LABEL_KEY] == l.CAPACITY_TYPE_RESERVED
        [claim] = store.nodeclaims()
        rid_req = [
            r for r in claim.spec.requirements if r.get("key") == RESERVATION_ID_LABEL
        ]
        assert rid_req and rid_req[0]["values"] == ["res-1"]

    def test_capacity_not_oversubscribed_across_loops(self):
        """A launched reserved instance consumes catalog capacity, so the
        NEXT provisioning loop cannot double-book the reservation — and
        deleting the node frees it again."""
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
        from karpenter_tpu.state.store import ObjectStore
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = ObjectStore(clock)
        cloud = KwokCloudProvider(store, catalog=reserved_catalog(cap=1, cpu=4, extra_plain=0))
        mgr = Manager(store, cloud, clock)
        store.create(ObjectStore.NODEPOOLS, pool())
        store.create(ObjectStore.PODS, make_pod("a", cpu=3.0))
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        assert len(store.nodes()) == 1
        # second loop: the reservation is consumed — strict provisioning
        # must NOT launch a second instance into it
        store.create(ObjectStore.PODS, make_pod("b", cpu=3.0))
        for _ in range(3):
            mgr.run_until_idle()
            cloud.simulate_kubelet_ready()
            mgr.run_until_idle()
        reserved_nodes = [
            n
            for n in store.nodes()
            if n.metadata.labels.get(l.CAPACITY_TYPE_LABEL_KEY) == l.CAPACITY_TYPE_RESERVED
        ]
        assert len(reserved_nodes) == 1, "reservation double-booked across loops"
        # freeing the node restores the slot for the pending pod
        pod_a = next(p for p in store.pods() if p.name == "a")
        pod_a.status.phase = "Succeeded"
        store.update(ObjectStore.PODS, pod_a)
        store.delete(ObjectStore.PODS, pod_a.name)
        claim = store.nodeclaims()[0]
        store.delete(ObjectStore.NODECLAIMS, claim.name)
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        pod_b = next(p for p in store.pods() if p.name == "b")
        assert pod_b.spec.node_name, "freed reservation not reused"

    def test_reserved_mix_differential(self):
        """BASELINE config #5 shape: spot/on-demand/reserved mix at small
        scale — both engines agree on packing and reservations."""
        catalog = instance_types(16) + [
            new_instance_type(
                "res-8x", cpu=8, reservations=[("test-zone-1", "big-res", 2)]
            )
        ]
        pods = [make_pod(f"p-{i}", cpu=1.0, memory="1Gi") for i in range(12)]
        solve_both(catalog, pods)
