"""Golden tests: the tensor encoding + kernels must agree with the
pure-Python oracle (karpenter_tpu.scheduling) on randomized requirement
sets — the Phase-0 correctness gate for the TPU solver."""

import numpy as np
import pytest

from karpenter_tpu.models import labels as l
from karpenter_tpu.ops import kernels
from karpenter_tpu.ops.encode import ProblemEncoder, Vocab, encode_requirements
from karpenter_tpu.scheduling import Operator, Requirement, Requirements

KEYS = ["zone", "arch", "team", l.LABEL_TOPOLOGY_ZONE, "tier"]
VALUES = ["a", "b", "c", "1", "5", "17", "x"]


OPS = [
    Operator.IN,
    Operator.NOT_IN,
    Operator.EXISTS,
    Operator.DOES_NOT_EXIST,
    Operator.GT,
    Operator.LT,
    Operator.GTE,
    Operator.LTE,
]


def random_requirement(rng, key) -> Requirement:
    op = OPS[int(rng.integers(0, len(OPS)))]
    if op in (Operator.GT, Operator.LT, Operator.GTE, Operator.LTE):
        return Requirement.new(key, op, str(rng.integers(0, 20)))
    if op in (Operator.EXISTS, Operator.DOES_NOT_EXIST):
        return Requirement.new(key, op)
    n = int(rng.integers(1, 4))
    vals = [str(v) for v in rng.choice(VALUES, size=n, replace=False)]
    return Requirement.new(key, op, *vals)


def random_requirements(rng) -> Requirements:
    n_keys = int(rng.integers(0, len(KEYS) + 1))
    keys = list(rng.choice(KEYS, size=n_keys, replace=False))
    out = Requirements()
    for k in keys:
        out.add(random_requirement(rng, k))
        if rng.random() < 0.3:  # occasionally intersect two reqs on one key
            out.add(random_requirement(rng, k))
    return out


@pytest.fixture(scope="module")
def req_batch():
    rng = np.random.default_rng(42)
    sets = [random_requirements(rng) for _ in range(40)]
    vocab = Vocab()
    for s in sets:
        vocab.observe(s)
    # ensure every key exists in vocab even if only bounds-ops hit it
    for k in KEYS:
        vocab.add_key(k)
        for v in VALUES:
            vocab.add_value(k, v)
    enc = encode_requirements(vocab, sets)
    return sets, vocab, enc


class TestGoldenKernels:
    def test_mask_matches_has(self, req_batch):
        sets, vocab, enc = req_batch
        mask = np.asarray(enc.mask)
        for b, s in enumerate(sets):
            for r in s:
                k = vocab.key_to_id[r.key]
                for vid, val in enumerate(vocab.values[k]):
                    assert mask[b, k, vid] == r.has(val), (r, val)

    def test_intersects_golden(self, req_batch):
        sets, vocab, enc = req_batch
        got = np.asarray(kernels.intersects(enc, enc))
        for i, a in enumerate(sets):
            for j, b in enumerate(sets):
                want = a.intersects(b) is None
                assert got[i, j] == want, f"{i} vs {j}: {a} || {b}"

    def test_compatible_golden(self, req_batch):
        sets, vocab, enc = req_batch
        wk = vocab.well_known_mask()
        got = np.asarray(kernels.compatible(enc, enc, wk))
        for i, a in enumerate(sets):
            for j, b in enumerate(sets):
                want = a.is_compatible(b, allow_undefined=l.WELL_KNOWN_LABELS)
                assert got[i, j] == want, f"{i} vs {j}: {a} || {b}"

    def test_lenient_golden(self, req_batch):
        sets, vocab, enc = req_batch
        got = np.asarray(kernels.lenient(enc))
        for b, s in enumerate(sets):
            for r in s:
                k = vocab.key_to_id[r.key]
                assert got[b, k] == r.is_lenient(), r

    def test_intersect_sets_golden(self, req_batch):
        """encode(A.add(B)) must behave identically to
        intersect_sets(encode(A), encode(B))."""
        sets, vocab, enc = req_batch
        import karpenter_tpu.ops.kernels as K

        n = len(sets)
        perm = list(range(1, n)) + [0]
        b_enc = kernels.take_set(enc, np.array(perm))
        combined = kernels.intersect_sets(enc, b_enc)
        for i in range(n):
            a, b = sets[i], sets[perm[i]]
            host = a.copy()
            host.add(*b.values())
            host_enc = encode_requirements(vocab, [host])
            got = kernels.take_set(combined, i)
            assert np.array_equal(np.asarray(got.mask), np.asarray(host_enc.mask[0])), (a, b)
            assert np.array_equal(np.asarray(got.defined), np.asarray(host_enc.defined[0]))
            assert np.array_equal(np.asarray(got.inf), np.asarray(host_enc.inf[0]))
            # bounds/excl only observable when inf; compare gated
            inf = np.asarray(got.inf)
            assert np.array_equal(np.asarray(got.excl) & inf, np.asarray(host_enc.excl[0]) & inf)
            assert np.array_equal(
                np.where(inf, np.asarray(got.gte), 0), np.where(inf, np.asarray(host_enc.gte[0]), 0)
            )
            assert np.array_equal(
                np.where(inf, np.asarray(got.lte), 0), np.where(inf, np.asarray(host_enc.lte[0]), 0)
            )
            # and the derived leniency agrees
            got_len = np.asarray(K.lenient(kernels.take_set(combined, np.array([i]))))[0]
            want_len = np.asarray(K.lenient(host_enc))[0]
            assert np.array_equal(got_len, want_len)


class TestEncoder:
    def test_pod_encoding(self):
        from karpenter_tpu.models.pod import make_pod

        enc = ProblemEncoder()
        pods = [
            make_pod("a", cpu=1, memory="1Gi", node_selector={l.LABEL_TOPOLOGY_ZONE: "z1"}),
            make_pod("b", cpu=2, memory="2Gi"),
        ]
        for p in pods:
            enc.observe_pod(p)
        pt = enc.encode_pods(pods)
        assert pt.requests.shape[0] == 2
        # cpu column
        cpu_id = enc.resource_names.index("cpu")
        assert pt.requests[0, cpu_id] == 1.0
        assert pt.requests[1, cpu_id] == 2.0
        pods_id = enc.resource_names.index("pods")
        assert pt.requests[0, pods_id] == 1.0
        # zone requirement encoded
        zk = enc.vocab.key_to_id[l.LABEL_TOPOLOGY_ZONE]
        assert bool(np.asarray(pt.reqs.defined)[0, zk])
        assert not bool(np.asarray(pt.reqs.defined)[1, zk])

    def test_instance_type_encoding(self):
        from karpenter_tpu.cloudprovider.fake import instance_types

        its = instance_types(8)
        enc = ProblemEncoder()
        for it in its:
            enc.observe_instance_type(it)
        itt = enc.encode_instance_types(its)
        assert itt.n_types == 8
        assert bool(np.asarray(itt.valid).all())
        # every type has exactly one allocatable group, available in 4 zones × 2 cts
        zc = np.asarray(itt.zc_avail)
        assert zc.shape[1] == 1
        assert int(zc[0, 0].sum()) == 8
        # price matrix finite where available
        prices = np.asarray(itt.price_zc)
        assert np.isfinite(prices[zc[:, 0]]).all()
        # allocatable below capacity (overhead subtracted)
        cpu_id = enc.resource_names.index("cpu")
        alloc = np.asarray(itt.alloc)
        for t, it in enumerate(its):
            assert alloc[t, 0, cpu_id] < it.capacity["cpu"]
            assert alloc[t, 0, cpu_id] == pytest.approx(it.allocatable()["cpu"], rel=1e-5)

    def test_offering_value_allowed(self):
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.models.pod import make_pod

        its = instance_types(4)
        pod = make_pod("p", node_selector={l.LABEL_TOPOLOGY_ZONE: "test-zone-2"})
        enc = ProblemEncoder()
        for it in its:
            enc.observe_instance_type(it)
        enc.observe_pod(pod)
        pt = enc.encode_pods([pod])
        zone_kid, _ = enc.zone_ct_key_ids()
        z2 = enc.vocab.value_to_id[zone_kid]["test-zone-2"]
        z1 = enc.vocab.value_to_id[zone_kid]["test-zone-1"]
        allowed = np.asarray(kernels.value_allowed(pt.reqs, zone_kid, np.array([z1, z2])))
        assert not allowed[0, 0] and allowed[0, 1]
