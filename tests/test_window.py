"""Differential parity for the active-window claims compaction (ISSUE 5).

The solver's carry keeps hot per-claim tensors only for a bounded window
W of resident open claims; capacity-dead claims are evicted into the
frozen bank between dispatches, and window-bound opens spill into the
host's NO_ROOM escalation (grow the window, re-solve). None of that may
move a single pod: every windowed solve must be BIT-identical to the
host oracle and to the un-windowed device solve, across the three
dispatch modes (fill / kind-scan / per-pod) crossed with pipeline
chunking at K in {1, 2, 4}.

Everything here is host-only (CPU mesh) and sized for tier-1 — the
window path needs no accelerator to be exercised at small W.
"""

import numpy as np
import pytest

import bench
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod

from test_solver import assert_same_packing


def make_templates(n_types=40):
    pool = NodePool()
    pool.metadata.name = "default"
    return build_templates([(pool, instance_types(n_types))])


def windowed_scheduler(monkeypatch, window, k=0, n_types=40, max_claims=128,
                       solve_chunk=None):
    """A TPUScheduler with the active window forced to `window` columns
    (0 = un-windowed baseline) and the pipeline forced to K chunk groups."""
    if window:
        monkeypatch.setenv("KTPU_SCAN_WINDOW", str(window))
    else:
        monkeypatch.delenv("KTPU_SCAN_WINDOW", raising=False)
    if k > 1:
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", str(k))
        monkeypatch.setenv("KTPU_PIPELINE_MIN_PODS", "0")
    else:
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
    if solve_chunk is not None:
        monkeypatch.setenv("KTPU_SOLVE_CHUNK", str(solve_chunk))
    return TPUScheduler(
        make_templates(n_types), pod_pad=None, max_claims=max_claims
    )


def assert_scan_coherent(sched):
    """The occupancy record must be internally consistent."""
    scan = sched.last_timings.get("scan")
    assert scan is not None, "windowed solve must record last_timings['scan']"
    assert scan["resident"] + scan["frozen"] == scan["n_open"], scan
    assert scan["live_hw"] <= scan["window"], scan
    assert scan["window"] <= scan["n_claims"], scan
    return scan


def run_window_parity(monkeypatch, pods, n_types, max_claims, window,
                      budgets=None, solve_chunk=None, ks=(1, 2, 4)):
    """Solve windowed at each K; pin against the un-windowed unchunked
    device solve AND the host oracle."""
    href, _ = bench.host_solve(make_templates(n_types), pods)
    if budgets is not None:
        from karpenter_tpu.controllers.provisioning.host_scheduler import (
            HostScheduler,
        )
        from karpenter_tpu.controllers.provisioning.topology import (
            Topology,
            build_universe_domains,
        )

        templates = make_templates(n_types)
        topo = Topology.build(
            list(pods), build_universe_domains(templates, []), []
        )
        href = HostScheduler(templates, budgets=budgets, topology=topo).solve(
            list(pods)
        )
    base_sched = windowed_scheduler(
        monkeypatch, 0, 0, n_types, max_claims, solve_chunk=solve_chunk
    )
    base = base_sched.solve(pods, budgets=budgets)
    assert_same_packing(href, base)
    for k in ks:
        sched = windowed_scheduler(
            monkeypatch, window, k, n_types, max_claims, solve_chunk=solve_chunk
        )
        result = sched.solve(pods, budgets=budgets)
        assert_same_packing(base, result)  # vs un-windowed device solve
        assert_same_packing(href, result)  # vs the host oracle
        assert_scan_coherent(sched)
    return base


class TestWindowedParity:
    def test_fill_path_small_window(self, monkeypatch):
        """Selector-only pods (kind-level fill scan) with the window well
        below the claims the solve opens: overflow falls back via the
        NO_ROOM escalation and still lands the oracle packing."""
        run_window_parity(monkeypatch, bench.selector_pods(128), 40, 128, 8)

    def test_topology_mix_small_window(self, monkeypatch):
        """The reference mix crosses fill + kind-scan dispatches with a
        compacted carry threaded between them."""
        run_window_parity(monkeypatch, bench.mixed_pods(96), 40, 128, 16)

    def test_perpod_resume_with_compacted_carry(self, monkeypatch):
        """Finite budgets force the per-pod scan; a small solve_chunk makes
        several solve_from dispatches with compaction (and possible window
        spill) between them — pinned vs the unchunked un-windowed solve."""
        budgets = {"default": {"cpu": 100000.0}}
        run_window_parity(
            monkeypatch,
            bench.mixed_pods(72),
            24,
            128,
            12,
            budgets=budgets,
            solve_chunk=24,
        )


class TestWindowOverflow:
    def test_overflow_grows_and_recovers(self, monkeypatch):
        """Open claims far beyond W: the spill surfaces in the scan stats
        and the metric, the escalation re-solves with a grown window, and
        nothing ends up unschedulable."""
        from karpenter_tpu.utils.metrics import SCAN_WINDOW_SPILLS

        pods = [make_pod(f"big-{i}", cpu=1.8) for i in range(24)]
        href, _ = bench.host_solve(make_templates(16), pods)
        spills0 = SCAN_WINDOW_SPILLS.get()
        sched = windowed_scheduler(monkeypatch, 4, 0, 16, 64)
        result = sched.solve(pods)
        assert not result.unschedulable
        assert_same_packing(href, result)
        scan = assert_scan_coherent(sched)
        # the FINAL (escalated) solve ran with a grown window
        assert scan["window"] > 4
        assert SCAN_WINDOW_SPILLS.get() > spills0, (
            "the window-bound refusal must land in "
            "ktpu_scan_window_spills_total"
        )

    def test_forced_window_reported_in_timings(self, monkeypatch):
        sched = windowed_scheduler(monkeypatch, 8, 0, 16, 64)
        result = sched.solve([make_pod(f"p-{i}", cpu=0.5) for i in range(12)])
        assert not result.unschedulable
        scan = assert_scan_coherent(sched)
        assert scan["window"] == 8
        assert scan["spills"] == 0


class TestFrozenBank:
    def test_dead_claims_evict_between_dispatches(self, monkeypatch):
        """Two kinds sized so the first kind's claims are capacity-dead
        once only the second kind remains (headroom < the remaining
        minimum request): the boundary compaction must evict them to the
        frozen bank, keep residency within a window smaller than the
        total opens, and still produce the oracle packing."""
        pods = [make_pod(f"big-{i}", cpu=1.8) for i in range(12)] + [
            make_pod(f"mid-{i}", cpu=0.9) for i in range(12)
        ]
        href, _ = bench.host_solve(make_templates(16), pods)
        base = windowed_scheduler(monkeypatch, 0, 0, 16, 64).solve(pods)
        assert_same_packing(href, base)
        # force a dispatch boundary between the two fill segments
        sched = windowed_scheduler(monkeypatch, 16, 4, 16, 64)
        result = sched.solve(pods)
        assert_same_packing(base, result)
        scan = assert_scan_coherent(sched)
        assert scan["compactions"] >= 1, scan
        assert scan["frozen"] > 0, (
            f"expected capacity-dead claims in the frozen bank, got {scan}"
        )
        # residency stayed below total opens — the whole point
        assert scan["live_hw"] < scan["n_open"], scan

    def test_warm_adaptive_window_shrinks(self, monkeypatch):
        """With no forced window, warm solves size the window from the
        live high-water, not the cumulative opens."""
        pods = [make_pod(f"big-{i}", cpu=1.8) for i in range(12)] + [
            make_pod(f"mid-{i}", cpu=0.9) for i in range(12)
        ]
        sched = windowed_scheduler(monkeypatch, 0, 4, 16, 1024)
        r1 = sched.solve(pods)
        assert not r1.unschedulable
        scan1 = assert_scan_coherent(sched)
        r2 = sched.solve(pods)
        assert not r2.unschedulable
        scan2 = assert_scan_coherent(sched)
        assert scan2["window"] <= scan1["window"]
        assert len(r1.claims) == len(r2.claims)


class TestPackedBitsets:
    def test_pack_roundtrip_and_ops(self, rng):
        import jax.numpy as jnp

        from karpenter_tpu.ops import kernels as k

        a = rng.random((7, 70)) < 0.3
        b = rng.random((7, 70)) < 0.3
        pa, pb = k.pack_bool_np(a), k.pack_bool_np(b)
        assert pa.dtype == np.uint32 and pa.shape == (7, 3)
        assert np.array_equal(np.asarray(k.pack_bool(jnp.asarray(a))), pa)
        assert np.array_equal(np.asarray(k.unpack_bool(jnp.asarray(pa), 70)), a)
        assert np.array_equal(
            np.asarray(k.packed_conflict(jnp.asarray(pa), jnp.asarray(pb))),
            (a & b).any(-1),
        )
        assert np.array_equal(
            np.asarray(k.packed_any(jnp.asarray(pa))), a.any(-1)
        )
        assert np.array_equal(
            np.asarray(k.packed_count_and(jnp.asarray(pa), jnp.asarray(pb))),
            (a & b).sum(-1),
        )

    def test_host_ports_still_conflict_windowed(self, monkeypatch):
        """Port bitsets ride packed through the windowed carry: two pods
        demanding the same host port must land on different nodes."""
        from karpenter_tpu.models.pod import HostPort

        pods = []
        for i in range(6):
            p = make_pod(f"hp-{i}", cpu=0.5)
            p.spec.host_ports = [HostPort(port=8080)]
            pods.append(p)
        href, _ = bench.host_solve(make_templates(16), pods)
        sched = windowed_scheduler(monkeypatch, 8, 0, 16, 64)
        result = sched.solve(pods)
        assert_same_packing(href, result)
        assert len(result.claims) == 6  # one port-conflicting pod per node
