"""Differential parity + tracing for the pipelined solve (ISSUE 3).

The software pipeline (scheduler._decode's chunk-group path) overlaps
wire fetch + host decode of chunk i with device execution of chunks > i.
Overlap must never change the answer: K ∈ {1, 2, 4} chunk groups must
produce BIT-identical packings to the host oracle AND to the unchunked
device solve, across the three dispatch modes (kind-level fill,
same-kind topology scan, per-pod scan) crossed with chunking.

Also covers the satellite fixes:
  * the fetch-prep cache keys on the pad signature, so a bucket change
    (vocab growth) or a resized claims axis rebuilds the jitted prep;
  * solve.pipeline / solve.pipeline.chunk[i] spans with overlap
    attribution, stitched across the gRPC split;
  * per chunk-group host_rss_mb / cpu_s envelope samples.
"""

import pytest

import bench
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.tracing.tracer import TRACER

from test_solver import assert_same_packing


@pytest.fixture
def tracer():
    TRACER.reset()
    TRACER.enable()
    yield TRACER
    TRACER.disable()
    TRACER.reset()


def make_templates(n_types=40):
    pool = NodePool()
    pool.metadata.name = "default"
    return build_templates([(pool, instance_types(n_types))])


def pipelined_scheduler(monkeypatch, k, n_types=40, max_claims=128, solve_chunk=None):
    """A TPUScheduler with the pipeline forced to K chunk groups (K <= 1
    disables it — the single-fetch baseline)."""
    if k > 1:
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", str(k))
        monkeypatch.setenv("KTPU_PIPELINE_MIN_PODS", "0")
    else:
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
    if solve_chunk is not None:
        monkeypatch.setenv("KTPU_SOLVE_CHUNK", str(solve_chunk))
    return TPUScheduler(
        make_templates(n_types), pod_pad=None, max_claims=max_claims
    )


def run_cross_parity(monkeypatch, pods, n_types, max_claims, budgets=None,
                     solve_chunk=None, expect_pipeline=True):
    """Solve at K in {1, 2, 4}; assert host-oracle parity and unchunked
    device parity for every K."""
    href, _ = bench.host_solve(make_templates(n_types), pods)
    if budgets is not None:
        from karpenter_tpu.controllers.provisioning.host_scheduler import (
            HostScheduler,
        )
        from karpenter_tpu.controllers.provisioning.topology import (
            Topology,
            build_universe_domains,
        )

        templates = make_templates(n_types)
        topo = Topology.build(
            list(pods), build_universe_domains(templates, []), []
        )
        href = HostScheduler(templates, budgets=budgets, topology=topo).solve(
            list(pods)
        )
    base = None
    for k in (1, 2, 4):
        sched = pipelined_scheduler(
            monkeypatch, k, n_types, max_claims, solve_chunk=solve_chunk
        )
        result = sched.solve(pods, budgets=budgets)
        pl = sched.last_timings.get("pipeline")
        if k == 1:
            base = result
            assert pl is None, "K=1 must stay on the single-fetch path"
        else:
            if expect_pipeline:
                assert pl is not None, f"K={k} solve did not pipeline"
                assert 2 <= pl["n_chunks"] <= k
                # satellite: per chunk-group envelope samples, not just
                # the per-solve stage numbers
                for c in pl["chunks"]:
                    assert "host_rss_mb" in c and "cpu_s" in c
            assert_same_packing(base, result)  # vs the unchunked device solve
        assert_same_packing(href, result)  # vs the host oracle
    return base


class TestPipelinedParity:
    def test_fill_path_selectors(self, monkeypatch):
        """Selector-only pods ride the kind-level fill scan; splitting the
        fill run into chunk groups must not move a single pod."""
        run_cross_parity(monkeypatch, bench.selector_pods(160), 40, 128)

    def test_topology_heavy_mix(self, monkeypatch):
        """The reference mix (TSC-zone/TSC-hostname/affinity/anti fifths)
        crosses fill + kind-scan dispatches with chunking."""
        run_cross_parity(monkeypatch, bench.mixed_pods(120), 40, 128)

    def test_perpod_path_under_budgets(self, monkeypatch):
        """Finite pool budgets disable fill/kscan routing, forcing the
        per-pod scan — its solve_from chunks each become a decode group
        (solve_chunk shrunk so the small problem still chunks)."""
        budgets = {"default": {"cpu": 100000.0}}
        run_cross_parity(
            monkeypatch,
            bench.mixed_pods(96),
            24,
            128,
            budgets=budgets,
            solve_chunk=24,
        )

    @pytest.mark.slow
    def test_2048x400_parity(self, monkeypatch):
        """The ISSUE-named size: 2048 x 400, K in {1, 2, 4} vs host oracle
        and vs the unchunked device solve (excluded from tier-1 by the
        slow marker — the CPU host oracle at this size takes minutes)."""
        run_cross_parity(monkeypatch, bench.selector_pods(2048), 400, 256)

    @pytest.mark.slow
    def test_2048_topology_mix_parity(self, monkeypatch):
        run_cross_parity(monkeypatch, bench.mixed_pods(2048), 400, 512)


class TestFetchPrepInvalidation:
    def test_pad_bucket_change_rebuilds_prep(self, monkeypatch):
        """Satellite fix: the jitted fetch-prep cache must key on the pad
        signature — growing the vocab across a v_pad bucket (and any
        claims-axis resize) rebuilds the prep instead of reusing a stale
        executable against resized tensors."""
        sched = pipelined_scheduler(monkeypatch, 0, n_types=16, max_claims=64)
        pods1 = [make_pod(f"a-{i}", cpu=0.5) for i in range(24)]
        r1 = sched.solve(pods1)
        assert not r1.unschedulable
        sigs1 = {key[-1] for key in sched._fetch_prep_cache}
        assert sigs1, "first solve must populate the prep cache"
        # 12 distinct values of a custom key: max_values crosses the
        # 8 -> 16 v_pad bucket, so every problem tensor re-pads
        pods2 = [
            make_pod(
                f"b-{i}",
                cpu=0.5,
                node_selector={"example.com/custom": f"v-{i}"},
            )
            for i in range(12)
        ]
        r2 = sched.solve(pods1 + pods2)
        assert len(r2.unschedulable) == len(pods2)  # custom key matches no IT
        sigs2 = {key[-1] for key in sched._fetch_prep_cache}
        assert len(sigs2) > len(sigs1), (
            "pad-bucket change must mint a new prep-cache signature, "
            f"got {sigs2}"
        )
        # and the original workload still solves correctly afterwards
        r3 = sched.solve(pods1)
        assert not r3.unschedulable


class TestPipelineTracing:
    def test_chunk_spans_report_overlap(self, monkeypatch, tracer):
        """solve.pipeline carries overlap_frac > 0 on a multi-chunk solve;
        each chunk lands as solve.pipeline.chunk[i] with wire/decode/
        in-flight attribution."""
        sched = pipelined_scheduler(monkeypatch, 2, n_types=24, max_claims=64)
        pods = bench.mixed_pods(96)
        with tracer.span("root") as root:
            result = sched.solve(pods)
        assert not result.unschedulable
        trace = tracer.trace(root.trace_id)
        by = {}
        for s in trace["spans"]:
            by.setdefault(s["name"], []).append(s)
        assert "solve.pipeline" in by, sorted(by)
        pipe = by["solve.pipeline"][-1]
        assert pipe["attrs"]["overlap_frac"] > 0
        chunk_names = [n for n in by if n.startswith("solve.pipeline.chunk[")]
        assert "solve.pipeline.chunk[0]" in chunk_names
        assert "solve.pipeline.chunk[1]" in chunk_names
        for name in chunk_names:
            attrs = by[name][-1]["attrs"]
            assert "wire_s" in attrs and "decode_s" in attrs
            assert "in_flight" in attrs

    def test_chunk_spans_stitch_over_grpc(self, monkeypatch, tracer):
        """A streamed remote Solve's server-side pipeline chunk spans carry
        the CLIENT's trace id (ktpu-trace-id metadata stitching), and the
        stream actually carried chunk frames."""
        from karpenter_tpu.rpc import RemoteScheduler, serve

        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "2")
        monkeypatch.setenv("KTPU_PIPELINE_MIN_PODS", "0")
        server, addr = serve("127.0.0.1:0")
        try:
            remote = RemoteScheduler(addr, make_templates(24))
            with tracer.span("client-root") as root:
                result = remote.solve(bench.mixed_pods(96))
            remote.close()
            assert not result.unschedulable
            assert remote.last_stream["chunks"] >= 2, remote.last_stream
            trace = tracer.trace(root.trace_id)
            names = {s["name"] for s in trace["spans"]}
            assert "rpc.SolveStream" in names
            assert "rpc.server.SolveStream" in names
            assert "solve.pipeline.chunk[0]" in names, sorted(names)
            # stitched: every span (client and server side) shares the
            # client root's trace id
            assert all(s["trace_id"] == root.trace_id for s in trace["spans"])
        finally:
            server.stop(0)
