"""End-to-end slice (BASELINE config #1 shape): pending pods flow through
store -> batcher -> TPU scheduler -> NodeClaims -> kwok launch -> node
registration/initialization -> kube-scheduler-sim binding."""

import numpy as np
import pytest

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodeclaim import COND_INITIALIZED, COND_LAUNCHED, COND_REGISTERED
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock


@pytest.fixture
def env():
    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=instance_types(50))
    mgr = Manager(store, cloud, clock)
    pool = NodePool()
    pool.metadata.name = "default"
    store.create(ObjectStore.NODEPOOLS, pool)
    return clock, store, cloud, mgr


def make_pods(n, rng=None):
    rng = rng or np.random.default_rng(0)
    return [
        make_pod(
            f"p-{i}",
            cpu=float(rng.choice([0.25, 0.5, 1.0, 2.0])),
            memory=f"{rng.choice([0.5, 1.0, 2.0])}Gi",
        )
        for i in range(n)
    ]


class TestProvisioningE2E:
    def test_full_cycle(self, env):
        clock, store, cloud, mgr = env
        for pod in make_pods(100):
            store.create(ObjectStore.PODS, pod)
        assert mgr.batcher.pending
        mgr.run_until_idle()

        claims = store.nodeclaims()
        assert claims, "provisioning created no claims"
        for c in claims:
            assert c.conditions.is_true(COND_LAUNCHED)
            assert c.conditions.is_true(COND_REGISTERED)
            assert c.status.provider_id.startswith("kwok://")

        # kwok "kubelet" heartbeats -> nodes Ready -> initialization
        assert cloud.simulate_kubelet_ready() == len(claims)
        mgr.run_until_idle()
        for c in store.nodeclaims():
            assert c.conditions.is_true(COND_INITIALIZED)

        # nodes carry instance labels and dropped the unregistered taint
        nodes = store.nodes()
        assert len(nodes) == len(claims)
        for n in nodes:
            assert n.metadata.labels[l.NODEPOOL_LABEL_KEY] == "default"
            assert n.metadata.labels[l.LABEL_INSTANCE_TYPE]
            assert all(t.key != l.UNREGISTERED_TAINT_KEY for t in n.spec.taints)

        # the kube-scheduler sim binds every pending pod
        binder = KubeSchedulerSim(store, mgr.cluster)
        bound = binder.bind_pending()
        assert bound == 100
        assert all(p.spec.node_name for p in store.pods())

        # cluster mirror agrees
        assert mgr.cluster.synced()
        assert sum(len(sn.pods) for sn in mgr.cluster.nodes()) == 100

    def test_batch_window_debounce(self, env):
        clock, store, cloud, mgr = env
        store.create(ObjectStore.PODS, make_pods(1)[0])
        assert not mgr.batcher.ready()  # window open, idle not elapsed
        clock.step(1.1)
        assert mgr.batcher.ready()

    def test_no_double_provisioning(self, env):
        clock, store, cloud, mgr = env
        for pod in make_pods(20):
            store.create(ObjectStore.PODS, pod)
        mgr.run_until_idle()
        n_claims = len(store.nodeclaims())
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        # nothing pending anymore -> another pass creates nothing
        mgr.batcher.trigger()
        clock.step(2.0)
        mgr.run_until_idle()
        assert len(store.nodeclaims()) == n_claims

    def test_no_double_provisioning_before_nodes_ready(self, env):
        """Pods scheduled to in-flight claims must not be re-provisioned
        when new pods trigger another pass before nodes turn Ready."""
        clock, store, cloud, mgr = env
        for pod in make_pods(20):
            store.create(ObjectStore.PODS, pod)
        mgr.run_until_idle()
        claims_before = {c.name for c in store.nodeclaims()}
        total_cpu_before = sum(c.spec.requests.get("cpu", 0) for c in store.nodeclaims())
        # nodes NOT ready yet; a straggler pod arrives
        store.create(ObjectStore.PODS, make_pod("straggler", cpu=0.25))
        mgr.run_until_idle()
        new_claims = [c for c in store.nodeclaims() if c.name not in claims_before]
        # only the straggler got capacity, not all 21 pods again
        new_cpu = sum(c.spec.requests.get("cpu", 0) for c in new_claims)
        assert new_cpu < total_cpu_before / 2
        assert len(store.nodeclaims()) <= len(claims_before) + 1

    def test_nodepool_created_after_pods(self, env):
        """Pods arriving before any NodePool exists must be provisioned once
        a pool appears (the gated trigger survives / re-fires)."""
        clock = FakeClock()
        store = ObjectStore(clock)
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider

        cloud = KwokCloudProvider(store, catalog=instance_types(50))
        mgr = Manager(store, cloud, clock)
        for pod in make_pods(5):
            store.create(ObjectStore.PODS, pod)
        mgr.run_until_idle()
        assert store.nodeclaims() == []
        pool = NodePool()
        pool.metadata.name = "late"
        store.create(ObjectStore.NODEPOOLS, pool)
        mgr.run_until_idle()
        assert store.nodeclaims(), "late pool never provisioned pending pods"

    def test_liveness_deletes_unregistered_claim(self, env):
        """A claim that never registers is deleted after the launch TTL —
        exercises the fake-clock creation timestamps."""
        clock, store, cloud, mgr = env
        from karpenter_tpu.cloudprovider import CreateError

        # make every create fail with a retryable error -> claim never launches
        orig_create = cloud.create
        cloud.create = lambda c: (_ for _ in ()).throw(
            CreateError("cloud down", reason="Scripted")
        )
        store.create(ObjectStore.PODS, make_pods(1)[0])
        mgr.run_until_idle()
        assert len(store.nodeclaims()) == 1
        clock.step(6 * 60.0)  # past the 5m launch TTL
        claims = store.nodeclaims()
        for c in claims:
            mgr.lifecycle.reconcile(c)
        assert store.nodeclaims() == []
        cloud.create = orig_create

    def test_second_batch_reuses_existing_nodes(self, env):
        """Once nodes exist with spare capacity, a later batch must fill
        them (tier-1 existing-node placement) instead of opening claims."""
        clock, store, cloud, mgr = env
        for pod in make_pods(30):
            store.create(ObjectStore.PODS, pod)
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        n_claims = len(store.nodeclaims())
        assert n_claims >= 1
        # a small second wave fits in the headroom of existing nodes
        for i in range(3):
            store.create(ObjectStore.PODS, make_pod(f"wave2-{i}", cpu=0.1, memory="64Mi"))
        mgr.run_until_idle()
        assert len(store.nodeclaims()) == n_claims, "second wave opened new claims"
        bound = KubeSchedulerSim(store, mgr.cluster).bind_pending()
        assert bound == 3

    def test_nodepool_node_limit_respected(self, env):
        from karpenter_tpu.models.nodepool import Limits

        clock, store, cloud, mgr = env
        pool = store.get(ObjectStore.NODEPOOLS, "default")
        pool.spec.limits = Limits(resources={"nodes": 2})
        store.update(ObjectStore.NODEPOOLS, pool)
        for pod in make_pods(200):
            store.create(ObjectStore.PODS, pod)
        mgr.run_until_idle()
        assert len(store.nodeclaims()) <= 2

    def test_insufficient_capacity_deletes_claim(self, env):
        clock, store, cloud, mgr = env
        # a pod too big for the catalog never yields a claim at all
        store.create(ObjectStore.PODS, make_pod("huge", cpu=10000.0))
        mgr.run_until_idle()
        assert store.nodeclaims() == []

    def test_claim_deletion_finalizes(self, env):
        clock, store, cloud, mgr = env
        for pod in make_pods(10):
            store.create(ObjectStore.PODS, pod)
        mgr.run_until_idle()
        claims = store.nodeclaims()
        assert claims
        name = claims[0].name
        store.delete(ObjectStore.NODECLAIMS, name)
        mgr.run_until_idle()
        assert store.get(ObjectStore.NODECLAIMS, name) is None
        # backing node removed too
        assert all(
            n.spec.provider_id != claims[0].status.provider_id for n in store.nodes()
        )
