"""Sharded-vs-unsharded bit-parity in a FRESH backend (ISSUE 8 satellite).

One subprocess (pattern: tests/test_compile_cache.py restart child) forces
XLA_FLAGS=--xla_force_host_platform_device_count=8 + the KTPU_MESH=2x4
env override, then pins small fill / kscan / topology-bearing /
existing-node / per-pod solves on the (dp × it) mesh bit-identical to the
single-device solve AND the host oracle, windowed and un-windowed. The in-process dp-merge differential
suite lives in tests/test_shard.py; this twin proves the same parity
holds under a cold backend with the mesh built purely from env knobs
(the deployment configuration the solver server uses).
"""

import json
import os
import subprocess
import sys

import pytest

_CHILD = r"""
import os, json
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["KTPU_PIPELINE_CHUNKS"] = "3"
os.environ["KTPU_PIPELINE_MIN_PODS"] = "32"
from karpenter_tpu.utils.accel import force_cpu
force_cpu()

import numpy as np
from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.controllers.provisioning.host_scheduler import HostScheduler
from karpenter_tpu.controllers.provisioning.topology import Topology, build_universe_domains
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod
from karpenter_tpu.parallel import make_mesh

N_TYPES = 24  # >= 12 so every kind (incl. the 2-cpu saturating ones) schedules

def make_templates():
    pool = NodePool(); pool.metadata.name = "default"
    return build_templates([(pool, instance_types(N_TYPES))])

def fill_pods():
    # mixed-size kinds (dp replay rung) + saturating kinds (dp graft rung)
    pods = []
    for i in range(96):
        k = i // 16
        pods.append(make_pod(f"f-{i}", cpu=[0.25, 0.5, 1.0][k % 3],
                             memory=f"{[0.5, 1.0][k % 2]}Gi"))
    for i in range(96):
        p = make_pod(f"g-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(i // 24)}
        pods.append(p)
    return pods

def kscan_pods():
    pods = fill_pods()[:64]
    for i in range(48):
        p = make_pod(f"z-{i}", cpu=0.5, memory="0.5Gi")
        p.metadata.labels = {"spread": "z"}
        p.spec.topology_spread_constraints = [TopologySpreadConstraint(
            max_skew=1, topology_key=l.LABEL_TOPOLOGY_ZONE,
            label_selector={"spread": "z"})]
        pods.append(p)
    return pods

def kscan_dp_pods():
    # >=2 zonal kinds with DISJOINT spread selectors + saturating sizes:
    # the kscan dp-speculative path (ISSUE 13) splits the run into chunk
    # groups and the per-domain deadness verdict lets them commit
    pods = []
    for i in range(192):
        k = i // 48
        p = make_pod(f"zd-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(k), "spread": f"z{k}"}
        p.spec.topology_spread_constraints = [TopologySpreadConstraint(
            max_skew=1, topology_key=l.LABEL_TOPOLOGY_ZONE,
            label_selector={"spread": f"z{k}"})]
        pods.append(p)
    return pods

def topo_pods():
    # hostname-spread kinds with DISJOINT selectors: hg interaction but no
    # vg interaction keeps them batchable (the fill route), so they ride
    # the topo_fill speculation family; saturating sizes let groups commit
    pods = []
    for i in range(96):
        k = i // 24
        p = make_pod(f"t-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(k), "hspread": f"h{k}"}
        p.spec.topology_spread_constraints = [TopologySpreadConstraint(
            max_skew=1, topology_key=l.LABEL_HOSTNAME,
            label_selector={"hspread": f"h{k}"})]
        pods.append(p)
    return pods

def existing_pods():
    # saturating kinds solved AGAINST real existing nodes: the dp rows
    # carry per-existing-node debit deltas and the disjoint-touch verdict
    # bit lets later rounds commit once the nodes fill (ISSUE 14)
    pods = []
    for i in range(96):
        p = make_pod(f"e-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(i // 24)}
        pods.append(p)
    return pods

def make_existing_nodes():
    from karpenter_tpu.controllers.provisioning.host_scheduler import ExistingSimNode
    from karpenter_tpu.scheduling import Requirements
    from karpenter_tpu.utils import resources as res
    nodes = []
    for i in range(2):
        name = f"exist-{i}"
        labels = {
            l.LABEL_TOPOLOGY_ZONE: "test-zone-1",
            l.LABEL_INSTANCE_TYPE: "s-4x-amd64",
            l.CAPACITY_TYPE_LABEL_KEY: l.CAPACITY_TYPE_ON_DEMAND,
            l.LABEL_ARCH: l.ARCH_AMD64,
            l.LABEL_OS: "linux",
            l.LABEL_HOSTNAME: name,
            l.NODEPOOL_LABEL_KEY: "default",
        }
        nodes.append(ExistingSimNode(
            name=name, index=i,
            requirements=Requirements.from_labels(labels),
            available={res.CPU: 4.0, res.MEMORY: float(8 * 2**30),
                       res.PODS: 50.0},
        ))
    return nodes

def perpod_pods():
    # TWO distinct vg keys per kind (zone + capacity-type spread) defeat
    # the single-key kscan check, so the run takes the per-pod scan —
    # solve_perpod_dp speculates one 64-pod chunk per dp row (ISSUE 14)
    pods = []
    for i in range(128):
        k = i // 64
        p = make_pod(f"pp-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(k), "spread": f"p{k}"}
        p.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                max_skew=1, topology_key=l.LABEL_TOPOLOGY_ZONE,
                label_selector={"spread": f"p{k}"}),
            TopologySpreadConstraint(
                max_skew=1, topology_key=l.CAPACITY_TYPE_LABEL_KEY,
                label_selector={"spread": f"p{k}"}),
        ]
        pods.append(p)
    return pods

def host_solve(pods, existing=None):
    templates = make_templates()
    if existing:
        return HostScheduler(templates, existing_nodes=existing).solve(list(pods))
    topo = Topology.build(list(pods), build_universe_domains(templates, []), [])
    return HostScheduler(templates, topology=topo).solve(list(pods))

def identical(a, b):
    if a.assignments != b.assignments: return "assignments"
    if a.existing_assignments != b.existing_assignments: return "existing"
    if len(a.claims) != len(b.claims): return "n_claims"
    if [(p.uid, r) for p, r in a.unschedulable] != [(p.uid, r) for p, r in b.unschedulable]:
        return "unschedulable"
    for x, y in zip(a.claims, b.claims):
        if x.hostname != y.hostname: return "hostname"
        if [it.name for it in x.instance_types] != [it.name for it in y.instance_types]:
            return "instance_types"
        if x.used != y.used: return "used"
        if str(x.requirements) != str(y.requirements): return "requirements"
    return ""

def matches_host(host, dev):
    if len(host.claims) != len(dev.claims): return "n_claims"
    if host.assignments != dev.assignments: return "assignments"
    if host.existing_assignments != dev.existing_assignments: return "existing"
    for slot, hc in {c.slot: c for c in host.claims}.items():
        tc = {c.slot: c for c in dev.claims}[slot]
        if [p.uid for p in hc.pods] != [p.uid for p in tc.pods]: return "pods"
        if {it.name for it in hc.instance_types} != {it.name for it in tc.instance_types}:
            return "instance_types"
        for k, v in hc.used.items():
            if abs(tc.used.get(k, 0.0) - v) > 1e-9: return "used"
    return ""

mesh = make_mesh()  # KTPU_MESH=2x4 from env
out = {"mesh": dict((k, int(v)) for k, v in mesh.shape.items())}
cases = [("fill", fill_pods(), None), ("kscan", kscan_pods(), None),
         ("kscan_dp", kscan_dp_pods(), None), ("topo", topo_pods(), None),
         ("existing", existing_pods(), make_existing_nodes),
         ("perpod", perpod_pods(), None)]
only = os.environ.get("KTPU_PARITY_CASES")
if only:
    keep = set(only.split(","))
    cases = [c for c in cases if c[0] in keep]
for name, pods, exist_fn in cases:
    # the ISSUE-13/14 dp cases run un-windowed only: the windowed rungs
    # are pinned in-process by tests/test_shard.py, and every extra
    # (case, window) pair recompiles the whole dp executable set in this
    # cold child
    windows = (0, 48) if name in ("fill", "kscan") else (0,)
    # the per-pod family splits on KTPU_SOLVE_CHUNK (read at scheduler
    # construction): 64 gives 128 pods -> 2 speculative dp rows
    if name == "perpod":
        os.environ["KTPU_SOLVE_CHUNK"] = "64"
    else:
        os.environ.pop("KTPU_SOLVE_CHUNK", None)
    for window in windows:
        if window:
            os.environ["KTPU_SCAN_WINDOW"] = str(window)
        else:
            os.environ.pop("KTPU_SCAN_WINDOW", None)
        meshed_sched = TPUScheduler(make_templates(), mesh=mesh)
        meshed = meshed_sched.solve(list(pods), exist_fn() if exist_fn else [])
        single = TPUScheduler(make_templates()).solve(
            list(pods), exist_fn() if exist_fn else [])
        rec = {
            "diff": identical(meshed, single),
            "host_diff": matches_host(
                host_solve(pods, exist_fn() if exist_fn else None), meshed),
            "claims": len(meshed.claims),
        }
        shard = (meshed_sched.last_timings or {}).get("shard") or {}
        rec["merge_rounds"] = shard.get("merge_rounds", 0)
        rec["committed"] = shard.get("groups_committed", 0)
        rec["replayed"] = shard.get("groups_replayed", 0)
        rec["families"] = {
            f: s["committed"] for f, s in shard.get("families", {}).items()}
        out[f"{name}_w{window}"] = rec
print(json.dumps(out))
"""


def _run_child(case_names):
    env = dict(os.environ)
    env["KTPU_MESH"] = "2x4"
    env["KTPU_PARITY_CASES"] = ",".join(case_names)
    env.pop("KTPU_SCAN_WINDOW", None)
    out = subprocess.run(
        [sys.executable, "-c", _CHILD],
        capture_output=True,
        text=True,
        env=env,
        timeout=560,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-3000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    # the env override shaped the mesh
    assert res.pop("mesh") == {"dp": 2, "it": 4}
    for case, rec in res.items():
        assert rec["diff"] == "", f"{case}: meshed != single-device ({rec['diff']})"
        assert rec["host_diff"] == "", f"{case}: meshed != host oracle ({rec['host_diff']})"
        assert rec["claims"] >= 1, case
    return res


def test_sharded_solves_bit_identical_in_fresh_backend(tmp_path):
    res = _run_child(["fill", "kscan", "kscan_dp"])
    # the fill cases must actually exercise the dp merge loop, and the
    # saturating kinds must commit at least one speculative graft
    assert res["fill_w0"]["merge_rounds"] >= 1
    assert res["fill_w0"]["committed"] >= 1, res["fill_w0"]
    assert res["fill_w48"]["merge_rounds"] >= 1
    # disjoint-selector zonal kinds take the kscan dp-speculative path
    # and commit speculative grafts (ISSUE 13)
    assert res["kscan_dp_w0"]["merge_rounds"] >= 1
    assert res["kscan_dp_w0"]["committed"] >= 1, res["kscan_dp_w0"]
    # a single-kind kscan run has nothing to split into speculative groups
    assert res["kscan_w0"]["merge_rounds"] == 0


@pytest.mark.slow
def test_stateful_families_bit_identical_in_fresh_backend(tmp_path):
    """The three ISSUE 14 families in a cold backend: hostname-spread
    (topology-BEARING) fill, real existing nodes (per-node debit deltas,
    parity incl. existing_assignments vs the HostScheduler oracle) and
    the per-pod dp fan-out — each commits at least one speculative
    round."""
    res = _run_child(["topo", "existing", "perpod"])
    assert res["topo_w0"]["families"].get("topo_fill", 0) >= 1, res["topo_w0"]
    assert res["existing_w0"]["families"].get("existing", 0) >= 1, res["existing_w0"]
    assert res["perpod_w0"]["families"].get("perpod", 0) >= 1, res["perpod_w0"]
