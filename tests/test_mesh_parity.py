"""Sharded-vs-unsharded bit-parity in a FRESH backend (ISSUE 8 satellite).

One subprocess (pattern: tests/test_compile_cache.py restart child) forces
XLA_FLAGS=--xla_force_host_platform_device_count=8 + the KTPU_MESH=2x4
env override, then pins small fill / kscan / perpod solves on the
(dp × it) mesh bit-identical to the single-device solve AND the host
oracle, windowed and un-windowed. The in-process dp-merge differential
suite lives in tests/test_shard.py; this twin proves the same parity
holds under a cold backend with the mesh built purely from env knobs
(the deployment configuration the solver server uses).
"""

import json
import os
import subprocess
import sys

_CHILD = r"""
import os, json
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["KTPU_PIPELINE_CHUNKS"] = "3"
os.environ["KTPU_PIPELINE_MIN_PODS"] = "32"
from karpenter_tpu.utils.accel import force_cpu
force_cpu()

import numpy as np
from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.controllers.provisioning.host_scheduler import HostScheduler
from karpenter_tpu.controllers.provisioning.topology import Topology, build_universe_domains
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import PodAffinityTerm, TopologySpreadConstraint, make_pod
from karpenter_tpu.parallel import make_mesh

N_TYPES = 24  # >= 12 so every kind (incl. the 2-cpu saturating ones) schedules

def make_templates():
    pool = NodePool(); pool.metadata.name = "default"
    return build_templates([(pool, instance_types(N_TYPES))])

def fill_pods():
    # mixed-size kinds (dp replay rung) + saturating kinds (dp graft rung)
    pods = []
    for i in range(96):
        k = i // 16
        pods.append(make_pod(f"f-{i}", cpu=[0.25, 0.5, 1.0][k % 3],
                             memory=f"{[0.5, 1.0][k % 2]}Gi"))
    for i in range(96):
        p = make_pod(f"g-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(i // 24)}
        pods.append(p)
    return pods

def kscan_pods():
    pods = fill_pods()[:64]
    for i in range(48):
        p = make_pod(f"z-{i}", cpu=0.5, memory="0.5Gi")
        p.metadata.labels = {"spread": "z"}
        p.spec.topology_spread_constraints = [TopologySpreadConstraint(
            max_skew=1, topology_key=l.LABEL_TOPOLOGY_ZONE,
            label_selector={"spread": "z"})]
        pods.append(p)
    return pods

def kscan_dp_pods():
    # >=2 zonal kinds with DISJOINT spread selectors + saturating sizes:
    # the kscan dp-speculative path (ISSUE 13) splits the run into chunk
    # groups and the per-domain deadness verdict lets them commit
    pods = []
    for i in range(192):
        k = i // 48
        p = make_pod(f"zd-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(k), "spread": f"z{k}"}
        p.spec.topology_spread_constraints = [TopologySpreadConstraint(
            max_skew=1, topology_key=l.LABEL_TOPOLOGY_ZONE,
            label_selector={"spread": f"z{k}"})]
        pods.append(p)
    return pods

def perpod_pods():
    pods = fill_pods()[:64]
    for i in range(24):
        p = make_pod(f"h-{i}", cpu=0.5, memory="0.5Gi")
        p.metadata.labels = {"app": "web"}
        p.spec.pod_anti_affinity = [PodAffinityTerm(
            topology_key=l.LABEL_HOSTNAME, label_selector={"app": "web"})]
        pods.append(p)
    return pods

def host_solve(pods):
    templates = make_templates()
    topo = Topology.build(list(pods), build_universe_domains(templates, []), [])
    return HostScheduler(templates, topology=topo).solve(list(pods))

def identical(a, b):
    if a.assignments != b.assignments: return "assignments"
    if a.existing_assignments != b.existing_assignments: return "existing"
    if len(a.claims) != len(b.claims): return "n_claims"
    if [(p.uid, r) for p, r in a.unschedulable] != [(p.uid, r) for p, r in b.unschedulable]:
        return "unschedulable"
    for x, y in zip(a.claims, b.claims):
        if x.hostname != y.hostname: return "hostname"
        if [it.name for it in x.instance_types] != [it.name for it in y.instance_types]:
            return "instance_types"
        if x.used != y.used: return "used"
        if str(x.requirements) != str(y.requirements): return "requirements"
    return ""

def matches_host(host, dev):
    if len(host.claims) != len(dev.claims): return "n_claims"
    if host.assignments != dev.assignments: return "assignments"
    for slot, hc in {c.slot: c for c in host.claims}.items():
        tc = {c.slot: c for c in dev.claims}[slot]
        if [p.uid for p in hc.pods] != [p.uid for p in tc.pods]: return "pods"
        if {it.name for it in hc.instance_types} != {it.name for it in tc.instance_types}:
            return "instance_types"
        for k, v in hc.used.items():
            if abs(tc.used.get(k, 0.0) - v) > 1e-9: return "used"
    return ""

mesh = make_mesh()  # KTPU_MESH=2x4 from env
out = {"mesh": dict((k, int(v)) for k, v in mesh.shape.items())}
cases = [("fill", fill_pods()), ("kscan", kscan_pods()),
         ("kscan_dp", kscan_dp_pods()), ("perpod", perpod_pods())]
for name, pods in cases:
    # kscan_dp runs un-windowed only: the windowed kscan-dp rung is pinned
    # in-process by tests/test_shard.py, and every extra (case, window)
    # pair recompiles the whole dp executable set in this cold child
    for window in ((0,) if name == "kscan_dp" else (0, 48)):
        if window:
            os.environ["KTPU_SCAN_WINDOW"] = str(window)
        else:
            os.environ.pop("KTPU_SCAN_WINDOW", None)
        meshed_sched = TPUScheduler(make_templates(), mesh=mesh)
        meshed = meshed_sched.solve(list(pods))
        single = TPUScheduler(make_templates()).solve(list(pods))
        rec = {
            "diff": identical(meshed, single),
            "host_diff": matches_host(host_solve(pods), meshed),
            "claims": len(meshed.claims),
        }
        shard = (meshed_sched.last_timings or {}).get("shard") or {}
        rec["merge_rounds"] = shard.get("merge_rounds", 0)
        rec["committed"] = shard.get("groups_committed", 0)
        rec["replayed"] = shard.get("groups_replayed", 0)
        out[f"{name}_w{window}"] = rec
print(json.dumps(out))
"""


def test_sharded_solves_bit_identical_in_fresh_backend(tmp_path):
    env = dict(os.environ)
    env["KTPU_MESH"] = "2x4"
    env.pop("KTPU_SCAN_WINDOW", None)
    out = subprocess.run(
        [sys.executable, "-c", _CHILD],
        capture_output=True,
        text=True,
        env=env,
        timeout=560,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-3000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    # the env override shaped the mesh
    assert res.pop("mesh") == {"dp": 2, "it": 4}
    for case, rec in res.items():
        assert rec["diff"] == "", f"{case}: meshed != single-device ({rec['diff']})"
        assert rec["host_diff"] == "", f"{case}: meshed != host oracle ({rec['host_diff']})"
        assert rec["claims"] >= 1, case
    # the fill cases must actually exercise the dp merge loop, and the
    # saturating kinds must commit at least one speculative graft
    assert res["fill_w0"]["merge_rounds"] >= 1
    assert res["fill_w0"]["committed"] >= 1, res["fill_w0"]
    assert res["fill_w48"]["merge_rounds"] >= 1
    # disjoint-selector zonal kinds take the kscan dp-speculative path
    # and commit speculative grafts (ISSUE 13)
    assert res["kscan_dp_w0"]["merge_rounds"] >= 1
    assert res["kscan_dp_w0"]["committed"] >= 1, res["kscan_dp_w0"]
    # a single-kind kscan run has nothing to split into speculative
    # groups, and per-pod (hostname anti-affinity) kinds stay sequential
    assert res["kscan_w0"]["merge_rounds"] == 0
    assert res["perpod_w0"]["merge_rounds"] == 0
