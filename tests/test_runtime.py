"""Operator runtime: leader election, health/readyz probes, profiling
endpoints (reference pkg/operator/operator.go:126-243)."""

import json
import urllib.request

from karpenter_tpu.operator import Operator
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.options import Options
from karpenter_tpu.utils.runtime import (
    LEASES,
    HealthConfig,
    LeaderElector,
    serve_health,
)


class TestLeaderElection:
    def test_first_contender_acquires(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        a = LeaderElector(store, "a", clock)
        assert a.try_acquire_or_renew()
        assert a.is_leader
        assert store.get(LEASES, a.lease_name).holder == "a"

    def test_second_contender_waits_then_takes_over_on_expiry(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        a = LeaderElector(store, "a", clock)
        b = LeaderElector(store, "b", clock)
        assert a.try_acquire_or_renew()
        assert not b.try_acquire_or_renew()  # lease held
        clock.step(10.0)
        a.try_acquire_or_renew()  # renewal extends the lease
        clock.step(10.0)
        assert not b.try_acquire_or_renew()  # renewed 10s ago, not expired
        clock.step(6.0)  # now 16s past the last renewal > 15s duration
        assert b.try_acquire_or_renew(), "expired lease not taken over"
        assert not a.try_acquire_or_renew(), "deposed leader kept leading"
        assert not a.is_leader

    def test_release_on_cancel_hands_over_immediately(self):
        # start=0.0 pins the empty-holder check: with now <= lease_duration
        # the expiry test alone can never fire, so a released lease must be
        # recognized by its empty holder, not by expiry
        clock = FakeClock(start=0.0)
        store = ObjectStore(clock)
        a = LeaderElector(store, "a", clock)
        b = LeaderElector(store, "b", clock)
        assert a.try_acquire_or_renew()
        a.release()  # clean shutdown (operator.go:176)
        assert b.try_acquire_or_renew(), "failover waited a full TTL"

    def test_operator_tick_gated_on_leadership(self):
        clock = FakeClock()
        op = Operator.new(clock=clock, options=Options(leader_elect=True))
        # steal the lease first so the operator's elector loses the race
        rival = LeaderElector(op.store, "rival", clock)
        assert rival.try_acquire_or_renew()
        from karpenter_tpu.models.pod import make_pod

        op.store.create(ObjectStore.PODS, make_pod("p", cpu=0.5))
        op.tick()  # not leader: no reconcile runs
        assert not op.store.nodeclaims(), "non-leader provisioned"
        rival.release()
        op.tick()  # acquires and reconciles
        assert op.elector.is_leader


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read().decode()


class TestHealthServer:
    def test_endpoints(self):
        ready = {"v": False}
        server, port = serve_health(
            HealthConfig(ready_checks={"gate": lambda: ready["v"]})
        )
        try:
            assert _get(port, "/healthz") == (200, "ok")
            try:
                _get(port, "/readyz")
                raise AssertionError("readyz green while the gate is red")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert "gate" in json.loads(e.read().decode())["failed"]
            ready["v"] = True
            assert _get(port, "/readyz") == (200, "ok")
            status, body = _get(port, "/metrics")
            assert status == 200 and "karpenter_" in body
            # profiling is opt-in (operator.go:205 --enable-profiling)
            try:
                _get(port, "/debug/pprof/threads")
                raise AssertionError("profiling reachable while disabled")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.shutdown()

    def test_profiling_endpoints_when_enabled(self):
        server, port = serve_health(HealthConfig(enable_profiling=True))
        try:
            status, body = _get(port, "/debug/pprof/threads")
            assert status == 200 and "thread" in body
            status, body = _get(port, "/debug/pprof/profile?seconds=0.1")
            # the all-thread sampling profiler reports sample counts over
            # collapsed stacks (a cProfile would only see the handler
            # thread sleeping)
            assert status == 200 and "sampling rounds" in body
            assert ";" in body  # at least one non-handler thread stack
        finally:
            server.shutdown()

    def test_envelope_endpoint_surfaces_live_series(self):
        """/debug/envelope (behind --enable-profiling): snapshots the
        running envelope sampler — stages + recent RSS/CPU series — or a
        one-shot reading when no sampler is active."""
        import json as _json

        from karpenter_tpu.envelope.sampler import ResourceSampler

        server, port = serve_health(HealthConfig(enable_profiling=True))
        try:
            # no sampler running: one-shot reading
            status, body = _get(port, "/debug/envelope")
            assert status == 200
            out = _json.loads(body)
            assert out["rss_mb"] > 0 and out["stages"] == {}
            # live sampler: stages and series appear
            with ResourceSampler(interval_s=0.01) as sampler:
                with sampler.stage("probe"):
                    import time as _t

                    _t.sleep(0.05)
                status, body = _get(port, "/debug/envelope")
            out = _json.loads(body)
            assert status == 200 and "probe" in out["stages"]
            assert out["series"], "live series empty under a running sampler"
        finally:
            server.shutdown()

    def test_envelope_endpoint_gated_without_profiling(self):
        import urllib.error

        server, port = serve_health(HealthConfig(enable_profiling=False))
        try:
            try:
                _get(port, "/debug/envelope")
                raise AssertionError("/debug/envelope reachable while disabled")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.shutdown()

    def test_operator_wires_probe_server(self):
        clock = FakeClock()
        op = Operator.new(clock=clock, options=Options(health_probe_port=-1))
        try:
            assert op.health_port > 0
            assert _get(op.health_port, "/healthz") == (200, "ok")
            # an empty cluster state mirror is synced trivially -> ready
            status, _ = _get(op.health_port, "/readyz")
            assert status == 200
        finally:
            op.shutdown()
