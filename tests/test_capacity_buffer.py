"""CapacityBuffer status controller: template/scalable resolution, replica
computation, ReadyForProvisioning + Provisioning conditions, and the
emptiness guard for headroom nodes.

Reference: pkg/controllers/capacitybuffer/controller.go (resolution,
computeReplicas, 30s requeue), helpers.go:32-68 (limit/percentage math),
pkg/controllers/provisioning/buffers.go:140-380 (Provisioning condition,
bufferPodCountsFromResults, emptiness protection).
"""

from __future__ import annotations

from karpenter_tpu.controllers.capacity_buffer import (
    COND_PROVISIONING,
    COND_READY_FOR_PROVISIONING,
    CapacityBuffer,
    CapacityBufferController,
    PodTemplate,
    Scalable,
    resolved_replicas,
)
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import PodSpec, make_pod
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.clock import FakeClock


def _env():
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
    from karpenter_tpu.controllers.manager import Manager

    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=instance_types(10))
    mgr = Manager(store, cloud, clock)
    pool = NodePool()
    pool.metadata.name = "default"
    store.create(ObjectStore.NODEPOOLS, pool)
    return clock, store, cloud, mgr


def _buffer(name, **kwargs):
    b = CapacityBuffer(**kwargs)
    b.metadata.name = name
    return b


class TestReplicaResolution:
    def test_inline_template_fixed_replicas(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        ctrl = CapacityBufferController(store, clock)
        b = _buffer("warm", replicas=3, pod_template=PodSpec(requests={res.CPU: 1.0}))
        store.create(ObjectStore.CAPACITY_BUFFERS, b)
        out = ctrl.reconcile()
        assert out == {"resolved": 1, "failed": 0}
        assert b.conditions.is_true(COND_READY_FOR_PROVISIONING)
        assert b.status.replicas == 3 and resolved_replicas(b) == 3

    def test_pod_template_ref_resolution_and_not_found(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        ctrl = CapacityBufferController(store, clock)
        b = _buffer("warm", replicas=2, pod_template_ref="tmpl")
        store.create(ObjectStore.CAPACITY_BUFFERS, b)
        out = ctrl.reconcile()
        assert out["failed"] == 1
        assert b.conditions.is_false(COND_READY_FOR_PROVISIONING)
        assert (
            b.conditions.get(COND_READY_FOR_PROVISIONING).reason
            == "PodTemplateNotFound"
        )
        assert resolved_replicas(b) == 0  # failed resolution: no headroom
        tmpl = PodTemplate(spec=PodSpec(requests={res.CPU: 0.5}))
        tmpl.metadata.name = "tmpl"
        store.create(ObjectStore.POD_TEMPLATES, tmpl)
        ctrl.reconcile()
        assert b.conditions.is_true(COND_READY_FOR_PROVISIONING)
        assert resolved_replicas(b) == 2

    def test_scalable_percentage_is_ceil_with_floor_one(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        ctrl = CapacityBufferController(store, clock)
        s = Scalable(replicas=10, pod_spec=PodSpec(requests={res.CPU: 1.0}))
        s.metadata.name = "deploy"
        store.create(ObjectStore.SCALABLES, s)
        # ceil(10 * 25 / 100) = 3 (helpers.go:59-68)
        b = _buffer("pct", scalable_ref="deploy", percentage=25)
        # 1% of 10 -> ceil(0.1) floored at 1
        tiny = _buffer("tiny", scalable_ref="deploy", percentage=1)
        # max(fixed, percentage): fixed 5 beats 3
        both = _buffer("both", scalable_ref="deploy", percentage=25, replicas=5)
        for x in (b, tiny, both):
            store.create(ObjectStore.CAPACITY_BUFFERS, x)
        ctrl.reconcile()
        assert b.status.replicas == 3
        assert tiny.status.replicas == 1
        assert both.status.replicas == 5

    def test_limits_bound_the_replica_count(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        ctrl = CapacityBufferController(store, clock)
        spec = PodSpec(requests={res.CPU: 2.0})
        # floor(5/2) = 2 bounds the fixed 4 (helpers.go:32-56)
        capped = _buffer("capped", replicas=4, pod_template=spec, limits={res.CPU: 5.0})
        # limits alone determine the count when no size constraint is set
        only = _buffer("only-limits", pod_template=spec, limits={res.CPU: 6.0})
        for x in (capped, only):
            store.create(ObjectStore.CAPACITY_BUFFERS, x)
        ctrl.reconcile()
        assert capped.status.replicas == 2
        assert only.status.replicas == 3

    def test_thirty_second_requeue(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        ctrl = CapacityBufferController(store, clock)
        ctrl.reconcile()
        assert ctrl.maybe_reconcile() is None
        clock.step(31.0)
        assert ctrl.maybe_reconcile() is not None


class TestProvisioningCondition:
    def test_headroom_lifecycle_requires_new_then_fits_existing(self):
        from karpenter_tpu.controllers.manager import KubeSchedulerSim

        clock, store, cloud, mgr = _env()
        b = _buffer(
            "warm", replicas=2, pod_template=PodSpec(requests={res.CPU: 1.0})
        )
        store.create(ObjectStore.CAPACITY_BUFFERS, b)  # event: resolve+trigger
        assert b.conditions.is_true(COND_READY_FOR_PROVISIONING)
        clock.step(2.0)
        mgr.run_until_idle()
        claims = store.nodeclaims()
        assert claims, "no headroom provisioned"
        # first pass: the headroom needed new claims
        assert b.conditions.is_false(COND_PROVISIONING)
        assert b.conditions.get(COND_PROVISIONING).reason == "RequiresNewCapacity"
        # nodes come up; the next pass places headroom on existing capacity
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        # virtual pods stay nominated to their claims for the nomination
        # window; the next solve that re-evaluates them comes after expiry
        clock.step(121.0)
        mgr.batcher.trigger()
        clock.step(2.0)
        mgr.run_until_idle()
        assert b.conditions.is_true(COND_PROVISIONING)
        assert b.conditions.get(COND_PROVISIONING).reason == "FitsExistingCapacity"

    def test_real_pods_displace_virtuals_and_emptiness_guard_holds(self):
        from karpenter_tpu.controllers.manager import KubeSchedulerSim

        clock, store, cloud, mgr = _env()
        b = _buffer(
            "warm", replicas=2, pod_template=PodSpec(requests={res.CPU: 1.0})
        )
        store.create(ObjectStore.CAPACITY_BUFFERS, b)
        clock.step(2.0)
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        clock.step(121.0)  # past the nomination window
        mgr.batcher.trigger()
        clock.step(2.0)
        mgr.run_until_idle()
        # headroom nodes host ONLY virtual pods, yet emptiness must not
        # reap them (buffers.go:145-150 bufferPodCounts)
        assert mgr.cluster.buffer_pod_counts, "no headroom counts recorded"
        clock.step(60.0)
        cmd = mgr.run_disruption_once()
        assert cmd is None or not cmd.candidates, "emptiness reaped headroom"
        # real pods arrive and displace the virtual headroom on the nodes
        for i in range(2):
            store.create(ObjectStore.PODS, make_pod(f"real-{i}", cpu=1.0))
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        bound = [p for p in store.pods() if p.spec.node_name]
        assert len(bound) == 2, "real pods did not bind onto headroom nodes"


class TestBufferEmpty:
    def test_zero_replicas_reports_buffer_empty(self):
        clock, store, _cloud, mgr = _env()
        b = _buffer("empty", replicas=0, pod_template=PodSpec(requests={res.CPU: 1.0}))
        store.create(ObjectStore.CAPACITY_BUFFERS, b)
        store.create(ObjectStore.PODS, make_pod("p-0", cpu=0.5))
        clock.step(2.0)
        mgr.run_until_idle()
        assert b.conditions.is_false(COND_PROVISIONING)
        assert b.conditions.get(COND_PROVISIONING).reason == "BufferEmpty"
