"""Gang-aware multi-host slice scheduling (ISSUE 6).

The contract under test:

- DIFFERENTIAL: gang solves — mixed with fill / kind-scan / per-pod
  singleton dispatches, chunked at K in {1, 2, 4}, windowed and
  un-windowed — are BIT-identical to the host gang oracle
  (HostScheduler._place_gang), and the non-gang path stays bit-identical
  to its own oracle (the pre-PR contract, untouched);
- ALL-OR-NOTHING: a gang either fully places on one slice-shaped claim
  group in a dispatch or every member cleanly fails together with one
  reason — no partial placement ever decodes, no singleton ever lands on
  a slice host, and ranks map contiguously onto slice hosts;
- ORCHESTRATION: partial gangs wait for stragglers (clock-injected
  timeout), invalid gangs surface loudly, and the bind gate holds a gang
  out of the cluster until every member can bind;
- DISRUPTION: a slice's claim group is atomic — candidates are computed
  per gang, budgets/methods select whole units, and no command ever
  evicts a strict subset of a gang's hosts.

Everything here is host-only (CPU mesh) and sized for tier-1.
"""

import numpy as np
import pytest

import bench
from karpenter_tpu.controllers.provisioning.host_scheduler import HostScheduler
from karpenter_tpu.gang import (
    GANG_CLAIM_ANNOTATION,
    GANG_INVALID_REASON,
    GANG_NAME_ANNOTATION,
    GANG_RANK_ANNOTATION,
    GANG_SIZE_ANNOTATION,
    GANG_WAITING_REASON,
    GangWaitTracker,
    collect_gangs,
    gang_of,
    make_gang_pods,
    order_gangs,
    partially_bound_gangs,
)
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.clock import FakeClock

from test_solver import assert_same_packing
from test_window import make_templates, windowed_scheduler


# -- differential helpers -----------------------------------------------------


def host_oracle(pods, n_types=16, budgets=None):
    """The host gang oracle on the identical problem (the same topology
    construction bench.host_solve/_encode use)."""
    from karpenter_tpu.controllers.provisioning.topology import (
        Topology,
        build_universe_domains,
    )

    templates = make_templates(n_types)
    topo = Topology.build(list(pods), build_universe_domains(templates, []), [])
    return HostScheduler(templates, budgets=budgets, topology=topo).solve(list(pods))


def assert_gang_shape(result, key, size):
    """Slice-structure invariants on one engine's result: the gang's
    claims are dedicated (no foreign pods), hold contiguous rank blocks in
    slot order, and cover every rank exactly once."""
    gang_claims = sorted(
        (c for c in result.claims if getattr(c, "gang", None) == key),
        key=lambda c: c.slot,
    )
    ranks = []
    for c in gang_claims:
        claim_ranks = []
        for p in c.pods:
            parsed = gang_of(p)
            assert parsed is not None and parsed[0] == key, (
                f"foreign pod {p.metadata.name} on slice host {c.hostname}"
            )
            claim_ranks.append(parsed[2])
        assert claim_ranks == sorted(claim_ranks)
        ranks.extend(claim_ranks)
    assert ranks == list(range(size)), (
        f"ranks not contiguous across slice hosts: {ranks}"
    )
    # no gang pod may sit on a non-gang claim
    for c in result.claims:
        if getattr(c, "gang", None) != key:
            assert not any(
                (g := gang_of(p)) is not None and g[0] == key for p in c.pods
            )
    return gang_claims


def run_gang_parity(monkeypatch, pods, n_types=16, max_claims=128, window=0,
                    ks=(1, 2, 4), budgets=None, gangs=()):
    """Solve at each chunking K (optionally windowed); pin every run
    against the unchunked un-windowed device solve AND the host gang
    oracle, then check slice-structure invariants on both engines."""
    href = host_oracle(pods, n_types, budgets=budgets)
    base_sched = windowed_scheduler(monkeypatch, 0, 0, n_types, max_claims)
    base = base_sched.solve(pods, budgets=budgets)
    assert_same_packing(href, base)
    for key, size in gangs:
        assert_gang_shape(href, key, size)
        assert_gang_shape(base, key, size)
    for k in ks:
        sched = windowed_scheduler(monkeypatch, window, k, n_types, max_claims)
        result = sched.solve(pods, budgets=budgets)
        assert_same_packing(base, result)
        assert_same_packing(href, result)
        for key, size in gangs:
            assert_gang_shape(result, key, size)
    return href, base


# -- differential parity ------------------------------------------------------


class TestGangParity:
    def test_gang_with_fill_singles(self, monkeypatch):
        """One gang + selector singletons: the gang rides the gang-atomic
        kernel, singletons the kind-level fill scan, across K chunks."""
        pods = make_gang_pods("train-a", 8, cpu=1.5) + bench.selector_pods(24)
        run_gang_parity(
            monkeypatch, pods, gangs=[("default/train-a", 8)]
        )

    def test_multiple_gangs_largest_first(self, monkeypatch):
        """Three gangs of different slice footprints + singles: both
        engines share the largest-slice-first gang order, so packing is
        identical and each slice stays dedicated."""
        pods = (
            make_gang_pods("small", 2, cpu=0.5)
            + [make_pod(f"s-{i}", cpu=0.5) for i in range(12)]
            + make_gang_pods("big", 6, cpu=1.5)
            + make_gang_pods("mid", 4, cpu=1.0)
        )
        run_gang_parity(
            monkeypatch,
            pods,
            gangs=[
                ("default/small", 2),
                ("default/big", 6),
                ("default/mid", 4),
            ],
        )

    def test_gang_with_kscan_topology_singles(self, monkeypatch):
        """Singletons carrying zonal TSC / affinity topology ride the
        kind-scan and per-pod dispatches while the (topology-free) gang
        rides the gang kernel — mixed dispatch modes in one solve."""
        pods = make_gang_pods("train-k", 6, cpu=1.2) + bench.mixed_pods(30)
        run_gang_parity(
            monkeypatch, pods, n_types=24, gangs=[("default/train-k", 6)]
        )

    def test_gang_windowed_small_window(self, monkeypatch):
        """An active window far smaller than the slice: the gang's
        window-bound refusal reuses the NO_ROOM spill-and-retry path
        (solve_round grows the axis and re-solves) and still lands the
        oracle packing."""
        pods = make_gang_pods("train-w", 8, cpu=1.5) + [
            make_pod(f"w-{i}", cpu=0.5) for i in range(8)
        ]
        run_gang_parity(
            monkeypatch, pods, window=4, gangs=[("default/train-w", 8)]
        )

    def test_gang_under_budgets_stays_on_device(self, monkeypatch):
        """Finite pool budgets now ride the device gang kernel (per-block
        subtractMax debits in the rank-block loop): the solve stays on
        device — zero gang_constraints fallbacks — and stays bit-identical
        to the host oracle."""
        budgets = {"default": {"cpu": 100000.0}}
        before = metrics.SOLVER_FALLBACK.get(reason="gang_constraints")
        pods = make_gang_pods("train-b", 4, cpu=1.0) + [
            make_pod(f"b-{i}", cpu=0.5) for i in range(8)
        ]
        href = host_oracle(pods, 16, budgets=budgets)
        sched = windowed_scheduler(monkeypatch, 0, 0, 16, 128)
        result = sched.solve(pods, budgets=budgets)
        assert_same_packing(href, result)
        assert_gang_shape(result, "default/train-b", 4)
        assert metrics.SOLVER_FALLBACK.get(reason="gang_constraints") == before

    def test_gang_with_topology_stays_on_device(self, monkeypatch):
        """A gang kind carrying topology interaction (zonal TSC on the
        members) rides the gang-aware kscan (one vg evaluation per rank
        block) instead of tripping _GangHostRoute — zero fallbacks,
        bit-identical to the host oracle."""
        from karpenter_tpu.models import labels as l
        from karpenter_tpu.models.pod import TopologySpreadConstraint

        before = metrics.SOLVER_FALLBACK.get(reason="gang_constraints")
        pods = make_gang_pods("train-t", 4, cpu=1.0)
        for p in pods:
            p.metadata.labels = {"spread": "zonal"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    label_selector={"spread": "zonal"},
                )
            ]
        href = host_oracle(pods, 16)
        sched = windowed_scheduler(monkeypatch, 0, 0, 16, 128)
        result = sched.solve(pods)
        assert_same_packing(href, result)
        assert metrics.SOLVER_FALLBACK.get(reason="gang_constraints") == before

    def test_non_gang_solves_untouched(self, monkeypatch):
        """The non-gang path must not shift by a single pod: the standard
        mixed workload still matches its oracle (and the gang partition
        code never runs — no gang annotations present)."""
        run_gang_parity(monkeypatch, bench.mixed_pods(48), n_types=24)


def _spread_gang(name, size, cpu, topology_key, sel_value):
    """A gang whose members all carry one topology-spread constraint with
    a shared selector (the single-key shape the gang-aware kscan admits)."""
    from karpenter_tpu.models.pod import TopologySpreadConstraint

    pods = make_gang_pods(name, size, cpu=cpu)
    for p in pods:
        p.metadata.labels = dict(p.metadata.labels or {}, spread=sel_value)
        p.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=topology_key,
                label_selector={"spread": sel_value},
            )
        ]
    return pods


class TestGangConstraintParity:
    """Gang × {zonal-spread, hostname-spread, budget} host-oracle parity
    across chunks K in {1, 2, 4} — the frozen differential target for the
    gang-aware kscan (these classes used to raise _GangHostRoute; now the
    rank-block loop evaluates them on device, bit-identical either way)."""

    def test_gang_zonal_spread_parity(self, monkeypatch):
        from karpenter_tpu.models import labels as l

        pods = _spread_gang("gz", 6, 1.0, l.LABEL_TOPOLOGY_ZONE, "gz") + [
            make_pod(f"gzs-{i}", cpu=0.5) for i in range(8)
        ]
        run_gang_parity(monkeypatch, pods, gangs=[("default/gz", 6)])

    def test_gang_hostname_spread_parity(self, monkeypatch):
        from karpenter_tpu.models import labels as l

        pods = _spread_gang("gh", 4, 1.0, l.LABEL_HOSTNAME, "gh") + [
            make_pod(f"ghs-{i}", cpu=0.5) for i in range(6)
        ]
        run_gang_parity(monkeypatch, pods, gangs=[("default/gh", 4)])

    def test_gang_budget_parity(self, monkeypatch):
        budgets = {"default": {"cpu": 64.0}}
        pods = make_gang_pods("gb", 4, cpu=1.0) + [
            make_pod(f"gbs-{i}", cpu=0.5) for i in range(8)
        ]
        run_gang_parity(
            monkeypatch, pods, budgets=budgets, gangs=[("default/gb", 4)]
        )

    def test_gang_tight_nodes_budget_spills_identically(self, monkeypatch):
        """A nodes budget too small for the slice (budget.nodes < want is
        the host's pre-block gang gate — resource budgets only narrow the
        candidate set up front): both engines must spill the whole gang
        (all-or-nothing) while the singletons still place."""
        budgets = {"default": {"nodes": 1.0}}
        pods = make_gang_pods("gt", 4, cpu=6.0) + [
            make_pod(f"gts-{i}", cpu=0.25) for i in range(4)
        ]
        href, base = run_gang_parity(monkeypatch, pods, budgets=budgets)
        gang_unsched = {
            p.metadata.name for p, _ in base.unschedulable
            if p.metadata.name.startswith("gt-")
        }
        assert gang_unsched == {f"gt-{r}" for r in range(4)}

    def test_gang_zonal_under_budget_parity(self, monkeypatch):
        """Both new constraint classes at once: zonal spread narrows the
        per-block remaining set, and the budget debit is charged per block
        over exactly that narrowed set (host _charge_budget semantics)."""
        from karpenter_tpu.models import labels as l

        budgets = {"default": {"cpu": 48.0}}
        pods = _spread_gang("gzb", 6, 1.0, l.LABEL_TOPOLOGY_ZONE, "gzb") + [
            make_pod(f"gzbs-{i}", cpu=0.5) for i in range(6)
        ]
        run_gang_parity(
            monkeypatch, pods, budgets=budgets, gangs=[("default/gzb", 6)]
        )


# -- all-or-nothing semantics -------------------------------------------------


class TestAllOrNothing:
    def test_unplaceable_gang_fails_together(self, monkeypatch):
        """A gang no instance type can host: every member fails with ONE
        reason, and the singletons in the same solve still place."""
        pods = make_gang_pods("huge", 4, cpu=10000.0) + [
            make_pod(f"ok-{i}", cpu=0.5) for i in range(6)
        ]
        href, base = run_gang_parity(monkeypatch, pods)
        unsched = {p.metadata.name for p, _ in base.unschedulable}
        assert unsched == {f"huge-{r}" for r in range(4)}
        reasons = {r for _, r in base.unschedulable}
        assert len(reasons) == 1, f"split reasons across one gang: {reasons}"
        assert len(base.claims) >= 1  # singles placed

    def test_incomplete_gang_held_out(self, monkeypatch):
        """Missing ranks keep the WHOLE gang out of the solve (waiting
        reason), identically on both engines."""
        pods = make_gang_pods("partial", 4, cpu=1.0)[:2] + [
            make_pod(f"ok-{i}", cpu=0.5) for i in range(4)
        ]
        href, base = run_gang_parity(monkeypatch, pods)
        waiting = {
            p.metadata.name for p, r in base.unschedulable if r == GANG_WAITING_REASON
        }
        assert waiting == {"partial-0", "partial-1"}

    def test_invalid_gangs_surface_loudly(self, monkeypatch):
        """Duplicate ranks, conflicting sizes, heterogeneous members:
        rejected with invalid reasons, never silently solved."""
        dup = make_gang_pods("dup", 2, cpu=0.5)
        dup[1].metadata.annotations[GANG_RANK_ANNOTATION] = "0"
        hetero = make_gang_pods("hetero", 2, cpu=0.5)
        hetero[1].spec.requests["cpu"] = 1.5
        pods = dup + hetero + [make_pod("ok-0", cpu=0.5)]
        href, base = run_gang_parity(monkeypatch, pods)
        invalid = {
            p.metadata.name
            for p, r in base.unschedulable
            if r.startswith(GANG_INVALID_REASON)
        }
        assert "dup-1" in invalid
        assert {"hetero-0", "hetero-1"} <= invalid

    def test_no_singleton_backfills_slice_headroom(self, monkeypatch):
        """A slice host with spare room (last rank block not full) must
        NOT accept singleton pods — gang claims are dedicated on both
        engines (host tier-2 skips them; the device freezes them)."""
        # gang of 3 at 0.5 cpu: per-host fill > 1, so the last slice host
        # has headroom a greedy tier-2 would love to fill
        pods = make_gang_pods("lone", 3, cpu=0.5) + [
            make_pod(f"bf-{i}", cpu=0.5) for i in range(6)
        ]
        href, base = run_gang_parity(
            monkeypatch, pods, gangs=[("default/lone", 3)]
        )
        for result in (href, base):
            for c in result.claims:
                if getattr(c, "gang", None):
                    assert all(
                        gang_of(p) is not None for p in c.pods
                    ), "singleton backfilled a slice host"


# -- annotations, ordering, straggler wait ------------------------------------


class TestGangCollect:
    def test_parse_and_validate(self):
        p = make_gang_pods("g", 2)[1]
        assert gang_of(p) == ("default/g", 2, 1)
        p.metadata.annotations[GANG_RANK_ANNOTATION] = "2"  # rank >= size
        assert gang_of(p) is None
        p.metadata.annotations[GANG_RANK_ANNOTATION] = "x"
        assert gang_of(p) is None
        p.metadata.annotations.pop(GANG_NAME_ANNOTATION)
        assert gang_of(p) is None
        q = make_gang_pods("q", 2)[0]
        q.metadata.annotations[GANG_SIZE_ANNOTATION] = "0"
        assert gang_of(q) is None

    def test_collect_partitions_and_rejects(self):
        good = make_gang_pods("good", 2)
        clash = make_gang_pods("clash", 2)
        clash[1].metadata.annotations[GANG_SIZE_ANNOTATION] = "3"
        singles = [make_pod("s-0"), make_pod("s-1")]
        gangs, out_singles, invalid = collect_gangs(good + clash + singles)
        assert {g.key for g in gangs} == {"default/good", "default/clash"}
        assert [p.metadata.name for p in out_singles] == ["s-0", "s-1"]
        assert [p.metadata.name for p, _ in invalid] == ["clash-1"]
        good_spec = next(g for g in gangs if g.key == "default/good")
        assert good_spec.complete and good_spec.missing == 0

    def test_order_largest_slice_first(self):
        small = make_gang_pods("small", 2, cpu=0.5)
        big = make_gang_pods("big", 4, cpu=2.0)
        gangs, _, _ = collect_gangs(small + big)
        ordered = order_gangs(gangs)
        assert [g.key for g in ordered] == ["default/big", "default/small"]

    def test_wait_tracker_timeout_and_completion(self):
        clock = FakeClock()
        tracker = GangWaitTracker(clock, timeout_s=30.0)
        partial_pods = make_gang_pods("w", 3)[:2]
        gangs, _, _ = collect_gangs(partial_pods)
        ready, waiting, timed_out = tracker.admit(gangs)
        assert not ready and not timed_out and len(waiting) == 1
        clock.step(31.0)
        gangs, _, _ = collect_gangs(partial_pods)
        ready, waiting, timed_out = tracker.admit(gangs)
        assert len(timed_out) == 1  # reported once, then the window restarts
        gangs, _, _ = collect_gangs(partial_pods)
        ready, waiting, timed_out = tracker.admit(gangs)
        assert len(waiting) == 1 and not timed_out
        # completion observes the wait histogram and releases the timer
        h0 = metrics.GANG_WAIT_DURATION.totals.get((), 0)
        clock.step(5.0)
        gangs, _, _ = collect_gangs(make_gang_pods("w", 3))
        ready, waiting, timed_out = tracker.admit(gangs)
        assert len(ready) == 1
        assert metrics.GANG_WAIT_DURATION.totals.get((), 0) == h0 + 1
        assert not tracker._first_seen


# -- disruption atomicity -----------------------------------------------------


def _gang_env(n_gangs=2, gang_size=3, n_singles=2, consolidate_after=0.0,
              cpu=1.5):
    """kwok harness with bound gangs + singles; returns the usual stack."""
    from karpenter_tpu.envelope.scenarios import _harness, _provision

    clock, store, cloud, mgr = _harness(
        catalog_size=64, consolidate_after=consolidate_after
    )
    pods = []
    for gi in range(n_gangs):
        pods.extend(make_gang_pods(f"dg-{gi}", gang_size, cpu=cpu))
    pods.extend(make_pod(f"dgs-{i}", cpu=0.5) for i in range(n_singles))
    _provision(mgr, store, cloud, pods)
    assert not partially_bound_gangs(store.pods())
    assert all(p.spec.node_name for p in store.pods())
    return clock, store, cloud, mgr


def _gang_claim_names(store, key):
    return {
        c.name
        for c in store.nodeclaims()
        if c.metadata.annotations.get(GANG_CLAIM_ANNOTATION) == key
    }


class TestGangDisruption:
    def test_claims_annotated_and_candidates_grouped(self):
        from karpenter_tpu.controllers.disruption.candidates import (
            atomic_units,
            build_candidates,
            gang_key_of_node,
        )

        clock, store, cloud, mgr = _gang_env()
        assert len(_gang_claim_names(store, "default/dg-0")) >= 1
        # every slice host's StateNode resolves its gang key
        keyed = [
            gang_key_of_node(sn)
            for sn in mgr.cluster.nodes()
            if gang_key_of_node(sn)
        ]
        assert len(keyed) == len(_gang_claim_names(store, "default/dg-0")) + len(
            _gang_claim_names(store, "default/dg-1")
        )
        # candidate units: one per gang (complete), singletons alone
        from karpenter_tpu.cloudprovider.errors import instance_types_or_none
        from karpenter_tpu.state.store import ObjectStore

        pools = {p.name: p for p in store.nodepools()}
        its = {
            it.name: it
            for p in pools.values()
            for it in instance_types_or_none(cloud, p) or ()
        }
        cands = build_candidates(mgr.cluster, pools, its, clock)
        units = atomic_units(cands)
        by_key = {}
        for u in units:
            if u[0].gang_key:
                by_key[u[0].gang_key] = len(u)
        hosts_per_gang = len(_gang_claim_names(store, "default/dg-0"))
        assert by_key.get("default/dg-0") == hosts_per_gang
        assert by_key.get("default/dg-1") == hosts_per_gang

    def test_blocked_host_withdraws_whole_gang(self):
        from karpenter_tpu.cloudprovider.errors import instance_types_or_none
        from karpenter_tpu.controllers.disruption.candidates import build_candidates
        from karpenter_tpu.models import labels as l
        from karpenter_tpu.state.store import ObjectStore

        clock, store, cloud, mgr = _gang_env(n_gangs=1, gang_size=4, n_singles=1)
        # block ONE slice host via do-not-disrupt on a pod
        victim = next(
            p for p in store.pods() if gang_of(p) is not None and p.spec.node_name
        )
        victim.metadata.annotations[l.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        store.update(ObjectStore.PODS, victim)
        pools = {p.name: p for p in store.nodepools()}
        its = {
            it.name: it
            for p in pools.values()
            for it in instance_types_or_none(cloud, p) or ()
        }
        cands = build_candidates(mgr.cluster, pools, its, clock)
        assert not any(c.gang_key for c in cands), (
            "one blocked slice host must withdraw every host of the gang"
        )

    def test_budget_never_splits_a_gang(self):
        from karpenter_tpu.controllers.disruption.methods import _within_budget

        clock, store, cloud, mgr = _gang_env(n_gangs=1, gang_size=3, n_singles=0, cpu=24.0)
        from karpenter_tpu.cloudprovider.errors import instance_types_or_none
        from karpenter_tpu.controllers.disruption.candidates import build_candidates

        pools = {p.name: p for p in store.nodepools()}
        its = {
            it.name: it
            for p in pools.values()
            for it in instance_types_or_none(cloud, p) or ()
        }
        cands = build_candidates(mgr.cluster, pools, its, clock)
        gang_cands = [c for c in cands if c.gang_key]
        n_hosts = len(gang_cands)
        if n_hosts < 2:
            pytest.skip("slice fit on one host in this catalog")
        # a budget smaller than the slice takes NONE of its hosts
        chosen = _within_budget(gang_cands, {"default": n_hosts - 1})
        assert chosen == []
        chosen = _within_budget(gang_cands, {"default": n_hosts})
        assert len(chosen) == n_hosts

    def test_emptiness_evicts_finished_slice_atomically(self):
        from karpenter_tpu.state.store import ObjectStore

        from test_disruption import delete_pods, disrupt_through_validation

        clock, store, cloud, mgr = _gang_env(n_gangs=1, gang_size=4, n_singles=1)
        slice_nodes = {
            p.spec.node_name for p in store.pods() if gang_of(p) is not None
        }
        single_node = next(
            p.spec.node_name for p in store.pods() if gang_of(p) is None
        )
        # the training job finishes: every gang pod completes
        delete_pods(store, mgr, lambda p: gang_of(p) is not None)
        clock.step(60.0)
        cmd = disrupt_through_validation(mgr, clock)
        assert cmd is not None and cmd.reason == "Empty"
        gang_cands = [c for c in cmd.candidates if c.gang_key == "default/dg-0"]
        assert len(gang_cands) == len(slice_nodes), (
            "emptiness must take the whole slice, never a subset"
        )
        # settle the deletions: every slice host leaves TOGETHER, the
        # singleton's (non-empty) host survives
        for _ in range(4):
            mgr.run_until_idle()
            clock.step(16.0)
            mgr.run_disruption_once()
        node_names = {n.name for n in store.nodes()}
        assert not (slice_nodes & node_names), "slice hosts lingered"
        assert single_node in node_names

    def test_partial_gang_violation_tripwire(self):
        from karpenter_tpu.cloudprovider.errors import instance_types_or_none
        from karpenter_tpu.controllers.disruption.candidates import (
            build_candidates,
            partial_gang_violation,
        )

        clock, store, cloud, mgr = _gang_env(n_gangs=1, gang_size=4, n_singles=0, cpu=24.0)
        pools = {p.name: p for p in store.nodepools()}
        its = {
            it.name: it
            for p in pools.values()
            for it in instance_types_or_none(cloud, p) or ()
        }
        cands = build_candidates(mgr.cluster, pools, its, clock)
        gang_cands = [c for c in cands if c.gang_key]
        if len(gang_cands) < 2:
            pytest.skip("slice fit on one host in this catalog")
        assert partial_gang_violation(gang_cands, mgr.cluster) is None
        assert (
            partial_gang_violation(gang_cands[:-1], mgr.cluster)
            == "default/dg-0"
        )


# -- e2e: storm + chaos -------------------------------------------------------


class TestTrainingStorm:
    def test_training_storm_scenario_under_envelope(self):
        from karpenter_tpu.envelope.scenarios import run_scenario

        result = run_scenario("training_storm")
        assert result.detail["gangs"] == 3
        assert result.detail["slice_hosts"] >= result.detail["gangs"]

    def test_ice_storm_mid_gang_never_partial(self):
        """Chaos variant: an ICE storm hits claim launches while gangs are
        in flight. At EVERY observable point, each gang is fully bound or
        fully pending; the storm bends the path, never the invariant, and
        everything converges once the storm passes."""
        from karpenter_tpu.controllers.manager import KubeSchedulerSim
        from karpenter_tpu.envelope.scenarios import _harness
        from karpenter_tpu.faultinject import FAULT, active_plan
        from karpenter_tpu.state.store import ObjectStore

        clock, store, cloud, mgr = _harness(catalog_size=64)
        pods = (
            make_gang_pods("ice-a", 4, cpu=1.5)
            + make_gang_pods("ice-b", 3, cpu=1.0)
            + [make_pod(f"ice-s-{i}", cpu=0.5) for i in range(6)]
        )
        plan = {
            "seed": 13,
            "rules": [
                {"point": "cloud.create", "error": "ice", "p": 0.5, "times": 6}
            ],
        }
        with active_plan(plan):
            for p in pods:
                store.create(ObjectStore.PODS, p)
            for _ in range(24):
                mgr.run_until_idle()
                cloud.simulate_kubelet_ready()
                mgr.run_until_idle()
                KubeSchedulerSim(store, mgr.cluster).bind_pending()
                partial = partially_bound_gangs(store.pods())
                assert not partial, f"partial gang bound mid-storm: {partial}"
                if all(p.spec.node_name for p in store.pods()):
                    break
                mgr.batcher.trigger()
                clock.step(5.0)
            injected = FAULT.fires("cloud.create")
        assert injected >= 1, "the ICE storm never fired"
        stranded = [p.name for p in store.pods() if not p.spec.node_name]
        assert not stranded, f"stranded after the storm: {stranded}"
        assert not partially_bound_gangs(store.pods())
        # outcome accounting saw the gangs land
        assert metrics.GANG_PLACEMENTS.get(outcome="placed") >= 1
        # and the partial-placement tripwire never fired, ever
        assert metrics.GANG_PLACEMENTS.get(outcome="partial") == 0
