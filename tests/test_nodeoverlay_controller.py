"""NodeOverlay runtime controller: validation, conflict detection, the
unevaluated-pool gate, and the 6h revalidation requeue.

Reference: pkg/controllers/nodeoverlay/controller.go:62-300 (reconcile,
conflict rules, status conditions), store.go:45-288 (evaluated store,
UnevaluatedNodePoolError on unevaluated pools), suite_test.go.
"""

from __future__ import annotations

import pytest

from karpenter_tpu.cloudprovider import UnevaluatedNodePoolError
from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.cloudprovider.overlay import NodeOverlay, OverlayCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeoverlay import (
    CONDITION_VALIDATION_SUCCEEDED,
    REQUEUE_SECONDS,
    EvaluatedOverlayStore,
    NodeOverlayController,
)
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock


def _overlay(name, weight=0, price=None, capacity=None, requirements=None):
    o = NodeOverlay(
        requirements=requirements or [],
        weight=weight,
        price=price,
        capacity=capacity or {},
    )
    o.metadata.name = name
    return o


def _pool(name="default"):
    pool = NodePool()
    pool.metadata.name = name
    return pool


def _env(n_types=4):
    clock = FakeClock()
    store = ObjectStore(clock)
    inner = KwokCloudProvider(store, catalog=instance_types(n_types))
    cloud = OverlayCloudProvider(inner, store)
    evaluated = EvaluatedOverlayStore()
    cloud.evaluated_store = evaluated
    ctrl = NodeOverlayController(store, inner, clock, evaluated)
    return clock, store, inner, cloud, ctrl


class TestUnevaluatedGate:
    def test_gate_until_first_evaluation_then_unblocks(self):
        _clock, store, _inner, cloud, ctrl = _env()
        pool = _pool()
        store.create(ObjectStore.NODEPOOLS, pool)
        # before the controller has ever evaluated: the catalog is refused
        # (store.go:64-65) — the error type exists to be RAISED
        with pytest.raises(UnevaluatedNodePoolError):
            cloud.get_instance_types(pool)
        out = ctrl.reconcile()
        assert out["evaluated_pools"] == 1
        assert cloud.get_instance_types(pool)

    def test_new_pool_is_gated_until_revalidated(self):
        _clock, store, _inner, cloud, ctrl = _env()
        store.create(ObjectStore.NODEPOOLS, _pool("a"))
        ctrl.reconcile()
        late = _pool("late")
        store.create(ObjectStore.NODEPOOLS, late)
        with pytest.raises(UnevaluatedNodePoolError):
            cloud.get_instance_types(late)
        ctrl.reconcile()
        assert cloud.get_instance_types(late)


class TestConflictDetection:
    def test_equal_weight_price_overlays_conflict(self):
        _clock, store, _inner, _cloud, ctrl = _env()
        store.create(ObjectStore.NODEPOOLS, _pool())
        a = _overlay("a-first", weight=5, price="+10%")
        b = _overlay("b-second", weight=5, price="-10%")
        for o in (a, b):
            store.create(ObjectStore.NODE_OVERLAYS, o)
        out = ctrl.reconcile()
        # name tie-break: a-first wins, b-second conflicts
        # (store.go:267-287 — equal lowestWeight on a touched offering)
        assert out["active"] == 1 and out["conflicted"] == 1
        assert a.conditions.is_true(CONDITION_VALIDATION_SUCCEEDED)
        assert b.conditions.is_false(CONDITION_VALIDATION_SUCCEEDED)
        assert b.conditions.get(CONDITION_VALIDATION_SUCCEEDED).reason == "Conflict"

    def test_different_weights_do_not_conflict_heaviest_wins(self):
        _clock, store, _inner, cloud, ctrl = _env()
        pool = _pool()
        store.create(ObjectStore.NODEPOOLS, pool)
        heavy = _overlay("heavy", weight=10, price="5.0")
        light = _overlay("light", weight=1, price="9.0")
        for o in (heavy, light):
            store.create(ObjectStore.NODE_OVERLAYS, o)
        out = ctrl.reconcile()
        assert out["conflicted"] == 0 and out["active"] == 2
        for it in cloud.get_instance_types(pool):
            assert all(of.price == 5.0 for of in it.offerings)

    def test_equal_weight_capacity_conflict_needs_overlapping_resources(self):
        _clock, store, _inner, _cloud, ctrl = _env()
        store.create(ObjectStore.NODEPOOLS, _pool())
        gpus = _overlay("a-gpus", weight=3, capacity={"example.com/gpu": 4.0})
        clash = _overlay("b-clash", weight=3, capacity={"example.com/gpu": 2.0})
        tpus = _overlay("c-tpus", weight=3, capacity={"example.com/tpu": 8.0})
        for o in (gpus, tpus, clash):
            store.create(ObjectStore.NODE_OVERLAYS, o)
        out = ctrl.reconcile()
        # b-clash overlaps a-gpus' resource at the same weight -> conflict;
        # c-tpus touches a disjoint resource -> coexists (store.go:212-238:
        # the conflict needs a key overlap with the LAST same-weight entry)
        assert out["conflicted"] == 1
        assert gpus.conditions.is_true(CONDITION_VALIDATION_SUCCEEDED)
        assert tpus.conditions.is_true(CONDITION_VALIDATION_SUCCEEDED)
        assert clash.conditions.is_false(CONDITION_VALIDATION_SUCCEEDED)

    def test_non_overlapping_selectors_never_conflict(self):
        _clock, store, _inner, _cloud, ctrl = _env(n_types=8)
        store.create(ObjectStore.NODEPOOLS, _pool())
        spot = _overlay(
            "spot",
            weight=5,
            price="-50%",
            requirements=[
                {
                    "key": l.CAPACITY_TYPE_LABEL_KEY,
                    "operator": "In",
                    "values": [l.CAPACITY_TYPE_SPOT],
                }
            ],
        )
        od = _overlay(
            "od",
            weight=5,
            price="+50%",
            requirements=[
                {
                    "key": l.CAPACITY_TYPE_LABEL_KEY,
                    "operator": "In",
                    "values": [l.CAPACITY_TYPE_ON_DEMAND],
                }
            ],
        )
        for o in (spot, od):
            store.create(ObjectStore.NODE_OVERLAYS, o)
        out = ctrl.reconcile()
        # same weight, but they touch DISJOINT offerings — no conflict
        assert out["conflicted"] == 0 and out["active"] == 2


class TestRuntimeValidation:
    def test_invalid_price_sets_runtime_validation_condition(self):
        _clock, store, _inner, cloud, ctrl = _env()
        pool = _pool()
        store.create(ObjectStore.NODEPOOLS, pool)
        bad = _overlay("bad", price="banana")
        good = _overlay("good", price="+100%")
        for o in (bad, good):
            store.create(ObjectStore.NODE_OVERLAYS, o)
        out = ctrl.reconcile()
        assert out["invalid"] == 1 and out["active"] == 1
        assert bad.conditions.is_false(CONDITION_VALIDATION_SUCCEEDED)
        assert (
            bad.conditions.get(CONDITION_VALIDATION_SUCCEEDED).reason
            == "RuntimeValidation"
        )
        # the invalid overlay is NOT applied; the valid one is
        base = {it.name: it for it in ctrl.inner.get_instance_types(pool)}
        for it in cloud.get_instance_types(pool):
            for of, of0 in zip(it.offerings, base[it.name].offerings):
                assert of.price == pytest.approx(of0.price * 2)

    def test_negative_capacity_rejected(self):
        _clock, store, _inner, _cloud, ctrl = _env()
        store.create(ObjectStore.NODEPOOLS, _pool())
        bad = _overlay("neg", capacity={"example.com/gpu": -1.0})
        store.create(ObjectStore.NODE_OVERLAYS, bad)
        out = ctrl.reconcile()
        assert out["invalid"] == 1
        assert bad.conditions.is_false(CONDITION_VALIDATION_SUCCEEDED)


class TestManagerWiring:
    def _managed(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        inner = KwokCloudProvider(store, catalog=instance_types(4))
        cloud = OverlayCloudProvider(inner, store)
        mgr = Manager(store, cloud, clock)
        return clock, store, inner, cloud, mgr

    def test_manager_wires_controller_and_lifts_gate(self):
        _clock, store, _inner, cloud, mgr = self._managed()
        assert mgr.nodeoverlay is not None
        assert cloud.evaluated_store is mgr.nodeoverlay.evaluated
        pool = _pool()
        store.create(ObjectStore.NODEPOOLS, pool)  # _on_nodepool revalidates
        assert cloud.get_instance_types(pool)

    def test_provisioning_follows_overlay_price_through_the_gate(self):
        _clock, store, _inner, _cloud, mgr = self._managed()
        store.create(ObjectStore.NODEPOOLS, _pool())
        o = _overlay("pricey", price="1000.0")
        store.create(ObjectStore.NODE_OVERLAYS, o)  # _on_overlay revalidates
        store.create(ObjectStore.PODS, make_pod("p-1", cpu=0.5))
        mgr.batcher.trigger()
        mgr.run_until_idle()
        claims = store.nodeclaims()
        assert claims, "provisioning stayed gated after overlay evaluation"

    def test_six_hour_requeue(self):
        clock, store, _inner, _cloud, mgr = self._managed()
        store.create(ObjectStore.NODEPOOLS, _pool())
        ctrl = mgr.nodeoverlay
        before = ctrl._next_requeue
        assert ctrl.maybe_reconcile() is None  # inside the window: no-op
        clock.step(REQUEUE_SECONDS + 1.0)
        assert ctrl.maybe_reconcile() is not None
        assert ctrl._next_requeue > before


class TestPricingInformer:
    """Re-price on pricing change (state/informer/pricing.go analog): an
    overlay price change must re-derive every live claim's ClusterCost
    entry — the Balanced-scoring denominator — without any claim churn."""

    def _bound_cluster(self, n_pods=4):
        from karpenter_tpu.cloudprovider.fake import new_instance_type
        from karpenter_tpu.controllers.manager import KubeSchedulerSim

        clock = FakeClock()
        store = ObjectStore(clock)
        inner = KwokCloudProvider(
            store,
            catalog=[new_instance_type("n-4x", cpu=4), new_instance_type("n-8x", cpu=8)],
        )
        cloud = OverlayCloudProvider(inner, store)
        mgr = Manager(store, cloud, clock)
        pool = _pool()
        pool.spec.disruption.consolidation_policy = "Balanced"
        store.create(ObjectStore.NODEPOOLS, pool)
        for i in range(n_pods):
            store.create(
                ObjectStore.PODS,
                make_pod(f"p-{i}", cpu=2.0, node_selector={l.LABEL_INSTANCE_TYPE: "n-4x"}),
            )
        mgr.run_until_idle()
        inner.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        mgr.run_until_idle()
        assert all(p.spec.node_name for p in store.pods())
        return clock, store, mgr

    def test_overlay_price_change_reprices_ledger_without_claim_churn(self):
        _clock, store, mgr = self._bound_cluster()
        cost0 = mgr.cost.pool_cost("default")
        assert cost0 > 0
        versions = {c.name: c.metadata.resource_version for c in store.nodeclaims()}
        store.create(ObjectStore.NODE_OVERLAYS, _overlay("surge", price="+900%"))
        # ledger repriced from the overlaid catalog, claims untouched
        assert mgr.cost.pool_cost("default") == pytest.approx(10.0 * cost0, rel=1e-6)
        assert {
            c.name: c.metadata.resource_version for c in store.nodeclaims()
        } == versions, "repricing must not churn claims"

    def test_overlay_price_change_flips_balanced_decision(self):
        """A delete-consolidation of one of four single-pod nodes scores
        ratio = (savings/poolCost)/(disruption/poolDisruption) = 1.0 with
        the pre-overlay ledger (approved at k=2), and 0.1 once a +900%
        overlay reprices the denominator — the decision must flip on the
        overlay event alone, with zero claim churn (balanced.go:47-130)."""
        from karpenter_tpu.controllers.disruption.candidates import Candidate
        from karpenter_tpu.controllers.disruption.methods import Command

        _clock, store, mgr = self._bound_cluster(n_pods=4)
        pool = store.get(ObjectStore.NODEPOOLS, "default")
        per_claim = mgr.cost.pool_cost("default") / 4.0
        sn = mgr.cluster.node_by_name(store.nodes()[0].name)
        assert sn is not None and sn.pods
        candidate = Candidate(
            state_node=sn,
            nodepool=pool,
            instance_type=None,
            price=per_claim,
            reschedulable_pods=[],
            disruption_cost=2.0,  # 1.0 node + 1.0 for its single pod
        )
        cmd = Command(candidates=[candidate], replacements=[], reason="Underutilized")
        assert mgr.disruption._balanced_approves(cmd, [candidate])
        store.create(ObjectStore.NODE_OVERLAYS, _overlay("surge", price="+900%"))
        assert not mgr.disruption._balanced_approves(cmd, [candidate]), (
            "Balanced approved against a stale pool cost after repricing"
        )
