"""Pluggable batched placement objectives (objectives/, ISSUE 19).

The acceptance properties under test:

- ``lexical`` is BIT-identical to the pre-objective solver: explicit
  lexical, unset env, and typo'd policy names all leave Templates.rank
  unmaterialized and reproduce the same packing;
- every non-lexical policy is exact under its own rank: the meshed,
  windowed, pipelined solve equals the single-device sequential solve of
  the same policy (rank is state-independent data, so the dp/window
  machinery's proofs carry over unchanged);
- the canonical ranks mean what they claim: ``cost_min`` strictly lowers
  the fleet price on a mixed-generation multi-pool problem, host rank
  construction matches the encode-side price columns;
- the K-variant fill dispatch commits the best-scoring feasible row and
  is never WORSE than the single-variant (canonical) solve, with one
  verdict-word fetch per merge round;
- the objective-twin shadow audit passes on honest scores and CATCHES a
  lying scorer (KTPU_GUARD_LIE=objective): divergence recorded, the
  "objective" path quarantines, and the next solve routes back onto
  lexical;
- consolidation orders atomic units by the same scores: cost_min walks
  priciest-first and EXCLUDES unknown-price candidates from the cost
  ranking (the candidates.py silent-0.0 fix, ktpu_pricing_missing_total).
"""

import numpy as np
import pytest

import bench
from karpenter_tpu import guard, objectives
from karpenter_tpu.cloudprovider.fake import instance_types, new_instance_type
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.objectives import oracle as obj_oracle
from karpenter_tpu.objectives import scoring as obj_scoring
from karpenter_tpu.parallel import make_mesh
from karpenter_tpu.utils.metrics import (
    OBJECTIVE_ROUNDS,
    OBJECTIVE_VARIANT_WINS,
    PRICING_MISSING,
)

from test_shard import (
    assert_bit_identical,
    make_templates,
    mixed_kind_pods,
    perpod_kind_pods,
    zonal_kind_pods,
)

NON_LEXICAL = ("cost_min", "frag_aware", "topo_spread", "gang_slice")


@pytest.fixture(autouse=True)
def _clean_objective_state(monkeypatch):
    """Every test starts with no policy selected, no quarantine, and the
    guard knobs unset."""
    for var in (
        "KTPU_OBJECTIVE",
        "KTPU_OBJECTIVE_K",
        "KTPU_GUARD_AUDIT_RATE",
        "KTPU_GUARD_LIE",
        "KTPU_PIPELINE_CHUNKS",
        "KTPU_PIPELINE_MIN_PODS",
        "KTPU_SCAN_WINDOW",
    ):
        monkeypatch.delenv(var, raising=False)
    guard.QUARANTINE.reset()
    guard.reset_log()
    yield
    guard.QUARANTINE.reset()
    guard.reset_log()


def mixed_pool_templates(n_types=48, families=("m", "s", "c", "e")):
    """One pool per instance family, priciest family FIRST so lexical's
    weight order is the expensive choice and cost_min has real work to do
    (fake catalog price multipliers: m=1.2, s=1.0, c=0.8, e=0.6)."""
    catalog = instance_types(n_types)
    pools = []
    for fam in families:
        p = NodePool()
        p.metadata.name = f"{fam}-pool"
        p.spec.template.spec.requirements = [
            {
                "key": "karpenter-tpu.sh/instance-family",
                "operator": "In",
                "values": [fam],
            },
        ]
        pools.append((p, catalog))
    return build_templates(pools)


def objective_scheduler(monkeypatch, templates, *, pipeline=True, window=0,
                        mesh_n=0, objective=None):
    if pipeline:
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "4")
        monkeypatch.setenv("KTPU_PIPELINE_MIN_PODS", "32")
    else:
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
    if window:
        monkeypatch.setenv("KTPU_SCAN_WINDOW", str(window))
    else:
        monkeypatch.delenv("KTPU_SCAN_WINDOW", raising=False)
    mesh = make_mesh(mesh_n) if mesh_n else None
    return TPUScheduler(templates, mesh=mesh, objective=objective)


class TestRegistry:
    def test_precedence_nodepool_env_default(self, monkeypatch):
        assert objectives.resolve_policy() == "lexical"
        monkeypatch.setenv("KTPU_OBJECTIVE", "frag_aware")
        assert objectives.resolve_policy() == "frag_aware"
        assert objectives.resolve_policy("cost_min") == "cost_min"

    def test_unknown_names_fall_back_to_lexical(self, monkeypatch):
        monkeypatch.setenv("KTPU_OBJECTIVE", "cheapest_pls")
        assert objectives.resolve_policy() == "lexical"
        assert objectives.resolve_policy("also_bogus") == "lexical"

    def test_quarantine_reverts_to_lexical(self, monkeypatch):
        monkeypatch.setenv("KTPU_OBJECTIVE", "cost_min")
        assert objectives.active_policy() == "cost_min"
        guard.QUARANTINE.trip("objective", reason="test")
        assert objectives.active_policy() == "lexical"
        guard.QUARANTINE.clear("objective")
        assert objectives.active_policy() == "cost_min"

    def test_variant_count(self, monkeypatch):
        from karpenter_tpu.ops.solver import VARIANT_MAX

        assert objectives.variant_count(8) == 8  # default: dp extent
        assert objectives.variant_count(0) == 1
        monkeypatch.setenv("KTPU_OBJECTIVE_K", "3")
        assert objectives.variant_count(8) == 3
        monkeypatch.setenv("KTPU_OBJECTIVE_K", "999")
        assert objectives.variant_count(8) == VARIANT_MAX
        monkeypatch.setenv("KTPU_OBJECTIVE_K", "junk")
        assert objectives.variant_count(4) == 4

    def test_objective_ids_match_solver_constants(self):
        from karpenter_tpu.ops import solver

        assert objectives.objective_id("lexical") == solver.OBJ_LEXICAL
        assert objectives.objective_id("cost_min") == solver.OBJ_COST_MIN
        assert objectives.objective_id("frag_aware") == solver.OBJ_FRAG_AWARE
        assert objectives.objective_id("topo_spread") == solver.OBJ_TOPO_SPREAD
        assert objectives.objective_id("gang_slice") == solver.OBJ_GANG_SLICE


class TestCanonicalRanks:
    def test_cost_min_rank_tracks_price_floor(self):
        templates = mixed_pool_templates()
        rank = obj_scoring.canonical_rank("cost_min", templates)
        prices = [obj_scoring.template_price(t) for t in templates]
        # rank order == ascending price-floor order (ties by weight index)
        order = sorted(range(len(templates)), key=lambda g: (prices[g], g))
        for pos, g in enumerate(order):
            assert rank[g] == pos
        # e-pool (0.6x multiplier) must outrank m-pool (1.2x)
        by_name = {t.nodepool_name: rank[i] for i, t in enumerate(templates)}
        assert by_name["e-pool"] < by_name["c-pool"] < by_name["s-pool"] < by_name["m-pool"]

    def test_lexical_rank_is_identity(self):
        templates = mixed_pool_templates()
        assert np.array_equal(
            obj_scoring.canonical_rank("lexical", templates),
            np.arange(len(templates), dtype=np.int32),
        )

    def test_rank_matches_encode_price_columns(self):
        """Host rank construction and the device price column agree: the
        encode-side template price floor induces the same cost_min order
        as the scoring-side catalog walk."""
        from karpenter_tpu.ops import encode as ops_encode

        templates = mixed_pool_templates()
        sched = TPUScheduler(templates)
        sched.solve(bench.mixed_pods(8))  # trigger the static encode
        price_t = np.asarray(ops_encode.type_price_column(sched.it_tensors))
        tmpl_its = np.asarray(sched.template_tensors.its)
        g_floor = ops_encode.template_price_column(tmpl_its, price_t)
        host_floor = np.array(
            [obj_scoring.template_price(t) for t in templates], dtype=np.float32
        )
        assert np.allclose(g_floor[: len(templates)], host_floor, rtol=1e-5)

    def test_variant_ranks_shape_and_perturbation(self):
        rank = np.array([2, 0, 3, 1], dtype=np.int32)
        out = obj_scoring.variant_ranks(rank, 3)
        assert out.shape == (3, 4)
        assert np.array_equal(out[0], rank)  # row 0 canonical
        order = np.argsort(rank, kind="stable")
        for k in (1, 2):
            expect = rank.copy()
            expect[order[k]] = rank.min() - 1
            assert np.array_equal(out[k], expect)
        # KV clamps to G
        assert obj_scoring.variant_ranks(rank, 99).shape == (4, 4)


class TestLexicalBitParity:
    def test_explicit_lexical_matches_default(self, monkeypatch):
        pods = mixed_kind_pods(128)
        base = TPUScheduler(make_templates()).solve(list(pods))
        monkeypatch.setenv("KTPU_OBJECTIVE", "lexical")
        sched = TPUScheduler(make_templates())
        r = sched.solve(list(pods))
        # lexical never materializes a rank column at all
        assert sched.template_tensors.rank is None
        assert_bit_identical(r, base)

    def test_typo_policy_matches_default(self, monkeypatch):
        pods = mixed_kind_pods(128)
        base = TPUScheduler(make_templates()).solve(list(pods))
        monkeypatch.setenv("KTPU_OBJECTIVE", "cheepest")
        r = TPUScheduler(make_templates()).solve(list(pods))
        assert_bit_identical(r, base)

    def test_lexical_meshed_pipeline_parity(self, monkeypatch):
        """The dp fill path with no policy selected is untouched by the
        objective machinery (routes through _run_fill_dp, not the variant
        dispatch)."""
        pods = mixed_kind_pods(256)
        meshed = objective_scheduler(
            monkeypatch, make_templates(), mesh_n=8
        ).solve(list(pods))
        single = objective_scheduler(
            monkeypatch, make_templates(), pipeline=False
        ).solve(list(pods))
        assert_bit_identical(meshed, single)
        assert OBJECTIVE_ROUNDS.get(policy="lexical", outcome="committed") == 0


class TestPolicyDifferential:
    """Every policy, every route: the meshed/windowed/pipelined solve is
    bit-identical to the single-device sequential solve under the SAME
    policy (K pinned to 1 so both sides run the canonical rank)."""

    @pytest.mark.parametrize("policy", NON_LEXICAL)
    def test_fill_route_parity(self, monkeypatch, policy):
        monkeypatch.setenv("KTPU_OBJECTIVE", policy)
        monkeypatch.setenv("KTPU_OBJECTIVE_K", "1")
        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        pods = mixed_kind_pods(192)
        meshed = objective_scheduler(
            monkeypatch, mixed_pool_templates(), mesh_n=8
        ).solve(list(pods))
        single = objective_scheduler(
            monkeypatch, mixed_pool_templates(), pipeline=False
        ).solve(list(pods))
        assert_bit_identical(meshed, single)
        # the device scorer agreed with the host oracle on every audit
        assert not guard.divergences("objective")
        assert not guard.QUARANTINE.active("objective")

    @pytest.mark.parametrize("policy", NON_LEXICAL)
    def test_fill_windowed_parity(self, monkeypatch, policy):
        monkeypatch.setenv("KTPU_OBJECTIVE", policy)
        monkeypatch.setenv("KTPU_OBJECTIVE_K", "1")
        pods = mixed_kind_pods(192)
        windowed = objective_scheduler(
            monkeypatch, mixed_pool_templates(), mesh_n=8, window=64
        ).solve(list(pods))
        single = objective_scheduler(
            monkeypatch, mixed_pool_templates(), pipeline=False
        ).solve(list(pods))
        assert_bit_identical(windowed, single)

    @pytest.mark.parametrize("policy", ("cost_min", "topo_spread"))
    def test_kscan_route_parity(self, monkeypatch, policy):
        monkeypatch.setenv("KTPU_OBJECTIVE", policy)
        monkeypatch.setenv("KTPU_OBJECTIVE_K", "1")
        pods = zonal_kind_pods(128)
        meshed = objective_scheduler(
            monkeypatch, mixed_pool_templates(), mesh_n=8
        ).solve(list(pods))
        single = objective_scheduler(
            monkeypatch, mixed_pool_templates(), pipeline=False
        ).solve(list(pods))
        assert_bit_identical(meshed, single)

    @pytest.mark.parametrize("policy", ("cost_min", "frag_aware"))
    def test_perpod_route_parity(self, monkeypatch, policy):
        monkeypatch.setenv("KTPU_OBJECTIVE", policy)
        monkeypatch.setenv("KTPU_OBJECTIVE_K", "1")
        pods = perpod_kind_pods(128)
        meshed = objective_scheduler(
            monkeypatch, mixed_pool_templates(), mesh_n=8
        ).solve(list(pods))
        single = objective_scheduler(
            monkeypatch, mixed_pool_templates(), pipeline=False
        ).solve(list(pods))
        assert_bit_identical(meshed, single)

    def test_cost_min_strictly_cheaper_on_mixed_pools(self, monkeypatch):
        pods = bench.mixed_pods(192)
        lex = objective_scheduler(
            monkeypatch, mixed_pool_templates(), pipeline=False
        ).solve(list(pods))
        monkeypatch.setenv("KTPU_OBJECTIVE", "cost_min")
        cheap = objective_scheduler(
            monkeypatch, mixed_pool_templates(), pipeline=False
        ).solve(list(pods))
        assert not lex.unschedulable and not cheap.unschedulable
        p_lex = obj_oracle.total_price_per_hour(lex)
        p_cheap = obj_oracle.total_price_per_hour(cheap)
        assert p_cheap < p_lex  # 0.6x family beats the 1.2x weight leader

    def test_nodepool_objective_threads_through(self, monkeypatch):
        """The NodePool placement_objective kwarg wins over the env."""
        monkeypatch.setenv("KTPU_OBJECTIVE", "lexical")
        pods = bench.mixed_pods(96)
        sched = objective_scheduler(
            monkeypatch, mixed_pool_templates(), pipeline=False,
            objective="cost_min",
        )
        r = sched.solve(list(pods))
        assert sched._active_policy == "cost_min"
        pools = {c.template.nodepool_name for c in r.claims}
        assert pools == {"e-pool"}


class TestVariantDispatch:
    def test_kvariant_commits_and_fetches_one_word_per_round(
        self, monkeypatch
    ):
        monkeypatch.setenv("KTPU_OBJECTIVE", "cost_min")
        pods = mixed_kind_pods(256)
        sched = objective_scheduler(
            monkeypatch, mixed_pool_templates(), mesh_n=8
        )
        before = OBJECTIVE_ROUNDS.get(policy="cost_min", outcome="committed")
        r = sched.solve(list(pods))
        assert not r.unschedulable
        shard = sched.last_timings["shard"]
        committed = (
            OBJECTIVE_ROUNDS.get(policy="cost_min", outcome="committed") - before
        )
        assert committed >= 1
        # ONE verdict-word fetch per merge round, 4 bytes each
        assert shard["verdict_fetches"] == shard["merge_rounds"]
        assert shard["verdict_bytes"] == 4 * shard["merge_rounds"]

    def test_kvariant_winner_is_round_argmin(self, monkeypatch):
        """Every verdict word's top byte IS the argmin-score feasible
        variant of its round (ties to the lowest index, all-infeasible
        pins 0) — the commit really takes the best-scoring row. (The
        per-round argmin is greedy, so the K-variant TOTAL is not
        guaranteed below the canonical solve's; the per-round property is
        the contract.)"""
        from karpenter_tpu.ops import solver as ops_solver_mod

        monkeypatch.setenv("KTPU_OBJECTIVE", "cost_min")
        recorded = []
        orig = ops_solver_mod.solve_fill_variants

        def spy(*a, **k):
            out = orig(*a, **k)
            recorded.append(out)
            return out

        monkeypatch.setattr(ops_solver_mod, "solve_fill_variants", spy)
        r = objective_scheduler(
            monkeypatch, mixed_pool_templates(), mesh_n=8
        ).solve(mixed_kind_pods(192))
        assert not r.unschedulable
        assert recorded
        for _spec, _ys, word, scores in recorded:
            w = int(np.asarray(word))
            winner = (w >> 24) & 0xFF
            feas_bits = w & ((1 << 24) - 1)
            s = np.asarray(scores)
            feas = np.array(
                [(feas_bits >> i) & 1 for i in range(s.shape[0])], dtype=bool
            )
            if feas.any():
                assert winner == int(np.argmin(np.where(feas, s, np.inf)))
            else:
                assert winner == 0

    def test_variant_wins_accounted(self, monkeypatch):
        monkeypatch.setenv("KTPU_OBJECTIVE", "cost_min")
        pods = mixed_kind_pods(192)
        before = sum(
            OBJECTIVE_VARIANT_WINS.get(policy="cost_min", variant=v)
            for v in ("canonical", "perturbed")
        )
        objective_scheduler(
            monkeypatch, mixed_pool_templates(), mesh_n=8
        ).solve(list(pods))
        after = sum(
            OBJECTIVE_VARIANT_WINS.get(policy="cost_min", variant=v)
            for v in ("canonical", "perturbed")
        )
        assert after > before  # every committed round records its winner


class TestGuardObjectiveTwin:
    def test_honest_scores_pass_audit(self, monkeypatch):
        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        monkeypatch.setenv("KTPU_OBJECTIVE", "cost_min")
        objective_scheduler(
            monkeypatch, mixed_pool_templates(), mesh_n=8
        ).solve(mixed_kind_pods(192))
        audits = [a for a in guard.AUDIT_LOG if a["path"] == "objective"]
        assert audits and all(a["verdict"] == "pass" for a in audits)
        assert not guard.QUARANTINE.active("objective")

    def test_lying_scorer_quarantines_back_to_lexical(self, monkeypatch):
        """The seeded lying-scorer fixture: KTPU_GUARD_LIE=objective
        skews the device-reported score by +1.0, the host oracle twin
        catches it on the first audited commit, the path quarantines, and
        the NEXT solve runs lexical — bit-identical to no policy at
        all."""
        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        monkeypatch.setenv("KTPU_GUARD_LIE", "objective")
        monkeypatch.setenv("KTPU_OBJECTIVE", "cost_min")
        pods = mixed_kind_pods(192)
        sched = objective_scheduler(monkeypatch, mixed_pool_templates(), mesh_n=8)
        sched.solve(list(pods))
        assert guard.divergences("objective")
        assert guard.QUARANTINE.active("objective")
        # quarantined: the same scheduler's next solve is lexical
        monkeypatch.delenv("KTPU_GUARD_LIE", raising=False)
        r = sched.solve(list(pods))
        assert sched._active_policy == "lexical"
        monkeypatch.delenv("KTPU_OBJECTIVE", raising=False)
        base = objective_scheduler(
            monkeypatch, mixed_pool_templates(), mesh_n=8
        ).solve(list(pods))
        assert_bit_identical(r, base)
        # TTL expiry (simulated via clear) restores the policy
        guard.QUARANTINE.clear("objective")
        monkeypatch.setenv("KTPU_OBJECTIVE", "cost_min")
        sched.solve(list(pods))
        assert sched._active_policy == "cost_min"


def _mk_candidate(name, price, pods_n=1, zone="test-zone-1", known=True,
                  gang=None):
    from karpenter_tpu.controllers.disruption.candidates import Candidate
    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.nodeclaim import NodeClaim
    from karpenter_tpu.models.objects import ObjectMeta
    from karpenter_tpu.models.pod import make_pod
    from karpenter_tpu.state.cluster import StateNode

    claim = NodeClaim(metadata=ObjectMeta(name=name))
    claim.metadata.labels[l.LABEL_TOPOLOGY_ZONE] = zone
    sn = StateNode(node_claim=claim)
    return Candidate(
        state_node=sn,
        nodepool=NodePool(),
        instance_type=None,
        price=price,
        price_known=known,
        reschedulable_pods=[make_pod(f"{name}-p{i}") for i in range(pods_n)],
        disruption_cost=1.0 + pods_n,
        gang_key=gang,
    )


class TestConsolidationOrdering:
    def test_lexical_is_legacy_savings_ratio(self):
        from karpenter_tpu.controllers.disruption.methods import (
            _order_units,
            _unit_savings_ratio,
        )

        units = [
            [_mk_candidate("a", 4.0, pods_n=1)],
            [_mk_candidate("b", 1.0, pods_n=3)],
            [_mk_candidate("c", 8.0, pods_n=2)],
        ]
        assert _order_units(list(units)) == sorted(
            units, key=_unit_savings_ratio
        )

    def test_cost_min_walks_priciest_first_excluding_unknown(
        self, monkeypatch
    ):
        from karpenter_tpu.controllers.disruption.methods import _order_units

        monkeypatch.setenv("KTPU_OBJECTIVE", "cost_min")
        cheap = [_mk_candidate("cheap", 1.0)]
        pricey = [_mk_candidate("pricey", 9.0)]
        unknown = [_mk_candidate("mystery", 0.0, known=False)]
        out = _order_units([cheap, unknown, pricey])
        # priciest known first; the unknown-price unit TRAILS the ranking
        # instead of sorting as the cheapest node in the fleet
        assert out == [pricey, cheap, unknown]

    def test_cost_min_respects_quarantine(self, monkeypatch):
        from karpenter_tpu.controllers.disruption.methods import (
            _order_units,
            _unit_savings_ratio,
        )

        monkeypatch.setenv("KTPU_OBJECTIVE", "cost_min")
        guard.QUARANTINE.trip("objective", reason="test")
        units = [
            [_mk_candidate("a", 4.0)],
            [_mk_candidate("b", 9.0)],
        ]
        assert _order_units(list(units)) == sorted(
            units, key=_unit_savings_ratio
        )

    def test_topo_spread_drains_crowded_zone_first(self, monkeypatch):
        from karpenter_tpu.controllers.disruption.methods import _order_units

        monkeypatch.setenv("KTPU_OBJECTIVE", "topo_spread")
        z1 = [
            [_mk_candidate("a", 1.0, zone="test-zone-1")],
            [_mk_candidate("b", 1.0, zone="test-zone-1")],
            [_mk_candidate("c", 1.0, zone="test-zone-1")],
        ]
        z2 = [[_mk_candidate("d", 1.0, zone="test-zone-2")]]
        out = _order_units(z2 + z1)
        assert out[:3] == z1  # 3-node zone drains before the 1-node zone

    def test_pricing_missing_counted_and_marked(self):
        """A node whose (zone, capacity-type) has no catalog price keeps
        the legacy 0.0 for the ratio math but is MARKED price_known=False
        and counted — never silently the cheapest."""
        from karpenter_tpu.controllers.disruption.candidates import (
            build_candidates,
        )
        from karpenter_tpu.models import labels as l
        from karpenter_tpu.models.nodeclaim import (
            COND_INITIALIZED,
            NodeClaim,
        )
        from karpenter_tpu.models.node import Node
        from karpenter_tpu.models.objects import ObjectMeta
        from karpenter_tpu.state.cluster import Cluster
        from karpenter_tpu.utils.clock import Clock

        it = new_instance_type("it-priced", zones=("test-zone-1",))
        cluster = Cluster()
        pool = NodePool()
        clock = Clock()
        for name, zone in (("n-ok", "test-zone-1"), ("n-gap", "test-zone-9")):
            claim = NodeClaim(metadata=ObjectMeta(name=name))
            claim.metadata.labels.update(
                {
                    l.LABEL_INSTANCE_TYPE: "it-priced",
                    l.LABEL_TOPOLOGY_ZONE: zone,
                    l.CAPACITY_TYPE_LABEL_KEY: "on-demand",
                    l.NODEPOOL_LABEL_KEY: pool.name,
                }
            )
            claim.status.provider_id = f"fake://{name}"
            claim.conditions.set_true(COND_INITIALIZED)
            cluster.update_nodeclaim(claim)
            node = Node(metadata=ObjectMeta(name=name))
            node.metadata.labels.update(claim.metadata.labels)
            node.spec.provider_id = f"fake://{name}"
            cluster.update_node(node)
        before = PRICING_MISSING.get()
        out = build_candidates(
            cluster, {pool.name: pool}, {"it-priced": it}, clock
        )
        assert PRICING_MISSING.get() == before + 1
        by_name = {c.name: c for c in out}
        assert by_name["n-ok"].price_known and by_name["n-ok"].price > 0
        assert not by_name["n-gap"].price_known
        assert by_name["n-gap"].price == 0.0


class TestOracle:
    def test_total_price_uses_cheapest_member(self, monkeypatch):
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        pods = bench.mixed_pods(64)
        r = TPUScheduler(mixed_pool_templates()).solve(list(pods))
        total = obj_oracle.total_price_per_hour(r)
        expect = 0.0
        for c in r.claims:
            prices = [
                obj_scoring.min_available_price(it) for it in c.instance_types
            ]
            best = min((p for p in prices if np.isfinite(p)), default=0.0)
            expect += best
        assert total == pytest.approx(expect)
