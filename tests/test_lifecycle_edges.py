"""Lifecycle edges: registration hooks + taint sync, initialization's
resource/DRA readiness checks, liveness condition stamping, and the
volume-detach await in finalization.

Reference: pkg/controllers/nodeclaim/lifecycle/registration.go:59-221
(hooks gate + syncNode), initialization.go:56-263 (requested resources
registered, DRA pools published), liveness.go:59-113, and
pkg/controllers/node/termination/controller.go:236-277
(awaitVolumeDetachment incl. the non-drainable filter and TGP override).
"""

from __future__ import annotations

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.node import VolumeAttachment
from karpenter_tpu.models.nodeclaim import (
    COND_INITIALIZED,
    COND_REGISTERED,
    COND_VOLUMES_DETACHED,
)
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.models.taints import UNREGISTERED_NO_EXECUTE_TAINT, Taint
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock


def _env(catalog=None):
    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=catalog or instance_types(8))
    mgr = Manager(store, cloud, clock)
    pool = NodePool()
    pool.metadata.name = "default"
    store.create(ObjectStore.NODEPOOLS, pool)
    return clock, store, cloud, mgr


class _Hook:
    """A NodeLifecycleHook analog the fake provider can carry."""

    name = "test-hook"

    def __init__(self):
        self.ready = False

    def registered(self, claim) -> bool:
        return self.ready


class TestRegistrationHooks:
    def test_hook_gates_registration_until_ready(self):
        clock, store, cloud, mgr = _env()
        hook = _Hook()
        cloud.registration_hooks = lambda: [hook]
        store.create(ObjectStore.PODS, make_pod("p", cpu=0.5))
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        claim = store.nodeclaims()[0]
        node = store.node_by_provider_id(claim.status.provider_id)
        # hook not ready: labels synced, claim NOT registered, taint kept
        assert not claim.conditions.is_true(COND_REGISTERED)
        assert any(
            t.match(UNREGISTERED_NO_EXECUTE_TAINT) for t in node.spec.taints
        ), "unregistered taint must stay while hooks gate"
        hook.ready = True
        mgr._dirty_claims.add(claim.name)
        mgr.run_until_idle()
        claim = store.nodeclaims()[0]
        node = store.node_by_provider_id(claim.status.provider_id)
        assert claim.conditions.is_true(COND_REGISTERED)
        assert not any(
            t.match(UNREGISTERED_NO_EXECUTE_TAINT) for t in node.spec.taints
        )

    def test_hooks_forward_through_decorators(self):
        from karpenter_tpu.cloudprovider.metrics import MetricsCloudProvider
        from karpenter_tpu.cloudprovider.overlay import OverlayCloudProvider

        clock = FakeClock()
        store = ObjectStore(clock)
        inner = KwokCloudProvider(store, catalog=instance_types(4))
        hook = _Hook()
        inner.registration_hooks = lambda: [hook]
        cloud = MetricsCloudProvider(OverlayCloudProvider(inner, store))
        assert cloud.registration_hooks() == [hook]

    def test_claim_taints_sync_onto_node(self):
        clock, store, cloud, mgr = _env()
        pod = make_pod("p", cpu=0.5)
        pod.spec.tolerations = []
        pool = store.nodepools()[0]
        pool.spec.template.spec.taints = [
            Taint(key="dedicated", value="batch", effect="NoSchedule")
        ]
        store.update(ObjectStore.NODEPOOLS, pool)
        from karpenter_tpu.models.pod import Toleration

        pod.spec.tolerations = [
            Toleration(key="dedicated", operator="Equal", value="batch", effect="NoSchedule")
        ]
        store.create(ObjectStore.PODS, pod)
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        claim = store.nodeclaims()[0]
        node = store.node_by_provider_id(claim.status.provider_id)
        assert claim.conditions.is_true(COND_REGISTERED)
        # registration.go:213-216: claim taints merge onto the node even
        # when the provider fabricated it without them
        assert any(
            t.key == "dedicated" and t.value == "batch" for t in node.spec.taints
        )


class TestInitializationChecks:
    def test_requested_extended_resource_blocks_until_registered(self):
        clock, store, cloud, mgr = _env()
        store.create(ObjectStore.PODS, make_pod("p", cpu=0.5))
        mgr.run_until_idle()
        claim = store.nodeclaims()[0]
        claim.spec.requests["example.com/gpu"] = 2.0
        store.update(ObjectStore.NODECLAIMS, claim)
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        claim = store.nodeclaims()[0]
        assert claim.conditions.is_true(COND_REGISTERED)
        # the kubelet zeroes extended resources until the device plugin
        # registers (initialization.go:130-146)
        assert not claim.conditions.is_true(COND_INITIALIZED)
        node = store.node_by_provider_id(claim.status.provider_id)
        node.status.allocatable["example.com/gpu"] = 2.0
        store.update(ObjectStore.NODES, node)
        mgr.run_until_idle()
        assert store.nodeclaims()[0].conditions.is_true(COND_INITIALIZED)

    def test_dra_driver_pools_block_until_published(self):
        from karpenter_tpu.scheduling.dra.types import ResourceSlice

        clock, store, cloud, mgr = _env()
        store.create(ObjectStore.PODS, make_pod("p", cpu=0.5))
        mgr.run_until_idle()
        claim = store.nodeclaims()[0]
        claim.metadata.annotations[l.DRA_DRIVERS_ANNOTATION_KEY] = "gpu.example.com"
        store.update(ObjectStore.NODECLAIMS, claim)
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        claim = store.nodeclaims()[0]
        assert claim.conditions.is_true(COND_REGISTERED)
        assert not claim.conditions.is_true(COND_INITIALIZED)
        node = store.node_by_provider_id(claim.status.provider_id)
        store.create(
            ObjectStore.RESOURCE_SLICES,
            ResourceSlice(driver="gpu.example.com", pool="p0", node_name=node.name),
        )
        mgr.run_until_idle()
        assert store.nodeclaims()[0].conditions.is_true(COND_INITIALIZED)


class TestLivenessReason:
    def test_liveness_reap_stamps_condition(self):
        clock, store, cloud, mgr = _env()
        # a never-ready hook keeps registration gated (the kwok provider
        # fabricates the node immediately, so without the gate the claim
        # registers on the first pass and liveness never applies)
        cloud.registration_hooks = lambda: [_Hook()]
        store.create(ObjectStore.PODS, make_pod("p", cpu=0.5))
        mgr.run_until_idle()
        reaped = []
        store.watch(
            ObjectStore.NODECLAIMS,
            lambda e, c: reaped.append(c) if e.value == "Deleted" else None,
        )
        clock.step(6 * 60.0)
        for c in store.nodeclaims():
            mgr._dirty_claims.add(c.name)
        mgr.run_until_idle()
        assert reaped, "liveness did not reap the unregistered claim"
        cond = reaped[0].conditions.get(COND_REGISTERED)
        assert cond is not None and cond.reason == "LivenessTimeout"


class TestVolumeDetachAwait:
    def _bound_node(self):
        clock, store, cloud, mgr = _env()
        store.create(ObjectStore.PODS, make_pod("p", cpu=0.5))
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        claim = store.nodeclaims()[0]
        node = store.node_by_provider_id(claim.status.provider_id)
        return clock, store, cloud, mgr, claim, node

    def test_termination_waits_for_attachments(self):
        clock, store, cloud, mgr, claim, node = self._bound_node()
        va = VolumeAttachment(node_name=node.name, attacher="ebs.csi", pvc_name="vol-1")
        va.metadata.name = "va-1"
        store.create(ObjectStore.VOLUME_ATTACHMENTS, va)
        store.delete(ObjectStore.NODECLAIMS, claim.name)
        mgr.run_until_idle()
        claim = store.get(ObjectStore.NODECLAIMS, claim.name)
        assert claim is not None, "instance terminated before volumes detached"
        cond = claim.conditions.get(COND_VOLUMES_DETACHED)
        assert cond is not None and cond.reason == "AwaitingVolumeDetachment"
        # the attach-detach controller finishes its cleanup; the manager's
        # VOLUME_ATTACHMENTS informer re-drives the deleting claim
        store.delete(ObjectStore.VOLUME_ATTACHMENTS, "va-1")
        mgr.run_until_idle()
        assert store.get(ObjectStore.NODECLAIMS, claim.name) is None

    def test_tgp_overrides_the_wait(self):
        clock, store, cloud, mgr, claim, node = self._bound_node()
        claim.spec.termination_grace_period_seconds = 30.0
        store.update(ObjectStore.NODECLAIMS, claim)
        va = VolumeAttachment(node_name=node.name, attacher="ebs.csi", pvc_name="vol-1")
        va.metadata.name = "va-1"
        store.create(ObjectStore.VOLUME_ATTACHMENTS, va)
        store.delete(ObjectStore.NODECLAIMS, claim.name)
        mgr.run_until_idle()
        assert store.get(ObjectStore.NODECLAIMS, claim.name) is not None
        clock.step(31.0)
        mgr._dirty_claims.add(claim.name)
        mgr.run_until_idle()
        # grace elapsed: termination proceeds despite the attachment
        # (controller.go:270-276, VolumesDetached False/GracePeriodElapsed)
        assert store.get(ObjectStore.NODECLAIMS, claim.name) is None
