"""Seeded chaos scenarios against the fault-injection subsystem (ISSUE 4).

The contract under test, end to end on kwok + fake clock:

- with a seeded ``FaultPlan`` injecting ICE storms, transient launch
  errors, apiserver flakes, stream cuts at every chunk index, and device
  dispatch failures, provisioning/consolidation still CONVERGE (every
  pod bound, no duplicate NodeClaims, capacity reclaimed) — failures
  bend the path, never the destination;
- solver results are bit-identical to the unfaulted run once retries
  succeed (the degradation ladder and stream recovery preserve the
  differential-parity contract);
- blacked-out offerings stop being picked for the TTL and return after;
- fault points cost ~0 when disabled (the tracer's bar).
"""

import random

import pytest

import bench
from karpenter_tpu.cloudprovider import errors
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
from karpenter_tpu.cloudprovider.unavailable import UnavailableOfferings
from karpenter_tpu.controllers.nodeclaim_lifecycle import (
    LAUNCH_ATTEMPTS_ANNOTATION,
    MAX_LAUNCH_ATTEMPTS,
    NodeClaimLifecycleController,
)
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.envelope.scenarios import _harness, _provision, _settle
from karpenter_tpu.faultinject import FAULT, FaultInjector, FaultPlan, active_plan
from karpenter_tpu.models.nodeclaim import COND_LAUNCHED, NodeClaim
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.objects import ObjectMeta
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.clock import FakeClock

from test_solver import assert_same_packing


def make_templates(n_types=16):
    pool = NodePool()
    pool.metadata.name = "default"
    return build_templates([(pool, instance_types(n_types))])


# -- plan mechanics -----------------------------------------------------------


class TestFaultPlan:
    def test_rules_fire_with_times_and_skip(self):
        plan = FaultPlan.from_dict(
            {"rules": [{"point": "x", "error": "transient", "times": 2, "skip": 1}]}
        )
        outcomes = []
        with active_plan(plan):
            for _ in range(5):
                try:
                    FAULT.point("x")
                    outcomes.append("ok")
                except errors.TransientError:
                    outcomes.append("err")
        # first hit skipped, next two fire, budget spent
        assert outcomes == ["ok", "err", "err", "ok", "ok"]
        assert not FAULT.enabled  # context manager deactivated

    def test_glob_points_and_ctx_match(self):
        plan = FaultPlan.from_dict(
            {
                "rules": [
                    {"point": "cloud.*", "error": "throttle", "match": {"zone": "z2"}}
                ]
            }
        )
        with active_plan(plan):
            FAULT.point("cloud.create", zone="z1")  # match filter misses
            FAULT.point("api.patch", zone="z2")  # glob misses
            with pytest.raises(errors.ThrottleError):
                FAULT.point("cloud.create", zone="z2")

    def test_seeded_probability_is_deterministic(self):
        plan = FaultPlan.from_dict(
            {"seed": 13, "rules": [{"point": "x", "error": "transient", "p": 0.5}]}
        )

        def pattern():
            out = []
            with active_plan(plan):
                for _ in range(30):
                    try:
                        FAULT.point("x")
                        out.append(0)
                    except errors.TransientError:
                        out.append(1)
            return out

        first, second = pattern(), pattern()
        assert first == second  # reactivation reseeds identically
        assert 0 < sum(first) < 30  # actually probabilistic

    def test_counters_and_metric(self):
        before = metrics.FAULT_INJECTIONS.get(point="y", mode="error")
        with active_plan({"rules": [{"point": "y", "error": "terminal", "times": 3}]}):
            for _ in range(3):
                with pytest.raises(errors.TerminalError):
                    FAULT.point("y")
            FAULT.point("y")  # budget spent: passes through
            assert FAULT.fires("y") == 3
        assert metrics.FAULT_INJECTIONS.get(point="y", mode="error") == before + 3

    def test_latency_mode_lets_the_call_proceed(self):
        with active_plan(
            {"rules": [{"point": "slow", "mode": "latency", "delay_s": 0.0}]}
        ):
            FAULT.point("slow")  # no raise
            assert FAULT.fires("slow") == 1

    def test_env_activation(self, monkeypatch, tmp_path):
        spec = '{"seed": 3, "rules": [{"point": "z", "error": "transient"}]}'
        monkeypatch.setenv("KTPU_FAULT_PLAN", spec)
        inj = FaultInjector()
        assert inj.maybe_activate_from_env()
        with pytest.raises(errors.TransientError):
            inj.point("z")
        # file form
        path = tmp_path / "plan.json"
        path.write_text(spec)
        monkeypatch.setenv("KTPU_FAULT_PLAN", str(path))
        inj2 = FaultInjector()
        assert inj2.maybe_activate_from_env()
        # unset -> inert
        monkeypatch.delenv("KTPU_FAULT_PLAN")
        assert not FaultInjector().maybe_activate_from_env()


class TestOverhead:
    def test_disabled_point_is_near_free(self):
        inj = FaultInjector()
        import time

        t0 = time.perf_counter()
        for _ in range(100_000):
            inj.point("hot.path")
        elapsed = time.perf_counter() - t0
        # the tracer's disabled-span bar (test_tracing.py): generous CI
        # bound, typically < 30ms
        assert elapsed < 2.0, f"100k disabled fault points took {elapsed:.3f}s"


# -- blackout cache -----------------------------------------------------------


class TestBlackoutCache:
    def test_mark_expire_and_generation(self):
        clock = FakeClock()
        cache = UnavailableOfferings(clock, ttl_seconds=60.0)
        g0 = cache.generation
        cache.mark("s-4x-amd64", "test-zone-1", "spot")
        assert cache.is_unavailable("s-4x-amd64", "test-zone-1", "spot")
        assert not cache.is_unavailable("s-4x-amd64", "test-zone-2", "spot")
        assert cache.generation == g0 + 1
        clock.step(61.0)
        assert cache.prune() == 1
        assert cache.generation == g0 + 2
        assert not cache.is_unavailable("s-4x-amd64", "test-zone-1", "spot")

    def test_mark_from_error_reads_offerings(self):
        cache = UnavailableOfferings(FakeClock())
        err = errors.InsufficientCapacityError(
            "no capacity", offerings=[("it-a", "z1", "spot"), ("it-b", "z2", "on-demand")]
        )
        assert cache.mark_from_error(err) == 2
        assert cache.is_unavailable("it-a", "z1", "spot")
        assert cache.is_unavailable("it-b", "z2", "on-demand")
        # an ICE without offering info marks nothing and doesn't crash
        assert cache.mark_from_error(errors.InsufficientCapacityError("bare")) == 0

    def test_filter_catalog_removes_offerings_and_empty_types(self):
        clock = FakeClock()
        cache = UnavailableOfferings(clock, ttl_seconds=60.0)
        its = instance_types(4)
        # empty cache: the fast path returns the SAME list object
        assert cache.filter_catalog(its) is its
        victim = its[0]
        first = victim.offerings[0]
        cache.mark(victim.name, first.zone, first.capacity_type)
        out = cache.filter_catalog(its)
        filtered = next(it for it in out if it.name == victim.name)
        assert len(filtered.offerings) == len(victim.offerings) - 1
        assert not any(
            o.zone == first.zone and o.capacity_type == first.capacity_type
            for o in filtered.offerings
        )
        # blackout EVERY offering of the victim -> the type drops out
        for o in victim.offerings:
            cache.mark(victim.name, o.zone, o.capacity_type)
        out = cache.filter_catalog(its)
        assert victim.name not in {it.name for it in out}
        # expiry restores the full catalog
        clock.step(61.0)
        assert cache.filter_catalog(its) is its

    def test_gauge_tracks_entries(self):
        cache = UnavailableOfferings(FakeClock())
        cache.mark("a", "z1", "spot")
        cache.mark("b", "z1", "spot")
        cache.mark("c", "z1", "on-demand")
        assert metrics.OFFERING_BLACKOUT.get(capacity_type="spot") == 2.0
        assert metrics.OFFERING_BLACKOUT.get(capacity_type="on-demand") == 1.0


# -- the degradation ladder (device dispatch -> host oracle) ------------------


class TestDeviceDispatchFallback:
    def test_dispatch_failure_degrades_to_host_with_identical_result(self):
        sched = TPUScheduler(make_templates(16), max_claims=64)
        pods = [make_pod(f"df-{i}", cpu=0.5, memory="512Mi") for i in range(48)]
        baseline = sched.solve(pods)
        assert not baseline.unschedulable
        before = metrics.SOLVER_FALLBACK.get(reason="device_dispatch")
        with active_plan(
            {"rules": [{"point": "solver.dispatch", "error": "runtime", "times": 1}]}
        ):
            degraded = sched.solve(pods)
        # the ladder: the solve did NOT fail, and the host oracle's answer
        # is bit-identical to the device's (the differential contract)
        assert_same_packing(baseline, degraded)
        assert metrics.SOLVER_FALLBACK.get(reason="device_dispatch") == before + 1
        # recovery: the next solve runs on the device again, same answer
        assert_same_packing(baseline, sched.solve(pods))


# -- lifecycle transient retry ------------------------------------------------


class TestLifecycleTransientRetry:
    def _env(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        cloud = FakeCloudProvider(catalog=instance_types(8))
        ctrl = NodeClaimLifecycleController(store, cloud, clock)
        claim = NodeClaim(metadata=ObjectMeta(name="tc-1"))
        store.create(ObjectStore.NODECLAIMS, claim)
        return store, ctrl, claim

    def test_bounded_retry_then_success(self):
        store, ctrl, claim = self._env()
        with active_plan(
            {"rules": [{"point": "cloud.create", "error": "throttle", "times": 2}]}
        ):
            ctrl.reconcile(claim)
            assert not claim.conditions.is_true(COND_LAUNCHED)
            assert claim.metadata.annotations[LAUNCH_ATTEMPTS_ANNOTATION] == "1"
            ctrl.reconcile(claim)
            assert claim.metadata.annotations[LAUNCH_ATTEMPTS_ANNOTATION] == "2"
            ctrl.reconcile(claim)  # budget left, fault exhausted -> launch
        assert claim.conditions.is_true(COND_LAUNCHED)
        assert store.get(ObjectStore.NODECLAIMS, "tc-1") is not None

    def test_budget_exhausted_gives_the_pods_back(self):
        store, ctrl, claim = self._env()
        with active_plan(
            {"rules": [{"point": "cloud.create", "error": "timeout"}]}
        ):
            for _ in range(MAX_LAUNCH_ATTEMPTS):
                ctrl.reconcile(claim)
        # claim deleted like an ICE: pods re-schedule onto a fresh claim
        assert store.get(ObjectStore.NODECLAIMS, "tc-1") is None

    def test_ice_marks_the_blackout_cache(self):
        store, ctrl, claim = self._env()
        assert len(ctrl.unavailable) == 0
        with active_plan(
            {"rules": [{"point": "cloud.create", "error": "ice", "times": 1}]}
        ):
            ctrl.reconcile(claim)
        # the fake provider attached the resolved offering to the ICE
        assert len(ctrl.unavailable) == 1
        assert store.get(ObjectStore.NODECLAIMS, "tc-1") is None


# -- seeded chaos e2e on kwok + fake clock ------------------------------------


def _settle_hard(mgr, store, cloud, rounds=16):
    """_settle with a larger round budget: faulted runs legitimately need
    extra provision->launch->bind cycles while retries drain."""
    _settle(mgr, store, cloud, rounds=rounds)


def _assert_converged(store, n_pods):
    pods = store.pods()
    assert len(pods) == n_pods
    stranded = [p.name for p in pods if not p.spec.node_name]
    assert not stranded, f"stranded pods: {stranded}"
    # no duplicate NodeClaims: one claim per node, distinct provider ids,
    # and every pod's node actually exists
    claims = store.nodeclaims()
    nodes = store.nodes()
    pids = [c.status.provider_id for c in claims if c.status.provider_id]
    assert len(pids) == len(set(pids)), "duplicate provider ids"
    assert len(claims) == len(nodes), (len(claims), len(nodes))
    node_names = {n.name for n in nodes}
    assert all(p.spec.node_name in node_names for p in pods)


class TestICEStormScaleOut:
    def test_scale_out_converges_through_an_ice_storm(self):
        clock, store, cloud, mgr = _harness(catalog_size=64)
        pods = [
            make_pod(f"ice-{i}", cpu=(0.25, 0.5, 1.0)[i % 3], memory="512Mi")
            for i in range(40)
        ]
        plan = {
            "seed": 11,
            "rules": [
                {"point": "cloud.create", "error": "ice", "p": 0.6, "times": 5}
            ],
        }
        with active_plan(plan):
            _provision(mgr, store, cloud, pods)
            _settle_hard(mgr, store, cloud)
            injected = FAULT.fires("cloud.create")
        assert injected >= 1, "the storm never fired"
        _assert_converged(store, 40)
        # every ICE carried its resolved offering into the blackout cache
        assert len(mgr.unavailable) >= 1
        assert metrics.FAULT_INJECTIONS.get(point="cloud.create", mode="error") >= injected

    def test_blackout_expiry_restores_offerings(self):
        clock, store, cloud, mgr = _harness(catalog_size=16)
        mgr.unavailable.mark("anything", "test-zone-1", "spot")
        gen = mgr.unavailable.generation
        clock.step(mgr.unavailable.ttl_seconds + 1.0)
        # the provisioner's next scheduler build prunes and invalidates
        store.create(ObjectStore.PODS, make_pod("bx-1", cpu=0.5))
        _settle_hard(mgr, store, cloud, rounds=6)
        assert len(mgr.unavailable) == 0
        assert mgr.unavailable.generation > gen
        _assert_converged(store, 1)


class TestBrownoutConsolidation:
    def test_consolidation_converges_through_provider_and_api_flakes(self):
        from karpenter_tpu.envelope.scenarios import _delete_pods, _disruption_cycles

        clock, store, cloud, mgr = _harness(catalog_size=64)
        n = 16
        survivors = {f"bc-{i}" for i in range(n // 2)}
        _provision(
            mgr, store, cloud,
            [make_pod(f"bc-{i}", cpu=1.5, memory="1Gi") for i in range(n)],
        )
        cpu_before = sum(nd.status.capacity["cpu"] for nd in store.nodes())
        _delete_pods(store, mgr, lambda p: p.name not in survivors)
        clock.step(60.0)
        retries_before = metrics.TRANSIENT_RETRIES.get(controller="disruption.queue")
        plan = {
            "seed": 23,
            "rules": [
                {"point": "cloud.create", "error": "throttle", "p": 0.5, "times": 3},
                {
                    "point": "api.delete",
                    "match": {"kind": ObjectStore.NODECLAIMS},
                    "error": "transient",
                    "times": 2,
                },
            ],
        }
        with active_plan(plan):
            executed = _disruption_cycles(clock, store, cloud, mgr, polls=10)
            _settle_hard(mgr, store, cloud)
        assert executed is not None, "no consolidation command produced"
        _settle_hard(mgr, store, cloud)
        cpu_after = sum(nd.status.capacity["cpu"] for nd in store.nodes())
        assert cpu_after < cpu_before, "no capacity reclaimed under brownout"
        _assert_converged(store, len(survivors))
        # the injected api.delete flakes were absorbed as bounded retries
        assert (
            metrics.TRANSIENT_RETRIES.get(controller="disruption.queue")
            >= retries_before
        )


# -- SolveStream cuts at every chunk index ------------------------------------


class _StreamEnv:
    def __init__(self, remote, pods, baseline, n_chunks):
        self.remote = remote
        self.pods = pods  # ONE pod list: uids must match across re-solves
        self.baseline = baseline
        self.n_chunks = n_chunks


@pytest.fixture(scope="class")
def stream_env():
    """One server + client + pod set + unfaulted baseline for the whole
    cut matrix (the jit cache and the Configure round-trip amortize, and
    every faulted result compares against the SAME baseline)."""
    import os

    saved = {k: os.environ.get(k) for k in ("KTPU_PIPELINE_CHUNKS", "KTPU_PIPELINE_MIN_PODS")}
    os.environ["KTPU_PIPELINE_CHUNKS"] = "2"
    os.environ["KTPU_PIPELINE_MIN_PODS"] = "0"
    from karpenter_tpu.rpc import RemoteScheduler, serve
    from karpenter_tpu.rpc.retry import Backoff

    server, addr = serve("127.0.0.1:0")
    remote = RemoteScheduler(addr, bench.make_templates(24))
    remote._backoff = Backoff(base_s=0.01, cap_s=0.05, rng=random.Random(0))
    pods = bench.mixed_pods(96)
    baseline = remote.solve(pods)
    assert not baseline.unschedulable
    n_chunks = remote.last_stream["chunks"]
    assert n_chunks >= 2, remote.last_stream
    try:
        yield _StreamEnv(remote, pods, baseline, n_chunks)
    finally:
        remote.close()
        server.stop(0)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class TestStreamCuts:
    def test_cut_at_every_chunk_index_recovers_bit_identical(self, stream_env):
        remote, baseline = stream_env.remote, stream_env.baseline
        n_chunks = stream_env.n_chunks
        for index in range(n_chunks + 1):  # +1: the cut before the final frame
            plan = {
                "seed": index,
                "rules": [
                    {
                        "point": "rpc.stream.chunk",
                        "match": {"index": index},
                        "error": "unavailable",
                        "times": 1,
                    }
                ],
            }
            with active_plan(plan):
                result = remote.solve(stream_env.pods)
                assert FAULT.fires("rpc.stream.chunk") == 1, f"cut at {index} missed"
            # the retry re-ran the stream from scratch; nothing from the
            # broken attempt leaked into the stitcher
            assert_same_packing(baseline, result)
            assert remote.last_stream["chunks"] == n_chunks

    def test_persistent_cut_downgrades_to_unary(self, stream_env):
        remote = stream_env.remote
        before = metrics.STREAM_RECOVERIES.get(outcome="downgraded_unary")
        with active_plan(
            {"rules": [{"point": "rpc.stream.chunk", "error": "unavailable"}]}
        ):
            result = remote.solve(stream_env.pods)
        assert_same_packing(stream_env.baseline, result)
        assert metrics.STREAM_RECOVERIES.get(outcome="downgraded_unary") == before + 1
        # the downgrade was per-call: streaming stays preferred
        assert remote._stream_ok
        remote.solve(stream_env.pods)
        assert remote.last_stream["chunks"] >= 2

    def test_send_failure_retries_with_backoff(self, stream_env):
        remote = stream_env.remote
        with active_plan(
            {"rules": [{"point": "rpc.solve.send", "error": "unavailable", "times": 1}]}
        ):
            result = remote.solve(stream_env.pods)
        assert_same_packing(stream_env.baseline, result)

    def test_breaker_opens_under_sustained_failure(self, stream_env):
        remote = stream_env.remote
        from karpenter_tpu.rpc.client import TRANSPORT_RETRIES
        from karpenter_tpu.rpc.retry import CircuitBreaker, CircuitOpenError

        # a private breaker so the class-scoped client's shared one isn't
        # poisoned for the other tests
        saved = remote._breaker
        t = [0.0]
        remote._breaker = CircuitBreaker(
            failure_threshold=TRANSPORT_RETRIES + 1, cooldown_s=60.0, now=lambda: t[0]
        )
        try:
            with active_plan(
                {"rules": [{"point": "rpc.solve.send", "error": "unavailable"}]}
            ):
                import grpc

                with pytest.raises(grpc.RpcError):
                    remote.solve(stream_env.pods)
                # every attempt failed -> breaker open -> fail fast
                assert remote._breaker.state == CircuitBreaker.OPEN
                with pytest.raises(CircuitOpenError):
                    remote.solve(stream_env.pods)
            # cooldown elapses, faults gone: the half-open probe heals it
            t[0] = 61.0
            result = remote.solve(stream_env.pods)
            assert not result.unschedulable
            assert remote._breaker.state == CircuitBreaker.CLOSED
        finally:
            remote._breaker = saved
