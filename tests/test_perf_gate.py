"""The MinPodsPerSec-style performance gate, run as a normal test.

Counterpart of the reference's scheduling benchmark assertion
(scheduling_benchmark_test.go:58,211-214: MinPodsPerSec = 100). The CI
environment is an 8-virtual-device CPU mesh (conftest.py), far slower than
the TPU the headline bench runs on, so the gate here asserts the
reference's own floor — 100 pods/sec — on a reference-mix workload sized
for CPU. bench.py measures the real headline on hardware.
"""

import time

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.models.nodepool import NodePool

MIN_PODS_PER_SEC = 100.0  # the reference gate (:58)


def test_reference_mix_meets_min_pods_per_sec():
    import bench

    pods = bench.mixed_pods(512)
    pool = NodePool()
    pool.metadata.name = "default"
    templates = build_templates([(pool, instance_types(400))])
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=128)
    result = sched.solve(pods)  # cold: compile dominates, not gated
    assert not result.unschedulable
    t0 = time.perf_counter()
    result = sched.solve(pods)
    wall = time.perf_counter() - t0
    assert not result.unschedulable
    rate = len(pods) / wall
    assert rate >= MIN_PODS_PER_SEC, f"{rate:.1f} pods/sec < {MIN_PODS_PER_SEC}"
