"""The MinPodsPerSec-style performance gate, run as a normal test.

Counterpart of the reference's scheduling benchmark assertion
(scheduling_benchmark_test.go:58,211-214: MinPodsPerSec = 100). The CI
environment is an 8-virtual-device CPU mesh (conftest.py), far slower than
the TPU the headline bench runs on, so the gate here asserts the
reference's own floor — 100 pods/sec — on a reference-mix workload sized
for CPU. bench.py measures the real headline on hardware.
"""

import time

import pytest

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.models.nodepool import NodePool

MIN_PODS_PER_SEC = 100.0  # the reference gate (:58)
# The accelerated-regime floor (VERDICT r3 #4), ratcheted to round-5
# reality (VERDICT r5 directive #3: measured 12,176 pods/sec — the old
# 1,500 floor would have passed a regression all the way back to round 3).
TPU_MIN_PODS_PER_SEC = 8000.0


def test_reference_mix_meets_min_pods_per_sec():
    import bench

    pods = bench.mixed_pods(512)
    pool = NodePool()
    pool.metadata.name = "default"
    templates = build_templates([(pool, instance_types(400))])
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=128)
    result = sched.solve(pods)  # cold: compile dominates, not gated
    assert not result.unschedulable
    t0 = time.perf_counter()
    result = sched.solve(pods)
    wall = time.perf_counter() - t0
    assert not result.unschedulable
    rate = len(pods) / wall
    assert rate >= MIN_PODS_PER_SEC, f"{rate:.1f} pods/sec < {MIN_PODS_PER_SEC}"


def test_tpu_regime_gate():
    """2048 selector pods x 400 types must clear 8,000 pods/sec when a real
    accelerator is attached (bench.py stage 1 enforces the same number).
    Skipped on the CPU mesh — the TPU regime can't be asserted there."""
    import jax

    if jax.devices()[0].platform == "cpu":
        pytest.skip("TPU-regime gate needs an accelerator")
    import bench

    pods = bench.selector_pods(2048)
    pool = NodePool()
    pool.metadata.name = "default"
    templates = build_templates([(pool, instance_types(400))])
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=256)
    assert not sched.solve(pods).unschedulable  # cold
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        result = sched.solve(pods)
        wall = time.perf_counter() - t0
        best = wall if best is None or wall < best else best
    assert not result.unschedulable
    rate = len(pods) / best
    assert rate >= TPU_MIN_PODS_PER_SEC, (
        f"TPU regime regression: {rate:.1f} pods/sec < {TPU_MIN_PODS_PER_SEC}"
    )


# VERDICT r4 #7: the north star and the 16k reference mix moved by integer
# factors between rounds with no gate catching it. Both are pinned here,
# ratcheted to round-5 reality (VERDICT r5 directive #3: north star
# measured 0.632 s, 16k mix 24,065 pods/sec best), plus a cold-compile
# ceiling so a persistent-cache key bust fails loudly instead of looking
# like a CI hang, and a whatif-batch floor so the 22x -> 13.8x r4->r5
# slide (VERDICT r5 weak #4) can never recur silently.
# ISSUE-13 ratchet (0.60 -> 0.45): the speculative merge loop now reads
# ONE packed verdict word per round instead of per-group scalar probes,
# so dispatch overlaps the pipelined decode again on speculative solves
# — the gate is TPU-only (this box is CPU-only; measured CPU numbers
# stay in the bench JSON comment as before), so the number binds on the
# next accelerator run.
NORTHSTAR_MAX_WALL_S = 0.45  # ISSUE-13 ratchet toward the 500ms target
# the active-window scan + incremental encode must actually move the
# splits, not just the wall: device_s below the r5 0.33s scan split and
# encode_s below 0.09s (both recorded in the bench JSON per stage)
NORTHSTAR_MAX_DEVICE_S = 0.30
NORTHSTAR_MAX_ENCODE_S = 0.09
# the pipelined solve must hide >= 30% of its wire+decode time behind
# in-flight device compute on the north-star workload (ISSUE 3; the same
# overlap_frac lands in the bench JSON under the stage's "pipeline" key)
NORTHSTAR_MIN_OVERLAP_FRAC = 0.3
MIXED_16K_MIN_PODS_PER_SEC = 15000.0  # ratchet from the 7,000 r5 gate
WARM_CACHE_COLD_COMPILE_MAX_S = 60.0  # observed ~6s with a warm cache


def _tpu_or_skip():
    import jax

    if jax.devices()[0].platform == "cpu":
        pytest.skip("TPU-regime gate needs an accelerator")


def test_northstar_wall_gate():
    """100k selector pods x 1000 types, warm, best-of-2 (the claims-axis
    warm-sizing recompile is absorbed by the first warm run)."""
    _tpu_or_skip()
    import bench

    pods = bench.selector_pods(100_000)
    templates = bench.make_templates(1000)
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=4096)
    assert not sched.solve(pods).unschedulable  # cold
    best = None
    best_timings = None
    for _ in range(2):
        t0 = time.perf_counter()
        result = sched.solve(pods)
        wall = time.perf_counter() - t0
        if best is None or wall < best:
            best, best_timings = wall, dict(sched.last_timings)
    assert not result.unschedulable
    assert best <= NORTHSTAR_MAX_WALL_S, (
        f"north-star regression: {best:.3f}s > {NORTHSTAR_MAX_WALL_S}s"
    )
    # ISSUE-5 sub-gates: the active-window scan and incremental encode
    # must move the splits themselves, not just the wall
    assert best_timings["device_s"] <= NORTHSTAR_MAX_DEVICE_S, (
        f"device scan regression: {best_timings['device_s']:.3f}s > "
        f"{NORTHSTAR_MAX_DEVICE_S}s (scan={best_timings.get('scan')})"
    )
    assert best_timings["encode_s"] <= NORTHSTAR_MAX_ENCODE_S, (
        f"encode regression: {best_timings['encode_s']:.3f}s > "
        f"{NORTHSTAR_MAX_ENCODE_S}s"
    )


def test_northstar_overlap_gate():
    """The software pipeline must actually overlap on the north-star solve:
    measured overlap_frac (the share of wire+decode time spent while later
    chunk groups were still in flight on the device) >= 0.3, recorded in
    last_timings["pipeline"] and in the bench JSON."""
    _tpu_or_skip()
    import bench

    pods = bench.selector_pods(100_000)
    templates = bench.make_templates(1000)
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=4096)
    assert not sched.solve(pods).unschedulable  # cold
    assert not sched.solve(pods).unschedulable  # warm (claims-axis resize)
    pl = sched.last_timings.get("pipeline")
    assert pl is not None, (
        "north-star solve did not pipeline (KTPU_PIPELINE_CHUNKS disabled "
        "or below the min-pods threshold?)"
    )
    assert pl["overlap_frac"] >= NORTHSTAR_MIN_OVERLAP_FRAC, (
        f"pipeline overlap regression: {pl['overlap_frac']} < "
        f"{NORTHSTAR_MIN_OVERLAP_FRAC} ({pl['n_chunks']} chunks, "
        f"wire {pl['wire_s']}s, host decode {pl['host_decode_s']}s)"
    )


def test_mixed_16k_throughput_gate():
    """The reference benchmark mix (3/5 topology-bearing pods) at 16384 x
    400 — the kind-scan path's headline; best-of-3 to ride out tunnel
    variance."""
    _tpu_or_skip()
    import bench

    pods = bench.mixed_pods(16384)
    templates = bench.make_templates(400)
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=4096)
    assert not sched.solve(pods).unschedulable  # cold
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        result = sched.solve(pods)
        wall = time.perf_counter() - t0
        best = wall if best is None or wall < best else best
    assert not result.unschedulable
    rate = len(pods) / best
    assert rate >= MIXED_16K_MIN_PODS_PER_SEC, (
        f"16k ref-mix regression: {rate:.1f} pods/sec < {MIXED_16K_MIN_PODS_PER_SEC}"
    )


def test_whatif_batch_speedup_gate():
    """The batched consolidation what-if must stay >= 10x over extrapolated
    sequential re-solves (VERDICT r5 weak #4: the 22x -> 13.8x slide went
    unnoticed because nothing gated it; measured 13.8x on TPU r5). The
    bench JSON records the same floor via bench.WHATIF_MIN_SPEEDUP_X."""
    _tpu_or_skip()
    import bench

    out = bench.run_whatif_stage(100)
    assert out["speedup_x"] >= bench.WHATIF_MIN_SPEEDUP_X, (
        f"whatif-batch regression: {out['speedup_x']}x < "
        f"{bench.WHATIF_MIN_SPEEDUP_X}x (batch wall {out['batch_s']}s "
        f"for {out['candidates']} candidates)"
    )


def test_warm_cache_cold_compile_ceiling():
    """A fresh process with the persistent XLA cache populated must reach
    its first solve inside the ceiling — a silent cache-key bust otherwise
    reads as a CI hang (VERDICT r4 weak #8)."""
    _tpu_or_skip()
    import bench

    out = bench.run_restart_stage(2048, 400, 256, on_tpu=True)
    assert isinstance(out, dict), f"restart probe failed: {out}"
    assert out["cold_s"] <= WARM_CACHE_COLD_COMPILE_MAX_S, (
        f"cold compile {out['cold_s']}s > {WARM_CACHE_COLD_COMPILE_MAX_S}s: "
        "persistent compile cache key bust?"
    )
