"""The MinPodsPerSec-style performance gate, run as a normal test.

Counterpart of the reference's scheduling benchmark assertion
(scheduling_benchmark_test.go:58,211-214: MinPodsPerSec = 100). The CI
environment is an 8-virtual-device CPU mesh (conftest.py), far slower than
the TPU the headline bench runs on, so the gate here asserts the
reference's own floor — 100 pods/sec — on a reference-mix workload sized
for CPU. bench.py measures the real headline on hardware.
"""

import time

import pytest

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.models.nodepool import NodePool

MIN_PODS_PER_SEC = 100.0  # the reference gate (:58)
# The accelerated-regime floor (VERDICT r3 #4): the round-3 16k decode
# regression (1,739 -> 795 pods/sec) sailed through CI because only the
# 100/sec reference floor was gated. On TPU hardware this gate fails loudly
# well before a regression of that size ships.
TPU_MIN_PODS_PER_SEC = 1500.0


def test_reference_mix_meets_min_pods_per_sec():
    import bench

    pods = bench.mixed_pods(512)
    pool = NodePool()
    pool.metadata.name = "default"
    templates = build_templates([(pool, instance_types(400))])
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=128)
    result = sched.solve(pods)  # cold: compile dominates, not gated
    assert not result.unschedulable
    t0 = time.perf_counter()
    result = sched.solve(pods)
    wall = time.perf_counter() - t0
    assert not result.unschedulable
    rate = len(pods) / wall
    assert rate >= MIN_PODS_PER_SEC, f"{rate:.1f} pods/sec < {MIN_PODS_PER_SEC}"


def test_tpu_regime_gate():
    """2048 selector pods x 400 types must clear 1,500 pods/sec when a real
    accelerator is attached (bench.py stage 1 enforces the same number).
    Skipped on the CPU mesh — the TPU regime can't be asserted there."""
    import jax

    if jax.devices()[0].platform == "cpu":
        pytest.skip("TPU-regime gate needs an accelerator")
    import bench

    pods = bench.selector_pods(2048)
    pool = NodePool()
    pool.metadata.name = "default"
    templates = build_templates([(pool, instance_types(400))])
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=256)
    assert not sched.solve(pods).unschedulable  # cold
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        result = sched.solve(pods)
        wall = time.perf_counter() - t0
        best = wall if best is None or wall < best else best
    assert not result.unschedulable
    rate = len(pods) / best
    assert rate >= TPU_MIN_PODS_PER_SEC, (
        f"TPU regime regression: {rate:.1f} pods/sec < {TPU_MIN_PODS_PER_SEC}"
    )


# VERDICT r4 #7: the north star and the 16k reference mix moved by integer
# factors between rounds with no gate catching it. Both are pinned here at
# ratcheted thresholds (best observed r5: north star 0.81s wall; 16k mix
# 18.1k pods/sec best / ~8k worst over tunnel variance), plus a
# cold-compile ceiling so a persistent-cache key bust fails loudly instead
# of looking like a CI hang.
NORTHSTAR_MAX_WALL_S = 1.1  # ratchet toward the 0.5s BASELINE target
MIXED_16K_MIN_PODS_PER_SEC = 7000.0  # ratchet from the 4,092 r4 number
WARM_CACHE_COLD_COMPILE_MAX_S = 60.0  # observed ~6s with a warm cache


def _tpu_or_skip():
    import jax

    if jax.devices()[0].platform == "cpu":
        pytest.skip("TPU-regime gate needs an accelerator")


def test_northstar_wall_gate():
    """100k selector pods x 1000 types, warm, best-of-2 (the claims-axis
    warm-sizing recompile is absorbed by the first warm run)."""
    _tpu_or_skip()
    import bench

    pods = bench.selector_pods(100_000)
    templates = bench.make_templates(1000)
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=4096)
    assert not sched.solve(pods).unschedulable  # cold
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        result = sched.solve(pods)
        wall = time.perf_counter() - t0
        best = wall if best is None or wall < best else best
    assert not result.unschedulable
    assert best <= NORTHSTAR_MAX_WALL_S, (
        f"north-star regression: {best:.3f}s > {NORTHSTAR_MAX_WALL_S}s"
    )


def test_mixed_16k_throughput_gate():
    """The reference benchmark mix (3/5 topology-bearing pods) at 16384 x
    400 — the kind-scan path's headline; best-of-3 to ride out tunnel
    variance."""
    _tpu_or_skip()
    import bench

    pods = bench.mixed_pods(16384)
    templates = bench.make_templates(400)
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=4096)
    assert not sched.solve(pods).unschedulable  # cold
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        result = sched.solve(pods)
        wall = time.perf_counter() - t0
        best = wall if best is None or wall < best else best
    assert not result.unschedulable
    rate = len(pods) / best
    assert rate >= MIXED_16K_MIN_PODS_PER_SEC, (
        f"16k ref-mix regression: {rate:.1f} pods/sec < {MIXED_16K_MIN_PODS_PER_SEC}"
    )


def test_warm_cache_cold_compile_ceiling():
    """A fresh process with the persistent XLA cache populated must reach
    its first solve inside the ceiling — a silent cache-key bust otherwise
    reads as a CI hang (VERDICT r4 weak #8)."""
    _tpu_or_skip()
    import bench

    out = bench.run_restart_stage(2048, 400, 256, on_tpu=True)
    assert isinstance(out, dict), f"restart probe failed: {out}"
    assert out["cold_s"] <= WARM_CACHE_COLD_COMPILE_MAX_S, (
        f"cold compile {out['cold_s']}s > {WARM_CACHE_COLD_COMPILE_MAX_S}s: "
        "persistent compile cache key bust?"
    )
