"""The MinPodsPerSec-style performance gate, run as a normal test.

Counterpart of the reference's scheduling benchmark assertion
(scheduling_benchmark_test.go:58,211-214: MinPodsPerSec = 100). The CI
environment is an 8-virtual-device CPU mesh (conftest.py), far slower than
the TPU the headline bench runs on, so the gate here asserts the
reference's own floor — 100 pods/sec — on a reference-mix workload sized
for CPU. bench.py measures the real headline on hardware.
"""

import time

import pytest

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.models.nodepool import NodePool

MIN_PODS_PER_SEC = 100.0  # the reference gate (:58)
# The accelerated-regime floor (VERDICT r3 #4): the round-3 16k decode
# regression (1,739 -> 795 pods/sec) sailed through CI because only the
# 100/sec reference floor was gated. On TPU hardware this gate fails loudly
# well before a regression of that size ships.
TPU_MIN_PODS_PER_SEC = 1500.0


def test_reference_mix_meets_min_pods_per_sec():
    import bench

    pods = bench.mixed_pods(512)
    pool = NodePool()
    pool.metadata.name = "default"
    templates = build_templates([(pool, instance_types(400))])
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=128)
    result = sched.solve(pods)  # cold: compile dominates, not gated
    assert not result.unschedulable
    t0 = time.perf_counter()
    result = sched.solve(pods)
    wall = time.perf_counter() - t0
    assert not result.unschedulable
    rate = len(pods) / wall
    assert rate >= MIN_PODS_PER_SEC, f"{rate:.1f} pods/sec < {MIN_PODS_PER_SEC}"


def test_tpu_regime_gate():
    """2048 selector pods x 400 types must clear 1,500 pods/sec when a real
    accelerator is attached (bench.py stage 1 enforces the same number).
    Skipped on the CPU mesh — the TPU regime can't be asserted there."""
    import jax

    if jax.devices()[0].platform == "cpu":
        pytest.skip("TPU-regime gate needs an accelerator")
    import bench

    pods = bench.selector_pods(2048)
    pool = NodePool()
    pool.metadata.name = "default"
    templates = build_templates([(pool, instance_types(400))])
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=256)
    assert not sched.solve(pods).unschedulable  # cold
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        result = sched.solve(pods)
        wall = time.perf_counter() - t0
        best = wall if best is None or wall < best else best
    assert not result.unschedulable
    rate = len(pods) / best
    assert rate >= TPU_MIN_PODS_PER_SEC, (
        f"TPU regime regression: {rate:.1f} pods/sec < {TPU_MIN_PODS_PER_SEC}"
    )
