"""DRA allocator tests.

Behavioral ports of the reference's dynamicresources suite
(allocator_test.go, pool_test.go, request_test.go, constraint_test.go,
types_test.go): selector matching, exclusive/multi-alloc availability,
MatchAttribute constraints with backtracking, FirstAvailable fallback,
All-mode, shared counters (partitionable devices), consumable capacity,
slice topology contribution, generation supersession, pessimistic-max
commit/release across instance types, and attribute bindings.
"""

import pytest

from karpenter_tpu.scheduling.dra import (
    AllocatedDeviceState,
    Allocator,
    CounterConsumption,
    CounterSet,
    Device,
    DeviceCapacity,
    DeviceClaimStatus,
    DeviceClass,
    DeviceID,
    DeviceRequest,
    DeviceSubRequest,
    DRAError,
    DRANodeClaim,
    MatchConstraintSpec,
    ResourceClaim,
    ResourceSlice,
    gather_pools,
)
from karpenter_tpu.scheduling.dra.constraints import AttributeBindingDecl, AttributeBindings
from karpenter_tpu.scheduling.dra.types import RequestPolicy
from karpenter_tpu.scheduling.requirements import Requirement, Requirements


def gpu(name, memory="16Gi", vendor="acme", **attrs):
    return Device(
        name=name,
        attributes={"vendor": vendor, **attrs},
        capacity={"memory": DeviceCapacity(value=float(str(memory).rstrip("Gi")) * 2**30)},
    )


def slice_of(*devices, driver="gpu.acme.com", pool="pool-a", all_nodes=True, **kw):
    return ResourceSlice(driver=driver, pool=pool, devices=list(devices), all_nodes=all_nodes, **kw)


def claim(name, *requests, constraints=()):
    return ResourceClaim(name=name, requests=list(requests), constraints=list(constraints))


def req(name="r0", count=1, selectors=(), device_class="", mode="ExactCount", capacity=None):
    return DeviceRequest(
        name=name,
        device_class=device_class,
        selectors=list(selectors),
        allocation_mode=mode,
        count=count,
        capacity_requests=capacity,
    )


def nodeclaim(id="nc-1", its=("it-a",), slices=None, reqs=None, nodepool="np", node_name=""):
    return DRANodeClaim(
        id=id,
        nodepool=nodepool,
        requirements=reqs or Requirements(),
        instance_types=list(its),
        resource_slices=slices or {},
        node_name=node_name,
    )


class TestSelectorEngine:
    def test_attribute_match_and_driver_fallback(self):
        a = Allocator([slice_of(gpu("d0"), gpu("d1", vendor="other"))])
        r = a.allocate(
            nodeclaim(),
            [claim("c", req(selectors=['device.attributes["vendor"] == "acme"']))],
        )
        r.commit()
        meta = a.metadata_for_claim("default/c")
        assert [d.device_id.device for d in meta.devices["it-a"]] == ["d0"]
        # Driver-qualified spelling resolves against unqualified attributes.
        a2 = Allocator([slice_of(gpu("d0"))])
        r2 = a2.allocate(
            nodeclaim(),
            [claim("c", req(selectors=['device.attributes["gpu.acme.com/vendor"] == "acme"']))],
        )
        assert r2.instance_types == ["it-a"]

    def test_capacity_and_boolean_operators(self):
        a = Allocator([slice_of(gpu("small", memory="8Gi"), gpu("big", memory="32Gi"))])
        r = a.allocate(
            nodeclaim(),
            [
                claim(
                    "c",
                    req(
                        selectors=[
                            'device.capacity["memory"] >= quantity("16Gi") && !(device.driver == "other")'
                        ]
                    ),
                )
            ],
        )
        r.commit()
        meta = a.metadata_for_claim("default/c")
        assert [d.device_id.device for d in meta.devices["it-a"]] == ["big"]

    def test_missing_attribute_is_no_match_not_error(self):
        a = Allocator([slice_of(gpu("d0"))])
        with pytest.raises(DRAError, match="no instance type"):
            a.allocate(
                nodeclaim(),
                [claim("c", req(selectors=['device.attributes["nonexistent"] == "x"']))],
            )

    def test_invalid_selector_is_validation_error(self):
        a = Allocator([slice_of(gpu("d0"))])
        with pytest.raises(DRAError, match="selector"):
            a.allocate(nodeclaim(), [claim("c", req(selectors=["__import__('os')"]))])

    def test_device_class_selectors_combine(self):
        classes = {"acme-gpu": DeviceClass(name="acme-gpu", selectors=['device.attributes["vendor"] == "acme"'])}
        a = Allocator([slice_of(gpu("d0", vendor="other"), gpu("d1"))], device_classes=classes)
        r = a.allocate(nodeclaim(), [claim("c", req(device_class="acme-gpu"))])
        r.commit()
        assert a.metadata_for_claim("default/c").devices["it-a"][0].device_id.device == "d1"

    def test_unknown_device_class_fails(self):
        a = Allocator([slice_of(gpu("d0"))])
        with pytest.raises(DRAError, match="DeviceClass"):
            a.allocate(nodeclaim(), [claim("c", req(device_class="missing"))])


class TestExclusiveAllocation:
    def test_two_nodeclaims_contend_for_one_device(self):
        slices = [slice_of(gpu("only"))]
        a = Allocator(slices)
        r1 = a.allocate(nodeclaim(id="nc-1"), [claim("c1", req())])
        r1.commit()
        with pytest.raises(DRAError):
            a.allocate(nodeclaim(id="nc-2"), [claim("c2", req())])

    def test_release_instance_type_frees_device(self):
        a = Allocator([slice_of(gpu("only"))])
        r1 = a.allocate(nodeclaim(id="nc-1", its=("it-a",)), [claim("c1", req())])
        r1.commit()
        a.release_instance_types("nc-1", "it-a")
        r2 = a.allocate(nodeclaim(id="nc-2"), [claim("c2", req())])
        assert r2.instance_types == ["it-a"]

    def test_same_nodeclaim_different_its_share_device(self):
        # A NodeClaim collapses to one IT, so one device may be allocated
        # under each candidate IT (allocationtracker.go:262-264).
        a = Allocator([slice_of(gpu("only"))])
        r = a.allocate(nodeclaim(id="nc-1", its=("it-a", "it-b")), [claim("c1", req())])
        assert r.instance_types == ["it-a", "it-b"]

    def test_preallocated_devices_unavailable(self):
        state = AllocatedDeviceState(exclusive_devices={DeviceID("gpu.acme.com", "pool-a", "d0")})
        a = Allocator([slice_of(gpu("d0"), gpu("d1"))], allocated_state=state)
        r = a.allocate(nodeclaim(), [claim("c", req())])
        r.commit()
        assert a.metadata_for_claim("default/c").devices["it-a"][0].device_id.device == "d1"


class TestConstraints:
    def test_match_attribute_forces_same_value(self):
        devices = [
            gpu("a0", numa="0"),
            gpu("a1", numa="1"),
            gpu("a2", numa="1"),
        ]
        a = Allocator([slice_of(*devices)])
        r = a.allocate(
            nodeclaim(),
            [claim("c", req(count=2), constraints=[MatchConstraintSpec(attribute="numa")])],
        )
        r.commit()
        chosen = {d.device_id.device for d in a.metadata_for_claim("default/c").devices["it-a"]}
        # a0 pins numa=0 first but has no partner; backtracking finds the pair.
        assert chosen == {"a1", "a2"}

    def test_match_attribute_across_requests(self):
        devices = [
            Device(name="gpu0", attributes={"kind": "gpu", "root": "p1"}),
            Device(name="nic0", attributes={"kind": "nic", "root": "p2"}),
            Device(name="nic1", attributes={"kind": "nic", "root": "p1"}),
        ]
        a = Allocator([slice_of(*devices)])
        r = a.allocate(
            nodeclaim(),
            [
                claim(
                    "c",
                    req(name="gpu", selectors=['device.attributes["kind"] == "gpu"']),
                    req(name="nic", selectors=['device.attributes["kind"] == "nic"']),
                    constraints=[MatchConstraintSpec(attribute="root", requests=["gpu", "nic"])],
                )
            ],
        )
        r.commit()
        chosen = {d.device_id.device for d in a.metadata_for_claim("default/c").devices["it-a"]}
        assert chosen == {"gpu0", "nic1"}

    def test_typed_equality_no_cross_type_pin(self):
        devices = [
            Device(name="d0", attributes={"v": 1}),
            Device(name="d1", attributes={"v": "1"}),
        ]
        a = Allocator([slice_of(*devices)])
        with pytest.raises(DRAError):
            a.allocate(
                nodeclaim(),
                [claim("c", req(count=2), constraints=[MatchConstraintSpec(attribute="v")])],
            )

    def test_distinct_attribute_unsupported(self):
        a = Allocator([slice_of(gpu("d0"))])
        with pytest.raises(DRAError, match="DistinctAttribute"):
            a.allocate(
                nodeclaim(),
                [
                    claim(
                        "c",
                        req(),
                        constraints=[MatchConstraintSpec(attribute="", distinct_attribute="x")],
                    )
                ],
            )


class TestAttributeBindings:
    def _bindings(self):
        return AttributeBindings.build(
            {
                ("np", "it-a"): [
                    AttributeBindingDecl(
                        attribute="pci-root",
                        devices=[
                            ("gpu.acme.com", "tmpl", "g0"),
                            ("gpu.acme.com", "tmpl", "n0"),
                        ],
                    ),
                    # Transitivity: n0~n1 implies g0~n1.
                    AttributeBindingDecl(
                        attribute="pci-root",
                        devices=[
                            ("gpu.acme.com", "tmpl", "n0"),
                            ("gpu.acme.com", "tmpl", "n1"),
                        ],
                    ),
                ]
            }
        )

    def test_runtime_only_attribute_via_binding(self):
        templates = {
            "it-a": [
                ResourceSlice(
                    driver="gpu.acme.com",
                    pool="tmpl",
                    devices=[Device(name="g0"), Device(name="n1"), Device(name="x9")],
                    potential=True,
                )
            ]
        }
        a = Allocator([], attribute_bindings=self._bindings())
        r = a.allocate(
            nodeclaim(slices=templates),
            [
                claim(
                    "c",
                    req(count=2),
                    constraints=[MatchConstraintSpec(attribute="pci-root")],
                )
            ],
        )
        r.commit()
        chosen = {d.device_id.device for d in a.metadata_for_claim("default/c").devices["it-a"]}
        # x9 participates in no binding group, so the transitive g0-n1 pair wins.
        assert chosen == {"g0", "n1"}

    def test_no_binding_group_fails(self):
        templates = {
            "it-a": [
                ResourceSlice(
                    driver="gpu.acme.com",
                    pool="tmpl",
                    devices=[Device(name="x1"), Device(name="x2")],
                    potential=True,
                )
            ]
        }
        a = Allocator([], attribute_bindings=self._bindings())
        with pytest.raises(DRAError):
            a.allocate(
                nodeclaim(slices=templates),
                [claim("c", req(count=2), constraints=[MatchConstraintSpec(attribute="pci-root")])],
            )


class TestFirstAvailable:
    def test_falls_through_to_second_subrequest(self):
        a = Allocator([slice_of(gpu("cheap", tier="b"))])
        r = a.allocate(
            nodeclaim(),
            [
                claim(
                    "c",
                    DeviceRequest(
                        name="r0",
                        first_available=[
                            DeviceSubRequest(
                                name="premium", selectors=['device.attributes["tier"] == "a"']
                            ),
                            DeviceSubRequest(
                                name="standard", selectors=['device.attributes["tier"] == "b"']
                            ),
                        ],
                    ),
                )
            ],
        )
        r.commit()
        result = a.metadata_for_claim("default/c").devices["it-a"][0]
        assert result.device_id.device == "cheap"
        assert str(result.request_name) == "r0/standard"


class TestAllMode:
    def test_allocates_every_matching_device(self):
        a = Allocator([slice_of(gpu("d0"), gpu("d1"), gpu("d2", vendor="other"))])
        r = a.allocate(
            nodeclaim(),
            [claim("c", req(mode="All", selectors=['device.attributes["vendor"] == "acme"']))],
        )
        r.commit()
        chosen = {d.device_id.device for d in a.metadata_for_claim("default/c").devices["it-a"]}
        assert chosen == {"d0", "d1"}

    def test_incomplete_pool_rejects_all_mode(self):
        s = slice_of(gpu("d0"))
        s.resource_slice_count = 2  # a second slice never arrived
        a = Allocator([s])
        with pytest.raises(DRAError, match="incomplete"):
            a.allocate(nodeclaim(), [claim("c", req(mode="All"))])

    def test_duplicate_device_names_invalidate_pool(self):
        a = Allocator(
            [
                ResourceSlice(
                    driver="d",
                    pool="p",
                    generation=1,
                    resource_slice_count=2,
                    all_nodes=True,
                    devices=[gpu("dup")],
                ),
                ResourceSlice(
                    driver="d",
                    pool="p",
                    generation=1,
                    resource_slice_count=2,
                    all_nodes=True,
                    devices=[gpu("dup")],
                ),
            ]
        )
        with pytest.raises(DRAError, match="invalid"):
            a.allocate(nodeclaim(), [claim("c", req(mode="All"))])


class TestConsumableCapacity:
    def _shared_device(self, total="10"):
        return Device(
            name="shared",
            allow_multiple_allocations=True,
            capacity={"bandwidth": DeviceCapacity(value=float(total))},
        )

    def test_capacity_gates_multi_alloc(self):
        a = Allocator([slice_of(self._shared_device())])
        r1 = a.allocate(nodeclaim(id="nc-1"), [claim("c1", req(capacity={"bandwidth": 6.0}))])
        r1.commit()
        with pytest.raises(DRAError):
            a.allocate(nodeclaim(id="nc-2"), [claim("c2", req(capacity={"bandwidth": 6.0}))])
        r3 = a.allocate(nodeclaim(id="nc-3"), [claim("c3", req(capacity={"bandwidth": 4.0}))])
        assert r3.instance_types == ["it-a"]

    def test_unrequested_dimension_consumes_full_value(self):
        a = Allocator([slice_of(self._shared_device())])
        r1 = a.allocate(nodeclaim(id="nc-1"), [claim("c1", req())])
        r1.commit()
        with pytest.raises(DRAError):
            a.allocate(nodeclaim(id="nc-2"), [claim("c2", req(capacity={"bandwidth": 1.0}))])

    def test_request_policy_rounds_up(self):
        d = Device(
            name="shared",
            allow_multiple_allocations=True,
            capacity={
                "bandwidth": DeviceCapacity(
                    value=10.0,
                    request_policy=RequestPolicy(valid_range_min=4.0, valid_range_step=4.0),
                )
            },
        )
        a = Allocator([slice_of(d)])
        r1 = a.allocate(nodeclaim(id="nc-1"), [claim("c1", req(capacity={"bandwidth": 5.0}))])
        r1.commit()  # rounds to 8
        with pytest.raises(DRAError):
            a.allocate(nodeclaim(id="nc-2"), [claim("c2", req(capacity={"bandwidth": 1.0}))])

    def test_nonexistent_dimension_fails(self):
        a = Allocator([slice_of(self._shared_device())])
        with pytest.raises(DRAError):
            a.allocate(nodeclaim(), [claim("c", req(capacity={"nope": 1.0}))])


class TestPartitionableDevices:
    def _partitioned_pool(self):
        """A GPU partitioned into slices drawing from one memory budget."""
        counter_slice = ResourceSlice(
            driver="gpu.acme.com",
            pool="mig",
            generation=1,
            resource_slice_count=2,
            shared_counters=[CounterSet(name="gpu0", counters={"memory": 40.0})],
        )
        device_slice = ResourceSlice(
            driver="gpu.acme.com",
            pool="mig",
            generation=1,
            resource_slice_count=2,
            all_nodes=True,
            devices=[
                Device(
                    name="mig-20-a",
                    consumes_counters=[CounterConsumption("gpu0", {"memory": 20.0})],
                ),
                Device(
                    name="mig-20-b",
                    consumes_counters=[CounterConsumption("gpu0", {"memory": 20.0})],
                ),
                Device(
                    name="mig-40",
                    consumes_counters=[CounterConsumption("gpu0", {"memory": 40.0})],
                ),
            ],
        )
        return [counter_slice, device_slice]

    def test_counter_budget_limits_partitions(self):
        a = Allocator(self._partitioned_pool())
        r1 = a.allocate(nodeclaim(id="nc-1"), [claim("c1", req(count=2))])
        r1.commit()  # two 20s exhaust the 40 budget
        with pytest.raises(DRAError):
            a.allocate(nodeclaim(id="nc-2"), [claim("c2", req())])

    def test_release_returns_counter_budget(self):
        a = Allocator(self._partitioned_pool())
        r1 = a.allocate(nodeclaim(id="nc-1"), [claim("c1", req(count=2))])
        r1.commit()
        a.release_instance_types("nc-1", "it-a")
        r2 = a.allocate(nodeclaim(id="nc-2"), [claim("c2", req())])
        assert r2.instance_types == ["it-a"]

    def test_independent_counter_sets_not_over_pruned(self):
        # Exhausting counter set A must not prune devices that draw only on
        # set B (a refinement over the reference's pool-level prune).
        slices = [
            ResourceSlice(
                driver="d",
                pool="p",
                generation=1,
                resource_slice_count=2,
                shared_counters=[
                    CounterSet(name="A", counters={"x": 40.0}),
                    CounterSet(name="B", counters={"x": 40.0}),
                ],
            ),
            ResourceSlice(
                driver="d",
                pool="p",
                generation=1,
                resource_slice_count=2,
                all_nodes=True,
                devices=[
                    Device(name="a-full", consumes_counters=[CounterConsumption("A", {"x": 40.0})]),
                    Device(name="b-full", consumes_counters=[CounterConsumption("B", {"x": 40.0})]),
                ],
            ),
        ]
        a = Allocator(slices)
        r = a.allocate(nodeclaim(), [claim("c", req(count=2))])
        r.commit()
        chosen = {d.device_id.device for d in a.metadata_for_claim("default/c").devices["it-a"]}
        assert chosen == {"a-full", "b-full"}

    def test_pessimistic_max_across_its(self):
        # nc-1 allocates one 20 partition under each of it-a and it-b; the
        # budget charge is the pessimistic max (20), not the sum (40).
        pool = self._partitioned_pool()
        a = Allocator(pool)
        r = a.allocate(
            DRANodeClaim(
                id="nc-1",
                nodepool="np",
                requirements=Requirements(),
                instance_types=["it-a", "it-b"],
                resource_slices={},
            ),
            [claim("c1", req())],
        )
        r.commit()
        with pytest.raises(DRAError):
            a.allocate(nodeclaim(id="nc-2"), [claim("c2", req(count=2))])
        # Releasing it-a leaves it-b's 20 charged; a single partition still fits.
        a.release_instance_types("nc-1", "it-a")
        r2 = a.allocate(nodeclaim(id="nc-2"), [claim("c2", req())])
        assert r2.instance_types == ["it-a"]


class TestTemplateDevices:
    def _templates(self, its=("it-a", "it-b")):
        return {
            it: [
                ResourceSlice(
                    driver="tpu.acme.com",
                    pool=f"tmpl-{it}",
                    potential=True,
                    devices=[gpu("t0", vendor="acme"), gpu("t1", vendor="acme")],
                )
            ]
            for it in its
        }

    def test_template_devices_per_it(self):
        a = Allocator([])
        r = a.allocate(
            nodeclaim(its=("it-a", "it-b"), slices=self._templates()),
            [claim("c", req(count=2))],
        )
        r.commit()
        meta = a.metadata_for_claim("default/c")
        assert meta.used_template_devices
        assert set(meta.devices) == {"it-a", "it-b"}

    def test_template_claim_node_local(self):
        # A claim satisfied with template devices pins pods to that NodeClaim.
        a = Allocator([])
        r = a.allocate(nodeclaim(id="nc-1", slices=self._templates(("it-a",))), [claim("c", req())])
        r.commit()
        with pytest.raises(DRAError, match="different in-flight"):
            a.allocate(nodeclaim(id="nc-2", slices=self._templates(("it-a",))), [claim("c", req())])
        # Same NodeClaim: already satisfied, no new DFS needed.
        r2 = a.allocate(nodeclaim(id="nc-1", slices=self._templates(("it-a",))), [claim("c", req())])
        assert r2.allocation is None

    def test_template_counters_are_per_it(self):
        templates = {
            "it-a": [
                ResourceSlice(
                    driver="tpu.acme.com",
                    pool="tmpl",
                    potential=True,
                    shared_counters=[CounterSet(name="hbm", counters={"gb": 32.0})],
                    devices=[
                        Device(name="half-a", consumes_counters=[CounterConsumption("hbm", {"gb": 16.0})]),
                        Device(name="half-b", consumes_counters=[CounterConsumption("hbm", {"gb": 16.0})]),
                        Device(name="full", consumes_counters=[CounterConsumption("hbm", {"gb": 32.0})]),
                    ],
                )
            ]
        }
        a = Allocator([])
        r1 = a.allocate(nodeclaim(id="nc-1", slices=templates), [claim("c1", req(count=2))])
        r1.commit()
        # The two halves consumed the 32GB budget on nc-1/it-a.
        with pytest.raises(DRAError):
            a.allocate(nodeclaim(id="nc-1", slices=templates), [claim("c2", req())])


class TestTopology:
    def _zonal_slices(self):
        zone_a = Requirements(Requirement.new("topology.kubernetes.io/zone", "In", "zone-a"))
        return [
            ResourceSlice(
                driver="net.acme.com",
                pool="zonal",
                node_selector_terms=[zone_a],
                devices=[gpu("za")],
            )
        ]

    def test_device_topology_contributes_requirements(self):
        a = Allocator(self._zonal_slices())
        r = a.allocate(nodeclaim(), [claim("c", req())])
        zone_req = r.requirements.get("topology.kubernetes.io/zone")
        assert zone_req is not None and zone_req.has("zone-a")

    def test_incompatible_nodeclaim_rejected(self):
        a = Allocator(self._zonal_slices())
        reqs = Requirements(Requirement.new("topology.kubernetes.io/zone", "In", "zone-b"))
        with pytest.raises(DRAError):
            a.allocate(nodeclaim(reqs=reqs), [claim("c", req())])

    def test_node_name_pinned_slice(self):
        s = ResourceSlice(driver="d", pool="p", node_name="node-7", devices=[gpu("local")])
        a = Allocator([s])
        # In-flight NodeClaims (no node name) can't reach node-pinned slices.
        with pytest.raises(DRAError):
            a.allocate(nodeclaim(), [claim("c", req())])
        r = a.allocate(nodeclaim(id="nc-e", node_name="node-7"), [claim("c", req())])
        assert r.instance_types == ["it-a"]
        # The device pins the claim to its node's hostname, so a pod sharing
        # the claim can't land on a different node.
        r.commit()
        assert a.metadata_for_claim("default/c").total_requirements.get(
            "kubernetes.io/hostname"
        ).has("node-7")
        other = Requirements(Requirement.new("kubernetes.io/hostname", "In", "node-99"))
        with pytest.raises(DRAError, match="incompatible"):
            a.allocate(
                nodeclaim(id="nc-other", node_name="node-99", reqs=other), [claim("c", req())]
            )

    def test_or_terms_fold_as_union(self):
        # A slice selectable in zone-a OR zone-b contributes the union, not
        # the (empty) intersection, so its devices stay allocatable.
        terms = [
            Requirements(Requirement.new("topology.kubernetes.io/zone", "In", "zone-a")),
            Requirements(Requirement.new("topology.kubernetes.io/zone", "In", "zone-b")),
        ]
        s = ResourceSlice(driver="d", pool="p", node_selector_terms=terms, devices=[gpu("d0")])
        a = Allocator([s])
        reqs = Requirements(Requirement.new("topology.kubernetes.io/zone", "In", "zone-a"))
        r = a.allocate(nodeclaim(reqs=reqs), [claim("c", req())])
        zone = r.requirements.get("topology.kubernetes.io/zone")
        assert zone.has("zone-a")

    def test_or_terms_in_claim_allocation(self):
        zone_ab = [
            Requirements(Requirement.new("topology.kubernetes.io/zone", "In", "zone-a")),
            Requirements(Requirement.new("topology.kubernetes.io/zone", "In", "zone-b")),
        ]
        c = ResourceClaim(name="done", allocation=DeviceClaimStatus(node_selector_terms=zone_ab))
        a = Allocator([])
        reqs = Requirements(Requirement.new("topology.kubernetes.io/zone", "In", "zone-a"))
        r = a.allocate(nodeclaim(reqs=reqs), [c])
        assert r.requirements.get("topology.kubernetes.io/zone").has("zone-a")

    def test_malformed_quantity_is_no_match_not_crash(self):
        a = Allocator([slice_of(gpu("d0"))])
        with pytest.raises(DRAError, match="no instance type"):
            a.allocate(
                nodeclaim(),
                [claim("c", req(selectors=['device.capacity["memory"] > quantity("10Q")']))],
            )

    def test_in_cluster_allocated_claim_folds_topology(self):
        zone_a = Requirements(Requirement.new("topology.kubernetes.io/zone", "In", "zone-a"))
        c = ResourceClaim(
            name="done",
            allocation=DeviceClaimStatus(node_selector_terms=[zone_a]),
        )
        a = Allocator([])
        r = a.allocate(nodeclaim(), [c])
        assert r.requirements.get("topology.kubernetes.io/zone").has("zone-a")
        reqs = Requirements(Requirement.new("topology.kubernetes.io/zone", "In", "zone-b"))
        with pytest.raises(DRAError, match="incompatible"):
            a.allocate(nodeclaim(id="nc-2", reqs=reqs), [c])

    def test_claim_reserved_by_deleting_pods_reallocates(self):
        c = ResourceClaim(
            name="migrating",
            requests=[req()],
            allocation=DeviceClaimStatus(),
            reserved_for=["pod-uid-1"],
        )
        a = Allocator([slice_of(gpu("d0"))], deleting_pod_uids={"pod-uid-1"})
        r = a.allocate(nodeclaim(), [c])
        r.commit()
        assert a.metadata_for_claim("default/migrating") is not None
        # With a live consumer the claim stays committed in place.
        c2 = ResourceClaim(
            name="pinned",
            requests=[req()],
            allocation=DeviceClaimStatus(),
            reserved_for=["pod-uid-1", "live-pod"],
        )
        a2 = Allocator([slice_of(gpu("d0"))], deleting_pod_uids={"pod-uid-1"})
        r2 = a2.allocate(nodeclaim(), [c2])
        assert r2.allocation is None


class TestPools:
    def test_generation_supersession(self):
        old = ResourceSlice(driver="d", pool="p", generation=1, all_nodes=True, devices=[gpu("old")])
        new = ResourceSlice(driver="d", pool="p", generation=2, all_nodes=True, devices=[gpu("new")])
        pools = gather_pools([old, new], Requirements())
        assert len(pools) == 1
        assert [dw.device.name for dw in pools[0].devices] == ["new"]
        assert not pools[0].incomplete

    def test_incomplete_pool_still_usable_for_exact_count(self):
        s = slice_of(gpu("d0"))
        s.resource_slice_count = 3
        a = Allocator([s])
        # ExactCount skips incomplete pools' devices entirely (allocator.go:806).
        with pytest.raises(DRAError):
            a.allocate(nodeclaim(), [claim("c", req())])

    def test_max_devices_cap(self):
        a = Allocator([slice_of(*[gpu(f"d{i}") for i in range(40)])])
        with pytest.raises(DRAError, match="maximum"):
            a.allocate(nodeclaim(), [claim("c", req(count=33))])
