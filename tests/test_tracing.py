"""Decision-provenance tracing (karpenter_tpu/tracing/).

Covers the PR-2 tentpole acceptance criteria:
- a north-star-shaped solve (kwok provider, fake clock) yields ONE trace
  with nested batcher/encode/dispatch/wire/decode/bind spans whose
  durations reconcile with the scheduler's stage timings;
- a remote Solve over the gRPC split stitches client + server spans into
  a single trace (shared trace id);
- an unschedulable pod surfaces an explainer event naming the failing
  requirement and the relaxation rungs attempted, and the
  ktpu_unschedulable_pods gauge carries a matching reason label;
- measured overhead: coarse-span tracing costs < 1 % of a solve when
  enabled, ~0 when disabled.
"""

import json
import time
import urllib.request

import pytest

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.controllers.provisioning import build_templates
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import (
    NodeAffinity,
    PreferredSchedulingTerm,
    make_pod,
)
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.tracing import TRACER, Tracer, decision_for, reason_slug
from karpenter_tpu.utils.clock import FakeClock


@pytest.fixture
def tracer():
    """The process-global tracer, enabled for the test and cleaned after
    (other suites rely on the disabled default)."""
    TRACER.reset()
    TRACER.enable()
    yield TRACER
    TRACER.disable()
    TRACER.reset()


def build_env(catalog_size=30):
    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=instance_types(catalog_size))
    mgr = Manager(store, cloud, clock)
    return clock, store, cloud, mgr


def default_pool(name="default") -> NodePool:
    pool = NodePool()
    pool.metadata.name = name
    return pool


def spans_by_name(trace):
    out = {}
    for s in trace["spans"]:
        out.setdefault(s["name"], []).append(s)
    return out


class TestTracerCore:
    def test_nested_spans_share_a_trace(self, tracer):
        with tracer.span("root", kind="test") as root:
            with tracer.span("child") as child:
                with tracer.span("grandchild") as gc:
                    pass
        assert child.trace_id == root.trace_id == gc.trace_id
        assert child.parent_id == root.span_id
        assert gc.parent_id == child.span_id
        trace = tracer.trace(root.trace_id)
        assert trace is not None and len(trace["spans"]) == 3
        assert trace["root"] == "root"
        # children's intervals nest inside the root's
        by = spans_by_name(trace)
        r = by["root"][0]
        for name in ("child", "grandchild"):
            s = by[name][0]
            assert s["start"] >= r["start"]
            assert s["start"] + s["duration_s"] <= r["start"] + r["duration_s"] + 1e-6

    def test_sibling_roots_are_separate_traces(self, tracer):
        with tracer.span("a") as a:
            pass
        with tracer.span("b") as b:
            pass
        assert a.trace_id != b.trace_id
        assert len(tracer.traces()) == 2

    def test_ring_buffer_bounded(self):
        t = Tracer(max_traces=8)
        t.enable()
        for i in range(50):
            with t.span(f"r{i}"):
                pass
        traces = t.traces()
        assert len(traces) == 8
        assert traces[-1]["root"] == "r49"  # most recent survive

    def test_disabled_records_nothing(self):
        t = Tracer()
        assert not t.enabled  # default off without KTPU_TRACE_DIR
        with t.span("x") as sp:
            sp.set(a=1)  # the no-op span supports the full surface
        assert t.traces() == []
        assert t.context() is None

    def test_record_span_requires_a_parent(self, tracer):
        tracer.record_span("orphan", 1.0)  # silently dropped
        with tracer.span("root") as root:
            tracer.record_span("batcher.wait", 2.5, simulated=True)
        trace = tracer.trace(root.trace_id)
        by = spans_by_name(trace)
        assert "orphan" not in by
        assert by["batcher.wait"][0]["duration_s"] == pytest.approx(2.5)

    def test_exception_marks_span_and_still_flushes(self, tracer):
        with pytest.raises(ValueError):
            with tracer.span("boom") as sp:
                raise ValueError("x")
        trace = tracer.trace(sp.trace_id)
        assert trace["spans"][0]["attrs"]["error"] == "ValueError"

    def test_jsonl_export(self, tracer, tmp_path, monkeypatch):
        monkeypatch.setenv("KTPU_TRACE_DIR", str(tmp_path))
        with tracer.span("exported"):
            with tracer.span("inner"):
                pass
        files = list(tmp_path.glob("ktpu-traces-*.jsonl"))
        assert len(files) == 1
        lines = files[0].read_text().strip().splitlines()
        assert len(lines) == 1
        trace = json.loads(lines[0])
        assert trace["root"] == "exported"
        assert {s["name"] for s in trace["spans"]} == {"exported", "inner"}

    def test_trace_dir_implies_enabled(self, monkeypatch):
        monkeypatch.setenv("KTPU_TRACE_DIR", "/tmp/anywhere")
        assert Tracer().enabled
        monkeypatch.delenv("KTPU_TRACE_DIR")
        assert not Tracer().enabled


class TestOverhead:
    def test_disabled_span_is_near_free(self):
        t = Tracer()
        t0 = time.perf_counter()
        for _ in range(100_000):
            with t.span("x"):
                pass
        elapsed = time.perf_counter() - t0
        # ~0 when disabled: generous CI bound, typically < 30ms
        assert elapsed < 2.0, f"100k disabled spans took {elapsed:.3f}s"

    def test_enabled_span_cost_fits_one_percent_budget(self):
        t = Tracer(max_traces=4)
        t.enable()
        n = 2_000
        t0 = time.perf_counter()
        for _ in range(n):
            with t.span("root"):
                with t.span("child"):
                    pass
        per_span = (time.perf_counter() - t0) / (2 * n)
        # a north-star solve carries ~20 coarse spans in ~0.85s; < 1%
        # means < 425us per span. Assert 4x headroom under that.
        assert per_span < 100e-6, f"enabled span cost {per_span * 1e6:.0f}us"


class TestProvisioningTrace:
    """Acceptance: one trace for a kwok/fake-clock solve with nested
    batcher/encode/dispatch/wire/decode/bind spans whose durations
    reconcile with the scheduler's stage timings."""

    def _run_scenario(self, n_pods=64):
        clock, store, cloud, mgr = build_env()
        store.create(ObjectStore.NODEPOOLS, default_pool())
        for i in range(n_pods):
            store.create(ObjectStore.PODS, make_pod(f"p-{i}", cpu=0.5))
        with TRACER.span("scenario") as root:
            mgr.run_until_idle()
            cloud.simulate_kubelet_ready()
            mgr.run_until_idle()
            KubeSchedulerSim(store, mgr.cluster).bind_pending()
        bound = sum(1 for p in store.pods() if p.spec.node_name)
        assert bound == n_pods
        return mgr, root

    def test_one_trace_with_all_pipeline_spans(self, tracer):
        mgr, root = self._run_scenario()
        trace = tracer.trace(root.trace_id)
        assert trace is not None
        by = spans_by_name(trace)
        for name in (
            "provisioning",
            "batcher.wait",
            "topology.build",
            "solve",
            "solve.round",
            "solve.encode",
            "solve.dispatch",
            "solve.wire",
            "solve.decode",
            "claims.create",
            "lifecycle.drain",
            "lifecycle.nodeclaim",
            "bind.pending",
        ):
            assert name in by, f"missing span {name}; got {sorted(by)}"
        # every span belongs to the single scenario trace
        assert all(s["trace_id"] == root.trace_id for s in trace["spans"])
        # at least one dispatch-mode child recorded
        assert any(n.startswith("solve.dispatch.") for n in by)

    def test_span_durations_reconcile_with_stage_timings(self, tracer):
        mgr, root = self._run_scenario()
        timings = mgr.provisioner._scheduler_cache[1].last_timings
        trace = tracer.trace(root.trace_id)
        by = spans_by_name(trace)
        encode = sum(s["duration_s"] for s in by["solve.encode"])
        dispatch = sum(s["duration_s"] for s in by["solve.dispatch"])
        wire = sum(s["duration_s"] for s in by["solve.wire"])
        decode = sum(s["duration_s"] for s in by["solve.decode"])
        total = encode + dispatch + decode
        staged = timings["encode_s"] + timings["device_s"] + timings["decode_s"]
        # the spans bracket the same perf_counter regions the stage
        # timings measure (one relaxation round here), so both the stage
        # sums and the per-stage splits must agree to within bookkeeping
        # noise. Absolute slack covers CI scheduling jitter.
        slack = 0.25 * staged + 0.05
        assert abs(total - staged) < slack, (total, staged)
        assert encode >= timings["encode_s"] - slack
        # device_s = dispatch + the decode prefix ending at the fetch, so
        # dispatch+wire covers it
        assert dispatch + wire >= timings["device_s"] - slack
        assert decode >= timings["decode_s"] - slack
        # nesting: wire inside decode's solve-round window
        r = by["solve.round"][0]
        for name in ("solve.encode", "solve.dispatch", "solve.decode"):
            s = by[name][0]
            assert s["start"] >= r["start"] - 1e-6
            assert s["start"] + s["duration_s"] <= r["start"] + r["duration_s"] + 1e-6

    def test_tracing_off_changes_nothing(self):
        # no fixture: tracer stays disabled; the same scenario must
        # produce zero traces and still fully schedule
        TRACER.reset()
        clock, store, cloud, mgr = build_env()
        store.create(ObjectStore.NODEPOOLS, default_pool())
        for i in range(16):
            store.create(ObjectStore.PODS, make_pod(f"p-{i}", cpu=0.5))
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        assert TRACER.traces() == []


class TestInjectedClock:
    """The tracer's clocks are injectable (ISSUE 12 deflake satellite):
    span ordering and durations are asserted against a deterministic
    tick counter, not the wall clock."""

    def test_span_ordering_and_durations_under_injected_clock(self):
        ticks = iter(float(i) for i in range(100))
        walls = iter(float(1000 + i) for i in range(100))
        t = Tracer(clock=lambda: next(ticks), wall=lambda: next(walls))
        t.enable()
        with t.span("root") as root:
            with t.span("child"):
                pass
        trace = t.trace(root.trace_id)
        assert trace is not None
        by = {s["name"]: s for s in trace["spans"]}
        # clock reads: root start=0 (+wall), child start=1, child end=2,
        # root end=3 — ordering and durations are exact, no sleeps, no
        # wall-clock interleaving assumptions
        assert by["root"]["start"] == 0.0
        assert by["child"]["start"] == 1.0
        assert by["child"]["duration_s"] == 1.0
        assert by["root"]["duration_s"] == 3.0
        assert by["child"]["start"] > by["root"]["start"]
        assert (
            by["child"]["start"] + by["child"]["duration_s"]
            <= by["root"]["start"] + by["root"]["duration_s"]
        )
        assert by["root"]["wall_start"] == 1000.0

    def test_record_span_uses_injected_clock(self):
        ticks = iter(float(i) for i in range(100))
        walls = iter(float(1000 + i) for i in range(100))
        t = Tracer(clock=lambda: next(ticks), wall=lambda: next(walls))
        t.enable()
        with t.span("root") as root:
            t.record_span("waited", 0.5)
        trace = t.trace(root.trace_id)
        by = {s["name"]: s for s in trace["spans"]}
        # record_span ends at the injected now (tick 1) and backdates
        assert by["waited"]["start"] == 0.5
        assert by["waited"]["duration_s"] == 0.5


class TestRemoteSolveStitching:
    """Acceptance: a remote Solve yields a single stitched trace — the
    server-side spans carry the client's trace id."""

    def test_client_and_server_spans_share_the_trace(self, tracer):
        from karpenter_tpu.rpc import RemoteScheduler, serve

        server, addr = serve("127.0.0.1:0")
        try:
            templates = build_templates([(default_pool(), instance_types(8))])
            remote = RemoteScheduler(addr, templates)
            with tracer.span("client-root") as root:
                result = remote.solve([make_pod(f"p-{i}", cpu=0.5) for i in range(12)])
            remote.close()
            assert not result.unschedulable
            # the server handler's spans flush on ITS thread: in-process
            # the refcounted trace can complete after the client exits
            # its root span, so an immediate read may miss the server
            # fragment. Poll (bounded) until the fragment lands instead
            # of assuming wall-clock ordering across threads.
            import time as _time

            deadline = _time.monotonic() + 5.0
            by = {}
            while _time.monotonic() < deadline:
                trace = tracer.trace(root.trace_id)
                by = spans_by_name(trace) if trace else {}
                if any(name.startswith("rpc.server.") for name in by):
                    break
                _time.sleep(0.01)
            # solves prefer the streaming SolveStream crossing (unary
            # Solve remains the downgrade path on older servers)
            method = "SolveStream" if "rpc.SolveStream" in by else "Solve"
            assert f"rpc.{method}" in by  # the client-side wire crossing
            assert f"rpc.server.{method}" in by  # the server fragment
            assert "solve.encode" in by  # server-side solve internals
            # stitched: one trace id across both sides of the socket
            assert all(s["trace_id"] == root.trace_id for s in trace["spans"])
            # the server fragment hangs off the client's rpc span
            server_root = by[f"rpc.server.{method}"][0]
            assert server_root["parent_id"] == by[f"rpc.{method}"][0]["span_id"]
        finally:
            server.stop(0)


class TestExplainer:
    def test_reason_slugs(self):
        assert reason_slug("scheduling timeout exceeded") == "solve_timeout"
        assert reason_slug("no compatible in-flight claim or template") == "incompatible"
        assert reason_slug("claim-slot capacity exhausted; raise max_claims") == "no_room"
        assert reason_slug("something else entirely") == "other"

    def test_decision_names_the_failing_requirement(self):
        templates = build_templates([(default_pool(), instance_types(8))])
        pod = make_pod("p-stuck", cpu=0.5, node_selector={"example.com/missing": "x"})
        d = decision_for(
            pod, "no compatible in-flight claim or template", templates, ["preferred-node-affinity"]
        )
        assert d.rejections and d.rejections[0]["class"] == "requirement"
        assert "example.com/missing" in d.rejections[0]["detail"]
        msg = d.message()
        assert "example.com/missing" in msg
        assert "preferred-node-affinity" in msg

    def test_unschedulable_pod_event_gauge_and_trace_decision(self, tracer):
        from karpenter_tpu.utils import metrics

        clock, store, cloud, mgr = build_env()
        store.create(ObjectStore.NODEPOOLS, default_pool())
        # schedulable companion + a pod pinned to an undefined label, with
        # a preference so the relaxation ladder demonstrably ran
        store.create(ObjectStore.PODS, make_pod("p-ok", cpu=0.5))
        stuck = make_pod("p-stuck", cpu=0.5, node_selector={"example.com/rack": "r1"})
        stuck.spec.node_affinity = NodeAffinity(
            preferred=[
                PreferredSchedulingTerm(
                    1, [{"key": "x", "operator": "In", "values": ["a"]}]
                )
            ]
        )
        store.create(ObjectStore.PODS, stuck)
        with TRACER.span("scenario") as root:
            mgr.run_until_idle()
        # explainer event: failing requirement + relaxation rungs
        events = mgr.recorder.for_object("Pod", "p-stuck")
        assert events, "no FailedScheduling event for the stuck pod"
        msg = events[-1].message
        assert events[-1].reason == "FailedScheduling"
        assert "example.com/rack" in msg
        assert "relaxed preferences" in msg
        assert "preferred-node-affinity" in msg
        # gauge: reasoned label matches the canonical slug
        assert metrics.UNSCHEDULABLE_PODS.get(reason="incompatible") == 1.0
        # the SchedulingDecision record rode the trace
        trace = tracer.trace(root.trace_id)
        decisions = trace.get("decisions", [])
        assert any(
            d["pod"] == "p-stuck" and d["relaxed"] for d in decisions
        ), decisions


class TestDebugTracesEndpoint:
    def test_endpoint_serves_ring_and_gates_on_profiling(self, tracer):
        from karpenter_tpu.utils.runtime import HealthConfig, serve_health

        with tracer.span("visible"):
            pass
        server, port = serve_health(HealthConfig(enable_profiling=True))
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/traces", timeout=5
            ).read()
            payload = json.loads(body)
            assert payload["enabled"] is True
            assert any(t["root"] == "visible" for t in payload["traces"])
        finally:
            server.shutdown()
        server, port = serve_health(HealthConfig(enable_profiling=False))
        try:
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/traces", timeout=5
                )
        finally:
            server.shutdown()
