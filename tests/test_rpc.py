"""The gRPC control/solver split (rpc/): wire codec fidelity, remote/local
solve parity, and the full provisioning pipeline through the socket.

The reference seam being reproduced is the CloudProvider decorator
(pkg/cloudprovider/metrics/cloudprovider.go) — here crossed for real at
the Scheduler boundary (SURVEY.md §2.9: control plane over DCN, solver
next to the accelerator)."""

import pytest

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.controllers.provisioning.host_scheduler import pod_content_sig
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import Budget, NodePool
from karpenter_tpu.models.pod import (
    HostPort,
    NodeAffinity,
    PodAffinityTerm,
    PreferredSchedulingTerm,
    TopologySpreadConstraint,
    make_pod,
)
from karpenter_tpu.models.taints import Toleration
from karpenter_tpu.rpc import RemoteScheduler, serve
from karpenter_tpu.rpc import convert
from karpenter_tpu.rpc.codec import decode_templates, encode_templates
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.options import Options


@pytest.fixture(scope="module")
def solver_server():
    server, addr = serve("127.0.0.1:0")
    yield addr
    server.stop(0)


def default_pool(name="default") -> NodePool:
    pool = NodePool()
    pool.metadata.name = name
    return pool


def diverse_pods(n):
    """The reference benchmark's fifths: generic / TSC-zone / TSC-host /
    affinity / anti-affinity (scheduling_benchmark_test.go:259-272)."""
    pods = []
    for i in range(n):
        p = make_pod(f"p-{i}", cpu=0.5, memory="512Mi")
        kind = i % 5
        if kind == 1:
            p.metadata.labels = {"spread": "zonal"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    label_selector={"spread": "zonal"},
                )
            ]
        elif kind == 2:
            p.metadata.labels = {"spread": "host"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_HOSTNAME,
                    label_selector={"spread": "host"},
                )
            ]
        elif kind == 3:
            p.metadata.labels = {"aff": "group"}
            p.spec.pod_affinity = [
                PodAffinityTerm(
                    topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"aff": "group"}
                )
            ]
        elif kind == 4:
            p.metadata.labels = {"app": "nginx"}
            p.spec.pod_anti_affinity = [
                PodAffinityTerm(
                    topology_key=l.LABEL_HOSTNAME, label_selector={"app": "nginx"}
                )
            ]
        pods.append(p)
    return pods


class TestCodec:
    def test_template_catalog_roundtrip(self):
        pool = default_pool()
        pool.spec.weight = 7
        pool.spec.template.labels["team"] = "infra"
        pool.spec.template.spec.taints = []
        templates = build_templates([(pool, instance_types(24))])
        templates[0].daemon_requests = {"cpu": 0.25, "pods": 1.0}
        data = encode_templates(templates)
        back = decode_templates(data)
        assert len(back) == len(templates)
        t0, b0 = templates[0], back[0]
        assert b0.nodepool_name == t0.nodepool_name
        assert b0.weight == t0.weight
        assert b0.labels == t0.labels
        assert b0.daemon_requests == t0.daemon_requests
        assert str(b0.requirements) == str(t0.requirements)
        assert [it.name for it in b0.instance_types] == [
            it.name for it in t0.instance_types
        ]
        # offerings survive with prices, zones and availability
        it0, ib0 = t0.instance_types[0], b0.instance_types[0]
        assert it0.capacity == ib0.capacity
        assert [(o.zone, o.capacity_type, o.price, o.available) for o in it0.offerings] == [
            (o.zone, o.capacity_type, o.price, o.available) for o in ib0.offerings
        ]
        assert it0.allocatable() == ib0.allocatable()
        # the encoding is canonical: same input -> same bytes
        assert encode_templates(templates) == data

    def test_pod_roundtrip_preserves_kind_signature(self):
        pods = diverse_pods(5)
        # enrich the generic pod with the remaining spec surface
        pods[0].spec.node_selector = {l.LABEL_TOPOLOGY_ZONE: "test-zone-1"}
        pods[0].spec.tolerations = [
            Toleration(key="dedicated", operator="Equal", value="gpu", effect="NoSchedule")
        ]
        pods[0].spec.host_ports = [HostPort(port=8080, protocol="TCP")]
        pods[0].spec.node_affinity = NodeAffinity(
            preferred=[
                PreferredSchedulingTerm(
                    5, [{"key": "x", "operator": "In", "values": ["a", "b"]}]
                )
            ]
        )
        for pod in pods:
            back = convert.pod_from_pb(convert.pod_to_pb(pod))
            assert back.uid == pod.uid
            assert back.metadata.labels == pod.metadata.labels
            # the kind signature drives dedup/batching and packing order —
            # it must survive the wire bit-for-bit
            assert pod_content_sig(back) == pod_content_sig(pod)

    def test_existing_node_roundtrip_carries_used_and_host_ports(self):
        # a remote Solve must see in-use host ports and resources on
        # existing nodes exactly like the in-process engine
        # (scheduler.py existing-node seeding; existingnode.go:32-75)
        from karpenter_tpu.controllers.provisioning.host_scheduler import (
            ExistingSimNode,
        )
        from karpenter_tpu.scheduling.requirements import Requirements

        node = ExistingSimNode(
            name="n-1",
            index=0,
            requirements=Requirements.from_labels(
                {l.LABEL_HOSTNAME: "n-1", l.LABEL_TOPOLOGY_ZONE: "test-zone-1"}
            ),
            available={"cpu": 3.5, "memory": 2.0 * 2**30, "pods": 100.0},
            used={"cpu": 0.5, "pods": 10.0},
            host_ports=[("", 8080, "TCP"), ("0.0.0.0", 443, "TCP")],
        )
        back = convert.existing_from_pb(convert.existing_to_pb(node), 0)
        assert back.used == node.used
        assert back.host_ports == node.host_ports


class TestSolveParity:
    def _parity(self, addr, templates, pods, **kwargs):
        remote = RemoteScheduler(addr, templates)
        local = TPUScheduler(templates)
        r = remote.solve(pods, **kwargs)
        s = local.solve(pods, **kwargs)
        assert len(r.claims) == len(s.claims)
        assert r.assignments == s.assignments
        assert r.existing_assignments == s.existing_assignments
        assert sorted(reason for _, reason in r.unschedulable) == sorted(
            reason for _, reason in s.unschedulable
        )
        assert abs(r.total_price() - s.total_price()) < 1e-9
        for rc, sc in zip(r.claims, s.claims):
            assert rc.template.nodepool_name == sc.template.nodepool_name
            assert [it.name for it in rc.instance_types] == [
                it.name for it in sc.instance_types
            ]
            assert sorted(p.uid for p in rc.pods) == sorted(p.uid for p in sc.pods)
            assert rc.used == sc.used
        return r

    def test_selector_pods(self, solver_server):
        templates = build_templates([(default_pool(), instance_types(32))])
        pods = [
            make_pod(
                f"p-{i}",
                cpu=0.5,
                node_selector=(
                    {l.LABEL_TOPOLOGY_ZONE: f"test-zone-{1 + i % 3}"} if i % 2 else {}
                ),
            )
            for i in range(16)
        ]
        self._parity(solver_server, templates, pods)

    def test_reference_mix_topology(self, solver_server):
        """TSC + affinity + anti-affinity cross the wire: the server builds
        topology from shipped pods (no client callback crosses)."""
        templates = build_templates([(default_pool(), instance_types(32))])
        self._parity(solver_server, templates, diverse_pods(20))

    def test_budgets_and_weights(self, solver_server):
        heavy, light = default_pool("heavy"), default_pool("light")
        heavy.spec.weight = 90
        light.spec.weight = 10
        templates = build_templates(
            [(heavy, instance_types(16)), (light, instance_types(16))]
        )
        pods = [make_pod(f"p-{i}", cpu=1.0) for i in range(8)]
        self._parity(
            solver_server, templates, pods, budgets={"heavy": {"nodes": 1.0}}
        )

    def test_unschedulable_reason_crosses(self, solver_server):
        templates = build_templates([(default_pool(), instance_types(8))])
        pods = [make_pod("impossible", cpu=10_000.0)]
        r = self._parity(solver_server, templates, pods)
        assert len(r.unschedulable) == 1
        assert r.unschedulable[0][0].uid == pods[0].uid

    def test_relaxation_happens_server_side(self, solver_server):
        templates = build_templates([(default_pool(), instance_types(16))])
        pod = make_pod("p", cpu=0.5)
        pod.spec.node_affinity = NodeAffinity(
            preferred=[
                PreferredSchedulingTerm(
                    10,
                    [{"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In",
                      "values": ["zone-nowhere"]}],
                )
            ]
        )
        r = RemoteScheduler(solver_server, templates).solve([pod])
        assert not r.unschedulable  # the ladder ran remotely

    def test_stale_config_recovers(self, solver_server):
        """A superseded Configure (another client generation, or a solver
        restart) invalidates the config version; the client re-Configures
        and retries instead of leaving provisioning permanently broken."""
        templates = build_templates([(default_pool(), instance_types(8))])
        first = RemoteScheduler(solver_server, templates)
        stale = first._config_version
        # a DIFFERENT cluster shape supersedes `first` (an identical shape
        # now shares the config epoch and would NOT invalidate it)
        other = build_templates([(default_pool(), instance_types(12))])
        RemoteScheduler(solver_server, other)
        result = first.solve([make_pod("p", cpu=0.5)])
        assert len(result.claims) == 1
        assert first._config_version > stale  # re-Configure happened


class TestStreamStitcher:
    """The SolveStream chunk-stitching state machine, fed frames directly
    (no sockets): round-tagged frames make stale chunks — a chunk frame
    arriving after the reset that invalidated its relaxation round —
    discardable instead of silently stitched (ISSUE 4 satellite; the
    mid-stream-recovery hazard)."""

    @staticmethod
    def _chunk(round_no, claims=(), exist=(), unsched=()):
        from karpenter_tpu.rpc import solver_pb2 as pb
        from karpenter_tpu.rpc.service import FRAME_CHUNK, _round_bytes

        resp = pb.SolveResponse()
        for slot, uids in claims:
            m = resp.claims.add()
            m.slot = slot
            m.pod_uids.extend(uids)
        for uid, node in exist:
            a = resp.existing_assignments.add()
            a.pod_uid, a.node_name = uid, node
        for uid, reason in unsched:
            u = resp.unschedulable.add()
            u.pod_uid, u.reason = uid, reason
        return FRAME_CHUNK + _round_bytes(round_no) + resp.SerializeToString()

    @staticmethod
    def _reset(round_no):
        from karpenter_tpu.rpc.service import FRAME_RESET, _round_bytes

        return FRAME_RESET + _round_bytes(round_no)

    @staticmethod
    def _final(slim=True):
        from karpenter_tpu.rpc import solver_pb2 as pb
        from karpenter_tpu.rpc.service import FRAME_FINAL_FULL, FRAME_FINAL_SLIM

        tag = FRAME_FINAL_SLIM if slim else FRAME_FINAL_FULL
        return tag + pb.SolveResponse().SerializeToString()

    def test_in_order_rounds_stitch(self):
        from karpenter_tpu.rpc.client import StreamStitcher

        s = StreamStitcher()
        frames = [
            self._chunk(0, claims=[(0, ["a"])]),
            self._chunk(0, claims=[(0, ["b"]), (1, ["c"])]),
            self._final(),
        ]
        fed = [s.feed(f) for f in frames]
        assert fed == [False, False, True]
        assert s.tables()["claims"] == {0: ["a", "b"], 1: ["c"]}
        assert s.n_chunks == 2 and s.n_stale == 0 and not s.full

    def test_reset_discards_and_stale_chunk_is_dropped(self):
        """The regression: chunk(round 0) after reset(round 1) belongs to
        the abandoned round — it must NOT be stitched into round 1."""
        from karpenter_tpu.rpc.client import StreamStitcher
        from karpenter_tpu.utils.metrics import STREAM_STALE_FRAMES

        before = STREAM_STALE_FRAMES.get()
        s = StreamStitcher()
        s.feed(self._chunk(0, claims=[(0, ["old-a"])], unsched=[("u1", "NoFit")]))
        s.feed(self._reset(1))  # relaxation round restarted the tables
        assert s.tables()["claims"] == {}  # accumulated state discarded
        s.feed(self._chunk(0, claims=[(0, ["old-b"])]))  # STALE: round 0
        s.feed(self._chunk(1, claims=[(0, ["new-a"])]))
        s.feed(self._final())
        assert s.tables()["claims"] == {0: ["new-a"]}, "stale chunk was stitched"
        assert s.tables()["unsched"] == []
        assert s.n_stale == 1 and s.n_resets == 1 and s.n_chunks == 2
        assert STREAM_STALE_FRAMES.get() == before + 1

    def test_future_round_chunk_without_its_reset_is_dropped(self):
        """A chunk tagged PAST the live round (its reset frame never
        arrived — out-of-order delivery) is equally unstitchable."""
        from karpenter_tpu.rpc.client import StreamStitcher

        s = StreamStitcher()
        s.feed(self._chunk(0, claims=[(0, ["a"])]))
        s.feed(self._chunk(2, claims=[(0, ["phantom"])]))
        s.feed(self._final())
        assert s.tables()["claims"] == {0: ["a"]}
        assert s.n_stale == 1

    def test_full_final_carries_everything(self):
        from karpenter_tpu.rpc.client import StreamStitcher

        s = StreamStitcher()
        assert s.feed(self._final(slim=False))
        assert s.full and s.final is not None
        assert s.stats()["chunks"] == 0


class TestColumnarChunkFrames:
    """ISSUE-7 satellite: the zero-copy columnar chunk layout must stitch
    into EXACTLY the tables the legacy protobuf frames produce, respect
    the same round tagging, and round-trip every table shape. Since
    ISSUE 8 the server emits ONLY columnar frames; the CLIENT keeps
    decoding the legacy tag for old-server downgrade."""

    @staticmethod
    def _col_chunk(round_no, delta):
        from karpenter_tpu.rpc.codec import encode_chunk_columnar
        from karpenter_tpu.rpc.service import FRAME_CHUNK_COL, _round_bytes

        return FRAME_CHUNK_COL + _round_bytes(round_no) + encode_chunk_columnar(delta)

    def test_codec_roundtrip(self):
        from karpenter_tpu.rpc.codec import (
            decode_chunk_columnar,
            encode_chunk_columnar,
        )

        delta = {
            "claims": [(3, ["u-1", "u-2"]), (9, ["u-3"]), (12, [])],
            "existing": [("u-4", "node-a"), ("u-5", "node-b")],
            "unsched": [("u-6", "no room at all"), ("u-7", "taints")],
        }
        assert decode_chunk_columnar(encode_chunk_columnar(delta)) == delta
        empty = {"claims": [], "existing": [], "unsched": []}
        assert decode_chunk_columnar(encode_chunk_columnar(empty)) == empty
        # non-ascii uids must survive the UTF-8 blob
        uni = {"claims": [(0, ["pöd-ü"])], "existing": [], "unsched": []}
        assert decode_chunk_columnar(encode_chunk_columnar(uni)) == uni

    def test_columnar_stitches_identically_to_legacy(self):
        from karpenter_tpu.rpc.client import StreamStitcher

        deltas = [
            {"claims": [(0, ["a"])], "existing": [("e1", "n1")], "unsched": []},
            {"claims": [(0, ["b"]), (1, ["c"])], "existing": [],
             "unsched": [("u1", "NoFit")]},
        ]
        legacy = StreamStitcher()
        for d in deltas:
            legacy.feed(
                TestStreamStitcher._chunk(
                    0, claims=d["claims"], exist=d["existing"], unsched=d["unsched"]
                )
            )
        legacy.feed(TestStreamStitcher._final())
        col = StreamStitcher()
        for d in deltas:
            col.feed(self._col_chunk(0, d))
        col.feed(TestStreamStitcher._final())
        assert col.tables() == legacy.tables()
        assert col.n_chunks == legacy.n_chunks == 2

    def test_stale_columnar_chunk_is_dropped(self):
        from karpenter_tpu.rpc.client import StreamStitcher

        s = StreamStitcher()
        s.feed(self._col_chunk(0, {"claims": [(0, ["old"])], "existing": [],
                                   "unsched": []}))
        s.feed(TestStreamStitcher._reset(1))
        s.feed(self._col_chunk(0, {"claims": [(0, ["stale"])], "existing": [],
                                   "unsched": []}))
        s.feed(self._col_chunk(1, {"claims": [(0, ["new"])], "existing": [],
                                   "unsched": []}))
        s.feed(TestStreamStitcher._final())
        assert s.tables()["claims"] == {0: ["new"]}
        assert s.n_stale == 1

    def test_server_is_columnar_only(self, monkeypatch):
        """ISSUE-8 satellite: the legacy-frame server branch is GONE —
        the opt-out knob and the protobuf chunk re-encode no longer
        exist, while the client keeps decoding the legacy tag (the
        downgrade direction an old server needs)."""
        import karpenter_tpu.rpc.service as service
        from karpenter_tpu.rpc.client import StreamStitcher

        monkeypatch.setenv("KTPU_RPC_COLUMNAR", "0")  # must be inert now
        assert not hasattr(service, "columnar_enabled")
        assert not hasattr(service, "_chunk_to_pb")
        # legacy frames synthesized by an old server still stitch
        s = StreamStitcher()
        s.feed(
            TestStreamStitcher._chunk(
                0, claims=[(0, ["legacy-uid"])], exist=[], unsched=[]
            )
        )
        s.feed(TestStreamStitcher._final())
        assert s.tables()["claims"] == {0: ["legacy-uid"]}


class TestPipelineThroughSocket:
    def test_kwok_provisioning_e2e(self, solver_server):
        """The full pipeline — batcher, provisioner, lifecycle, binding —
        with every solve crossing the wire."""
        clock = FakeClock()
        store = ObjectStore(clock)
        cloud = KwokCloudProvider(store, catalog=instance_types(32))
        opts = Options(solver_endpoint=solver_server)
        mgr = Manager(store, cloud, clock, options=opts)
        pool = default_pool()
        pool.spec.disruption.budgets = [Budget(nodes="100%")]
        store.create(ObjectStore.NODEPOOLS, pool)
        for i in range(12):
            store.create(ObjectStore.PODS, make_pod(f"p-{i}", cpu=1.0, memory="1Gi"))
        mgr.run_until_idle()
        from karpenter_tpu.rpc.client import RemoteScheduler as RS

        assert isinstance(mgr.provisioner._scheduler_cache[1], RS)
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        bound = sum(1 for p in store.pods() if p.spec.node_name)
        assert bound == 12
        assert len(store.nodes()) >= 1

    def test_consolidation_through_socket(self, solver_server):
        """Disruption what-ifs ride the remote Solve (whatif_batch declines
        remotely and methods fall back to sequential simulates)."""
        clock = FakeClock()
        store = ObjectStore(clock)
        cloud = KwokCloudProvider(store, catalog=instance_types(64))
        opts = Options(solver_endpoint=solver_server)
        mgr = Manager(store, cloud, clock, options=opts)
        pool = default_pool()
        pool.spec.disruption.consolidate_after_seconds = 0.0
        pool.spec.disruption.consolidation_policy = "WhenEmptyOrUnderutilized"
        pool.spec.disruption.budgets = [Budget(nodes="100%")]
        pool.spec.template.spec.requirements = [
            {
                "key": l.CAPACITY_TYPE_LABEL_KEY,
                "operator": "In",
                "values": [l.CAPACITY_TYPE_ON_DEMAND],
            }
        ]
        store.create(ObjectStore.NODEPOOLS, pool)
        for i in range(8):
            store.create(ObjectStore.PODS, make_pod(f"p-{i}", cpu=1.5, memory="1Gi"))
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        mgr.run_until_idle()
        cpu_before = sum(n.status.capacity["cpu"] for n in store.nodes())
        for pod in list(store.pods()):
            if pod.name not in ("p-0", "p-1"):
                pod.status.phase = "Succeeded"
                store.update(ObjectStore.PODS, pod)
                store.delete(ObjectStore.PODS, pod.name)
        mgr.run_until_idle()
        clock.step(60.0)
        executed = None
        for _ in range(8):
            cmd = mgr.run_disruption_once()
            executed = executed or cmd
            cloud.simulate_kubelet_ready()
            mgr.run_until_idle()
            KubeSchedulerSim(store, mgr.cluster).bind_pending()
            clock.step(20.0)
        assert executed is not None
        cpu_after = sum(n.status.capacity["cpu"] for n in store.nodes())
        assert cpu_after < cpu_before


class TestWhatIfOverRPC:
    def test_remote_whatif_matches_local_prefilter(self, solver_server):
        """The batched consolidation prefilter crosses the wire: remote
        verdicts == the in-process whatif_batch on the same cluster."""
        from karpenter_tpu.testing import build_bound_cluster, node_candidates

        clock, store, cloud, mgr = build_bound_cluster(n_pods=6, pod_cpu=2.0)
        prov = mgr.provisioner
        candidates = node_candidates(store)
        scenarios = [[c] for c in candidates]
        local = prov.simulate_batch(scenarios)
        assert local is not None

        # point the SAME provisioner at the remote solver and re-ask
        prov.solver_endpoint = solver_server
        prov._scheduler_cache = None
        remote_sched = prov._build_scheduler()
        from karpenter_tpu.rpc.client import RemoteScheduler as RS

        assert isinstance(remote_sched, RS)
        remote = prov.simulate_batch(scenarios)
        assert remote is not None, "remote WhatIf declined unexpectedly"
        assert remote == local

    def test_disruption_uses_remote_whatif(self, solver_server):
        """End-to-end: the disruption controller's batched prefilter rides
        the WhatIf RPC (no sequential-only fallback) and consolidation
        still shrinks the cluster."""
        from karpenter_tpu.controllers.manager import KubeSchedulerSim
        from karpenter_tpu.models.pod import make_pod

        clock = FakeClock()
        store = ObjectStore(clock)
        cloud = KwokCloudProvider(store, catalog=instance_types(64))
        opts = Options(solver_endpoint=solver_server)
        mgr = Manager(store, cloud, clock, options=opts)
        pool = default_pool()
        pool.spec.disruption.consolidate_after_seconds = 0.0
        pool.spec.disruption.consolidation_policy = "WhenEmptyOrUnderutilized"
        pool.spec.disruption.budgets = [Budget(nodes="100%")]
        pool.spec.template.spec.requirements = [
            {
                "key": l.CAPACITY_TYPE_LABEL_KEY,
                "operator": "In",
                "values": [l.CAPACITY_TYPE_ON_DEMAND],
            }
        ]
        store.create(ObjectStore.NODEPOOLS, pool)
        for i in range(8):
            store.create(ObjectStore.PODS, make_pod(f"p-{i}", cpu=1.5, memory="1Gi"))
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        mgr.run_until_idle()
        cpu_before = sum(n.status.capacity["cpu"] for n in store.nodes())
        for pod in list(store.pods()):
            if pod.name not in ("p-0", "p-1"):
                pod.status.phase = "Succeeded"
                store.update(ObjectStore.PODS, pod)
                store.delete(ObjectStore.PODS, pod.name)
        mgr.run_until_idle()
        clock.step(60.0)
        executed = None
        for _ in range(8):
            cmd = mgr.run_disruption_once()
            executed = executed or cmd
            cloud.simulate_kubelet_ready()
            mgr.run_until_idle()
            KubeSchedulerSim(store, mgr.cluster).bind_pending()
            clock.step(20.0)
        assert executed is not None
        assert sum(n.status.capacity["cpu"] for n in store.nodes()) < cpu_before


class TestMeshedRemoteSolver:
    def test_service_with_mesh_matches_local(self, monkeypatch):
        """The production multi-chip deployment: a solver service whose
        scheduler shards over the device mesh (KTPU_MESH_DEVICES env of
        the SOLVER process), answering a control plane over the wire with
        results bit-identical to a local single-device solve."""
        monkeypatch.setenv("KTPU_MESH_DEVICES", "8")
        server, addr = serve("127.0.0.1:0")
        try:
            templates = build_templates([(default_pool(), instance_types(32))])
            remote = RemoteScheduler(addr, templates)
            pods = diverse_pods(24)
            r = remote.solve(pods)
            s = TPUScheduler(templates).solve(pods)
            assert not r.unschedulable
            assert r.assignments == s.assignments
            assert len(r.claims) == len(s.claims)
            assert abs(r.total_price() - s.total_price()) < 1e-9
        finally:
            server.stop(0)


def test_rpc_durations_are_measured(solver_server):
    """The decorator-seam observability parity (cloudprovider/metrics):
    every RPC crossing records into the duration histogram."""
    from karpenter_tpu.utils.metrics import REGISTRY

    templates = build_templates([(default_pool(), instance_types(8))])
    remote = RemoteScheduler(solver_server, templates)
    remote.solve([make_pod("p", cpu=0.5)])
    exposition = REGISTRY.expose()
    assert 'karpenter_solver_rpc_duration_seconds' in exposition
    assert 'method="Configure"' in exposition
    # solves prefer the streaming path (SolveStream) and downgrade to the
    # unary Solve on older servers — either way the crossing is measured
    assert 'method="Solve"' in exposition or 'method="SolveStream"' in exposition


class TestDRAOverRPC:
    """VERDICT r4 #6: the DRAProblem snapshot crosses the Solve RPC and a
    DRA pod schedules identically via RemoteScheduler — allocation
    metadata included (rpc/dra_codec.py; allocator.go:231-296)."""

    def _dra_setup(self):
        from karpenter_tpu.cloudprovider.fake import new_instance_type
        from karpenter_tpu.scheduling.dra.integration import DRAProblem
        from karpenter_tpu.scheduling.dra.types import (
            Device,
            DeviceClass,
            DeviceRequest,
            ResourceClaim,
            ResourceSlice,
        )
        from karpenter_tpu.state.store import ObjectStore
        from karpenter_tpu.utils.clock import FakeClock

        small = new_instance_type("small-4x", cpu=4)
        accel = new_instance_type("accel-8x", cpu=8)
        accel.dra_slices = [
            ResourceSlice(
                driver="tpu.dra.x-k8s.io",
                pool="accel",
                potential=True,
                devices=[
                    Device(name=f"chip{i}", attributes={"kind": "tpu"})
                    for i in range(4)
                ],
            )
        ]
        templates = build_templates([(default_pool(), [small, accel])])
        store = ObjectStore(FakeClock())
        store.create(
            ObjectStore.DEVICE_CLASSES,
            DeviceClass(name="tpu", selectors=['device.attributes["kind"] == "tpu"']),
        )
        store.create(
            ObjectStore.RESOURCE_CLAIMS,
            ResourceClaim(
                name="train",
                requests=[DeviceRequest(name="r0", device_class="tpu", count=2)],
            ),
        )
        pods = [make_pod("worker", cpu=1.0, resource_claims=["train"])]

        def problem_factory():
            # a solve commits into its problem's allocator state, so each
            # engine gets a fresh build over the SAME pods/store
            problem = DRAProblem.build(store, pods, {"default": [small, accel]})
            assert problem is not None
            return problem

        return templates, pods, problem_factory

    def test_dra_pod_schedules_identically_over_the_wire(self, solver_server):
        templates, pods, make_problem = self._dra_setup()
        remote = RemoteScheduler(solver_server, templates)
        local = TPUScheduler(templates)
        r = remote.solve(pods, dra_problem=make_problem())
        s = local.solve(pods, dra_problem=make_problem())
        assert not r.unschedulable and not s.unschedulable
        assert len(r.claims) == len(s.claims) == 1
        assert [it.name for it in r.claims[0].instance_types] == [
            it.name for it in s.claims[0].instance_types
        ]
        assert [it.name for it in r.claims[0].instance_types] == ["accel-8x"]
        # the allocation metadata the deviceallocation controller consumes
        # round-trips: same claim keys, nodeclaim ids, devices
        assert r.dra is not None and s.dra is not None
        rm = r.dra.allocator.claim_allocation_metadata
        sm = s.dra.allocator.claim_allocation_metadata
        assert sorted(rm) == sorted(sm)
        for key in rm:
            a, b = rm[key], sm[key]
            assert a.nodeclaim_id == b.nodeclaim_id
            assert a.used_template_devices == b.used_template_devices
            assert {
                it: [(tuple(r_.device_id), tuple(r_.request_name)) for r_ in rs]
                for it, rs in a.devices.items()
            } == {
                it: [(tuple(r_.device_id), tuple(r_.request_name)) for r_ in rs]
                for it, rs in b.devices.items()
            }
            assert str(a.total_requirements) == str(b.total_requirements)

    def test_dra_problem_codec_roundtrip(self):
        from karpenter_tpu.rpc.dra_codec import decode_dra_problem, encode_dra_problem

        templates, _pods, make_problem = self._dra_setup()
        problem = make_problem()
        data = encode_dra_problem(problem)
        back = decode_dra_problem(data, templates)
        assert encode_dra_problem(back) == data  # canonical: fixed point
        assert sorted(back.claims_by_pod) == sorted(problem.claims_by_pod)
        assert {s.pool for s in back.in_cluster_slices} == {
            s.pool for s in problem.in_cluster_slices
        }
        assert back.device_classes.keys() == problem.device_classes.keys()
