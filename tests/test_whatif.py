"""Batched consolidation what-if tests.

The vmapped scenario batch (ops/solver.py solve_whatif +
TPUScheduler.whatif_batch) must agree with the sequential simulate path on
feasibility and replacement count — the tensorized twin of the reference's
per-candidate SimulateScheduling loop (multinodeconsolidation.go:136-183).
"""

import pytest

from karpenter_tpu.cloudprovider.fake import new_instance_type
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock


from karpenter_tpu.testing import FakeCandidate, build_bound_cluster, node_candidates


def build_cluster(n_small_pods=6, pod_cpu=2.0):
    """Shared fixture: several 4-cpu nodes, each carrying bound pods."""
    return build_bound_cluster(n_pods=n_small_pods, pod_cpu=pod_cpu)


def sequential_signal(provisioner, candidates):
    """The ground truth the batch must reproduce: sequential simulate's
    (feasible, n_new_claims)."""
    excluded = {c.name for c in candidates}
    extra = [p for c in candidates for p in c.reschedulable_pods]
    result = provisioner.simulate(excluded, extra)
    if result is None:
        return None
    extra_uids = {p.uid for p in extra}
    unscheduled = {p.uid for p, _ in result.unschedulable} & extra_uids
    return (not unscheduled, len(result.claims))


class TestWhatIfBatch:
    def test_differential_vs_sequential(self):
        clock, store, cloud, mgr = build_cluster()
        candidates = node_candidates(store)
        assert len(candidates) >= 3
        # all prefixes plus each single candidate — the exact scenario mix
        # the consolidation methods submit
        scenarios = [candidates[:n] for n in range(1, len(candidates) + 1)]
        scenarios += [[c] for c in candidates]
        signals = mgr.provisioner.simulate_batch(scenarios)
        assert signals is not None
        assert len(signals) == len(scenarios)
        for scen, got in zip(scenarios, signals):
            want = sequential_signal(mgr.provisioner, scen)
            assert want is not None
            assert got == want, f"scenario {[c.name for c in scen]}: batch {got} vs sequential {want}"

    def test_infeasible_scenario_detected(self):
        # Remove every node at once with a catalog too small to absorb all
        # pods onto one replacement: the all-nodes scenario still succeeds
        # (new claims open), but feasibility and claim count must agree
        # with the sequential path — including the n_new > 1 signal the
        # consolidation filter rejects.
        clock, store, cloud, mgr = build_cluster(n_small_pods=8)
        candidates = node_candidates(store)
        scenarios = [candidates]
        signals = mgr.provisioner.simulate_batch(scenarios)
        want = sequential_signal(mgr.provisioner, candidates)
        assert signals[0] == want

    def test_csi_attach_limits_ride_the_batch(self):
        # VERDICT r4 #5: CSI-limit scenarios used to decline to sequential
        # simulation; now displaced pods re-attach their PVC columns inside
        # the batched solve (volumeusage.go:201-208 x
        # multinodeconsolidation.go:136-183). Verdicts must match the
        # sequential path exactly.
        from karpenter_tpu.scheduling.hostports import (
            PersistentVolumeClaim,
            StorageClass,
        )

        clock, store, cloud, mgr = build_cluster(n_small_pods=4)
        sc = StorageClass(provisioner="ebs.csi")
        sc.metadata.name = "standard"
        store.create(ObjectStore.STORAGE_CLASSES, sc)
        for i, p in enumerate(sorted(store.pods(), key=lambda p: p.name)):
            claim = PersistentVolumeClaim(storage_class="standard")
            claim.metadata.name = f"vol-{i}"
            store.create(ObjectStore.PVCS, claim)
            p.spec.pvc_names = [f"vol-{i}"]
        # every node publishes a TIGHT attach limit, so consolidation onto
        # a survivor is capacity-feasible but attach-infeasible beyond it
        for node in store.nodes():
            node.spec.csi_drivers = {"ebs.csi": 2}
        candidates = node_candidates(store)
        assert len(candidates) >= 3
        scenarios = [candidates[:n] for n in range(1, len(candidates) + 1)]
        scenarios += [[c] for c in candidates]
        signals = mgr.provisioner.simulate_batch(scenarios)
        assert signals is not None, "CSI-limit scenarios must not decline"
        for scen, got in zip(scenarios, signals):
            want = sequential_signal(mgr.provisioner, scen)
            assert want is not None
            assert got == want, (
                f"scenario {[c.name for c in scen]}: batch {got} vs sequential {want}"
            )

    def test_anti_affinity_bound_pods_fall_back_to_sequential(self):
        # Inverse anti-affinity groups derive from bound pods, which differ
        # per exclusion set; the shared batch encoding can't represent that,
        # so simulate_batch must return None (sequential fallback), never a
        # misaligned answer.
        from karpenter_tpu.models.pod import PodAffinityTerm

        clock = FakeClock()
        store = ObjectStore(clock)
        catalog = [new_instance_type("n-4x", cpu=4), new_instance_type("n-8x", cpu=8)]
        cloud = KwokCloudProvider(store, catalog=catalog)
        mgr = Manager(store, cloud, clock)
        store.create(ObjectStore.NODEPOOLS, NodePool())
        for i in range(3):
            pod = make_pod(
                f"aa{i}",
                cpu=2.0,
                node_selector={l.LABEL_INSTANCE_TYPE: "n-4x"},
            )
            pod.metadata.labels["app"] = "aa"
            pod.spec.pod_anti_affinity = [
                PodAffinityTerm(topology_key=l.LABEL_HOSTNAME, label_selector={"app": "aa"})
            ]
            store.create(ObjectStore.PODS, pod)
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        mgr.run_until_idle()
        candidates = node_candidates(store)
        assert len(candidates) >= 2
        signals = mgr.provisioner.simulate_batch([[c] for c in candidates])
        if signals is not None:
            # if the encoding could align, it must still match sequential
            for c, got in zip(candidates, signals):
                assert got == sequential_signal(mgr.provisioner, [c])

    def test_multinode_consolidation_uses_batch(self, monkeypatch):
        # The disruption controller's multi-node pass should produce the
        # same command with the batch prefilter as with pure binary search,
        # while issuing at most one batch call.
        def build_underutilized():
            """6 nodes at 3x 1-cpu pods each, then shed 2 pods per node:
            displaced pods fit in siblings' free capacity, so a multi-node
            delete prefix is genuinely consolidatable."""
            clock, store, cloud, mgr = build_cluster(n_small_pods=18, pod_cpu=1.0)
            keep_first = set()
            doomed = []
            for p in store.pods():
                if p.spec.node_name not in keep_first:
                    keep_first.add(p.spec.node_name)
                else:
                    doomed.append(p.name)
            for name in doomed:
                pod = store.get(ObjectStore.PODS, name)
                pod.status.phase = "Succeeded"
                store.update(ObjectStore.PODS, pod)
                store.delete(ObjectStore.PODS, name)
            mgr.run_until_idle()
            # permissive budget so multi-node consolidation can disrupt
            # several nodes (the default 10% caps a 6-node cluster at 1)
            from karpenter_tpu.models.nodepool import Budget

            pool = store.get(ObjectStore.NODEPOOLS, "default")
            pool.spec.disruption.budgets = [Budget(nodes="100%")]
            store.update(ObjectStore.NODEPOOLS, pool)
            return clock, store, cloud, mgr

        clock, store, cloud, mgr = build_underutilized()
        calls = {"batch": 0, "seq": 0}
        orig_batch = mgr.provisioner.simulate_batch
        orig_seq = mgr.provisioner.simulate

        def counting_batch(scenarios):
            calls["batch"] += 1
            return orig_batch(scenarios)

        def counting_seq(excluded, extra, deadline=None):
            calls["seq"] += 1
            return orig_seq(excluded, extra, deadline=deadline)

        monkeypatch.setattr(mgr.provisioner, "simulate_batch", counting_batch)
        monkeypatch.setattr(mgr.provisioner, "simulate", counting_seq)

        def drive(mgr_, clock_, cloud_, store_):
            """Poll until a command executes (staging + 15s validation)."""
            clock_.step(60.0)
            executed = None
            for _ in range(6):
                cmd = mgr_.run_disruption_once()
                executed = executed or cmd
                cloud_.simulate_kubelet_ready()
                mgr_.run_until_idle()
                KubeSchedulerSim(store_, mgr_.cluster).bind_pending()
                clock_.step(20.0)
                if executed is not None:
                    break
            return executed

        cmd = drive(mgr, clock, cloud, store)
        assert calls["batch"] >= 1, "the batch prefilter never ran"
        assert cmd is not None, "no consolidation command produced"

        # parity: an identical cluster with the batch disabled (pure binary
        # search) must reach the same decision
        clock2, store2, cloud2, mgr2 = build_underutilized()
        mgr2.provisioner.simulate_batch = lambda scenarios: None
        cmd2 = drive(mgr2, clock2, cloud2, store2)
        assert cmd2 is not None
        assert cmd.reason == cmd2.reason
        assert sorted(c.name for c in cmd.candidates) == sorted(
            c.name for c in cmd2.candidates
        )
        assert len(cmd.replacements) == len(cmd2.replacements)
