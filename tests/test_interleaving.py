"""Reconciler-interleaving scenarios (VERDICT r3 weak #7): controllers
racing each other on the shared store/cluster state, asserting the
invariants the reference's ordering guards protect — no double launches,
no stranded pods, clean rollbacks (queue.go:342-349, helpers.go:133-152,
garbagecollection/controller.go:64-133)."""

import pytest

from karpenter_tpu.cloudprovider import errors
from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import Budget, NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.models.taints import DISRUPTED_NO_SCHEDULE_TAINT
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock


def build_env(catalog_size=64, consolidate_after=0.0):
    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=instance_types(catalog_size))
    mgr = Manager(store, cloud, clock)
    pool = NodePool()
    pool.metadata.name = "default"
    pool.spec.disruption.consolidate_after_seconds = consolidate_after
    pool.spec.disruption.consolidation_policy = "WhenEmptyOrUnderutilized"
    pool.spec.disruption.budgets = [Budget(nodes="100%")]
    pool.spec.template.spec.requirements = [
        {
            "key": l.CAPACITY_TYPE_LABEL_KEY,
            "operator": "In",
            "values": [l.CAPACITY_TYPE_ON_DEMAND],
        }
    ]
    store.create(ObjectStore.NODEPOOLS, pool)
    return clock, store, cloud, mgr


def provision(mgr, store, cloud, pods):
    for p in pods:
        store.create(ObjectStore.PODS, p)
    mgr.run_until_idle()
    cloud.simulate_kubelet_ready()
    mgr.run_until_idle()
    KubeSchedulerSim(store, mgr.cluster).bind_pending()
    mgr.run_until_idle()


def settle(mgr, store, cloud, rounds=4):
    for _ in range(rounds):
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()


def shrink(store, mgr, keep):
    for pod in list(store.pods()):
        if pod.name not in keep:
            pod.status.phase = "Succeeded"
            store.update(ObjectStore.PODS, pod)
            store.delete(ObjectStore.PODS, pod.name)
    mgr.run_until_idle()


def bound_pods(store):
    return {p.name: p.spec.node_name for p in store.pods() if p.spec.node_name}


class TestDisruptionRacesProvisioning:
    def test_pods_arriving_in_validation_window_never_strand(self):
        """Fresh pods bind onto a candidate node inside the 15s validation
        window. The re-simulation counts them as reschedulable (the command
        may legitimately proceed — validation.go re-sims with the CURRENT
        pods), but no pod may end up permanently stranded: evicted
        newcomers re-provision, at worst after their optimistic nomination
        window (cluster.go nomination TTL) expires."""
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod(f"p-{i}", cpu=1.5) for i in range(6)])
        shrink(store, mgr, keep={"p-0"})
        clock.step(60.0)
        assert mgr.run_disruption_once() is None  # staged, not executed
        # race: a burst of new pods binds onto the doomed capacity
        target = store.nodes()[0].name
        for i in range(3):
            newcomer = make_pod(f"late-{i}", cpu=1.0)
            newcomer.spec.node_name = target
            newcomer.status.phase = "Running"
            store.create(ObjectStore.PODS, newcomer)
        mgr.run_until_idle()
        clock.step(16.0)
        for _ in range(4):
            mgr.run_disruption_once()
            settle(mgr, store, cloud, rounds=1)
            clock.step(16.0)
        # let optimistic nominations to full nodes expire, then re-settle
        clock.step(121.0)
        settle(mgr, store, cloud, rounds=4)
        for i in range(3):
            pod = next(p for p in store.pods() if p.name == f"late-{i}")
            assert pod.spec.node_name, "pod stranded by the disruption race"
            assert store.get(ObjectStore.NODES, pod.spec.node_name) is not None

    def test_provisioning_during_drain_excludes_draining_node(self):
        """Pending pods arriving while a node drains must not be nominated
        to it (the disrupted taint + marked_for_deletion exclusion)."""
        clock, store, cloud, mgr = build_env(catalog_size=16)
        provision(mgr, store, cloud, [make_pod("p-0", cpu=1.0)])
        node = store.nodes()[0]
        node.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
        store.update(ObjectStore.NODES, node)
        mgr.cluster.mark_for_deletion(node.spec.provider_id)
        store.create(ObjectStore.PODS, make_pod("late", cpu=0.25))
        settle(mgr, store, cloud)
        late = next(p for p in store.pods() if p.name == "late")
        assert late.spec.node_name and late.spec.node_name != node.name


class TestLaunchFailureMidConsolidation:
    def test_replacement_launch_failure_rolls_back(self):
        """The replacement claim fails to launch (insufficient capacity):
        the command rolls back — candidates untainted, nodes alive, bound
        pods untouched (queue.go:186-257 waitOrTerminate failure path)."""
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod(f"p-{i}", cpu=1.5) for i in range(6)])
        shrink(store, mgr, keep={"p-0", "p-1"})
        before = bound_pods(store)
        n_nodes = len(store.nodes())
        orig_create = cloud.unwrapped.create if hasattr(cloud, "unwrapped") else cloud.create

        def failing_create(claim):
            raise errors.InsufficientCapacityError("zone exhausted (injected)")

        cloud.create = failing_create
        try:
            clock.step(60.0)
            for _ in range(5):
                mgr.run_disruption_once()
                clock.step(16.0)
        finally:
            cloud.create = orig_create
        # rollback: original nodes and bindings intact, no disrupted taints
        assert len(store.nodes()) == n_nodes
        assert bound_pods(store) == before
        for node in store.nodes():
            assert not any(
                t.match(DISRUPTED_NO_SCHEDULE_TAINT) for t in node.spec.taints
            ), "rollback left the disrupted taint"
        assert not mgr.disruption.queue.in_flight


class TestGCDuringDrain:
    def test_instance_vanishes_mid_termination(self):
        """The cloud instance disappears while the node drains: GC
        reconciles cloud truth, the claim+node go away, and the drained
        pods re-provision instead of stranding
        (garbagecollection/controller.go:64-133)."""
        clock, store, cloud, mgr = build_env(catalog_size=16)
        provision(mgr, store, cloud, [make_pod("p-0", cpu=1.0)])
        claim = store.nodeclaims()[0]
        node = store.nodes()[0]
        # drain starts (graceful delete -> taint + evictions)
        store.delete(ObjectStore.NODECLAIMS, claim.name)
        mgr.run_until_idle()
        # the instance dies behind the controller's back mid-drain
        node.metadata.finalizers = []
        store.delete(ObjectStore.NODES, node.name)
        mgr.run_maintenance()
        settle(mgr, store, cloud)
        assert store.get(ObjectStore.NODECLAIMS, claim.name) is None
        assert store.get(ObjectStore.NODES, node.name) is None
        # the displaced pod re-provisioned onto fresh capacity
        pod = next(p for p in store.pods() if p.name == "p-0")
        assert pod.spec.node_name and pod.spec.node_name != node.name


class TestExpirationRacesDisruption:
    def test_candidate_expires_while_command_in_flight(self):
        """A consolidation command's candidate claim hits expireAfter and
        is force-deleted while the replacement is still coming up: the
        queue must complete or roll back without crashing, and no pod may
        strand (expiration/controller.go:58-107 is forceful)."""
        clock, store, cloud, mgr = build_env()
        pool = store.get(ObjectStore.NODEPOOLS, "default")
        pool.spec.template.spec.expire_after_seconds = 300.0
        store.update(ObjectStore.NODEPOOLS, pool)
        provision(mgr, store, cloud, [make_pod(f"p-{i}", cpu=1.5) for i in range(6)])
        shrink(store, mgr, keep={"p-0", "p-1"})
        clock.step(60.0)
        mgr.run_disruption_once()  # stages
        clock.step(16.0)
        mgr.run_disruption_once()  # executes: replacements created
        # expiry fires for the original claims mid-flight
        clock.step(300.0)
        mgr.run_maintenance()
        for _ in range(6):
            mgr.run_disruption_once()  # drains the orchestration queue
            settle(mgr, store, cloud, rounds=1)
        # no stranded pods, no leaked in-flight commands
        survivors = [p for p in store.pods() if p.name in ("p-0", "p-1")]
        assert len(survivors) == 2
        for p in survivors:
            assert p.spec.node_name, f"{p.name} stranded"
            assert store.get(ObjectStore.NODES, p.spec.node_name) is not None
        assert not mgr.disruption.queue.in_flight


class TestRepairRacesWorkload:
    def test_unhealthy_node_force_replaced(self):
        """Node goes unhealthy while running pods; the repair controller
        force-deletes after the toleration window and the pods re-provision
        (health/controller.go:110-215)."""
        from karpenter_tpu.cloudprovider.spi import RepairPolicy

        clock, store, cloud, mgr = build_env(catalog_size=16)
        cloud.repair_policies = lambda: [
            RepairPolicy(condition_type="Ready", condition_status="False",
                         toleration_seconds=30.0)
        ]
        provision(mgr, store, cloud, [make_pod("p-0", cpu=1.0)])
        node = store.nodes()[0]
        mgr.health.observe(node.name, "Ready", "False")  # kubelet feed
        clock.step(60.0)
        mgr.run_maintenance()
        settle(mgr, store, cloud, rounds=5)
        pod = next(p for p in store.pods() if p.name == "p-0")
        assert pod.spec.node_name and pod.spec.node_name != node.name

    def test_drift_marked_before_registration_not_disrupted(self):
        """Pool spec changes while a claim is in flight (launched, node not
        yet registered): drift may mark the claim, but disruption must not
        act on an unregistered candidate; once registered the node cycles
        cleanly (nodeclaim/disruption drift + candidate validation)."""
        clock, store, cloud, mgr = build_env()
        for p in [make_pod("p-0", cpu=1.0)]:
            store.create(ObjectStore.PODS, p)
        mgr.run_until_idle()  # claim launched, node NOT ready yet
        pool = store.get(ObjectStore.NODEPOOLS, "default")
        pool.spec.template.labels["team"] = "changed"
        store.update(ObjectStore.NODEPOOLS, pool)
        mgr.mark_drift()
        # disruption poll with an unregistered candidate: nothing happens
        assert mgr.run_disruption_once() is None
        assert len(store.nodeclaims()) == 1
        # after registration, the drifted node is replaced without losing p-0
        settle(mgr, store, cloud)
        clock.step(30.0)
        executed = None
        for _ in range(10):
            executed = executed or mgr.run_disruption_once()
            settle(mgr, store, cloud, rounds=1)
            clock.step(16.0)
            if executed is not None and not mgr.disruption.queue.in_flight:
                break
        assert executed is not None and executed.reason == "Drifted"
        # keep polling until the orchestration queue fully drains
        for _ in range(6):
            if not mgr.disruption.queue.in_flight:
                break
            mgr.run_disruption_once()
            settle(mgr, store, cloud, rounds=1)
            clock.step(16.0)
        settle(mgr, store, cloud, rounds=4)
        pod = next(p for p in store.pods() if p.name == "p-0")
        assert pod.spec.node_name
        node = store.get(ObjectStore.NODES, pod.spec.node_name)
        assert node is not None
        assert node.metadata.labels.get("team") == "changed"


class TestDaemonSetArrival:
    def test_new_daemonset_provokes_solve_pass(self):
        """The DAEMONSETS watch (state/informer/daemonset.go analog): a
        daemonset created while pods sit pending must trigger the batcher
        and produce a fresh solve pass — without any pod/pool event."""
        from karpenter_tpu.models.daemonset import DaemonSet
        from karpenter_tpu.models.pod import PodSpec
        from karpenter_tpu.utils import resources as res

        clock, store, cloud, mgr = build_env(catalog_size=8)
        # a pod no 1-cpu shape can hold: it stays provisionable while
        # every solve pass comes up empty and the batch window resets
        store.create(ObjectStore.PODS, make_pod("wedged", cpu=64.0))
        mgr.run_until_idle()
        assert not store.nodeclaims()
        assert not mgr.batcher.pending, "batch window should have drained"

        passes = []
        original = mgr.provisioner.reconcile
        mgr.provisioner.reconcile = lambda *a, **kw: passes.append(1) or original(*a, **kw)

        # an unrelated daemonset arriving re-triggers provisioning: the
        # overhead groups changed, so the pending pod deserves a fresh pass
        ds = DaemonSet()
        ds.metadata.name = "late-agent"
        ds.pod_template = PodSpec(requests={res.CPU: 0.1})
        store.create(ObjectStore.DAEMONSETS, ds)
        assert mgr.batcher.pending, "daemonset event did not trigger the batcher"
        mgr.run_until_idle()
        assert passes, "no solve pass followed the daemonset event"

    def test_daemonset_without_pending_pods_is_quiet(self):
        """No provisionable pods -> a daemonset event must NOT open a batch
        window (the informer triggers work, it doesn't invent it)."""
        from karpenter_tpu.models.daemonset import DaemonSet
        from karpenter_tpu.models.pod import PodSpec
        from karpenter_tpu.utils import resources as res

        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod("p", cpu=0.5)])
        assert not mgr.batcher.pending
        ds = DaemonSet()
        ds.metadata.name = "quiet-agent"
        ds.pod_template = PodSpec(requests={res.CPU: 0.1})
        store.create(ObjectStore.DAEMONSETS, ds)
        assert not mgr.batcher.pending
