"""The expectations DSL (pkg/test/expectations analog) exercised on real
scenarios, plus the in-process resource-budget suite
(test/suites/performance/thresholds.go:28-43 analog)."""

from karpenter_tpu import testing as T
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod


class TestExpectations:
    def test_expect_provisioned_returns_nodes(self):
        e = T.env()
        e.nodepool()
        nodes = T.expect_provisioned(e, *e.pods(5, cpu=1.0))
        assert len(nodes) == 5
        assert all(n.status.ready for n in nodes)
        T.expect_metric_at_least(
            "karpenter_nodeclaims_created_total",
            1.0,
            reason="provisioning",
            nodepool="default",
            min_values_relaxed="false",
        )

    def test_expect_not_provisioned(self):
        e = T.env()
        e.nodepool()
        impossible = make_pod("huge", cpu=100000.0)
        T.expect_not_provisioned(e, impossible)

    def test_expect_skew_zonal_spread(self):
        e = T.env()
        e.nodepool()
        pods = []
        for i in range(9):
            p = make_pod(f"s-{i}", cpu=1.0)
            p.metadata.labels = {"spread": "zonal"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    label_selector={"spread": "zonal"},
                )
            ]
            pods.append(p)
        T.expect_provisioned(e, *pods)
        counts = T.expect_max_skew(
            e, l.LABEL_TOPOLOGY_ZONE, {"spread": "zonal"}, max_skew=1
        )
        assert sum(counts.values()) == 9

    def test_expect_metric_failure_raises(self):
        import pytest

        with pytest.raises(AssertionError):
            T.expect_metric("karpenter_nodes_created_total", -1.0, nodepool="nope")


class TestResourceBudgets:
    """The e2e performance suite's controller memory/CPU thresholds,
    in-process: solves must fit a bounded footprint and repeated solves
    must not leak (basic_test.go:50-59's <260MB controller analog, scaled
    for the JAX runtime this process carries)."""

    def test_solve_path_memory_and_cpu_budget(self):
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.controllers.provisioning import (
            TPUScheduler,
            build_templates,
        )
        from karpenter_tpu.models.nodepool import NodePool

        pool = NodePool()
        pool.metadata.name = "default"
        templates = build_templates([(pool, instance_types(100))])
        pods = [make_pod(f"p-{i}", cpu=0.5) for i in range(512)]
        sched = TPUScheduler(templates, pod_pad=512, max_claims=64)
        sched.solve(pods)  # cold: compile + caches (unbudgeted)
        budget = {}
        with T.measure_resources(budget):
            for _ in range(3):
                result = sched.solve(pods)
        assert not result.unschedulable
        # warm solves: bounded growth and bounded host CPU
        assert budget["rss_mb"] < 256, f"warm-solve RSS grew {budget['rss_mb']:.0f}MB"
        assert budget["cpu_s"] < 30.0, f"warm solves burned {budget['cpu_s']:.1f}s CPU"

    def test_repeated_solves_do_not_leak(self):
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.controllers.provisioning import (
            TPUScheduler,
            build_templates,
        )
        from karpenter_tpu.models.nodepool import NodePool

        pool = NodePool()
        pool.metadata.name = "default"
        templates = build_templates([(pool, instance_types(50))])
        pods = [make_pod(f"p-{i}", cpu=0.5) for i in range(128)]
        sched = TPUScheduler(templates, pod_pad=128, max_claims=32)
        for _ in range(3):
            sched.solve(pods)  # settle caches
        before = T.current_rss_mb()
        for _ in range(10):
            sched.solve(pods)
        growth = T.current_rss_mb() - before
        assert growth < 64, f"10 warm solves leaked {growth:.0f}MB"
