"""CSI volume attach limits + combinatorial volume-topology alternatives.

Mirrors reference pkg/scheduling/volumeusage.go behavior (distinct-PVC
per-driver limits on existing nodes) and volumetopology.go's alternatives
loop (try zone B after zone A fails)."""

import pytest

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.provisioning import (
    HostScheduler,
    TPUScheduler,
    build_templates,
)
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.scheduling.hostports import PersistentVolumeClaim, StorageClass
from karpenter_tpu.scheduling.requirements import Operator, Requirement, Requirements
from karpenter_tpu.scheduling.volumes import (
    VolumeUsage,
    get_volumes,
    merge_alternatives,
    vol_union,
    volume_requirement_alternatives,
)
from karpenter_tpu.utils import resources as res

from tests.test_solver import default_pool, make_existing


def pvc(name, storage_class="standard", bound_zone=None, driver=None):
    p = PersistentVolumeClaim(storage_class=storage_class, bound_zone=bound_zone, driver=driver)
    p.metadata.name = name
    return p


def sc(name, zones=None, provisioner="", allowed_topologies=None):
    s = StorageClass(zones=zones, provisioner=provisioner, allowed_topologies=allowed_topologies)
    s.metadata.name = name
    return s


class TestVolumeUsage:
    def test_union_dedups_shared_pvcs(self):
        a = {"ebs.csi.aws.com": {"pvc-1"}}
        b = {"ebs.csi.aws.com": {"pvc-1", "pvc-2"}}
        assert vol_union(a, b) == {"ebs.csi.aws.com": {"pvc-1", "pvc-2"}}

    def test_exceeds_limits(self):
        u = VolumeUsage()
        u.add_limit("d", 2)
        u.add("pod-1", {"d": {"v1", "v2"}})
        assert u.exceeds_limits({"d": {"v3"}}) is not None
        # a shared pvc doesn't count twice
        assert u.exceeds_limits({"d": {"v1"}}) is None
        # unlimited driver never blocks
        assert u.exceeds_limits({"other": {"x", "y", "z"}}) is None

    def test_delete_pod_rebuilds(self):
        u = VolumeUsage()
        u.add("pod-1", {"d": {"v1"}})
        u.add("pod-2", {"d": {"v1", "v2"}})
        u.delete_pod("pod-2")
        assert u.volumes == {"d": {"v1"}}
        u.delete_pod("pod-1")
        assert u.volumes == {}

    def test_copy_is_deep(self):
        u = VolumeUsage()
        u.add_limit("d", 1)
        u.add("pod-1", {"d": {"v1"}})
        c = u.copy()
        c.add("pod-2", {"d": {"v2"}})
        assert u.volumes == {"d": {"v1"}}
        assert c.exceeds_limits({}) is not None

    def test_get_volumes_driver_resolution(self):
        pod = make_pod("p")
        pod.spec.pvc_names = ["a", "b", "c", "missing"]
        pvcs = {
            # bound PV's CSI driver wins (volumeusage.go:168-180)
            "a": pvc("a", storage_class="zonal", driver="pv.csi"),
            # unbound resolves via the class provisioner
            "b": pvc("b", storage_class="zonal"),
            # class without provisioner -> untracked (non-CSI)
            "c": pvc("c", storage_class="plain"),
        }
        classes = {"zonal": sc("zonal", provisioner="sc.csi"), "plain": sc("plain")}
        vols = get_volumes(pod, pvcs, classes)
        assert vols == {"pv.csi": {"a"}, "sc.csi": {"b"}}


class TestAttachLimits:
    def test_limit_forces_second_node(self):
        """Existing node takes one PVC-bearing pod, the second pod's volume
        would exceed the driver limit -> a new claim opens."""
        templates = build_templates([(default_pool(), instance_types(8))])
        pods = []
        pod_volumes = {}
        for i in range(2):
            p = make_pod(f"p-{i}", cpu=0.25)
            p.spec.pvc_names = [f"vol-{i}"]
            pods.append(p)
            pod_volumes[p.uid] = {"ebs": {f"vol-{i}"}}
        node = make_existing("node-a", 0, cpu_avail=8.0)
        usage = VolumeUsage()
        usage.add_limit("ebs", 1)
        node.volume_usage = usage
        result = HostScheduler(
            templates, existing_nodes=[node], pod_volumes=pod_volumes
        ).solve(pods)
        assert len(result.existing_assignments) == 1
        assert len(result.claims) == 1
        assert not result.unschedulable

    def test_shared_pvc_dedups(self):
        """Two pods mounting the SAME pvc consume one attachment."""
        templates = build_templates([(default_pool(), instance_types(8))])
        pods = []
        pod_volumes = {}
        for i in range(2):
            p = make_pod(f"p-{i}", cpu=0.25)
            p.spec.pvc_names = ["shared"]
            pods.append(p)
            pod_volumes[p.uid] = {"ebs": {"shared"}}
        node = make_existing("node-a", 0, cpu_avail=8.0)
        usage = VolumeUsage()
        usage.add_limit("ebs", 1)
        node.volume_usage = usage
        result = HostScheduler(
            templates, existing_nodes=[node], pod_volumes=pod_volumes
        ).solve(pods)
        assert len(result.existing_assignments) == 2
        assert not result.claims

    def test_unlimited_node_unaffected(self):
        templates = build_templates([(default_pool(), instance_types(8))])
        pods = []
        pod_volumes = {}
        for i in range(3):
            p = make_pod(f"p-{i}", cpu=0.25)
            p.spec.pvc_names = [f"vol-{i}"]
            pods.append(p)
            pod_volumes[p.uid] = {"ebs": {f"vol-{i}"}}
        node = make_existing("node-a", 0, cpu_avail=8.0)  # no volume_usage
        result = HostScheduler(
            templates, existing_nodes=[node], pod_volumes=pod_volumes
        ).solve(pods)
        assert len(result.existing_assignments) == 3

    def _parity(self, templates, pods, nodes_factory, pod_volumes):
        """Device vs host on an attach-limited problem — the device must
        solve it IN TENSOR (no host fallback) with identical results."""
        from karpenter_tpu.utils.metrics import SOLVER_HOST_FALLBACKS

        before = SOLVER_HOST_FALLBACKS.get(reason="volume_limits")
        host = HostScheduler(
            templates, existing_nodes=nodes_factory(), pod_volumes=pod_volumes
        ).solve(list(pods))
        tpu = TPUScheduler(templates).solve(
            pods, existing_nodes=nodes_factory(), pod_volumes=pod_volumes
        )
        assert SOLVER_HOST_FALLBACKS.get(reason="volume_limits") == before, (
            "attach limits fell back to the host"
        )
        assert tpu.existing_assignments == host.existing_assignments
        assert tpu.assignments == host.assignments
        assert len(tpu.claims) == len(host.claims)
        assert [p.uid for p, _ in tpu.unschedulable] == [
            p.uid for p, _ in host.unschedulable
        ]
        return tpu, host

    def test_device_solves_limits_in_tensor(self):
        """VERDICT r3 #9: distinct-PVC attach caps ride the device scan
        (per-driver popcounts over a (driver, pvc) column vocabulary) —
        SOLVER_HOST_FALLBACKS{volume_limits} stays flat."""
        templates = build_templates([(default_pool(), instance_types(8))])
        pods = []
        pod_volumes = {}
        for i in range(2):
            p = make_pod(f"p-{i}", cpu=0.25)
            p.spec.pvc_names = [f"vol-{i}"]
            pods.append(p)
            pod_volumes[p.uid] = {"ebs": {f"vol-{i}"}}

        def nodes():
            n = make_existing("node-a", 0, cpu_avail=8.0)
            u = VolumeUsage()
            u.add_limit("ebs", 1)
            n.volume_usage = u
            return [n]

        tpu, host = self._parity(templates, pods, nodes, pod_volumes)
        assert len(tpu.claims) == 1  # second pod forced onto a new claim

    def test_device_shared_pvc_dedups(self):
        """Pods of one kind share PVCs: the union counts each once, so a
        whole batch lands on a 1-attachment node (fill path)."""
        templates = build_templates([(default_pool(), instance_types(8))])
        pods = []
        pod_volumes = {}
        for i in range(4):
            p = make_pod(f"p-{i}", cpu=0.25)
            p.spec.pvc_names = ["shared"]
            pods.append(p)
            pod_volumes[p.uid] = {"ebs": {"shared"}}

        def nodes():
            n = make_existing("node-a", 0, cpu_avail=8.0)
            u = VolumeUsage()
            u.add_limit("ebs", 1)
            n.volume_usage = u
            return [n]

        tpu, _host = self._parity(templates, pods, nodes, pod_volumes)
        assert len(tpu.existing_assignments) == 4
        assert not tpu.claims

    def test_device_resident_volumes_seed_usage(self):
        """A node's RESIDENT pods' volumes count against the cap before any
        new pod lands (cluster.go:845-857 populateVolumeLimits)."""
        templates = build_templates([(default_pool(), instance_types(8))])
        p = make_pod("p", cpu=0.25)
        p.spec.pvc_names = ["new-vol"]
        pod_volumes = {p.uid: {"ebs": {"new-vol"}}}

        def nodes():
            n = make_existing("node-a", 0, cpu_avail=8.0)
            u = VolumeUsage()
            u.add_limit("ebs", 2)
            u.add("resident-1", {"ebs": {"old-1"}})
            u.add("resident-2", {"ebs": {"old-2"}})
            n.volume_usage = u
            return [n]

        tpu, _host = self._parity(templates, [p], nodes, pod_volumes)
        assert not tpu.existing_assignments  # cap already saturated
        assert len(tpu.claims) == 1

    def test_over_cap_node_still_takes_volume_free_pods(self):
        """A node whose resident distinct-PVC count already exceeds a
        shrunk cap: volume-free pods still land there (the host gates the
        check on `if pod_vols`), while ANY volume-carrying pod is refused
        — even one whose volumes belong to unlimited drivers (the union
        check sees the over-cap driver regardless)."""
        templates = build_templates([(default_pool(), instance_types(8))])
        free = make_pod("p-free", cpu=0.25)
        nfs = make_pod("p-nfs", cpu=0.25)
        nfs.spec.pvc_names = ["n1"]
        pod_volumes = {nfs.uid: {"nfs": {"n1"}}}  # nfs publishes NO limit

        def nodes():
            n = make_existing("node-a", 0, cpu_avail=8.0)
            u = VolumeUsage()
            u.add_limit("ebs", 1)  # shrank after attach:
            u.add("resident-1", {"ebs": {"old-1"}})
            u.add("resident-2", {"ebs": {"old-2"}})
            n.volume_usage = u
            return [n]

        tpu, _host = self._parity(templates, [free, nfs], nodes, pod_volumes)
        assert tpu.existing_assignments == {free.uid: "node-a"}
        assert len(tpu.claims) == 1  # the nfs pod opens a claim

    def test_device_multi_driver_limits(self):
        """Per-driver caps are independent: an ebs-saturated node still
        takes nfs volumes, and vice versa."""
        templates = build_templates([(default_pool(), instance_types(8))])
        pe = make_pod("p-ebs", cpu=0.25)
        pe.spec.pvc_names = ["e1"]
        pn = make_pod("p-nfs", cpu=0.25)
        pn.spec.pvc_names = ["n1"]
        pod_volumes = {pe.uid: {"ebs": {"e1"}}, pn.uid: {"nfs": {"n1"}}}

        def nodes():
            n = make_existing("node-a", 0, cpu_avail=8.0)
            u = VolumeUsage()
            u.add_limit("ebs", 0)  # saturated
            u.add_limit("nfs", 1)
            n.volume_usage = u
            return [n]

        tpu, _host = self._parity(templates, [pe, pn], nodes, pod_volumes)
        assert list(tpu.existing_assignments) == [pn.uid]
        assert len(tpu.claims) == 1  # the ebs pod opens a claim


class TestAlternatives:
    def test_storage_class_terms_are_alternatives(self):
        pod = make_pod("p")
        pod.spec.pvc_names = ["data"]
        classes = {
            "multi": sc(
                "multi",
                allowed_topologies=[
                    {l.LABEL_TOPOLOGY_ZONE: ["test-zone-1"]},
                    {l.LABEL_TOPOLOGY_ZONE: ["test-zone-2"]},
                ],
            )
        }
        alts = volume_requirement_alternatives(pod, {"data": pvc("data", "multi")}, classes)
        assert len(alts) == 2
        assert sorted(next(iter(a.get(l.LABEL_TOPOLOGY_ZONE).values)) for a in alts) == [
            "test-zone-1",
            "test-zone-2",
        ]

    def test_bound_zone_single_alternative(self):
        pod = make_pod("p")
        pod.spec.pvc_names = ["data"]
        alts = volume_requirement_alternatives(
            pod, {"data": pvc("data", bound_zone="test-zone-2")}, {}
        )
        assert len(alts) == 1
        assert alts[0].get(l.LABEL_TOPOLOGY_ZONE).values == frozenset({"test-zone-2"})

    def test_compatible_cross_product_prunes(self):
        """Two volumes: one allows zones {1,2}, the other {2,3} -> only the
        compatible combination(s) survive (volumetopology.go:104-118)."""
        a = Requirements()
        a.add(Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, "z1", "z2"))
        b1 = Requirements()
        b1.add(Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, "z2", "z3"))
        b2 = Requirements()
        b2.add(Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, "z4"))
        merged = merge_alternatives([a], [b1, b2])
        assert len(merged) == 1
        assert merged[0].get(l.LABEL_TOPOLOGY_ZONE).values == frozenset({"z2"})

    def test_all_incompatible_keeps_full_product(self):
        a = Requirements()
        a.add(Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, "z1"))
        b = Requirements()
        b.add(Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, "z2"))
        merged = merge_alternatives([a], [b])
        assert len(merged) == 1  # kept, not dropped (volumetopology.go:96-102)

    def test_second_zone_tried_after_first_fails(self):
        """Alternative order is honored: zone-1 is tried first, but the
        catalog only offers zone-2, so the pod lands there (the reference's
        tryVolumeAlternative loop, nodeclaim.go:149-161)."""
        pool = default_pool()
        pool.spec.template.spec.requirements = [
            {"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["test-zone-2"]}
        ]
        templates = build_templates([(pool, instance_types(8))])
        pod = make_pod("p", cpu=0.25)
        pod.spec.pvc_names = ["data"]
        alts = volume_requirement_alternatives(
            pod,
            {"data": pvc("data", "multi")},
            {
                "multi": sc(
                    "multi",
                    allowed_topologies=[
                        {l.LABEL_TOPOLOGY_ZONE: ["test-zone-1"]},
                        {l.LABEL_TOPOLOGY_ZONE: ["test-zone-2"]},
                    ],
                )
            },
        )
        vol = {pod.uid: alts}
        host = HostScheduler(templates, volume_reqs=vol).solve([pod])
        assert len(host.claims) == 1
        assert not host.unschedulable
        zone = host.claims[0].requirements.get(l.LABEL_TOPOLOGY_ZONE).values
        assert zone == frozenset({"test-zone-2"})
        # device engine routes multi-alternative problems to the host oracle
        tpu = TPUScheduler(templates).solve([pod], volume_reqs=vol)
        assert len(tpu.claims) == 1
        assert tpu.claims[0].requirements.get(l.LABEL_TOPOLOGY_ZONE).values == frozenset(
            {"test-zone-2"}
        )

    def test_single_alternative_stays_on_device(self):
        """One alternative folds into the device solve (no fallback)."""
        from karpenter_tpu.utils.metrics import SOLVER_HOST_FALLBACKS

        templates = build_templates([(default_pool(), instance_types(16))])
        pod = make_pod("p", cpu=0.25)
        pod.spec.pvc_names = ["data"]
        alts = volume_requirement_alternatives(
            pod, {"data": pvc("data", "zonal")}, {"zonal": sc("zonal", zones=["test-zone-2"])}
        )
        assert len(alts) == 1
        vol = {pod.uid: alts}
        before = SOLVER_HOST_FALLBACKS.get(reason="volume_alternatives")
        host = HostScheduler(templates, volume_reqs=vol).solve([pod])
        tpu = TPUScheduler(templates).solve([pod], volume_reqs=vol)
        assert SOLVER_HOST_FALLBACKS.get(reason="volume_alternatives") == before
        assert len(tpu.claims) == len(host.claims) == 1
        for c in tpu.claims:
            assert sorted(c.requirements.get(l.LABEL_TOPOLOGY_ZONE).values) == ["test-zone-2"]


class TestProvisionerWiring:
    def test_csinode_limits_flow_through(self):
        """End-to-end: a node publishing csi_drivers limits fits only one
        PVC attachment; the second pod gets a fresh claim."""
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
        from karpenter_tpu.models.nodepool import NodePool
        from karpenter_tpu.state.store import ObjectStore
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = ObjectStore(clock)
        cloud = KwokCloudProvider(store, catalog=instance_types(16))
        mgr = Manager(store, cloud, clock)
        pool = NodePool()
        pool.metadata.name = "default"
        store.create(ObjectStore.NODEPOOLS, pool)
        # land a seed pod so one node exists
        store.create(ObjectStore.PODS, make_pod("seed", cpu=0.25))
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        mgr.run_until_idle()
        nodes = store.nodes()
        assert len(nodes) == 1
        nodes[0].spec.csi_drivers = {"ebs": 1}
        store.create(ObjectStore.STORAGE_CLASSES, sc("standard", provisioner="ebs"))
        for i in range(2):
            p = make_pod(f"pv-{i}", cpu=0.25)
            p.spec.pvc_names = [f"vol-{i}"]
            store.create(ObjectStore.PODS, p)
            store.create(ObjectStore.PVCS, pvc(f"vol-{i}", storage_class="standard"))
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        bound = KubeSchedulerSim(store, mgr.cluster).bind_pending()
        # the limited node takes at most one of the two pvc pods; a new
        # claim covers the other
        assert len(store.nodeclaims()) == 2
        per_node = {}
        for p in store.pods():
            if p.spec.pvc_names and p.spec.node_name:
                per_node.setdefault(p.spec.node_name, []).append(p.name)
        assert all(len(v) == 1 for v in per_node.values())
