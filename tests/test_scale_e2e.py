"""Scale e2e: a mixed workload (the reference benchmark's pod-family mix)
through the full kwok harness — provisioning, binding, and a consolidation
cycle — verifying global invariants rather than exact placements."""

import numpy as np

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import Budget, NodePool
from karpenter_tpu.models.pod import PodAffinityTerm, TopologySpreadConstraint, make_pod
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock


def mixed_pods(n, rng):
    pods = []
    for i in range(n):
        p = make_pod(
            f"mix-{i}",
            cpu=float(rng.choice([0.25, 0.5, 1.0, 2.0])),
            memory=f"{rng.choice([0.5, 1.0, 2.0])}Gi",
        )
        kind = i % 5
        if kind == 1:
            p.metadata.labels = {"spread": "zonal"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    label_selector={"spread": "zonal"},
                )
            ]
        elif kind == 2:
            p.metadata.labels = {"spread": "host"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=2,
                    topology_key=l.LABEL_HOSTNAME,
                    label_selector={"spread": "host"},
                )
            ]
        elif kind == 3:
            p.metadata.labels = {"aff": "group"}
            p.spec.pod_affinity = [
                PodAffinityTerm(topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"aff": "group"})
            ]
        elif kind == 4:
            p.metadata.labels = {"anti": "self"}
            p.spec.pod_anti_affinity = [
                PodAffinityTerm(topology_key=l.LABEL_HOSTNAME, label_selector={"anti": "self"})
            ]
        pods.append(p)
    return pods


def test_mixed_workload_full_cycle():
    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=instance_types(100))
    mgr = Manager(store, cloud, clock)
    pool = NodePool()
    pool.metadata.name = "default"
    pool.spec.disruption.budgets = [Budget(nodes="100%")]
    store.create(ObjectStore.NODEPOOLS, pool)

    rng = np.random.default_rng(5)
    pods = mixed_pods(300, rng)
    for p in pods:
        store.create(ObjectStore.PODS, p)

    # provision + register + bind until converged (multi-pass: affinity
    # groups may need a second batch once zones collapse)
    for _ in range(6):
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        if all(p.spec.node_name for p in store.pods()):
            break
        mgr.batcher.trigger()
        clock.step(2.0)

    bound = [p for p in store.pods() if p.spec.node_name]
    assert len(bound) == 300, f"only {len(bound)}/300 pods bound"

    # invariant: zonal spread within skew over the spread-labeled pods
    zone_counts = {}
    node_zone = {n.name: n.metadata.labels[l.LABEL_TOPOLOGY_ZONE] for n in store.nodes()}
    for p in store.pods():
        if p.metadata.labels.get("spread") == "zonal":
            z = node_zone[p.spec.node_name]
            zone_counts[z] = zone_counts.get(z, 0) + 1
    assert zone_counts and max(zone_counts.values()) - min(zone_counts.values()) <= 1

    # invariant: hostname anti-affinity holds — one anti pod per node
    per_node = {}
    for p in store.pods():
        if p.metadata.labels.get("anti") == "self":
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
    assert per_node and max(per_node.values()) == 1

    # invariant: zone affinity pods co-located in one zone
    aff_zones = {
        node_zone[p.spec.node_name]
        for p in store.pods()
        if p.metadata.labels.get("aff") == "group"
    }
    assert len(aff_zones) == 1

    # shrink the workload and run disruption cycles: capacity must drop
    # while every surviving pod stays bound after settling
    survivors = {f"mix-{i}" for i in range(60)}
    for pod in list(store.pods()):
        if pod.name not in survivors:
            pod.status.phase = "Succeeded"
            store.update(ObjectStore.PODS, pod)
            store.delete(ObjectStore.PODS, pod.name)
    mgr.run_until_idle()
    cpu_before = sum(n.status.capacity["cpu"] for n in store.nodes())
    clock.step(60.0)
    for _ in range(10):
        mgr.run_disruption_once()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        clock.step(20.0)
    cpu_after = sum(n.status.capacity["cpu"] for n in store.nodes())
    assert cpu_after < cpu_before, "no capacity reclaimed"
    for _ in range(4):
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
    unbound = [p.name for p in store.pods() if not p.spec.node_name]
    assert not unbound, f"pods stranded after consolidation: {unbound}"
