"""Fleet-scale serving (fleet/, ISSUE 16).

Three properties under test, each end-to-end where it matters:

- **Session mobility**: two in-process replicas behind one client over
  real sockets; killing the replica that holds the resident session
  mid-stream must hand the session off — the survivor rebuilds resident
  state from the ledger capsule's round transcript and the rebuilt
  fingerprint equals the lost one's, so the client sees ZERO lost rounds
  and zero ``invalidated`` re-snapshots, and every post-handoff round
  stays bit-identical to a cold re-solve + the host oracle.
- **Shared guardrail bus**: a quarantine trip on replica A routes
  replica B's next resident round onto the sequential twin within one
  round; trips never echo back; audit verdicts and compile-cache
  announcements ride the same bus (in-process hub and the file backend).
- **Admission control**: the bounded solve queue sheds the OLDEST
  waiting round to the host-solve ladder (counted under
  ``ktpu_fleet_shed_total{reason="queue_full"}``) and serves tenants
  round-robin, FIFO within one tenant.

Everything here is host-only (conftest pins JAX to 8 virtual CPU
devices) and sized for tier-1.
"""

import threading
import time

import numpy as np
import pytest

from karpenter_tpu.controllers.provisioning import TPUScheduler
from karpenter_tpu.faultinject import active_plan
from karpenter_tpu.fleet import AdmissionQueue, FileBus, FleetMember, InProcessHub
from karpenter_tpu.guard import audit as guard_audit
from karpenter_tpu.guard.quarantine import QUARANTINE, Quarantine
from karpenter_tpu.obs import fleetobs
from karpenter_tpu.obs import ledger as obs_ledger
from karpenter_tpu.rpc import RemoteScheduler, serve
from karpenter_tpu.rpc import client as rpc_client
from karpenter_tpu.rpc.service import SolverService
from karpenter_tpu.utils.metrics import (
    FLEET_BUS_MESSAGES,
    FLEET_HANDOFFS,
    FLEET_RETARGETS,
    FLEET_SHED,
    FLEET_WARM_ANNOUNCED,
    RESIDENT_ROUNDS,
    SESSION_EVICTIONS,
)

from test_resident import assert_identical, cold_solve, kind_pods, make_templates

OUTCOMES = (
    "adopted",
    "no_capsule",
    "fingerprint_mismatch",
    "replay_failed",
    "shape_mismatch",
)


@pytest.fixture
def fast_failover(monkeypatch):
    """One transport retry with millisecond backoff: a killed replica is
    detected and retargeted in well under a round, as the bench's chaos
    stage configures it."""
    monkeypatch.setattr(rpc_client, "TRANSPORT_RETRIES", 1)
    monkeypatch.setattr(rpc_client, "RETRY_BASE_SECONDS", 0.01)
    monkeypatch.setattr(rpc_client, "RETRY_CAP_SECONDS", 0.02)


def _handoff_counts():
    return {k: FLEET_HANDOFFS.get(outcome=k) for k in OUTCOMES}


class TestGuardrailBus:
    def test_file_bus_roundtrip_across_instances(self, tmp_path):
        """The file backend is an append-only per-topic log: a SECOND
        instance over the same directory (another process, in production)
        sees everything, offsets resume mid-stream, and a torn tail line
        is never consumed."""
        a = FileBus(str(tmp_path))
        b = FileBus(str(tmp_path))
        a.publish("quarantine", {"path": "resident", "origin": "a", "n": 1})
        a.publish("quarantine", {"path": "grid", "origin": "a", "n": 2})
        a.publish("audit", {"verdict": "pass", "origin": "a"})
        msgs, off = b.fetch("quarantine", 0)
        assert [m["n"] for m in msgs] == [1, 2]
        again, off2 = b.fetch("quarantine", off)
        assert again == [] and off2 == off
        a.publish("quarantine", {"path": "resident", "origin": "a", "n": 3})
        late, _ = b.fetch("quarantine", off)
        assert [m["n"] for m in late] == [3]
        msgs, _ = b.fetch("audit", 0)
        assert msgs == [{"verdict": "pass", "origin": "a"}]
        # a torn tail (a writer died mid-append) stays unconsumed: the
        # offset parks before the partial line until it is completed
        with open(str(tmp_path / "quarantine.jsonl"), "ab") as fh:
            fh.write(b'{"path": "resi')
        msgs, off3 = b.fetch("quarantine", 0)
        assert [m["n"] for m in msgs] == [1, 2, 3]
        tail, _ = b.fetch("quarantine", off3)
        assert tail == []

    def test_quarantine_trip_propagates_without_echo(self, tmp_path):
        """A local trip on A's breaker reaches B over the file bus within
        one pump, carries the origin in the reason, and is NOT republished
        by B (remote trips must not loop)."""
        qa, qb = Quarantine(), Quarantine()
        ma = FleetMember(FileBus(str(tmp_path)), "rep-a", quarantine=qa)
        mb = FleetMember(FileBus(str(tmp_path)), "rep-b", quarantine=qb)
        try:
            pub0 = FLEET_BUS_MESSAGES.get(topic="quarantine", direction="published")
            qa.trip("resident", reason="shadow-audit divergence", ttl_s=60.0)
            assert not qb.active("resident")
            assert mb.pump() >= 1
            assert qb.active("resident")
            assert qb.reason("resident").startswith("fleet:rep-a:")
            # the remote application must not have been republished: A's
            # next pump finds nothing foreign, and exactly ONE quarantine
            # message was ever published
            assert ma.pump() == 0
            assert (
                FLEET_BUS_MESSAGES.get(topic="quarantine", direction="published")
                == pub0 + 1
            )
        finally:
            ma.close()
            mb.close()

    def test_audit_verdicts_and_compile_warmth_ride_the_bus(self):
        hub = InProcessHub()
        ma = FleetMember(hub, "rep-a", quarantine=Quarantine())
        mb = FleetMember(hub, "rep-b", quarantine=Quarantine())
        try:
            guard_audit.record_audit("resident", "pass", "fleet-test")
            mb.pump()
            got = [a for a in mb.remote_audits if a.get("origin") == "rep-a"]
            assert got and got[-1]["verdict"] == "pass"
            assert got[-1]["path"] == "resident"
            # a peer's fresh jit compile marks the kernel key warm here
            # (the cross-process compile-cache warmer announcement)
            warm0 = FLEET_WARM_ANNOUNCED.get(kernel="solve_core")
            hub.publish(
                "compile", {"kernel": "solve_core", "seconds": 1.2, "origin": "rep-a"}
            )
            mb.pump()
            assert "solve_core" in mb.warm_kernels
            assert FLEET_WARM_ANNOUNCED.get(kernel="solve_core") == warm0 + 1
        finally:
            ma.close()
            mb.close()


class TestSessionRegistry:
    def test_lru_eviction_honors_recency_and_cap(self, monkeypatch):
        """KTPU_SESSION_CAP bounds the registry with LRU ordering: a
        refreshed session survives the insertion that evicts the stale
        one, the eviction is counted under reason="capacity", and the
        evicted client recovers with exactly one silent re-snapshot."""
        monkeypatch.setenv("KTPU_SESSION_CAP", "2")
        svc = SolverService()
        server, addr = serve(service=svc)
        try:
            templates = make_templates()
            pods = kind_pods("a", 8)
            c1 = RemoteScheduler(addr, templates, max_claims=128)
            c2 = RemoteScheduler(addr, templates, max_claims=128)
            c3 = RemoteScheduler(addr, templates, max_claims=128)
            c1.solve(list(pods))
            c2.solve(list(pods))
            assert len(svc._sessions) == 2
            # touching c1 refreshes its LRU slot: c3's arrival evicts c2
            c1.solve(list(pods) + kind_pods("x", 2))
            cap0 = SESSION_EVICTIONS.get(reason="capacity")
            c3.solve(list(pods))
            assert SESSION_EVICTIONS.get(reason="capacity") == cap0 + 1
            assert set(svc._sessions) == {c1._session_id, c3._session_id}
            inv0 = RESIDENT_ROUNDS.get(mode="invalidated")
            r = c2.solve(list(pods) + kind_pods("y", 3))
            assert RESIDENT_ROUNDS.get(mode="invalidated") == inv0 + 1
            assert not r.unschedulable
        finally:
            server.stop(0)

    def test_same_shape_configure_preserves_sessions(self):
        """An unrelated Configure with the IDENTICAL cluster shape shares
        the config epoch: no version bump, resident sessions survive, the
        next round is still the delta path. A genuinely different shape
        is a new epoch and evicts under reason="epoch"."""
        svc = SolverService()
        server, addr = serve(service=svc)
        try:
            c1 = RemoteScheduler(addr, make_templates(), max_claims=128)
            union = kind_pods("a", 10)
            c1.solve(list(union))
            assert c1._session_fpr
            v1 = c1._config_version
            inv0 = RESIDENT_ROUNDS.get(mode="invalidated")
            d0 = RESIDENT_ROUNDS.get(mode="delta")
            c2 = RemoteScheduler(addr, make_templates(), max_claims=128)
            assert c2._config_version == v1  # same epoch: no supersede
            assert len(svc._sessions) == 1
            union = union + kind_pods("b", 4)
            r = c1.solve(list(union))
            assert RESIDENT_ROUNDS.get(mode="invalidated") == inv0
            assert RESIDENT_ROUNDS.get(mode="delta") == d0 + 1
            assert_identical(cold_solve(union), r)
            # different shape -> new epoch: the registry drains under
            # reason="epoch" and c1's next round re-snapshots once
            e0 = SESSION_EVICTIONS.get(reason="epoch")
            RemoteScheduler(addr, make_templates(n_types=8), max_claims=128)
            assert SESSION_EVICTIONS.get(reason="epoch") == e0 + 1
            inv1 = RESIDENT_ROUNDS.get(mode="invalidated")
            union = union + kind_pods("c", 3)
            r = c1.solve(list(union))
            assert RESIDENT_ROUNDS.get(mode="invalidated") == inv1 + 1
            assert not r.unschedulable
        finally:
            server.stop(0)


class TestFleetHandoff:
    def test_kill_a_mid_stream_hands_off_and_quarantine_routes_b(
        self, fast_failover
    ):
        """The tentpole, end to end over real sockets: two replicas share
        a bus; the client streams a seeded Poisson delta trace at A; A is
        killed mid-stream. The re-solve must route to B, which rebuilds
        the resident session from A's last capsule — fingerprint-exact,
        so the client keeps its session identity: zero rounds lost, zero
        ``invalidated`` re-snapshots, every round bit-identical to a cold
        re-solve + host oracle. Then a quarantine trip on A's breaker
        routes B's next resident round onto the sequential twin."""
        hub = InProcessHub()
        qa = Quarantine()
        ma = FleetMember(hub, "rep-a", quarantine=qa)
        # B's breaker IS the process-global one, exactly as a real replica
        # process wires it: the remote trip must route B's solve path
        mb = FleetMember(hub, "rep-b")
        svc_a = SolverService(fleet=ma)
        svc_b = SolverService(fleet=mb)
        server_a, addr_a = serve(service=svc_a)
        server_b, addr_b = serve(service=svc_b)
        killed = False
        inv0 = RESIDENT_ROUNDS.get(mode="invalidated")
        h0 = _handoff_counts()
        rt0 = FLEET_RETARGETS.get(reason="transport")
        seq0 = obs_ledger.LEDGER.seq()
        try:
            remote = RemoteScheduler(
                f"{addr_a},{addr_b}", make_templates(), max_claims=128
            )
            rng = np.random.default_rng(7)
            union = kind_pods("a", 16) + kind_pods("b", 8)
            r = remote.solve(list(union))
            assert not r.unschedulable
            for rnd in range(6):
                if rnd == 3:
                    server_a.stop(0)
                    killed = True
                union = union + kind_pods(f"d{rnd}", int(rng.poisson(3.0)) + 1)
                r = remote.solve(list(union))
                assert not r.unschedulable  # zero rounds lost across the kill
            assert_identical(cold_solve(union), r)
            h1 = _handoff_counts()
            assert h1["adopted"] == h0["adopted"] + 1
            for bad in OUTCOMES[1:]:
                assert h1[bad] == h0[bad], bad
            # the handoff was INVISIBLE to the client: no SESSION_LOST,
            # no cold re-snapshot round
            assert RESIDENT_ROUNDS.get(mode="invalidated") == inv0
            assert FLEET_RETARGETS.get(reason="transport") >= rt0 + 1
            # the fleet observatory stitches the kill into ONE coherent
            # story (ISSUE 17): every round sig appears exactly once
            # fleet-wide (the adoption replay is marked, not re-counted),
            # and the handed-off round's trace id shows up on BOTH
            # replicas — the origin round on rep-a, the replay on rep-b
            recs = [
                r for r in fleetobs.fleet_records(dirs=[])
                if (r.get("seq") or 0) > seq0
            ]
            counts = fleetobs.round_counts(recs)
            dup = {s: n for s, n in counts.items() if n != 1}
            assert not dup, f"rounds stitched more than once: {dup}"
            replays = [r for r in recs if r.get("replay")]
            assert replays, "adoption recorded no replay-marked rounds"
            assert all(r.get("replica") == "rep-b" for r in replays)
            handoff_tid = (replays[0].get("trace") or {}).get("id")
            assert handoff_tid
            stitched = fleetobs.stitch(handoff_tid, recs)
            assert stitched is not None and stitched["consistent"]
            assert {"rep-a", "rep-b"} <= set(stitched["replicas"])
            # the failed-over round crossed a retarget + a server hop, so
            # its hop count exceeds a clean round's
            assert stitched["max_hop"] >= 2
            # /debug/trace/<id> is the same stitch; its Perfetto form is a
            # valid document (the schema round-trip lives in test_fleetobs)
            assert fleetobs.debug_trace(handoff_tid) is not None
            # a trip on A's breaker reaches B's via the bus (pumped at the
            # top of the next solve RPC) and routes that round sequential
            qa.trip("resident", reason="shadow-audit divergence", ttl_s=120.0)
            union = union + kind_pods("z", 2)
            r = remote.solve(list(union))
            assert QUARANTINE.active("resident")
            assert QUARANTINE.reason("resident").startswith("fleet:rep-a:")
            session = next(iter(svc_b._sessions.values()))
            assert (session.last_mode, session.last_reason) == (
                "full",
                "quarantined",
            )
            assert_identical(cold_solve(union), r)
        finally:
            QUARANTINE.clear("resident")
            if not killed:
                server_a.stop(0)
            server_b.stop(0)
            ma.close()
            mb.close()

    def test_fault_evict_readopts_from_own_archive(self):
        """The chaos point the SESSION_LOST suite injects
        (rpc.session.evict) stops being client-visible once a fleet
        member is attached: the registry eviction re-adopts from the
        member's OWN capsule archive — no SESSION_LOST, no invalidated
        round (contrast guard.TestSessionLost, where fleet is None)."""
        member = FleetMember(InProcessHub(), "solo", quarantine=Quarantine())
        svc = SolverService(fleet=member)
        server, addr = serve(service=svc)
        try:
            remote = RemoteScheduler(addr, make_templates(), max_claims=128)
            union = kind_pods("a", 12)
            remote.solve(list(union))
            union = union + kind_pods("b", 5)
            remote.solve(list(union))
            assert remote._session_fpr
            inv0 = RESIDENT_ROUNDS.get(mode="invalidated")
            a0 = FLEET_HANDOFFS.get(outcome="adopted")
            f0 = SESSION_EVICTIONS.get(reason="fault")
            union = union + kind_pods("c", 4)
            plan = {
                "rules": [
                    {"point": "rpc.session.evict", "error": "runtime", "times": 1}
                ]
            }
            with active_plan(plan):
                r = remote.solve(list(union))
            assert SESSION_EVICTIONS.get(reason="fault") == f0 + 1
            assert FLEET_HANDOFFS.get(outcome="adopted") == a0 + 1
            assert RESIDENT_ROUNDS.get(mode="invalidated") == inv0
            assert_identical(cold_solve(union), r)
        finally:
            server.stop(0)
            member.close()


class TestAdmission:
    def test_shed_oldest_and_round_robin_fairness(self):
        """Pure queue semantics, deterministically sequenced: over a full
        queue the OLDEST waiter is shed (bounding every round's queue
        time), and release() serves tenants round-robin, FIFO within one
        tenant."""
        q = AdmissionQueue(2)
        assert q.acquire("main") == "run"  # idle queue: immediate slot
        verdicts = {}
        order = []
        cond = threading.Condition()

        def waiter(name, tenant):
            v = q.acquire(tenant)
            with cond:
                verdicts[name] = v
                if v == "run":
                    order.append(tenant)
                cond.notify_all()
            if v == "run":
                q.release()

        threads = []
        # arrival order b1, c1 fills the queue; b2's arrival sheds b1
        for name, tenant in [("b1", "b"), ("c1", "c")]:
            t = threading.Thread(target=waiter, args=(name, tenant))
            t.start()
            threads.append(t)
            deadline = time.monotonic() + 10.0
            while q.depth() < len(threads) and time.monotonic() < deadline:
                time.sleep(0.005)
        t = threading.Thread(target=waiter, args=("b2", "b"))
        t.start()
        threads.append(t)
        with cond:
            assert cond.wait_for(lambda: "b1" in verdicts, timeout=10.0)
        assert verdicts["b1"] == "shed"
        assert q.shed_count == 1
        q.release()  # hand the held slot down the queue
        for t in threads:
            t.join(timeout=10.0)
            assert not t.is_alive()
        assert verdicts["c1"] == "run" and verdicts["b2"] == "run"
        # c ran before b's second round: round-robin across tenants even
        # though b2 had been waiting no longer than c1
        assert order == ["c", "b"]
        assert q.depth() == 0

    def test_overload_sheds_to_host_ladder_over_socket(self):
        """With the device slot held and a capacity-1 queue, concurrent
        Solve RPCs shed all but the newest waiter onto the host-solve
        ladder — counted in ktpu_fleet_shed_total{reason="queue_full"} —
        and EVERY caller still gets a complete placement."""
        svc = SolverService(admission=AdmissionQueue(1))
        server, addr = serve(max_workers=8, service=svc)
        try:
            templates = make_templates()
            pods = kind_pods("a", 10)
            clients = [
                RemoteScheduler(addr, make_templates(), max_claims=128)
                for _ in range(3)
            ]
            local = TPUScheduler(templates, max_claims=128).solve(list(pods))
            shed0 = FLEET_SHED.get(reason="queue_full")
            assert svc._admission.acquire("test-holder") == "run"
            results = {}

            def solve(i):
                results[i] = clients[i].solve(list(pods))

            threads = [
                threading.Thread(target=solve, args=(i,)) for i in range(3)
            ]
            for t in threads:
                t.start()
            # each arrival over the full queue sheds the then-oldest
            # waiter: 3 waiters against capacity 1 -> exactly 2 sheds
            deadline = time.monotonic() + 30.0
            while (
                svc._admission.shed_count < 2 or svc._admission.depth() < 1
            ) and time.monotonic() < deadline:
                time.sleep(0.01)
            assert svc._admission.shed_count == 2
            svc._admission.release()
            for t in threads:
                t.join(timeout=60.0)
                assert not t.is_alive()
            assert FLEET_SHED.get(reason="queue_full") == shed0 + 2
            for r in results.values():
                assert not r.unschedulable
                assert len(r.claims) == len(local.claims)
                assert sum(len(c.pods) for c in r.claims) == len(pods)
        finally:
            server.stop(0)
