"""Differential parity for the resident incremental solver (ISSUE 7).

A ResidentSession keeps SolverState on device across solve() calls and
feeds only the pod delta through the pipeline: arrivals append via the
scan-prefix property, suffix departures retract via the retract_tail
kernel. None of that may move a single pod: every round that stays on the
delta path must be BIT-identical to a cold full re-solve of the current
pod set in session (arrival) order AND to the host oracle — across
windowed/un-windowed resident states and pipeline chunking at K in
{1, 2, 4}. Rounds the session cannot prove delta-safe (departure of a
base pod, vocab growth, an arrival below the eviction floor, a failing
arrival) must fall back to a full re-solve — still bit-identical, just
counted under a different mode.

Everything here is host-only and sized for tier-1.
"""

import numpy as np
import pytest

from karpenter_tpu.controllers.provisioning import (
    HostScheduler,
    TPUScheduler,
    build_templates,
)
from karpenter_tpu.controllers.provisioning.scheduler import ResidentSession
from karpenter_tpu.controllers.provisioning.topology import (
    Topology,
    build_universe_domains,
)
from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod

from test_solver import assert_same_packing


def make_templates(n_types=12):
    pool = NodePool()
    pool.metadata.name = "default"
    return build_templates([(pool, instance_types(n_types))])


def kind_pods(name, n, cpu=1.0):
    out = []
    for i in range(n):
        p = make_pod(f"{name}-{i}", cpu=cpu, memory="1Gi")
        p.metadata.labels = {"app": name}
        out.append(p)
    return out


def session_scheduler(monkeypatch, window=0, k=1):
    """A ResidentSession over a TPUScheduler with the active window and
    pipeline chunking forced (0 / 1 = defaults)."""
    monkeypatch.setenv("KTPU_RESIDENT", "1")
    if window:
        monkeypatch.setenv("KTPU_SCAN_WINDOW", str(window))
    else:
        monkeypatch.delenv("KTPU_SCAN_WINDOW", raising=False)
    if k > 1:
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", str(k))
        monkeypatch.setenv("KTPU_PIPELINE_MIN_PODS", "0")
    else:
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
    return TPUScheduler(make_templates(), max_claims=128).resident_session()


def cold_solve(pods):
    """The cold comparator: a FRESH un-warmed device solve of the pods in
    session (arrival) order, plus the host oracle on the same problem."""
    device = TPUScheduler(make_templates(), max_claims=128).solve(list(pods))
    templates = make_templates()
    topo = Topology.build(list(pods), build_universe_domains(templates, []), [])
    host = HostScheduler(templates, topology=topo).solve(list(pods))
    assert_same_packing(host, device)
    return device


def assert_identical(cold, got):
    """assert_same_packing plus the hostname sequence (claims must reuse
    the exact placeholder order a cold decode would mint)."""
    assert_same_packing(cold, got)
    assert {c.slot: c.hostname for c in cold.claims} == {
        c.slot: c.hostname for c in got.claims
    }


class TestResidentDifferential:
    @pytest.mark.parametrize("window", [0, 8])
    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_arrivals_only(self, monkeypatch, window, k):
        session = session_scheduler(monkeypatch, window, k)
        base = kind_pods("a", 16) + kind_pods("b", 12)
        union = list(base)
        r = session.solve(list(union))
        assert session.last_mode == "full"
        assert_identical(cold_solve(union), r)
        for rnd in range(3):
            union = union + kind_pods(f"d{rnd}", 6)
            r = session.solve(list(union))
            assert session.last_mode == "delta", session.last_reason
            assert_identical(cold_solve(union), r)
        stats = session.last_timings["resident"]
        assert stats["mode"] == "delta"

    def test_same_kind_arrival_appends(self, monkeypatch):
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 12) + kind_pods("b", 12)
        session.solve(list(base))
        # more pods of the LAST kind tie with its resident pods and sort
        # after them (stable lexsort) — still an exact append
        union = base + kind_pods("b", 6)
        r = session.solve(list(union))
        assert session.last_mode == "delta", session.last_reason
        assert_identical(cold_solve(union), r)

    def test_smaller_arrival_without_compaction(self, monkeypatch):
        # un-windowed small base -> no boundary compaction -> no eviction
        # floor: a smaller arrival batch still appends (it sorts after
        # every resident by size)
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 16)
        session.solve(list(base))
        union = base + kind_pods("small", 5, cpu=0.5)
        r = session.solve(list(union))
        assert session.last_mode == "delta", session.last_reason
        assert_identical(cold_solve(union), r)

    @pytest.mark.parametrize("window", [0, 8])
    def test_departures_retract(self, monkeypatch, window):
        session = session_scheduler(monkeypatch, window)
        base = kind_pods("a", 16)
        session.solve(list(base))
        b1 = kind_pods("d1", 8)
        session.solve(list(base + b1))
        assert session.last_mode == "delta", session.last_reason
        # the most recent round departs wholesale: the retract kernel path
        r = session.solve(list(base))
        assert session.last_mode == "delta", session.last_reason
        assert_identical(cold_solve(base), r)

    def test_multi_round_suffix_retract(self, monkeypatch):
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 16)
        session.solve(list(base))
        b1, b2 = kind_pods("d1", 6), kind_pods("d2", 6)
        session.solve(list(base + b1))
        session.solve(list(base + b1 + b2))
        assert session.last_mode == "delta"
        # undo BOTH delta rounds in one go
        r = session.solve(list(base))
        assert session.last_mode == "delta", session.last_reason
        assert_identical(cold_solve(base), r)

    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_mixed_round(self, monkeypatch, k):
        session = session_scheduler(monkeypatch, 0, k)
        base = kind_pods("a", 16)
        session.solve(list(base))
        b1 = kind_pods("d1", 8)
        session.solve(list(base + b1))
        # one round departs the latest batch AND lands a fresh one
        b2 = kind_pods("d2", 5)
        union = base + b2
        r = session.solve(list(union))
        assert session.last_mode == "delta", session.last_reason
        assert_identical(cold_solve(union), r)

    def test_ghost_kind_rearrival_gets_a_fresh_rank(self, monkeypatch):
        """Regression: a round that retracts kind B's only batch AND
        lands a NEW batch of B-content pods (after a fresh kind D in the
        union order) must not reuse B's stale rank — cold first-appearance
        order puts D's pods first on equal-size ties."""
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 12)
        session.solve(list(base))
        session.solve(list(base + kind_pods("b", 6)))
        assert session.last_mode == "delta"
        union = base + kind_pods("d", 5) + kind_pods("b", 5)
        r = session.solve(list(union))
        assert session.last_mode == "delta", session.last_reason
        assert_identical(cold_solve(union), r)

    def test_retract_of_base_pod_triggers_full_resolve(self, monkeypatch):
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 16)
        session.solve(list(base))
        b1 = kind_pods("d1", 6)
        session.solve(list(base + b1))
        # a departure reaching into the BASE cannot retract: full re-solve
        union = base[1:] + b1
        r = session.solve(list(union))
        assert session.last_mode == "full", session.last_reason
        assert_identical(cold_solve(union), r)
        # ... and the session re-adopts: the next arrival is a delta again
        union2 = union + kind_pods("d2", 4)
        r2 = session.solve(list(union2))
        assert session.last_mode == "delta", session.last_reason
        assert_identical(cold_solve(union2), r2)

    def test_partial_batch_departure_triggers_full_resolve(self, monkeypatch):
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 16)
        session.solve(list(base))
        b1 = kind_pods("d1", 8)
        session.solve(list(base + b1))
        # half the batch departs: not round-aligned -> full re-solve
        union = base + b1[:4]
        r = session.solve(list(union))
        assert session.last_mode == "full", session.last_reason
        assert_identical(cold_solve(union), r)

    def test_epoch_invalidation_on_vocab_growth(self, monkeypatch):
        # the in-session analog of a catalog/template change: an arrival
        # whose selector introduces a new vocab key — the resident problem
        # tensors predate it, so the session must invalidate and rebuild
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 16)
        session.solve(list(base))
        newcomer = make_pod("sel-0", cpu=1.0, memory="1Gi")
        newcomer.spec.node_selector = {"example.com/team": "search"}
        union = base + [newcomer]
        r = session.solve(list(union))
        assert session.last_mode == "invalidated", session.last_reason
        # the full re-solve is still exact (the newcomer fails placement
        # or places per the catalog — either way identical to cold)
        cold = TPUScheduler(make_templates(), max_claims=128).solve(list(union))
        assert cold.assignments == r.assignments
        assert len(cold.claims) == len(r.claims)

    def test_windowed_eviction_floor_falls_back(self, monkeypatch):
        # a windowed base large enough to run boundary compaction sets the
        # eviction floor; an arrival BELOW it could have fit an evicted
        # claim, so the session must not append it
        monkeypatch.setenv("KTPU_COMPACT_MIN_PODS", "8")
        # two fill segments + K=2 pipeline chunks -> a dispatch boundary
        # with pods remaining, so boundary compaction actually runs
        session = session_scheduler(monkeypatch, window=8, k=2)
        base = kind_pods("a", 12) + kind_pods("b", 12)
        session.solve(list(base))
        assert session._r is not None and session._r["compact_rmin"] is not None, (
            "base solve ran no boundary compaction; the floor gate is untested"
        )
        union = base + kind_pods("tiny", 4, cpu=0.25)
        r = session.solve(list(union))
        assert session.last_mode == "full", session.last_reason
        assert session.last_reason == "below_eviction_floor"
        assert_identical(cold_solve(union), r)

    def test_unschedulable_arrival_falls_back(self, monkeypatch):
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 12)
        session.solve(list(base))
        whale = make_pod("whale-0", cpu=10000.0, memory="1Gi")
        union = base + [whale]
        r = session.solve(list(union))
        # the failing arrival routes to the full path (relaxation is a
        # whole-problem loop); identical to cold, including the failure
        assert session.last_mode == "full", session.last_reason
        cold = TPUScheduler(make_templates(), max_claims=128).solve(list(union))
        assert cold.assignments == r.assignments
        assert [p.uid for p, _ in cold.unschedulable] == [
            p.uid for p, _ in r.unschedulable
        ]
        # a failing pod parks the session (cold relaxation would re-shed
        # every round); once it departs, residency resumes
        assert session._r is None

    def test_resident_disabled_restores_snapshot_path(self, monkeypatch):
        monkeypatch.setenv("KTPU_RESIDENT", "0")
        session = TPUScheduler(make_templates(), max_claims=128).resident_session()
        base = kind_pods("a", 12)
        session.solve(list(base))
        assert session._r is None
        union = base + kind_pods("d1", 4)
        r = session.solve(list(union))
        assert session._r is None  # never goes resident
        assert_identical(cold_solve(union), r)

    def test_existing_node_change_invalidates(self, monkeypatch):
        from karpenter_tpu.controllers.provisioning.host_scheduler import (
            ExistingSimNode,
        )
        from karpenter_tpu.models import labels as l
        from karpenter_tpu.scheduling import Requirement, Requirements

        def node(name, cpu=8.0):
            return ExistingSimNode(
                name=name,
                index=0,
                requirements=Requirements(
                    Requirement.new(l.LABEL_HOSTNAME, "In", name)
                ),
                available={"cpu": cpu, "memory": 8 * 2**30, "pods": 100.0},
            )

        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 12)
        session.solve(list(base), [node("n-1")])
        union = base + kind_pods("d1", 4)
        # same node content -> delta; changed content -> invalidated
        r = session.solve(list(union), [node("n-1")])
        assert session.last_mode == "delta", session.last_reason
        cold_sched = TPUScheduler(make_templates(), max_claims=128)
        cold = cold_sched.solve(list(union), [node("n-1")])
        assert cold.assignments == r.assignments
        assert cold.existing_assignments == r.existing_assignments
        union2 = union + kind_pods("d2", 4)
        session.solve(list(union2), [node("n-1", cpu=4.0)])
        assert session.last_mode == "invalidated", session.last_reason


class TestResidentMetrics:
    def test_round_modes_are_counted(self, monkeypatch):
        from karpenter_tpu.utils.metrics import (
            RESIDENT_DELTA_PODS,
            RESIDENT_ROUNDS,
        )

        d0 = RESIDENT_ROUNDS.get(mode="delta")
        f0 = RESIDENT_ROUNDS.get(mode="full")
        i0 = RESIDENT_ROUNDS.get(mode="invalidated")
        h0 = RESIDENT_DELTA_PODS.observations() if hasattr(
            RESIDENT_DELTA_PODS, "observations"
        ) else None
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 12)
        session.solve(list(base))  # full
        union = base + kind_pods("d1", 4)
        session.solve(list(union))  # delta
        newcomer = make_pod("sel-0", cpu=1.0, memory="1Gi")
        newcomer.spec.node_selector = {"example.com/team": "search"}
        session.solve(list(union + [newcomer]))  # invalidated
        assert RESIDENT_ROUNDS.get(mode="full") == f0 + 1
        assert RESIDENT_ROUNDS.get(mode="delta") == d0 + 1
        assert RESIDENT_ROUNDS.get(mode="invalidated") == i0 + 1
        del h0


class TestKscanIncrementalGrid:
    def test_same_request_segments_reuse_the_grid(self):
        """Consecutive kind-scan segments with identical request vectors
        skip the full-width [W, T, GR] recompute (the STATUS Known-gaps
        lever) — pinned against the host oracle and counted."""
        from karpenter_tpu.models import labels as l
        from karpenter_tpu.models.pod import TopologySpreadConstraint
        from karpenter_tpu.utils.metrics import KSCAN_GRID_UPDATES

        import bench

        pods = []
        for k in range(3):
            for i in range(8):
                p = make_pod(f"z{k}-{i}", cpu=1.0, memory="1Gi")
                p.metadata.labels = {"spread": "zonal", "shard": f"s{k}"}
                p.spec.topology_spread_constraints = [
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=l.LABEL_TOPOLOGY_ZONE,
                        label_selector={"spread": "zonal"},
                    )
                ]
                pods.append(p)
        inc0 = KSCAN_GRID_UPDATES.get(mode="incremental")
        templates = make_templates(24)
        sched = TPUScheduler(templates, max_claims=64)
        result = sched.solve(list(pods))
        host, _ = bench.host_solve(templates, pods)
        assert_same_packing(host, result)
        scan = sched.last_timings.get("scan") or {}
        # 3 same-request segments -> at least one boundary reuse
        assert scan.get("kscan_grid_incremental", 0) >= 1, scan
        assert KSCAN_GRID_UPDATES.get(mode="incremental") > inc0
