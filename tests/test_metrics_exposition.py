"""/metrics exposition correctness + registry registration guards.

PR-2 satellites: label escaping, cumulative histogram buckets,
_sum/_count consistency, presence of the reference-parity families, the
idempotent get_or_register guard, and the double-Manager construction
case that previously relied on registration luck.
"""

import math
import re

import pytest

from karpenter_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    REGISTRY,
    Registry,
)


def parse_samples(text: str) -> dict:
    """exposition -> {(name, frozenset(label pairs)): value} with escapes
    folded back, so assertions read like a Prometheus client."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (.+)$", line)
        assert m, f"unparsable exposition line: {line!r}"
        name, labels_raw, value = m.groups()
        labels = {}
        if labels_raw:
            for pair in re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"', labels_raw):
                k, v = pair
                labels[k] = (
                    v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
                )
        out[(name, frozenset(labels.items()))] = float(value)
    return out


class TestExpositionCorrectness:
    def test_label_escaping_round_trips(self):
        reg = Registry()
        c = reg.counter("ktpu_test_total", "a counter", ("path",))
        nasty = 'a"b\\c\nd'
        c.inc(3.0, path=nasty)
        text = reg.expose()
        # raw text must not contain an unescaped quote/newline in a value
        assert '\\"' in text and "\\n" in text and "\\\\" in text
        samples = parse_samples(text)
        assert samples[("ktpu_test_total", frozenset({("path", nasty)}))] == 3.0

    def test_help_escaping(self):
        reg = Registry()
        reg.gauge("ktpu_test_gauge", "line one\nline two \\ slash")
        text = reg.expose()
        help_line = [l for l in text.splitlines() if l.startswith("# HELP")][0]
        assert "\n" not in help_line
        assert "line one\\nline two \\\\ slash" in help_line

    def test_histogram_buckets_cumulative_and_consistent(self):
        reg = Registry()
        h = reg.histogram(
            "ktpu_test_seconds", "h", ("op",), buckets=(0.1, 1.0, 10.0)
        )
        values = [0.05, 0.5, 0.5, 5.0, 50.0]
        for v in values:
            h.observe(v, op="x")
        text = reg.expose()
        samples = parse_samples(text)

        def bucket(le):
            return samples[("ktpu_test_seconds_bucket", frozenset({("op", "x"), ("le", le)}))]

        cum = [bucket("0.1"), bucket("1"), bucket("10"), bucket("+Inf")]
        assert cum == [1, 3, 4, 5]
        assert all(a <= b for a, b in zip(cum, cum[1:])), "buckets not cumulative"
        count = samples[("ktpu_test_seconds_count", frozenset({("op", "x")}))]
        total = samples[("ktpu_test_seconds_sum", frozenset({("op", "x")}))]
        assert count == cum[-1] == len(values)
        assert total == pytest.approx(sum(values))

    def test_unlabeled_histogram_buckets(self):
        reg = Registry()
        h = reg.histogram("ktpu_plain_seconds", "h", buckets=(1.0,))
        h.observe(0.5)
        h.observe(2.0)
        samples = parse_samples(reg.expose())
        assert samples[("ktpu_plain_seconds_bucket", frozenset({("le", "1")}))] == 1
        assert samples[("ktpu_plain_seconds_bucket", frozenset({("le", "+Inf")}))] == 2

    def test_reference_parity_families_exposed(self):
        text = REGISTRY.expose()
        for family in (
            "ktpu_scheduler_batch_window_seconds",
            "ktpu_scheduler_queue_depth_pods",
            "ktpu_unschedulable_pods",
            "ktpu_voluntary_disruption_decisions_total",
            "ktpu_voluntary_disruption_eligible_nodes",
            "ktpu_nodeclaims_transition_duration_seconds",
            "ktpu_nodeclaims_termination_duration_seconds",
        ):
            assert f"# TYPE {family} " in text, f"{family} not registered"


class TestRegistrationGuard:
    def test_get_or_register_is_idempotent(self):
        reg = Registry()
        a = reg.counter("ktpu_x_total", "help", ("k",))
        b = reg.counter("ktpu_x_total", "different help text ok", ("k",))
        assert a is b
        a.inc(k="v")
        assert b.get(k="v") == 1.0  # one family, one series — no double count

    def test_type_mismatch_raises(self):
        reg = Registry()
        reg.counter("ktpu_y_total", "h")
        with pytest.raises(TypeError):
            reg.gauge("ktpu_y_total", "h")

    def test_label_mismatch_raises(self):
        reg = Registry()
        reg.counter("ktpu_z_total", "h", ("a",))
        with pytest.raises(ValueError):
            reg.counter("ktpu_z_total", "h", ("a", "b"))

    def test_generic_get_or_register(self):
        reg = Registry()
        h = reg.get_or_register(Histogram, "ktpu_w_seconds", "h", (), buckets=(1.0,))
        assert reg.get_or_register(Histogram, "ktpu_w_seconds") is h
        assert reg.get_or_register(Gauge, "ktpu_g", "h").__class__ is Gauge
        assert reg.get_or_register(Counter, "ktpu_c_total", "h").__class__ is Counter

    def test_second_manager_construction_does_not_double_count(self):
        """Manager restart in one process (tests do this constantly): the
        module-level families must be shared, never re-registered into
        duplicate series or duplicate exposition blocks."""
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.controllers.manager import Manager
        from karpenter_tpu.models.nodepool import NodePool
        from karpenter_tpu.models.pod import make_pod
        from karpenter_tpu.state.store import ObjectStore
        from karpenter_tpu.utils import metrics
        from karpenter_tpu.utils.clock import FakeClock

        def build():
            clock = FakeClock()
            store = ObjectStore(clock)
            cloud = KwokCloudProvider(store, catalog=instance_types(8))
            mgr = Manager(store, cloud, clock)
            pool = NodePool()
            pool.metadata.name = "default"
            store.create(ObjectStore.NODEPOOLS, pool)
            store.create(ObjectStore.PODS, make_pod("p", cpu=0.5))
            mgr.run_until_idle()
            return metrics.NODECLAIMS_CREATED.get(
                reason="provisioning", nodepool="default", min_values_relaxed="false"
            )

        first = build()
        second = build()
        # the second manager increments the SAME family by exactly one
        assert second == first + 1.0
        text = metrics.REGISTRY.expose()
        type_lines = [l for l in text.splitlines() if l.startswith("# TYPE ")]
        assert len(type_lines) == len(set(type_lines)), "duplicate family exposition"


class TestHistogramSemantics:
    def test_percentile_and_time_still_work(self):
        reg = Registry()
        h = reg.histogram("ktpu_t_seconds", "h", buckets=(0.1, 1.0))
        with h.time():
            pass
        assert h.totals[()] == 1
        assert not math.isnan(h.percentile(0.5))
