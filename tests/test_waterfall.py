"""Critical-path waterfall + perf-regression sentinel (ISSUE 15).

The acceptance properties under test:

- the span algebra is exact: per-span self-times plus the reconciled
  ``other`` remainder telescope to the measured wall, bounded storage
  never breaks the rollup, and ``KTPU_WATERFALL=0`` turns the whole
  instrument into a no-op;
- a REAL solve reconciles: the summed waterfall segments account for the
  round wall with ``other`` <= 5% on the fill-dp, kscan-dp, and
  pipelined paths (the in-process 8-virtual-device mesh from conftest);
- every dp row is accounted: committed + replayed + idle == total, the
  ``ktpu_shard_dp_utilization`` gauge carries the fractions, and the
  per-family speculation efficiency lands in the shard record;
- ``sync_blocked_s`` splits into verdict fetches vs block_until_ready
  drains while the old key stays their sum (compat);
- ``bench_diff`` flags an injected 2x regression in a single segment and
  passes an identical-JSON self-diff (exit 0).
"""

import json
import time

import pytest

from karpenter_tpu.controllers.provisioning import TPUScheduler
from karpenter_tpu.obs import bench_diff, waterfall
from karpenter_tpu.parallel import make_mesh

from test_shard import (
    dp_scheduler,
    make_templates,
    mixed_kind_pods,
    saturating_kind_pods,
    zonal_kind_pods,
)


def _segments_sum(rec):
    return sum(rec["segments"].values())


class TestSpanAlgebra:
    def test_nested_self_times_telescope_to_wall(self):
        wf = waterfall.RoundWaterfall()
        with wf.span("outer"):
            time.sleep(0.02)
            with wf.span("inner"):
                time.sleep(0.02)
        time.sleep(0.01)  # un-spanned gap -> other
        rec = wf.finalize()
        segs = rec["segments"]
        assert segs["inner"] >= 0.015
        # outer's self-time excludes the child's interval
        assert segs["outer"] < segs["outer"] + segs["inner"]
        assert segs["other"] >= 0.005
        # segments are stored rounded to 1e-6, so the telescoped sum can
        # drift by a few microseconds per segment
        assert abs(_segments_sum(rec) - rec["wall_s"]) < 1e-4
        assert rec["other_frac"] == pytest.approx(
            segs["other"] / rec["wall_s"], abs=1e-3
        )

    def test_add_debits_the_enclosing_span(self):
        wf = waterfall.RoundWaterfall()
        with wf.span("dispatch"):
            time.sleep(0.02)
            wf.add("wire", 0.015)
        rec = wf.finalize()
        # the externally measured leaf came out of dispatch's self-time
        assert rec["segments"]["wire"] == pytest.approx(0.015, abs=1e-6)
        assert rec["segments"]["dispatch"] <= rec["wall_s"] - 0.015 + 1e-3
        assert abs(_segments_sum(rec) - rec["wall_s"]) < 1e-4

    def test_explicit_wall_reconciles(self):
        wf = waterfall.RoundWaterfall()
        with wf.span("a"):
            pass
        rec = wf.finalize(wall_s=1.0)
        assert rec["wall_s"] == 1.0
        assert abs(_segments_sum(rec) - 1.0) < 1e-4
        assert rec["other_frac"] > 0.99

    def test_span_storage_is_bounded_but_rollup_stays_exact(self):
        wf = waterfall.RoundWaterfall()
        for i in range(waterfall.MAX_SPANS + 50):
            with wf.span(f"s{i % 4}"):
                pass
        rec = wf.finalize()
        assert rec["dropped_spans"] == 50
        assert len(rec["spans"]["name"]) == waterfall.MAX_SPANS
        # overflow spans still landed in the per-name rollup
        assert abs(_segments_sum(rec) - rec["wall_s"]) < 1e-4

    def test_name_rollup_folds_tail_into_misc(self):
        wf = waterfall.RoundWaterfall()
        for i in range(waterfall.MAX_NAMES + 8):
            wf.add(f"leaf{i}", 0.001)
        # synthetic add() leaves claim more time than really elapsed, so
        # reconcile against an explicit wall that covers them
        rec = wf.finalize(wall_s=1.0)
        assert "misc" in rec["segments"]
        assert len(rec["segments"]) == waterfall.MAX_NAMES + 2  # + misc + other
        assert abs(_segments_sum(rec) - 1.0) < 1e-4

    def test_exception_unwind_closes_open_spans(self):
        wf = waterfall.RoundWaterfall()
        with pytest.raises(RuntimeError):
            with wf.span("outer"):
                wf.span("abandoned").__enter__()  # never closed explicitly
                raise RuntimeError("boom")
        rec = wf.finalize()
        assert abs(_segments_sum(rec) - rec["wall_s"]) < 1e-4

    def test_open_close_span_pairing(self):
        wf = waterfall.RoundWaterfall()
        token = waterfall._ACTIVE.set(wf)
        try:
            sp = waterfall.open_span("loop")
            waterfall.add_current("leaf", 0.001)
            waterfall.close_span(sp)
        finally:
            waterfall._ACTIVE.reset(token)
        rec = wf.finalize()
        assert "loop" in rec["segments"] and "leaf" in rec["segments"]

    def test_disabled_by_env_is_a_noop(self, monkeypatch):
        monkeypatch.setenv(waterfall.ENV_OPT_OUT, "0")
        with waterfall.round_waterfall() as wf:
            assert wf is None
            assert waterfall.current() is None
            waterfall.add_current("ghost", 1.0)  # must not raise
            with waterfall.span("ghost") as sp:
                assert sp is None
            assert waterfall.open_span("ghost") is None

    def test_render_lines(self):
        wf = waterfall.RoundWaterfall()
        with wf.span("encode"):
            time.sleep(0.01)
        with wf.span("dispatch"):
            with wf.span("dispatch.fill"):
                time.sleep(0.01)
        lines = waterfall.render(wf.finalize())
        assert lines[0].startswith("waterfall wall=")
        assert any("encode" in ln and "#" in ln for ln in lines[1:])
        # children indent under their parents
        assert any("  dispatch.fill" in ln for ln in lines[1:])


class TestSolveReconciliation:
    """The tentpole pin: a real round's waterfall accounts for the
    measured wall with other <= 5%, on every dispatch shape. Warm solves
    (the steady state the instrument is for); the cold solve's compile
    lands inside dispatch/enqueue spans so it reconciles too, but its
    jitter is not what we gate on."""

    def _reconciled(self, sched, pods):
        sched.solve(list(pods))  # cold: compile
        sched.solve(list(pods))  # warm
        wf = sched.last_timings.get("waterfall")
        assert wf, "instrumented solve must record a waterfall"
        assert abs(_segments_sum(wf) - wf["wall_s"]) < 1e-3
        assert wf["other_frac"] <= 0.05, wf["segments"]
        return wf

    def test_fill_dp_round_reconciles(self, monkeypatch):
        sched = dp_scheduler(monkeypatch)
        wf = self._reconciled(sched, saturating_kind_pods(256, 8))
        # the dp merge loop's leaves are attributed by name
        assert any(k.startswith("fill_dp.") for k in wf["segments"])
        assert any(k.startswith("enqueue.") for k in wf["segments"])

    def test_kscan_dp_round_reconciles(self, monkeypatch):
        sched = dp_scheduler(monkeypatch)
        wf = self._reconciled(sched, zonal_kind_pods(192, 4))
        assert any(k.startswith("kscan_dp.") for k in wf["segments"])

    def test_pipelined_round_reconciles(self, monkeypatch):
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "4")
        monkeypatch.setenv("KTPU_PIPELINE_MIN_PODS", "32")
        sched = TPUScheduler(make_templates(24))
        wf = self._reconciled(sched, saturating_kind_pods(256, 8))
        assert "pipeline" in sched.last_timings
        assert "encode" in wf["segments"] and "decode" in wf["segments"]

    def test_sequential_round_reconciles(self):
        sched = TPUScheduler(make_templates(12), max_claims=128)
        self._reconciled(sched, mixed_kind_pods(48, 4))

    def test_segment_metric_observed(self, monkeypatch):
        from karpenter_tpu.utils.metrics import ROUND_SEGMENT_SECONDS

        def observed(segment):
            key = ROUND_SEGMENT_SECONDS._key({"segment": segment})
            return ROUND_SEGMENT_SECONDS.totals.get(key, 0)

        n0 = observed("other")
        sched = TPUScheduler(make_templates(12), max_claims=128)
        sched.solve(list(mixed_kind_pods(48, 4)))
        assert observed("other") == n0 + 1
        assert observed("encode") >= 1

    def test_opt_out_skips_recording(self, monkeypatch):
        monkeypatch.setenv(waterfall.ENV_OPT_OUT, "0")
        sched = TPUScheduler(make_templates(12), max_claims=128)
        sched.solve(list(mixed_kind_pods(48, 4)))
        assert "waterfall" not in sched.last_timings


class TestDpUtilization:
    """Tentpole (a): every dp row of every merge round is accounted —
    committed, replayed, or padded-idle — and the per-family speculation
    efficiency (committed-pod-seconds / dispatched-pod-seconds) rides the
    shard record."""

    def test_rows_account_and_gauge(self, monkeypatch):
        from karpenter_tpu.utils.metrics import SHARD_DP_UTILIZATION

        sched = dp_scheduler(monkeypatch)
        sched.solve(list(saturating_kind_pods(256, 8)))
        sh = sched.last_timings["shard"]
        total = sh["dp_rows_total"]
        assert total > 0
        assert (
            sh["dp_rows_committed"] + sh["dp_rows_replayed"] + sh["dp_rows_idle"]
            == total
        )
        fracs = {
            s: SHARD_DP_UTILIZATION.get(state=s)
            for s in ("committed", "replayed", "idle")
        }
        assert sum(fracs.values()) == pytest.approx(1.0, abs=1e-6)
        assert fracs["committed"] == pytest.approx(
            sh["dp_rows_committed"] / total, abs=1e-6
        )

    def test_saturating_kinds_commit_at_full_efficiency(self, monkeypatch):
        sched = dp_scheduler(monkeypatch)
        sched.solve(list(saturating_kind_pods(256, 8)))
        sh = sched.last_timings["shard"]
        eff = sh["speculation_efficiency"]
        assert eff.get("fill") == pytest.approx(1.0)
        assert sh["families"]["fill"]["dispatched_pod_s"] > 0

    def test_replaying_kinds_burn_efficiency(self, monkeypatch):
        """Mixed-size kinds force replays: dispatched pod-seconds exceed
        committed pod-seconds, so efficiency drops below 1."""
        sched = dp_scheduler(monkeypatch)
        sched.solve(list(mixed_kind_pods(256, 8)))
        sh = sched.last_timings["shard"]
        if sh["dp_rows_replayed"] == 0:
            pytest.skip("adversarial mix committed everywhere on this build")
        assert sh["speculation_efficiency"]["fill"] < 1.0

    def test_sync_blocked_splits_by_phase(self, monkeypatch):
        """Satellite: verdict fetches vs block_until_ready drains are
        separately attributed; the old sync_blocked_s key stays their sum
        so existing dashboards keep reading."""
        sched = dp_scheduler(monkeypatch)
        sched.solve(list(saturating_kind_pods(256, 8)))
        sh = sched.last_timings["shard"]
        assert sh["sync_verdict_s"] > 0
        assert sh["sync_drain_s"] > 0
        assert sh["sync_blocked_s"] == pytest.approx(
            sh["sync_verdict_s"] + sh["sync_drain_s"], rel=1e-6
        )
        assert sh["merge_wall_s"] >= sh["sync_blocked_s"]


class TestBenchDiff:
    """The perf-regression sentinel: identical self-diff passes, a 2x
    single-segment injection fails, sub-floor jitter is ignored."""

    BASE = {
        "detail": {
            "mixed_4096x400": {
                "wall_s": 1.0,
                "encode_s": 0.2,
                "nodes": 37,  # not a timing leaf: never compared
                "waterfall": {
                    "wall_s": 1.0,
                    "other_frac": 0.01,
                    "segments": {
                        "encode": 0.2,
                        "dispatch": 0.6,
                        "fill_dp.device": 0.15,
                        "other": 0.01,
                    },
                },
            }
        }
    }

    def test_identical_self_diff_passes(self):
        diff = bench_diff.diff_docs(self.BASE, json.loads(json.dumps(self.BASE)))
        assert diff["rows"] and not diff["regressions"]

    def test_single_segment_2x_regression_is_flagged(self):
        cand = json.loads(json.dumps(self.BASE))
        seg = cand["detail"]["mixed_4096x400"]["waterfall"]["segments"]
        seg["fill_dp.device"] = 0.30  # 2x one segment, everything else flat
        diff = bench_diff.diff_docs(self.BASE, cand)
        paths = [r["path"] for r in diff["regressions"]]
        assert paths == [
            "detail.mixed_4096x400.waterfall.segments.fill_dp.device"
        ]
        assert diff["regressions"][0]["ratio"] == pytest.approx(2.0)

    def test_counts_are_not_timings(self):
        cand = json.loads(json.dumps(self.BASE))
        cand["detail"]["mixed_4096x400"]["nodes"] = 500  # not _s-suffixed
        assert not bench_diff.diff_docs(self.BASE, cand)["regressions"]

    def test_absolute_floor_ignores_tiny_jitter(self):
        a = {"stages": {"x_s": 0.001}}
        b = {"stages": {"x_s": 0.003}}  # 3x but only +2ms
        assert not bench_diff.diff_docs(a, b)["regressions"]

    def test_structural_changes_are_notes_not_regressions(self):
        cand = json.loads(json.dumps(self.BASE))
        cand["detail"]["new_stage"] = {"wall_s": 99.0}
        diff = bench_diff.diff_docs(self.BASE, cand)
        assert not diff["regressions"]
        assert "detail.new_stage.wall_s" in diff["only_b"]

    def test_threshold_env_var(self, monkeypatch):
        monkeypatch.setenv(bench_diff.ENV_THRESHOLD, "5.0")
        cand = json.loads(json.dumps(self.BASE))
        cand["detail"]["mixed_4096x400"]["wall_s"] = 3.0  # 3x < 1+5.0
        assert not bench_diff.diff_docs(self.BASE, cand)["regressions"]

    def test_cli_exit_codes(self, tmp_path, capsys):
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps(self.BASE))
        cand = json.loads(json.dumps(self.BASE))
        cand["detail"]["mixed_4096x400"]["waterfall"]["segments"]["dispatch"] = 1.3
        b.write_text(json.dumps(cand))
        assert bench_diff.main([str(a), str(a)]) == 0
        assert bench_diff.main([str(a), str(b)]) == 1
        out = capsys.readouterr().out
        assert "REGRESSED" in out and "segments.dispatch" in out
        assert bench_diff.main([str(a), str(tmp_path / "missing.json")]) == 2

    def _with_coverage(self, fracs):
        doc = json.loads(json.dumps(self.BASE))
        doc["detail"]["shard_8192x200"] = {"coverage_fraction": dict(fracs)}
        return doc

    def test_coverage_drop_is_flagged(self):
        """ISSUE 20: a per-family dp coverage fraction dropping >= 0.05
        regresses — a family sliding off the dp path costs the
        speculation win without moving any timing leaf."""
        base = self._with_coverage({"perpod": 0.9, "kscan": 1.0})
        cand = self._with_coverage({"perpod": 0.8, "kscan": 1.0})
        diff = bench_diff.diff_docs(base, cand)
        paths = [r["path"] for r in diff["regressions"]]
        assert paths == ["detail.shard_8192x200.coverage_fraction.perpod"]

    def test_coverage_jitter_and_increase_pass(self):
        base = self._with_coverage({"perpod": 0.9, "kscan": 0.5})
        # -0.04 is under the ratchet floor; +0.3 is an improvement
        cand = self._with_coverage({"perpod": 0.86, "kscan": 0.8})
        assert not bench_diff.diff_docs(base, cand)["regressions"]

    def test_coverage_zero_routed_family_is_a_note(self):
        """A family absent from one document (the run never routed it,
        so no fraction was recorded) is structural, not a regression."""
        base = self._with_coverage({"perpod": 0.9, "gang": 0.0})
        cand = self._with_coverage({"perpod": 0.9})
        diff = bench_diff.diff_docs(base, cand)
        assert not diff["regressions"]
        assert (
            "detail.shard_8192x200.coverage_fraction.gang" in diff["only_a"]
        )

    def test_bench_baseline_flag_wires_the_sentinel(self):
        """bench.py --baseline exists and routes through diff_docs."""
        import bench as bench_mod

        assert hasattr(bench_mod, "_wf_digest")
        wf = bench_mod._wf_digest(
            {"waterfall": {"wall_s": 1.0, "other_frac": 0.01,
                           "segments": {"other": 0.01}, "spans": {}}}
        )
        assert wf == {
            "wall_s": 1.0,
            "other_frac": 0.01,
            "segments": {"other": 0.01},
        }
        assert bench_mod._wf_digest({}) is None
