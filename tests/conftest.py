"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the real
multi-chip path via __graft_entry__.dryrun_multichip).

Note: the axon TPU plugin in this image overrides the JAX_PLATFORMS env
var (jax.config.jax_platforms comes up as "axon,cpu"), so we must force
the CPU platform through jax.config.update, and the XLA flag must be in
the environment before the backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (-m 'not slow'); run on demand",
    )


@pytest.fixture
def rng():
    import numpy as np

    return np.random.default_rng(0)
