"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the real
multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os

# Must be set before jax import anywhere in the test process.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture
def rng():
    import numpy as np

    return np.random.default_rng(0)
